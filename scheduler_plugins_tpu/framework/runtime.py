"""The scheduling cycle: fuse enabled plugins into one jitted batched solve.

Reference dataflow per pending pod (SURVEY.md §1): QueueSort -> PreFilter ->
Filter(xnodes) -> PreScore -> Score(xnodes) -> Normalize -> Reserve -> Permit.
Here the whole pending batch runs as a single `lax.scan` whose body evaluates
every enabled plugin's tensor contribution for one pod against the carried
SolverState (free capacity, quota usage, gang counts), then commits the chosen
node before the next pod — preserving the reference's one-pod-at-a-time
semantics while keeping each step fully vectorized over nodes.

Permit is evaluated after the scan as a segment reduction over gangs
(quorum = assigned-before + scheduled-this-cycle >= MinMember), mirroring
/root/reference/pkg/coscheduling/core/core.go:308-345; the host shell
(`Scheduler.schedule`) then binds, parks, or rejects.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
from flax import struct

from scheduler_plugins_tpu.framework.plugin import Plugin, SolverState
from scheduler_plugins_tpu.ops.fit import fits_one, free_capacity, pod_fit_demand
from scheduler_plugins_tpu.state.snapshot import ClusterSnapshot, SnapshotMeta
from scheduler_plugins_tpu.utils import observability as obs

#: attribution name for failures owned by the FRAMEWORK, not a profile
#: plugin: scheduling gates, resource-fit exhaustion, wave-capacity
#: exhaustion in the batched path (the upstream built-in fit plugin name)
BUILTIN_FIT = "NodeResourcesFit"


def _is_tpu_backend() -> bool:
    """True when the default backend is a TPU, including tunneled platforms
    ("axon") whose platform name is not "tpu" — probe the device kind as the
    capability check."""
    try:
        backend = jax.default_backend()
        if backend in ("tpu", "axon"):
            return True
        kind = jax.devices()[0].device_kind.lower()
    except Exception:  # backend init failure: treat as non-TPU
        return False
    return "tpu" in kind


@struct.dataclass
class SolveResult:
    assignment: jnp.ndarray  # (P,) int32 node index, -1 unschedulable
    admitted: jnp.ndarray  # (P,) bool PreFilter verdict
    wait: jnp.ndarray  # (P,) bool Permit said Wait (gang quorum unmet)
    state: SolverState  # final carried state
    #: (P,) int32 unschedulability attribution, the upstream
    #: `UnschedulablePlugins` signal per pod: -1 = placed; 0 = built-in
    #: (gated, or resource fit exhausted against the carried free
    #: capacity); 1+i = profile plugin i (its PreFilter rejected the pod,
    #: or its Filter first emptied the remaining feasible node set in
    #: profile order). Decoded via `Scheduler.fail_plugin_names`.
    failed_plugin: Optional[jnp.ndarray] = None


def solve_output_anomaly(assignment, admitted, wait, n_nodes: int):
    """Reason string when solve outputs violate the framework contract,
    else None — integer (P,) assignment in [-1, n_nodes), matching-shape
    admitted/wait, no NaNs. THE one statement of the output contract:
    the resilience watchdog (`resilience.watchdog`) runs it after every
    device solve's completion fence to classify garbage output (a
    desynced tunnel answers with plausible-length junk) as a backend
    fault rather than committing it."""
    import numpy as np

    a = np.asarray(assignment)
    if a.ndim != 1 or not np.issubdtype(a.dtype, np.integer):
        return f"assignment dtype/rank {a.dtype}/{a.ndim}"
    if a.size and (int(a.min()) < -1 or int(a.max()) >= n_nodes):
        return (
            f"assignment out of range [{int(a.min())}, {int(a.max())}] "
            f"vs {n_nodes} nodes"
        )
    for name, arr in (("admitted", admitted), ("wait", wait)):
        x = np.asarray(arr)
        if x.shape != a.shape:
            return f"{name} shape {x.shape} != assignment {a.shape}"
        if np.issubdtype(x.dtype, np.floating) and np.isnan(x).any():
            return f"NaN in {name}"
    return None


def _admit_with_attribution(plugins, state, snap, p, ok0):
    """PreFilter sweep with attribution: (ok, admit_code) where
    `admit_code` is the FIRST plugin (profile order) whose verdict flipped
    the pod inadmissible, -1 when none did — the upstream
    UnschedulablePlugins attribution at PreFilter. THE one copy of the
    attribution ordering, shared by the sequential scan and the batched
    reduction (`Scheduler.attribution_codes`) so the two cannot drift."""
    ok = ok0
    admit_code = jnp.int32(-1)
    for i, plugin in enumerate(plugins):
        verdict = plugin.admit(state, snap, p)
        if verdict is not None:
            admit_code = jnp.where(
                (admit_code < 0) & ok & ~verdict, jnp.int32(i), admit_code
            )
            ok &= verdict
    return ok, admit_code


def _filter_with_attribution(plugins, state, snap, p, fit0, rows=None):
    """Filter chain with attribution: (feasible, filter_code) where
    `filter_code` is the first plugin whose Filter emptied the
    still-feasible node set, -1 when none did. Shared like
    `_admit_with_attribution`. `rows` (plugin position -> precomputed
    (P, N) verdict rows, the batched solver's class-collapsed
    `filter_batch`/`batch_rows` outputs) substitutes `rows[i][p]` for the
    per-pod `filter` call — how `parallel.solver.batch_explain_rows`
    derives the batched explain through THIS same chain, so the two
    explain paths cannot drift."""
    feasible = fit0
    alive = fit0.any()
    filter_code = jnp.int32(-1)
    for i, plugin in enumerate(plugins):
        if rows is not None and i in rows:
            mask = rows[i][p]
        else:
            mask = plugin.filter(state, snap, p)
        if mask is not None:
            feasible &= mask
            now_alive = feasible.any()
            filter_code = jnp.where(
                (filter_code < 0) & alive & ~now_alive,
                jnp.int32(i), filter_code,
            )
            alive = now_alive
    return feasible, filter_code


def _free_with_nominee_holds(state, snap, p):
    """Effective free capacity pod `p`'s built-in fit sees: nominated
    pods' demand holds capacity against lower-or-equal-priority pods
    (upstream AddNominatedPods; the pod's own batch row excluded, and a
    batch nominee stops holding once placed). Shared by the sequential
    solve step and the explain body (`_explain_one`) so the explain
    surface reproduces exactly the fit the parity path enforced."""
    if snap.nominees is None:
        return state.free
    nm = snap.nominees
    live = (
        nm.mask
        & (nm.priority >= snap.pods.priority[p])
        & (nm.batch_idx != p)
    )
    if state.placed_mask is not None:
        placed_in_batch = (nm.batch_idx >= 0) & state.placed_mask[
            jnp.maximum(nm.batch_idx, 0)
        ]
        live &= ~placed_in_batch
    hold = jnp.zeros_like(state.free).at[
        jnp.maximum(nm.node, 0)
    ].add(jnp.where(live[:, None], nm.demand, 0))
    return state.free - hold


def _score_columns(plugins, state, snap, p, feasible, rows=None):
    """((L, N) int64 per-plugin weighted normalized score columns,
    (N,) int64 total) for pod `p` — THE one copy of the explain score
    decomposition. Each column is exactly the `weight * normalize(raw,
    feasible)` term the solve step folds into its total, so the columns
    sum to the solver's node score by construction; plugins without a
    Score contribute a zero column (the upstream score dump lists every
    scoring plugin). `rows` substitutes the batched solver's
    class-collapsed `score_batch`/`batch_rows` rows for the per-pod
    `score` call (same drift guarantee as `_filter_with_attribution`)."""
    N = state.free.shape[0]
    cols = []
    total = jnp.zeros(N, jnp.int64)
    for i, plugin in enumerate(plugins):
        if rows is not None and i in rows:
            raw = rows[i][p]
        else:
            raw = plugin.score(state, snap, p)
        if raw is None:
            cols.append(jnp.zeros(N, jnp.int64))
            continue
        col = (plugin.eff_weight * plugin.normalize(raw, feasible)).astype(
            jnp.int64
        )
        cols.append(col)
        total = total + col
    return jnp.stack(cols), total


def _explain_one(plugins, state0, snap, p, filter_rows=None, score_rows=None):
    """Explain body for one pod against the cycle-initial state: admit +
    attribution, built-in fit + margin, the filter chain, and the
    per-plugin score columns — shared (via the `*_rows` overrides) by the
    sequential and batched explain entries."""
    ok0 = snap.pods.mask[p] & ~snap.pods.gated[p]
    ok, admit_code = _admit_with_attribution(plugins, state0, snap, p, ok0)
    demand = pod_fit_demand(snap.pods.req[p])
    # built-in fit margin: the binding resource's headroom (min over the
    # axis of effective free - demand, nominee holds included — the same
    # capacity the solve step fits against); masked nodes get the sentinel
    free_eff = _free_with_nominee_holds(state0, snap, p)
    margin = jnp.min(free_eff - demand[None, :], axis=1)
    margin = jnp.where(snap.nodes.mask, margin, jnp.int64(-(2 ** 62)))
    fit0 = fits_one(snap.pods.req[p], free_eff, snap.nodes.mask)
    feasible, filter_code = _filter_with_attribution(
        plugins, state0, snap, p, fit0, rows=filter_rows
    )
    feasible &= ok
    columns, total = _score_columns(
        plugins, state0, snap, p, feasible, rows=score_rows
    )
    fail_code = _encode_fail(
        ok0, admit_code, fit0.any(), filter_code, jnp.int32(-1)
    )
    return ok, fail_code, feasible, margin, columns, total


def run_explain_rows(scheduler, snap, indices, auxes, program, explain_fn):
    """Shared plumbing for the two explain entries (`Scheduler
    .explain_rows` sequential, `parallel.solver.batch_explain_rows`
    batched): power-of-two index-bucket padding (bounded retraces, like
    `attribution_codes`), the per-static_key jit cache with compile
    attribution, aux binding defaults, and the host transfer + slice-to-S
    packaging of `_explain_one`'s outputs. The entries define ONLY
    `explain_fn(snap, state0, auxes, idx)` — where the per-plugin rows
    come from — so their output contract cannot drift."""
    import numpy as np

    plugins = tuple(scheduler.profile.plugins)
    idx = np.asarray(indices, np.int32)
    if idx.size == 0:
        N = snap.num_nodes
        L = max(len(plugins), 1)
        return {
            "admitted": np.zeros(0, bool),
            "fail_code": np.zeros(0, np.int32),
            "feasible": np.zeros((0, N), bool),
            "fit_margin": np.zeros((0, N), np.int64),
            "columns": np.zeros((0, L, N), np.int64),
            "total": np.zeros((0, N), np.int64),
        }
    bucket = 1 << int(idx.size - 1).bit_length()
    idx_padded = np.full(bucket, idx[0], np.int32)
    idx_padded[: idx.size] = idx
    # weight tuple in the key: explain bakes `eff_weight` host ints into
    # its trace — a live-weight swap (Scheduler.set_live_weights) must
    # retrace this cold path, not serve stale-weight score columns
    key = (program,) + scheduler.weights_key() + tuple(
        p.static_key() for p in plugins
    )
    cache = scheduler._solve_cache
    if key not in cache:
        cache[key] = obs.compile_watch(jax.jit(explain_fn), program=program)
    if auxes is None:
        auxes = tuple(p.aux() for p in plugins)
    out = cache[key](
        snap, scheduler.initial_state(snap), auxes, jnp.asarray(idx_padded)
    )
    ok, fail, feasible, margin, columns, total = (
        np.asarray(x)[: idx.size] for x in out
    )
    return {
        "admitted": ok,
        "fail_code": fail,
        "feasible": feasible,
        "fit_margin": margin,
        "columns": columns,
        "total": total,
    }


def _encode_fail(ok0, admit_code, fit0_any, filter_code, fallback):
    """Merge the stage attributions into one code (see
    `SolveResult.failed_plugin`): PreFilter rejections name their plugin
    first (upstream runs PreFilter before the node sweep), then built-in
    fit, then the first Filter plugin that emptied the feasible set, then
    `fallback` (0 = built-in for the sequential scan, where reaching it
    means in-cycle capacity exhaustion; -1 = "feasible cycle-initially"
    for the batched reduction)."""
    return jnp.where(
        ~ok0,
        jnp.int32(0),
        jnp.where(
            admit_code >= 0,
            admit_code + 1,
            jnp.where(
                ~fit0_any,
                jnp.int32(0),
                jnp.where(filter_code >= 0, filter_code + 1, fallback),
            ),
        ),
    )


def _solve_step(plugins, carry, p, snap: ClusterSnapshot):
    """One pod of the bit-faithful sequential scan: PreFilter -> built-in
    fit (nominee holds) -> Filter chain -> Score/Normalize weighted sum ->
    argmax select -> Reserve commits — THE parity-path step body, shared by
    `Scheduler.solve`, the vmapped counterfactual sweep
    (`parallel.solver.sweep_solve_fn`) and the K-lane speculative solve
    (`parallel.lanes.lane_solve_fn`, which feeds it a one-pod snapshot
    view per step), so no fast path can drift from the parity program."""
    state = carry
    # PreFilter, with per-plugin attribution (shared helper)
    ok0 = snap.pods.mask[p] & ~snap.pods.gated[p]
    ok, admit_code = _admit_with_attribution(
        plugins, state, snap, p, ok0
    )
    # Filter: built-in resource fit (nominee capacity holds
    # included — see _free_with_nominee_holds) + plugin filters
    free_eff = _free_with_nominee_holds(state, snap, p)
    fit0 = fits_one(snap.pods.req[p], free_eff, snap.nodes.mask)
    # Filter chain with attribution (shared helper) — exact
    # against the CARRIED state: the parity path's ground truth
    feasible, filter_code = _filter_with_attribution(
        plugins, state, snap, p, fit0
    )
    feasible &= ok
    # Score + Normalize, weighted sum (eff_weight: the static profile int,
    # or the traced per-candidate scalar a sweep lane bound)
    total = jnp.zeros(state.free.shape[0], jnp.int64)
    for plugin in plugins:
        raw = plugin.score(state, snap, p)
        if raw is not None:
            total = total + plugin.eff_weight * plugin.normalize(raw, feasible)
    # select: argmax score among feasible, lowest index tie-break
    masked = jnp.where(feasible, total, jnp.int64(-(2**62)))
    choice = jnp.where(
        feasible.any(), jnp.argmax(masked).astype(jnp.int32), jnp.int32(-1)
    )
    # built-in Reserve: commit capacity
    demand = pod_fit_demand(snap.pods.req[p])
    onehot = (jnp.arange(state.free.shape[0]) == choice)[:, None]
    state = state.replace(
        free=state.free - jnp.where(choice >= 0, onehot * demand[None, :], 0)
    )
    if state.placed_mask is not None:
        state = state.replace(
            placed_mask=state.placed_mask.at[p].set(choice >= 0)
        )
    if snap.scheduling is not None:
        # built-in: selector/domain carries are shared by multiple
        # plugins (spread, inter-pod affinity) — commit once
        from scheduler_plugins_tpu.ops.selectors import commit_tracks

        state = commit_tracks(state, snap.scheduling, p, choice)
    for plugin in plugins:
        state = plugin.commit(state, snap, p, choice)
    # attribution code (SolveResult.failed_plugin); fallback 0:
    # a failed pod that no stage rejected lost to in-cycle
    # capacity consumption -> built-in fit
    fail_code = jnp.where(
        choice >= 0,
        jnp.int32(-1),
        _encode_fail(ok0, admit_code, fit0.any(), filter_code,
                     jnp.int32(0)),
    )
    return state, (choice, ok, fail_code)


def sequential_solve_body(plugins, snap: ClusterSnapshot,
                          state0: SolverState, auxes, unroll: int = 1,
                          weights=None) -> SolveResult:
    """The traced sequential parity solve over one snapshot: bind aux (and
    optionally a traced (L,) per-plugin `weights` vector — the tuning
    sweep's counterfactual channel), hoist presolves, scan `_solve_step`,
    reduce gang quorum. `Scheduler._make_solve` jits this with
    weights=None; `parallel.solver.sweep_solve_fn` vmaps it over K weight
    vectors so every candidate shares one compile."""
    # bind per-plugin traced aux inputs (weight vectors, cost
    # matrices) so they are solve ARGUMENTS, not baked constants
    for plugin, aux in zip(plugins, auxes):
        plugin.bind_aux(aux)  # also clears any stale weight override
    if weights is not None:
        for i, plugin in enumerate(plugins):
            plugin.bind_weight(weights[i])
    # loop-invariant per-solve precomputes (hoisted out of the scan)
    for plugin in plugins:
        plugin.bind_presolve(plugin.prepare_solve(snap))
    P = snap.num_pods
    state, (assignment, admitted, failed_plugin) = jax.lax.scan(
        lambda c, p: _solve_step(plugins, c, p, snap), state0,
        jnp.arange(P), unroll=unroll,
    )
    wait = jnp.zeros(P, bool)
    if snap.gangs is not None and state.gang_scheduled is not None:
        # Permit quorum: previously-assigned + this cycle's placements
        total_per_gang = snap.gangs.assigned + state.gang_scheduled
        quorum = total_per_gang >= snap.gangs.min_member
        gang = snap.pods.gang
        in_gang = gang >= 0
        pod_quorum = jnp.where(
            in_gang, quorum[jnp.maximum(gang, 0)], True
        )
        wait = (assignment >= 0) & ~pod_quorum
    return SolveResult(
        assignment=assignment, admitted=admitted, wait=wait,
        state=state, failed_plugin=failed_plugin,
    )


#: the solve modes a profile may select (`Profile.solve_mode`): the
#: bit-faithful sequential parity scan (default), or the packing
#: optimizer — wave placement + iterative consolidation refinement
#: (`parallel.solver.packing_profile_solve`; docs/PACKING.md). The wave
#: throughput path stays caller-selected (stream_chunk / the batched
#: entries), not a profile mode — it has no per-profile knobs.
SOLVE_MODES = ("sequential", "packing")


@dataclass
class PackingConfig:
    """Knobs of the packing solve mode (docs/PACKING.md). All of
    `iterations` / `price_weight` / `temperature` / `decay` ride the
    traced `aux()` vector (CLAUDE.md aux-channel discipline — one
    compile, tunable online); `mover_cap` is a static shape knob."""

    #: refinement-round budget (0 = the wave placement bit-identically)
    iterations: int = 32
    #: weight of the fragmentation price vs the score term in each bid
    price_weight: float = 4.0
    #: initial minimum fill edge a target must have over the donor
    temperature: float = 0.0
    #: per-round multiplicative temperature decay, in (0, 1]
    decay: float = 0.5
    #: static per-round mover-window width
    mover_cap: int = 128

    def __post_init__(self):
        if self.iterations < 0:
            raise ValueError("packing iterations must be >= 0")
        if int(self.iterations) != self.iterations:
            # the jax build floors the traced budget to match the numpy
            # twin — reject fractional config values instead of silently
            # rounding a tuner's proposal
            raise ValueError(
                f"packing iterations must be integral, got "
                f"{self.iterations!r}"
            )
        if self.price_weight < 0:
            raise ValueError("packing priceWeight must be >= 0")
        if self.temperature < 0:
            raise ValueError("packing temperature must be >= 0")
        if not 0 < self.decay <= 1:
            raise ValueError("packing decay must be in (0, 1]")
        if self.mover_cap < 1:
            raise ValueError("packing moverCap must be >= 1")

    def aux(self):
        """The (4,) traced float64 knob vector (`ops.packing`)."""
        from scheduler_plugins_tpu.ops.packing import pack_aux_vector

        return pack_aux_vector(
            self.iterations, self.price_weight, self.temperature,
            self.decay,
        )


@dataclass
class Profile:
    """An enabled-plugin set, the equivalent of one KubeSchedulerConfiguration
    profile (SURVEY.md §5 config system)."""

    plugins: Sequence[Plugin] = field(default_factory=list)
    #: queue-sort plugin; None selects the first enabled plugin that overrides
    #: `queue_key` (a profile enables exactly one QueueSort upstream), falling
    #: back to upstream PrioritySort semantics
    queue_sort: Optional[Plugin] = None
    #: PostFilter preemption engine; None auto-selects from the enabled
    #: plugins (CapacityScheduling -> quota-aware preemption,
    #: PreemptionToleration -> default preemption with toleration)
    preemption: Optional[object] = None
    name: str = "tpu-scheduler"
    #: which solve serves this profile's cycles (`SOLVE_MODES`);
    #: "sequential" is the bit-faithful parity path every differential
    #: gate anchors on, "packing" opts into the consolidation optimizer
    solve_mode: str = "sequential"
    #: packing-mode knobs (ignored under other modes)
    packing: PackingConfig = field(default_factory=PackingConfig)

    def __post_init__(self):
        if self.solve_mode not in SOLVE_MODES:
            raise ValueError(
                f"unknown solve mode {self.solve_mode!r}; "
                f"expected one of {SOLVE_MODES}"
            )
        if self.queue_sort is None:
            for plugin in self.plugins:
                if type(plugin).queue_key is not Plugin.queue_key or hasattr(
                    plugin, "queue_compare"
                ):
                    self.queue_sort = plugin
                    break
        if self.preemption is None:
            for plugin in self.plugins:
                if hasattr(plugin, "preemption_engine"):
                    self.preemption = plugin.preemption_engine()
                    break


class Scheduler:
    """Host shell around the jitted solve.

    Owns nothing but the profile; cluster state comes in as a snapshot and
    decisions go back to the caller (the `state.cluster.Cluster` store drives
    bind/park/reject)."""

    def __init__(self, profile: Profile):
        self.profile = profile
        self._solve_cache = {}
        #: (L,) int64 live per-plugin weight vector, or None (static
        #: profile weights). Set via `set_live_weights` — the online
        #: tuner's rollout seam (ISSUE 15).
        self._live_weights = None

    # -- queue ----------------------------------------------------------
    def sort_pending(self, pods, cluster=None):
        """QueueSort: order the pending list with the profile's comparator
        (default: upstream PrioritySort — priority desc, then queue time).
        Plugins exposing a pairwise `queue_compare` (TopologicalSort) are
        used via cmp_to_key, preserving exact Less() semantics."""
        qs = self.profile.queue_sort
        qs_name = qs.name if qs is not None else "PrioritySort"
        with obs.extension_span("QueueSort", qs_name, pods=len(pods)):
            if qs is not None and hasattr(qs, "queue_compare"):
                import functools

                return sorted(
                    pods,
                    key=functools.cmp_to_key(
                        lambda a, b: qs.queue_compare(a, b, cluster)
                    ),
                )

            def key(pod):
                if qs is not None:
                    k = qs.queue_key(pod, cluster)
                    if k is not None:
                        return k
                return (
                    -pod.priority, pod.creation_ms,
                    f"{pod.namespace}/{pod.name}",
                )

            return sorted(pods, key=key)

    # -- solve ----------------------------------------------------------
    def prepare(self, meta: SnapshotMeta, cluster=None):
        for plugin in self.profile.plugins:
            with obs.extension_span("Prepare", plugin.name):
                plugin.prepare(meta)
                if hasattr(plugin, "prepare_cluster"):
                    plugin.prepare_cluster(meta, cluster)

    # -- live weights (the online tuner's rollout seam) -----------------
    @property
    def live_weights(self):
        """The (L,) int64 live weight vector, or None when the static
        profile weights rule."""
        return self._live_weights

    def set_live_weights(self, weights) -> None:
        """Swap the profile's per-plugin score weights LIVE, with zero
        recompiles on the hot path (ISSUE 15 / ROADMAP item 2): while a
        live vector is set, `solve` routes through the "solve_live"
        program, whose weights are a TRACED (L,) argument bound per
        plugin via `Plugin.bind_weight` — the aux-channel discipline
        applied to the one profile knob the config format keeps
        host-side, exactly like the counterfactual sweep's lanes
        (`parallel.solver.sweep_solve_fn`), so every subsequent swap or
        rollback is an argument change, never a retrace. The plugins'
        host `weight` ints are updated in lockstep so every host-side
        consumer (the degraded-mode `resilience.hostsolve` parity solve,
        the flight recorder's capture, the explain tables — whose cold
        jit caches key on the weight tuple) sees the same vector the
        traced solve multiplies by. `None` reverts to the static profile
        weights (the original profile ints are NOT restored — pass the
        incumbent vector explicitly to roll back)."""
        import numpy as np

        if weights is None:
            self._live_weights = None
            return
        w = np.asarray(weights, np.int64)
        if w.shape != (len(self.profile.plugins),):
            raise ValueError(
                f"live weights shape {w.shape} != "
                f"({len(self.profile.plugins)},)"
            )
        if (w < 1).any():
            raise ValueError("live weights must be positive (the solve "
                             "contracts require positive weights)")
        self._live_weights = w
        for plugin, wi in zip(self.profile.plugins, w):
            plugin.weight = int(wi)
        self._evict_stale_weight_programs()

    def weights_key(self) -> tuple:
        """The marked host weight tuple — folded into the jit-cache keys
        of every program that BAKES `plugin.weight` as a trace constant
        (explain, profile scores, the batched/packing solvers), so a
        live-weight swap retraces those cold paths instead of silently
        serving scores computed under stale weights. The hot sequential
        path never pays this: its live variant traces weights as an
        argument. The "weights" marker makes the segment locatable in
        the flat cache-key tuples so `set_live_weights` can EVICT
        stale-weight entries — without eviction a long-tuning daemon
        would accumulate one permanent compiled program per historical
        weight vector per cold path."""
        return ("weights",) + tuple(
            int(p.weight) for p in self.profile.plugins
        )

    def _evict_stale_weight_programs(self) -> None:
        """Drop cached programs keyed on a weight tuple other than the
        current one (see `weights_key`) — bounds the cold-path cache at
        one entry per program under live tuning."""
        current = self.weights_key()
        span = len(self.profile.plugins) + 1
        for key in list(self._solve_cache):
            if not isinstance(key, tuple) or "weights" not in key:
                continue
            i = key.index("weights")
            if key[i:i + span] != current:
                del self._solve_cache[key]

    def _make_solve(self, unroll: int, live: bool = False):
        plugins = tuple(self.profile.plugins)

        if live:
            def solve_live(
                snap: ClusterSnapshot, state0: SolverState, auxes, weights
            ) -> SolveResult:
                return sequential_solve_body(
                    plugins, snap, state0, auxes, unroll, weights=weights
                )

            return jax.jit(solve_live)

        def solve(
            snap: ClusterSnapshot, state0: SolverState, auxes
        ) -> SolveResult:
            return sequential_solve_body(plugins, snap, state0, auxes, unroll)

        return jax.jit(solve)

    def _scan_unroll(self) -> int:
        """Scan unroll factor: amortizes per-step loop overhead on TPU
        (~+20%); the body stays strictly one-pod-at-a-time (bit-faithful).
        CPU (tests) keeps 1 — extra compile time buys nothing there. The
        bench environment exposes the TPU through a tunneled backend whose
        platform name is "axon", so the default gates on device kind, not
        backend name. SPT_SCAN_UNROLL overrides for tuning — read host-side
        per solve and folded into the trace-cache key, so changing it
        retraces instead of being silently baked."""
        import os

        raw = os.environ.get("SPT_SCAN_UNROLL")
        if raw is None:
            return 8 if _is_tpu_backend() else 1
        try:
            unroll = int(raw)
        except ValueError:
            raise ValueError(f"SPT_SCAN_UNROLL={raw!r} is not an integer")
        if unroll < 1:
            raise ValueError(f"SPT_SCAN_UNROLL must be >= 1, got {unroll}")
        return unroll

    def solve(self, snap: ClusterSnapshot, state0: Optional[SolverState] = None,
              auxes=None, mode: Optional[str] = None):
        """Run the fused plugin pipeline over the snapshot's pending batch.
        `auxes` overrides the per-plugin traced aux pytrees (normally
        recomputed from the prepared plugins) — the flight-recorder replay
        path (`tools/replay.py`) force-binds the RECORDED arrays so the
        solve consumes exactly what the recorded cycle saw.

        `mode` selects the solve (None = the profile's `solve_mode`):
        "sequential" is the bit-faithful parity scan below; "packing"
        dispatches to `parallel.solver.packing_profile_solve` (wave
        placement + consolidation refinement, docs/PACKING.md) and
        returns its `PackingSolveView` (assignment/admitted/wait, no
        SolverState carry). Replay/differential callers that NEED the
        parity semantics pass mode="sequential" explicitly so a packing
        profile can never change what they certify."""
        if mode is None:
            mode = self.profile.solve_mode
        if mode == "packing":
            from scheduler_plugins_tpu.parallel.solver import (
                packing_profile_solve,
            )

            if self._live_weights is not None:
                # the packing waves rank on a single scoring plugin's
                # static scores (weight-invariant argmax), but its bid
                # arithmetic has no traced-weight channel — refuse
                # rather than silently ignore a live vector
                raise ValueError(
                    "live weights require the sequential parity path "
                    "(profile solve_mode 'packing' has no traced-weight "
                    "channel)"
                )
            if auxes is not None:
                raise ValueError(
                    "auxes= replay override requires the sequential "
                    "parity path (pass mode='sequential')"
                )
            if state0 is not None:
                # same rule as auxes: the packing solve builds its own
                # donation-safe initial state — silently dropping a
                # caller-prepared carry would solve against different
                # state than the caller intended
                raise ValueError(
                    "state0= requires the sequential parity path "
                    "(pass mode='sequential')"
                )
            return packing_profile_solve(
                self, snap, mover_cap=self.profile.packing.mover_cap
            )
        if mode != "sequential":
            raise ValueError(f"unknown solve mode {mode!r}")
        if state0 is None:
            state0 = self.initial_state(snap)
        if auxes is None:
            auxes = tuple(plugin.aux() for plugin in self.profile.plugins)
        unroll = self._scan_unroll()
        live = self._live_weights
        if live is not None:
            # the live-weights variant: ONE compile per (unroll,
            # static_key) like the static program, with the weight
            # vector a traced argument — promotions and rollbacks are
            # argument changes, zero recompiles (the aux discipline)
            key = ("solve_live", unroll) + tuple(
                plugin.static_key() for plugin in self.profile.plugins
            )
            if key not in self._solve_cache:
                self._solve_cache[key] = obs.compile_watch(
                    self._make_solve(unroll, live=True), program="solve_live"
                )
            return self._solve_cache[key](
                snap, state0, auxes, jnp.asarray(live)
            )
        key = ("solve", unroll) + tuple(
            plugin.static_key() for plugin in self.profile.plugins
        )
        if key not in self._solve_cache:
            self._solve_cache[key] = obs.compile_watch(
                self._make_solve(unroll), program="solve"
            )
        return self._solve_cache[key](snap, state0, auxes)

    def filter_verdicts(self, snap: ClusterSnapshot, pod_index: int):
        """(N,) AND of the enabled plugins' Filter verdicts for one pod
        against the cycle-initial state (resource fit excluded — callers
        handle capacity themselves). Used by the preemption dry run, which
        mirrors RunFilterPluginsWithNominatedPods: plugin filters see the
        CURRENT cache state, exactly as the reference's re-filter does
        (removing victims from the NodeInfo does not change e.g. the NRT
        cache view the TopologyMatch filter reads)."""
        plugins = tuple(self.profile.plugins)
        key = ("filter_verdicts",) + tuple(p.static_key() for p in plugins)
        if key not in self._solve_cache:

            def verdicts(snap, state0, auxes, p):
                for plugin, aux in zip(plugins, auxes):
                    plugin.bind_aux(aux)
                # presolve deliberately NOT bound: it precomputes whole-batch
                # tensors to amortize a P-step scan, but this entry evaluates
                # ONE pod — the plugins' per-row fallbacks are cheaper here
                for plugin in plugins:
                    plugin.bind_presolve(None)
                feasible = jnp.ones(snap.num_nodes, bool)
                for plugin in plugins:
                    mask = plugin.filter(state0, snap, p)
                    if mask is not None:
                        feasible &= mask
                return feasible

            self._solve_cache[key] = obs.compile_watch(
                jax.jit(verdicts), program="filter_verdicts"
            )
        auxes = tuple(plugin.aux() for plugin in plugins)
        return self._solve_cache[key](
            snap, self.initial_state(snap), auxes, pod_index
        )

    # -- attribution / explain ------------------------------------------
    def fail_plugin_names(self) -> list:
        """Decoder for attribution codes (`SolveResult.failed_plugin` /
        `attribution_codes`): code 0 (and any negative code on a failed
        pod) -> the built-in fit, code 1+i -> profile plugin i."""
        return [BUILTIN_FIT] + [p.name for p in self.profile.plugins]

    def attribution_codes(self, snap: ClusterSnapshot, indices):
        """(len(indices),) int32 unschedulability attribution for the
        `indices` pod rows against the CYCLE-INITIAL state — the batched
        paths' reduction of the per-plugin PreFilter verdicts and Filter
        masks they already evaluate (profile_batch_fn's per_pod pass
        computes exactly these masks; this entry re-derives them through
        the SAME shared helpers as the sequential scan so the two cannot
        drift). Only the failed rows are evaluated — the working set is
        (S, N) for S failures, never the (P, N) batch the streamed
        pipeline exists to avoid — and the row index vector is padded to
        a power-of-two bucket so jit retraces stay bounded.

        Encoding matches `SolveResult.failed_plugin`, except -1 here means
        "feasible cycle-initially": a failed pod with code -1 lost to
        in-cycle capacity consumption and decodes to the built-in fit
        (cycle.py maps code <= 0 -> built-in). For the sequential parity
        path the in-solve codes (exact against the carried state) take
        precedence; this entry is the fallback for solve paths without
        one."""
        import numpy as np

        plugins = tuple(self.profile.plugins)
        idx = np.asarray(indices, np.int32)
        if idx.size == 0:
            return np.zeros(0, np.int32)
        bucket = 1 << int(idx.size - 1).bit_length()
        idx_padded = np.full(bucket, idx[0], np.int32)
        idx_padded[: idx.size] = idx
        key = ("attribution",) + tuple(p.static_key() for p in plugins)
        if key not in self._solve_cache:

            def codes(snap, state0, auxes, idx):
                for plugin, aux in zip(plugins, auxes):
                    plugin.bind_aux(aux)
                for plugin in plugins:
                    plugin.bind_presolve(plugin.prepare_solve(snap))

                def one(p):
                    ok0 = snap.pods.mask[p] & ~snap.pods.gated[p]
                    ok, admit_code = _admit_with_attribution(
                        plugins, state0, snap, p, ok0
                    )
                    fit0 = fits_one(
                        snap.pods.req[p], state0.free, snap.nodes.mask
                    )
                    feasible, filter_code = _filter_with_attribution(
                        plugins, state0, snap, p, fit0
                    )
                    return _encode_fail(
                        ok0, admit_code, fit0.any(), filter_code,
                        jnp.int32(-1),
                    )

                return jax.vmap(one)(idx)

            self._solve_cache[key] = obs.compile_watch(
                jax.jit(codes), program="attribution"
            )
        auxes = tuple(plugin.aux() for plugin in plugins)
        out = self._solve_cache[key](
            snap, self.initial_state(snap), auxes, jnp.asarray(idx_padded)
        )
        return np.asarray(out)[: idx.size]

    def explain_rows(self, snap: ClusterSnapshot, indices, auxes=None):
        """Per-plugin score decomposition for the `indices` pod rows
        against the CYCLE-INITIAL state — the "why this node" surface
        behind `CycleReport.explain`, the daemon's `/explain?uid=` and
        `tools/replay.py explain` (the upstream `--v=10` per-plugin score
        dump). Row work is (S, N) for S requested rows, padded to a
        power-of-two bucket like `attribution_codes` so retraces stay
        bounded; `auxes` force-binds recorded config arrays on replay.

        Returns host numpy arrays (each sliced to len(indices)):
        `admitted` (S,), `fail_code` (S,) int32 (`_encode_fail` encoding,
        -1 = feasible cycle-initially), `feasible` (S, N), `fit_margin`
        (S, N) int64 (min over resources of effective free - demand,
        nominee capacity holds included — `_free_with_nominee_holds`, the
        same fit the solve step enforces; -2^62 on masked nodes),
        `columns` (S, L, N) int64 weighted normalized
        per-plugin scores in profile order, `total` (S, N) int64 = the
        column sum, which reproduces the solve step's weighted node score
        (`_score_columns` is the same code path).

        Scores are cycle-initial — the objective both solve modes rank by
        (`parallel.solver.profile_initial_scores`); in-cycle carry effects
        on later pods' scores are a sequential-scan refinement this
        surface deliberately does not chase (the batched/streamed solves
        never see them either). `parallel.solver.batch_explain_rows`
        computes these same outputs through the batched solver's
        class-collapsed row hooks; tests/test_explain.py gates the two
        for agreement."""
        plugins = tuple(self.profile.plugins)

        def explain(snap, state0, auxes, idx):
            for plugin, aux in zip(plugins, auxes):
                plugin.bind_aux(aux)
            for plugin in plugins:
                plugin.bind_presolve(plugin.prepare_solve(snap))
            return jax.vmap(
                lambda p: _explain_one(plugins, state0, snap, p)
            )(idx)

        return run_explain_rows(self, snap, indices, auxes, "explain", explain)

    def initial_state(self, snap: ClusterSnapshot) -> SolverState:
        free = free_capacity(snap.nodes.alloc, snap.nodes.requested)
        eq_used = snap.quota.used if snap.quota is not None else None
        gang_sched = None
        gang_inflight = None
        if snap.gangs is not None:
            G = snap.gangs.min_member.shape[0]
            gang_sched = jnp.zeros(G, jnp.int32)
            gang_inflight = jnp.zeros((G, snap.num_resources), jnp.int64)
        net_placed = (
            snap.network.placed_node if snap.network is not None else None
        )
        if snap.numa is not None:
            from scheduler_plugins_tpu.ops.numa import live_avail_init

            numa_avail = live_avail_init(snap.numa)
        else:
            numa_avail = None
        placed_mask = (
            jnp.zeros(snap.num_pods, bool)
            if snap.quota is not None or snap.nominees is not None
            else None
        )
        sel_counts = None
        sel_dom_counts = None
        anti_domains = None
        sym_counts = None
        if snap.scheduling is not None:
            if (
                snap.scheduling.track_node_base is not None
                and snap.scheduling.spread_needs_node_counts
            ):
                # the node-level carry is only materialized when a spread
                # eligibility row actually excludes a keyed node
                sel_counts = jnp.asarray(snap.scheduling.track_node_base)
            if snap.scheduling.track_base is not None:
                sel_dom_counts = jnp.asarray(snap.scheduling.track_base)
            if snap.scheduling.exist_anti_base is not None:
                anti_domains = jnp.asarray(snap.scheduling.exist_anti_base)
            if snap.scheduling.sym_base is not None:
                sym_counts = jnp.asarray(snap.scheduling.sym_base)
        return SolverState(
            free=free,
            eq_used=eq_used,
            gang_scheduled=gang_sched,
            gang_inflight=gang_inflight,
            net_placed=net_placed,
            numa_avail=numa_avail,
            placed_mask=placed_mask,
            sel_counts=sel_counts,
            sel_dom_counts=sel_dom_counts,
            anti_domains=anti_domains,
            sym_counts=sym_counts,
        )


def now_ms() -> int:
    return int(time.time() * 1000)
