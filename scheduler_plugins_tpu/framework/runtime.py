"""The scheduling cycle: fuse enabled plugins into one jitted batched solve.

Reference dataflow per pending pod (SURVEY.md §1): QueueSort -> PreFilter ->
Filter(xnodes) -> PreScore -> Score(xnodes) -> Normalize -> Reserve -> Permit.
Here the whole pending batch runs as a single `lax.scan` whose body evaluates
every enabled plugin's tensor contribution for one pod against the carried
SolverState (free capacity, quota usage, gang counts), then commits the chosen
node before the next pod — preserving the reference's one-pod-at-a-time
semantics while keeping each step fully vectorized over nodes.

Permit is evaluated after the scan as a segment reduction over gangs
(quorum = assigned-before + scheduled-this-cycle >= MinMember), mirroring
/root/reference/pkg/coscheduling/core/core.go:308-345; the host shell
(`Scheduler.schedule`) then binds, parks, or rejects.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
from flax import struct

from scheduler_plugins_tpu.framework.plugin import Plugin, SolverState
from scheduler_plugins_tpu.ops.fit import fits_one, free_capacity, pod_fit_demand
from scheduler_plugins_tpu.state.snapshot import ClusterSnapshot, SnapshotMeta


def _is_tpu_backend() -> bool:
    """True when the default backend is a TPU, including tunneled platforms
    ("axon") whose platform name is not "tpu" — probe the device kind as the
    capability check."""
    try:
        backend = jax.default_backend()
        if backend in ("tpu", "axon"):
            return True
        kind = jax.devices()[0].device_kind.lower()
    except Exception:  # backend init failure: treat as non-TPU
        return False
    return "tpu" in kind


@struct.dataclass
class SolveResult:
    assignment: jnp.ndarray  # (P,) int32 node index, -1 unschedulable
    admitted: jnp.ndarray  # (P,) bool PreFilter verdict
    wait: jnp.ndarray  # (P,) bool Permit said Wait (gang quorum unmet)
    state: SolverState  # final carried state


@dataclass
class Profile:
    """An enabled-plugin set, the equivalent of one KubeSchedulerConfiguration
    profile (SURVEY.md §5 config system)."""

    plugins: Sequence[Plugin] = field(default_factory=list)
    #: queue-sort plugin; None selects the first enabled plugin that overrides
    #: `queue_key` (a profile enables exactly one QueueSort upstream), falling
    #: back to upstream PrioritySort semantics
    queue_sort: Optional[Plugin] = None
    #: PostFilter preemption engine; None auto-selects from the enabled
    #: plugins (CapacityScheduling -> quota-aware preemption,
    #: PreemptionToleration -> default preemption with toleration)
    preemption: Optional[object] = None
    name: str = "tpu-scheduler"

    def __post_init__(self):
        if self.queue_sort is None:
            for plugin in self.plugins:
                if type(plugin).queue_key is not Plugin.queue_key or hasattr(
                    plugin, "queue_compare"
                ):
                    self.queue_sort = plugin
                    break
        if self.preemption is None:
            for plugin in self.plugins:
                if hasattr(plugin, "preemption_engine"):
                    self.preemption = plugin.preemption_engine()
                    break


class Scheduler:
    """Host shell around the jitted solve.

    Owns nothing but the profile; cluster state comes in as a snapshot and
    decisions go back to the caller (the `state.cluster.Cluster` store drives
    bind/park/reject)."""

    def __init__(self, profile: Profile):
        self.profile = profile
        self._solve_cache = {}

    # -- queue ----------------------------------------------------------
    def sort_pending(self, pods, cluster=None):
        """QueueSort: order the pending list with the profile's comparator
        (default: upstream PrioritySort — priority desc, then queue time).
        Plugins exposing a pairwise `queue_compare` (TopologicalSort) are
        used via cmp_to_key, preserving exact Less() semantics."""
        qs = self.profile.queue_sort
        if qs is not None and hasattr(qs, "queue_compare"):
            import functools

            return sorted(
                pods,
                key=functools.cmp_to_key(
                    lambda a, b: qs.queue_compare(a, b, cluster)
                ),
            )

        def key(pod):
            if qs is not None:
                k = qs.queue_key(pod, cluster)
                if k is not None:
                    return k
            return (-pod.priority, pod.creation_ms, f"{pod.namespace}/{pod.name}")

        return sorted(pods, key=key)

    # -- solve ----------------------------------------------------------
    def prepare(self, meta: SnapshotMeta, cluster=None):
        for plugin in self.profile.plugins:
            plugin.prepare(meta)
            if hasattr(plugin, "prepare_cluster"):
                plugin.prepare_cluster(meta, cluster)

    def _make_solve(self, unroll: int):
        plugins = tuple(self.profile.plugins)

        def step(carry, p, snap: ClusterSnapshot):
            state = carry
            # PreFilter
            ok = snap.pods.mask[p] & ~snap.pods.gated[p]
            for plugin in plugins:
                verdict = plugin.admit(state, snap, p)
                if verdict is not None:
                    ok &= verdict
            # Filter: built-in resource fit + plugin filters. Nominated
            # pods' demand holds capacity against lower-or-equal-priority
            # pods (upstream AddNominatedPods: priority >= evaluated pod,
            # same UID excluded); a batch nominee stops holding once placed.
            free_eff = state.free
            if snap.nominees is not None:
                nm = snap.nominees
                live = (
                    nm.mask
                    & (nm.priority >= snap.pods.priority[p])
                    & (nm.batch_idx != p)
                )
                if state.placed_mask is not None:
                    placed_in_batch = (nm.batch_idx >= 0) & state.placed_mask[
                        jnp.maximum(nm.batch_idx, 0)
                    ]
                    live &= ~placed_in_batch
                hold = jnp.zeros_like(state.free).at[
                    jnp.maximum(nm.node, 0)
                ].add(jnp.where(live[:, None], nm.demand, 0))
                free_eff = state.free - hold
            feasible = fits_one(snap.pods.req[p], free_eff, snap.nodes.mask)
            for plugin in plugins:
                mask = plugin.filter(state, snap, p)
                if mask is not None:
                    feasible &= mask
            feasible &= ok
            # Score + Normalize, weighted sum
            total = jnp.zeros(state.free.shape[0], jnp.int64)
            for plugin in plugins:
                raw = plugin.score(state, snap, p)
                if raw is not None:
                    total = total + plugin.weight * plugin.normalize(raw, feasible)
            # select: argmax score among feasible, lowest index tie-break
            masked = jnp.where(feasible, total, jnp.int64(-(2**62)))
            choice = jnp.where(
                feasible.any(), jnp.argmax(masked).astype(jnp.int32), jnp.int32(-1)
            )
            # built-in Reserve: commit capacity
            demand = pod_fit_demand(snap.pods.req[p])
            onehot = (jnp.arange(state.free.shape[0]) == choice)[:, None]
            state = state.replace(
                free=state.free - jnp.where(choice >= 0, onehot * demand[None, :], 0)
            )
            if state.placed_mask is not None:
                state = state.replace(
                    placed_mask=state.placed_mask.at[p].set(choice >= 0)
                )
            if snap.scheduling is not None:
                # built-in: selector/domain carries are shared by multiple
                # plugins (spread, inter-pod affinity) — commit once
                from scheduler_plugins_tpu.ops.selectors import commit_tracks

                state = commit_tracks(state, snap.scheduling, p, choice)
            for plugin in plugins:
                state = plugin.commit(state, snap, p, choice)
            return state, (choice, ok)

        def solve(
            snap: ClusterSnapshot, state0: SolverState, auxes
        ) -> SolveResult:
            # bind per-plugin traced aux inputs (weight vectors, cost
            # matrices) so they are solve ARGUMENTS, not baked constants
            for plugin, aux in zip(plugins, auxes):
                plugin.bind_aux(aux)
            # loop-invariant per-solve precomputes (hoisted out of the scan)
            for plugin in plugins:
                plugin.bind_presolve(plugin.prepare_solve(snap))
            P = snap.num_pods
            state, (assignment, admitted) = jax.lax.scan(
                lambda c, p: step(c, p, snap), state0, jnp.arange(P),
                unroll=unroll,
            )
            wait = jnp.zeros(P, bool)
            if snap.gangs is not None and state.gang_scheduled is not None:
                # Permit quorum: previously-assigned + this cycle's placements
                total_per_gang = snap.gangs.assigned + state.gang_scheduled
                quorum = total_per_gang >= snap.gangs.min_member
                gang = snap.pods.gang
                in_gang = gang >= 0
                pod_quorum = jnp.where(
                    in_gang, quorum[jnp.maximum(gang, 0)], True
                )
                wait = (assignment >= 0) & ~pod_quorum
            return SolveResult(
                assignment=assignment, admitted=admitted, wait=wait, state=state
            )

        return jax.jit(solve)

    def _scan_unroll(self) -> int:
        """Scan unroll factor: amortizes per-step loop overhead on TPU
        (~+20%); the body stays strictly one-pod-at-a-time (bit-faithful).
        CPU (tests) keeps 1 — extra compile time buys nothing there. The
        bench environment exposes the TPU through a tunneled backend whose
        platform name is "axon", so the default gates on device kind, not
        backend name. SPT_SCAN_UNROLL overrides for tuning — read host-side
        per solve and folded into the trace-cache key, so changing it
        retraces instead of being silently baked."""
        import os

        raw = os.environ.get("SPT_SCAN_UNROLL")
        if raw is None:
            return 8 if _is_tpu_backend() else 1
        try:
            unroll = int(raw)
        except ValueError:
            raise ValueError(f"SPT_SCAN_UNROLL={raw!r} is not an integer")
        if unroll < 1:
            raise ValueError(f"SPT_SCAN_UNROLL must be >= 1, got {unroll}")
        return unroll

    def solve(self, snap: ClusterSnapshot, state0: Optional[SolverState] = None):
        """Run the fused plugin pipeline over the snapshot's pending batch."""
        if state0 is None:
            state0 = self.initial_state(snap)
        auxes = tuple(plugin.aux() for plugin in self.profile.plugins)
        unroll = self._scan_unroll()
        key = ("solve", unroll) + tuple(
            plugin.static_key() for plugin in self.profile.plugins
        )
        if key not in self._solve_cache:
            self._solve_cache[key] = self._make_solve(unroll)
        return self._solve_cache[key](snap, state0, auxes)

    def filter_verdicts(self, snap: ClusterSnapshot, pod_index: int):
        """(N,) AND of the enabled plugins' Filter verdicts for one pod
        against the cycle-initial state (resource fit excluded — callers
        handle capacity themselves). Used by the preemption dry run, which
        mirrors RunFilterPluginsWithNominatedPods: plugin filters see the
        CURRENT cache state, exactly as the reference's re-filter does
        (removing victims from the NodeInfo does not change e.g. the NRT
        cache view the TopologyMatch filter reads)."""
        plugins = tuple(self.profile.plugins)
        key = ("filter_verdicts",) + tuple(p.static_key() for p in plugins)
        if key not in self._solve_cache:

            def verdicts(snap, state0, auxes, p):
                for plugin, aux in zip(plugins, auxes):
                    plugin.bind_aux(aux)
                # presolve deliberately NOT bound: it precomputes whole-batch
                # tensors to amortize a P-step scan, but this entry evaluates
                # ONE pod — the plugins' per-row fallbacks are cheaper here
                for plugin in plugins:
                    plugin.bind_presolve(None)
                feasible = jnp.ones(snap.num_nodes, bool)
                for plugin in plugins:
                    mask = plugin.filter(state0, snap, p)
                    if mask is not None:
                        feasible &= mask
                return feasible

            self._solve_cache[key] = jax.jit(verdicts)
        auxes = tuple(plugin.aux() for plugin in plugins)
        return self._solve_cache[key](
            snap, self.initial_state(snap), auxes, pod_index
        )

    def initial_state(self, snap: ClusterSnapshot) -> SolverState:
        free = free_capacity(snap.nodes.alloc, snap.nodes.requested)
        eq_used = snap.quota.used if snap.quota is not None else None
        gang_sched = None
        gang_inflight = None
        if snap.gangs is not None:
            G = snap.gangs.min_member.shape[0]
            gang_sched = jnp.zeros(G, jnp.int32)
            gang_inflight = jnp.zeros((G, snap.num_resources), jnp.int64)
        net_placed = (
            snap.network.placed_node if snap.network is not None else None
        )
        if snap.numa is not None:
            from scheduler_plugins_tpu.ops.numa import live_avail_init

            numa_avail = live_avail_init(snap.numa)
        else:
            numa_avail = None
        placed_mask = (
            jnp.zeros(snap.num_pods, bool)
            if snap.quota is not None or snap.nominees is not None
            else None
        )
        sel_counts = None
        sel_dom_counts = None
        anti_domains = None
        sym_counts = None
        if snap.scheduling is not None:
            if (
                snap.scheduling.track_node_base is not None
                and snap.scheduling.spread_needs_node_counts
            ):
                # the node-level carry is only materialized when a spread
                # eligibility row actually excludes a keyed node
                sel_counts = jnp.asarray(snap.scheduling.track_node_base)
            if snap.scheduling.track_base is not None:
                sel_dom_counts = jnp.asarray(snap.scheduling.track_base)
            if snap.scheduling.exist_anti_base is not None:
                anti_domains = jnp.asarray(snap.scheduling.exist_anti_base)
            if snap.scheduling.sym_base is not None:
                sym_counts = jnp.asarray(snap.scheduling.sym_base)
        return SolverState(
            free=free,
            eq_used=eq_used,
            gang_scheduled=gang_sched,
            gang_inflight=gang_inflight,
            net_placed=net_placed,
            numa_avail=numa_avail,
            placed_mask=placed_mask,
            sel_counts=sel_counts,
            sel_dom_counts=sel_dom_counts,
            anti_domains=anti_domains,
            sym_counts=sym_counts,
        )


def now_ms() -> int:
    return int(time.time() * 1000)
