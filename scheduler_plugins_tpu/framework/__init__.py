"""The scheduling framework runtime.

Tensor-shaped mirror of the upstream scheduler framework's extension points
(QueueSort / PreFilter / Filter / Score / Normalize / Reserve / Permit /
PostFilter — see SURVEY.md §1 L1): plugins contribute masked tensor
transformations instead of per-node callbacks, and the cycle driver fuses them
into one jitted solve over the whole pending batch.
"""

from scheduler_plugins_tpu.framework.cycle import (  # noqa: F401
    CycleReport,
    run_cycle,
)
from scheduler_plugins_tpu.framework.laned_cycle import (  # noqa: F401
    LanedCycle,
)
from scheduler_plugins_tpu.framework.pipeline_cycle import (  # noqa: F401
    CycleTimeline,
    PipelinedCycle,
)
from scheduler_plugins_tpu.framework.plugin import (  # noqa: F401
    Plugin,
    SolverState,
)
from scheduler_plugins_tpu.framework.runtime import (  # noqa: F401
    PackingConfig,
    Profile,
    Scheduler,
    SolveResult,
)
