"""Load-watcher metrics collector.

Mirror of the Trimaran Collector (/root/reference/pkg/trimaran/collector.go:
42-150): polls a load-watcher-compatible HTTP endpoint (`GET /watcher`) for
`WatcherMetrics` JSON —

    {"Window": {"Duration": "15m", "Start": ..., "End": ...},
     "Data": {"NodeMetricsMap": {
        "<node>": {"Metrics": [
            {"Type": "CPU"|"Memory", "Operator": "Latest"|"Average"|"Std",
             "Value": <float>, "Unit": ...}, ...]}}}}

— and folds it into the cluster store's `node_metrics` mapping (percent of
capacity, the exact GetResourceData selection rules: Average preferred,
Latest/empty operator as fallback, Std separate;
/root/reference/pkg/trimaran/resourcestats.go:88-106). The reference refreshes
every 30 seconds in a goroutine; here `refresh()` is explicit and the caller
owns the cadence (a thread or the cycle loop).
"""

from __future__ import annotations

import json
import urllib.request
from typing import Optional

#: metric type / operator strings (load-watcher watcher package)
CPU = "CPU"
MEMORY = "Memory"
LATEST = "Latest"
AVERAGE = "Average"
STD = "Std"

DEFAULT_REFRESH_SECONDS = 30  # collector.go:33


def parse_watcher_metrics(payload: dict) -> dict[str, dict]:
    """WatcherMetrics JSON -> per-node metric dict for `Cluster.node_metrics`."""
    out: dict[str, dict] = {}
    node_map = (payload.get("Data") or {}).get("NodeMetricsMap") or {}
    for node, node_metrics in node_map.items():
        entry: dict = {}
        cpu_avg_found = mem_avg_found = False
        for metric in node_metrics.get("Metrics", []):
            mtype = metric.get("Type")
            op = metric.get("Operator", "")
            value = float(metric.get("Value", 0.0))
            if mtype == CPU:
                if op == AVERAGE:
                    entry["cpu_avg"] = value
                    cpu_avg_found = True
                elif op == STD:
                    entry["cpu_std"] = value
                elif op in ("", LATEST) and not cpu_avg_found:
                    entry["cpu_avg"] = value
                if op in (AVERAGE, LATEST):
                    # TargetLoadPacking's own selection lets a later
                    # Latest override Average (targetloadpacking.go:130-139)
                    entry["cpu_tlp"] = value
                    # Peaks breaks on the FIRST Average-or-Latest sample
                    # (peaks.go:118-131)
                    entry.setdefault("cpu_peaks", value)
            elif mtype == MEMORY:
                if op == AVERAGE:
                    entry["mem_avg"] = value
                    mem_avg_found = True
                elif op == STD:
                    entry["mem_std"] = value
                elif op in ("", LATEST) and not mem_avg_found:
                    entry["mem_avg"] = value
        if entry:
            out[node] = entry
    return out


class AsyncLoadWatcherCollector:
    """Cadence-owning collector: polls in a background thread so a slow or
    dead watcher never blocks the scheduling cycle (the reference polls in
    its own goroutine, collector.go:89-97). Completed fetches REPLACE this
    source's previous contribution in the store — nodes the watcher stopped
    reporting are evicted (falling back to the neutral no-metrics path), and
    other sources' nodes are untouched. Failures keep the previous data."""

    def __init__(self, client,
                 refresh_seconds: int = DEFAULT_REFRESH_SECONDS):
        # back-compat: a bare address selects the HTTP service client
        self.collector = (
            LoadWatcherCollector(client) if isinstance(client, str) else client
        )
        self.refresh_ms = refresh_seconds * 1000
        self.last_ms: Optional[int] = None
        self.latest: Optional[dict] = None
        self.my_nodes: set[str] = set()
        self.thread = None

    def tick(self, cluster, now_ms: int) -> None:
        """Install any completed fetch; start a new one when the cadence is
        due and none is in flight. Never blocks."""
        import threading

        latest = self.latest
        if latest is not None:
            current = cluster.node_metrics or {}
            merged = {
                node: m for node, m in current.items()
                if node not in self.my_nodes or node in latest
            }
            merged.update(latest)
            cluster.node_metrics = merged
            self.my_nodes = set(latest)
            self.latest = None
        due = self.last_ms is None or now_ms - self.last_ms >= self.refresh_ms
        in_flight = self.thread is not None and self.thread.is_alive()
        if not due or in_flight:
            return
        self.last_ms = now_ms

        def fetch():
            try:
                self.latest = self.collector.fetch()
            except Exception:  # graft-lint: ignore[GL010] — reference cache behavior: a failed fetch keeps the previous metrics window
                pass

        self.thread = threading.Thread(
            target=fetch, daemon=True, name="load-watcher",
        )
        self.thread.start()


class LoadWatcherCollector:
    """HTTP client against a load-watcher service (`WatcherAddress` arg,
    apis/config TrimaranSpec)."""

    def __init__(self, watcher_address: str, timeout_s: float = 5.0):
        self.watcher_address = watcher_address.rstrip("/")
        self.timeout_s = timeout_s
        self.last_payload: Optional[dict] = None

    def fetch(self) -> dict[str, dict]:
        with urllib.request.urlopen(
            f"{self.watcher_address}/watcher", timeout=self.timeout_s
        ) as resp:
            self.last_payload = json.loads(resp.read())
        return parse_watcher_metrics(self.last_payload)

    def refresh(self, cluster) -> dict[str, dict]:
        """One collector tick: fetch and install into the cluster store.
        On failure the previous metrics stay (the reference keeps serving the
        cached WatcherMetrics when a fetch errors)."""
        try:
            metrics = self.fetch()
        except Exception:
            return cluster.node_metrics or {}
        cluster.node_metrics = metrics
        return metrics


#: MetricProviderSpec.Type values (apis/config/types.go:73-79)
METRIC_PROVIDER_TYPES = (
    "KubernetesMetricsServer", "Prometheus", "SignalFx",
)


def _authed_get(address: str, path_and_query: str, token: str,
                insecure_skip_verify: bool, timeout_s: float,
                auth_header: str = "Authorization",
                auth_prefix: str = "Bearer ") -> dict:
    """One GET with optional token auth / unverified TLS — the HTTP
    plumbing all library-mode clients share (SignalFx overrides the header
    to X-SF-TOKEN)."""
    import ssl

    req = urllib.request.Request(address + path_and_query)
    if token:
        req.add_header(auth_header, f"{auth_prefix}{token}")
    ctx = None
    if insecure_skip_verify and address.startswith("https"):
        ctx = ssl._create_unverified_context()
    with urllib.request.urlopen(req, timeout=timeout_s, context=ctx) as resp:
        return json.loads(resp.read())


class PrometheusCollector:
    """Library-mode metrics client for `MetricProvider.Type: Prometheus` —
    the in-process equivalent of load-watcher's prometheus provider
    (/root/reference/pkg/trimaran/collector.go:63-73 NewLibraryClient).
    Queries the Prometheus HTTP API for per-node cpu/memory utilisation
    percentages; samples land as Average metrics (the provider aggregates
    over its range window)."""

    CPU_QUERY = (
        '100 - (avg by (instance) '
        '(rate(node_cpu_seconds_total{mode="idle"}[15m])) * 100)'
    )
    MEM_QUERY = (
        "100 * (1 - avg_over_time(node_memory_MemAvailable_bytes[15m]) "
        "/ node_memory_MemTotal_bytes)"
    )

    def __init__(self, address: str, token: str = "",
                 insecure_skip_verify: bool = False, timeout_s: float = 5.0):
        if not address:
            raise ValueError("Prometheus metric provider requires an address")
        self.address = address.rstrip("/")
        self.token = token
        self.insecure_skip_verify = insecure_skip_verify
        self.timeout_s = timeout_s

    def _query(self, promql: str) -> dict[str, float]:
        import urllib.parse

        payload = _authed_get(
            self.address,
            f"/api/v1/query?query={urllib.parse.quote(promql)}",
            self.token, self.insecure_skip_verify, self.timeout_s,
        )
        out: dict[str, float] = {}
        for result in (payload.get("data") or {}).get("result", []):
            instance = (result.get("metric") or {}).get("instance", "")
            # instance labels commonly carry the scrape port
            node = instance.split(":")[0]
            try:
                out[node] = float(result["value"][1])
            except (KeyError, IndexError, TypeError, ValueError):
                continue
        return out

    def fetch(self) -> dict[str, dict]:
        cpu = self._query(self.CPU_QUERY)
        mem = self._query(self.MEM_QUERY)
        out: dict[str, dict] = {}
        for node, value in cpu.items():
            out.setdefault(node, {}).update(
                {"cpu_avg": value, "cpu_tlp": value, "cpu_peaks": value}
            )
        for node, value in mem.items():
            out.setdefault(node, {})["mem_avg"] = value
        return out


_QUANTITY_SUFFIXES = {
    # decimal (incl. the sub-unit suffixes metrics-server emits: real
    # node CPU usage comes back in nanocores, e.g. "236786820n")
    "n": 1e-9, "u": 1e-6, "m": 1e-3,
    "k": 10**3, "M": 10**6, "G": 10**9, "T": 10**12, "P": 10**15,
    "E": 10**18,
    # binary
    "Ki": 1 << 10, "Mi": 1 << 20, "Gi": 1 << 30, "Ti": 1 << 40,
    "Pi": 1 << 50, "Ei": 1 << 60,
}


def parse_quantity_millis(text: str) -> int:
    """resource.Quantity string -> integer MILLI-units ("250m" -> 250,
    "2" -> 2000, "236786820n" -> 236, "1Gi" -> 1024^3 * 1000). Shared by
    cpu (millicores) and memory (millibytes — the caller divides
    percentages, so the scale cancels)."""
    text = str(text).strip()
    for suffix, mult in sorted(
        _QUANTITY_SUFFIXES.items(), key=lambda kv: -len(kv[0])
    ):
        if text.endswith(suffix):
            return int(float(text[: -len(suffix)]) * mult * 1000)
    return int(float(text) * 1000)


class KubernetesMetricsServerCollector:
    """Library-mode client for `MetricProvider.Type: KubernetesMetricsServer`
    — the in-process equivalent of load-watcher's metrics-server provider
    (/root/reference/pkg/trimaran/collector.go:63-73 NewLibraryClient).

    Plain HTTP against the aggregated metrics API (no SDK):
    `GET /apis/metrics.k8s.io/v1beta1/nodes` for usage and
    `GET /api/v1/nodes` for capacity, both on the apiserver `address`;
    utilisation lands as Average percentages like the other providers."""

    METRICS_PATH = "/apis/metrics.k8s.io/v1beta1/nodes"
    NODES_PATH = "/api/v1/nodes"

    def __init__(self, address: str, token: str = "",
                 insecure_skip_verify: bool = False, timeout_s: float = 5.0):
        if not address:
            raise ValueError(
                "KubernetesMetricsServer metric provider requires an address"
            )
        self.address = address.rstrip("/")
        self.token = token
        self.insecure_skip_verify = insecure_skip_verify
        self.timeout_s = timeout_s

    def _get(self, path: str) -> dict:
        return _authed_get(self.address, path, self.token,
                           self.insecure_skip_verify, self.timeout_s)

    def fetch(self) -> dict[str, dict]:
        usage = {
            item["metadata"]["name"]: item.get("usage", {})
            for item in self._get(self.METRICS_PATH).get("items", [])
        }
        capacity = {}
        for item in self._get(self.NODES_PATH).get("items", []):
            status = item.get("status", {})
            capacity[item["metadata"]["name"]] = (
                status.get("capacity") or status.get("allocatable") or {}
            )
        out: dict[str, dict] = {}
        for node, use in usage.items():
            cap = capacity.get(node)
            if not cap:
                continue
            entry: dict = {}
            for res, keys in (
                ("cpu", ("cpu_avg", "cpu_tlp", "cpu_peaks")),
                ("memory", ("mem_avg",)),
            ):
                if res not in use or res not in cap:
                    continue
                cap_m = parse_quantity_millis(cap[res])
                if cap_m <= 0:
                    continue
                pct = 100.0 * parse_quantity_millis(use[res]) / cap_m
                for key in keys:
                    entry[key] = pct
            if entry:
                out[node] = entry
        return out


class SignalFxCollector:
    """Library-mode client for `MetricProvider.Type: SignalFx` — the
    in-process equivalent of load-watcher's SignalFx provider selected by
    the reference's collector (/root/reference/pkg/trimaran/collector.go:
    63-73 NewLibraryClient; type constant apis/config/types.go:77).

    Plain HTTP against the SignalFx REST API (no SDK, same pattern as the
    Prometheus / metrics-server clients):

    - `GET /v1/timeserieswindow?query=sf_metric:"cpu.utilization"` (and
      `memory.utilization`) with `X-SF-TOKEN` auth pulls the last window of
      samples for every reporting time series;
    - time-series ids resolve to their `host` dimension via ONE bulk
      metadata query per metric (`GET /v2/metrictimeseries?query=...`),
      falling back to per-tsid lookups only for ids the bulk result missed;
      the tsid->host map is cached across fetches (tsids are stable, so
      steady-state fetches cost two requests total).

    Window samples average into an Average-operator percentage like the
    other providers (cpu/memory utilization metrics are already percent of
    capacity)."""

    TIMESERIES_PATH = "/v1/timeserieswindow"
    METADATA_PATH = "/v2/metrictimeseries/"
    CPU_METRIC = "cpu.utilization"
    MEM_METRIC = "memory.utilization"
    WINDOW_MS = 10 * 60 * 1000

    def __init__(self, address: str, token: str = "",
                 insecure_skip_verify: bool = False, timeout_s: float = 5.0):
        if not address:
            raise ValueError("SignalFx metric provider requires an address")
        self.address = address.rstrip("/")
        self.token = token
        self.insecure_skip_verify = insecure_skip_verify
        self.timeout_s = timeout_s
        self._tsid_host: dict[str, str] = {}
        self.last_error: Optional[str] = None

    def _get(self, path_and_query: str) -> dict:
        """SignalFx auth rides the X-SF-TOKEN header, not a Bearer token."""
        return _authed_get(
            self.address, path_and_query, self.token,
            self.insecure_skip_verify, self.timeout_s,
            auth_header="X-SF-TOKEN", auth_prefix="",
        )

    def _warn_once(self, message: str) -> None:
        """Record the FIRST metadata-resolution failure of the current fetch
        in `last_error` and emit one warning for it; repeats within the same
        fetch are counted by the caller retrying next fetch, not re-warned
        (a bad address/token would otherwise flood — or, before this hook
        existed, read as silently-empty metrics)."""
        if self.last_error is None:
            import warnings

            warnings.warn(f"SignalFx collector: {message}", stacklevel=3)
        self.last_error = message

    @staticmethod
    def _meta_host(meta: dict) -> str:
        return str((meta.get("dimensions") or {}).get("host", "")
                   or meta.get("host", ""))

    def _resolve_hosts(self, tsids, metric: str) -> None:
        """Fill the tsid->host cache for any unresolved ids: one bulk
        metadata query for the metric, then per-tsid fallback for stragglers
        (avoids N serial lookups on a cold cache)."""
        import urllib.parse

        missing = [t for t in tsids if t not in self._tsid_host]
        if not missing:
            return
        query = urllib.parse.quote(f'sf_metric:"{metric}"')
        try:
            bulk = self._get(
                f"{self.METADATA_PATH.rstrip('/')}?query={query}"
                f"&limit={max(len(missing) * 2, 1000)}"
            )
            for item in bulk.get("results", []):
                tsid = str(item.get("id", ""))
                host = self._meta_host(item)
                # only cache RESOLVED hosts: a series whose metadata has no
                # host dimension yet (indexing lag) must retry next fetch,
                # not be suppressed forever
                if tsid and host:
                    self._tsid_host[tsid] = host
        except Exception as exc:
            # fall through to per-tsid lookups, but surface the failure: a
            # bad address/token would otherwise read as silently-empty
            # metrics (warn once per fetch, not once per tsid)
            self._warn_once(f"bulk metadata query failed: {exc!r}")
        for tsid in missing:
            if tsid in self._tsid_host:
                continue
            try:
                meta = self._get(self.METADATA_PATH + tsid)
            except Exception as exc:
                self._warn_once(f"metadata lookup for tsid {tsid} failed: "
                                f"{exc!r}")
                continue  # transient: retry next fetch, don't cache
            host = self._meta_host(meta)
            if host:
                self._tsid_host[tsid] = host

    def _metric_by_host(self, metric: str) -> dict[str, float]:
        import time as _time
        import urllib.parse

        end_ms = int(_time.time() * 1000)
        query = urllib.parse.quote(f'sf_metric:"{metric}"')
        payload = self._get(
            f"{self.TIMESERIES_PATH}?query={query}"
            f"&startMs={end_ms - self.WINDOW_MS}&endMs={end_ms}"
        )
        series = {
            tsid: [
                float(point[1]) for point in samples
                if isinstance(point, (list, tuple)) and len(point) >= 2
            ]
            for tsid, samples in (payload.get("data") or {}).items()
        }
        self._resolve_hosts([t for t, v in series.items() if v], metric)
        # multiple tsids can resolve to one host (agent restart leaves the
        # old and new series both inside the window) — pool their samples
        by_host: dict[str, list] = {}
        for tsid, values in series.items():
            if not values:
                continue
            host = self._tsid_host.get(tsid)
            if host:
                by_host.setdefault(host, []).extend(values)
        return {
            host: sum(values) / len(values)
            for host, values in by_host.items()
        }

    def fetch(self) -> dict[str, dict]:
        self.last_error = None
        cpu = self._metric_by_host(self.CPU_METRIC)
        mem = self._metric_by_host(self.MEM_METRIC)
        out: dict[str, dict] = {}
        for node, value in cpu.items():
            out.setdefault(node, {}).update(
                {"cpu_avg": value, "cpu_tlp": value, "cpu_peaks": value}
            )
        for node, value in mem.items():
            out.setdefault(node, {})["mem_avg"] = value
        return out


def make_metrics_client(watcher_address: Optional[str] = None,
                        metric_provider: Optional[dict] = None):
    """collector.go:60-73: a WatcherAddress selects the remote service
    client; otherwise the MetricProviderSpec selects an in-process library
    client (Prometheus, KubernetesMetricsServer and SignalFx all bundled as
    plain-HTTP clients)."""
    if watcher_address:
        return LoadWatcherCollector(watcher_address)
    mp = metric_provider or {}
    mtype = mp.get("type", "KubernetesMetricsServer")
    if mtype not in METRIC_PROVIDER_TYPES:
        raise ValueError(f"invalid metric provider type {mtype!r}")
    cls = {
        "Prometheus": PrometheusCollector,
        "KubernetesMetricsServer": KubernetesMetricsServerCollector,
        "SignalFx": SignalFxCollector,
    }[mtype]
    return cls(
        mp.get("address", ""),
        token=mp.get("token", ""),
        insecure_skip_verify=bool(mp.get("insecureSkipVerify", False)),
    )
