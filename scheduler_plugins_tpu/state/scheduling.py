"""In-tree scheduling-spec tensors: taints/tolerations, node affinity, and
pod-label selector/topology-domain counting (topology spread, pod affinity).

The upstream kube-scheduler plugins NodeAffinity, TaintToleration,
PodTopologySpread and InterPodAffinity are not part of the reference repo,
but every real KubeSchedulerConfiguration profile combines the reference's
plugins with them (docs/PARITY.md "companion plugins"). Their semantics are
label/taint matching — string work that does not belong on the TPU. The
TPU-first formulation:

- intern each pod's node-filter spec (nodeSelector + required node affinity)
  and toleration set into a small set of UNIQUE specs (workload replicas
  share specs), evaluate each unique spec against every node ONCE host-side
  (numpy bools), and hand the solver dense lookup tables:

      node_term_ok  (T+1, N) bool   required-affinity verdict per spec
      pref_score    (U+1, N) int64  summed weights of matching preferred terms
      tol_ok        (T2, N) bool    no untolerated NoSchedule/NoExecute taint
      tol_prefer    (T2, N) int64   untolerated PreferNoSchedule taint count

  The per-pod Filter/Score inside the jitted solve is then a single row
  gather — O(1) per (pod, node) regardless of expression complexity.

- intern the pod-label selectors of spread constraints / affinity terms into
  S unique (namespace-scope, selector) groups and the topology keys into K
  codes; count matching ASSIGNED pods per (group, node) once host-side
  (`sel_base`), and record which PENDING pods match each group
  (`pend_match`) so the solver can carry live counts through in-cycle
  placements (`SolverState.sel_counts`). Per-domain aggregation is then a
  segment-sum over `topo_code` rows inside the jitted solve.

Row T (pad row) of `node_term_ok` is all-true: pods with no node constraint
index it. `pref_score` row U is all-zero. Toleration sets always index a
real row (the empty set is a legitimate set that tolerates nothing).
"""

from __future__ import annotations

from typing import Optional, Sequence

import numpy as np
from flax import struct

from scheduler_plugins_tpu.api.objects import (
    LabelSelector,
    LabelSelectorRequirement,
    Node,
    Pod,
)

I64 = np.int64
I32 = np.int32

#: Static scheduling-table bases with a LIVE SolverState carry counterpart
#: (pytree path relative to the snapshot root -> carry field name) — the
#: selector/topology-domain counts seeded host-side and then carried through
#: in-cycle placements. Companion map to
#: `state.snapshot.CARRY_COUNTERPARTS`; consumed by `tools/jaxpr_audit.py`
#: rule JA001 (a compiled solve must not derive live counts from these
#: static bases while the carry is dead).
TRACK_CARRY_COUNTERPARTS = {
    ".scheduling.track_node_base": "sel_counts",
    ".scheduling.track_base": "sel_dom_counts",
    ".scheduling.exist_anti_base": "anti_domains",
    ".scheduling.sym_base": "sym_counts",
}


@struct.dataclass
class SchedulingState:
    """Dense lookup tables for the in-tree companion plugins."""

    node_term_ok: np.ndarray  # (T+1, N) bool
    pod_node_term: np.ndarray  # (P,) int32 row index (T = unconstrained)
    pref_score: np.ndarray  # (U+1, N) int64
    pod_pref: np.ndarray  # (P,) int32 row index (U = no preferences)
    tol_ok: np.ndarray  # (T2, N) bool
    tol_prefer: np.ndarray  # (T2, N) int64
    pod_tol: np.ndarray  # (P,) int32 row index
    # --- selector/topology-domain counting (spread + inter-pod affinity);
    # None when no pending pod carries such constraints. A "track" is a
    # unique (selector group, topology key) pair; live counts are carried
    # per (track, domain) — (TR, D) — so the per-pod checks and per-
    # placement commits are O(constraints x domains), never O(N) ----------
    pend_match: Optional[np.ndarray] = None  # (S, P) bool pod in sel group
    topo_code: Optional[np.ndarray] = None  # (K, N) int32 domain code (-1)
    topo_has: Optional[np.ndarray] = None  # (K, N) bool key present
    domain_exists: Optional[np.ndarray] = None  # (K, D) bool
    track_sel: Optional[np.ndarray] = None  # (TR,) int32 selector group
    track_topo: Optional[np.ndarray] = None  # (TR,) int32 key code
    #: (TR, N) int64 matching ASSIGNED pods per NODE. Node-level (not
    #: domain-level) so PodTopologySpread's nodeAffinityPolicy /
    #: nodeTaintsPolicy can exclude ineligible nodes' pods per (pod,
    #: constraint) at aggregation time.
    track_node_base: Optional[np.ndarray] = None
    #: (TR, D) the same counts per topology domain (nodes with the key
    #: only) — InterPodAffinity's O(1)-gather view
    track_base: Optional[np.ndarray] = None
    # per-pod spread constraints, padded to CT
    spread_track: Optional[np.ndarray] = None  # (P, CT) int32 track index
    spread_topo: Optional[np.ndarray] = None  # (P, CT) int32 key code
    spread_max_skew: Optional[np.ndarray] = None  # (P, CT) int64
    spread_hard: Optional[np.ndarray] = None  # (P, CT) bool DoNotSchedule
    spread_self: Optional[np.ndarray] = None  # (P, CT) bool pod matches own sel
    spread_mask: Optional[np.ndarray] = None  # (P, CT) bool
    #: (P, CT) int64 minDomains (0 = unset): when fewer ELIGIBLE domains
    #: than this exist, the global minimum is treated as 0 (upstream
    #: podtopologyspread minMatchNum)
    spread_min_domains: Optional[np.ndarray] = None
    #: (P, CT) bool nodeAffinityPolicy == Honor: only nodes matching the
    #: pod's nodeSelector/required affinity count toward domains/minimum
    spread_policy_affinity: Optional[np.ndarray] = None
    #: (P, CT) bool nodeTaintsPolicy == Honor: only nodes whose
    #: NoSchedule/NoExecute taints the pod tolerates count
    spread_policy_taints: Optional[np.ndarray] = None
    #: (EL, N) bool interned node-eligibility rows (class-keys x policies),
    #: fully static -> precomputed host-side; (P, CT) row index
    spread_elig: Optional[np.ndarray] = None
    spread_elig_idx: Optional[np.ndarray] = None
    #: STATIC python bool (not a pytree leaf): True only when some (pod,
    #: constraint) eligibility row actually excludes a node that carries
    #: the constraint's key. False -> the spread plugin reads the O(1)
    #: (TR, D) domain mirror and the (TR, N) node carry is not materialized
    spread_needs_node_counts: bool = struct.field(
        pytree_node=False, default=False
    )
    # per-pod inter-pod affinity terms, padded to AT/BT/WT. `*_self` marks
    # the upstream first-pod special case: the term matches the incoming
    # pod itself, so an otherwise-empty cluster does not deadlock.
    aff_track: Optional[np.ndarray] = None  # (P, AT) int32 required affinity
    aff_topo: Optional[np.ndarray] = None  # (P, AT) int32 key code
    aff_self: Optional[np.ndarray] = None  # (P, AT) bool
    aff_mask: Optional[np.ndarray] = None  # (P, AT) bool
    anti_track: Optional[np.ndarray] = None  # (P, BT) int32 required anti
    anti_topo: Optional[np.ndarray] = None  # (P, BT) int32
    anti_mask: Optional[np.ndarray] = None  # (P, BT) bool
    # preferred (anti-)affinity terms: weighted domain-count scoring
    waff_track: Optional[np.ndarray] = None  # (P, WT) int32
    waff_topo: Optional[np.ndarray] = None  # (P, WT) int32
    waff_weight: Optional[np.ndarray] = None  # (P, WT) int64 (negative=anti)
    waff_mask: Optional[np.ndarray] = None  # (P, WT) bool
    # EXISTING pods' required anti-affinity (symmetry): an incoming pod
    # matching group `exist_anti_sel[e]` is blocked on nodes whose domain
    # (under `exist_anti_topo[e]`) hosts a pod carrying term e. Domain
    # presence is carried live (`SolverState.anti_domains`) because pending
    # pods' own anti terms join E and their placements create new blocks.
    exist_anti_sel: Optional[np.ndarray] = None  # (E,) int32 selector group
    exist_anti_topo: Optional[np.ndarray] = None  # (E,) int32 key code
    exist_anti_base: Optional[np.ndarray] = None  # (E, D) bool assigned
    #: (E, P) which pending pods carry term e (their placement marks the
    #: domain) — identity, not selector match
    exist_anti_carrier: Optional[np.ndarray] = None
    #: (E, P) which pending pods MATCH term e's selector (they get blocked)
    exist_anti_match: Optional[np.ndarray] = None
    # Symmetric SCORE terms (upstream interpodaffinity PreScore): each
    # existing pod's preferred (anti-)affinity terms add +-weight, and its
    # REQUIRED affinity terms add HardPodAffinityWeight, to every node in
    # the existing pod's domain when the term's selector matches the
    # INCOMING pod. E2 axis = unique (selector, key, weight, hard) tuples.
    sym_sel: Optional[np.ndarray] = None  # (E2,) int32 selector group
    sym_topo: Optional[np.ndarray] = None  # (E2,) int32 key code
    sym_weight: Optional[np.ndarray] = None  # (E2,) int64 (+-w; hard rows 1)
    sym_hard: Optional[np.ndarray] = None  # (E2,) bool required-term rows
    sym_base: Optional[np.ndarray] = None  # (E2, D) int64 carrier counts
    #: (E2, P) how many of pending pod q's terms are row e2 — q's
    #: placement adds that many carriers to its domain
    sym_carrier: Optional[np.ndarray] = None


def _node_filter_key(pod: Pod):
    return (
        tuple(sorted(pod.node_selector.items())),
        tuple(
            (
                tuple(
                    (r.key, r.operator, tuple(r.values))
                    for r in term.match_expressions
                ),
                tuple(
                    (r.key, r.operator, tuple(r.values))
                    for r in term.match_fields
                ),
            )
            for term in pod.node_affinity_required
        ),
    )


def _pref_key(pod: Pod):
    return tuple(
        (
            t.weight,
            tuple(
                (r.key, r.operator, tuple(r.values))
                for r in t.preference.match_expressions
            ),
            tuple(
                (r.key, r.operator, tuple(r.values))
                for r in t.preference.match_fields
            ),
        )
        for t in pod.node_affinity_preferred
    )


def _tol_key(pod: Pod):
    return tuple(
        sorted(
            (t.key, t.operator, t.value, t.effect) for t in pod.tolerations
        )
    )


def _node_filter_matches(pod: Pod, node: Node) -> bool:
    """spec.nodeSelector AND (OR over required affinity terms) — upstream
    component-helpers nodeaffinity.GetRequiredNodeAffinity semantics."""
    for k, v in pod.node_selector.items():
        if node.labels.get(k) != v:
            return False
    if pod.node_affinity_required:
        return any(t.matches(node) for t in pod.node_affinity_required)
    return True


def _has_selector_specs(pending, assigned) -> bool:
    # assigned pods' terms matter too: required anti (symmetry blocks) and
    # preferred/required affinity (symmetric score toward incoming pods)
    return any(
        p.topology_spread
        or p.pod_affinity_required
        or p.pod_anti_affinity_required
        or p.pod_affinity_preferred
        or p.pod_anti_affinity_preferred
        for p in pending
    ) or any(
        p.pod_anti_affinity_required
        or p.pod_affinity_required
        or p.pod_affinity_preferred
        or p.pod_anti_affinity_preferred
        for p in assigned
    )


def relevant(nodes, pending, assigned=()) -> bool:
    """Whether any spec exists that makes the tables non-trivial."""
    return (
        any(n.taints for n in nodes)
        or any(
            p.node_selector
            or p.node_affinity_required
            or p.node_affinity_preferred
            for p in pending
        )
        or _has_selector_specs(pending, assigned)
    )


def build_scheduling(
    nodes: Sequence[Node],
    pending: Sequence[Pod],
    N: int,
    P: int,
    assigned: Sequence[Pod] = (),
    namespaces: Sequence = (),
) -> Optional[SchedulingState]:
    """Lower specs into `SchedulingState`; None when nothing is relevant.
    `namespaces` are the cluster's Namespace objects — the
    PodAffinityTerm.namespaceSelector targets."""
    if not relevant(nodes, pending, assigned):
        return None

    term_rows: dict = {}
    pref_rows: dict = {}
    tol_rows: dict = {}
    pod_node_term = np.zeros(P, I32)
    pod_pref = np.zeros(P, I32)
    pod_tol = np.zeros(P, I32)
    term_pods: list[Pod] = []
    pref_pods: list[Pod] = []
    tol_pods: list[Pod] = []
    for i, pod in enumerate(pending):
        if pod.node_selector or pod.node_affinity_required:
            k = _node_filter_key(pod)
            if k not in term_rows:
                term_rows[k] = len(term_rows)
                term_pods.append(pod)
            pod_node_term[i] = term_rows[k]
        else:
            pod_node_term[i] = -1  # remapped to the all-true pad row below
        if pod.node_affinity_preferred:
            k = _pref_key(pod)
            if k not in pref_rows:
                pref_rows[k] = len(pref_rows)
                pref_pods.append(pod)
            pod_pref[i] = pref_rows[k]
        else:
            pod_pref[i] = -1
        k = _tol_key(pod)
        if k not in tol_rows:
            tol_rows[k] = len(tol_rows)
            tol_pods.append(pod)
        pod_tol[i] = tol_rows[k]

    T, U, T2 = len(term_rows), len(pref_rows), max(len(tol_rows), 1)
    node_term_ok = np.zeros((T + 1, N), bool)
    node_term_ok[T] = True  # unconstrained row
    pref_score = np.zeros((U + 1, N), I64)
    tol_ok = np.ones((T2, N), bool)
    tol_prefer = np.zeros((T2, N), I64)

    for t, pod in enumerate(term_pods):
        for n, node in enumerate(nodes):
            node_term_ok[t, n] = _node_filter_matches(pod, node)
    for u, pod in enumerate(pref_pods):
        for n, node in enumerate(nodes):
            pref_score[u, n] = sum(
                t.weight
                for t in pod.node_affinity_preferred
                if t.preference.matches(node)
            )
    for s, pod in enumerate(tol_pods):
        for n, node in enumerate(nodes):
            for taint in node.taints:
                if any(t.tolerates(taint) for t in pod.tolerations):
                    continue
                if taint.effect in ("NoSchedule", "NoExecute"):
                    tol_ok[s, n] = False
                elif taint.effect == "PreferNoSchedule":
                    tol_prefer[s, n] += 1

    return SchedulingState(
        node_term_ok=node_term_ok,
        pod_node_term=np.where(pod_node_term < 0, T, pod_node_term).astype(I32),
        pref_score=pref_score,
        pod_pref=np.where(pod_pref < 0, U, pod_pref).astype(I32),
        tol_ok=tol_ok,
        tol_prefer=tol_prefer,
        pod_tol=pod_tol,
        **_build_selector_tables(
            nodes, pending, assigned, N, P, namespaces,
            pod_aff_rows=node_term_ok[
                np.where(pod_node_term < 0, T, pod_node_term)
            ],
            pod_tol_rows=tol_ok[pod_tol],
        ),
    )


def _merged_spread_selector(pod: Pod, tsc):
    """matchLabelKeys (upstream podtopologyspread): the incoming pod's
    values for the listed keys are appended to the selector as exact-match
    requirements; keys the pod lacks are ignored; a nil selector stays nil
    (matches nothing)."""
    sel = tsc.label_selector
    if sel is None or not tsc.match_label_keys:
        return sel
    extra = [
        k for k in tsc.match_label_keys if k in pod.labels
    ]
    if not extra:
        return sel
    return LabelSelector(
        match_labels=dict(sel.match_labels),
        match_expressions=list(sel.match_expressions)
        + [
            LabelSelectorRequirement(k, "In", (pod.labels[k],))
            for k in extra
        ],
    )


def _term_scope(pod: Pod, term, namespaces) -> tuple:
    """Effective namespace scope of a PodAffinityTerm: the explicit list
    plus namespaces matching namespaceSelector (EMPTY selector matches
    every namespace -> the "*" wildcard scope). The own-namespace fallback
    applies ONLY when the list is empty AND the selector is nil — a
    non-nil selector matching zero namespaces yields an empty scope that
    matches nothing (upstream GetNamespaceLabelsSnapshot semantics)."""
    scope = set(term.namespaces)
    sel = getattr(term, "namespace_selector", None)
    if sel is not None:
        if not sel.match_labels and not sel.match_expressions:
            return ("*",)
        scope.update(ns.name for ns in namespaces if sel.matches(ns.labels))
    elif not scope:
        scope = {pod.namespace}
    return tuple(sorted(scope))


def _build_selector_tables(
    nodes, pending, assigned, N, P, namespaces=(),
    pod_aff_rows=None, pod_tol_rows=None,
) -> dict:
    """Selector-group / topology-domain / track tables for PodTopologySpread
    and InterPodAffinity: a track = unique (selector group, topology key)
    pair; assigned pods aggregate into per-(track, domain) base counts;
    existing/pending required anti-affinity terms form the E axis."""
    if not _has_selector_specs(pending, assigned):
        return {}

    sels: dict = {}  # (ns scope, selector key) -> index
    sel_objs: list = []  # (ns tuple, LabelSelector-or-None)
    keys: dict = {}  # topology key -> index
    key_names: list[str] = []
    tracks: dict = {}  # (sel idx, key idx) -> track index

    def sel_id(ns_scope: tuple, selector) -> int:
        k = (ns_scope, None if selector is None else selector._key())
        if k not in sels:
            sels[k] = len(sels)
            sel_objs.append((ns_scope, selector))
        return sels[k]

    def key_id(name: str) -> int:
        if name not in keys:
            keys[name] = len(keys)
            key_names.append(name)
        return keys[name]

    def track_id(s: int, k: int) -> int:
        if (s, k) not in tracks:
            tracks[(s, k)] = len(tracks)
        return tracks[(s, k)]

    def term_ids(pod: Pod, term) -> tuple[int, int, int]:
        """(sel, key, track) for a PodAffinityTerm scoped to the pod."""
        scope = _term_scope(pod, term, namespaces)
        s = sel_id(scope, term.label_selector)
        k = key_id(term.topology_key)
        return s, k, track_id(s, k)

    CT = max((len(p.topology_spread) for p in pending), default=1) or 1
    spread_track = np.zeros((P, CT), I32)
    spread_topo = np.zeros((P, CT), I32)
    spread_max_skew = np.zeros((P, CT), I64)
    spread_hard = np.zeros((P, CT), bool)
    spread_self = np.zeros((P, CT), bool)
    spread_mask = np.zeros((P, CT), bool)
    spread_min_domains = np.zeros((P, CT), I64)
    spread_policy_affinity = np.zeros((P, CT), bool)
    spread_policy_taints = np.zeros((P, CT), bool)
    for i, pod in enumerate(pending):
        for c, tsc in enumerate(pod.topology_spread):
            sel = _merged_spread_selector(pod, tsc)
            s = sel_id((pod.namespace,), sel)
            k = key_id(tsc.topology_key)
            spread_track[i, c] = track_id(s, k)
            spread_topo[i, c] = k
            spread_max_skew[i, c] = tsc.max_skew
            spread_hard[i, c] = tsc.when_unsatisfiable == "DoNotSchedule"
            spread_self[i, c] = _sel_matches(sel, (pod.namespace,), pod)
            spread_mask[i, c] = True
            spread_min_domains[i, c] = tsc.min_domains or 0
            spread_policy_affinity[i, c] = (
                tsc.node_affinity_policy != "Ignore"
            )
            spread_policy_taints[i, c] = tsc.node_taints_policy == "Honor"

    # inter-pod affinity terms (incoming pod's own)
    AT = max((len(p.pod_affinity_required) for p in pending), default=1) or 1
    BT = (
        max((len(p.pod_anti_affinity_required) for p in pending), default=1)
        or 1
    )
    WT = (
        max(
            (
                len(p.pod_affinity_preferred)
                + len(p.pod_anti_affinity_preferred)
                for p in pending
            ),
            default=1,
        )
        or 1
    )
    aff_track = np.zeros((P, AT), I32)
    aff_topo = np.zeros((P, AT), I32)
    aff_self = np.zeros((P, AT), bool)
    aff_mask = np.zeros((P, AT), bool)
    anti_track = np.zeros((P, BT), I32)
    anti_topo = np.zeros((P, BT), I32)
    anti_mask = np.zeros((P, BT), bool)
    waff_track = np.zeros((P, WT), I32)
    waff_topo = np.zeros((P, WT), I32)
    waff_weight = np.zeros((P, WT), I64)
    waff_mask = np.zeros((P, WT), bool)
    # E axis: unique required anti-affinity (selector, key) pairs carried by
    # assigned OR pending pods (symmetry: carriers block matching pods)
    anti_terms: dict = {}  # (sel, key) -> e index

    def anti_term_id(s: int, k: int) -> int:
        if (s, k) not in anti_terms:
            anti_terms[(s, k)] = len(anti_terms)
        return anti_terms[(s, k)]

    pend_carriers: list[list[int]] = []  # per e, pending carrier indices
    for i, pod in enumerate(pending):
        for c, term in enumerate(pod.pod_affinity_required):
            s, k, t = term_ids(pod, term)
            aff_track[i, c] = t
            aff_topo[i, c] = k
            aff_self[i, c] = _sel_matches(
                term.label_selector, _term_scope(pod, term, namespaces), pod
            )
            aff_mask[i, c] = True
        for c, term in enumerate(pod.pod_anti_affinity_required):
            s, k, t = term_ids(pod, term)
            anti_track[i, c] = t
            anti_topo[i, c] = k
            anti_mask[i, c] = True
            e = anti_term_id(s, k)
            while len(pend_carriers) <= e:
                pend_carriers.append([])
            pend_carriers[e].append(i)
        w = 0
        for wt in pod.pod_affinity_preferred:
            s, k, t = term_ids(pod, wt.term)
            waff_track[i, w] = t
            waff_topo[i, w] = k
            waff_weight[i, w] = wt.weight
            waff_mask[i, w] = True
            w += 1
        for wt in pod.pod_anti_affinity_preferred:
            s, k, t = term_ids(pod, wt.term)
            waff_track[i, w] = t
            waff_topo[i, w] = k
            waff_weight[i, w] = -wt.weight
            waff_mask[i, w] = True
            w += 1

    # assigned pods' anti terms join E; remember who carries each term
    assigned_carrier_terms: list[tuple[Pod, int]] = []
    for pod in assigned:
        for term in pod.pod_anti_affinity_required:
            scope = _term_scope(pod, term, namespaces)
            s = sel_id(scope, term.label_selector)
            k = key_id(term.topology_key)
            e = anti_term_id(s, k)
            while len(pend_carriers) <= e:
                pend_carriers.append([])
            assigned_carrier_terms.append((pod, e))

    # --- symmetric score terms (E2 axis) --------------------------------
    sym_terms: dict = {}  # (sel, key, weight, hard) -> e2
    sym_rows: list = []

    def sym_id(sel: int, k: int, weight: int, hard: bool) -> int:
        key = (sel, k, weight, hard)
        if key not in sym_terms:
            sym_terms[key] = len(sym_rows)
            sym_rows.append(key)
        return sym_terms[key]

    def pod_sym_terms(pod: Pod):
        """(e2, count) pairs for one pod's score-symmetric terms."""
        out_counts: dict = {}
        for wt in pod.pod_affinity_preferred:
            s2 = sel_id(_term_scope(pod, wt.term, namespaces),
                        wt.term.label_selector)
            e2 = sym_id(s2, key_id(wt.term.topology_key), wt.weight, False)
            out_counts[e2] = out_counts.get(e2, 0) + 1
        for wt in pod.pod_anti_affinity_preferred:
            s2 = sel_id(_term_scope(pod, wt.term, namespaces),
                        wt.term.label_selector)
            e2 = sym_id(s2, key_id(wt.term.topology_key), -wt.weight, False)
            out_counts[e2] = out_counts.get(e2, 0) + 1
        for term in pod.pod_affinity_required:
            s2 = sel_id(_term_scope(pod, term, namespaces),
                        term.label_selector)
            e2 = sym_id(s2, key_id(term.topology_key), 1, True)
            out_counts[e2] = out_counts.get(e2, 0) + 1
        return out_counts

    assigned_sym: list[tuple[str, int, int]] = []  # (node name, e2, count)
    for pod in assigned:
        terms = pod_sym_terms(pod)
        if terms and pod.node_name is not None:
            assigned_sym.extend(
                (pod.node_name, e2, c) for e2, c in terms.items()
            )
    pending_sym: list[tuple[int, int, int]] = []  # (pod idx, e2, count)
    for i, pod in enumerate(pending):
        for e2, c in pod_sym_terms(pod).items():
            pending_sym.append((i, e2, c))

    S, K = len(sel_objs), max(len(key_names), 1)
    # topology domain codes per key (value interned per key)
    topo_code = np.full((K, N), -1, I32)
    topo_has = np.zeros((K, N), bool)
    domain_values: list[dict] = [dict() for _ in range(K)]
    for k, name in enumerate(key_names):
        for n, node in enumerate(nodes):
            val = node.labels.get(name)
            if val is None:
                continue
            dv = domain_values[k]
            if val not in dv:
                dv[val] = len(dv)
            topo_code[k, n] = dv[val]
            topo_has[k, n] = True
    D = max((len(dv) for dv in domain_values), default=1) or 1
    domain_exists = np.zeros((K, D), bool)
    for k, dv in enumerate(domain_values):
        for code in dv.values():
            domain_exists[k, code] = True

    # --- static spread node-eligibility rows (upstream node-inclusion:
    # per-class all-keys presence, nodeAffinityPolicy, nodeTaintsPolicy).
    # Interned: replicas share rows; the common all-true row is index 0.
    elig_rows: dict = {}
    elig_list: list = []
    spread_elig_idx = np.zeros((P, CT), I32)
    needs_node_counts = False

    def elig_intern(row: np.ndarray) -> int:
        key = row.tobytes()
        if key not in elig_rows:
            elig_rows[key] = len(elig_list)
            elig_list.append(row)
        return elig_rows[key]

    elig_intern(np.ones(N, bool))  # row 0: no exclusions
    any_taints = any(n.taints for n in nodes)
    for i, pod in enumerate(pending):
        if not pod.topology_spread:
            continue
        class_keys = {True: [], False: []}
        for tsc in pod.topology_spread:
            class_keys[tsc.when_unsatisfiable == "DoNotSchedule"].append(
                keys[tsc.topology_key]
            )
        for c, tsc in enumerate(pod.topology_spread):
            row = np.ones(N, bool)
            hard = tsc.when_unsatisfiable == "DoNotSchedule"
            for k in class_keys[hard]:
                row &= topo_has[k]
            if spread_policy_affinity[i, c] and (
                pod.node_selector or pod.node_affinity_required
            ):
                # reuse the interned node-affinity verdict row
                row &= pod_aff_rows[i]
            if spread_policy_taints[i, c] and any_taints:
                # reuse the interned untolerated-taint row
                row &= pod_tol_rows[i]
            spread_elig_idx[i, c] = elig_intern(row)
            k = keys[tsc.topology_key]
            if np.any(~row & (topo_code[k] >= 0)):
                needs_node_counts = True
    spread_elig = np.stack(elig_list)

    TR = max(len(tracks), 1)
    track_sel = np.zeros(TR, I32)
    track_topo = np.zeros(TR, I32)
    for (s, k), t in tracks.items():
        track_sel[t] = s
        track_topo[t] = k

    node_pos = {node.name: n for n, node in enumerate(nodes)}
    track_node_base = np.zeros((TR, N), I64)
    track_base = np.zeros((TR, D), I64)
    for pod in assigned:
        n = node_pos.get(pod.node_name)
        if n is None:
            continue
        for (s, k), t in tracks.items():
            ns, selector = sel_objs[s]
            if _sel_matches(selector, ns, pod):
                track_node_base[t, n] += 1
                code = topo_code[k, n]
                if code >= 0:
                    track_base[t, code] += 1
    pend_match = np.zeros((S, P), bool)
    for i, pod in enumerate(pending):
        for s, (ns, selector) in enumerate(sel_objs):
            pend_match[s, i] = _sel_matches(selector, ns, pod)

    out = dict(
        pend_match=pend_match,
        topo_code=topo_code,
        topo_has=topo_has,
        domain_exists=domain_exists,
        track_sel=track_sel,
        track_topo=track_topo,
        track_node_base=track_node_base if needs_node_counts else None,
        track_base=track_base,
        spread_track=spread_track,
        spread_topo=spread_topo,
        spread_max_skew=spread_max_skew,
        spread_hard=spread_hard,
        spread_self=spread_self,
        spread_mask=spread_mask,
        spread_min_domains=spread_min_domains,
        spread_policy_affinity=spread_policy_affinity,
        spread_policy_taints=spread_policy_taints,
        spread_elig=spread_elig,
        spread_elig_idx=spread_elig_idx,
        spread_needs_node_counts=needs_node_counts,
        aff_track=aff_track,
        aff_topo=aff_topo,
        aff_self=aff_self,
        aff_mask=aff_mask,
        anti_track=anti_track,
        anti_topo=anti_topo,
        anti_mask=anti_mask,
        waff_track=waff_track,
        waff_topo=waff_topo,
        waff_weight=waff_weight,
        waff_mask=waff_mask,
    )

    if anti_terms:
        E = len(anti_terms)
        exist_anti_sel = np.zeros(E, I32)
        exist_anti_topo = np.zeros(E, I32)
        for (s, k), e in anti_terms.items():
            exist_anti_sel[e] = s
            exist_anti_topo[e] = k
        exist_anti_base = np.zeros((E, D), bool)
        for pod, e in assigned_carrier_terms:
            n = node_pos.get(pod.node_name)
            if n is None:
                continue
            code = topo_code[exist_anti_topo[e], n]
            if code >= 0:
                exist_anti_base[e, code] = True
        exist_anti_carrier = np.zeros((E, P), bool)
        for e, carriers in enumerate(pend_carriers):
            for i in carriers:
                exist_anti_carrier[e, i] = True
        exist_anti_match = np.zeros((E, P), bool)
        for e in range(E):
            exist_anti_match[e] = pend_match[exist_anti_sel[e]]
        out.update(
            exist_anti_sel=exist_anti_sel,
            exist_anti_topo=exist_anti_topo,
            exist_anti_base=exist_anti_base,
            exist_anti_carrier=exist_anti_carrier,
            exist_anti_match=exist_anti_match,
        )
    if sym_rows:
        E2 = len(sym_rows)
        sym_sel = np.zeros(E2, I32)
        sym_topo = np.zeros(E2, I32)
        sym_weight = np.zeros(E2, I64)
        sym_hard = np.zeros(E2, bool)
        for e2, (s2, k, w, hard) in enumerate(sym_rows):
            sym_sel[e2], sym_topo[e2] = s2, k
            sym_weight[e2], sym_hard[e2] = w, hard
        sym_base = np.zeros((E2, D), I64)
        for node_name, e2, cnt in assigned_sym:
            n = node_pos.get(node_name)
            if n is None:
                continue
            code = topo_code[sym_topo[e2], n]
            if code >= 0:
                sym_base[e2, code] += cnt
        sym_carrier = np.zeros((E2, P), I64)
        for i, e2, cnt in pending_sym:
            sym_carrier[e2, i] = cnt
        out.update(
            sym_sel=sym_sel,
            sym_topo=sym_topo,
            sym_weight=sym_weight,
            sym_hard=sym_hard,
            sym_base=sym_base,
            sym_carrier=sym_carrier,
        )
    return out


def _sel_matches(selector, ns_scope, pod: Pod) -> bool:
    """Namespace-scoped label-selector match (metav1: a nil selector matches
    nothing; an empty selector matches everything). `ns_scope` is a str or
    a tuple of namespaces (PodAffinityTerm.namespaces)."""
    if isinstance(ns_scope, str):
        ns_scope = (ns_scope,)
    if "*" not in ns_scope and pod.namespace not in ns_scope:
        return False
    if selector is None:
        return False
    return selector.matches(pod.labels)
