"""Mutable host-side cluster store.

The event-driven shell the reference builds out of client-go informers +
plugin-local caches (SURVEY.md §1 dataflow): object upserts/deletes come in,
snapshots go out. Also owns the scheduling-runtime bookkeeping that must not
live on-device: Permit reservations (waiting pods), gang deadlines, backoff and
failure times (/root/reference/pkg/coscheduling/core/core.go:134-192).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

from scheduler_plugins_tpu.api import events as ev
from scheduler_plugins_tpu.api.objects import (
    AppGroup,
    ElasticQuota,
    NetworkTopology,
    Node,
    NodeResourceTopology,
    Pod,
    PodDisruptionBudget,
    PodGroup,
    PodPhase,
    PriorityClass,
    SeccompProfile,
)
from scheduler_plugins_tpu.obs import ledger as podledger
from scheduler_plugins_tpu.state.snapshot import build_snapshot


@dataclass
class Cluster:
    nodes: dict[str, Node] = field(default_factory=dict)
    pods: dict[str, Pod] = field(default_factory=dict)  # keyed by uid
    pod_groups: dict[str, PodGroup] = field(default_factory=dict)  # ns/name
    quotas: dict[str, ElasticQuota] = field(default_factory=dict)  # namespace
    nrts: dict[str, NodeResourceTopology] = field(default_factory=dict)
    app_groups: dict[str, AppGroup] = field(default_factory=dict)
    network_topologies: dict[str, NetworkTopology] = field(default_factory=dict)
    seccomp_profiles: dict[str, SeccompProfile] = field(default_factory=dict)
    priority_classes: dict[str, PriorityClass] = field(default_factory=dict)
    pdbs: dict[str, PodDisruptionBudget] = field(default_factory=dict)
    #: Namespace objects (labels) — PodAffinityTerm.namespaceSelector targets
    namespaces: dict[str, "Namespace"] = field(default_factory=dict)
    node_metrics: Optional[dict] = None
    #: TargetLoadPacking pod CPU-prediction parameters
    #: (multiplier, default-request millis) — installed by the plugin's
    #: configure_cluster from DefaultRequests/DefaultRequestsMultiplier
    #: (apis/config/v1/defaults.go:76-90)
    tlp_prediction: tuple = (1.5, 1000)
    #: optional NRT cache policy (state.nrt_cache); when set, snapshots read
    #: the cache's adjusted zone view instead of the raw NRT objects
    nrt_cache: Optional[object] = None
    #: profile names THIS scheduler owns: only pods whose
    #: spec.schedulerName matches enter the queue (the upstream scheduler
    #: dequeues per-profile; a second-scheduler deployment must never
    #: steal default-scheduler pods). Other pods still count for capacity,
    #: gang membership and NRT foreign-pod tracking.
    scheduler_names: set = field(
        default_factory=lambda: {"tpu-scheduler"}
    )

    # scheduling-runtime bookkeeping (host-only)
    reserved: dict[str, str] = field(default_factory=dict)  # uid -> node
    #: per-POD permit deadlines (the upstream waitingPods timers,
    #: coscheduling.go:227-235): uid -> wall-clock ms at which this waiting
    #: pod's Permit times out; each sibling gets its own timer at ITS
    #: reservation time, and the earliest firing rejects the whole gang
    pod_deadline_ms: dict[str, int] = field(default_factory=dict)
    gang_backoff_until_ms: dict[str, int] = field(default_factory=dict)
    gang_last_failure_ms: dict[str, int] = field(default_factory=dict)
    #: recently-bound pods whose load the metrics provider has not reported
    #: yet (the trimaran PodAssignEventHandler ScheduledPodsCache,
    #: /root/reference/pkg/trimaran/handler.go:47-171): uid -> (bind ms, node)
    recent_bindings: dict[str, tuple[int, str]] = field(default_factory=dict)
    #: uids of LIVE pods carrying spread/affinity specs — the native
    #: snapshot fast path must disengage while any exist, because the
    #: scheduling tables need the assigned pod objects it skips
    _selector_spec_pods: set = field(default_factory=set)
    # EnqueueExtensions bookkeeping (upstream scheduling queue): a monotonic
    # event counter, the last counter value per event kind, and per-pod
    # unschedulable records (event counter at failure, flush deadline).
    #: upstream podMaxInUnschedulablePodsDuration: failed pods re-enter the
    #: batch unconditionally after this long even with no event
    requeue_flush_ms: int = 5 * 60 * 1000
    event_seq: int = field(default=0)
    event_last: dict[str, int] = field(default_factory=dict)
    unschedulable_since: dict[str, tuple[int, int]] = field(
        default_factory=dict
    )
    # requeue backoff (upstream backoffQ: k8s.io/kubernetes
    # pkg/scheduler/internal/queue/scheduling_queue.go
    # calculateBackoffDuration — podInitialBackoffDuration 1s doubling to
    # podMaxBackoffDuration 10s per scheduling attempt): per-pod attempt
    # counts and the wall-clock ms before which `_requeue_eligible` must
    # not re-admit the pod. The jitter multiplier is DETERMINISTIC
    # (blake2b of seed/uid/attempt, in [0.5, 1.0]) so colliding retries
    # spread out while a seeded run replays exactly.
    backoff_initial_ms: int = 1000
    backoff_max_ms: int = 10_000
    backoff_seed: int = 0
    pod_attempts: dict[str, int] = field(default_factory=dict)
    pod_backoff_until_ms: dict[str, int] = field(default_factory=dict)
    #: last failure stamp per pod — one cycle can mark the same pod twice
    #: (bind-loop failure + whole-gang rejection); only the first marks
    #: an ATTEMPT
    _pod_last_failure_ms: dict[str, int] = field(default_factory=dict)
    #: optional `serving.deltas.DeltaSink`: when set (ServeEngine.attach),
    #: the mutators below push typed node-column delta events alongside
    #: their `note_event` calls — the O(changed) feed the resident-state
    #: serving engine ingests instead of re-snapshotting (docs/SERVING.md)
    delta_sink: Optional[object] = None
    #: opt-in O(changed) pending index (`enable_pending_index`, the
    #: pipelined cycle engine's ingest path): uid -> Pod for every
    #: currently-schedulable pod, maintained by the same mutators that
    #: notify the delta sink. None (the default) keeps `pending_pods` as
    #: the exact O(pods) scan the serial engine has always run.
    _pending_idx: Optional[dict] = None
    #: admission serial per uid, reproducing the pods-dict iteration
    #: order the scan yields: assigned at FIRST add (dict updates keep
    #: their position), re-assigned when a removed uid is re-added
    #: (Python dicts move it to the end) — so the indexed queue order is
    #: bit-identical to the scan's, ties and all
    _pod_order: dict = field(default_factory=dict)
    _order_next: int = 0

    def note_event(self, kind: str) -> None:
        """Record a cluster event ("Resource/Action", `api.events`) for
        requeue gating."""
        self.event_seq += 1
        self.event_last[kind] = self.event_seq

    def mark_unschedulable(self, uid: str, now_ms: int) -> None:
        """Park a pod and charge one backoff attempt: duration =
        min(initial * 2^(attempts-1), max) scaled by the deterministic
        jitter in [0.5, 1.0] (upstream calculateBackoffDuration shape —
        see the field comment above for the citation). A successful bind
        or a pod delete clears the attempt count."""
        if self._pod_last_failure_ms.get(uid) != now_ms:
            self._pod_last_failure_ms[uid] = now_ms
            attempts = self.pod_attempts.get(uid, 0) + 1
            self.pod_attempts[uid] = attempts
            base = min(
                self.backoff_initial_ms * (1 << min(attempts - 1, 30)),
                self.backoff_max_ms,
            )
            self.pod_backoff_until_ms[uid] = now_ms + int(
                base * (0.5 + 0.5 * self._backoff_jitter(uid, attempts))
            )
            led = podledger.LEDGER
            if led.enabled:
                # the charged branch only: a same-now re-mark (bind-loop
                # failure + whole-gang rejection in one cycle) is one
                # attempt and one ledger transition
                pod = self.pods.get(uid)
                led.on_unschedulable(
                    uid, attempts,
                    self.pod_backoff_until_ms[uid] - now_ms,
                    bool(pod is not None and pod.pod_group()),
                )
        self.unschedulable_since[uid] = (
            self.event_seq,
            now_ms + self.requeue_flush_ms,
        )

    def _backoff_jitter(self, uid: str, attempt: int) -> float:
        """[0, 1) from blake2b(seed:uid:attempt) — stable across runs
        and processes (Python's hash() is salted; an rng stream would
        depend on failure ORDER, which serve/baseline arms must not)."""
        import hashlib

        h = hashlib.blake2b(
            f"{self.backoff_seed}:{uid}:{attempt}".encode(), digest_size=8
        ).digest()
        return int.from_bytes(h, "big") / 2.0 ** 64

    def _clear_backoff(self, uid: str) -> None:
        self.pod_attempts.pop(uid, None)
        self.pod_backoff_until_ms.pop(uid, None)
        self._pod_last_failure_ms.pop(uid, None)

    # -- native mirror ----------------------------------------------------
    def attach_native_store(self):
        """Mirror the hot node columns into the C++ columnar store
        (bridge/snapshot_store.cc) so snapshots read them via memcpy exports
        instead of an O(assigned pods) Python accumulate per cycle (the
        informer-cache -> NodeInfo lowering the reference keeps in Go).
        Replays current state; subsequent upserts/binds/deletes maintain it
        incrementally. The fast path engages only when the snapshot's
        resource axis is exactly the canonical four (the store layout,
        CLAUDE.md invariant) and no side-table subsystems need the assigned
        pod objects."""
        from scheduler_plugins_tpu.bridge import NativeStore
        from scheduler_plugins_tpu.api.resources import CANONICAL

        self._native = NativeStore(len(CANONICAL))
        self._native_node_ids: dict[str, int] = {}
        self._native_pod_ids: dict[str, int] = {}
        #: monotonic — deletions must never free an id for reuse, or a new
        #: pod would silently replace a live one's store contribution
        self._native_next_pod_id = 0
        #: object keys carrying extended resources the 4-slot store cannot
        #: represent; the fast path disengages while any are LIVE (deleting
        #: the object re-enables it)
        self._native_incompat: set[str] = set()
        self._native_replaying = True
        try:
            for node in self.nodes.values():
                self._native_upsert_node(node)
            for pod in self.pods.values():
                self._native_upsert_pod(pod)  # re-binds live reservations
        finally:
            self._native_replaying = False
        return self._native

    @property
    def native(self):
        return getattr(self, "_native", None)

    def _canon_vec(self, key, *quantity_maps):
        from scheduler_plugins_tpu.api.resources import CANONICAL

        import numpy as np

        vecs = []
        incompat = False
        for quantities in quantity_maps:
            vec = np.zeros(len(CANONICAL), np.int64)
            for r, v in quantities.items():
                try:
                    vec[CANONICAL.index(r)] = v
                except ValueError:
                    # extended resource: the 4-slot store can't carry it
                    incompat = True
            vecs.append(vec)
        if incompat:
            self._native_incompat.add(key)
        else:
            self._native_incompat.discard(key)
        return vecs

    def _native_upsert_node(self, node: Node):
        is_new = node.name not in self._native_node_ids
        if is_new:
            self._native_node_ids[node.name] = len(self._native_node_ids)
        alloc, cap = self._canon_vec(
            f"node/{node.name}", node.allocatable, node.capacity
        )
        self._native.upsert_node(self._native_node_ids[node.name], alloc, cap)
        if not is_new or getattr(self, "_native_replaying", False):
            # known node (routine status update), or the attach replay will
            # upsert every pod afterwards anyway: nothing to re-link
            return
        # pods mirrored before their node arrived (cross-watch event
        # ordering) were stored unbound: re-upsert them now
        for pod in self.pods.values():
            if pod.node_name == node.name:
                self._native_upsert_pod(pod)
        for uid, rnode in self.reserved.items():
            if rnode == node.name and uid in self._native_pod_ids:
                self._native.bind(
                    self._native_pod_ids[uid],
                    self._native_node_ids[node.name],
                )

    def _native_upsert_pod(self, pod: Pod):
        if pod.uid not in self._native_pod_ids:
            # ids are never reused: a delete+re-add is a new incarnation
            self._native_pod_ids[pod.uid] = self._native_next_pod_id
            self._native_next_pod_id += 1
        req, lim = self._canon_vec(
            f"pod/{pod.uid}", pod.effective_request(), pod.effective_limits()
        )
        self._native.upsert_pod(
            self._native_pod_ids[pod.uid],
            req,
            limits=lim,
            priority=pod.priority,
            creation_ms=pod.creation_ms,
            node_id=self._native_node_ids.get(pod.node_name, -1),
            terminating=pod.terminating,
        )
        # a re-upsert of a permit-reserved pod must not drop its hold
        rnode = self.reserved.get(pod.uid)
        if rnode is not None and rnode in self._native_node_ids:
            self._native.bind(
                self._native_pod_ids[pod.uid], self._native_node_ids[rnode]
            )

    def _native_rebuild(self):
        """Node deletion invalidates store row order: replay from scratch
        (rare control-plane event; everything else is incremental)."""
        self._native.close()
        self.attach_native_store()

    # -- upserts ---------------------------------------------------------
    def add_node(self, node: Node):
        self.note_event(
            ev.NODE_UPDATE if node.name in self.nodes else ev.NODE_ADD
        )
        self.nodes[node.name] = node
        if self.native is not None:
            self._native_upsert_node(node)
        if self.delta_sink is not None:
            self.delta_sink.node_upsert(node)

    def remove_node(self, name: str):
        if self.nodes.pop(name, None) is not None:
            self.note_event(ev.NODE_DELETE)
            if self.delta_sink is not None:
                self.delta_sink.node_delete(name)
        if self.native is not None:
            self._native_rebuild()

    @staticmethod
    def _has_selector_specs(pod: Pod) -> bool:
        return bool(
            pod.topology_spread
            or pod.pod_affinity_required
            or pod.pod_anti_affinity_required
            or pod.pod_affinity_preferred
            or pod.pod_anti_affinity_preferred
        )

    def _held_node(self, pod: Optional[Pod]) -> Optional[str]:
        """The node whose usage columns `pod` currently contributes to:
        its binding, else its permit reservation (reserved pods hold
        capacity exactly like bound ones in the snapshot's assigned
        view). None for plain pending pods."""
        if pod is None:
            return None
        return pod.node_name or self.reserved.get(pod.uid)

    def _gang_gated_key(self, pod: Optional[Pod]) -> Optional[str]:
        """The gang this pod counts into as an UNBOUND, scheduling-gated
        member (the `gated_pods()` contribution to the snapshot's gang
        gated/total counters), or None — the serving engine's resident
        gang side table tracks transitions of this predicate
        (serving.deltas.GANG_GATED)."""
        if pod is None or pod.node_name is not None:
            return None
        if not pod.scheduling_gated or pod.terminating:
            return None
        name = pod.pod_group()
        if not name:
            return None
        return f"{pod.namespace}/{name}"

    def add_pod(self, pod: Pod):
        old = self.pods.get(pod.uid)
        self.note_event(ev.POD_UPDATE if old is not None else ev.POD_ADD)
        if old is None and pod.node_name is None:
            led = podledger.LEDGER
            if led.enabled:
                led.on_first_seen(pod)
        if self.delta_sink is not None:
            # an upsert swaps the pod's assigned contribution wholesale
            # (requests may have changed; a stale echo may drop the node)
            old_hold = self._held_node(old)
            if old_hold is not None:
                self.delta_sink.pod_unassigned(old, old_hold)
            # gated-gang-membership transition, captured at event time
            # (the upsert replaces the object wholesale)
            old_gated = self._gang_gated_key(old)
            new_gated = self._gang_gated_key(pod)
            if old_gated != new_gated:
                if old_gated is not None:
                    self.delta_sink.gang_gated(old_gated, -1)
                if new_gated is not None:
                    self.delta_sink.gang_gated(new_gated, +1)
        self.pods[pod.uid] = pod
        if self.delta_sink is not None:
            new_hold = self._held_node(pod)
            if new_hold is not None:
                self.delta_sink.pod_assigned(pod, new_hold)
            self.delta_sink.note_nomination(pod)
        if self._has_selector_specs(pod):
            # spread/affinity tables need ASSIGNED pod objects at snapshot
            # build, which the native fast path skips (pod specs are
            # immutable, so count on add/remove)
            self._selector_spec_pods.add(pod.uid)
        if self.nrt_cache is not None and hasattr(self.nrt_cache, "track_pod"):
            # foreign-pod detection (cache/foreign_pods.go:42-99)
            self.nrt_cache.track_pod(pod)
        if self.native is not None:
            self._native_upsert_pod(pod)
        self._index_add_pod(pod, was_present=old is not None)

    def remove_pod(self, uid: str):
        self.release_reservation(uid)  # notifies the NRT cache too
        self._selector_spec_pods.discard(uid)
        self.unschedulable_since.pop(uid, None)
        self._clear_backoff(uid)
        pod = self.pods.pop(uid, None)
        # after the pop: release_reservation may have re-indexed the
        # still-present pod above; a removed uid must leave both tables
        # (a later re-add lands at the end, like the pods dict)
        self._index_drop_pod(uid, forget_order=True)
        if pod is not None:
            self.note_event(ev.POD_DELETE)
            if pod.node_name is None:
                led = podledger.LEDGER
                if led.enabled:
                    led.on_delete(uid)
            if self.delta_sink is not None:
                if pod.node_name is not None:
                    # bound pod's usage leaves with it (a reserved pod's
                    # hold was already released above)
                    self.delta_sink.pod_unassigned(pod, pod.node_name)
                gated = self._gang_gated_key(pod)
                if gated is not None:
                    self.delta_sink.gang_gated(gated, -1)
                self.delta_sink.forget_nomination(uid)
        if (
            pod is not None
            and pod.node_name is not None
            and self.nrt_cache is not None
        ):
            # a bound pod's assumed deduction must not outlive the pod
            self.nrt_cache.unreserve(pod.node_name, pod)
        if pod is not None and self.native is not None:
            pod_id = self._native_pod_ids.pop(uid, None)
            if pod_id is not None:
                self._native.delete_pod(pod_id)
            self._native_incompat.discard(f"pod/{uid}")

    def mark_terminating(self, uid: str, now_ms: int):
        """DELETE issued (preemption victim): flips the terminating flag in
        both the object model and the native mirror."""
        pod = self.pods.get(uid)
        if pod is None:
            return
        was_terminating = pod.terminating
        # gated-gang contribution captured BEFORE the in-place flip (a
        # terminating gated member leaves `gated_pods()`)
        gated = (
            self._gang_gated_key(pod)
            if self.delta_sink is not None and not was_terminating else None
        )
        pod.deletion_ms = now_ms
        if not was_terminating:
            led = podledger.LEDGER
            if led.enabled:
                led.on_terminating(uid)
        if gated is not None:
            self.delta_sink.gang_gated(gated, -1)
        self._index_drop_pod(uid)
        self.note_event(ev.POD_UPDATE)
        if self.native is not None:
            self._native_upsert_pod(pod)
        if self.delta_sink is not None and not was_terminating:
            # the held-capacity node, binding OR reservation: a reserved
            # victim's terminating flag counts at its reserved node in the
            # snapshot's assigned view, and the eventual release subtracts
            # the event-time flag — skipping the +1 here would leave the
            # resident terminating column permanently negative
            held = self._held_node(pod)
            if held is not None:
                self.delta_sink.pod_terminating(pod, held)

    def add_pod_group(self, pg: PodGroup):
        self.note_event(
            ev.POD_GROUP_UPDATE if pg.full_name in self.pod_groups
            else ev.POD_GROUP_ADD
        )
        self.pod_groups[pg.full_name] = pg

    def add_quota(self, eq: ElasticQuota):
        self.note_event(
            ev.ELASTIC_QUOTA_UPDATE if eq.namespace in self.quotas
            else ev.ELASTIC_QUOTA_ADD
        )
        self.quotas[eq.namespace] = eq

    def add_nrt(self, nrt: NodeResourceTopology):
        self.note_event(
            ev.NRT_UPDATE if nrt.node_name in self.nrts
            else ev.NRT_ADD
        )
        self.nrts[nrt.node_name] = nrt
        if self.nrt_cache is not None:
            self.nrt_cache.update_nrt(nrt)

    def remove_nrt(self, node_name: str):
        """NRT CR deleted: evict from the cache tier too, or the snapshot
        keeps building NUMA tables from the stale copy forever."""
        if node_name in self.nrts:
            self.note_event(ev.NRT_DELETE)
        self.nrts.pop(node_name, None)
        if self.nrt_cache is not None:
            self.nrt_cache.delete_nrt(node_name)

    def add_app_group(self, ag: AppGroup):
        self.note_event(
            ev.APP_GROUP_UPDATE
            if f"{ag.namespace}/{ag.name}" in self.app_groups
            else ev.APP_GROUP_ADD
        )
        self.app_groups[f"{ag.namespace}/{ag.name}"] = ag

    def add_network_topology(self, nt: NetworkTopology):
        self.note_event(
            ev.NETWORK_TOPOLOGY_UPDATE
            if f"{nt.namespace}/{nt.name}" in self.network_topologies
            else ev.NETWORK_TOPOLOGY_ADD
        )
        self.network_topologies[f"{nt.namespace}/{nt.name}"] = nt

    def add_seccomp_profile(self, sp: SeccompProfile):
        self.note_event(
            ev.SECCOMP_PROFILE_UPDATE
            if sp.full_name in self.seccomp_profiles
            else ev.SECCOMP_PROFILE_ADD
        )
        self.seccomp_profiles[sp.full_name] = sp

    def add_priority_class(self, pc: PriorityClass):
        self.note_event(
            ev.PRIORITY_CLASS_UPDATE if pc.name in self.priority_classes
            else ev.PRIORITY_CLASS_ADD
        )
        self.priority_classes[pc.name] = pc

    def add_namespace(self, ns):
        self.note_event(
            ev.NAMESPACE_UPDATE if ns.name in self.namespaces
            else ev.NAMESPACE_ADD
        )
        self.namespaces[ns.name] = ns

    def add_pdb(self, pdb: PodDisruptionBudget):
        self.note_event(
            ev.PDB_UPDATE
            if f"{pdb.namespace}/{pdb.name}" in self.pdbs
            else ev.PDB_ADD
        )
        self.pdbs[f"{pdb.namespace}/{pdb.name}"] = pdb

    # -- derived ---------------------------------------------------------
    def pod_group_of(self, pod: Pod) -> Optional[PodGroup]:
        name = pod.pod_group()
        if not name:
            return None
        return self.pod_groups.get(f"{pod.namespace}/{name}")

    def gang_sort_time(self, pg: PodGroup) -> int:
        """Queue-sort timestamp for a gang: last schedule-failure time when
        set (defeats head-of-line blocking, core.go:365-384), else creation."""
        return self.gang_last_failure_ms.get(pg.full_name, pg.creation_ms)

    def gang_members(self, pg: PodGroup) -> list[Pod]:
        return [
            p
            for p in self.pods.values()
            if p.namespace == pg.namespace
            and p.pod_group() == pg.name
        ]

    def _pending_eligible(self, pod: Pod) -> bool:
        """THE schedulable-queue predicate — one copy shared by the scan
        and the maintained index, so the two views cannot drift."""
        return (
            pod.node_name is None
            and pod.uid not in self.reserved
            and pod.phase == PodPhase.PENDING
            and not pod.terminating
            and not pod.scheduling_gated
            and pod.scheduler_name in self.scheduler_names
        )

    def enable_pending_index(self) -> None:
        """Switch `pending_pods` from the O(pods) scan to a maintained
        O(changed) index (the pipelined engine's ingest path,
        docs/SCALING.md). Call AFTER `scheduler_names` and the initial
        population are configured; mutators keep it exact from here on.
        Code that flips a pod's eligibility IN PLACE (outside the store
        mutators — the same blind spot the delta sink has) must call
        `reindex_pod`."""
        self._pod_order = {uid: i for i, uid in enumerate(self.pods)}
        self._order_next = len(self._pod_order)
        self._pending_idx = {
            p.uid: p for p in self.pods.values() if self._pending_eligible(p)
        }

    def disable_pending_index(self) -> None:
        self._pending_idx = None
        self._pod_order = {}
        self._order_next = 0

    def reindex_pod(self, uid: str) -> None:
        """Re-evaluate one pod's pending-index membership after an
        in-place eligibility flip (phase / scheduling gate)."""
        pod = self.pods.get(uid)
        if pod is not None and pod.node_name is None:
            led = podledger.LEDGER
            if led.enabled:
                led.on_gate_flip(uid, bool(pod.scheduling_gated))
        if self._pending_idx is None:
            return
        if pod is not None and self._pending_eligible(pod):
            self._pending_idx[uid] = pod
        else:
            self._pending_idx.pop(uid, None)

    def _index_add_pod(self, pod: Pod, was_present: bool) -> None:
        if self._pending_idx is None:
            return
        if not was_present or pod.uid not in self._pod_order:
            # first sighting (or re-add after a remove): dicts append
            self._pod_order[pod.uid] = self._order_next
            self._order_next += 1
        if self._pending_eligible(pod):
            self._pending_idx[pod.uid] = pod
        else:
            self._pending_idx.pop(pod.uid, None)

    def _index_drop_pod(self, uid: str, forget_order: bool = False) -> None:
        if self._pending_idx is None:
            return
        self._pending_idx.pop(uid, None)
        if forget_order:
            self._pod_order.pop(uid, None)

    def pending_pods(self) -> list[Pod]:
        """Schedulable queue: gated pods stay out (upstream keeps them off
        activeQ entirely — they are neither attempted nor reported failed),
        and only pods addressed to one of `scheduler_names` enter (the
        upstream per-profile dequeue). With the opt-in index enabled the
        list is assembled O(pending log pending) in the identical order
        (admission serials mirror the dict iteration the scan performs)."""
        if self._pending_idx is not None:
            order = self._pod_order
            return sorted(
                self._pending_idx.values(), key=lambda p: order[p.uid]
            )
        return [
            p
            for p in self.pods.values()
            if self._pending_eligible(p)
        ]

    def admission_serial(self, uid: str) -> int:
        """The pod's position in admission order — the reproducible
        partition key of the K-lane engine's "hash" mode
        (`parallel.lanes.lane_key`). With the pending index enabled this
        is the maintained `_pod_order` serial (survives removes of other
        pods); without it, the dict-iteration position (the same order
        the index would have assigned). -1 for an unknown uid."""
        if self._pod_order:
            return self._pod_order.get(uid, -1)
        for i, known in enumerate(self.pods):
            if known == uid:
                return i
        return -1

    def gated_pods(self) -> list[Pod]:
        return [
            p
            for p in self.pods.values()
            if p.node_name is None and p.scheduling_gated and not p.terminating
        ]

    # -- binding / reservations -----------------------------------------
    def bind(self, uid: str, node_name: str, now_ms: int = 0):
        held = self.reserved.pop(uid, None)
        self.pod_deadline_ms.pop(uid, None)
        self.unschedulable_since.pop(uid, None)
        self._clear_backoff(uid)
        self.note_event(ev.POD_UPDATE)  # assigned: spec.nodeName set
        if self.delta_sink is not None:
            if held != node_name:
                # a reservation-to-bind on the SAME node is already
                # counted; anything else transfers the contribution
                if held is not None:
                    self.delta_sink.pod_unassigned(self.pods[uid], held)
                self.delta_sink.pod_assigned(self.pods[uid], node_name)
            # a (defensively possible) gated pod leaves `gated_pods()`
            # the moment nodeName lands — its gang gated count drops
            gated = self._gang_gated_key(self.pods[uid])
            if gated is not None:
                self.delta_sink.gang_gated(gated, -1)
            # bound pods never count toward the nominated column
            self.delta_sink.forget_nomination(uid)
        self.pods[uid].node_name = node_name
        self._index_drop_pod(uid)
        led = podledger.LEDGER
        if led.enabled:
            led.on_bind(uid, node_name)
        self.recent_bindings[uid] = (now_ms, node_name)
        if self.nrt_cache is not None:
            # Reserve -> bind -> PostBind lifecycle for the NRT cache
            self.nrt_cache.reserve(node_name, self.pods[uid])
            self.nrt_cache.post_bind(node_name, self.pods[uid])
        if self.native is not None:
            # no-op if the reservation already bound it to this node
            self._native.bind(
                self._native_pod_ids[uid], self._native_node_ids[node_name]
            )

    def reserve(self, uid: str, node_name: str):
        """Permit said Wait: hold the placement without binding."""
        self.reserved[uid] = node_name
        self._index_drop_pod(uid)
        led = podledger.LEDGER
        if led.enabled:
            led.on_reserve(uid, node_name)
        if self.delta_sink is not None:
            # a reservation holds capacity exactly like a binding
            self.delta_sink.pod_assigned(self.pods[uid], node_name)
        if self.nrt_cache is not None:
            self.nrt_cache.reserve(node_name, self.pods[uid])
        if self.native is not None:
            # a reservation holds capacity exactly like a binding
            self._native.bind(
                self._native_pod_ids[uid], self._native_node_ids[node_name]
            )

    def release_reservation(self, uid: str):
        self.pod_deadline_ms.pop(uid, None)
        node = self.reserved.pop(uid, None)
        if node is not None and self.delta_sink is not None:
            self.delta_sink.pod_unassigned(self.pods[uid], node)
        if node is not None and self.nrt_cache is not None:
            self.nrt_cache.unreserve(node, self.pods[uid])
        if node is not None and self.native is not None:
            # re-upsert as unbound (removes the hold's contribution)
            self._native_upsert_pod(self.pods[uid])
        if node is not None:
            self.reindex_pod(uid)

    def gang_reservations(self, pg: PodGroup) -> list[str]:
        return [
            uid
            for uid, _ in self.reserved.items()
            if (p := self.pods.get(uid)) is not None
            and p.namespace == pg.namespace
            and p.pod_group() == pg.name
        ]

    #: metrics-agent reporting interval: recently-bound pods within this
    #: window are presumed unreported and their predicted CPU is added
    #: (handler.go comment; BASELINE.md metrics freshness envelope)
    METRICS_REPORT_INTERVAL_MS = 60_000
    #: ScheduledPodsCache GC horizon (handler.go: 5 minutes)
    BINDING_CACHE_GC_MS = 300_000

    def _metrics_with_missing(self, now_ms: int):
        """Augment node metrics with the missing-utilization compensation
        (targetloadpacking.go:148-168): predicted CPU of pods bound within
        the metrics reporting interval, per node."""
        # GC the binding cache regardless of metrics config, or it grows
        # unboundedly on clusters without trimaran metrics
        for uid, (ts, _) in list(self.recent_bindings.items()):
            if now_ms - ts > self.BINDING_CACHE_GC_MS:
                del self.recent_bindings[uid]
        if self.node_metrics is None:
            return None
        missing: dict[str, int] = {}
        for uid, (ts, node) in self.recent_bindings.items():
            pod = self.pods.get(uid)
            if pod is None or now_ms - ts >= self.METRICS_REPORT_INTERVAL_MS:
                continue
            missing[node] = missing.get(node, 0) + pod.tlp_predicted_cpu_millis(
                *self.tlp_prediction
            )
        if not missing:
            return self.node_metrics
        merged = {name: dict(m) for name, m in self.node_metrics.items()}
        for node, millis in missing.items():
            merged.setdefault(node, {})["missing_cpu_millis"] = (
                merged.get(node, {}).get("missing_cpu_millis", 0) + millis
            )
        return merged

    # -- snapshot --------------------------------------------------------
    def _assigned_pods(self, exclude=frozenset()):
        """Bound pods plus reserved (permit-waiting) pods materialized with
        their held node — THE definition of 'assigned' for snapshot
        lowering and the preemption dry-run's hypothetical rebuild (one
        source so the two views cannot desynchronize)."""
        import copy

        assigned = [
            p for p in self.pods.values()
            if p.node_name is not None and p.uid not in exclude
        ]
        for uid, node in self.reserved.items():
            pod = self.pods.get(uid)
            if pod is not None and pod.node_name is None and uid not in exclude:
                held = copy.copy(pod)
                held.node_name = node
                assigned.append(held)
        return assigned

    def post_eviction_tables(self, snap, meta, exclude_uids):
        """Pod-derived side tables with `exclude_uids` treated as already
        evicted: the preemption dry run's post-eviction filter view
        (capacity_scheduling.go SelectVictimsOnNode removes victims from
        the NodeInfo before RunFilterPluginsWithNominatedPods). Rebuilds
        the scheduling track bases (affinity/anti-affinity/spread existing-
        pod counts) and decrements the network placed-workload counts; the
        NRT cache view is deliberately NOT touched — upstream's
        TopologyMatch filter reads its own overreserve cache, which victim
        removal does not update either. Returns a snapshot sharing every
        other table with `snap`."""
        import jax
        import jax.numpy as jnp
        import numpy as np

        from scheduler_plugins_tpu.state import scheduling as _sched

        excl = set(exclude_uids)
        new_sched = snap.scheduling
        if snap.scheduling is not None:
            nodes = [self.nodes[n] for n in meta.node_names if n in self.nodes]
            pending = [
                self.pods[uid] for uid in meta.pod_names if uid in self.pods
            ]
            assigned = self._assigned_pods(exclude=excl)
            new_sched = _sched.build_scheduling(
                nodes, pending, snap.num_nodes, snap.num_pods,
                assigned=assigned, namespaces=list(self.namespaces.values()),
            )
            if new_sched is not None:
                new_sched = jax.tree.map(jnp.asarray, new_sched)
        new_network = snap.network
        if snap.network is not None and getattr(meta, "workloads", None):
            placed = np.asarray(snap.network.placed_node).copy()
            node_pos = {name: i for i, name in enumerate(meta.node_names)}
            wl_pos = {name: i for i, name in enumerate(meta.workloads)}
            for uid in excl:
                pod = self.pods.get(uid)
                if pod is None or pod.node_name not in node_pos:
                    continue
                sel = pod.workload_selector()
                wc = wl_pos.get(f"{pod.namespace}/{sel}") if sel else None
                if wc is not None:
                    ni = node_pos[pod.node_name]
                    placed[wc, ni] = max(placed[wc, ni] - 1, 0)
            new_network = snap.network.replace(
                placed_node=jnp.asarray(placed)
            )
        return snap.replace(scheduling=new_sched, network=new_network)

    def snapshot(self, pending: list[Pod], now_ms: int = 0, **kwargs):
        """Lower current state for the solver. Reserved (permit-waiting) pods
        count as assigned to their reserved node — they hold capacity and
        quorum exactly like the reference's waiting pods in assignedPodsByPG."""
        # native fast path: node usage columns come from the C++ store,
        # which accounts every bound AND reserved pod incrementally — the
        # O(assigned) Python accumulate is skipped. Assigned pod objects are
        # still needed whenever a side-table subsystem reads them.
        native_exports = None
        if (
            self.native is not None
            and not self._native_incompat
            and not self.pod_groups
            and not self.quotas
            and not self.app_groups
            and not self.seccomp_profiles
            and not self._selector_spec_pods
        ):
            exports = self._native.export_nodes()
            if len(exports["ids"]) == len(self.nodes) and all(
                self._native_node_ids.get(n) == i
                for i, n in enumerate(self.nodes)
            ):
                native_exports = exports
        if native_exports is not None:
            assigned = []
        else:
            assigned = self._assigned_pods()
        backed_off = [
            name
            for name, until in self.gang_backoff_until_ms.items()
            if until > now_ms
        ]
        metrics = self._metrics_with_missing(now_ms)
        nrt_list = list(self.nrts.values())
        stale_nodes: list[str] = []
        if self.nrt_cache is not None:
            nrt_list, stale = self.nrt_cache.view()
            stale_nodes = list(stale)
        return build_snapshot(
            list(self.nodes.values()),
            pending,
            assigned_pods=assigned,
            pod_groups=list(self.pod_groups.values()),
            quotas=list(self.quotas.values()),
            nrts=nrt_list,
            stale_nrt_nodes=stale_nodes,
            app_groups=list(self.app_groups.values()),
            node_metrics=metrics,
            backed_off_gangs=backed_off,
            extra_pods=self.gated_pods(),
            seccomp_profiles=list(self.seccomp_profiles.values()),
            native_nodes=native_exports,
            tlp_prediction=self.tlp_prediction,
            sysched_default_profile=getattr(
                self, "sysched_default_profile", None
            ),
            namespaces=list(self.namespaces.values()),
            **kwargs,
        )
