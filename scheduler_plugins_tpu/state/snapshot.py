"""Dense-tensor cluster snapshot.

The reference walks an object graph per pod x per node (NodeInfo lists,
informer caches). Here the whole scheduling problem is lowered once per cycle
into a pytree of dense int64/float64 arrays with static (bucketed) shapes:

- nodes:   (N, R) allocatable / requested / non-zero-requested, region/zone
           codes, per-node pod-state counters.
- pods:    (P, R) effective requests for the *pending batch*, priority, QoS,
           namespace / gang / app-group codes, queue-sort keys.
- gangs:   (G,) PodGroup min-member / membership counts, (G, R) MinResources.
- quota:   (Q, R) ElasticQuota min/max/used indexed by namespace code.
- metrics: (N,) load-watcher utilisation mu/sigma percentages.
- numa:    (N, Z, R) per-zone availability + topology-manager config codes.

Name<->code mappings and resource-axis metadata live in `SnapshotMeta`, which
is host-only and deliberately NOT part of the pytree, so jit sees only arrays
(changing names never retriggers compilation; changing bucket sizes does).

Quantities are int64 in reference units (SURVEY.md §7) — bit-identical
placement needs integer compares, e.g.
/root/reference/pkg/capacityscheduling/elasticquota.go:189-221.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional, Sequence

import numpy as np
from flax import struct

from scheduler_plugins_tpu.api.objects import (
    AppGroup,
    ElasticQuota,
    Node,
    NodeResourceTopology,
    Pod,
    PodGroup,
)
from scheduler_plugins_tpu.api.resources import (
    CANONICAL,
    CPU,
    DEFAULT_MEMORY_REQUEST,
    DEFAULT_MILLI_CPU_REQUEST,
    MEMORY,
    PODS,
    ResourceIndex,
)
from scheduler_plugins_tpu.state import scheduling as _sched
from scheduler_plugins_tpu.utils.intmath import bucket_size

I64 = np.int64
I32 = np.int32
F64 = np.float64

#: Static snapshot tensors that have a LIVE SolverState carry counterpart
#: (keyed by pytree path relative to the snapshot root -> carry field name,
#: `framework.plugin.SolverState`). The CLAUDE.md invariant — in-cycle
#: mutations flow through carries, never through re-reads of the static
#: snapshot — is machine-checked on the COMPILED programs by
#: `tools/jaxpr_audit.py` (rule JA001): a traced solve whose outputs depend
#: on one of these tensors while the carry counterpart is dead in the jaxpr
#: has bypassed the carry. The scheduling-table counterparts live in
#: `state.scheduling.TRACK_CARRY_COUNTERPARTS`.
CARRY_COUNTERPARTS = {
    ".nodes.requested": "free",
    ".quota.used": "eq_used",
    ".gangs.assigned": "gang_scheduled",
    ".network.placed_node": "net_placed",
    ".numa.available": "numa_avail",
    # the gang phase's resident rank assignment (gangs.topology
    # RankGangState.prev_assigned -> the SolverState.rank_nodes carry):
    # the rank-gang solve must thread in-cycle placements through the
    # carry, never re-read the static resident tensor
    ".ranks.prev_assigned": "rank_nodes",
}


@struct.dataclass
class NodeState:
    alloc: np.ndarray  # (N, R) int64 allocatable
    capacity: np.ndarray  # (N, R) int64 node capacity (TLP/Peaks read this)
    requested: np.ndarray  # (N, R) int64 sum of assigned pods' requests
    nonzero_requested: np.ndarray  # (N, R) int64 with upstream non-zero defaults
    #: (N, R) sum of assigned pods' effective limits clamped to >= requests
    #: per pod (trimaran SetMaxLimits, resourcestats.go:225-231)
    limits: np.ndarray
    mask: np.ndarray  # (N,) bool — real, schedulable node
    region: np.ndarray  # (N,) int32 region code (-1 unset)
    zone: np.ndarray  # (N,) int32 zone code (-1 unset)
    pod_count: np.ndarray  # (N,) int32 assigned pods
    terminating: np.ndarray  # (N,) int32 terminating pods (PodState score)
    nominated: np.ndarray  # (N,) int32 nominated pods (PodState score)


@struct.dataclass
class PodState:
    req: np.ndarray  # (P, R) int64 effective request (pods slot = 0)
    limits: np.ndarray  # (P, R) int64 trimaran effective limits (unclamped)
    #: (P,) TargetLoadPacking per-pod CPU prediction with default args
    predicted_cpu_millis: np.ndarray
    #: (P, C, R) raw per-container requests, init containers first — the NUMA
    #: container-scope Filter/Score iterate containers individually
    #: (filter.go:39-78, score.go:152-165)
    container_req: np.ndarray
    container_is_init: np.ndarray  # (P, C) bool
    container_mask: np.ndarray  # (P, C) bool
    priority: np.ndarray  # (P,) int64
    ns: np.ndarray  # (P,) int32 namespace code
    gang: np.ndarray  # (P,) int32 gang code (-1 = not in a PodGroup)
    qos: np.ndarray  # (P,) int32 QOSClass
    mask: np.ndarray  # (P,) bool
    creation_ms: np.ndarray  # (P,) int64 queue-sort timestamp
    gated: np.ndarray  # (P,) bool SchedulingGated


@struct.dataclass
class GangState:
    """PodGroup bookkeeping (/root/reference/pkg/coscheduling/core/core.go)."""

    min_member: np.ndarray  # (G,) int32
    total_members: np.ndarray  # (G,) int32 siblings known cluster-wide
    assigned: np.ndarray  # (G,) int32 already bound/running members
    gated: np.ndarray  # (G,) int32 SchedulingGated siblings
    min_resources: np.ndarray  # (G, R) int64 whole-gang demand
    has_min_resources: np.ndarray  # (G,) bool
    creation_ms: np.ndarray  # (G,) int64 (failure-time override applied)
    backed_off: np.ndarray  # (G,) bool recently rejected
    #: (G, R) extra whole-cluster capacity visible to this gang's
    #: CheckClusterResource because its own assigned pods are added back
    #: (core.go:433-467 getNodeResource removes the gang's pods)
    cluster_slack: np.ndarray  # (G, R) int64
    mask: np.ndarray  # (G,) bool


@struct.dataclass
class QuotaState:
    """ElasticQuota arrays indexed by namespace code
    (/root/reference/pkg/capacityscheduling/elasticquota.go:34-87)."""

    min: np.ndarray  # (Q, R) int64
    max: np.ndarray  # (Q, R) int64
    used: np.ndarray  # (Q, R) int64
    has_quota: np.ndarray  # (Q,) bool namespace has an EQ
    #: nominated-pod tables (capacity_scheduling.go:226-263). M nominees:
    #: their requests, per-(nominee, pending-pod) contribution masks for the
    #: own-Max ("in EQ": same namespace, priority >= pod) and aggregate-Min
    #: checks, and each nominee's index in the pending batch (-1 if outside)
    #: so in-scan placements drop them from the aggregates (upstream removes
    #: a pod from the nominated set the moment it is assumed).
    nom_req: np.ndarray  # (M, R) int64
    nom_in_eq_mask: np.ndarray  # (M, P) bool
    nom_total_mask: np.ndarray  # (M, P) bool
    nom_batch_idx: np.ndarray  # (M,) int32


@struct.dataclass
class MetricsState:
    """Load-watcher node metrics in percent of capacity
    (/root/reference/pkg/trimaran/collector.go, resourcestats.go:33-107)."""

    cpu_avg: np.ndarray  # (N,) float64 %
    #: (N,) the CPU value TargetLoadPacking reads — its selection loop lets a
    #: later Latest override Average (targetloadpacking.go:130-139); defaults
    #: to cpu_avg
    cpu_tlp: np.ndarray
    #: (N,) the CPU value Peaks reads — the FIRST Average-or-Latest sample in
    #: report order (peaks.go:118-131); defaults to cpu_tlp/cpu_avg
    cpu_peaks: np.ndarray
    cpu_std: np.ndarray  # (N,) float64 %
    mem_avg: np.ndarray  # (N,) float64 %
    mem_std: np.ndarray  # (N,) float64 %
    cpu_valid: np.ndarray  # (N,) bool
    #: (N,) whether an Average/Latest CPU metric was actually seen — TLP
    #: requires one (targetloadpacking.go:130-146) and must not score a
    #: std-only node from a defaulted 0/avg value
    cpu_tlp_valid: np.ndarray
    mem_valid: np.ndarray  # (N,) bool
    #: predicted-but-unreported CPU millis per node (ScheduledPodsCache
    #: compensation, /root/reference/pkg/trimaran/handler.go:47-171)
    missing_cpu_millis: np.ndarray  # (N,) int64


@struct.dataclass
class NumaState:
    """Per-node NUMA zones from NodeResourceTopology CRs
    (/root/reference/pkg/noderesourcetopology/numaresources.go:32-103)."""

    available: np.ndarray  # (N, Z, R) int64
    allocatable: np.ndarray  # (N, Z, R) int64
    zone_mask: np.ndarray  # (N, Z) bool
    #: per-resource "zone reports this resource" mask — NUMA affinity only
    #: applies to reported resources (numaresources.go:105-135)
    reported: np.ndarray  # (N, Z, R) bool
    policy: np.ndarray  # (N,) int32 TopologyManagerPolicy
    scope: np.ndarray  # (N,) int32 TopologyManagerScope
    distances: np.ndarray  # (N, Z, Z) int32 SLIT costs (default 10)
    has_nrt: np.ndarray  # (N,) bool
    #: (N,) cache freshness: not-fresh nodes are Unschedulable for any
    #: non-best-effort pod (filter.go:194-197) and score 0
    fresh: np.ndarray
    #: (N,) per-node topology-manager MaxNUMANodes (LeastNUMA normalization,
    #: least_numa.go:88-102; default 8)
    max_numa: np.ndarray
    #: STATIC per-resource power-of-2 rescale enabling the f32 NUMA fast
    #: path: every zone quantity and pending request is exactly divisible by
    #: its scale and the rescaled values keep `value * 100 < 2^24` (exact in
    #: float32, scale-invariant trunc-division scores). None when any
    #: resource fails the guard — solvers then carry float64. Part of the
    #: pytree STRUCTURE, so jit retraces when packability changes.
    pack_scales: Optional[tuple] = struct.field(pytree_node=False, default=None)


@struct.dataclass
class SyscallState:
    """SySched syscall-set tensors (/root/reference/pkg/sysched/sysched.go).

    The per-existing-pod difference sum decomposes per syscall:
        sum_p |newHost - p| = pod_count * |newHost| - sum_s newHost[s] * counts[n, s]
    so only per-node unions and per-syscall pod counts are needed.
    """

    pod_sets: np.ndarray  # (P, S) bool — pending pods' syscall sets
    has_profile: np.ndarray  # (P,) bool
    host_sets: np.ndarray  # (N, S) bool — union over assigned pods
    #: (N, S) number of assigned pods on the node whose set contains syscall s
    counts: np.ndarray
    host_pod_count: np.ndarray  # (N,) int32 assigned pods (HostToPods length)


@struct.dataclass
class NomineeState:
    """Unbound pods nominated to a node after preemption: their demand HOLDS
    node capacity against lower-or-equal-priority pods during the solve — the
    upstream nominator's AddNominatedPods semantics
    (RunFilterPluginsWithNominatedPods adds nominated pods with priority >=
    the evaluated pod). A nominee inside the pending batch stops holding the
    moment it places (tracked via `SolverState.placed_mask`)."""

    node: np.ndarray  # (M,) int32 nominated node index
    demand: np.ndarray  # (M, R) int64 fit demand (pods slot = 1)
    priority: np.ndarray  # (M,) int64
    batch_idx: np.ndarray  # (M,) int32 index in the pending batch, -1 outside
    mask: np.ndarray  # (M,) bool


@struct.dataclass
class ClusterSnapshot:
    nodes: NodeState
    pods: PodState
    gangs: Optional[GangState] = None
    quota: Optional[QuotaState] = None
    metrics: Optional[MetricsState] = None
    numa: Optional[NumaState] = None
    network: Optional["NetworkState"] = None
    syscalls: Optional[SyscallState] = None
    nominees: Optional[NomineeState] = None
    #: in-tree companion-plugin tables (taints, node affinity) — see
    #: state.scheduling
    scheduling: Optional["_sched.SchedulingState"] = None

    @property
    def num_nodes(self) -> int:
        return self.nodes.alloc.shape[0]

    @property
    def num_pods(self) -> int:
        return self.pods.req.shape[0]

    @property
    def num_resources(self) -> int:
        return self.nodes.alloc.shape[1]


@struct.dataclass
class NetworkState:
    """AppGroup dependency + topology cost tensors
    (/root/reference/pkg/networkaware/networkoverhead/networkoverhead.go:448-638).

    Costs between a candidate node and an already-placed dependency pod depend
    only on (region, zone) codes, so placed pods aggregate into per-zone /
    per-region counts and cost lookup is a small dense gather instead of a
    per-pod map search.
    """

    dep_workload: np.ndarray  # (P, D) int32 workload code (-1 pad)
    dep_max_cost: np.ndarray  # (P, D) int64
    dep_mask: np.ndarray  # (P, D) bool
    pod_workload: np.ndarray  # (P,) int32 pending pod's own workload (-1 none)
    #: (W, N) placed pods per workload per node; the live copy is carried
    #: through the scan (SolverState.net_placed) so in-cycle placements are
    #: visible to later pods
    placed_node: np.ndarray
    zone_region: np.ndarray  # (ZC,) int32 region code of each zone (-1 unknown)
    #: class-level dependency rows, one per WORKLOAD code: every pod of a
    #: workload shares its dependency list, so batched filter/score tallies
    #: run once per class ((W, N) work) and gather by `pod_workload`
    #: instead of vmapping the (D, N) tallies over every pod
    cls_dep_workload: np.ndarray = None  # (W, D) int32
    cls_dep_max_cost: np.ndarray = None  # (W, D) int64
    cls_dep_mask: np.ndarray = None  # (W, D) bool


@dataclass
class SnapshotMeta:
    """Host-only name<->code mappings for one snapshot."""

    index: ResourceIndex
    node_names: list[str] = field(default_factory=list)
    pod_names: list[str] = field(default_factory=list)  # pending batch, queue order
    namespaces: list[str] = field(default_factory=list)
    gang_names: list[str] = field(default_factory=list)
    regions: list[str] = field(default_factory=list)
    zones: list[str] = field(default_factory=list)
    workloads: list[str] = field(default_factory=list)

    def node_id(self, name: str) -> int:
        return self.node_names.index(name)

    def ns_id(self, name: str) -> int:
        return self.namespaces.index(name)


class _Interner:
    """O(1) name -> stable-code interning over a shared list."""

    def __init__(self, table: list[str]):
        self.table = table
        self.pos = {name: i for i, name in enumerate(table)}

    def code(self, name: str) -> int:
        i = self.pos.get(name)
        if i is None:
            i = len(self.table)
            self.table.append(name)
            self.pos[name] = i
        return i

    def get(self, name: str) -> int:
        """Code for `name`, or -1 if never interned."""
        return self.pos.get(name, -1)


def nonzero_request(req: np.ndarray, index: ResourceIndex) -> np.ndarray:
    """Apply the upstream non-zero defaults used for scoring accounting:
    pods without cpu/memory requests are charged 100m / 200Mi."""
    out = req.copy()
    cpu_i = index.position(CPU)
    mem_i = index.position(MEMORY)
    if out[cpu_i] == 0:
        out[cpu_i] = DEFAULT_MILLI_CPU_REQUEST
    if out[mem_i] == 0:
        out[mem_i] = DEFAULT_MEMORY_REQUEST
    return out


class _PodRow:
    """Cached per-pod lowering pieces for `build_pod_state` — everything
    derivable from the pod SPEC alone (requests/limits encodes, container
    rows, QoS, TLP prediction), keyed by pod object identity so a feed
    upsert (which replaces the object wholesale) naturally invalidates.
    Meta-dependent codes (namespace interning, gang code) and in-place
    mutable flags (scheduling gate) are never cached."""

    __slots__ = ("pod", "index", "tlp", "req", "limits", "predicted",
                 "creq", "cinit", "qos")

    def __init__(self, pod, index, tlp_prediction):
        self.pod = pod
        self.index = index
        self.tlp = tlp_prediction
        self.req = index.encode(pod.effective_request())
        self.limits = index.encode(pod.effective_limits())
        self.predicted = pod.tlp_predicted_cpu_millis(*tlp_prediction)
        conts = list(pod.init_containers) + list(pod.containers)
        self.creq = np.stack(
            [index.encode(c.requests) for c in conts]
        ) if conts else np.zeros((0, len(index)), I64)
        self.cinit = np.array(
            [c < len(pod.init_containers) for c in range(len(conts))], bool
        )
        self.qos = int(pod.qos_class())


def build_pod_state(
    pending_pods: Sequence[Pod],
    P: int,
    index: ResourceIndex,
    ns_in: "_Interner",
    gang_of,
    tlp_prediction: tuple = (1.5, 1000),
    row_cache: dict | None = None,
) -> PodState:
    """Lower the pending batch into `PodState` (host numpy) — THE one copy
    of the pod-tensor lowering, shared by `build_snapshot` and the serving
    engine's per-cycle assembly (`serving.engine.ServeEngine._assemble`),
    so the two paths produce bit-identical pod tensors by construction.
    `ns_in` interns namespace codes into the caller's meta table;
    `gang_of(pod) -> int` maps a pod to its gang code (-1 outside).
    `row_cache` (uid -> `_PodRow`, the streaming serve engine's O(changed)
    assembly) memoizes the spec-derived pieces across cycles for pods
    that retry — entries re-derive whenever the pod object, resource axis
    or TLP parameters differ, so a hit is bit-identical by construction."""
    R = len(index)
    preq = np.zeros((P, R), I64)
    plimits = np.zeros((P, R), I64)
    ppredicted = np.zeros(P, I64)
    C = max(
        max(
            (len(p.init_containers) + len(p.containers) for p in pending_pods),
            default=1,
        ),
        1,
    )
    pcreq = np.zeros((P, C, R), I64)
    pcinit = np.zeros((P, C), bool)
    pcmask = np.zeros((P, C), bool)
    ppriority = np.zeros(P, I64)
    pns = np.zeros(P, I32)
    pgang = np.full(P, -1, I32)
    pqos = np.zeros(P, I32)
    pmask = np.zeros(P, bool)
    pcreated = np.zeros(P, I64)
    pgated = np.zeros(P, bool)
    for i, pod in enumerate(pending_pods):
        row = None
        if row_cache is not None:
            row = row_cache.get(pod.uid)
            if (
                row is None or row.pod is not pod or row.index is not index
                or row.tlp != tlp_prediction
            ):
                row = row_cache[pod.uid] = _PodRow(pod, index, tlp_prediction)
        if row is not None:
            preq[i] = row.req
            plimits[i] = row.limits
            ppredicted[i] = row.predicted
            nC = row.creq.shape[0]
            pcreq[i, :nC] = row.creq
            pcinit[i, :nC] = row.cinit
            pcmask[i, :nC] = True
            pqos[i] = row.qos
        else:
            preq[i] = index.encode(pod.effective_request())
            plimits[i] = index.encode(pod.effective_limits())
            ppredicted[i] = pod.tlp_predicted_cpu_millis(*tlp_prediction)
            for c, cont in enumerate(
                list(pod.init_containers) + list(pod.containers)
            ):
                pcreq[i, c] = index.encode(cont.requests)
                pcinit[i, c] = c < len(pod.init_containers)
                pcmask[i, c] = True
            pqos[i] = int(pod.qos_class())
        ppriority[i] = pod.priority
        pns[i] = ns_in.code(pod.namespace)
        pgang[i] = gang_of(pod)
        pmask[i] = True
        pcreated[i] = pod.creation_ms
        pgated[i] = pod.scheduling_gated
    return PodState(
        req=preq,
        limits=plimits,
        predicted_cpu_millis=ppredicted,
        container_req=pcreq,
        container_is_init=pcinit,
        container_mask=pcmask,
        priority=ppriority,
        ns=pns,
        gang=pgang,
        qos=pqos,
        mask=pmask,
        creation_ms=pcreated,
        gated=pgated,
    )


def gang_object_tables(pod_groups, gang_pos, index, G: int,
                       backed_off_gangs) -> dict:
    """The PodGroup-OBJECT-derived `GangState` columns (min_member,
    creation, backoff, MinResources incl. the pods-slot MinMember
    injection, mask) — THE one copy of this lowering, shared by
    `build_snapshot` and the serving engine's resident side-table
    assembly (`serving.engine.ServeEngine._assemble`), so the two paths
    produce bit-identical object columns by construction. The per-pod
    AGGREGATE columns (total/assigned/gated/cluster_slack) are the
    caller's: the fresh path accumulates them from the pod population,
    the serving engine from its O(changed) resident side tables."""
    R = len(index)
    pods_i = index.position(PODS)
    backed_off = set(backed_off_gangs)
    gang_min = np.ones(G, I32)
    gang_minres = np.zeros((G, R), I64)
    gang_has_minres = np.zeros(G, bool)
    gang_created = np.zeros(G, I64)
    gang_backoff = np.zeros(G, bool)
    gang_mask = np.zeros(G, bool)
    for pg in pod_groups:
        g = gang_pos[pg.full_name]
        gang_mask[g] = True
        gang_min[g] = pg.min_member
        gang_created[g] = pg.creation_ms
        gang_backoff[g] = pg.full_name in backed_off
        if pg.min_resources:
            gang_minres[g] = index.encode(pg.min_resources)
            gang_has_minres[g] = True
            # MinResources demand includes a pods slot of MinMember
            # (core.go:295-297 injects minResources[pods] = MinMember)
            gang_minres[g, pods_i] = pg.min_member
    return {
        "min_member": gang_min,
        "min_resources": gang_minres,
        "has_min_resources": gang_has_minres,
        "creation_ms": gang_created,
        "backed_off": gang_backoff,
        "mask": gang_mask,
    }


def quota_object_tables(quotas, index, ns_in: "_Interner", Q: int):
    """The ElasticQuota-OBJECT-derived `QuotaState` columns (min, max,
    has_quota) — one copy shared by `build_snapshot` and the serving
    engine (same rationale as `gang_object_tables`). Callers must have
    interned every quota namespace into `ns_in` already (the fresh
    path's interning order: batch first, then quotas, then assigned)."""
    R = len(index)
    qmin = np.zeros((Q, R), I64)
    qmax = np.full((Q, R), np.iinfo(I64).max, I64)
    qhas = np.zeros(Q, bool)
    for q in quotas:
        nsi = ns_in.get(q.namespace)
        qhas[nsi] = True
        qmin[nsi] = index.encode(q.min)
        # absent resources in Max are unbounded (UpperBound semantics,
        # /root/reference/pkg/capacityscheduling/elasticquota.go:96-120)
        qmax[nsi] = index.encode(q.max, default=np.iinfo(I64).max)
    return qmin, qmax, qhas


def empty_quota_nominees(R: int, P: int):
    """The nominee-table defaults an empty nominated set produces
    (M = 1 all-zero rows, batch_idx -1) — the serving engine's case by
    construction: its compatibility gate excludes every nomination."""
    return (
        np.zeros((1, R), I64),
        np.zeros((1, P), bool),
        np.zeros((1, P), bool),
        np.full(1, -1, I32),
    )


def build_snapshot(
    nodes: Sequence[Node],
    pending_pods: Sequence[Pod],
    assigned_pods: Sequence[Pod] = (),
    pod_groups: Sequence[PodGroup] = (),
    quotas: Sequence[ElasticQuota] = (),
    nrts: Sequence[NodeResourceTopology] = (),
    app_groups: Sequence[AppGroup] = (),
    node_metrics: Optional[dict] = None,
    extra_resources: Sequence[str] = (),
    pad_nodes: Optional[int] = None,
    pad_pods: Optional[int] = None,
    backed_off_gangs: Sequence[str] = (),
    extra_pods: Sequence[Pod] = (),
    stale_nrt_nodes: Sequence[str] = (),
    seccomp_profiles: Sequence = (),
    native_nodes: Optional[dict] = None,
    tlp_prediction: tuple = (1.5, 1000),
    sysched_default_profile: Optional[str] = None,
    namespaces: Sequence = (),
) -> tuple[ClusterSnapshot, SnapshotMeta]:
    """Lower host objects into a `ClusterSnapshot`.

    `pending_pods` become the pod batch (in the given order — queue order is
    decided by the framework before calling this). `assigned_pods` only
    contribute to node usage / gang+quota accounting. `extra_pods` are pods
    that are neither schedulable nor assigned (e.g. SchedulingGated) but still
    count toward gang membership and gated-quorum accounting.

    `native_nodes`, when given, is a `bridge.NativeStore.export_nodes()` dict
    whose rows are in the SAME order as `nodes`; the hot node columns (alloc,
    capacity, requested, nonzero, limits, pod_count, terminating) are taken
    from it verbatim — the caller guarantees the store already accounts every
    assigned/reserved pod, so `assigned_pods` should be empty. Engaged only
    when the resource axis is exactly the canonical four (the store layout).
    """
    index = ResourceIndex.union(
        {r: 0 for r in extra_resources},
        *[n.allocatable for n in nodes],
        *[pg.min_resources for pg in pod_groups],
        *[q.min for q in quotas],
        *[q.max for q in quotas],
        *[p.effective_request() for p in list(pending_pods) + list(assigned_pods)],
        *[z.available for t in nrts for z in t.zones],
        *[z.allocatable for t in nrts for z in t.zones],
    )
    R = len(index)
    N = pad_nodes or bucket_size(max(len(nodes), 1))
    P = pad_pods or bucket_size(max(len(pending_pods), 1))

    meta = SnapshotMeta(index=index)
    meta.node_names = [n.name for n in nodes]
    meta.pod_names = [p.uid for p in pending_pods]
    regions_in = _Interner(meta.regions)
    zones_in = _Interner(meta.zones)
    ns_in = _Interner(meta.namespaces)
    gangs_in = _Interner(meta.gang_names)

    # --- nodes ---------------------------------------------------------
    alloc = np.zeros((N, R), I64)
    capacity = np.zeros((N, R), I64)
    requested = np.zeros((N, R), I64)
    nonzero_req = np.zeros((N, R), I64)
    node_limits = np.zeros((N, R), I64)
    node_mask = np.zeros(N, bool)
    region = np.full(N, -1, I32)
    zone = np.full(N, -1, I32)
    pod_count = np.zeros(N, I32)
    terminating = np.zeros(N, I32)
    nominated = np.zeros(N, I32)

    use_native = native_nodes is not None and tuple(index.names) == CANONICAL
    node_pos = {}
    for i, node in enumerate(nodes):
        node_pos[node.name] = i
        if not use_native:
            alloc[i] = index.encode(node.allocatable)
            capacity[i] = index.encode(node.capacity)
        node_mask[i] = not node.unschedulable
        if node.region:
            region[i] = regions_in.code(node.region)
        if node.zone:
            zone[i] = zones_in.code(node.zone)

    # nominated counter (PodState score, pod_state.go:56): every unbound pod
    # with a nomination counts, wherever it lives — upstream's nominator keeps
    # a popped pod's own nomination until assume, so the batch is included
    seen_nominated: set = set()
    nominee_pods: list[Pod] = []
    for pod in list(pending_pods) + list(assigned_pods) + list(extra_pods):
        if (
            pod.node_name is None
            and pod.nominated_node_name in node_pos
            and pod.uid not in seen_nominated
        ):
            seen_nominated.add(pod.uid)
            nominated[node_pos[pod.nominated_node_name]] += 1
            nominee_pods.append(pod)

    pods_i = index.position(PODS)
    if use_native:
        # hot columns straight from the C++ store exports (the store
        # already accounts every assigned/reserved pod, pods slot included)
        n_act = len(nodes)
        alloc[:n_act] = native_nodes["alloc"]
        capacity[:n_act] = native_nodes["capacity"]
        requested[:n_act] = native_nodes["requested"]
        nonzero_req[:n_act] = native_nodes["nonzero_requested"]
        node_limits[:n_act] = native_nodes["limits"]
        pod_count[:n_act] = native_nodes["pod_count"]
        terminating[:n_act] = native_nodes["terminating"]
    else:
        for pod in assigned_pods:
            if pod.node_name is None or pod.node_name not in node_pos:
                continue
            i = node_pos[pod.node_name]
            req = index.encode(pod.effective_request())
            requested[i] += req
            nonzero_req[i] += nonzero_request(req, index)
            # limits clamped to >= requests per pod (SetMaxLimits)
            node_limits[i] += np.maximum(
                index.encode(pod.effective_limits()), req
            )
            pod_count[i] += 1
            if pod.terminating:
                terminating[i] += 1

        # the "pods" resource is accounted as a count, not a request sum
        requested[:, pods_i] = pod_count
        nonzero_req[:, pods_i] = pod_count

    node_state = NodeState(
        alloc=alloc,
        capacity=capacity,
        requested=requested,
        nonzero_requested=nonzero_req,
        limits=node_limits,
        mask=node_mask,
        region=region,
        zone=zone,
        pod_count=pod_count,
        terminating=terminating,
        nominated=nominated,
    )

    # --- gangs ---------------------------------------------------------
    gang_pos = {}
    for pg in pod_groups:
        gang_pos[pg.full_name] = gangs_in.code(pg.full_name)
    G = max(len(gang_pos), 1)
    obj = gang_object_tables(pod_groups, gang_pos, index, G,
                             backed_off_gangs)
    gang_total = np.zeros(G, I32)
    gang_assigned = np.zeros(G, I32)

    def _gang_of(pod: Pod) -> int:
        name = pod.pod_group()
        if not name:
            return -1
        return gang_pos.get(f"{pod.namespace}/{name}", -1)

    gang_gated = np.zeros(G, I32)
    # cluster_slack[g] = total demand of already-assigned members, added back
    # in the cluster sweep (getNodeResource removes the gang's own pods,
    # core.go:433-467; raw sums make the correction a plain total)
    gang_slack = np.zeros((G, R), I64)
    for pod in list(pending_pods) + list(assigned_pods) + list(extra_pods):
        g = _gang_of(pod)
        if g >= 0:
            gang_total[g] += 1
            if pod.node_name is not None:
                gang_assigned[g] += 1
                if pod.node_name in node_pos:
                    vec = index.encode(pod.effective_request())
                    vec[pods_i] = 1
                    gang_slack[g] += vec
            elif pod.scheduling_gated:
                gang_gated[g] += 1

    gang_state = (
        GangState(
            total_members=gang_total,
            assigned=gang_assigned,
            gated=gang_gated,
            cluster_slack=gang_slack,
            **obj,
        )
        if pod_groups
        else None
    )

    # --- pods (pending batch) -----------------------------------------
    pod_state = build_pod_state(
        pending_pods, P, index, ns_in, _gang_of, tlp_prediction
    )

    # --- quota ---------------------------------------------------------
    quota_state = None
    if quotas:
        for q in quotas:
            ns_in.code(q.namespace)
        for pod in assigned_pods:
            ns_in.code(pod.namespace)
        Q = max(len(meta.namespaces), 1)
        qused = np.zeros((Q, R), I64)
        qmin, qmax, qhas = quota_object_tables(quotas, index, ns_in, Q)
        for pod in assigned_pods:
            if pod.node_name is None:
                continue
            nsi = ns_in.get(pod.namespace)
            if qhas[nsi]:
                qused[nsi] += index.encode(pod.effective_request())
        # nominated-pod tables
        nominated = [
            p
            for p in list(pending_pods) + list(extra_pods)
            if p.nominated_node_name is not None and p.node_name is None
        ]
        batch_pos = {p.uid: i for i, p in enumerate(pending_pods)}
        M = max(len(nominated), 1)
        nom_req = np.zeros((M, R), I64)
        nom_in_eq_mask = np.zeros((M, P), bool)
        nom_total_mask = np.zeros((M, P), bool)
        nom_batch_idx = np.full(M, -1, I32)
        if nominated:
            over_min = np.any(qused > qmin, axis=1)  # (Q,) usedOverMin
            for j, m in enumerate(nominated):
                m_ns = ns_in.get(m.namespace)
                if m_ns < 0 or not qhas[m_ns]:
                    continue
                nom_req[j] = index.encode(m.effective_request())
                nom_batch_idx[j] = batch_pos.get(m.uid, -1)
                from scheduler_plugins_tpu.ops.quota import nominee_contribution

                for i, pod in enumerate(pending_pods):
                    if m.uid == pod.uid:
                        continue
                    in_eq, total = nominee_contribution(
                        m.namespace == pod.namespace, m.priority,
                        pod.priority, bool(over_min[m_ns]),
                    )
                    nom_in_eq_mask[j, i] = in_eq
                    nom_total_mask[j, i] = total
        quota_state = QuotaState(
            min=qmin, max=qmax, used=qused, has_quota=qhas,
            nom_req=nom_req, nom_in_eq_mask=nom_in_eq_mask,
            nom_total_mask=nom_total_mask, nom_batch_idx=nom_batch_idx,
        )

    # --- metrics --------------------------------------------------------
    metrics_state = None
    if node_metrics is not None:
        cpu_avg = np.zeros(N, F64)
        cpu_tlp = np.zeros(N, F64)
        cpu_peaks = np.zeros(N, F64)
        cpu_std = np.zeros(N, F64)
        mem_avg = np.zeros(N, F64)
        mem_std = np.zeros(N, F64)
        cpu_valid = np.zeros(N, bool)
        cpu_tlp_valid = np.zeros(N, bool)
        mem_valid = np.zeros(N, bool)
        missing = np.zeros(N, I64)
        for name, m in node_metrics.items():
            if name not in node_pos:
                continue
            i = node_pos[name]
            if "cpu_avg" in m:
                cpu_avg[i] = m["cpu_avg"]
            cpu_tlp[i] = m.get("cpu_tlp", m.get("cpu_avg", 0.0))
            cpu_peaks[i] = m.get(
                "cpu_peaks", m.get("cpu_tlp", m.get("cpu_avg", 0.0))
            )
            cpu_std[i] = m.get("cpu_std", 0.0)
            # a node with ANY cpu sample (avg/latest or std-only) is valid:
            # GetResourceData returns isValid=true, avg=0 for std-only
            # (resourcestats.go:88-106)
            cpu_valid[i] = "cpu_avg" in m or "cpu_std" in m
            cpu_tlp_valid[i] = "cpu_tlp" in m or "cpu_avg" in m
            if "mem_avg" in m:
                mem_avg[i] = m["mem_avg"]
            mem_valid[i] = "mem_avg" in m or "mem_std" in m
            mem_std[i] = m.get("mem_std", 0.0)
            missing[i] = m.get("missing_cpu_millis", 0)
        metrics_state = MetricsState(
            cpu_avg=cpu_avg,
            cpu_tlp=cpu_tlp,
            cpu_peaks=cpu_peaks,
            cpu_std=cpu_std,
            mem_avg=mem_avg,
            mem_std=mem_std,
            cpu_valid=cpu_valid,
            cpu_tlp_valid=cpu_tlp_valid,
            mem_valid=mem_valid,
            missing_cpu_millis=missing,
        )

    # --- numa -----------------------------------------------------------
    numa_state = None
    if nrts:
        # zone axis is indexed by NUMA id (zones lists may arrive unordered;
        # costs are keyed by numa_id, so both axes must share the id space)
        Z = max(
            max((z.numa_id + 1 for t in nrts for z in t.zones), default=1), 1
        )
        z_avail = np.zeros((N, Z, R), I64)
        z_alloc = np.zeros((N, Z, R), I64)
        z_mask = np.zeros((N, Z), bool)
        z_reported = np.zeros((N, Z, R), bool)
        policy = np.zeros(N, I32)
        scope = np.zeros(N, I32)
        distances = np.full((N, Z, Z), 10, I32)
        has_nrt = np.zeros(N, bool)
        nrt_fresh = np.ones(N, bool)
        max_numa = np.full(N, 8, I32)
        for name in stale_nrt_nodes:
            if name in node_pos:
                nrt_fresh[node_pos[name]] = False
        for t in nrts:
            if t.node_name not in node_pos:
                continue
            i = node_pos[t.node_name]
            has_nrt[i] = True
            policy[i] = int(t.policy)
            scope[i] = int(t.scope)
            max_numa[i] = t.max_numa_nodes
            for zinfo in t.zones:
                z = zinfo.numa_id
                z_mask[i, z] = True
                z_avail[i, z] = index.encode(zinfo.available)
                z_alloc[i, z] = index.encode(zinfo.allocatable)
                for rname in zinfo.available:
                    z_reported[i, z, index.position(rname)] = True
                for other, cost in zinfo.costs.items():
                    if other < Z:
                        distances[i, z, other] = cost
        numa_state = NumaState(
            available=z_avail,
            allocatable=z_alloc,
            zone_mask=z_mask,
            reported=z_reported,
            policy=policy,
            scope=scope,
            distances=distances,
            has_nrt=has_nrt,
            fresh=nrt_fresh,
            max_numa=max_numa,
            pack_scales=_numa_pack_scales(
                z_avail, z_alloc, pod_state.req, pod_state.container_req, R
            ),
        )

    # nominee capacity holds (upstream AddNominatedPods semantics)
    nominee_state = None
    if nominee_pods:
        M = len(nominee_pods)
        batch_pos_nom = {p.uid: i for i, p in enumerate(pending_pods)}
        nom_node = np.zeros(M, I32)
        nom_demand = np.zeros((M, R), I64)
        nom_pri = np.zeros(M, I64)
        nom_batch = np.full(M, -1, I32)
        for j, p in enumerate(nominee_pods):
            nom_node[j] = node_pos[p.nominated_node_name]
            nom_demand[j] = index.encode(p.effective_request())
            nom_demand[j, pods_i] = 1
            nom_pri[j] = p.priority
            nom_batch[j] = batch_pos_nom.get(p.uid, -1)
        nominee_state = NomineeState(
            node=nom_node, demand=nom_demand, priority=nom_pri,
            batch_idx=nom_batch, mask=np.ones(M, bool),
        )

    snapshot = ClusterSnapshot(
        nominees=nominee_state,
        nodes=node_state,
        pods=pod_state,
        gangs=gang_state,
        quota=quota_state,
        metrics=metrics_state,
        numa=numa_state,
        network=_build_network(
            app_groups, pending_pods, assigned_pods, node_pos, region, zone, meta, P
        )
        if app_groups
        else None,
        syscalls=_build_syscalls(
            seccomp_profiles, pending_pods, assigned_pods, node_pos, N, P,
            default_profile=sysched_default_profile,
        )
        if seccomp_profiles
        else None,
        scheduling=_sched.build_scheduling(
            nodes, pending_pods, N, P, assigned=assigned_pods,
            namespaces=namespaces,
        ),
    )
    # hand jit-ready device arrays to callers (numpy is build-time only;
    # tracer indexing inside lax.scan requires jax arrays)
    import jax
    import jax.numpy as jnp

    snapshot = jax.tree.map(jnp.asarray, snapshot)
    return snapshot, meta


#: rescaled quantities must keep value * MAX_NODE_SCORE (100) exactly
#: representable in float32
_F32_PACK_LIMIT = (1 << 24) // 128


def _numa_pack_scales(z_avail, z_alloc, preq, pcreq, R):
    """Per-resource power-of-2 scales for the f32 NUMA fast path, or None.

    A resource packs when every zone quantity and every pending (container)
    request is divisible by 2^k and the rescaled maximum stays below
    2^24/128 (so `value * 100` is exact in float32). Scale-invariance of the
    trunc-division strategy scores (floor of an unchanged rational) keeps
    packed placements bit-identical to the int64 semantics.
    """
    scales = []
    for r in range(R):
        vals = np.concatenate(
            [z_avail[:, :, r].ravel(), z_alloc[:, :, r].ravel(),
             preq[:, r].ravel(), pcreq[:, :, r].ravel()]
        )
        vals = vals[vals > 0]
        if vals.size == 0:
            scales.append(1)
            continue
        # largest power of two dividing every value: min of lowest set bits
        scale = int(np.min(vals & -vals))
        if int(vals.max()) // scale >= _F32_PACK_LIMIT:
            return None
        scales.append(scale)
    return tuple(scales)


def _build_network(app_groups, pending_pods, assigned_pods, node_pos, region, zone, meta, P):
    """Lower AppGroup dependencies + placed-pod locations into NetworkState.
    Cost matrices are attached later by the NetworkOverhead plugin config
    (they come from the NetworkTopology CR, not the AppGroup)."""
    # intern workload selectors
    workloads_in = _Interner(meta.workloads)
    dep_lists = {}  # workload code -> [(dep workload code, max cost)]
    for ag in app_groups:
        for w in ag.workloads:
            wc = workloads_in.code(f"{ag.namespace}/{w.selector}")
            dep_lists[wc] = [
                (workloads_in.code(f"{ag.namespace}/{d.workload_selector}"), d.max_network_cost)
                for d in w.dependencies
            ]
    W = max(len(meta.workloads), 1)
    D = max(max((len(v) for v in dep_lists.values()), default=1), 1)
    ZC = max(len(meta.zones), 1)
    RC = max(len(meta.regions), 1)
    N = region.shape[0]

    dep_workload = np.full((P, D), -1, I32)
    dep_max_cost = np.zeros((P, D), I64)
    dep_mask = np.zeros((P, D), bool)
    pod_workload = np.full(P, -1, I32)
    for i, pod in enumerate(pending_pods):
        sel = pod.workload_selector()
        key = f"{pod.namespace}/{sel}"
        wc = workloads_in.get(key) if sel else -1
        if wc < 0:
            continue
        pod_workload[i] = wc
        deps = dep_lists.get(wc, [])
        for d, (dw, mc) in enumerate(deps):
            dep_workload[i, d] = dw
            dep_max_cost[i, d] = mc
            dep_mask[i, d] = True

    placed_node = np.zeros((W, N), I32)
    zone_region = np.full(ZC, -1, I32)
    for ni in range(N):
        if zone[ni] >= 0 and region[ni] >= 0:
            zone_region[zone[ni]] = region[ni]
    for pod in assigned_pods:
        sel = pod.workload_selector()
        if not sel or pod.node_name not in node_pos:
            continue
        key = f"{pod.namespace}/{sel}"
        wc = workloads_in.get(key)
        if wc < 0:
            continue
        placed_node[wc, node_pos[pod.node_name]] += 1

    cls_dep_workload = np.full((W, D), -1, I32)
    cls_dep_max_cost = np.zeros((W, D), I64)
    cls_dep_mask = np.zeros((W, D), bool)
    for wc, deps in dep_lists.items():
        for d, (dw, mc) in enumerate(deps):
            cls_dep_workload[wc, d] = dw
            cls_dep_max_cost[wc, d] = mc
            cls_dep_mask[wc, d] = True

    return NetworkState(
        dep_workload=dep_workload,
        dep_max_cost=dep_max_cost,
        dep_mask=dep_mask,
        pod_workload=pod_workload,
        placed_node=placed_node,
        zone_region=zone_region,
        cls_dep_workload=cls_dep_workload,
        cls_dep_max_cost=cls_dep_max_cost,
        cls_dep_mask=cls_dep_mask,
    )


#: pod annotations whose key contains this mark carry an SPO profile path
#: (sysched.go SPO_ANNOTATION)
SPO_ANNOTATION = "seccomp.security.alpha.kubernetes.io"


def parse_profile_path(path: str):
    """parseNameNS (sysched.go:67-83): namespace = second-to-last path
    segment, name = last segment minus extension; <2 segments = invalid."""
    if not path:
        return None
    parts = path.split("/")
    if len(parts) < 2:
        return None
    name = parts[-1]
    if "." in name:
        name = name[: name.rindex(".")]
    return f"{parts[-2]}/{name}"


def _build_syscalls(
    profiles, pending_pods, assigned_pods, node_pos, N, P,
    default_profile=None,
):
    """Lower seccomp profiles + pod references into SyscallState
    (/root/reference/pkg/sysched/sysched.go:124-210): pod syscall set =
    union of (container SeccompProfile references) + (the first SPO
    annotation's profile); pods resolving NO syscalls fall back to the
    configured default profile (the all-syscalls CR), and only when that
    too is missing does the plugin score them MaxInt64-equivalent."""
    by_name = {}
    universe: list[str] = []
    pos: dict[str, int] = {}
    for prof in profiles:
        by_name[prof.full_name] = prof
        for sc in sorted(prof.syscalls):
            if sc not in pos:
                pos[sc] = len(universe)
                universe.append(sc)
    S = max(len(universe), 1)

    def resolve(ref, namespace):
        if not ref:
            return None
        if ref.count("/") >= 2 or ref.endswith(".json"):
            # localhost profile path (operator/<ns>/<name>.json)
            ref = parse_profile_path(ref)
        elif "/" not in ref:
            # bare names resolve in the pod's own namespace
            ref = f"{namespace}/{ref}"
        return by_name.get(ref) if ref else None

    def pod_set(pod):
        vec = np.zeros(S, bool)
        found = False
        for cont in list(pod.containers) + list(pod.init_containers):
            prof = resolve(cont.seccomp_profile, pod.namespace)
            if prof is not None:
                found = True
                for sc in prof.syscalls:
                    vec[pos[sc]] = True
        # SPO auto-annotation: the reference merges the FIRST seccomp
        # annotation then breaks (sysched.go:171-196); Go map order is
        # random — we pin sorted key order for determinism
        for key in sorted(pod.annotations):
            if SPO_ANNOTATION in key:
                prof = resolve(pod.annotations[key], pod.namespace)
                if prof is not None:
                    found = True
                    for sc in prof.syscalls:
                        vec[pos[sc]] = True
                break
        if not found and default_profile is not None:
            prof = by_name.get(default_profile)
            if prof is not None and prof.syscalls:
                found = True
                for sc in prof.syscalls:
                    vec[pos[sc]] = True
        return vec, found

    pod_sets = np.zeros((P, S), bool)
    has_profile = np.zeros(P, bool)
    for i, pod in enumerate(pending_pods):
        pod_sets[i], has_profile[i] = pod_set(pod)

    host_sets = np.zeros((N, S), bool)
    counts = np.zeros((N, S), I32)
    host_pods = np.zeros(N, I32)
    for pod in assigned_pods:
        if pod.node_name not in node_pos:
            continue
        ni = node_pos[pod.node_name]
        vec, _ = pod_set(pod)
        host_sets[ni] |= vec
        counts[ni] += vec
        host_pods[ni] += 1
    return SyscallState(
        pod_sets=pod_sets,
        has_profile=has_profile,
        host_sets=host_sets,
        counts=counts,
        host_pod_count=host_pods,
    )
