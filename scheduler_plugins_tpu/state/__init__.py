"""Cluster state: the dense-tensor snapshot consumed by the jitted solver and
the mutable host-side store that builds/maintains it from cluster events."""

from scheduler_plugins_tpu.state.cluster import Cluster  # noqa: F401
from scheduler_plugins_tpu.state.snapshot import (  # noqa: F401
    ClusterSnapshot,
    SnapshotMeta,
    build_snapshot,
)
