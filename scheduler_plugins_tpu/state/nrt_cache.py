"""NodeResourceTopology cache tier — host-side, event-driven bookkeeping.

Reference: /root/reference/pkg/noderesourcetopology/cache (SURVEY.md §2.6).
Three interchangeable policies select how zone availability reaches the
snapshot between a Reserve and the node agent's next NRT report:

- `PassthroughCache`    always reads the live NRT objects; always fresh
  (cache/passthrough.go).
- `DiscardReservedCache` blocks a node entirely between Reserve and
  PostBind/Unreserve (reservationMap keyed node -> podUIDs,
  cache/discardreserved.go:46-110).
- `OverReserveCache`    the flagship: stores NRT deep-copies plus per-node
  assumed pod requests; the view deducts assumed resources from EVERY zone
  pessimistically (cache/store.go:129-160, overreserve.go:101-127); nodes
  hosting foreign pods are not fresh; a background resync accepts a node's
  newer NRT only when the agent-stamped pod fingerprint matches the pods the
  scheduler believes are on the node (overreserve.go:276-348), then flushes
  and bumps the generation (overreserve.go:351-373).

The pod fingerprint is functionally equivalent to the podfingerprint library:
a stable hash over the sorted (namespace, name) pairs of the node's pods.
"""

from __future__ import annotations

import copy
import hashlib
from dataclasses import dataclass, field
from typing import Iterable, Optional

from scheduler_plugins_tpu.api.objects import (
    NodeResourceTopology,
    Pod,
    PodPhase,
    QOSClass,
)
from scheduler_plugins_tpu.api.resources import CPU, MEMORY, add_quantities
from scheduler_plugins_tpu.utils import observability as obs


def uses_exclusive_resources(pod: Pod) -> bool:
    """AreExclusiveForPod (resourcerequests/exclusive.go:47-95): extended
    resources are always exclusive (devices); for Guaranteed pods, integral
    CPU, any memory and hugepages are exclusive. Non-restartable init
    containers are ignored (they finish before steady state)."""
    qos = pod.qos_class()
    containers = [
        c for c in pod.init_containers if c.restart_policy_always
    ] + list(pod.containers)
    for c in containers:
        for name, qty in c.requests.items():
            # extended resources are devices; kubernetes.io/-prefixed names
            # are NATIVE (IsNativeResource, exclusive.go:74-77)
            if "/" in name and not name.startswith("kubernetes.io/"):
                return True
            if qos != QOSClass.GUARANTEED:
                continue
            if name == CPU and qty > 0 and qty % 1000 == 0:
                return True
            if (name == MEMORY or name.startswith("hugepages-")) and qty > 0:
                return True
    return False


def compute_pod_fingerprint(pods: Iterable[tuple[str, str]]) -> str:
    """Stable fingerprint over (namespace, name) pairs — the contract of the
    podfingerprint library: agent and scheduler compute it independently from
    their own view of the node's pods and compare."""
    h = hashlib.sha256()
    for ns, name in sorted(pods):
        h.update(f"{ns}/{name};".encode())
    return "pfp0v1:" + h.hexdigest()[:16]


class NrtCache:
    """Interface: snapshot-facing view + scheduling lifecycle hooks."""

    def view(self) -> tuple[list[NodeResourceTopology], set[str]]:
        """Returns (adjusted NRT list, stale node names)."""
        raise NotImplementedError

    def reserve(self, node: str, pod: Pod) -> None:  # Reserve
        pass

    def unreserve(self, node: str, pod: Pod) -> None:  # Unreserve
        pass

    def post_bind(self, node: str, pod: Pod) -> None:  # PostBind
        pass

    def update_nrt(self, nrt: NodeResourceTopology) -> None:  # informer event
        raise NotImplementedError

    def delete_nrt(self, node: str) -> None:  # informer delete event
        """CR deleted: the node no longer publishes topology; every cache
        tier must drop its copy (and any pending resync state)."""
        for attr in ("nrts", "pending"):
            store = getattr(self, attr, None)
            if store is not None:
                store.pop(node, None)


class PassthroughCache(NrtCache):
    """Live API reads, always fresh (cache/passthrough.go)."""

    def __init__(self):
        self.nrts: dict[str, NodeResourceTopology] = {}

    def update_nrt(self, nrt: NodeResourceTopology) -> None:
        self.nrts[nrt.node_name] = nrt

    def view(self):
        return list(self.nrts.values()), set()


class DiscardReservedCache(NrtCache):
    """Node fully blocked while any reservation is in flight
    (cache/discardreserved.go:46-110)."""

    def __init__(self):
        self.nrts: dict[str, NodeResourceTopology] = {}
        self.reservations: dict[str, set[str]] = {}

    def update_nrt(self, nrt: NodeResourceTopology) -> None:
        self.nrts[nrt.node_name] = nrt

    def reserve(self, node: str, pod: Pod) -> None:
        self.reservations.setdefault(node, set()).add(pod.uid)

    def unreserve(self, node: str, pod: Pod) -> None:
        self._clear(node, pod)

    def post_bind(self, node: str, pod: Pod) -> None:
        self._clear(node, pod)

    def _clear(self, node: str, pod: Pod) -> None:
        uids = self.reservations.get(node)
        if uids is not None:
            uids.discard(pod.uid)
            if not uids:
                del self.reservations[node]

    def view(self):
        stale = {node for node, uids in self.reservations.items() if uids}
        return list(self.nrts.values()), stale


@dataclass
class OverReserveCache(NrtCache):
    """Pessimistic over-reservation with fingerprint-gated resync."""

    #: scheduler profile names considered "ours" — running pods with a
    #: different schedulerName mark their node foreign
    #: (cache/foreign_pods.go:42-99)
    our_schedulers: set[str] = field(default_factory=lambda: {"tpu-scheduler"})
    #: ForeignPodsDetect mode: "All" (default) or "OnlyExclusiveResources",
    #: which narrows foreign marking to pods with pinned cpus/devices
    #: (apis/config defaults: ForeignPodsDetect=All;
    #: resourcerequests/exclusive.go:47-95)
    foreign_pods_detect: str = "All"
    #: Cache.ResyncMethod (store.go:204-222 podFingerprintForNodeTopology):
    #: which pods enter the expected-fingerprint computation. "All" = every
    #: known pod; "OnlyExclusiveResources" = only pods pinning cpus/devices;
    #: "Autodetect" (default) = follow the agent's stamped method attribute
    #: per NRT (pod_fingerprint_method == "with-exclusive-resources").
    resync_method: str = "Autodetect"
    #: Cache.InformerMode (podprovider/podprovider.go:37-93): which pod
    #: events the cache's pod view (fingerprints, foreign tracking) sees.
    #: "Dedicated" (reference default for this cache) = every bound pod;
    #: "Shared" = only pods in Running phase — the shared informer's
    #: relevance predicate (IsPodRelevantShared), so a bound-but-not-yet-
    #: running pod is invisible to fingerprints and foreign detection.
    informer_mode: str = "Dedicated"

    def pod_relevant(self, pod: Pod) -> bool:
        """The provider's PodFilterFunc. Deviation from the reference's
        Dedicated predicate: a bound pod in Pending phase counts here (the
        host store binds without simulating kubelet phase transitions, so
        bound+Pending is normal, not the unexpected-listing case the
        reference logs and drops)."""
        if self.informer_mode == "Shared":
            return pod.phase == PodPhase.RUNNING
        return pod.node_name is not None

    def __post_init__(self):
        self.nrts: dict[str, NodeResourceTopology] = {}  # flushed copies
        self.pending: dict[str, NodeResourceTopology] = {}  # awaiting resync
        #: node -> uid -> (namespace, name, request)
        self.assumed: dict[str, dict[str, tuple[str, str, dict]]] = {}
        self.foreign: set[str] = set()
        self.maybe_overreserved: set[str] = set()
        self.attr_changed: set[str] = set()
        self.generation = 0

    # -- informer events -------------------------------------------------
    def update_nrt(self, nrt: NodeResourceTopology) -> None:
        node = nrt.node_name
        if nrt.policy != getattr(self.nrts.get(node), "policy", nrt.policy) or (
            nrt.scope != getattr(self.nrts.get(node), "scope", nrt.scope)
        ):
            # kubelet config change -> must resync (cache/attr_watch.go:40-99)
            self.attr_changed.add(node)
        if (
            node not in self.assumed
            and node not in self.foreign
            and node not in self.maybe_overreserved
        ):
            # clean node: the informer keeps the store fresh directly; only
            # nodes with live deductions defer to the fingerprint-gated
            # resync (overreserve.go informer path vs resync path)
            self.nrts[node] = copy.deepcopy(nrt)
            self.pending.pop(node, None)
        else:
            self.pending[node] = copy.deepcopy(nrt)

    def track_pod(self, pod: Pod) -> None:
        """Informer pod event: a running pod owned by another scheduler marks
        its node foreign (cache/foreign_pods.go); in OnlyExclusiveResources
        mode, only pods that pin cpus/devices count. The informer-mode
        relevance predicate gates which pod events this view sees at all."""
        if not pod.node_name or pod.scheduler_name in self.our_schedulers:
            return
        if not self.pod_relevant(pod):
            return
        if (
            self.foreign_pods_detect == "OnlyExclusiveResources"
            and not uses_exclusive_resources(pod)
        ):
            return
        self.foreign.add(pod.node_name)

    # -- scheduling lifecycle -------------------------------------------
    def reserve(self, node: str, pod: Pod) -> None:
        if node not in self.nrts:
            # no NRT data yet: nothing to over-reserve against
            # (overreserve.go:151-163)
            return
        self.assumed.setdefault(node, {})[pod.uid] = (
            pod.namespace,
            pod.name,
            pod.effective_request(),
        )

    def unreserve(self, node: str, pod: Pod) -> None:
        self.assumed.get(node, {}).pop(pod.uid, None)

    def mark_maybe_overreserved(self, node: str) -> None:
        """Filter failure on a cached view: the deduction may be stale
        (filter.go:220-223)."""
        self.maybe_overreserved.add(node)

    # -- view ------------------------------------------------------------
    def view(self):
        out = []
        for node, nrt in self.nrts.items():
            total = {}
            for _, _, req in self.assumed.get(node, {}).values():
                total = add_quantities(total, req)
            if total:
                adjusted = copy.deepcopy(nrt)
                for zone in adjusted.zones:
                    # deduct assumed from EVERY zone pessimistically
                    # (cache/store.go:129-160)
                    zone.available = {
                        name: qty - total.get(name, 0)
                        for name, qty in zone.available.items()
                    }
                out.append(adjusted)
            else:
                out.append(nrt)
        return out, set(self.foreign)

    # -- resync loop -----------------------------------------------------
    def desynced_nodes(self) -> set[str]:
        """dirty = foreign ∪ maybe-overreserved ∪ attr-changed
        (GetDesyncedNodes, overreserve.go:212-245)."""
        return self.foreign | self.maybe_overreserved | self.attr_changed

    def resync(self, node_pods: dict[str, list[Pod]]) -> list[str]:
        """One resync pass: for each dirty node with a pending NRT, accept it
        only when the agent-stamped fingerprint matches the pods the
        scheduler knows on that node (overreserve.go:276-348). Returns the
        flushed node names; bumps the generation once if any flushed."""
        flushed = []
        for node in sorted(self.desynced_nodes()):
            candidate = self.pending.get(node)
            if candidate is None:
                if node in self.attr_changed and node in self.nrts:
                    # config change already applied via the informer path
                    self.attr_changed.discard(node)
                continue
            if node not in self.attr_changed:
                # fingerprint from the scheduler's pod view only (the
                # reference reads the pod lister; a deleted pod must not
                # block convergence). Config-changed nodes flush
                # unconditionally (overreserve.go separate ConfigChanged loop).
                # ResyncMethod narrows which pods enter the computation to
                # match how the agent fingerprinted (store.go:204-250):
                only_excl = self.resync_method == "OnlyExclusiveResources" or (
                    self.resync_method == "Autodetect"
                    and candidate.pod_fingerprint_method
                    == "with-exclusive-resources"
                )
                known = {
                    (p.namespace, p.name)
                    for p in node_pods.get(node, [])
                    if not only_excl or uses_exclusive_resources(p)
                }
                expected = compute_pod_fingerprint(known)
                if not candidate.pod_fingerprint:
                    continue  # no fingerprint data: refuse (overreserve.go:306-310)
                if candidate.pod_fingerprint != expected:
                    continue  # agent hasn't caught up; keep the cached view
            self.nrts[node] = candidate
            del self.pending[node]
            # the matched report covers exactly the node's bound pods: drop
            # their assumed deductions, but keep in-flight (permit-waiting)
            # reservations the agent cannot know about yet
            covered = {(p.namespace, p.name) for p in node_pods.get(node, [])}
            remaining = {
                uid: entry
                for uid, entry in self.assumed.get(node, {}).items()
                if (entry[0], entry[1]) not in covered
            }
            if remaining:
                self.assumed[node] = remaining
            else:
                self.assumed.pop(node, None)
            self.foreign.discard(node)
            self.maybe_overreserved.discard(node)
            self.attr_changed.discard(node)
            flushed.append(node)
        if flushed:
            self.generation += 1  # overreserve.go:369
            obs.metrics.inc(obs.CACHE_RESYNC_FLUSHES, len(flushed))
        return flushed
