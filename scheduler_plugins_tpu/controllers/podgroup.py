"""PodGroup status reconciler.

Mirror of /root/reference/pkg/controllers/podgroup_controller.go:66-139 — the
phase machine driven by member pod phases:

    "" -> Pending
    Pending -> Scheduling once MinMember siblings exist (records OccupiedBy)
    Scheduling/Running: recount running/succeeded/failed;
        fewer siblings than MinMember  -> back to Pending
        succeeded+running < MinMember  -> Scheduling
        succeeded+running >= MinMember -> Running
        failed > 0 and failed+running+succeeded >= MinMember -> Failed (final)
        succeeded >= MinMember -> Finished (final)

Terminal phases and the 48h stale-schedule timeout stop reconciliation
(the reference emits a Timeout warning event).
"""

from __future__ import annotations

from scheduler_plugins_tpu.api.objects import PodGroup, PodGroupPhase, PodPhase
from scheduler_plugins_tpu.state.cluster import Cluster

STALE_SCHEDULE_MS = 48 * 3600 * 1000


def reconcile_pod_groups(cluster: Cluster, now_ms: int = 0) -> list[str]:
    """One reconcile pass over every PodGroup; returns emitted event strings
    (the recorder boundary)."""
    events = []
    for pg in cluster.pod_groups.values():
        events.extend(_reconcile_one(cluster, pg, now_ms))
    return events


def _pod_stats(pods) -> tuple[int, int, int]:
    running = sum(1 for p in pods if p.phase == PodPhase.RUNNING)
    succeeded = sum(1 for p in pods if p.phase == PodPhase.SUCCEEDED)
    failed = sum(1 for p in pods if p.phase == PodPhase.FAILED)
    return running, succeeded, failed


def _transition_event(pg: PodGroup, old_phase) -> list[str]:
    """Recorder boundary: one event per phase transition — the
    observability the reference gets from its status patches + manager
    logs (podgroup_controller.go:104-139 phase switch; the recorder itself
    upstream only carries the Timeout warning, line 87). Failure
    transitions record as Warning like the Timeout event, so event-type
    filters see gang failures."""
    if pg.phase == old_phase:
        return []
    etype = "Warning" if pg.phase == PodGroupPhase.FAILED else "Normal"
    return [
        f"{etype} {str(pg.phase)} {pg.full_name}: "
        f"phase transitioned from {str(old_phase) or 'unset'} to {str(pg.phase)}"
    ]


def _reconcile_one(cluster: Cluster, pg: PodGroup, now_ms: int) -> list[str]:
    if pg.phase in (PodGroupPhase.FINISHED, PodGroupPhase.FAILED):
        return []
    if (
        pg.phase in (PodGroupPhase.SCHEDULING, PodGroupPhase.PENDING)
        and pg.running == 0
        and pg.schedule_start_ms - pg.creation_ms > STALE_SCHEDULE_MS
    ):
        return [f"Warning Timeout {pg.full_name}: schedule time longer than 48 hours"]

    old_phase = pg.phase
    pods = cluster.gang_members(pg)
    if pg.phase == PodGroupPhase.PENDING or pg.phase == "":
        pg.phase = PodGroupPhase.PENDING
        if len(pods) >= pg.min_member:
            pg.phase = PodGroupPhase.SCHEDULING
            pg.schedule_start_ms = now_ms
            if pods:
                pg.occupied_by = pods[0].uid
        return _transition_event(pg, old_phase)

    pg.running, pg.succeeded, pg.failed = _pod_stats(pods)
    if len(pods) < pg.min_member:
        pg.phase = PodGroupPhase.PENDING
        return _transition_event(pg, old_phase)
    if pg.succeeded + pg.running < pg.min_member:
        pg.phase = PodGroupPhase.SCHEDULING
    if pg.succeeded + pg.running >= pg.min_member:
        pg.phase = PodGroupPhase.RUNNING
    if pg.failed != 0 and pg.failed + pg.running + pg.succeeded >= pg.min_member:
        pg.phase = PodGroupPhase.FAILED
    if pg.succeeded >= pg.min_member:
        pg.phase = PodGroupPhase.FINISHED
    return _transition_event(pg, old_phase)
