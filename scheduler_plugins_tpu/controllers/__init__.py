"""CRD status controllers — the companion controller binary's reconcilers
(/root/reference/cmd/controller, pkg/controllers)."""

from scheduler_plugins_tpu.controllers.elasticquota import (  # noqa: F401
    reconcile_elastic_quotas,
)
from scheduler_plugins_tpu.controllers.podgroup import (  # noqa: F401
    reconcile_pod_groups,
)
