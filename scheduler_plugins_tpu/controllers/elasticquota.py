"""ElasticQuota status reconciler.

Mirror of /root/reference/pkg/controllers/elasticquota_controller.go:50-109:
recompute `status.Used` as the sum of effective requests of RUNNING pods in
the quota's namespace, patch when changed, emit a Synced event.
"""

from __future__ import annotations

from scheduler_plugins_tpu.api.objects import PodPhase
from scheduler_plugins_tpu.api.resources import add_quantities
from scheduler_plugins_tpu.state.cluster import Cluster


def reconcile_elastic_quotas(cluster: Cluster) -> list[str]:
    """One reconcile pass over every ElasticQuota; returns emitted events.
    Single sweep over pods bucketed by namespace — O(pods + quotas)."""
    by_ns: dict[str, dict[str, int]] = {}
    for pod in cluster.pods.values():
        if pod.phase != PodPhase.RUNNING:
            continue
        by_ns[pod.namespace] = add_quantities(
            by_ns.get(pod.namespace, {}), pod.effective_request()
        )
    events = []
    for eq in cluster.quotas.values():
        used = by_ns.get(eq.namespace, {})
        if used != dict(eq.used):
            eq.used = used
            events.append(f"Normal Synced {eq.namespace}/{eq.name}")
    return events
