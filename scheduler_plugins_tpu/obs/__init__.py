"""Pod-level observability plane (the cross-cycle complement of utils.observability).

`obs.ledger` follows a POD across scheduling cycles — first-seen, queued,
backoff-held, gang-gated, nominated/reserved, bound-or-blamed — where every
earlier observability layer (tracer spans, flight recorder, quality gauges)
instruments one CYCLE. See docs/OBSERVABILITY.md §pod-lifecycle ledger.
"""

from . import ledger
from .ledger import LEDGER, Ledger, STAGES

__all__ = ["ledger", "LEDGER", "Ledger", "STAGES"]
