"""Compiled-cost observatory core (ISSUE 20): deterministic cost telemetry.

Every wall-clock perf number in this repo is hostage to a sick host and a
dead axon tunnel (all committed ``BENCH_r0*.json`` lines are CPU-backend,
and the perf sentry correctly quarantines them as degenerate). XLA's own
``cost_analysis()`` and ``memory_analysis()`` are pure functions of the
COMPILED program — the same ints on any machine, any load, any tunnel
state — so a cost delta between two commits has a ZERO noise floor. This
module is the one copy of that arithmetic, read by four consumers:

- ``tools/cost_observatory.py`` measures the full 24-program registry
  (the same one ``tools/tpu_lower.py`` / jaxpr_audit / kernel_audit
  share) and commits ``docs/cost_model.json``;
- ``tools/perf_sentry.py`` runs the cost arm: the deterministic second
  verdict that flags an algorithmic regression even on a host where the
  timing arm downgrades to ``degraded-host``;
- ``bench.py`` stamps every JSON line with the solve program's cost
  digest and a measured-vs-roofline calibration ratio;
- the daemon (``__main__.py``) and ``utils/flightrec.py`` stamp runtime
  device-memory watermarks and bundle cost provenance.

Hardware peaks live in ``parallel/vmem.py`` next to the VMEM budget (one
module owns all hardware numbers). The roofline is a step-time FLOOR:
``max(flops / peak_flops, bytes / hbm_bw)`` with the spec-sheet peaks —
valid evidence even while the tunnel is dead, and the sanity bound for
ROADMAP item 3's kernelized mega wave.
"""

from __future__ import annotations

import hashlib
import json
import os
from pathlib import Path

from scheduler_plugins_tpu.parallel.vmem import (
    HBM_BYTES_PER_S,
    PEAK_FLOPS_PER_S,
    VMEM_TARGET,
)

__all__ = [
    "COST_FIELDS",
    "MANIFEST_PATH",
    "compiled_cost",
    "roofline",
    "cost_digest",
    "manifest_digest",
    "load_manifest",
    "program_row",
    "budget_violations",
    "default_budgets",
    "device_memory_block",
    "stamp_device_memory",
]

#: repo-relative committed manifest (docs/cost_model.json)
MANIFEST_PATH = (
    Path(__file__).resolve().parent.parent.parent / "docs" / "cost_model.json"
)

#: the measured cost fields, in digest order — the cost SHAPE of a program.
#: `generated_code_size` is deliberately excluded: it tracks codegen
#: details (inlining luck, scheduling), not the algorithm.
COST_FIELDS = (
    "flops",
    "transcendentals",
    "bytes_accessed",
    "argument_bytes",
    "output_bytes",
    "temp_bytes",
    "peak_bytes",
)

#: budgeted subset of COST_FIELDS: the axes an algorithmic regression
#: moves (an accidental O(N*P) gather lands in flops+bytes, a
#: VMEM-spilling reshape in temp/peak bytes)
BUDGET_FIELDS = ("flops", "bytes_accessed", "peak_bytes")

#: review-gated budget headroom over a fresh measurement: wide enough to
#: absorb jax-version codegen drift, tight enough that a doubled
#: collective payload or a quadratic blow-up always breaches
BUDGET_HEADROOM = 1.5


def compiled_cost(fn, args, mesh=None) -> dict:
    """Static cost census of ``fn(*args)`` compiled on the CURRENT backend
    (the observatory runs it on CPU — deterministic per jax version).
    Returns ``{field: int}`` over ``COST_FIELDS``. ``peak_bytes`` is the
    conservative live-set bound argument+output+temp (XLA's CPU memory
    stats expose no tighter peak). Raises whatever lower/compile raises —
    the Mosaic-kernel programs are not CPU-compilable and the caller
    records them static-only."""
    from scheduler_plugins_tpu.parallel.mesh import ambient_mesh

    if mesh is not None:
        with ambient_mesh(mesh):
            compiled = fn.lower(*args).compile()
    else:
        compiled = fn.lower(*args).compile()
    ca = compiled.cost_analysis()
    if isinstance(ca, (list, tuple)):  # pre-0.5 jax returns [dict]
        ca = ca[0] if ca else {}
    ma = compiled.memory_analysis()
    row = {
        "flops": int(max(ca.get("flops", 0.0), 0.0)),
        "transcendentals": int(max(ca.get("transcendentals", 0.0), 0.0)),
        "bytes_accessed": int(max(ca.get("bytes accessed", 0.0), 0.0)),
        "argument_bytes": int(ma.argument_size_in_bytes),
        "output_bytes": int(ma.output_size_in_bytes),
        "temp_bytes": int(ma.temp_size_in_bytes),
    }
    row["peak_bytes"] = (
        row["argument_bytes"] + row["output_bytes"] + row["temp_bytes"]
    )
    return row


def roofline(
    flops: int, bytes_accessed: int, target: str | None = None
) -> dict:
    """TPU roofline projection for one program's static cost: predicted
    compute-vs-memory-bound verdict and the step-time floor in seconds.
    ``intensity`` is arithmetic intensity (flops/byte); the ``ridge``
    point is where the two roofs meet — below it the program is
    memory-bound on this generation. Pure arithmetic: the decision table
    in tests/test_cost_observatory.py pins it against hand-computed
    oracles."""
    target = target or VMEM_TARGET
    peak = PEAK_FLOPS_PER_S[target]
    bw = HBM_BYTES_PER_S[target]
    flops = max(int(flops), 0)
    bytes_accessed = max(int(bytes_accessed), 0)
    compute_s = flops / peak
    memory_s = bytes_accessed / bw
    ridge = peak / bw
    intensity = flops / bytes_accessed if bytes_accessed else float("inf")
    bound = "compute" if intensity >= ridge else "memory"
    return {
        "target": target,
        "intensity_flops_per_byte": round(intensity, 6)
        if intensity != float("inf") else None,
        "ridge_flops_per_byte": round(ridge, 6),
        "bound": bound,
        "compute_floor_us": round(compute_s * 1e6, 6),
        "memory_floor_us": round(memory_s * 1e6, 6),
        "step_floor_us": round(max(compute_s, memory_s) * 1e6, 6),
    }


def cost_digest(row: dict) -> str:
    """SHA-256 over the canonical cost shape of one program row.

    For CPU-compilable programs this is the COST_FIELDS vector; for the
    Mosaic-kernel programs (static-only rows) it falls back to the TPU
    StableHLO digest joined with the collective census — either way, two
    trees with the same digest have the same compiled cost shape, and an
    algorithmic change moves it. Digests are comparable only under one
    jax version (the manifest pins it, the tpu_lower discipline)."""
    basis: dict = {}
    if row.get(COST_FIELDS[0]) is not None:
        basis["cost"] = [int(row.get(f) or 0) for f in COST_FIELDS]
    if row.get("tpu"):
        basis["tpu_sha256"] = row["tpu"].get("sha256")
    if row.get("collectives"):
        basis["collectives"] = dict(sorted(row["collectives"].items()))
    text = json.dumps(basis, sort_keys=True, separators=(",", ":"))
    return hashlib.sha256(text.encode()).hexdigest()


def default_budgets(row: dict) -> dict:
    """Fresh review-gated budgets: BUDGET_HEADROOM over the measured
    value per budget field (ceil to int). Static-only rows (no CPU cost)
    get no budgets — their drift gate is the cost digest."""
    if row.get(BUDGET_FIELDS[0]) is None:
        return {}
    return {
        f: int(-(-int(row[f]) * BUDGET_HEADROOM // 1))
        for f in BUDGET_FIELDS
    }


def budget_violations(row: dict, budgets: dict | None) -> list[str]:
    """Budget-field values of ``row`` exceeding their committed budget.
    Empty budgets (static-only rows) never violate; a MISSING budget for
    a measured field is itself a violation — the gate must fail closed
    when a new cost axis ships unbudgeted."""
    if not budgets:
        return []
    out = []
    for f in BUDGET_FIELDS:
        measured = row.get(f)
        if measured is None:
            continue
        cap = budgets.get(f)
        if cap is None:
            out.append(f"{f}: measured {measured} has no committed budget")
        elif int(measured) > int(cap):
            out.append(f"{f}: measured {measured} exceeds budget {cap}")
    return out


def manifest_digest(manifest: dict) -> str:
    """Content digest of a cost manifest's program section (jax version
    included: cost shapes are only comparable under one pin). Stamped
    into flight-recorder bundles so `tools/replay.py info` can flag a
    bundle recorded under a different cost shape."""
    basis = {
        "jax": manifest.get("jax"),
        "programs": {
            name: row.get("cost_digest")
            for name, row in sorted(manifest.get("programs", {}).items())
        },
    }
    text = json.dumps(basis, sort_keys=True, separators=(",", ":"))
    return hashlib.sha256(text.encode()).hexdigest()


def load_manifest(path: str | os.PathLike | None = None) -> dict | None:
    """The committed docs/cost_model.json, or None when absent/unreadable
    (callers are null-safe: a missing manifest degrades bench columns to
    null and fails ONLY the explicit `make cost-audit-check` gate)."""
    p = Path(path) if path is not None else MANIFEST_PATH
    try:
        return json.loads(p.read_text())
    except (OSError, ValueError):
        return None


def program_row(name: str, manifest: dict | None = None) -> dict | None:
    """One program's committed cost row (manifest defaulting to the
    committed file), or None."""
    m = manifest if manifest is not None else load_manifest()
    if not m:
        return None
    return m.get("programs", {}).get(name)


# ---------------------------------------------------------------------------
# Runtime device-memory watermarks
# ---------------------------------------------------------------------------


def device_memory_block() -> dict:
    """JSON-ready device-memory snapshot for /healthz and the per-cycle
    gauges: per-device ``bytes_in_use`` / ``peak_bytes_in_use`` from the
    backend's allocator stats. CPU backends report no stats —
    ``available`` False with null totals, never an exception (the axon
    tunnel dying mid-call must not take a cycle down with it)."""
    per_device = []
    available = False
    backend = None
    try:
        import jax

        backend = jax.default_backend()
        for d in jax.local_devices():
            try:
                stats = d.memory_stats()
            except Exception:
                stats = None
            if not stats:
                continue
            available = True
            per_device.append({
                "id": d.id,
                "bytes_in_use": int(stats.get("bytes_in_use", 0)),
                "peak_bytes_in_use": int(
                    stats.get("peak_bytes_in_use", stats.get("bytes_in_use", 0))
                ),
            })
    except Exception:  # graft-lint: ignore[GL010] — telemetry probe on a possibly-dead backend: the watermark block must never take the tick down; `available: false` IS the recorded fault signal
        pass
    return {
        "backend": backend,
        "available": available,
        "bytes_in_use": sum(d["bytes_in_use"] for d in per_device)
        if per_device else None,
        "peak_bytes_in_use": sum(d["peak_bytes_in_use"] for d in per_device)
        if per_device else None,
        "devices": per_device,
    }


def stamp_device_memory(metrics=None) -> dict:
    """Per-cycle watermark stamp: read the allocator stats once and set
    the ``scheduler_device_bytes_in_use`` / ``..._peak_bytes_in_use``
    gauges (last write wins). Returns the /healthz memory block. One
    allocator read per cycle — far inside the established <= max(2%,
    jitter-floor) observability overhead bound (gated by
    tests/test_cost_observatory.py)."""
    block = device_memory_block()
    if metrics is None:
        from scheduler_plugins_tpu.utils import observability as obs

        metrics = obs.metrics
    if block["available"]:
        from scheduler_plugins_tpu.utils import observability as obs

        metrics.set_gauge(obs.DEVICE_BYTES_IN_USE, block["bytes_in_use"])
        metrics.set_gauge(
            obs.DEVICE_PEAK_BYTES, block["peak_bytes_in_use"]
        )
    return block
