"""Pod-lifecycle SLO ledger: cross-cycle per-pod latency decomposition.

The reference's vendored scheduler answers "how long did this pod wait,
and on what?" with the `e2e_scheduling_duration` / `pod_scheduling_attempts`
metric families (SURVEY.md §5; prometheus registration imported at
/root/reference/cmd/scheduler/main.go:23-24). Every observability layer
here so far instruments a CYCLE — this module follows a POD across cycles.

Design:

- **Append-only records, O(changed) per cycle.** The store mutators
  (`state.cluster`), the `run_cycle` stage functions, `GangPhase` parks,
  requeue-backoff charges and preemption nominations each push one
  transition when something HAPPENS to a pod; nothing ever scans the
  roster. Records retire to a bounded ring on bind/delete.

- **Telescoping stage accounting.** Each record keeps integer-nanosecond
  `stages` plus the stamp of its last transition; every transition closes
  the open interval (`stages[state] += t - last_ns; last_ns = t`), so
  `sum(stages) == retired_ns - first_seen_ns` holds EXACTLY, by
  construction, for every pod — the decomposition invariant
  `make ledger-smoke` and tests/test_ledger.py gate.

- **Engine-independent sequences.** Events carry `(cycle, lane, seq)`:
  the cycle that observed them, a lane (0 = ingest/solve-side, 1 = the
  bind/postbind stage, which `PipelinedCycle` runs on the flusher
  thread) and a per-(cycle, lane) counter. Wall stamps ride along but
  are excluded from `sequence()` — the serial and pipelined engines must
  produce IDENTICAL sequences on one input stream (the PR 11 bit-identity
  discipline extended to the observability plane). Failure blame lands as
  an IN-PLACE fill of the cycle's Unschedulable event (attribution may be
  deferred into the next overlap window; an append there would reorder).

- **Always cheap.** The global `LEDGER` is OFF by default; every feeding
  seam guards on `LEDGER.enabled` before doing any work. Enabled, the
  per-cycle cost is O(batch + transitions).

Everything is host-side: `time.monotonic_ns` never enters jit-traced code
(CLAUDE.md; lint rule GL008 is about traced programs, not this module).
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Optional

from ..api import events as ev
from ..utils import observability as obs

#: the fixed decomposition stages (docs/OBSERVABILITY.md): every retired
#: pod's e2e latency is partitioned into exactly these buckets
STAGES = (
    "queue_wait", "backoff_held", "gang_wait",
    "solve", "fence", "bind_flush",
)

#: wait-states a record can sit in between attempts (the first three
#: STAGES); in-attempt stages (solve/fence/bind_flush) are charged
#: arithmetically at the outcome visit and are never a resting state
_WAIT_STATES = frozenset(STAGES[:3])


class LedgerCycle:
    """Per-cycle ledger context: stamps + batch + the two lane counters.

    Created by `Ledger.cycle_open` (the `_cycle_open` stage function),
    carried on `CycleCtx.led`, and filled in by the stage functions as the
    cycle progresses. The bind stage may run on the pipelined engine's
    flusher thread — the stamps written here (pending/solve/fence) are
    all written by the main thread BEFORE the bind job is submitted, so
    the flusher only ever reads them.
    """

    __slots__ = (
        "cid", "now_ms", "batch", "t_open", "t_solve", "t_fence0",
        "t_fence1", "degraded", "solve_path", "_seq", "_lock",
    )

    def __init__(self, cid: int, now_ms: int, t_open: int):
        self.cid = cid
        self.now_ms = now_ms
        self.batch: frozenset = frozenset()
        self.t_open = t_open
        self.t_solve: Optional[int] = None
        self.t_fence0: Optional[int] = None
        self.t_fence1: Optional[int] = None
        self.degraded = False
        self.solve_path: Optional[str] = None
        self._seq = [0, 0]  # per-lane event counters
        self._lock = threading.Lock()

    def next_seq(self, lane: int) -> int:
        with self._lock:
            s = self._seq[lane]
            self._seq[lane] = s + 1
            return s

    def meta(self) -> dict:
        return {
            "cycle": self.cid,
            "now_ms": self.now_ms,
            "batch": len(self.batch),
            "degraded": self.degraded,
            "solve_path": self.solve_path,
        }


class PodRecord:
    """One pod's lifecycle: events + telescoping stage accounting."""

    __slots__ = (
        "uid", "priority", "gang", "gated", "first_ns", "first_cycle",
        "last_ns", "state", "stages", "events", "attempts", "outcome",
        "retired_ns",
    )

    def __init__(self, uid: str, priority: int, gang, t: int, cycle: int):
        self.uid = uid
        self.priority = priority
        self.gang = gang
        self.gated = False
        self.first_ns = t
        self.first_cycle = cycle
        self.last_ns = t
        self.state = "queue_wait"
        self.stages: dict[str, int] = {}
        # events: [cycle, lane, seq, kind, detail, t_ns]
        self.events: list[list] = []
        self.attempts = 0
        self.outcome: Optional[str] = None
        self.retired_ns: Optional[int] = None

    def e2e_ns(self) -> Optional[int]:
        if self.retired_ns is None:
            return None
        return self.retired_ns - self.first_ns

    def to_dict(self) -> dict:
        return {
            "uid": self.uid,
            "priority": self.priority,
            "gang": self.gang,
            "first_seen_ns": self.first_ns,
            "first_cycle": self.first_cycle,
            "state": self.state,
            "attempts": self.attempts,
            "outcome": self.outcome,
            "e2e_ms": (
                None if self.retired_ns is None
                else (self.retired_ns - self.first_ns) / 1e6
            ),
            "stages_ms": {k: v / 1e6 for k, v in self.stages.items()},
            "events": [
                {
                    "cycle": c, "lane": ln, "seq": s, "kind": k,
                    "detail": d, "t_ns": t,
                }
                for c, ln, s, k, d, t in self.events
            ],
        }


class Ledger:
    """The pod-lifecycle ledger + SLI engine. One global instance
    (`LEDGER`) serves the daemon; benches swap per-arm instances in via
    `use()` so interleaved arm-vs-arm runs never share records."""

    def __init__(self, retired_capacity: int = 4096, cycle_meta: int = 512):
        self.enabled = False
        self._lock = threading.RLock()
        self._records: dict[str, PodRecord] = {}
        self._retired: deque[PodRecord] = deque(maxlen=retired_capacity)
        self._cycle_meta: deque[dict] = deque(maxlen=cycle_meta)
        self._cycles = 0
        self._ambient_seq = 0
        self._scopes = threading.local()
        self._now = time.monotonic_ns
        self.pods_bound = 0
        self.pods_deleted = 0

    # -- lifecycle --------------------------------------------------------
    def start(self) -> "Ledger":
        self.enabled = True
        return self

    def stop(self) -> None:
        self.enabled = False

    def reset(self) -> None:
        with self._lock:
            self._records.clear()
            self._retired.clear()
            self._cycle_meta.clear()
            self._cycles = 0
            self._ambient_seq = 0
            self.pods_bound = 0
            self.pods_deleted = 0

    # -- cycle scopes -----------------------------------------------------
    # A scope pins (LedgerCycle, lane) to the CURRENT thread while a stage
    # function runs, so store-mutator hooks fired underneath it attribute
    # their events to the observing cycle — on whichever thread the
    # pipelined engine runs that stage. Outside any scope (daemon ingest,
    # bench churn between ticks) events attribute to the last opened
    # cycle on lane 0 with a global counter: both engines apply the same
    # stream at the same point, so ambient attribution matches too.

    def _stack(self) -> list:
        st = getattr(self._scopes, "stack", None)
        if st is None:
            st = self._scopes.stack = []
        return st

    def push_scope(self, led: Optional[LedgerCycle], lane: int) -> None:
        if led is not None:
            self._stack().append((led, lane))

    def pop_scope(self, led: Optional[LedgerCycle]) -> None:
        if led is not None:
            st = self._stack()
            if st:
                st.pop()

    def _coords(self) -> tuple:
        """(cycle, lane, seq) for an event appended right now."""
        st = getattr(self._scopes, "stack", None)
        if st:
            led, lane = st[-1]
            return led.cid, lane, led.next_seq(lane)
        with self._lock:
            s = self._ambient_seq
            self._ambient_seq = s + 1
            return self._cycles, 0, s

    def cycle_open(self, now_ms: int) -> Optional[LedgerCycle]:
        if not self.enabled:
            return None
        with self._lock:
            self._cycles += 1
            led = LedgerCycle(self._cycles, now_ms, self._now())
            self._cycle_meta.append(led.meta())
            return led

    def cycle_close(self, led: Optional[LedgerCycle]) -> None:
        """Refresh the cycle's meta entry (degraded/solve_path/batch are
        filled after `cycle_open` appended the initial snapshot)."""
        if led is None:
            return
        with self._lock:
            for i in range(len(self._cycle_meta) - 1, -1, -1):
                if self._cycle_meta[i]["cycle"] == led.cid:
                    self._cycle_meta[i] = led.meta()
                    break

    # -- internals --------------------------------------------------------
    def _append(self, rec: PodRecord, kind: str, detail: dict,
                t: int) -> None:
        assert kind in ev.LIFECYCLE_KINDS, kind
        c, lane, seq = self._coords()
        rec.events.append([c, lane, seq, kind, detail, t])

    def _charge(self, rec: PodRecord, t: int, stage: Optional[str] = None) -> None:
        """Close the open interval at stamp `t`, crediting the record's
        resting wait-state (or an explicit in-attempt stage)."""
        dt = t - rec.last_ns
        if dt:
            s = stage or rec.state
            rec.stages[s] = rec.stages.get(s, 0) + dt
        rec.last_ns = t

    def _charge_attempt(self, rec: PodRecord,
                        led: Optional[LedgerCycle], t: int) -> bool:
        """Stage-split one attempt using the observing cycle's stamps:
        wait-state up to solve dispatch, then solve / fence / bind-flush.
        Falls back to a plain wait-state charge when the pod was not in
        this cycle's batch (gang-phase binds, permit fan-out of pods
        reserved in earlier cycles, external binds)."""
        if (
            led is not None
            and rec.uid in led.batch
            and led.t_solve is not None
            and led.t_fence0 is not None
            and led.t_fence1 is not None
            and rec.last_ns <= led.t_solve
        ):
            self._charge(rec, led.t_solve)
            self._charge(rec, led.t_fence0, "solve")
            self._charge(rec, led.t_fence1, "fence")
            self._charge(rec, t, "bind_flush")
            rec.attempts += 1
            return True
        self._charge(rec, t)
        return False

    def _scope_cycle(self) -> Optional[LedgerCycle]:
        st = getattr(self._scopes, "stack", None)
        return st[-1][0] if st else None

    def _retire(self, rec: PodRecord, t: int, outcome: str) -> None:
        rec.outcome = outcome
        rec.retired_ns = t
        self._retired.append(rec)

    # -- feeding seams (store mutators + stage functions) -----------------
    def on_first_seen(self, pod) -> None:
        """`Cluster.add_pod` of a pending pod (node_name None)."""
        with self._lock:
            if pod.uid in self._records:
                return
            t = self._now()
            rec = PodRecord(
                pod.uid, pod.priority, pod.pod_group() or None, t,
                self._cycles,
            )
            if pod.scheduling_gated:
                rec.state = "gang_wait"
                rec.gated = True
            self._records[pod.uid] = rec
            self._append(rec, ev.LIFECYCLE_FIRST_SEEN, {
                "gated": bool(pod.scheduling_gated),
                "gang": rec.gang,
                "priority": pod.priority,
            }, t)

    def on_bind(self, uid: str, node: str) -> None:
        """`Cluster.bind`: close the lifecycle, feed the SLI engine."""
        with self._lock:
            rec = self._records.pop(uid, None)
            if rec is None:
                return
            t = self._now()
            led = self._scope_cycle()
            self._charge_attempt(rec, led, t)
            self._append(rec, ev.LIFECYCLE_BOUND, {"node": node}, t)
            self._retire(rec, t, "bound")
            self.pods_bound += 1
        # metrics feed outside the ledger lock (lock order: ledger ->
        # metrics would also be fine, but there is no reason to nest);
        # batched so the whole fan-out costs one metrics-lock round-trip
        feed = [
            (obs.E2E_SCHEDULING_MS, (t - rec.first_ns) / 1e6,
             (("priority", str(rec.priority)),)),
            (obs.POD_SCHEDULING_ATTEMPTS, float(max(rec.attempts, 1)), ()),
        ]
        feed.extend(
            (obs.POD_SCHEDULING_SLI_MS, ns / 1e6, (("stage", stage),))
            for stage, ns in rec.stages.items() if ns
        )
        obs.metrics.observe_batch(feed)

    def on_reserve(self, uid: str, node: str) -> None:
        """`Cluster.reserve` (Permit said Wait): the pod now waits on its
        gang's quorum — gang_wait until the fan-out bind or the release."""
        with self._lock:
            rec = self._records.get(uid)
            if rec is None:
                return
            t = self._now()
            self._charge_attempt(rec, self._scope_cycle(), t)
            rec.state = "gang_wait"
            self._append(rec, ev.LIFECYCLE_RESERVED, {"node": node}, t)

    def on_unschedulable(self, uid: str, attempt: int, window_ms: int,
                         gang: bool) -> None:
        """`Cluster.mark_unschedulable`'s charged branch: one backoff
        attempt. `window_ms` is the exact deterministic PR 9 window
        (min(initial·2^(n-1), max) scaled by the blake2b jitter) so the
        decision-table tests compare recorded windows, not wall clocks."""
        with self._lock:
            rec = self._records.get(uid)
            if rec is None:
                return
            t = self._now()
            self._charge_attempt(rec, self._scope_cycle(), t)
            rec.state = "gang_wait" if gang else "backoff_held"
            self._append(rec, ev.LIFECYCLE_UNSCHEDULABLE, {
                "attempt": attempt, "window_ms": window_ms, "by": None,
            }, t)

    def set_blame(self, uid: str, cid: Optional[int], plugin: str) -> None:
        """Fill `failed_by` blame into the cycle's Unschedulable event
        IN PLACE (never an append): attribution may run in the next
        tick's overlap window, and an appended event there would order
        differently between the serial and pipelined engines."""
        with self._lock:
            rec = self._records.get(uid)
            if rec is None:
                for r in reversed(self._retired):
                    if r.uid == uid:
                        rec = r
                        break
                if rec is None:
                    return
            for evt in reversed(rec.events):
                if evt[3] == ev.LIFECYCLE_UNSCHEDULABLE and (
                    cid is None or evt[0] == cid
                ):
                    evt[4]["by"] = plugin
                    return

    def on_wait(self, uid: str, state: str) -> None:
        """Requeue-gate classification (`_requeue_eligible`): transition
        the resting wait-state — at most one event per park episode
        (backoff expired -> event-waiting), never one per cycle. Gang
        parks keep their gang_wait label through backoff expiry."""
        with self._lock:
            rec = self._records.get(uid)
            if rec is None or rec.state == state:
                return
            if state == "queue_wait" and rec.state == "gang_wait":
                return
            t = self._now()
            self._charge(rec, t)
            rec.state = state
            self._append(rec, ev.LIFECYCLE_WAIT, {"state": state}, t)

    def on_nomination(self, uid: str, node: Optional[str]) -> None:
        """Preemption nomination set/clear (`_run_preemption`). A
        nominated pod bypasses backoff (the requeue gate's first check),
        so its resting state returns to queue_wait."""
        with self._lock:
            rec = self._records.get(uid)
            if rec is None:
                return
            t = self._now()
            if node is not None:
                self._charge(rec, t)
                rec.state = "queue_wait"
                self._append(
                    rec, ev.LIFECYCLE_NOMINATED, {"node": node}, t
                )
            else:
                self._append(rec, ev.LIFECYCLE_NOMINATION_CLEARED, {}, t)

    def on_gate_flip(self, uid: str, gated: bool) -> None:
        """`Cluster.reindex_pod` — the supported seam for in-place
        scheduling-gate flips (gang ungating). Re-index calls for other
        reasons (reservation releases) are no-ops: only an actual flip
        of the gate transitions the record."""
        with self._lock:
            rec = self._records.get(uid)
            if rec is None or rec.gated == gated:
                return
            t = self._now()
            self._charge(rec, t)
            rec.gated = gated
            rec.state = "gang_wait" if gated else "queue_wait"
            self._append(rec, ev.LIFECYCLE_GATE, {"gated": gated}, t)

    def on_terminating(self, uid: str) -> None:
        with self._lock:
            rec = self._records.get(uid)
            if rec is None:
                return
            self._append(rec, ev.LIFECYCLE_TERMINATING, {}, self._now())

    def on_delete(self, uid: str) -> None:
        """`Cluster.remove_pod` of a still-pending pod: retire without
        feeding the scheduled-pod SLIs (upstream's e2e family only
        observes pods that actually scheduled)."""
        with self._lock:
            rec = self._records.pop(uid, None)
            if rec is None:
                return
            t = self._now()
            self._charge(rec, t)
            self._append(rec, ev.LIFECYCLE_DELETED, {}, t)
            self._retire(rec, t, "deleted")
            self.pods_deleted += 1

    # -- reads ------------------------------------------------------------
    def timeline(self, uid: str) -> Optional[dict]:
        """One pod's full story (live or retired) — the daemon's
        `GET /pods/{uid}/timeline` and `tools/replay.py timeline`."""
        with self._lock:
            rec = self._records.get(uid)
            if rec is None:
                for r in reversed(self._retired):
                    if r.uid == uid:
                        rec = r
                        break
            if rec is None:
                return None
            out = rec.to_dict()
            out["cycles"] = [
                m for m in self._cycle_meta
                if rec.first_cycle <= m["cycle"]
                and (rec.retired_ns is None
                     or not rec.events
                     or m["cycle"] <= rec.events[-1][0])
            ]
            return out

    def sequence(self) -> list[tuple]:
        """The engine-comparable event sequence: (cycle, lane, seq, uid,
        kind, stable-detail) sorted — stamps excluded. Serial `run_cycle`
        and `PipelinedCycle` must produce EQUAL sequences on one stream."""
        with self._lock:
            rows = []
            for rec in list(self._retired) + list(self._records.values()):
                for c, lane, seq, kind, detail, _t in rec.events:
                    rows.append((
                        c, lane, seq, rec.uid, kind,
                        tuple(sorted(
                            (k, v) for k, v in detail.items()
                        )),
                    ))
            rows.sort()
            return rows

    def decomposition_errors(self) -> list[tuple]:
        """(uid, sum(stages), e2e) for every retired record where the
        telescoping invariant does NOT hold — always empty by
        construction; gated by tests and `make ledger-smoke`."""
        with self._lock:
            bad = []
            for rec in self._retired:
                total = sum(rec.stages.values())
                e2e = rec.e2e_ns()
                if e2e is not None and total != e2e:
                    bad.append((rec.uid, total, e2e))
            return bad

    def sli_summary(self) -> dict:
        """Exact percentiles over the retired ring — the `/healthz` SLI
        block and the bench lines' `sli` block. Histogram-family metrics
        (bucketed, prometheus) are fed at retirement by `on_bind`."""
        with self._lock:
            bound = [r for r in self._retired if r.outcome == "bound"]
            live = len(self._records)
            pods_bound, pods_deleted = self.pods_bound, self.pods_deleted
        out = {
            "pods_bound": pods_bound,
            "pods_deleted": pods_deleted,
            "pods_pending": live,
        }
        if not bound:
            return out
        e2e = sorted(r.e2e_ns() / 1e6 for r in bound)

        def pct(xs, q):
            return xs[min(len(xs) - 1, int(q * len(xs)))]

        out["e2e_ms"] = {
            "p50": pct(e2e, 0.50), "p90": pct(e2e, 0.90),
            "p99": pct(e2e, 0.99), "max": e2e[-1], "n": len(e2e),
        }
        out["attempts_mean"] = (
            sum(max(r.attempts, 1) for r in bound) / len(bound)
        )
        stage_ms = {s: 0.0 for s in STAGES}
        for r in bound:
            for s, ns in r.stages.items():
                stage_ms[s] = stage_ms.get(s, 0.0) + ns / 1e6
        out["stage_ms"] = stage_ms
        prios: dict[str, list] = {}
        for r in bound:
            prios.setdefault(str(r.priority), []).append(r.e2e_ns() / 1e6)
        out["by_priority"] = {
            p: {
                "n": len(xs),
                "p50": pct(sorted(xs), 0.50),
                "p99": pct(sorted(xs), 0.99),
            }
            for p, xs in prios.items()
        }
        return out

    def export(self) -> dict:
        """Full dump (bounded by the ring) — the flight-recorder bundle
        segment `tools/replay.py timeline` reconstructs stories from."""
        with self._lock:
            out = {
                "version": 1,
                "cycles": list(self._cycle_meta),
                "retired": [r.to_dict() for r in self._retired],
                "live": [r.to_dict() for r in self._records.values()],
            }
        out["sli"] = self.sli_summary()
        return out


#: the process-global ledger (daemon + tools). Benches swap per-arm
#: instances in via `use()` so interleaved arms never share records.
LEDGER = Ledger()


def use(ledger: Ledger) -> Ledger:
    """Install `ledger` as the global feeding target; returns the
    previous one (callers restore it when their arm finishes)."""
    global LEDGER
    prev, LEDGER = LEDGER, ledger
    return prev
