"""Runtime resilience: fault injection, solve watchdog, degraded-mode
failover, and the host-side parity solve (docs/ROBUSTNESS.md).

- `resilience.faults` — seeded deterministic fault plans fired at named
  sites (zero overhead when no plan is installed).
- `resilience.watchdog` — `SolveWatchdog` (deadline + seeded-jitter
  retries in a worker thread) and `Resilience` (the fast/degraded state
  machine `framework.cycle.run_cycle(resilience=...)` consumes).
- `resilience.hostsolve` — the numpy sequential parity solve degraded
  mode serves from, bit-identical to `Scheduler.solve` on the supported
  profile surface.
"""

from scheduler_plugins_tpu.resilience import faults
from scheduler_plugins_tpu.resilience.hostsolve import (
    host_sequential_solve,
    supports as supports_host_solve,
)
from scheduler_plugins_tpu.resilience.watchdog import (
    BackendUnavailable,
    GarbageOutput,
    Resilience,
    SolveWatchdog,
    call_with_deadline,
    solve_output_anomaly,
)

__all__ = [
    "faults",
    "host_sequential_solve",
    "supports_host_solve",
    "BackendUnavailable",
    "GarbageOutput",
    "Resilience",
    "SolveWatchdog",
    "call_with_deadline",
    "solve_output_anomaly",
]
