"""Host-side (numpy) sequential parity solve — the failover target.

When the device backend is gone (the axon tunnel's multi-hour outages,
CLAUDE.md), retrying the jitted solve just hangs again: the only way the
cycle loop keeps serving is a solve that never touches the backend. This
module is that path for the profiles it supports: a pure-numpy mirror of
`framework.runtime._solve_step`'s scan body — PreFilter gates, built-in
fit against the carried free capacity, weighted min-max-normalized
scoring, argmax with the lowest-index tie-break, capacity commit — in
the same int64 reference units with the same Go integer division, so
its placements are bit-identical to the sequential parity path by
construction (gated by tests/test_resilience.py::TestHostSolveParity).

Scope: profiles whose every plugin is Score-only with a host twin
(`NodeResourcesAllocatable` — the serving profile) on snapshots without
side tables (no gangs/quota/NUMA/network/scheduling/nominees). That is
exactly the surface `serving.engine.ServeEngine.compatible` serves, so
degraded-mode serving keeps the resident-state workload alive end to
end. `supports()` gates; unsupported profiles raise
`watchdog.BackendUnavailable` to the caller instead of guessing.
"""

from __future__ import annotations

import numpy as np

from scheduler_plugins_tpu.ops import MAX_NODE_SCORE, MIN_NODE_SCORE, PODS_I


def _go_div_np(a, b):
    """Numpy twin of `utils.intmath.go_div` (trunc-toward-zero, b > 0) —
    the floor+remainder-correction form, never abs()."""
    a = np.asarray(a)
    q = a // b
    r = a - q * b
    return np.where((a < 0) & (r != 0), q + 1, q).astype(a.dtype)


def supports(scheduler, snap) -> bool:
    """True when the host mirror covers this (profile, snapshot): every
    plugin carries the `host_static_scores` twin and no side-table
    subsystem (which would need carries the mirror does not model) is
    present."""
    from scheduler_plugins_tpu.plugins.noderesources import (
        NodeResourcesAllocatable,
    )

    if not all(
        isinstance(p, NodeResourcesAllocatable)
        for p in scheduler.profile.plugins
    ):
        return False
    return (
        snap.gangs is None
        and snap.quota is None
        and snap.numa is None
        and snap.network is None
        and snap.scheduling is None
        and snap.nominees is None
    )


def host_sequential_solve(scheduler, snap):
    """(assignment, admitted, wait, failed_plugin) numpy arrays for the
    supported profile surface — the exact outputs `Scheduler.solve`
    would produce (tests/test_resilience.py holds the two bit-equal).
    Callers must gate on `supports()` first."""
    alloc = np.asarray(snap.nodes.alloc)
    requested = np.asarray(snap.nodes.requested)
    node_mask = np.asarray(snap.nodes.mask)
    req = np.asarray(snap.pods.req)
    pod_mask = np.asarray(snap.pods.mask)
    gated = np.asarray(snap.pods.gated)
    P, N = req.shape[0], alloc.shape[0]

    free = alloc - requested  # the ops.fit.free_capacity rule
    # static per-node raw scores, one row per plugin (allocatable scores
    # rate the node, never the pod — resource_allocation.go:49-76)
    plugin_rows = []
    for plugin in scheduler.profile.plugins:
        weights = np.asarray(plugin.aux(), np.int64)
        weight_sum = max(int(weights.sum()), 1)
        raw = _go_div_np(
            (plugin.mode_sign * alloc * weights[None, :]).sum(axis=-1),
            weight_sum,
        )
        plugin_rows.append((int(plugin.weight), raw))

    assignment = np.full(P, -1, np.int32)
    admitted = np.zeros(P, bool)
    failed = np.zeros(P, np.int32)
    span = MAX_NODE_SCORE - MIN_NODE_SCORE
    for p in range(P):
        ok0 = bool(pod_mask[p]) and not bool(gated[p])
        admitted[p] = ok0
        demand = req[p].copy()
        demand[PODS_I] = 1
        feasible = np.all(demand[None, :] <= free, axis=-1) & node_mask
        feasible &= ok0
        if not feasible.any():
            # same encoding as runtime._encode_fail's sequential fallback:
            # every failure on this profile surface decodes to the
            # built-in fit (code 0); placed pods carry -1
            failed[p] = 0
            continue
        total = np.zeros(N, np.int64)
        for weight, raw in plugin_rows:
            lo = raw[feasible].min()
            hi = raw[feasible].max()
            rng = hi - lo
            if rng == 0:
                col = np.full(N, MIN_NODE_SCORE, np.int64)
            else:
                # operands non-negative: `//` matches Go int division
                col = (raw - lo) * span // rng + MIN_NODE_SCORE
            total += weight * np.where(feasible, col, 0)
        masked = np.where(feasible, total, np.int64(-(2 ** 62)))
        choice = int(np.argmax(masked))  # first max == lowest index
        assignment[p] = choice
        failed[p] = -1
        free[choice] -= demand
    wait = np.zeros(P, bool)  # no gangs on the supported surface
    return assignment, admitted, wait, failed
