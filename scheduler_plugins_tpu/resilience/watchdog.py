"""Solve watchdog + degraded-mode failover state machine.

Every device solve the cycle loop dispatches completes through a
host-transfer fence (`np.asarray`, never `block_until_ready` — CLAUDE.md)
**in a worker thread** with a deadline: a hung backend (the axon tunnel's
signature failure is blocking forever at 0% CPU) times out instead of
stalling the cycle loop. On timeout, device error, or garbage output the
watchdog retries with seeded-jitter exponential backoff; when the budget
is exhausted it raises `BackendUnavailable`, and `Resilience` fails over
to the host-side numpy parity solve (`resilience.hostsolve` —
bit-faithful by construction) and marks the process degraded
(`scheduler_degraded` gauge, `CycleReport.degraded`, daemon `/healthz`).
While degraded, periodic probation probes re-try the device path and
restore it the moment the backend answers again.

Threading note: a thread stuck in a hung backend call cannot be killed —
on timeout the watchdog ABANDONS its worker (daemon thread; the eventual
result is discarded, jitted solves are side-effect free) and builds a
fresh one for the next attempt. Abandoned workers are counted
(`scheduler_solve_workers_abandoned_total`) so a flapping backend is
visible, and bounded in practice by the backoff schedule.
"""

from __future__ import annotations

import os
import queue
import threading
import time
from typing import Optional

import numpy as np

from scheduler_plugins_tpu.framework.runtime import solve_output_anomaly
from scheduler_plugins_tpu.resilience import faults, hostsolve
from scheduler_plugins_tpu.utils import observability as obs


class BackendUnavailable(RuntimeError):
    """The device backend failed past the watchdog's retry budget (or no
    host fallback exists for the profile). `reason` is the structured
    classification ("timeout (2.0s)", "device-error: XlaRuntimeError",
    "garbage-output: ...")."""

    def __init__(self, reason: str):
        super().__init__(reason)
        self.reason = reason


class GarbageOutput(RuntimeError):
    """A solve returned, but its outputs fail the contract (out-of-range
    node indices, NaN, shape mismatch) — treated exactly like a device
    error: a desynced tunnel produces answers shaped like this."""




def call_with_deadline(fn, deadline_s: float, label: str = "call"):
    """Run `fn()` in a fresh daemon worker with a deadline. Raises
    `BackendUnavailable` on timeout (the worker is abandoned — it cannot
    be killed while stuck inside a backend call). The standalone helper
    behind `parallel.pipeline.run_chunk_pipeline(fetch_deadline_s=...)`;
    the cycle loop's stateful retry/failover logic lives in
    `SolveWatchdog`/`Resilience` below."""
    box: dict = {}
    done = threading.Event()

    def worker():
        try:
            box["value"] = fn()
        except BaseException as exc:  # noqa: BLE001 - re-raised below
            box["error"] = exc
        finally:
            done.set()

    t = threading.Thread(target=worker, daemon=True, name=f"wd-{label}")
    t.start()
    if not done.wait(deadline_s):
        obs.metrics.inc(obs.SOLVE_WORKERS_ABANDONED)
        raise BackendUnavailable(f"timeout ({deadline_s}s) in {label}")
    if "error" in box:
        raise box["error"]
    return box["value"]


class _Worker:
    """Persistent single DAEMON worker thread with a job queue.

    Deliberately NOT a `ThreadPoolExecutor`: its workers are non-daemon
    and joined at interpreter exit (`concurrent.futures.thread`'s atexit
    hook), so a worker stuck inside a hung backend call would block
    process shutdown forever — defeating the SIGTERM-exits-0 guarantee
    this subsystem exists to protect. A daemon thread dies with the
    process; an abandoned one idles harmlessly on its own queue."""

    def __init__(self):
        self._jobs: queue.SimpleQueue = queue.SimpleQueue()
        self._thread = threading.Thread(
            target=self._loop, daemon=True, name="solve-watchdog"
        )
        self._thread.start()

    def _loop(self):
        while True:
            fn, box, done = self._jobs.get()
            try:
                box["value"] = fn()
            except BaseException as exc:  # noqa: BLE001 - re-raised by caller
                box["error"] = exc
            finally:
                done.set()

    def submit(self, fn):
        box: dict = {}
        done = threading.Event()
        self._jobs.put((fn, box, done))
        return box, done


class SolveWatchdog:
    """Deadline + seeded-jitter retry policy around one callable.

    `timeout_s` defaults from SPT_SOLVE_TIMEOUT_S (30s — generous enough
    for a cold first compile on a healthy tunnel, small enough that a
    dead one is diagnosed within one cycle budget). Backoff mirrors the
    requeue schedule: base * 2^(attempt-1), capped, with a
    deterministic-per-seed jitter multiplier in [0.5, 1.0] so colliding
    retries from many processes spread out while a given seed replays
    exactly."""

    def __init__(self, timeout_s: Optional[float] = None,
                 max_attempts: int = 3, backoff_base_s: float = 0.05,
                 backoff_cap_s: float = 2.0, seed: int = 0):
        if timeout_s is None:
            timeout_s = float(os.environ.get("SPT_SOLVE_TIMEOUT_S", 30.0))
        self.timeout_s = timeout_s
        self.max_attempts = max(1, int(max_attempts))
        self.backoff_base_s = backoff_base_s
        self.backoff_cap_s = backoff_cap_s
        self._rng = np.random.default_rng(seed)
        self._worker: Optional[_Worker] = None
        self.abandoned = 0
        self.last_reason: Optional[str] = None

    def backoff_s(self, attempt: int) -> float:
        base = min(
            self.backoff_base_s * (2 ** (attempt - 1)), self.backoff_cap_s
        )
        return base * (0.5 + 0.5 * float(self._rng.random()))

    def _abandon(self) -> None:
        # the worker is stuck inside a backend call: it cannot be
        # interrupted, only orphaned (daemon thread, result discarded;
        # it can never block process exit)
        self._worker = None
        self.abandoned += 1
        obs.metrics.inc(obs.SOLVE_WORKERS_ABANDONED)

    def call_once(self, fn, label: str = "solve"):
        """One deadlined attempt; classifies failures into
        `BackendUnavailable` (timeout) or re-raises the device error."""
        if self._worker is None:
            self._worker = _Worker()
        box, done = self._worker.submit(fn)
        if not done.wait(self.timeout_s):
            self._abandon()
            raise BackendUnavailable(
                f"timeout ({self.timeout_s}s) in {label}"
            ) from None
        if "error" in box:
            raise box["error"]
        return box["value"]

    def run(self, fn, label: str = "solve", attempts: Optional[int] = None,
            on_fault=None):
        """Retry loop: deadline + backoff, then `BackendUnavailable` with
        the LAST failure's classification. `on_fault(reason)` fires on
        every failed attempt (the anti-entropy force-verify hook)."""
        attempts = attempts or self.max_attempts
        reason = "unknown"
        for attempt in range(1, attempts + 1):
            try:
                return self.call_once(fn, label=label)
            except BackendUnavailable as exc:
                reason = exc.reason
            except GarbageOutput as exc:
                reason = f"garbage-output: {exc}"
            except Exception as exc:  # device/runtime error from the solve
                reason = f"device-error: {type(exc).__name__}: {exc}"
            self.last_reason = reason
            obs.metrics.inc(obs.SOLVE_RETRIES, label=label)
            if on_fault is not None:
                on_fault(reason)
            if attempt < attempts:
                time.sleep(self.backoff_s(attempt))
        raise BackendUnavailable(reason)


class Resilience:
    """The cycle loop's degraded-mode state machine (one per scheduler
    process). `framework.cycle.run_cycle(resilience=...)` routes every
    solve through `solve_cycle`:

    - **fast** mode: device solve under the watchdog; on exhausted
      retries fail over to the host parity solve and go degraded.
    - **degraded** mode: host solve immediately (no device dispatch);
      every `probe_every` cycles a probation probe re-tries the device
      path (single attempt) and restores fast mode on success — the
      probe IS that cycle's solve, so recovery wastes no work.

    The optional `engine` (a `serving.engine.ServeEngine`) is notified
    on every fault (`note_fault`), forcing an anti-entropy verify at the
    next refresh — any fault is treated as potential state corruption.
    """

    def __init__(self, watchdog: Optional[SolveWatchdog] = None,
                 probe_every: int = 2, engine=None):
        self.watchdog = watchdog or SolveWatchdog()
        self.probe_every = max(1, int(probe_every))
        self.engine = engine
        self.mode = "fast"
        self.degraded_reason: Optional[str] = None
        self.cycle = 0
        self.degraded_cycles = 0
        self.failovers = 0
        #: (degraded_at_cycle, restored_at_cycle) pairs — recovery time
        #: in cycles is the difference, the chaos gate's bound
        self.recoveries: list = []
        self._degraded_at: Optional[int] = None
        obs.metrics.set_gauge(obs.DEGRADED, 0.0)

    @property
    def degraded(self) -> bool:
        return self.mode == "degraded"

    @property
    def degraded_at(self):
        """Cycle index of the active degradation (None while fast) —
        the chaos harness closes the recovery window from this."""
        return self._degraded_at

    # -- transitions ----------------------------------------------------
    def _on_fault(self, reason: str) -> None:
        if self.engine is not None:
            self.engine.note_fault(reason)

    def _enter_degraded(self, reason: str) -> None:
        self.mode = "degraded"
        self.degraded_reason = reason
        self._degraded_at = self.cycle
        self.failovers += 1
        obs.metrics.inc(obs.SOLVE_FAILOVERS)
        obs.metrics.set_gauge(obs.DEGRADED, 1.0)
        obs.logger.warning(
            "solve backend degraded (%s): failing over to the host "
            "sequential parity path", reason,
        )

    def _restore_fast(self) -> None:
        self.mode = "fast"
        self.recoveries.append((self._degraded_at, self.cycle))
        self._degraded_at = None
        self.degraded_reason = None
        obs.metrics.set_gauge(obs.DEGRADED, 0.0)
        obs.logger.info("solve backend recovered: fast path restored")

    # -- the per-cycle entry --------------------------------------------
    def solve_cycle(self, scheduler, snap, stream_chunk=None):
        """(assignment, admitted, wait, failed_plugin, path) — host numpy
        arrays, completion already forced. `path` is "device" or "host"."""
        self.cycle += 1
        if self.mode == "degraded":
            # anchored on the degradation cycle, not absolute parity: the
            # first probe fires exactly probe_every cycles after failover
            probe_due = (
                (self.cycle - self._degraded_at) % self.probe_every == 0
            )
            if probe_due:
                obs.metrics.inc(obs.PROBATION_PROBES)
                try:
                    out = self.watchdog.run(
                        lambda: self._device_call(
                            scheduler, snap, stream_chunk, probe=True
                        ),
                        label="probe", attempts=1, on_fault=self._on_fault,
                    )
                    self._restore_fast()
                    return out + ("device",)
                except BackendUnavailable:
                    pass  # still sick: stay degraded, serve from host
            self.degraded_cycles += 1
            return self._host_call(scheduler, snap) + ("host",)
        try:
            out = self.watchdog.run(
                lambda: self._device_call(scheduler, snap, stream_chunk),
                label="solve", on_fault=self._on_fault,
            )
            return out + ("device",)
        except BackendUnavailable as exc:
            self._enter_degraded(exc.reason)
            if not hostsolve.supports(scheduler, snap):
                # no bit-faithful fallback for this profile: surface the
                # outage to the caller (the daemon parks the cycle and
                # stays degraded) rather than inventing placements
                raise
            self.degraded_cycles += 1
            return self._host_call(scheduler, snap) + ("host",)

    # -- the two solve bodies -------------------------------------------
    def _device_call(self, scheduler, snap, stream_chunk, probe=False):
        """Runs IN THE WORKER THREAD: dispatch + host-transfer completion
        fence + output validation, with the SOLVE_DISPATCH/PROBE fault
        sites applied around it."""
        spec = None
        if faults.ACTIVE is not None:
            # a probation probe IS a solve dispatch (SOLVE_DISPATCH faults
            # hit it too); the PROBE site exists on top so tests can keep
            # the backend sick across probes specifically
            spec = faults.ACTIVE.fire(faults.SOLVE_DISPATCH)
            if spec is None and probe:
                spec = faults.ACTIVE.fire(faults.PROBE)
            if spec is not None and spec.kind == "hang":
                time.sleep(spec.seconds)
            elif spec is not None and spec.kind == "device-error":
                raise RuntimeError("injected device error")
        failed_np = None
        result = None
        if stream_chunk:
            from scheduler_plugins_tpu.parallel.pipeline import (
                streamed_profile_solve,
            )

            result = streamed_profile_solve(
                scheduler, snap, chunk=stream_chunk,
                # finer-grained hang detection INSIDE the chunk loop: the
                # whole-solve deadline above still bounds the worst case
                fetch_deadline_s=self.watchdog.timeout_s,
            )
        if result is not None:
            assignment, admitted, wait = result
        else:
            solved = scheduler.solve(snap)
            assignment, admitted, wait = (
                solved.assignment, solved.admitted, solved.wait
            )
            if solved.failed_plugin is not None:
                failed_np = np.asarray(solved.failed_plugin)
        # host transfers force completion inside the deadline window
        # (block_until_ready can return early through the tunneled
        # backend — CLAUDE.md)
        assignment = np.asarray(assignment)
        admitted = np.asarray(admitted)
        wait = np.asarray(wait)
        if spec is not None and spec.kind == "garbage":
            # a desynced tunnel answers with plausible-length junk
            assignment = assignment.copy()
            rng = faults.ACTIVE.rng
            assignment[: max(1, assignment.size // 8)] = rng.integers(
                snap.num_nodes, snap.num_nodes + 1000,
                size=max(1, assignment.size // 8),
            )
        anomaly = solve_output_anomaly(
            assignment, admitted, wait, snap.num_nodes
        )
        if anomaly is not None:
            raise GarbageOutput(anomaly)
        return assignment, admitted, wait, failed_np

    def _host_call(self, scheduler, snap):
        if not hostsolve.supports(scheduler, snap):
            raise BackendUnavailable(
                f"degraded ({self.degraded_reason}) and no host fallback "
                f"for profile {scheduler.profile.name!r}"
            )
        with obs.tracer.span("HostSolve", tid="cycle",
                             pending=snap.num_pods):
            return hostsolve.host_sequential_solve(scheduler, snap)
