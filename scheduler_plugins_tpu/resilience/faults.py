"""Deterministic fault injection: seeded plans fired at named sites.

The chaos harness (`bench.py --config 9` / `make chaos-smoke`) and the
resilience tests drive the runtime through the SAME code paths production
faults would take — a hung device solve, a device error, garbage solve
output, dropped/duplicated/corrupted `DeltaSink` events, a stalled feed,
a crash mid-cycle — by installing a `FaultPlan` into this module's
process-global registry. Each instrumented site calls `fire(SITE)`
(or reads `ACTIVE` directly) and interprets the returned `FaultSpec`.

Zero overhead when off: every site's fast path is a single module-global
`is None` check — no dict lookups, no rng draws, no allocation. The
production binaries never install a plan; only the chaos harness and
tests do.

Determinism: a plan is constructed from a seed alone
(`FaultPlan.standard`), every payload draw comes from a
`np.random.default_rng` stream owned by the plan, and sites fire in the
deterministic host-side cycle order — so two runs with the same seed
inject byte-identical fault sequences (the chaos gate's bit-identity
claim depends on this).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

import numpy as np

# -- site names (the instrumented seams) ------------------------------------

#: device solve dispatch (`resilience.watchdog.Resilience._device_call`):
#: kinds "hang" (worker sleeps past the deadline), "device-error"
#: (RuntimeError from the dispatch), "garbage" (solve output corrupted —
#: out-of-range node indices, the shape a desynced tunnel produces)
SOLVE_DISPATCH = "solve.dispatch"
#: delta-sink event push (`serving.deltas.DeltaSink._push`): kinds
#: "drop", "dup", "corrupt" (assign flipped to unassign — a sign error
#: only the anti-entropy digest can see; the Cluster store is untouched)
DELTA_EVENT = "delta.event"
#: harness-level feed stall before a cycle: kind "stall" with `seconds`
FEED_STALL = "feed.stall"
#: crash after the Bind/Permit phase of `framework.cycle.run_cycle`
#: (bindings landed, process state about to die): kind "crash"
CRASH_POST_BIND = "cycle.post_bind"
#: probation probe (`Resilience._probe`): kind "device-error" keeps the
#: backend looking sick so degraded mode persists across cycles
PROBE = "solve.probe"
#: shadow-lane sweep (`tuning.shadow.ShadowTuner._sweep_job`): kinds
#: "hang" (the sweep worker sleeps past the tuner deadline — the lane
#: must degrade to "no tuning", never stall or corrupt a tick),
#: "garbage" (every non-incumbent candidate's replayed placements are
#: corrupted to out-of-range node indices — the numpy replay oracles
#: must disqualify all of them, so nothing garbage can reach the live
#: weights)
TUNE_SWEEP = "tune.sweep"
#: live promotion application (`ShadowTuner.begin_cycle`): kind "crash"
#: (the apply raises mid-promotion — the tuner must keep the incumbent
#: weights live, count the fault, and recover or disable itself)
TUNE_PROMOTE = "tune.promote"

ALL_SITES = (SOLVE_DISPATCH, DELTA_EVENT, FEED_STALL, CRASH_POST_BIND, PROBE,
             TUNE_SWEEP, TUNE_PROMOTE)


class CrashInjected(RuntimeError):
    """Raised by the CRASH_POST_BIND site: simulates process death after
    bindings were committed. Carries the partially-built `CycleReport` so
    the harness can account the crashed cycle's (real, landed) binds."""

    def __init__(self, report=None):
        super().__init__("injected crash (cycle.post_bind)")
        self.report = report


@dataclass
class FaultSpec:
    """One scheduled fault: `kind` is site-specific (see site docs);
    `repeat` is how many consecutive fires at the site consume this spec
    (a hang that outlives the watchdog's retry budget needs repeat >
    max_attempts); `seconds` parameterizes hang/stall kinds."""

    site: str
    cycle: int
    kind: str
    repeat: int = 1
    seconds: float = 0.0
    #: sticky specs roll forward: they stay pending from their scheduled
    #: cycle until the site actually fires (delta faults need a sink
    #: event to pass through — a cycle with no pushes must not silently
    #: void the fault)
    sticky: bool = False
    #: filled by the registry as the spec fires (observability)
    fired: int = 0


@dataclass
class FaultPlan:
    """Seeded schedule of `FaultSpec`s, advanced cycle-by-cycle by the
    harness (`begin_cycle`) and consumed by the instrumented sites
    (`fire`)."""

    seed: int = 0
    specs: list = field(default_factory=list)
    #: every (cycle, site, kind) that actually fired, in order
    log: list = field(default_factory=list)
    _cycle: int = -1
    _rng: Optional[np.random.Generator] = None

    def __post_init__(self):
        self._rng = np.random.default_rng(self.seed)

    @property
    def rng(self) -> np.random.Generator:
        """The plan's payload stream (garbage values, corrupt picks) —
        one stream, drawn only when a fault fires, so injection stays
        deterministic given the seed and the fire order."""
        return self._rng

    def begin_cycle(self, cycle: int) -> None:
        self._cycle = cycle

    def pending(self, site: str) -> Optional[FaultSpec]:
        for spec in self.specs:
            if spec.site != site or spec.fired >= spec.repeat:
                continue
            due = (
                spec.cycle == self._cycle
                or (spec.sticky and spec.fired == 0
                    and 0 <= spec.cycle <= self._cycle)
            )
            if due:
                return spec
        return None

    def fire(self, site: str) -> Optional[FaultSpec]:
        spec = self.pending(site)
        if spec is None:
            return None
        spec.fired += 1
        self.log.append((self._cycle, site, spec.kind))
        return spec

    def unfired(self) -> list:
        """Specs that never fired (the harness asserts this is empty —
        a plan entry that missed its site is a harness bug, and a chaos
        run that silently skipped a fault must not pass the gate)."""
        return [s for s in self.specs if s.fired == 0]

    @classmethod
    def standard(cls, seed: int, cycles: int, hang_seconds: float = 3.0,
                 stall_seconds: float = 0.05) -> "FaultPlan":
        """The full fault taxonomy spread deterministically over
        `cycles` (docs/ROBUSTNESS.md): one of each kind, cycle slots
        drawn without replacement from a seeded stream so no two faults
        land on the same cycle (each fault's recovery window is measured
        in isolation). Requires cycles >= 10: 8 distinct slots must fit
        in [1, cycles-2] (cycle 0 and the last cycle stay fault-free)."""
        if cycles < 10:
            raise ValueError(
                f"standard plan needs >= 10 cycles (8 distinct slots in "
                f"[1, cycles-2]), got {cycles}"
            )
        rng = np.random.default_rng(seed)
        kinds = [
            (SOLVE_DISPATCH, "hang", dict(seconds=hang_seconds, repeat=4)),
            (SOLVE_DISPATCH, "device-error", dict(repeat=4)),
            (SOLVE_DISPATCH, "garbage", dict()),
            (DELTA_EVENT, "drop", dict()),
            (DELTA_EVENT, "dup", dict()),
            (DELTA_EVENT, "corrupt", dict()),
            (FEED_STALL, "stall", dict(seconds=stall_seconds)),
            (CRASH_POST_BIND, "crash", dict()),
        ]
        # leave cycle 0 fault-free (the first refresh builds the resident
        # base) and keep one clean cycle after the last fault
        slots = rng.choice(
            np.arange(1, cycles - 1), size=len(kinds), replace=False
        )
        plan = cls(seed=seed)
        for (site, kind, kw), cycle in zip(kinds, sorted(int(s) for s in slots)):
            plan.specs.append(FaultSpec(site=site, cycle=cycle, kind=kind, **kw))
        return plan


#: the process-global registry — `None` is THE fast path (every
#: instrumented site checks this before doing anything else)
ACTIVE: Optional[FaultPlan] = None


def install(plan: FaultPlan) -> FaultPlan:
    global ACTIVE
    ACTIVE = plan
    return plan


def clear() -> None:
    global ACTIVE
    ACTIVE = None


def fire(site: str) -> Optional[FaultSpec]:
    """Fire-and-consume for `site` this cycle; None when off/not due.
    Sites on hot paths should check `ACTIVE is None` inline first —
    this function exists for the cooler sites."""
    if ACTIVE is None:
        return None
    return ACTIVE.fire(site)


def mutate_delta(ev: tuple) -> list:
    """The DELTA_EVENT site's event transform: [] (drop), [ev, ev]
    (dup), or a corrupted copy (assign<->unassign sign flip; non-usage
    events degrade to drop). Poisons ONLY the sink's view — the Cluster
    store never sees the mutation, which is exactly what makes the
    divergence invisible to everything except the anti-entropy digest."""
    spec = None if ACTIVE is None else ACTIVE.fire(DELTA_EVENT)
    if spec is None:
        return [ev]
    if spec.kind == "drop":
        return []
    if spec.kind == "dup":
        return [ev, ev]
    # corrupt: flip a usage event's sign (pod_assign <-> pod_unassign)
    kind = ev[0]
    if kind == "pod_assign":
        return [("pod_unassign",) + ev[1:]]
    if kind == "pod_unassign":
        return [("pod_assign",) + ev[1:]]
    return []  # node events: corruption degrades to drop
