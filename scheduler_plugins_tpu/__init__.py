"""scheduler_plugins_tpu — a TPU-native batched cluster-scheduling framework.

Brand-new framework with the capabilities of kubernetes-sigs/scheduler-plugins
(gang scheduling, elastic quota + quota-aware preemption, allocatable/load/NUMA/
network-aware scoring, preemption toleration, syscall-aware spreading, CRD
controllers), re-designed TPU-first:

- Cluster state is a set of dense integer tensors (pods x resources,
  nodes x resources, nodes x NUMA-zones x resources, ...) instead of an object
  graph; see `scheduler_plugins_tpu.state.snapshot`.
- The per-pod x per-node Filter/Score hot loop of the reference
  (upstream kube-scheduler driving plugin callbacks per node) becomes batched
  tensor math under `jax.jit`: Filter is a (P, N) boolean reduction, Score is a
  (P, N) integer matrix, gang/quota admission are segment reductions.
- Placement itself is a `lax.scan` over the pod queue (bit-faithful to the
  one-pod-at-a-time reference semantics) or an optional faster wave mode.
- Multi-chip scaling shards the node axis over a `jax.sharding.Mesh`
  (see `scheduler_plugins_tpu.parallel`).

All resource quantities are int64 in the reference's own units (CPU in
millicores, memory in bytes) so placement decisions can be bit-identical with
the Go implementation.
"""

import jax

# Quota/score math must be int64 (memory is in *bytes*; allocatable-score
# weights go up to 1<<20) — see /root/reference/pkg/noderesources/resource_allocation.go:36.
# The ONE sanctioned in-package config mutation: x64 is part of the
# package's import contract (every consumer needs it before the first
# array), so the precision config is owned here rather than per-entrypoint.
jax.config.update("jax_enable_x64", True)  # graft-lint: ignore[GL007]

__version__ = "0.1.0"
