"""Rank-aware gang placement: topology-block waterfill as tensor math.

"Rank-Aware Resource Scheduling for Tightly-Coupled MPI Workloads on
Kubernetes" (arxiv 2603.22691) and "Tesserae" (arxiv 2508.04953) both show
that for gang jobs *which* nodes host the ranks — not just whether quorum
is reachable — dominates runtime: inter-rank network distance is the
objective. The reference composes nothing here (Coscheduling admits by
quorum alone, NetworkOverhead scores pods one at a time); this module is
the composition, beyond the reference's scope (docs/GANGS.md).

Model
-----
Nodes group into **topology blocks** (zone codes from the node labels; the
three levels a rank pair can sit at are node / block / cross-block, with
cross-block cost split by region — the NetworkTopology CR's zone and
region weight tables, lowered once into one (B, B) `block_cost` matrix by
`build_block_cost`). A gang of up to M ranks carries per-rank demand
vectors (heterogeneous: an MPI launcher rank may want more than its
workers). The placement objective per gang: minimize the max (and sum)
inter-rank pair cost

    cost(i, j) = 0                       same node
                 block_cost[b_i, b_j]    otherwise (diag = SAME_ZONE_COST)

subject to the identical hard constraints the per-pod solve enforces —
fit (free capacity per node), quota caps (ElasticQuota max per
namespace), and quorum (>= min_ranks ranks place, or NONE do).

Algorithm (the topology-block waterfill, `gang_solve`)
------------------------------------------------------
One `lax.scan` over gangs in queue order (carried free/eq_used/rank_nodes
— in-cycle mutations live in SolverState carries per CLAUDE.md, never
re-reads of a static snapshot). Per gang:

1. **Score blocks by packed-rank capacity**: inclusive cumulative rank
   demand (float64 — exact < 2^53, the `ops.assign` cumulative-demand
   bucket formulation; never a 2-D int64 cumsum) searchsorted against
   each block's free totals — how many queue-ranked ranks the block
   covers. Primary block = argmax packed capacity, lowest index on ties;
   a gang with RESIDENT ranks (elastic growth) instead anchors on the
   block holding most residents.
2. **Spill order**: remaining blocks ascend by `block_cost[primary, b]`,
   index tie-break (the key `cost * B + b` is unique, so any sort is
   stable); unblocked nodes come after every block.
3. **Exact rank scan**: ranks place one at a time in rank order, each to
   the first node in block-first order with capacity (and quota
   headroom) — the sequential-waterfill admission that keeps a bit-exact
   host twin (`gang_solve_np`). The first rank that fits nowhere kills
   the rest (placements are a queue prefix — no holes).
4. **Quorum revert**: resident + newly placed ranks < min_ranks rolls the
   gang's commits back — zero partial ranks, mirroring the whole-gang
   PostFilter rejection. Elastic gangs (min < desired) keep any prefix
   >= min.

`gang_solve_np` is the bit-identical sequential twin gated by
tests/test_differential.py (3-seed oracle: placements equal, fit/quota/
quorum replay clean); `pair_costs`/`gang_cost_stats` score the result for
the bench and the quality objectives (`tuning.quality.rank_gang_quality`).
"""

from __future__ import annotations

import numpy as np
from flax import struct

from scheduler_plugins_tpu.ops.network import MAX_COST, SAME_ZONE_COST

I64 = np.int64
I32 = np.int32

#: node-order sentinel for "never place here" (masked node)
_FAR = np.iinfo(np.int32).max


@struct.dataclass
class RankGangState:
    """Snapshot-side tensors for one gang-phase solve.

    Rank slots are per-gang rows: slot m of gang g is that gang's rank m
    in rank order (residents first, then pending by queue order — the
    order `gangs.phase.build_rank_gang_problem` fixes host-side).
    """

    rank_req: np.ndarray  # (G, M, R) int64 per-rank fit demand (pods slot 1)
    rank_mask: np.ndarray  # (G, M) bool — real rank slots this cycle
    #: (G, M) int32 resident rank -> node (-1 = pending, needs placement).
    #: THE snapshot counterpart of the `SolverState.rank_nodes` carry
    #: (state.snapshot.CARRY_COUNTERPARTS): the solve must thread its
    #: in-cycle placements through the carry, never re-read this tensor.
    prev_assigned: np.ndarray
    min_ranks: np.ndarray  # (G,) int32 quorum (elastic min)
    gang_ns: np.ndarray  # (G,) int32 namespace code (-1 = no quota scope)
    gang_mask: np.ndarray  # (G,) bool
    node_block: np.ndarray  # (N,) int32 topology-block (zone) code, -1 none
    block_cost: np.ndarray  # (B, B) int32 inter-block cost, diag SAME_ZONE
    quota_max: np.ndarray  # (Q, R) int64 ElasticQuota max per namespace
    quota_has: np.ndarray  # (Q,) bool namespace carries a quota


# ---------------------------------------------------------------------------
# block cost lowering (host)
# ---------------------------------------------------------------------------


def build_block_cost(zones, regions, zone_region, zone_cost, region_cost):
    """(B, B) int32 inter-block cost matrix over zone codes.

    Composition mirrors the NetworkOverhead pair tables
    (`ops.network.dependency_tallies`): same block -> SAME_ZONE_COST;
    different blocks, zone-cost pair known -> that cost; unknown but both
    regions known and different with a region-cost pair -> that cost;
    anything else -> MAX_COST. `zone_region` maps zone code -> region code
    (-1 unknown); `zone_cost`/`region_cost` are the dense -1-for-missing
    matrices `plugins.networkaware.NetworkOverhead.prepare_cluster`
    builds.
    """
    B = max(len(zones), 1)
    zone_cost = np.asarray(zone_cost)
    region_cost = np.asarray(region_cost)
    zone_region = np.asarray(zone_region)
    out = np.full((B, B), MAX_COST, I32)
    for a in range(B):
        for b in range(B):
            if a == b:
                out[a, b] = SAME_ZONE_COST
                continue
            if a < zone_cost.shape[0] and b < zone_cost.shape[1] and \
                    zone_cost[a, b] >= 0:
                out[a, b] = zone_cost[a, b]
                continue
            ra = zone_region[a] if a < zone_region.shape[0] else -1
            rb = zone_region[b] if b < zone_region.shape[0] else -1
            if ra >= 0 and rb >= 0:
                if ra == rb:
                    # same region, no zone pair in the CR: the reference's
                    # missing-zone-lookup MaxCost path
                    out[a, b] = MAX_COST
                elif region_cost[ra, rb] >= 0:
                    out[a, b] = region_cost[ra, rb]
    return out


# ---------------------------------------------------------------------------
# the jittable solve
# ---------------------------------------------------------------------------


def packed_rank_capacity(cumdem, block_free):
    """(B,) int32 packed-rank capacity per block: how many queue-ranked
    ranks each block's free totals cover — the `ops.assign`
    `_cumulative_demand_positions` bucketing transposed (searchsorted of
    block capacity into the inclusive cumulative demand, min over
    resources). `cumdem` (M, R) float64 inclusive cumulative rank demand;
    `block_free` (B, R) non-negative block free totals."""
    import jax
    import jax.numpy as jnp

    # count of m with cumdem[m, r] <= block_free[b, r], per resource
    counts = jax.vmap(
        lambda cd, bf: jnp.searchsorted(cd, bf, side="right"),
        in_axes=(1, 1), out_axes=1,
    )(cumdem, block_free.astype(jnp.float64))  # (B, R)
    return jnp.min(counts, axis=1).astype(jnp.int32)


def place_gang_one(gangs: RankGangState, g, free, eq_used, node_mask):
    """ONE gang's topology-block waterfill step against (`free`,
    `eq_used`) — THE shared per-gang body: the sequential scan
    (`gang_solve_body`) runs it with the live carries, the wave-batched
    solve (`gangs.waves`) vmaps it over a wave of independent gangs
    against the wave-start state. One copy, so the two paths cannot
    drift (and both stay bit-exact against `gang_solve_np`).

    Returns (choices, admitted, q_new, free_l, eq_l, resident, primary,
    has_res): `choices` are the PRE-revert tentative placements (the wave
    validator needs them even for quorum-failed gangs), `free_l`/`eq_l`
    the post-placement state BEFORE the quorum revert — callers apply
    `jnp.where(admitted, ...)` themselves.
    """
    import jax
    import jax.numpy as jnp

    G, M, R = gangs.rank_req.shape
    N = free.shape[0]
    B = gangs.block_cost.shape[0]
    node_block = gangs.node_block
    block_cost = gangs.block_cost.astype(jnp.int32)
    blk = jnp.maximum(node_block, 0)
    blocked = (node_block >= 0) & node_mask

    pending = gangs.rank_mask[g] & (gangs.prev_assigned[g] < 0)  # (M,)
    resident = gangs.rank_mask[g] & (gangs.prev_assigned[g] >= 0)
    dem = jnp.where(pending[:, None], gangs.rank_req[g], 0)  # (M, R)

    # 1. block scoring: packed-rank capacity over the gang's pending
    # demand prefix (cumulative-demand bucket machinery, f64 exact)
    freec = jnp.where(node_mask[:, None], jnp.clip(free, 0, None), 0)
    block_free = jnp.zeros((B, R), free.dtype).at[blk].add(
        jnp.where(blocked[:, None], freec, 0)
    )
    cumdem = jnp.cumsum(dem.astype(jnp.float64), axis=0)  # (M, R)
    packed = packed_rank_capacity(cumdem, block_free)  # (B,)
    res_cnt = jnp.zeros(B, jnp.int32).at[
        blk[jnp.maximum(gangs.prev_assigned[g], 0)]
    ].add(
        jnp.where(
            resident
            & (node_block[jnp.maximum(gangs.prev_assigned[g], 0)] >= 0),
            1, 0,
        )
    )
    has_res = res_cnt.sum() > 0
    # argmax takes the FIRST max — lowest block index on ties, in both
    # jnp and np (the twin relies on this)
    primary = jnp.where(
        has_res, jnp.argmax(res_cnt), jnp.argmax(packed)
    ).astype(jnp.int32)

    # 2. spill order: cost from primary asc, index tie-break (unique
    # keys make the sort order-independent); primary pinned first
    cost_from = block_cost[primary].at[primary].set(-1)
    block_order = jnp.argsort(
        cost_from.astype(jnp.int64) * B + jnp.arange(B)
    )
    block_pos = jnp.zeros(B, jnp.int64).at[block_order].set(
        jnp.arange(B, dtype=jnp.int64)
    )
    node_pos = jnp.where(
        blocked,
        block_pos[blk] * N + jnp.arange(N),
        jnp.where(node_mask, jnp.int64(B) * N + jnp.arange(N),
                  jnp.int64(_FAR)),
    )  # (N,) unique finite positions for usable nodes

    ns = gangs.gang_ns[g]
    nsc = jnp.maximum(ns, 0)
    has_quota = (ns >= 0) & gangs.quota_has[nsc]
    qmax = gangs.quota_max[nsc]

    # 3. exact rank scan: first-fit in block-first order, dead after
    # the first unplaceable rank (prefix placements, no holes)
    def place_rank(c, m):
        free_l, eq_l, dead = c
        d = dem[m]
        is_pending = pending[m]
        fits = jnp.all(free_l >= d[None, :], axis=1) & node_mask
        qok = ~has_quota | jnp.all(eq_l[nsc] + d <= qmax)
        feasible = fits & is_pending & ~dead & qok
        pos = jnp.where(feasible, node_pos, jnp.int64(_FAR))
        choice = jnp.where(
            feasible.any(), jnp.argmin(pos).astype(jnp.int32),
            jnp.int32(-1),
        )
        placed = choice >= 0
        onehot = (jnp.arange(N) == choice)[:, None]
        free_l = free_l - jnp.where(placed, onehot * d[None, :], 0)
        eq_l = eq_l.at[nsc].add(
            jnp.where(placed & has_quota, d, 0)
        )
        dead = dead | (is_pending & ~placed)
        return (free_l, eq_l, dead), choice

    (free_l, eq_l, _), choices = jax.lax.scan(
        place_rank, (free, eq_used, jnp.bool_(False)), jnp.arange(M)
    )

    # 4. quorum verdict: zero partial ranks below min (callers revert)
    q_new = jnp.sum(choices >= 0).astype(jnp.int32)
    q_total = q_new + jnp.sum(resident).astype(jnp.int32)
    admitted = gangs.gang_mask[g] & (q_total >= gangs.min_ranks[g])
    return choices, admitted, q_new, free_l, eq_l, resident, primary, has_res


def gang_solve_body(gangs: RankGangState, state0, node_mask):
    """Traced topology-block waterfill over every gang (see module doc).

    `state0` is a `framework.plugin.SolverState` carrying `free` (N, R),
    `eq_used` (Q, R) and `rank_nodes` (G, M) — `rank_nodes` MUST be
    initialized from `gangs.prev_assigned` (the resident assignment; the
    carry is the live copy, the snapshot tensor stays static). Returns
    (rank_nodes, admitted, placed_new, state) with the final carries.
    """
    import jax
    import jax.numpy as jnp

    G = gangs.rank_req.shape[0]

    def place_gang(carry, g):
        free, eq_used, rank_nodes = carry
        (choices, admitted, q_new, free_l, eq_l, resident, _primary,
         _has_res) = place_gang_one(gangs, g, free, eq_used, node_mask)
        free = jnp.where(admitted, free_l, free)
        eq_used = jnp.where(admitted, eq_l, eq_used)
        row = jnp.where(
            resident,
            gangs.prev_assigned[g],
            jnp.where(admitted, choices, jnp.int32(-1)),
        )
        rank_nodes = rank_nodes.at[g].set(row)
        return (free, eq_used, rank_nodes), (
            admitted, jnp.where(admitted, q_new, 0)
        )

    (free, eq_used, rank_nodes), (admitted, placed_new) = jax.lax.scan(
        place_gang,
        (state0.free, state0.eq_used, state0.rank_nodes),
        jnp.arange(G),
    )
    state = state0.replace(free=free, eq_used=eq_used, rank_nodes=rank_nodes)
    return rank_nodes, admitted, placed_new, state


def packed_rank_capacity_np(cumdem, block_free):
    """Host twin of `packed_rank_capacity` — identical float64
    searchsorted semantics (gated bit-exact by the gang differentials).
    Shared by `gang_solve_np` and the wave validator
    (`gangs.waves._primary_invariant`), so the host-side primary-block
    recomputation IS the solve's own scoring."""
    R = cumdem.shape[1]
    counts = np.stack(
        [
            np.searchsorted(
                cumdem[:, r], block_free[:, r].astype(np.float64),
                side="right",
            )
            for r in range(R)
        ],
        axis=1,
    )  # (B, R)
    return np.min(counts, axis=1).astype(I32)


def gang_solve_fn():
    """The jitted gang-solve program — one constructor so the bench, the
    phase, and the AOT/jaxpr certification gates (tools/tpu_lower.py,
    tools/jaxpr_audit.py `rank_gang_solve`) trace the same function."""
    import jax

    return jax.jit(gang_solve_body)


def pair_costs(rank_nodes, rank_mask, node_block, block_cost):
    """(G, M, M) int32 inter-rank pair costs (-1 = invalid pair: an
    unplaced slot, a padded slot, or the diagonal). Same-node pairs cost
    0; otherwise `block_cost[b_i, b_j]`, MAX_COST when either block is
    unknown. Works on jnp or np inputs (pure numpy here: the bench and
    the quality objectives consume host copies)."""
    rank_nodes = np.asarray(rank_nodes)
    rank_mask = np.asarray(rank_mask)
    node_block = np.asarray(node_block)
    block_cost = np.asarray(block_cost)
    live = rank_mask & (rank_nodes >= 0)  # (G, M)
    nb = np.where(live, node_block[np.maximum(rank_nodes, 0)], -1)
    known = nb >= 0
    nb0 = np.maximum(nb, 0)
    bc = block_cost[nb0[:, :, None], nb0[:, None, :]]
    cost = np.where(
        known[:, :, None] & known[:, None, :], bc, MAX_COST
    ).astype(I32)
    same_node = rank_nodes[:, :, None] == rank_nodes[:, None, :]
    cost = np.where(same_node, 0, cost)
    valid = live[:, :, None] & live[:, None, :]
    M = rank_nodes.shape[1]
    valid &= ~np.eye(M, dtype=bool)[None]
    return np.where(valid, cost, -1)


def gang_cost_stats(rank_nodes, rank_mask, node_block, block_cost):
    """Per-gang placement-cost stats: (max_cost (G,), sum_cost (G,)) int64
    over valid rank pairs (sum counts each unordered pair once; gangs with
    < 2 placed ranks score 0)."""
    pc = pair_costs(rank_nodes, rank_mask, node_block, block_cost)
    valid = pc >= 0
    max_cost = np.where(
        valid.any(axis=(1, 2)), np.max(np.where(valid, pc, 0), axis=(1, 2)), 0
    ).astype(I64)
    sum_cost = (np.sum(np.where(valid, pc, 0), axis=(1, 2)) // 2).astype(I64)
    return max_cost, sum_cost


# ---------------------------------------------------------------------------
# the bit-identical numpy sequential twin (differential-gate parity path)
# ---------------------------------------------------------------------------


def place_gang_np(gangs: RankGangState, g: int, free, eq_used, node_mask):
    """Host twin of `place_gang_one` for ONE gang against the live
    (`free`, `eq_used`) — identical operation order and tie-breaks
    (np.argmax/argmin take the first extremum, same as jnp). THE shared
    per-gang host body: `gang_solve_np` loops it in queue order, and the
    wave solve (`gangs.waves`) resolves conflicted lanes with it. Returns
    (choices (M,) int32, ok, q_new, free_l, eq_l, resident) — PRE-revert
    state like the traced body; callers apply the quorum revert."""
    rank_req = np.asarray(gangs.rank_req)
    rank_mask = np.asarray(gangs.rank_mask)
    prev = np.asarray(gangs.prev_assigned)
    node_block = np.asarray(gangs.node_block)
    block_cost = np.asarray(gangs.block_cost)
    quota_max = np.asarray(gangs.quota_max)
    quota_has = np.asarray(gangs.quota_has)
    node_mask = np.asarray(node_mask)

    G, M, R = rank_req.shape
    N = free.shape[0]
    B = block_cost.shape[0]
    blk = np.maximum(node_block, 0)
    blocked = (node_block >= 0) & node_mask

    pending = rank_mask[g] & (prev[g] < 0)
    resident = rank_mask[g] & (prev[g] >= 0)
    dem = np.where(pending[:, None], rank_req[g], 0)

    freec = np.where(node_mask[:, None], np.clip(free, 0, None), 0)
    block_free = np.zeros((B, R), I64)
    np.add.at(block_free, blk[blocked], freec[blocked])
    cumdem = np.cumsum(dem.astype(np.float64), axis=0)
    packed = packed_rank_capacity_np(cumdem, block_free)
    res_cnt = np.zeros(B, I32)
    res_nodes = np.maximum(prev[g], 0)
    res_ok = resident & (node_block[res_nodes] >= 0)
    np.add.at(res_cnt, blk[res_nodes[res_ok]], 1)
    primary = int(np.argmax(res_cnt) if res_cnt.sum() > 0
                  else np.argmax(packed))

    cost_from = block_cost[primary].astype(I64).copy()
    cost_from[primary] = -1
    block_order = np.argsort(cost_from * B + np.arange(B))
    block_pos = np.zeros(B, I64)
    block_pos[block_order] = np.arange(B)
    node_pos = np.where(
        blocked,
        block_pos[blk] * N + np.arange(N),
        np.where(node_mask, I64(B) * N + np.arange(N), I64(_FAR)),
    )

    ns = int(np.asarray(gangs.gang_ns)[g])
    nsc = max(ns, 0)
    has_quota = ns >= 0 and bool(quota_has[nsc])

    free_l = free.copy()
    eq_l = eq_used.copy()
    choices = np.full(M, -1, I32)
    dead = False
    for m in range(M):
        if not pending[m] or dead:
            continue
        d = dem[m]
        fits = np.all(free_l >= d[None, :], axis=1) & node_mask
        qok = (not has_quota) or bool(
            np.all(eq_l[nsc] + d <= quota_max[nsc])
        )
        feasible = fits & qok
        if not feasible.any():
            dead = True
            continue
        pos = np.where(feasible, node_pos, I64(_FAR))
        choice = int(np.argmin(pos))
        choices[m] = choice
        free_l[choice] -= d
        if has_quota:
            eq_l[nsc] += d

    q_new = int((choices >= 0).sum())
    q_total = q_new + int(resident.sum())
    ok = bool(np.asarray(gangs.gang_mask)[g]) and \
        q_total >= int(np.asarray(gangs.min_ranks)[g])
    return choices, ok, q_new, free_l, eq_l, resident


def gang_solve_np(gangs: RankGangState, free0, eq_used0, node_mask):
    """Host-side twin of `gang_solve_body`: the shared per-gang body
    (`place_gang_np`) looped in queue order — bit-exact against the jit
    solve (tests/test_differential.py gates this across seeds). Returns
    (rank_nodes (G, M) int32, admitted (G,) bool, placed_new (G,) int32,
    free (N, R), eq_used (Q, R))."""
    rank_mask = np.asarray(gangs.rank_mask)
    prev = np.asarray(gangs.prev_assigned)

    G, M, R = np.asarray(gangs.rank_req).shape

    free = np.asarray(free0).astype(I64).copy()
    eq_used = np.asarray(eq_used0).astype(I64).copy()
    rank_nodes = prev.astype(I32).copy()
    admitted = np.zeros(G, bool)
    placed_new = np.zeros(G, I32)

    for g in range(G):
        choices, ok, q_new, free_l, eq_l, resident = place_gang_np(
            gangs, g, free, eq_used, node_mask
        )
        if ok:
            free = free_l
            eq_used = eq_l
        admitted[g] = ok
        placed_new[g] = q_new if ok else 0
        row = np.where(resident, prev[g], choices if ok else -1)
        rank_nodes[g] = row
    return rank_nodes, admitted, placed_new, free, eq_used
