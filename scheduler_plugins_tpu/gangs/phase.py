"""GangPhase: the host orchestrator of the rank-aware gang solve.

`framework.cycle.run_cycle(gangs=GangPhase(...))` runs this phase AFTER
QueueSort and BEFORE the snapshot/per-pod solve: rank-aware gangs
(`PodGroup.rank_aware`) are lifted out of the pending batch, solved as
whole gangs by the topology-block waterfill (`gangs.topology`), and
their placements bound through the store mutators — so the per-pod path's
snapshot (built afterwards) sees the committed free/eq_used state, and
every event rides the `api.events` kind table (binds -> POD_UPDATE,
elastic deletes -> POD_DELETE, growth -> POD_ADD; no new literal kind
strings anywhere in this phase).

Responsibilities per cycle:

1. `reconcile` elastic gangs (`gangs.elastic`): shrink deletes the
   highest-cost ranks, growth clones member pods from the gang's rank
   template — both through `Cluster.remove_pod`/`add_pod` so the delta
   sink and requeue gating observe them.
2. Build the `RankGangState` tensors from one store snapshot (the same
   `Cluster.snapshot` lowering the per-pod path trusts — node axis,
   quota tables and zone/region codes are shared, so the gang solve
   enforces the identical hard constraints).
3. Solve (jit by default; `host_twin=True` runs the numpy sequential
   twin instead — the degraded-mode path). With `check_twin=True` BOTH
   run and `last_drift` records whether they disagreed (0.0 = bit-equal;
   the gang-smoke gate pins this at 0.0).
4. Bind placed ranks, reject quorum-failed gangs whole (zero partial
   ranks — members are parked unschedulable with the standard backoff),
   update the resident rank ledger O(changed), and stash the capture for
   the flight recorder (`annotate_record`).
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from scheduler_plugins_tpu.gangs import elastic as E
from scheduler_plugins_tpu.gangs import topology as T
from scheduler_plugins_tpu.utils.intmath import bucket_size

I64 = np.int64
I32 = np.int32

#: attribution name stamped into `CycleReport.failed_by` for pods a
#: quorum-failed rank gang parks (the phase is framework machinery, not a
#: profile plugin, so it owns its own name like BUILTIN_FIT does)
RANK_GANG_PLACEMENT = "RankGangPlacement"

DEFAULT_WEIGHTS_NAME = "UserDefined"
DEFAULT_NETWORK_TOPOLOGY_NAME = "nt-default"


def rank_gang_groups(cluster):
    """The rank-aware PodGroups of a cluster, in name order."""
    return [
        pg for _, pg in sorted(cluster.pod_groups.items())
        if getattr(pg, "rank_aware", False)
    ]


def _zone_region_costs(meta, cluster, weights_name, nt_name):
    """Dense (ZC, ZC)/(RC, RC) cost matrices on this snapshot's zone and
    region codes — the same lowering
    `plugins.networkaware.NetworkOverhead.prepare_cluster` performs
    (networkoverhead.go:448-497), duplicated here only in shape: both
    feed `gangs.topology.build_block_cost`."""
    ZC = max(len(meta.zones), 1)
    RC = max(len(meta.regions), 1)
    zone_cost = np.full((ZC, ZC), -1, I64)
    region_cost = np.full((RC, RC), -1, I64)
    nt = None
    for cand in cluster.network_topologies.values():
        if cand.name == nt_name:
            nt = cand
            break
    if nt is not None:
        weights = nt.weights.get(weights_name, {})
        for (orig, dest), cost in weights.get("zone", {}).items():
            if orig in meta.zones and dest in meta.zones:
                zone_cost[meta.zones.index(orig), meta.zones.index(dest)] = cost
        for (orig, dest), cost in weights.get("region", {}).items():
            if orig in meta.regions and dest in meta.regions:
                region_cost[
                    meta.regions.index(orig), meta.regions.index(dest)
                ] = cost
    return zone_cost, region_cost


def _block_cost_from_snapshot(meta, cluster, zones, regions,
                              weights_name, nt_name):
    """THE one zone/region -> block_cost derivation (shared by the solve
    build, the shrink reconcile, and the bench audit — three consumers of
    one rule set; a fix here cannot diverge them)."""
    zone_cost, region_cost = _zone_region_costs(
        meta, cluster, weights_name, nt_name
    )
    ZC = max(len(meta.zones), 1)
    zone_region = np.full(ZC, -1, I32)
    for ni in range(len(meta.node_names)):
        if zones[ni] >= 0 and regions[ni] >= 0:
            zone_region[zones[ni]] = regions[ni]
    return T.build_block_cost(
        meta.zones or [""], meta.regions, zone_region, zone_cost,
        region_cost,
    )


def block_cost_view(cluster, weights_name=DEFAULT_WEIGHTS_NAME,
                    nt_name=DEFAULT_NETWORK_TOPOLOGY_NAME):
    """(node_pos, zones (N,) int32, block_cost) from ONE empty-batch
    store snapshot — the audit-side lowering (bench `_gang_placement
    _costs`, elastic shrink). Built once per caller pass, never per
    gang."""
    snap, meta = cluster.snapshot([], now_ms=0)
    zones = np.asarray(snap.nodes.zone).astype(I32)
    regions = np.asarray(snap.nodes.region)
    node_pos = {name: i for i, name in enumerate(meta.node_names)}
    return node_pos, zones, _block_cost_from_snapshot(
        meta, cluster, zones, regions, weights_name, nt_name
    )


def build_rank_gang_problem(cluster, pending, now,
                            weights_name=DEFAULT_WEIGHTS_NAME,
                            nt_name=DEFAULT_NETWORK_TOPOLOGY_NAME,
                            serve=None):
    """Lower the cluster's rank-aware gangs into a solvable problem, or
    None when no rank-aware gang has pending members. With `serve` (a
    `serving.engine.ServeEngine` attached to this cluster) the node/
    quota/meta lowering comes from the engine's RESIDENT columns and
    side tables (O(changed) — the gang phase no longer pays an
    O(cluster) re-snapshot per cycle); an incompatible roster falls back
    to `Cluster.snapshot` transparently, exactly like the per-pod path.

    Returns a dict: the `RankGangState`, the initial free/eq_used/node
    mask arrays, `uids` (G lists of per-slot uids, None for pad slots),
    `gang_names` (G,), `node_names`, and `gang_pods` (the pending Pod
    objects the phase consumed — the cycle removes them from the batch).
    Rank order per gang: residents by (creation_ms, uid), then pending
    members in queue order — the slot order the solve's prefix semantics
    and the shrink keys rely on.
    """
    groups = rank_gang_groups(cluster)
    if not groups:
        return None
    by_gang_pending: dict[str, list] = {}
    consumed = []
    for pod in pending:
        pg = cluster.pod_group_of(pod)
        if pg is not None and getattr(pg, "rank_aware", False):
            by_gang_pending.setdefault(pg.full_name, []).append(pod)
            consumed.append(pod)
    active = [pg for pg in groups if by_gang_pending.get(pg.full_name)]
    if not active:
        return None

    # one trusted lowering for nodes/quota/codes — over EVERY consumed
    # member, so the resource-axis union covers any extended resource a
    # rank requests (a one-pod snapshot would KeyError encoding the rest;
    # the pod tensors themselves are irrelevant — the gang solve builds
    # its own rank rows). A serving engine provides the same view from
    # its resident state when the roster qualifies.
    snap = meta = None
    if serve is not None:
        refreshed = serve.refresh(cluster, consumed, now_ms=now)
        if refreshed is not None:
            snap, meta = refreshed
    if snap is None:
        snap, meta = cluster.snapshot(consumed, now_ms=now)
    alloc = np.asarray(snap.nodes.alloc)
    requested = np.asarray(snap.nodes.requested)
    node_mask = np.asarray(snap.nodes.mask)
    free0 = (alloc - requested).astype(I64)
    R = alloc.shape[1]
    node_pos = {name: i for i, name in enumerate(meta.node_names)}
    node_block = np.asarray(snap.nodes.zone).astype(I32)

    block_cost = _block_cost_from_snapshot(
        meta, cluster, np.asarray(snap.nodes.zone),
        np.asarray(snap.nodes.region), weights_name, nt_name,
    )

    if snap.quota is not None:
        eq_used0 = np.asarray(snap.quota.used).astype(I64)
        quota_max = np.asarray(snap.quota.max).astype(I64)
        quota_has = np.asarray(snap.quota.has_quota)
    else:
        eq_used0 = np.zeros((1, R), I64)
        quota_max = np.full((1, R), np.iinfo(I64).max, I64)
        quota_has = np.zeros(1, bool)

    from scheduler_plugins_tpu.api.resources import PODS

    pods_i = meta.index.position(PODS)
    G = bucket_size(len(active))
    max_members = 1
    rows = []
    for pg in active:
        pend = by_gang_pending[pg.full_name]
        residents = sorted(
            (
                p for p in cluster.gang_members(pg)
                if p.node_name is not None and p.node_name in node_pos
            ),
            key=lambda p: (p.creation_ms, p.uid),
        )
        members = residents + pend
        max_members = max(max_members, len(members))
        rows.append((pg, residents, pend, members))
    M = bucket_size(max_members)

    rank_req = np.zeros((G, M, R), I64)
    rank_mask = np.zeros((G, M), bool)
    prev_assigned = np.full((G, M), -1, I32)
    min_ranks = np.ones(G, I32)
    gang_ns = np.full(G, -1, I32)
    gang_mask = np.zeros(G, bool)
    uids: list[Optional[list]] = []
    gang_names = []
    for g, (pg, residents, pend, members) in enumerate(rows):
        gang_names.append(pg.full_name)
        gang_mask[g] = True
        lo, desired, _hi = E.elastic_bounds(pg)
        min_ranks[g] = lo
        try:
            gang_ns[g] = meta.namespaces.index(pg.namespace)
        except ValueError:
            gang_ns[g] = -1
        slot_uids = []
        for m, pod in enumerate(members[:M]):
            vec = meta.index.encode(pod.effective_request())
            vec[pods_i] = 1
            rank_req[g, m] = vec
            rank_mask[g, m] = True
            slot_uids.append(pod.uid)
            if pod.node_name is not None:
                prev_assigned[g, m] = node_pos[pod.node_name]
        uids.append(slot_uids)
    uids.extend([] for _ in range(G - len(rows)))
    gang_names.extend("" for _ in range(G - len(rows)))

    gangs = T.RankGangState(
        rank_req=rank_req,
        rank_mask=rank_mask,
        prev_assigned=prev_assigned,
        min_ranks=min_ranks,
        gang_ns=gang_ns,
        gang_mask=gang_mask,
        node_block=node_block,
        block_cost=block_cost,
        quota_max=quota_max,
        quota_has=quota_has,
    )
    return {
        "gangs": gangs,
        "free0": free0,
        "eq_used0": eq_used0,
        "node_mask": node_mask,
        "uids": uids,
        "gang_names": gang_names,
        "node_names": list(meta.node_names),
        "consumed": consumed,
    }


class GangPhase:
    """Long-lived gang-phase driver for one cluster (see module doc)."""

    def __init__(self, host_twin: bool = False, check_twin: bool = False,
                 weights_name: str = DEFAULT_WEIGHTS_NAME,
                 network_topology_name: str = DEFAULT_NETWORK_TOPOLOGY_NAME,
                 wave: bool = False, wave_width: Optional[int] = None):
        self.host_twin = host_twin
        self.check_twin = check_twin
        #: wave-batched solve (gangs.waves): independent gangs solved in
        #: parallel waves, bit-identical to the sequential scan by the
        #: conflict-fence acceptance rule — the sequential path stays the
        #: parity anchor (tests/test_differential.py)
        self.wave = wave
        self.wave_width = wave_width
        self.weights_name = weights_name
        self.network_topology_name = network_topology_name
        #: gang full_name -> {uid: node} resident rank ledger, updated
        #: O(changed) from this phase's own binds/releases (the serving
        #: engine's per-gang resident rank-assignment mirror)
        self.resident: dict[str, dict] = {}
        #: 0.0 when the jit solve and the numpy twin agreed bit-exactly on
        #: the last solved cycle (check_twin), else the mismatch fraction
        self.last_drift: Optional[float] = None
        #: the WORST drift over every solved cycle of this phase's
        #: lifetime — the gate value (`make gang-smoke` asserts on this;
        #: last_drift alone would let a mid-run divergence be masked by a
        #: later clean cycle)
        self.max_drift: Optional[float] = None
        self._jit = None
        self._grow_serial = 0
        self._last: Optional[dict] = None
        #: gang full_name -> last desired width this phase observed
        #: (`reconcile` diffs against it to record elastic desired-width
        #: TRANSITIONS on the flight-recorder manifest — the corpus
        #: signal the tuner needs to counterfactually sweep block
        #: policies, ROADMAP item 3)
        self._desired_seen: dict[str, int] = {}
        #: this cycle's observed transitions (rebuilt every reconcile
        #: pass, attached by `annotate_record`)
        self._elastic_transitions: list = []

    # -- elastic reconcile ----------------------------------------------
    def reconcile(self, cluster, now) -> dict:
        """Apply elastic grow/shrink transitions (gangs.elastic). Returns
        {gang: {"created": [uids], "released": [uids]}} for gangs that
        moved. Over-width gangs shed PENDING members first (newest
        clones, free — nothing placed yet, so the solve never binds ranks
        the next reconcile would delete), then live ranks by the
        highest-cost-first selection. The block-cost view is lowered ONCE
        per reconcile pass, not per shrinking gang."""
        moved: dict[str, dict] = {}
        view = None  # (node_pos, zones, block_cost), lowered lazily once
        self._elastic_transitions = []
        for pg in rank_gang_groups(cluster):
            lo, desired, hi = E.elastic_bounds(pg)
            prev = self._desired_seen.get(pg.full_name)
            if prev != desired:
                # first sighting records from=None (the corpus needs the
                # initial width too, not just later moves)
                self._elastic_transitions.append({
                    "gang": pg.full_name, "from": prev, "to": desired,
                    "min": lo, "max": hi,
                })
                self._desired_seen[pg.full_name] = desired
            members = cluster.gang_members(pg)
            live = [p for p in members if p.node_name is not None]
            total = len(members)
            released: list = []
            if total > desired:
                # pending extras above desired leave first, newest first
                spare = sorted(
                    (p for p in members if p.node_name is None),
                    key=lambda p: (p.creation_ms, p.uid), reverse=True,
                )[: total - desired]
                for p in spare:
                    cluster.remove_pod(p.uid)  # Pod/Delete (api.events)
                    released.append(p.uid)
            if len(live) > desired:
                if view is None:
                    view = block_cost_view(
                        cluster, self.weights_name,
                        self.network_topology_name,
                    )
                released += self._shrink(
                    cluster, pg, live, len(live) - desired, view
                )
            if released:
                moved[pg.full_name] = {"created": [], "released": released}
            elif total < desired and members:
                created = self._grow(cluster, pg, members, desired - total, now)
                moved[pg.full_name] = {"created": created, "released": []}
        return moved

    def _shrink(self, cluster, pg, live, n_release, view):
        """Delete the `n_release` highest-cost live ranks (elastic shrink
        order: max inter-rank pair cost desc, rank index desc). `view` is
        the reconcile pass's shared `block_cost_view`."""
        node_pos, zones, block_cost = view
        ordered = sorted(live, key=lambda p: (p.creation_ms, p.uid))
        M = len(ordered)
        rank_nodes = np.asarray(
            [[node_pos.get(p.node_name, -1) for p in ordered]], I32
        )
        live_mask = rank_nodes >= 0
        release = E.shrink_select_np(
            rank_nodes, live_mask, zones, block_cost,
            np.asarray([n_release], I32),
        )[0]
        released = []
        ledger = self.resident.setdefault(pg.full_name, {})
        for m in range(M):
            if release[m]:
                uid = ordered[m].uid
                cluster.remove_pod(uid)  # emits Pod/Delete (api.events)
                ledger.pop(uid, None)
                released.append(uid)
        return released

    def _grow(self, cluster, pg, members, n_new, now):
        """Clone `n_new` member pods from the gang's rank template (its
        first member in rank order) — the elastic growth path; the clones
        arrive as ordinary Pod/Add events and place next cycle anchored on
        the gang's resident block."""
        from scheduler_plugins_tpu.api.objects import Pod

        template = sorted(members, key=lambda p: (p.creation_ms, p.uid))[0]
        created = []
        for _ in range(n_new):
            self._grow_serial += 1
            name = f"{pg.name}-g{self._grow_serial:04d}"
            uid = f"{pg.namespace}/{name}"
            if uid in cluster.pods:
                continue
            cluster.add_pod(Pod(
                name=name,
                namespace=pg.namespace,
                containers=list(template.containers),
                init_containers=list(template.init_containers),
                priority=template.priority,
                labels=dict(template.labels),
                creation_ms=now + self._grow_serial,
            ))  # emits Pod/Add (api.events)
            created.append(uid)
        return created

    # -- the per-cycle entry --------------------------------------------
    def run(self, scheduler, cluster, pending, now, report, serve=None):
        """Solve + bind this cycle's rank gangs; returns the pending list
        with every rank-gang member removed (placed, parked, or waiting
        for quorum — rank pods NEVER fall through to the per-pod solve,
        which would undo the topology objective). `serve` routes the
        problem lowering through the resident serving engine
        (O(changed)) instead of a fresh cluster snapshot."""
        self._last = None
        moved = self.reconcile(cluster, now)
        if moved:
            # growth clones join THIS cycle's batch (convergence <= 2
            # cycles total); shrink deletions leave it. The rest of the
            # batch stays EXACTLY as the requeue gate admitted it — the
            # phase must not re-derive pending from the store, which
            # would smuggle parked pods past their backoff.
            created = [
                cluster.pods[uid]
                for m in moved.values() for uid in m["created"]
                if uid in cluster.pods
            ]
            pending = [p for p in pending if p.uid in cluster.pods]
            if created:
                pending = scheduler.sort_pending(
                    pending + created, cluster
                )
        prob = build_rank_gang_problem(
            cluster, pending, now, self.weights_name,
            self.network_topology_name, serve=serve,
        )
        if prob is None:
            return pending
        gangs = prob["gangs"]
        rank_nodes, admitted, placed_new = self._solve(prob)

        consumed = {p.uid for p in prob["consumed"]}
        remaining = [p for p in pending if p.uid not in consumed]
        max_cost, sum_cost = T.gang_cost_stats(
            rank_nodes, gangs.rank_mask, gangs.node_block, gangs.block_cost
        )
        stats = {}
        for g, name in enumerate(prob["gang_names"]):
            if not name:
                continue
            slot_uids = prob["uids"][g]
            pg = cluster.pod_groups.get(name)
            ledger = self.resident.setdefault(name, {})
            newly_bound = {}
            failed_uids = []
            for m, uid in enumerate(slot_uids):
                node_i = int(rank_nodes[g, m])
                was_resident = int(gangs.prev_assigned[g, m]) >= 0
                if was_resident:
                    ledger[uid] = prob["node_names"][node_i]
                    continue
                if node_i >= 0:
                    newly_bound[uid] = prob["node_names"][node_i]
                else:
                    failed_uids.append(uid)
            if bool(admitted[g]):
                for uid, node_name in newly_bound.items():
                    cluster.bind(uid, node_name, now)  # Pod/Update event
                    report.bound[uid] = node_name
                    ledger[uid] = node_name
                # elastic stragglers above quorum retry next cycle
                for uid in failed_uids:
                    report.failed.append(uid)
                    report.failed_by[uid] = RANK_GANG_PLACEMENT
                    cluster.mark_unschedulable(uid, now)
            else:
                # whole-gang rejection: zero partial ranks, standard
                # backoff parking (the PostFilter shape, host-side)
                for uid in list(newly_bound) + failed_uids:
                    report.failed.append(uid)
                    report.failed_by[uid] = RANK_GANG_PLACEMENT
                    cluster.mark_unschedulable(uid, now)
                if pg is not None:
                    cluster.gang_last_failure_ms[name] = now
                report.rejected_gangs.append(name)
            # prune ledger entries the store no longer backs (external
            # deletes/unbinds) — O(gang members), the changed set
            for uid in list(ledger):
                p = cluster.pods.get(uid)
                if p is None or p.node_name is None:
                    ledger.pop(uid, None)
            lo, desired, _ = E.elastic_bounds(pg) if pg is not None else (0, 0, 0)
            stats[name] = {
                "admitted": bool(admitted[g]),
                "placed_new": int(placed_new[g]),
                "resident": int((gangs.prev_assigned[g] >= 0).sum()),
                "desired": desired,
                "max_cost": int(max_cost[g]),
                "sum_cost": int(sum_cost[g]),
            }
        report.rank_gangs = stats
        self._last = {
            "gangs": gangs,
            "free0": prob["free0"],
            "eq_used0": prob["eq_used0"],
            "node_mask": prob["node_mask"],
            "rank_nodes": np.asarray(rank_nodes),
            "admitted": np.asarray(admitted),
        }
        return remaining

    def _solve(self, prob):
        gangs = prob["gangs"]
        want_np = self.host_twin or self.check_twin
        want_jit = not self.host_twin
        np_out = jit_out = None
        if want_np:
            np_out = T.gang_solve_np(
                gangs, prob["free0"], prob["eq_used0"], prob["node_mask"]
            )[:3]
        if want_jit and self.wave:
            from scheduler_plugins_tpu.gangs import waves as GW

            out = GW.wave_gang_solve(
                gangs, prob["free0"], prob["eq_used0"], prob["node_mask"],
                wave=self.wave_width or GW.DEFAULT_WAVE,
            )
            jit_out = tuple(np.asarray(x) for x in out[:3])
        elif want_jit:
            import jax
            import jax.numpy as jnp

            from scheduler_plugins_tpu.framework.plugin import SolverState

            if self._jit is None:
                self._jit = T.gang_solve_fn()
            state0 = SolverState(
                free=jnp.asarray(prob["free0"]),
                eq_used=jnp.asarray(prob["eq_used0"]),
                rank_nodes=jnp.asarray(gangs.prev_assigned),
            )
            gangs_j = jax.tree.map(jnp.asarray, gangs)
            out = self._jit(gangs_j, state0, jnp.asarray(prob["node_mask"]))
            jit_out = tuple(np.asarray(x) for x in out[:3])
        if want_jit and want_np:
            mismatches = int(
                (np.asarray(jit_out[0]) != np.asarray(np_out[0])).sum()
            ) + int((np.asarray(jit_out[1]) != np.asarray(np_out[1])).sum())
            self.last_drift = 0.0 if mismatches == 0 else (
                mismatches / max(np.asarray(jit_out[0]).size, 1)
            )
            self.max_drift = max(self.max_drift or 0.0, self.last_drift)
        return jit_out if want_jit else np_out

    # -- observability ---------------------------------------------------
    def annotate_record(self, rec) -> None:
        """Attach this cycle's gang solve — inputs AND outputs — to a
        flight-recorder record, so a recorded gang cycle replays
        bit-identically: re-running `gangs.topology.gang_solve_np` on the
        captured tensors must reproduce `rank_nodes` exactly
        (tests/test_gangs.py gates this). Elastic desired-width
        TRANSITIONS ride the manifest even on cycles with no gang solve
        (a shrink-only reconcile never reaches `_solve`): the tuner's
        counterfactual block-policy sweeps need the width timeline, not
        just the solved tensors."""
        if rec is None:
            return
        if self._elastic_transitions:
            rec.manifest["elastic_transitions"] = [
                dict(t) for t in self._elastic_transitions
            ]
        if self._last is None:
            return
        from scheduler_plugins_tpu.utils.flightrec import pack_pytree

        import dataclasses

        gangs = self._last["gangs"]
        spec = {
            "gangs": {
                f.name: np.asarray(getattr(gangs, f.name))
                for f in dataclasses.fields(gangs)
            },
            "free0": self._last["free0"],
            "eq_used0": self._last["eq_used0"],
            "node_mask": self._last["node_mask"],
            "rank_nodes": self._last["rank_nodes"],
            "admitted": self._last["admitted"],
        }
        rec.manifest["rank_gangs"] = pack_pytree(spec, rec.blobs)
