"""Rank-aware gang placement engine (ROADMAP item 4; docs/GANGS.md).

- `gangs.topology` — the topology-block waterfill gang solve (jit) and
  its bit-identical numpy sequential twin, plus block-cost lowering and
  placement-cost scoring.
- `gangs.elastic` — elastic (min, desired, max) gangs: highest-cost-first
  shrink selection (jit + twin) and the satisfaction objective.
- `gangs.phase` — the host `GangPhase` that `framework.cycle.run_cycle`
  runs ahead of the per-pod solve.
"""

from scheduler_plugins_tpu.gangs.elastic import (  # noqa: F401
    elastic_bounds,
    elastic_satisfaction,
    shrink_select,
    shrink_select_np,
)
from scheduler_plugins_tpu.gangs.phase import (  # noqa: F401
    GangPhase,
    RANK_GANG_PLACEMENT,
    build_rank_gang_problem,
)
from scheduler_plugins_tpu.gangs.topology import (  # noqa: F401
    RankGangState,
    build_block_cost,
    gang_cost_stats,
    gang_solve_body,
    gang_solve_fn,
    gang_solve_np,
    pair_costs,
    place_gang_one,
)
from scheduler_plugins_tpu.gangs.waves import (  # noqa: F401
    wave_gang_solve,
    wave_solve_body,
    wave_solve_fn,
)
