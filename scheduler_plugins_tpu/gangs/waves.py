"""Wave-batched gang solve: independent gangs solved together, bit-exact.

PR 10's `gang_solve_body` is a sequential `lax.scan` over gangs — at
Tesserae scale (arxiv 2508.04953: placement policy work must scale with
the cluster) a G-length scan of per-gang block scoring is the bottleneck
the cluster-life bench named (ROADMAP item 3). This module batches gangs
into **waves**: one jit solves a whole wave of gangs in parallel against
the wave-start state (`gangs.topology.place_gang_one`, the SAME per-gang
body the sequential scan runs), then a host validator walks the wave in
queue order, committing every lane whose speculative result is provably
identical to the sequential solve and resolving the conflicted lanes
in place with the shared per-gang host body (`place_gang_np` — the
numpy twin's own step). A wave therefore costs exactly ONE device
dispatch however the workload serializes; G gangs always take
ceil(G/W) dispatches.

Why the accepted prefix is bit-exact (docs/GANGS.md "conflict
detection") — gang g's solve against the sequential state S_{i-1}
equals its wave-start solve against S0 because commits only DECREASE
free and only INCREASE quota usage, which makes the first-fit scan
monotone. Two host-side checks per gang, against the commits accepted
earlier in the wave:

1. **Primary-block invariance** — block spill order depends only on the
   primary block (the cost matrix is static). Resident-anchored gangs
   pick their primary from `prev_assigned` (free-independent); for the
   rest the validator recomputes packed-rank capacity under the
   accepted block-level free deltas (`packed_rank_capacity_np` — the
   solve's own scoring, shared with the numpy twin) and requires the
   argmax to be unchanged, which pins the whole node order.
2. **Choice replay** — with the node order pinned, replay g's tentative
   (PRE-revert) choices against the current host state: every node
   ordered before a chosen node was infeasible at S0 under the gang's
   own in-scan depletion, and free(S_{i-1}) <= free(S0) pointwise, so
   it STAYS infeasible — the sequential scan can only pick the same
   node or fail. The replay therefore just re-checks, rank by rank in
   scan order, that the chosen node still fits the rank's demand and
   the quota row still clears (committing both into the simulation as
   it goes). A rank that found NO node at S0 finds none under smaller
   free either, so dead-prefix semantics replay for free. Quorum-failed
   gangs revalidate the same way — their no-op revert is only
   guaranteed equal if the whole scan replays.

The first gang of every wave validates trivially (no commits yet, so
its wave-start state IS its sequential state). A conflicted lane is
re-solved host-side against the committed state — bit-exact by
construction (it IS the twin's step) — and validation continues, so
the worst case degrades to the numpy sequential twin plus G/W device
dispatches, while the common case (steady-state reconcile: gangs
anchored across blocks, contention localized) validates most lanes and
turns G sequential scan steps into G/W parallel dispatches.

`wave_gang_solve` is gated bit-identical to `gang_solve_np` (and hence
to the sequential jit scan) by tests/test_differential.py; the mega
bench (bench.py --config 12) runs it at 10k nodes x 1k gangs. The wave
axis optionally shards over a ("gangs",) device mesh via shard_map —
free/eq/problem tensors replicate, gang lanes shard, zero collectives.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from scheduler_plugins_tpu.gangs import topology as T
from scheduler_plugins_tpu.utils.intmath import bucket_size

I64 = np.int64
I32 = np.int32

#: mesh axis name for the wave (gang-lane) dimension — NOT the node axis
#: (GL009 guards "nodes"; the wave solve never gathers over nodes at all)
GANGS_AXIS = "gangs"

#: default wave width: lanes solved per parallel dispatch. Bounds the
#: worst-case wasted work (every consecutive gang conflicting costs one
#: W-lane dispatch per accepted gang) while keeping the dispatch big
#: enough to amortize — the mega bench's acceptance runs are ~W long.
DEFAULT_WAVE = 64


def wave_solve_body(gangs: T.RankGangState, free, eq_used, node_mask, ids):
    """One wave: solve `ids` (W,) gangs independently against the SAME
    (`free`, `eq_used`) wave-start state — a vmap of the sequential
    scan's own per-gang body (`topology.place_gang_one`). Returns
    per-lane (choices (W, M), admitted (W,), q_new (W,), primary (W,),
    has_res (W,)); the post-placement free/eq of each lane stay internal
    (the host validator recommits accepted lanes exactly)."""
    import jax

    def lane(g):
        (choices, admitted, q_new, _free_l, _eq_l, _resident, primary,
         has_res) = T.place_gang_one(gangs, g, free, eq_used, node_mask)
        return choices, admitted, q_new, primary, has_res

    return jax.vmap(lane)(ids)


#: (shape-key, sharded) -> jitted wave program; equal shapes share one
#: compile like every other padded program in this repo
_WAVE_PROGRAMS: dict = {}


def wave_solve_fn(mesh=None):
    """The jitted wave program — one constructor shared by the solve
    loop, the bench, and the AOT/jaxpr certification gates
    (tools/tpu_lower.py `wave_gang_solve`). With a ("gangs",) `mesh` the
    wave axis shards over the devices via shard_map (problem tensors and
    the free/eq state replicate; the per-lane solve needs no
    collectives), so a wave of W gangs runs W/S per device."""
    import jax

    from scheduler_plugins_tpu.utils import observability as obs

    key = None if mesh is None else tuple(mesh.devices.flat)
    if key in _WAVE_PROGRAMS:
        return _WAVE_PROGRAMS[key]
    if mesh is None:
        fn = jax.jit(wave_solve_body)
    else:
        from jax.experimental.shard_map import shard_map
        from jax.sharding import PartitionSpec as P

        lanes = P(GANGS_AXIS)
        rep = P()

        def sharded(gangs, free, eq_used, node_mask, ids):
            body = shard_map(
                wave_solve_body,
                mesh=mesh,
                in_specs=(
                    jax.tree.map(lambda _: rep, gangs), rep, rep, rep,
                    lanes,
                ),
                out_specs=(lanes, lanes, lanes, lanes, lanes),
                check_rep=False,
            )
            return body(gangs, free, eq_used, node_mask, ids)

        fn = jax.jit(sharded)
    _WAVE_PROGRAMS[key] = obs.compile_watch(fn, program="wave_gang_solve")
    return _WAVE_PROGRAMS[key]


def _primary_invariant(gangs, g, block_free, packed_dev_primary):
    """True when gang g's primary-block choice is unchanged under the
    accepted commits' block deltas: recompute packed-rank capacity with
    the solve's own scoring (`packed_rank_capacity_np`) and compare the
    argmax to the device solve's wave-start primary."""
    dem = np.where(
        (gangs.rank_mask[g] & (gangs.prev_assigned[g] < 0))[:, None],
        gangs.rank_req[g], 0,
    )
    cumdem = np.cumsum(dem.astype(np.float64), axis=0)
    packed = T.packed_rank_capacity_np(cumdem, block_free)
    return int(np.argmax(packed)) == int(packed_dev_primary)


def wave_gang_solve(gangs: T.RankGangState, free0, eq_used0, node_mask,
                    wave: int = DEFAULT_WAVE, mesh=None,
                    stats: Optional[dict] = None):
    """Wave-batched gang solve, bit-identical to `gang_solve_np` /
    `gang_solve_body` (see module doc for the proof sketch). Returns
    (rank_nodes (G, M) int32, admitted (G,) bool, placed_new (G,) int32,
    free (N, R) int64, eq_used (Q, R) int64) — the numpy twin's exact
    output contract. `stats`, when given, collects {"waves", "accepted"}
    (dispatch count and per-wave acceptance runs)."""
    import jax.numpy as jnp

    rank_req = np.asarray(gangs.rank_req)
    rank_mask = np.asarray(gangs.rank_mask)
    prev = np.asarray(gangs.prev_assigned)
    gang_ns = np.asarray(gangs.gang_ns)
    gang_mask = np.asarray(gangs.gang_mask)
    node_block = np.asarray(gangs.node_block)
    quota_has = np.asarray(gangs.quota_has)
    node_mask_np = np.asarray(node_mask)

    G, M, R = rank_req.shape
    B = np.asarray(gangs.block_cost).shape[0]
    blocked = (node_block >= 0) & node_mask_np
    blk = np.maximum(node_block, 0)

    free = np.asarray(free0).astype(I64).copy()
    eq_used = np.asarray(eq_used0).astype(I64).copy()
    rank_nodes = prev.astype(I32).copy()
    admitted = np.zeros(G, bool)
    placed_new = np.zeros(G, I32)

    # queue order over the REAL gangs; pad slots (mask False) never solve
    # in the sequential scan either — their rows stay resident-only
    order = [g for g in range(G) if gang_mask[g]]
    for g in range(G):
        if not gang_mask[g]:
            rank_nodes[g] = np.where(rank_mask[g] & (prev[g] >= 0),
                                     prev[g], -1)

    fn = wave_solve_fn(mesh)
    W = wave
    if mesh is not None:
        n_dev = int(np.prod(mesh.devices.shape))
        W = max(W, n_dev)
        W = ((W + n_dev - 1) // n_dev) * n_dev
    # problem tensors staged to device ONCE — every wave re-reads them,
    # and re-staging (G, M, R) rank tensors per dispatch would double the
    # per-wave cost (measured; docs/SCALING.md)
    import jax

    gangs_dev = jax.tree.map(jnp.asarray, gangs)
    mask_dev = jnp.asarray(node_mask_np)
    quota_max = np.asarray(gangs.quota_max)

    i = 0
    n_waves = 0
    accepts: list[int] = []
    host_solves = 0
    while i < len(order):
        batch = order[i:i + W]
        ids = np.zeros(W, I32)  # pad lanes re-solve gang batch[0]: cheap,
        ids[:len(batch)] = batch  # ignored by the host acceptance loop
        ids[len(batch):] = batch[0]
        choices, adm, q_new, primary, has_res = (
            np.asarray(x) for x in fn(
                gangs_dev, jnp.asarray(free), jnp.asarray(eq_used),
                mask_dev, jnp.asarray(ids),
            )
        )
        n_waves += 1

        # wave-start block free totals (the scoring input), maintained
        # under accepted commits for the primary-invariance check
        freec = np.where(node_mask_np[:, None], np.clip(free, 0, None), 0)
        block_free = np.zeros((B, R), I64)
        np.add.at(block_free, blk[blocked], freec[blocked])

        accepted = 0
        dirty = False  # any committed placement since the wave dispatched
        for j, g in enumerate(batch):
            tentative = [
                (m, int(choices[j, m])) for m in range(M)
                if choices[j, m] >= 0
            ]
            ns = int(gang_ns[g])
            has_quota = ns >= 0 and bool(quota_has[ns])
            valid = True
            if dirty:  # the first lane of a wave validates trivially
                # 1. primary-block invariance pins the node order
                if not bool(has_res[j]) and not _primary_invariant(
                    gangs, g, block_free, primary[j]
                ):
                    valid = False
                else:
                    # 2. choice replay: each tentatively chosen node must
                    # still fit its rank's demand under the committed
                    # state (+ this gang's own earlier ranks), and the
                    # quota row must still clear — monotonicity covers
                    # everything else (see module doc)
                    sim_free: dict[int, np.ndarray] = {}
                    sim_eq = eq_used[ns].copy() if has_quota else None
                    for m, n in tentative:
                        d = rank_req[g, m]
                        fvec = sim_free.get(n)
                        if fvec is None:
                            fvec = free[n].copy()
                        if not (fvec >= d).all() or (
                            has_quota
                            and not (sim_eq + d <= quota_max[ns]).all()
                        ):
                            valid = False
                            break
                        sim_free[n] = fvec - d
                        if has_quota:
                            sim_eq = sim_eq + d
            if not valid:
                # conflicted lane: the wave-start speculation is stale —
                # resolve THIS gang exactly with the shared per-gang host
                # body (the numpy twin's own step) against the committed
                # state, and keep consuming the wave. No re-dispatch: a
                # wave costs exactly one device solve regardless of how
                # the workload serializes.
                host_solves += 1
                c_np, ok, qn, free_l, eq_l, resident = T.place_gang_np(
                    gangs, g, free, eq_used, node_mask_np
                )
                admitted[g] = ok
                placed_new[g] = qn if ok else 0
                row = np.where(resident, prev[g], c_np if ok else -1)
                rank_nodes[g] = row.astype(I32)
                if ok:
                    placed = [
                        (m, int(c_np[m])) for m in range(M) if c_np[m] >= 0
                    ]
                    free = free_l
                    eq_used = eq_l
                    for m, n in placed:
                        if blocked[n]:
                            block_free[blk[n]] -= rank_req[g, m]
                    if placed:
                        dirty = True
                continue
            # validated lane: commit the device solve — EXACTLY the
            # sequential semantics (revert on quorum failure — zero
            # partial ranks). A reverted gang committed NOTHING, so later
            # lanes only ever validate against genuinely committed state.
            ok = bool(adm[j])
            admitted[g] = ok
            placed_new[g] = int(q_new[j]) if ok else 0
            resident = rank_mask[g] & (prev[g] >= 0)
            row = np.where(
                resident, prev[g],
                choices[j].astype(I32) if ok else I32(-1),
            )
            rank_nodes[g] = row
            if ok:
                for m, n in tentative:
                    d = rank_req[g, m]
                    free[n] -= d
                    if blocked[n]:
                        block_free[blk[n]] -= d
                    if has_quota:
                        eq_used[ns] += d
                if tentative:
                    dirty = True
            accepted += 1
        accepts.append(accepted)
        i += len(batch)

    if stats is not None:
        stats["waves"] = n_waves
        stats["accepted"] = accepts
        stats["host_solves"] = host_solves
    return rank_nodes, admitted, placed_new, free, eq_used
