"""Elastic DL jobs: gangs with (min, desired, max) replica bounds.

Tesserae (arxiv 2508.04953) treats deep-learning jobs as gangs that
grow/shrink between scheduling rounds. Here an elastic gang is a
`PodGroup` with `rank_aware=True` and `desired_replicas`/`max_replicas`
set (`min_member` stays the hard quorum). The transitions:

- **shrink** (live members > desired): release the HIGHEST-COST ranks
  first — a rank's cost is its max inter-rank pair cost against the
  surviving set, so the topology outliers leave before well-packed
  ranks; ties release the highest rank index (the launcher, rank 0,
  leaves last). `shrink_select` is the jittable selection (registered
  with the AOT/jaxpr gates as `elastic_shrink`); `shrink_select_np` is
  its bit-identical host twin — `GangPhase.reconcile` applies the host
  twin's verdict through the store mutators, so the deletes emit
  `api.events.POD_DELETE` like any other removal.
- **grow** (live + pending members < desired): clone new member pods
  from the gang's rank template (its lowest-ranked live member). The
  clones enter the next cycle's pending batch, and the topology solve
  anchors them on the block already holding the gang's residents
  (`gangs.topology.gang_solve_body` primary-block rule) — so growth is
  an O(changed) delta (new pods + their binds ride the store's delta
  sink into the serving engine), never a gang re-placement.

The elastic state machine (docs/GANGS.md): Stable -> (desired bump) ->
Growing -> Stable in <= 2 cycles (reconcile creates, the next solve
places); Stable -> (desired drop) -> Shrinking -> Stable in 1 cycle
(reconcile deletes immediately). `elastic_satisfaction` scores the fleet:
mean over elastic gangs of live/desired, the quality objective
`tuning.quality` exports.
"""

from __future__ import annotations

import numpy as np

from scheduler_plugins_tpu.ops.network import MAX_COST

I32 = np.int32
I64 = np.int64


def rank_release_keys(rank_nodes, live, node_block, block_cost):
    """(G, M) int64 release-priority keys: `max-pair-cost * M + rank
    index` for live ranks (unique keys — highest key releases first),
    -1 for dead slots. Shared by the jit and numpy selections so the two
    cannot disagree on ordering."""
    import jax.numpy as jnp

    G, M = rank_nodes.shape
    nb = jnp.where(live, node_block[jnp.maximum(rank_nodes, 0)], -1)
    known = nb >= 0
    nb0 = jnp.maximum(nb, 0)
    bc = block_cost[nb0[:, :, None], nb0[:, None, :]].astype(jnp.int64)
    cost = jnp.where(known[:, :, None] & known[:, None, :], bc, MAX_COST)
    same_node = rank_nodes[:, :, None] == rank_nodes[:, None, :]
    cost = jnp.where(same_node, 0, cost)
    valid = live[:, :, None] & live[:, None, :]
    valid &= ~jnp.eye(M, dtype=bool)[None]
    per_rank = jnp.max(jnp.where(valid, cost, 0), axis=2)  # (G, M)
    keys = per_rank * M + jnp.arange(M)
    return jnp.where(live, keys, jnp.int64(-1))


def shrink_select(rank_nodes, live, node_block, block_cost, n_release):
    """(G, M) bool release mask: for each gang, mark the `n_release[g]`
    live ranks with the highest release keys (highest max inter-rank
    cost first, highest index tie-break). Jittable — the `elastic_shrink`
    program of the certification gates; `rank_nodes` is the resident
    rank-assignment carry (`SolverState.rank_nodes`)."""
    import jax.numpy as jnp

    keys = rank_release_keys(rank_nodes, live, node_block, block_cost)
    M = rank_nodes.shape[1]
    # rank of each slot in descending key order (0 = released first)
    order = jnp.argsort(-keys, axis=1)  # keys unique among live slots
    pos = jnp.zeros_like(order).at[
        jnp.arange(order.shape[0])[:, None], order
    ].set(jnp.arange(M)[None, :].repeat(order.shape[0], axis=0))
    return live & (pos < n_release[:, None])


def shrink_select_np(rank_nodes, live, node_block, block_cost, n_release):
    """Bit-identical host twin of `shrink_select` (the one `GangPhase`
    actually applies — deletions are host mutations)."""
    rank_nodes = np.asarray(rank_nodes)
    live = np.asarray(live)
    node_block = np.asarray(node_block)
    block_cost = np.asarray(block_cost)
    n_release = np.asarray(n_release)
    G, M = rank_nodes.shape
    nb = np.where(live, node_block[np.maximum(rank_nodes, 0)], -1)
    known = nb >= 0
    nb0 = np.maximum(nb, 0)
    bc = block_cost[nb0[:, :, None], nb0[:, None, :]].astype(I64)
    cost = np.where(known[:, :, None] & known[:, None, :], bc, MAX_COST)
    same_node = rank_nodes[:, :, None] == rank_nodes[:, None, :]
    cost = np.where(same_node, 0, cost)
    valid = live[:, :, None] & live[:, None, :]
    valid &= ~np.eye(M, dtype=bool)[None]
    per_rank = np.max(np.where(valid, cost, 0), axis=2)
    keys = np.where(live, per_rank * M + np.arange(M), -1)
    order = np.argsort(-keys, axis=1, kind="stable")
    pos = np.zeros_like(order)
    np.put_along_axis(
        pos, order, np.broadcast_to(np.arange(M), (G, M)).copy(), axis=1
    )
    return live & (pos < n_release[:, None])


def elastic_bounds(pg):
    """(min, desired, max) replica bounds for a PodGroup: `min_member` is
    the quorum floor; `desired_replicas` defaults to min (rigid gang);
    `max_replicas` caps desired. Clamping mirrors upstream scale
    subresource semantics (desired is clamped into [min, max]); a
    misconfigured `max_replicas < min_member` saturates at the quorum
    floor — shrinking a gang below its own quorum would manufacture the
    exact partial-rank state the solve exists to prevent."""
    lo = int(pg.min_member)
    desired = pg.desired_replicas if pg.desired_replicas is not None else lo
    hi = pg.max_replicas if pg.max_replicas is not None else max(desired, lo)
    hi = max(int(hi), lo)
    return lo, int(min(max(desired, lo), hi)), hi


def elastic_satisfaction(live_counts, desired_counts) -> float:
    """Mean over elastic gangs of min(live/desired, 1) — 1.0 when every
    elastic gang runs at its desired width (the Tesserae satisfaction
    fraction). Gangs with desired 0 are skipped; empty input -> 1.0."""
    live_counts = np.asarray(live_counts, np.float64)
    desired_counts = np.asarray(desired_counts, np.float64)
    mask = desired_counts > 0
    if not mask.any():
        return 1.0
    frac = np.minimum(live_counts[mask] / desired_counts[mask], 1.0)
    return float(frac.mean())
