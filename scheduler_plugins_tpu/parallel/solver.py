"""Sharded batched solve — the multi-chip scheduling step.

One "step" = the full pipeline over a pending wave, batched over pods and
sharded over the mesh:

    PreFilter (gang/quota admission against CARRIED usage, vmapped over pods)
 -> Filter (resource fit + plugin masks, (P, N) sharded pods x nodes)
 -> Score + Normalize (weighted sum)
 -> wave conflict resolution (queue-order admission per node AND per
    namespace quota, exact prefix sums)
 -> Permit (gang quorum as a segment reduction)

Node-axis reductions (argmax, fit all-reduce) and pod-axis prefix sums become
XLA collectives over ICI; side tables (quota, gangs) are replicated and their
segment sums psum naturally. Placements within a wave may differ from the
bit-faithful sequential scan (`Scheduler.solve`) exactly as documented in
`ops.assign.waterfill_assign` — this is the throughput path; the sequential
path remains the parity gate. Hard constraints (fit, quota Max/aggregate-Min,
gang quorum Wait) are enforced in both paths.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from scheduler_plugins_tpu.ops.allocatable import (
    MODE_LEAST,
    allocatable_scores,
    demote_scores_int32,
)
from scheduler_plugins_tpu.ops.assign import waterfill_assign_targeted
from scheduler_plugins_tpu.ops.fit import fits, free_capacity, pod_fit_demand
from scheduler_plugins_tpu.ops.gang import gang_admit
from scheduler_plugins_tpu.ops.quota import quota_admit
from scheduler_plugins_tpu.utils import observability as obs


def nominated_aggregates_batch(quota):
    """(P, R) nominee aggregates from the (M, P) masks x (M, R) requests."""
    in_eq = (
        quota.nom_in_eq_mask.astype(jnp.float64).T
        @ quota.nom_req.astype(jnp.float64)
    ).astype(jnp.int64)
    total = (
        quota.nom_total_mask.astype(jnp.float64).T
        @ quota.nom_req.astype(jnp.float64)
    ).astype(jnp.int64)
    return in_eq, total


def finalize_assignment(assignment, snap):
    """Shared tail: queue-order namespace quota enforcement + gang quorum
    Permit over the final placements (used by both batched solvers)."""
    if snap.quota is not None:
        placed = assignment >= 0
        quota_ok = _namespace_quota_prefix_ok(placed, snap, snap.quota.used)
        assignment = jnp.where(placed & ~quota_ok, -1, assignment)
    wait = jnp.zeros(snap.num_pods, bool)
    if snap.gangs is not None:
        placed = (assignment >= 0).astype(jnp.int32)
        gang = snap.pods.gang
        in_gang = gang >= 0
        G = snap.gangs.min_member.shape[0]
        sched = jnp.zeros(G, jnp.int32).at[jnp.maximum(gang, 0)].add(
            jnp.where(in_gang, placed, 0)
        )
        quorum = snap.gangs.assigned + sched >= snap.gangs.min_member
        pod_quorum = jnp.where(in_gang, quorum[jnp.maximum(gang, 0)], True)
        wait = (assignment >= 0) & ~pod_quorum
    return assignment, wait


def batch_admission(snap, free, eq_used=None):
    """(P,) PreFilter verdicts for the batch against the carried state
    (gang membership/backoff/MinResources + elastic quota)."""
    ok = snap.pods.mask & ~snap.pods.gated
    if snap.gangs is not None:
        gang_ok = jax.vmap(lambda g: gang_admit(snap.gangs, free, g))(
            snap.pods.gang
        )
        ok &= gang_ok
    if snap.quota is not None:
        used = eq_used if eq_used is not None else snap.quota.used
        # (P, R) nominee aggregates from the (M, P) tables — admission runs
        # before any placement here, so the static view is exact. float64
        # matmul avoids an (M, P, R) temporary AND the unsupported s64
        # dot_general on TPU (exact below 2^53).
        nom_in_eq, nom_total = nominated_aggregates_batch(snap.quota)
        quota_ok = jax.vmap(
            lambda ns, req, in_eq, total: quota_admit(
                used,
                snap.quota.min,
                snap.quota.max,
                snap.quota.has_quota,
                ns,
                req,
                in_eq,
                total,
            )
        )(snap.pods.ns, snap.pods.req, nom_in_eq, nom_total)
        ok &= quota_ok
    return ok


def _namespace_quota_prefix_ok_scan(assignment_order_ok, snap, eq_used):
    """(P,) queue-order quota admission, exact: a `lax.scan` threads admitted
    usage through the batch in queue order, so a pod is charged against Max
    (own namespace) and the aggregate-Min pool only if it was itself admitted
    — identical semantics to `quota_commit` threading through the sequential
    scan (no over-approximation from rejected pods' requests).

    Reference implementation: O(P) serial steps, which on TPU costs the
    per-step scan latency P times over. The production path is the
    reject-first-violator fixpoint below (`_namespace_quota_prefix_ok`),
    which is bit-identical (tests/test_parallel.py gates it) with serial
    depth = the number of actually-rejected pods instead of P."""
    quota = snap.quota
    agg_min = jnp.sum(jnp.where(quota.has_quota[:, None], quota.min, 0), axis=0)
    agg_used0 = jnp.sum(jnp.where(quota.has_quota[:, None], eq_used, 0), axis=0)

    def step(carry, x):
        used, agg_used = carry
        ns_p, req_p, active = x
        has_q = quota.has_quota[ns_p]
        own_ok = jnp.all(used[ns_p] + req_p <= quota.max[ns_p])
        agg_ok = jnp.all(agg_used + req_p <= agg_min)
        ok = ~has_q | (own_ok & agg_ok)
        add = jnp.where(active & has_q & ok, req_p, 0)
        return (used.at[ns_p].add(add), agg_used + add), ok

    (_, _), ok = jax.lax.scan(
        step,
        (eq_used, agg_used0),
        (snap.pods.ns, snap.pods.req, assignment_order_ok),
    )
    return ok


def _namespace_quota_prefix_ok(assignment_order_ok, snap, eq_used):
    """(P,) queue-order quota admission as a reject-first-violator fixpoint —
    the parallel reformulation of `_namespace_quota_prefix_ok_scan` with
    identical outputs on every pod.

    Why it is exact: evaluate every pod's Max/aggregate-Min checks against
    prefix sums over the currently-assumed-admitted set. Pods before the
    queue-FIRST violator see only truly-admitted pods in their prefixes (a
    kept pod passing with an over-approximated prefix also passes with the
    true, smaller one), so the first violator's own prefix is exact and its
    rejection is final. Removing it only shrinks later prefixes, so
    violators surface in increasing queue order and each `lax.while_loop`
    trip resolves one true rejection with O(log P)-depth parallel work
    (1-D float64 cumsums — exact below 2^53, the repo-wide quantity bound —
    plus a sorted-segment rebase; no O(P) serial chain). Trip count is the
    number of quota-rejected pods in the batch (typically ~0), worst case
    the candidate count.

    Mirrors /root/reference/pkg/capacityscheduling/capacity_scheduling.go
    PreFilter semantics (208-282) applied in queue order at Reserve time."""
    from scheduler_plugins_tpu.ops.assign import _segment_prefix

    quota = snap.quota
    ns = snap.pods.ns
    P = ns.shape[0]
    has_q = quota.has_quota[ns]
    cand = assignment_order_ok & has_q
    reqf = snap.pods.req.astype(jnp.float64)
    used0_ns = eq_used[ns].astype(jnp.float64)
    max_ns = quota.max[ns].astype(jnp.float64)
    agg_min = jnp.sum(
        jnp.where(quota.has_quota[:, None], quota.min, 0), axis=0
    ).astype(jnp.float64)
    agg_used0 = jnp.sum(
        jnp.where(quota.has_quota[:, None], eq_used, 0), axis=0
    ).astype(jnp.float64)

    # static queue-stable namespace grouping: sort by (ns, queue index) so
    # per-namespace prefixes are 1-D segment cumsums (CLAUDE.md: no 2-D int64
    # cumsums on TPU; float64 is exact here)
    order = jnp.argsort(ns.astype(jnp.int64) * P + jnp.arange(P))
    ns_sorted = ns[order]
    first = jnp.concatenate(
        [jnp.ones(1, bool), ns_sorted[1:] != ns_sorted[:-1]]
    )
    idx = jnp.arange(P)

    def verdicts(admitted):
        """(own_ok & agg_ok) per pod from EXCLUSIVE prefixes over `admitted`
        — the scan's view at each pod's own step."""
        charge = jnp.where(admitted[:, None], reqf, 0.0)
        incl_own_sorted = _segment_prefix(charge[order], first)
        excl_own = jnp.zeros_like(charge).at[order].set(
            incl_own_sorted - charge[order]
        )
        excl_agg = jnp.cumsum(charge, axis=0) - charge
        own_ok = jnp.all(used0_ns + excl_own + reqf <= max_ns, axis=1)
        agg_ok = jnp.all(agg_used0 + excl_agg + reqf <= agg_min, axis=1)
        return own_ok & agg_ok

    def first_violator(admitted):
        viol = admitted & ~verdicts(admitted)
        return jnp.min(jnp.where(viol, idx, P))

    def cond(carry):
        _, v = carry
        return v < P

    def body(carry):
        admitted, v = carry
        admitted = admitted & (idx != v)
        return admitted, first_violator(admitted)

    admitted0 = cand
    admitted, _ = jax.lax.while_loop(
        cond, body, (admitted0, first_violator(admitted0))
    )
    return ~has_q | verdicts(admitted)


def batch_solve(snap, weights, max_waves: int = 8, collect_stats: bool = False):
    """Full batched step: admission -> fit -> allocatable score -> wave
    assignment -> quota prefix enforcement -> gang quorum.
    Returns (assignment (P,), admitted (P,), wait (P,)), plus the per-wave
    occupancy stats dict when `collect_stats` (see
    `ops.assign.waterfill_assign_stateful`).

    Allocatable scores are STATIC per node (the reference scores
    allocatable, not free capacity — resource_allocation.go:49-76), so the
    targeted waterfill applies: per-wave work is O(P·R) target-row gathers,
    not the (P, N) feasibility/score matrix (which at north-star scale is
    ~4B compares per wave). Unschedulable nodes are excluded by zeroing
    their free capacity for the solve (a masked node can then never admit
    any pod — pod demands include a pods-slot of 1)."""
    free0 = free_capacity(snap.nodes.alloc, snap.nodes.requested)
    admitted = batch_admission(snap, free0)

    raw = demote_scores_int32(
        allocatable_scores(snap.nodes.alloc, weights, MODE_LEAST)
    )
    solve_free0 = jnp.where(snap.nodes.mask[:, None], free0, 0)
    out = waterfill_assign_targeted(
        raw.astype(jnp.int64), snap.pods.req, admitted, solve_free0,
        max_waves=max_waves, collect_stats=collect_stats,
    )
    assignment = out[0]

    assignment, wait = finalize_assignment(assignment, snap)
    if collect_stats:
        return assignment, admitted, wait, out[2]
    return assignment, admitted, wait


def packing_solve(snap, weights, pack_aux, max_waves: int = 8,
                  mover_cap: int = 128, collect_stats: bool = False):
    """`batch_solve`'s flagship semantics with the PACKING refinement
    appended (the third solve mode — ROADMAP item 1, ISSUE 14): the same
    admission -> static allocatable ranking -> targeted waterfill wave
    placement, then `ops.packing.packing_refine` consolidation rounds
    over the wave output, then the shared `finalize_assignment` tail.
    `pack_aux` is the (4,) traced knob vector (`ops.packing
    .pack_aux_vector`: iterations, price_weight, temperature, decay) —
    one compile serves every iteration budget, and budget 0 is
    bit-identical to `batch_solve` by construction (the refinement loop
    never runs).

    Hard constraints hold exactly as on the wave path: refinement moves
    never change WHICH pods are placed (fit holds per move via the
    sorted-segment admission), so the queue-order quota prefix and gang
    quorum families see the identical placed set. Returns
    (assignment, admitted, wait[, stats]) with stats =
    {waterfill stats, "packing": {rounds, moves, emptied}}."""
    from scheduler_plugins_tpu.ops.packing import packing_refine

    free0 = free_capacity(snap.nodes.alloc, snap.nodes.requested)
    admitted = batch_admission(snap, free0)
    raw = demote_scores_int32(
        allocatable_scores(snap.nodes.alloc, weights, MODE_LEAST)
    ).astype(jnp.int64)
    solve_free0 = jnp.where(snap.nodes.mask[:, None], free0, 0)
    out = waterfill_assign_targeted(
        raw, snap.pods.req, admitted, solve_free0,
        max_waves=max_waves, collect_stats=collect_stats,
    )
    assignment, free_w = out[0], out[1]
    assignment, free_p, pstats = packing_refine(
        raw, snap.pods.req, admitted, snap.nodes.alloc, snap.nodes.mask,
        free_w, assignment, pack_aux, mover_cap=mover_cap,
    )
    assignment, wait = finalize_assignment(assignment, snap)
    if collect_stats:
        return assignment, admitted, wait, {**out[2], "packing": pstats}
    return assignment, admitted, wait


#: the one jitted flagship packing program (bench config 13 + the AOT
#: manifests share this trace cache; knobs ride the traced pack_aux arg,
#: so sweeping budgets never recompiles)
_PACKING_SOLVE_JIT: dict = {}


def packing_solve_fn(max_waves: int = 8, mover_cap: int = 128,
                     collect_stats: bool = True):
    """The memoized jitted `packing_solve` entry:
    fn(snap, weights, pack_aux) — the program bench config 13 runs and
    `tools/tpu_lower.py` AOT-lowers (the same seam discipline as
    `profile_batch_fn`)."""
    key = (max_waves, mover_cap, collect_stats)
    fn = _PACKING_SOLVE_JIT.get(key)
    if fn is None:
        fn = _PACKING_SOLVE_JIT[key] = obs.compile_watch(
            jax.jit(
                lambda snap, weights, pack_aux: packing_solve(
                    snap, weights, pack_aux, max_waves=max_waves,
                    mover_cap=mover_cap, collect_stats=collect_stats,
                )
            ),
            program="packing_solve",
        )
    return fn


class PackingSolveView:
    """The (assignment, admitted, wait) triple a packing-mode solve
    returns to the cycle — deliberately NOT a `SolveResult`: the flight
    recorder keys replay semantics off the result type, and packing
    placements must never be recorded as sequential-parity outputs.
    `stats` carries the refinement counters when collected."""

    __slots__ = ("assignment", "admitted", "wait", "failed_plugin", "stats")

    def __init__(self, assignment, admitted, wait, stats=None):
        self.assignment = assignment
        self.admitted = admitted
        self.wait = wait
        self.failed_plugin = None
        self.stats = stats


def packing_profile_fn(scheduler, snap, mover_cap: int = 128,
                       max_waves: int = 8):
    """(jitted_fn, args) for the packing-mode PROFILE solve — the
    `Scheduler.solve(mode="packing")` body: the targeted fast-path head
    (vmapped PreFilter admission + the single scoring plugin's static
    node ranking, `fast_solve_head`), the wave waterfill, the packing
    refinement, and the shared finalize tail. Packing knobs ride the
    traced `pack_aux` argument built from `profile.packing` per solve —
    the aux-channel discipline, so tuning the budget/price online never
    recompiles.

    Packing mode requires the targeted fast-path profile shape (ONE
    pod-invariant scoring plugin, no per-(pod, node) filters —
    `fast_path_scoring`, the same gate the streamed pipeline uses):
    refinement moves re-place pods on any fitting node, which is only
    sound when resource fit is the sole per-node constraint. Profiles
    outside the gate raise TypeError (load_profile validates the same
    rule at config time)."""
    from scheduler_plugins_tpu.ops.packing import packing_refine
    from scheduler_plugins_tpu.utils import sanitize

    plugins = tuple(scheduler.profile.plugins)
    scoring_p = fast_path_scoring(plugins)
    if scoring_p is None:
        raise TypeError(
            "packing solve mode requires a profile on the targeted "
            "fast path (one pod-invariant scoring plugin, no filters) — "
            f"profile {scheduler.profile.name!r} does not qualify"
        )
    state0 = _donation_safe_state(scheduler.initial_state(snap))
    auxes = tuple(p.aux() for p in plugins)
    pack_aux = scheduler.profile.packing.aux()

    def pack_batch(snap, state0, auxes, pack_aux):
        admitted, raw, free0 = fast_solve_head(
            plugins, scoring_p, snap, state0, auxes
        )
        out = waterfill_assign_targeted(
            raw, snap.pods.req, admitted, free0, max_waves=max_waves,
        )
        assignment, free_p, pstats = packing_refine(
            raw, snap.pods.req, admitted, snap.nodes.alloc,
            snap.nodes.mask, out[1], out[0], pack_aux,
            mover_cap=mover_cap,
        )
        assignment, wait = finalize_assignment(assignment, snap)
        return assignment, admitted, wait, pstats

    key = ("profile_packing", max_waves, mover_cap,
           sanitize.enabled()) + scheduler.weights_key() + tuple(
        p.static_key() for p in plugins
    )
    cache = scheduler._solve_cache
    if key not in cache:
        if sanitize.enabled():
            fn = sanitize.checkified(pack_batch, program="profile_packing")
        else:
            fn = _wrap_donated(jax.jit(pack_batch, donate_argnums=(1,)))
        cache[key] = obs.compile_watch(fn, program="profile_packing")
    return cache[key], (snap, state0, auxes, pack_aux)


def packing_profile_solve(scheduler, snap, mover_cap: int = 128,
                          max_waves: int = 8):
    """Run the packing-mode profile solve; returns a `PackingSolveView`.
    Under `SPT_PACK_CERTIFY=1` the solve is additionally certified by the
    `tuning.gates` numpy replay oracles (fit/mask/quota/gang-quorum) and
    raises on ANY violation — the per-solve certification hook the
    pack-smoke CI gate runs unconditionally."""
    import os

    fn, args = packing_profile_fn(
        scheduler, snap, mover_cap=mover_cap, max_waves=max_waves
    )
    assignment, admitted, wait, pstats = fn(*args)
    view = PackingSolveView(
        assignment, admitted, wait,
        stats={k: int(v) for k, v in pstats.items()},
    )
    if os.environ.get("SPT_PACK_CERTIFY") == "1":
        import numpy as np

        from scheduler_plugins_tpu.tuning.gates import hard_violations

        verdict = hard_violations(
            snap, np.asarray(assignment), np.asarray(wait)
        )
        if verdict["total"]:
            raise AssertionError(
                f"packing solve violated hard constraints: {verdict}"
            )
    return view


def profile_batch_solve(scheduler, snap, max_waves: int = 8,
                        collect_stats: bool = False):
    """Run `profile_batch_fn`'s jitted solve — see that docstring for the
    semantics contract vs the sequential parity path."""
    fn, args = profile_batch_fn(
        scheduler, snap, max_waves=max_waves, collect_stats=collect_stats
    )
    return fn(*args)


#: sparse straggler-wave window for the profile solvers: re-filter rows per
#: straggler wave. 128 (vs the generic default 256) halves the dominant
#: (S, N, Z, R) NUMA re-filter cost per wave; wider straggler cohorts just
#: drain over more (cheaper) waves, and a stalled sparse wave still
#: escalates to one dense wave (ops.assign starvation guard).
PROFILE_STRAGGLER_CAP = 128


def fast_path_scoring(plugins):
    """The single scoring plugin of the targeted fast path, or None when
    the profile doesn't qualify — THE one copy of the gate (ISSUE 2
    review): no per-(pod, node) filters, no state-dependent plugins, ONE
    scoring plugin rating nodes pod-invariantly (`static_node_scores`)
    with positive weight (raw order == normalized-weighted order only
    holds for a positive weight — ADVICE r4). Shared by
    `profile_batch_fn`'s fast branch and the streamed pipeline solve
    (`parallel.pipeline.streamed_profile_solve`) so the two paths cannot
    gate differently."""
    from scheduler_plugins_tpu.framework.plugin import Plugin as _PluginBase

    plugins = tuple(plugins)
    scoring = tuple(
        p for p in plugins if type(p).score is not _PluginBase.score
    )
    filtering = tuple(
        p for p in plugins if type(p).filter is not _PluginBase.filter
    )
    ok = (
        not any(p.state_dependent_filter for p in plugins)
        and not filtering
        and len(scoring) == 1
        and type(scoring[0]).static_node_scores
        is not _PluginBase.static_node_scores
        and scoring[0].weight > 0
    )
    return scoring[0] if ok else None


def fast_solve_head(plugins, scoring, snap, state0, auxes):
    """Traced head shared by the targeted fast paths: bind aux/presolve,
    vmapped PreFilter admission, the raw static node ranking, and the
    masked initial free capacity. Returns (admitted (P,), raw (N,) int64,
    free0 (N, R))."""
    for plugin, aux in zip(plugins, auxes):
        plugin.bind_aux(aux)
    for plugin in plugins:
        plugin.bind_presolve(plugin.prepare_solve(snap))

    def admit_one(p):
        ok = snap.pods.mask[p] & ~snap.pods.gated[p]
        for plugin in plugins:
            verdict = plugin.admit(state0, snap, p)
            if verdict is not None:
                ok &= verdict
        return ok

    admitted = jax.vmap(admit_one)(jnp.arange(snap.num_pods))
    raw = scoring.static_node_scores(snap).astype(jnp.int64)
    free0 = jnp.where(snap.nodes.mask[:, None], state0.free, 0)
    return admitted, raw, free0


def _wrap_donated(fn):
    """Silence jax's "Some donated buffers were not usable" lowering
    warning for the profile solves ONLY: the state argument is donated as a
    whole, and the (N, R)/(N, Z, R) carries intentionally have no
    same-shape output to alias — XLA still releases them early (peak-memory
    win); the warning would otherwise fire on every first compile."""
    import functools
    import warnings

    @functools.wraps(fn)
    def wrapped(*args, **kwargs):
        with warnings.catch_warnings():
            warnings.filterwarnings(
                "ignore", message="Some donated buffers were not usable"
            )
            return fn(*args, **kwargs)

    return wrapped


def _donation_safe_state(state0):
    """SolverState with the leaves that may ALIAS snapshot tensors copied
    (eq_used is snap.quota.used; net_placed is snap.network.placed_node;
    the scheduling carries come from jnp.asarray over snapshot bases):
    the jitted profile solves donate the state argument, and a donated
    buffer that is also reachable through the non-donated snapshot
    argument would be written under the snapshot's feet. The copied
    tensors are side tables — (Q, R)/(W, N) — not the (N, ...) carries."""

    def copy(x):
        return None if x is None else jnp.asarray(x).copy()

    return state0.replace(
        eq_used=copy(state0.eq_used),
        net_placed=copy(state0.net_placed),
        sel_counts=copy(state0.sel_counts),
        sel_dom_counts=copy(state0.sel_dom_counts),
        anti_domains=copy(state0.anti_domains),
        sym_counts=copy(state0.sym_counts),
    )


def profile_batch_fn(scheduler, snap, max_waves: int = 8,
                     collect_stats: bool = False):
    """(jitted_fn, args) for the batched profile solve on `snap`, WITHOUT
    invoking it — the AOT seam: `tools/tpu_lower.py` exports exactly the
    callable the runtime executes (same trace-cache, same fast-path gate),
    so compile-readiness evidence covers the shipped program, not a
    re-derivation of it.

    Throughput mode for an ARBITRARY plugin profile: the same plugin
    tensor methods the sequential scan fuses are vmapped over the pod batch,
    then placed wave-parallel.

    Semantics vs the sequential parity path:

    - **Hard plugin constraints hold.** Filters of plugins whose verdict
      depends on earlier placements (`state_dependent_filter`: NUMA zone
      fitting, network dependency thresholds) are RE-EVALUATED every wave
      against the carried state with the previous waves' placements
      committed (`ops.assign.waterfill_assign_stateful`), and within a wave
      the NUMA plugin's exact zone guard checks each pod against the
      same-node demand of earlier same-wave winners — so a final placement
      never lands on a node whose zones were consumed mid-wave. Resource
      fit, queue-order node admission, quota prefix caps and gang quorum
      were already exact.
    - **Scores stay cycle-initial** (soft orderings): score tensors are
      computed once against the cycle-initial state, so tie-breaking and
      score-driven packing order may differ from the sequential scan —
      the wave trade-off documented in ops.assign.waterfill_assign.

    The jitted solve DONATES the state argument (`donate_argnums`): the
    SolverState carries (free, eq_used, gang_inflight, numa_avail) threaded
    through the wave loops update in place instead of holding a second
    copy of every carry alive across the dispatch. `args` is therefore
    single-shot — `profile_batch_fn` builds a fresh state per call, and a
    caller holding on to `args` must not invoke the returned fn twice with
    the same tuple (tools/graft_lint.py GL006 flags such reuse).

    Under `SPT_SANITIZE=1` (utils.sanitize) the solve is instead built as a
    checkify-instrumented jit — index OOB on the commit scatters, NaN,
    div-by-zero — with donation dropped (debug mode) and errors reported as
    structured JSON; the cache key carries the mode so toggling the env var
    never reuses a differently-instrumented program.
    """
    import jax

    from scheduler_plugins_tpu.utils import sanitize

    plugins = tuple(scheduler.profile.plugins)
    static_plugins = tuple(
        p for p in plugins if not p.state_dependent_filter
    )
    dyn_plugins = tuple(p for p in plugins if p.state_dependent_filter)
    from scheduler_plugins_tpu.framework.plugin import Plugin as _PluginBase

    for p in dyn_plugins:
        # the hard-constraint guarantee relies on wave commits actually
        # updating the carry — fail loudly, not silently, on a plugin that
        # declares a state-dependent filter with neither a batched Reserve
        # nor a sequential validator (framework-carried tracks count via
        # validate_at; see ops.selectors)
        if (
            type(p).commit_batch is _PluginBase.commit_batch
            and p.validate_at is None
        ):
            raise TypeError(
                f"{p.name}: state_dependent_filter requires commit_batch "
                "or validate_at"
            )
    state0 = _donation_safe_state(scheduler.initial_state(snap))
    auxes = tuple(p.aux() for p in plugins)

    # ---- targeted fast path ------------------------------------------
    # When the profile has NO per-(pod, node) filters and its single
    # scoring plugin rates nodes pod-invariantly (static_node_scores),
    # the whole (P, N) pipeline collapses: admission is a (P,) vmap, and
    # placement is the targeted waterfill (O(P·R) waves against the one
    # static node ranking). Gang quorum and the queue-order quota prefix
    # still run exactly in finalize_assignment. This is the shape of the
    # coscheduling/capacity profiles, where the reference spends its time
    # in PreFilter bookkeeping, not Filter fan-out
    # (capacity_scheduling.go:208-282). Ranking uses the plugin's RAW
    # static scores — sound because the gate (`fast_path_scoring`, shared
    # with the streamed pipeline solve) requires a SINGLE scoring plugin
    # and static_node_scores' contract requires its normalize to be
    # monotone with positive weight (framework/plugin.py).
    scoring_p = fast_path_scoring(plugins)
    if scoring_p is not None:

        def fast_batch(snap, state0, auxes):
            admitted, raw, free0 = fast_solve_head(
                plugins, scoring_p, snap, state0, auxes
            )
            out = waterfill_assign_targeted(
                raw, snap.pods.req, admitted, free0,
                max_waves=max_waves, collect_stats=collect_stats,
            )
            assignment, wait = finalize_assignment(out[0], snap)
            if collect_stats:
                return assignment, admitted, wait, out[2]
            return assignment, admitted, wait

        key = ("profile_batch_fast", max_waves, collect_stats,
               sanitize.enabled()) + scheduler.weights_key() + tuple(
            p.static_key() for p in plugins
        )
        cache = scheduler._solve_cache
        if key not in cache:
            if sanitize.enabled():
                fast_fn = sanitize.checkified(
                    fast_batch, program="profile_batch_fast"
                )
            else:
                fast_fn = _wrap_donated(
                    jax.jit(fast_batch, donate_argnums=(1,))
                )
            cache[key] = obs.compile_watch(
                fast_fn, program="profile_batch_fast"
            )
        return cache[key], (snap, state0, auxes)
    # ------------------------------------------------------------------

    def batch(snap, state0, auxes):
        for plugin, aux in zip(plugins, auxes):
            plugin.bind_aux(aux)
        for plugin in plugins:
            plugin.bind_presolve(plugin.prepare_solve(snap))
        P = snap.num_pods

        from scheduler_plugins_tpu.ops.fit import fits_one

        # class-collapsed whole-batch tensors (plugin.filter_batch /
        # score_batch): computed ONCE against state0, outside the per-pod
        # vmap; rows are gathered per pod below. A plugin providing them
        # does O(K·N) class work instead of O(P·N·...) vmapped work.
        def _batch_filter(plugin, state):
            if type(plugin).filter_batch is not _PluginBase.filter_batch:
                return plugin.filter_batch(state, snap)
            return None

        # class-collapsed cycle-initial rows — the shared hook dispatch
        # (`collapsed_batch_rows`) also feeds `batch_explain_rows`, so the
        # explain surface sees exactly the rows this solve ranks by
        filter0_rows, score_rows = collapsed_batch_rows(plugins, state0, snap)

        # plugins with batched score rows AND the base identity normalize
        # contribute a feasibility-independent weighted sum — fold them
        # into ONE whole-matrix total outside the per-pod vmap
        pre_total = None
        pre_ids = {
            i for i in score_rows
            if type(plugins[i]).normalize is _PluginBase.normalize
        }
        for i in pre_ids:
            term = plugins[i].weight * score_rows[i].astype(jnp.int32)
            pre_total = term if pre_total is None else pre_total + term

        def per_pod(p):
            ok = snap.pods.mask[p] & ~snap.pods.gated[p]
            for plugin in plugins:
                verdict = plugin.admit(state0, snap, p)
                if verdict is not None:
                    ok &= verdict
            # state-INDEPENDENT filters are wave-invariant: evaluate once;
            # normalize over the same fit-and-admit-filtered set the
            # sequential step uses (cycle-initial free capacity + the
            # cycle-initial view of the state-dependent filters)
            static_feasible = jnp.ones(snap.num_nodes, bool)
            for i, plugin in enumerate(plugins):
                if plugin not in static_plugins:
                    continue
                if i in filter0_rows:
                    static_feasible &= filter0_rows[i][p]
                    continue
                mask = plugin.filter(state0, snap, p)
                if mask is not None:
                    static_feasible &= mask
            feasible = (
                fits_one(snap.pods.req[p], state0.free, snap.nodes.mask)
                & static_feasible
            )
            for i, plugin in enumerate(plugins):
                if plugin not in dyn_plugins:
                    continue
                if i in filter0_rows:
                    feasible &= filter0_rows[i][p]
                    continue
                mask = plugin.filter(state0, snap, p)
                if mask is not None:
                    feasible &= mask
            feasible &= ok
            total = jnp.zeros(snap.num_nodes, jnp.int64)
            for i, plugin in enumerate(plugins):
                if i in pre_ids:
                    continue  # folded into pre_total outside the vmap
                raw = (
                    score_rows[i][p] if i in score_rows
                    else plugin.score(state0, snap, p)
                )
                if raw is not None:
                    total = total + plugin.weight * plugin.normalize(raw, feasible)
            # int32 demotion: normalized scores are <= 100 * sum(weights),
            # far inside int32 — halves the (P, N) score-matrix traffic in
            # the waterfill's per-wave argmax/mean passes
            total = total.astype(jnp.int32)
            if pre_total is not None:
                total = total + pre_total[p]
            return ok, static_feasible, feasible, total

        admitted, static_feasible, feasible0, scores0 = jax.vmap(per_pod)(
            jnp.arange(P)
        )

        def batch_fn(free, state, active):
            feasible = fits(
                snap.pods.req, free, pod_mask=active, node_mask=snap.nodes.mask
            ) & static_feasible
            for plugin in dyn_plugins:
                # class-collapsed whole-matrix re-filter when offered
                m = _batch_filter(plugin, state)
                if m is not None:
                    feasible &= m
                    continue
                def one(p, _pl=plugin):
                    return _pl.filter(state, snap, p)
                # a filter can opt out (None) on Python-level layout checks;
                # the probe's dead ops are DCE'd by XLA
                if one(jnp.int32(0)) is None:
                    continue
                feasible &= jax.vmap(one)(jnp.arange(P))
            return feasible, scores0

        def sub_batch_fn(free, state, idx, act_sub):
            """Sparse straggler re-filter: (S, N) rows for the `idx` pods
            only — a straggler wave re-runs the dyn filters on a small
            window instead of the whole batch."""
            feasible = fits(
                snap.pods.req[idx], free,
                pod_mask=act_sub, node_mask=snap.nodes.mask,
            ) & static_feasible[idx]
            for plugin in dyn_plugins:
                # row-sliced re-filter when offered (NUMA): S rows at S/P
                # of the whole-matrix cost — the whole-matrix form would
                # recompute (P, N, Z, R) per straggler wave
                if type(plugin).filter_rows is not _PluginBase.filter_rows:
                    r = plugin.filter_rows(state, snap, idx)
                    if r is not None:
                        feasible &= r
                        continue
                m = _batch_filter(plugin, state)
                if m is not None:
                    # class-collapsed rows: XLA folds the row gather into
                    # the (W, N) -> (P, N) class gather
                    feasible &= m[idx]
                    continue
                def one(p, _pl=plugin):
                    return _pl.filter(state, snap, p)
                if one(jnp.int32(0)) is None:
                    continue
                feasible &= jax.vmap(one)(idx)
            return feasible, scores0[idx]

        # hard DOMAIN constraints (topology spread, inter-pod anti-affinity)
        # span nodes, so neither the per-wave re-filter nor the same-node
        # wave guard can see a same-wave cross-node conflict. Validators
        # re-check each wave's winners sequentially in queue order against
        # the live carry inside the waterfill (O(1) gathers per pod on the
        # common fast path; a (CT,N)->(CT,D) scatter per pod only when a
        # spread node-inclusion policy excludes a keyed node); their
        # carries commit per pod there, every other dyn carry batch-commits
        # on the kept winners.
        validators = tuple(
            pl for pl in dyn_plugins if pl.validate_at is not None
        )
        batch_committers = tuple(
            pl for pl in dyn_plugins if pl.validate_at is None
        )

        def commit_fn(state, placed, choice):
            for plugin in batch_committers:
                state = plugin.commit_batch(state, snap, placed, choice)
            return state

        validate_fn = validate_commit_fn = None
        if validators:
            from scheduler_plugins_tpu.ops.selectors import commit_tracks

            def validate_fn(state, q, choice):
                ok = jnp.bool_(True)
                for pl in validators:
                    ok &= pl.validate_at(state, snap, q, choice)
                return ok

            def validate_commit_fn(state, q, choice):
                if snap.scheduling is not None:
                    state = commit_tracks(state, snap.scheduling, q, choice)
                for pl in validators:
                    state = pl.commit(state, snap, q, choice)
                return state

        guards, guard_demands = [], []
        for plugin in dyn_plugins:
            gdem = plugin.wave_guard_demand(snap)
            if gdem is not None:
                guards.append(
                    lambda state, p, n, pre, _pl=plugin: _pl.wave_guard(
                        state, snap, p, n, pre
                    )
                )
                guard_demands.append(gdem)
        capacity_fns = tuple(
            (lambda state, active, _pl=plugin: _pl.wave_capacity(
                state, snap, active
            ))
            for plugin in dyn_plugins
            if type(plugin).wave_capacity
            is not _PluginBase.wave_capacity
        )

        from scheduler_plugins_tpu.ops.assign import waterfill_assign_stateful

        out = waterfill_assign_stateful(
            batch_fn,
            commit_fn,
            tuple(guards),
            tuple(guard_demands),
            snap.pods.req,
            admitted,
            state0.free,
            state0,
            max_waves=max_waves,
            validate_fn=validate_fn,
            validate_commit_fn=validate_commit_fn,
            capacity_fns=capacity_fns,
            # wave 0 reuses the cycle-initial filter pass per_pod already
            # paid for (state is unchanged until the first commit)
            initial_batch=(feasible0, scores0),
            sub_batch_fn=sub_batch_fn,
            straggler_cap=PROFILE_STRAGGLER_CAP,
            collect_stats=collect_stats,
        )
        assignment, wait = finalize_assignment(out[0], snap)
        if collect_stats:
            return assignment, admitted, wait, out[3]
        return assignment, admitted, wait

    key = ("profile_batch", max_waves, collect_stats,
           sanitize.enabled()) + scheduler.weights_key() + tuple(
        p.static_key() for p in plugins
    )
    cache = scheduler._solve_cache
    if key not in cache:
        if sanitize.enabled():
            batch_fn_j = sanitize.checkified(batch, program="profile_batch")
        else:
            batch_fn_j = _wrap_donated(jax.jit(batch, donate_argnums=(1,)))
        cache[key] = obs.compile_watch(batch_fn_j, program="profile_batch")
    return cache[key], (snap, state0, auxes)


def sweep_solve_fn(scheduler):
    """The vmapped-over-weights counterfactual solve entry (the tuning
    observatory's hot program): a single jitted function

        fn(snap, state0, auxes, W (K, L) int64) ->
            (assignment (K, P), admitted (K, P), wait (K, P))

    that runs the bit-faithful sequential parity body
    (`framework.runtime.sequential_solve_body`) once per candidate weight
    vector, vmapped over the K axis — the per-candidate weight scalars are
    traced arguments bound through `Plugin.bind_weight` (the aux-channel
    discipline of CLAUDE.md applied to the one config knob the profile
    format keeps host-side), so K candidates share ONE compile and zero
    per-candidate retraces (`tools/tune.py` asserts this via the PR 5
    compile-watch counters, program "sweep_solve"). Lane k is
    bit-identical to a standalone `Scheduler.solve(auxes=)` on a profile
    whose static weights equal W[k] (tests/test_tuning.py gates it).

    Callers pad K to a power-of-two bucket (`tuning.sweep.pad_candidates`)
    so candidate-count churn stays within bounded retraces, exactly like
    `run_explain_rows`' index buckets."""
    from scheduler_plugins_tpu.framework.runtime import sequential_solve_body

    plugins = tuple(scheduler.profile.plugins)
    key = ("sweep_solve",) + tuple(p.static_key() for p in plugins)
    cache = scheduler._solve_cache
    if key not in cache:

        def sweep(snap, state0, auxes, W):
            def lane(w):
                r = sequential_solve_body(
                    plugins, snap, state0, auxes, unroll=1, weights=w
                )
                return r.assignment, r.admitted, r.wait

            return jax.vmap(lane)(W)

        cache[key] = obs.compile_watch(jax.jit(sweep), program="sweep_solve")
    return cache[key]


def collapsed_batch_rows(plugins, state0, snap):
    """(filter_rows, score_rows): plugin position -> class-collapsed whole-
    batch (P, N) rows from the `batch_rows` / `filter_batch` / `score_batch`
    hooks against the cycle-initial state — THE one copy of the hook
    dispatch, shared by the batched profile solve's cycle-initial pass and
    `batch_explain_rows`, so the explain surface consumes exactly the rows
    the batched solver ranks by."""
    from scheduler_plugins_tpu.framework.plugin import Plugin as _PluginBase

    filter_rows, score_rows = {}, {}
    for i, plugin in enumerate(plugins):
        # fused filter+score rows when offered: one shared-intermediate
        # pass instead of two (networkaware tallies)
        if type(plugin).batch_rows is not _PluginBase.batch_rows:
            fused = plugin.batch_rows(state0, snap)
            if fused is not None:
                f_row, s_row = fused
                if f_row is not None:
                    filter_rows[i] = f_row
                if s_row is not None:
                    score_rows[i] = s_row
                continue
        if type(plugin).filter_batch is not _PluginBase.filter_batch:
            m = plugin.filter_batch(state0, snap)
            if m is not None:
                filter_rows[i] = m
        if type(plugin).score_batch is not _PluginBase.score_batch:
            s = plugin.score_batch(state0, snap)
            if s is not None:
                score_rows[i] = s
    return filter_rows, score_rows


def batch_explain_rows(scheduler, snap, indices, auxes=None):
    """The BATCHED twin of `Scheduler.explain_rows`: identical output
    schema (admitted / fail_code / feasible / fit_margin / columns /
    total, sliced to len(indices)), but the per-plugin filter verdicts and
    raw scores come through the batched solver's class-collapsed row hooks
    (`collapsed_batch_rows`) — the rows `profile_batch_fn`'s cycle-initial
    pass actually ranks by — fed into the SAME shared explain body
    (`framework.runtime._explain_one`). The two entries differ only in
    where rows come from, so sequential and batched explains cannot
    drift; tests/test_explain.py asserts exact agreement."""
    from scheduler_plugins_tpu.framework.runtime import (
        _explain_one,
        run_explain_rows,
    )

    plugins = tuple(scheduler.profile.plugins)

    def explain(snap, state0, auxes, idx):
        for plugin, aux in zip(plugins, auxes):
            plugin.bind_aux(aux)
        for plugin in plugins:
            plugin.bind_presolve(plugin.prepare_solve(snap))
        filter_rows, score_rows = collapsed_batch_rows(plugins, state0, snap)
        return jax.vmap(
            lambda p: _explain_one(
                plugins, state0, snap, p,
                filter_rows=filter_rows, score_rows=score_rows,
            )
        )(idx)

    return run_explain_rows(
        scheduler, snap, indices, auxes, "batch_explain", explain
    )


def profile_initial_scores(scheduler, snap, auxes=None):
    """(P, N) weighted normalized plugin score matrix and (P, N) feasibility
    against the CYCLE-INITIAL state — the objective both solve modes rank
    nodes by before placements start. Used to quantify the batched path's
    placement-quality drift vs the sequential scan (VERDICT r2 item 8):
    score_sum(assignment) = Σ_p scores[p, assignment[p]] is comparable
    across modes because both optimize this same cycle-initial surface
    (the sequential path then re-evaluates state-dependent filters as it
    commits; scores stay cycle-initial in both, runtime.py step()).
    `auxes` force-binds recorded config arrays on the flight-recorder
    replay path (the tuner's drift anchor must score with exactly the
    recorded inputs), like `Scheduler.solve(auxes=)`."""
    import jax

    plugins = tuple(scheduler.profile.plugins)
    state0 = scheduler.initial_state(snap)
    if auxes is None:
        auxes = tuple(p.aux() for p in plugins)
    key = ("profile_scores",) + scheduler.weights_key() + tuple(
        p.static_key() for p in plugins
    )
    cache = scheduler._solve_cache
    if key not in cache:

        def scores_fn(snap, state0, auxes):
            for plugin, aux in zip(plugins, auxes):
                plugin.bind_aux(aux)
            for plugin in plugins:
                plugin.bind_presolve(plugin.prepare_solve(snap))

            from scheduler_plugins_tpu.ops.fit import fits_one

            def per_pod(p):
                feasible = fits_one(
                    snap.pods.req[p], state0.free, snap.nodes.mask
                )
                for plugin in plugins:
                    mask = plugin.filter(state0, snap, p)
                    if mask is not None:
                        feasible &= mask
                total = jnp.zeros(snap.num_nodes, jnp.int64)
                for plugin in plugins:
                    raw = plugin.score(state0, snap, p)
                    if raw is not None:
                        total = total + plugin.weight * plugin.normalize(
                            raw, feasible
                        )
                return total, feasible

            return jax.vmap(per_pod)(jnp.arange(snap.num_pods))

        cache[key] = obs.compile_watch(
            jax.jit(scores_fn), program="profile_scores"
        )
    return cache[key](snap, state0, auxes)


def score_drift_vs_sequential(scheduler, snap, seq_assignment,
                              bat_assignment):
    """Relative score-sum drift of the batched placements vs the sequential
    parity path on the shared cycle-initial objective
    (`profile_initial_scores`) — the single definition both the bench
    metric and the drift-bound test report, so they always measure the
    same quantity. Padded/unplaced slots carry assignment -1 and are
    excluded. Returns (drift, placed_seq, placed_bat)."""
    import numpy as np

    scores = np.asarray(profile_initial_scores(scheduler, snap)[0])
    seq = np.asarray(seq_assignment)
    bat = np.asarray(bat_assignment)

    def score_sum(a):
        placed = a >= 0
        return int(scores[np.nonzero(placed)[0], a[placed]].sum())

    s_seq, s_bat = score_sum(seq), score_sum(bat)
    drift = (s_bat - s_seq) / max(abs(s_seq), 1)
    return drift, int((seq >= 0).sum()), int((bat >= 0).sum())


def sharded_batch_solve(snap, mesh, weights, max_waves: int = 8):
    """Jit `batch_solve` with the snapshot sharded over `mesh`; XLA inserts
    the cross-shard collectives."""
    from scheduler_plugins_tpu.parallel.mesh import ambient_mesh, shard_snapshot

    snap = shard_snapshot(snap, mesh)
    with ambient_mesh(mesh):
        fn = obs.compile_watch(
            jax.jit(lambda s, w: batch_solve(s, w, max_waves)),
            program="sharded_batch_solve",
        )
        return fn(snap, weights)


def sharded_profile_batch_solve(scheduler, snap, mesh, max_waves: int = 8):
    """`profile_batch_solve` (the FULL plugin roster: NUMA wave guards,
    network thresholds, spread/affinity validators, trimaran scores — not
    just the flagship allocatable solve) with the snapshot sharded over
    `mesh`. Node-major tensors (free capacity, NUMA zone tables, score rows)
    split over the "nodes" axis, pod-major tensors over "pods"; side tables
    replicate, and XLA's sharding propagation inserts the cross-shard
    collectives for the argmax/segment reductions — the multi-chip analog of
    the reference runtime's 16-worker Filter/Score fan-out (SURVEY.md §2.9;
    /root/reference/pkg/noderesourcetopology/filter.go:90-160 is the hot
    loop that lands on the node-sharded axis).

    Placement semantics are those of `profile_batch_solve` (sharding never
    changes the math, only its partitioning); `tests/test_parallel.py`
    asserts sharded == unsharded placements on an 8-device CPU mesh."""
    from scheduler_plugins_tpu.parallel.mesh import ambient_mesh, shard_snapshot

    snap = shard_snapshot(snap, mesh)
    with ambient_mesh(mesh):
        return profile_batch_solve(scheduler, snap, max_waves=max_waves)


# ---------------------------------------------------------------------------
# Sharded wave solver: shard_map ring-election waterfill (node axis sharded)
# ---------------------------------------------------------------------------


def rank_order_inputs(raw_scores, free0, node_mask, n_shards: int):
    """(node_ids, rank_free) for the sharded wave solver: the node axis
    permuted into GLOBAL SCORE-RANK ORDER (stable argsort — the lowest-
    index tie-break of the single-device ranking is baked into the
    permutation) and padded to a multiple of `n_shards` with zero-capacity
    rows (node id -1), so each shard owns a contiguous global rank block
    and the shard-local wave kernels never need the (N,) score vector
    again. Masked nodes are zeroed like `batch_solve`'s solve_free0 — a
    masked node can then never admit any pod (pod demands include a
    pods-slot of 1). One O(N log N) sort + one gather per SOLVE (scores
    are static across waves and chunks), not per wave."""
    from scheduler_plugins_tpu.parallel.mesh import pad_to_shards

    N, R = free0.shape
    order_n = jnp.argsort(-raw_scores, stable=True)
    rank_free = jnp.where(node_mask[:, None], free0, 0)[order_n]
    node_ids = order_n.astype(jnp.int32)
    pad = pad_to_shards(N, n_shards) - N
    if pad:
        rank_free = jnp.concatenate(
            [rank_free, jnp.zeros((pad, R), rank_free.dtype)]
        )
        node_ids = jnp.concatenate(
            [node_ids, jnp.full((pad,), -1, jnp.int32)]
        )
    return node_ids, rank_free


def sharded_wave_chunk_solver(mesh, n_nodes: int, max_waves: int = 8,
                              rescue_window: int = 512,
                              lite_window: int = 1024,
                              collect_stats: bool = True,
                              use_pallas: bool | None = None,
                              pallas_interpret: bool | None = None):
    """The sharded wave chunk program: `ops.assign.waterfill_targeted_sharded`
    wrapped in a `shard_map` over `mesh`'s "nodes" axis and jitted with the
    resident rank-ordered free carry DONATED — the pipeline calling
    convention (`parallel.pipeline.run_chunk_pipeline`):

        fn(node_ids, req_chunk, mask_chunk, rank_free)
            -> ((assignment[, stats]), rank_free)

    `node_ids`/`rank_free` come from `rank_order_inputs` (node axis in
    global score-rank order, padded to the shard count); `n_nodes` is the
    PRE-PADDING node count those inputs were built from (the probe-clamp
    anchor — see the body's docstring); req/mask chunks are replicated. The carry stays device-resident and SHARDED across
    chunks — chunk boundaries never reassemble the node axis, and per-wave
    cross-shard traffic is O(shards) ring/psum collectives (see the body's
    docstring). Placements are bit-identical to the single-device
    `waterfill_assign_targeted` chunk program at any shard count (below
    the documented 2^53 cumulative-capacity bound).

    `use_pallas`/`pallas_interpret` (None = resolve from `SPT_PALLAS` /
    the backend via `parallel.kernels`) swap the per-wave framework
    collectives for the Pallas ring kernels — bit-identical placements,
    gated by tests/test_differential.py and `make pallas-smoke`."""
    from functools import partial

    from jax.experimental.shard_map import shard_map
    from jax.sharding import PartitionSpec as P

    from scheduler_plugins_tpu.ops.assign import waterfill_targeted_sharded
    from scheduler_plugins_tpu.parallel import kernels as pk
    from scheduler_plugins_tpu.parallel.mesh import NODES_AXIS
    from scheduler_plugins_tpu.parallel.pipeline import donated_chunk_solver
    from scheduler_plugins_tpu.utils import sanitize

    if use_pallas is None:
        # checkify cannot instrument pallas_call bodies — the sanitizer
        # gate keeps certifying the lax formulation, which is placement-
        # identical by the differential gates
        use_pallas = pk.pallas_enabled() and not sanitize.enabled()
    if pallas_interpret is None:
        pallas_interpret = pk.pallas_interpret()
    n_shards = mesh.shape[NODES_AXIS]
    body = partial(
        waterfill_targeted_sharded,
        axis_name=NODES_AXIS, n_shards=n_shards, n_real=n_nodes,
        max_waves=max_waves,
        rescue_window=rescue_window, lite_window=lite_window,
        collect_stats=collect_stats,
        use_pallas=use_pallas, pallas_interpret=pallas_interpret,
    )
    stats_spec = ({"occupancy": P(), "waves": P()},) if collect_stats else ()
    sharded_body = shard_map(
        body, mesh=mesh,
        in_specs=(P(NODES_AXIS, None), P(NODES_AXIS), P(), P()),
        out_specs=(P(), P(NODES_AXIS, None)) + stats_spec,
        check_rep=False,  # ppermute ring + replicated outputs via psum
    )

    def sharded_wave_chunk(node_ids, req_chunk, mask_chunk, rank_free):
        out = sharded_body(rank_free, node_ids, req_chunk, mask_chunk)
        if collect_stats:
            assignment, rank_free, stats = out
            return (assignment, stats), rank_free
        assignment, rank_free = out
        return (assignment,), rank_free

    return donated_chunk_solver(sharded_wave_chunk, carry_argnum=3)


#: built sharded-wave chunk solvers by (mesh, n_nodes, chunk, knobs) — the
#: trace-cache seam `sharded_wave_solve` reuses across calls (jit caches
#: per wrapper object, so rebuilding the wrapper would recompile)
_WAVE_SOLVER_CACHE: dict = {}

#: static collective census per solver identity, computed lazily for
#: tracer-enabled solves only (the merged trace's shard_wave/census row)
_WAVE_CENSUS_CACHE: dict = {}


def sharded_wave_solve(snap, mesh, weights, chunk: int | None = None,
                       max_waves: int = 8, rescue_window: int = 512,
                       collect_stats: bool = False):
    """`batch_solve`'s flagship semantics with the WAVE HOT LOOP sharded:
    admission (gang/quota PreFilter), the static allocatable ranking and
    the finalize tail (queue-order namespace quota prefix + gang quorum
    Permit) are unchanged; placement runs through the shard_map ring-
    election waterfill with the node axis sharded over `mesh` and the free
    carry resident per shard. Pods stream in queue-order chunks (`chunk`
    None = one chunk) with the carry threading device-side, donated.

    Hard constraints (fit, queue-order node admission, quota caps, gang
    quorum) hold exactly at every shard count; placements are bit-
    identical to the single-device wave path below the 2^53 cumulative-
    capacity bound (tests/test_shard_wave.py + tests/test_differential.py
    gate both). Returns (assignment, admitted, wait[, stats]).

    Under `SPT_PALLAS=1` the wave elections run as the `parallel.kernels`
    Pallas ring programs (interpret twins off-TPU) — resolved HERE so the
    solver cache key carries the mode and an env toggle never reuses a
    differently-built program."""
    from scheduler_plugins_tpu.parallel import kernels as pk
    from scheduler_plugins_tpu.parallel.mesh import NODES_AXIS, ambient_mesh
    from scheduler_plugins_tpu.utils import sanitize

    use_pallas = pk.pallas_enabled() and not sanitize.enabled()
    pallas_interpret = pk.pallas_interpret()
    free0 = free_capacity(snap.nodes.alloc, snap.nodes.requested)
    admitted = batch_admission(snap, free0)
    raw = demote_scores_int32(
        allocatable_scores(snap.nodes.alloc, weights, MODE_LEAST)
    ).astype(jnp.int64)
    n_shards = mesh.shape[NODES_AXIS]
    node_ids, rank_free = rank_order_inputs(
        raw, free0, snap.nodes.mask, n_shards
    )
    P = snap.num_pods
    chunk = P if chunk is None else min(chunk, P)
    if P % chunk != 0:
        raise ValueError(f"pod count {P} not a multiple of chunk {chunk}")
    # memoize the built solver per program identity: a fresh jit wrapper
    # per call would recompile the whole multi-device program on every
    # solve of the same shapes
    key = (mesh, free0.shape[0], chunk, max_waves, rescue_window,
           collect_stats, use_pallas, pallas_interpret)
    solve_chunk = _WAVE_SOLVER_CACHE.get(key)
    if solve_chunk is None:
        solve_chunk = _WAVE_SOLVER_CACHE[key] = sharded_wave_chunk_solver(
            mesh, free0.shape[0], max_waves=max_waves,
            rescue_window=rescue_window, collect_stats=collect_stats,
            use_pallas=use_pallas, pallas_interpret=pallas_interpret,
        )
    tracing = obs.tracer.enabled
    if tracing:
        # one-time static collective census for the merged trace (a
        # make_jaxpr trace per solver identity — cached; tracer-enabled
        # runs only, the hot path never pays it)
        census = _WAVE_CENSUS_CACHE.get(key)
        if census is None:
            with ambient_mesh(mesh):
                census = _WAVE_CENSUS_CACHE[key] = collective_census(
                    solve_chunk, node_ids, snap.pods.req[:chunk],
                    admitted[:chunk], rank_free,
                )
        obs.tracer.complete(
            "census", obs.tracer.now_ns(), 0, tid="shard_wave",
            args={"shards": n_shards, **census},
        )
    parts, stats_parts = [], []
    with ambient_mesh(mesh):
        for i, lo in enumerate(range(0, P, chunk)):
            start_ns = obs.tracer.now_ns() if tracing else 0
            out, rank_free = solve_chunk(
                node_ids, snap.pods.req[lo:lo + chunk],
                admitted[lo:lo + chunk], rank_free,
            )
            parts.append(out[0])
            if collect_stats:
                stats_parts.append(out[1])
            if tracing:
                # per-chunk row: host-sync envelope of dispatch through
                # stats transfer, stamped with the chunk's wave counters
                # (device numbers strictly via host transfer — GL008)
                args = {"chunk": i}
                if collect_stats:
                    import numpy as np

                    args["waves"] = int(np.asarray(out[1]["waves"]))
                    occ = [int(x) for x in np.asarray(out[1]["occupancy"])]
                    while len(occ) > 1 and occ[-1] == 0:
                        occ.pop()
                    args["wave_occupancy"] = occ
                obs.tracer.complete(
                    f"chunk[{i}]", start_ns,
                    obs.tracer.now_ns() - start_ns,
                    tid="shard_wave", args=args,
                )
    assignment = parts[0] if len(parts) == 1 else jnp.concatenate(parts)
    assignment, wait = finalize_assignment(assignment, snap)
    if collect_stats:
        stats = {
            "occupancy": sum(jnp.asarray(s["occupancy"]) for s in stats_parts),
            "waves": sum(jnp.asarray(s["waves"]) for s in stats_parts),
        }
        return assignment, admitted, wait, stats
    return assignment, admitted, wait


#: cross-shard collective primitives the census tracks; `all_gather` /
#: `all_to_all` should NEVER appear in the sharded wave program (the ring
#: election's silent degradation mode — graft_lint GL009 is the source-level
#: twin of this jaxpr-level check). `pallas_call` marks one fused ring
#: kernel program (the SPT_PALLAS path); `dma_start` equations inside its
#: body are the neighbor transfers — S-1 per ring, so the census stays the
#: per-wave O(shards) traffic bound in both formulations
COLLECTIVE_PRIMS = frozenset({
    "psum", "pmin", "pmax", "ppermute", "all_gather", "all_gather_invariant",
    "all_to_all", "pallas_call", "dma_start",
})


def collective_census(fn, *args):
    """{collective primitive: equation count} over the traced `fn(*args)`
    jaxpr, recursing through every sub-jaxpr (pjit/shard_map/while/scan/
    cond — and `pallas_call` kernel bodies, whose `dma_start` equations
    are the ring's neighbor transfers). Because the wave loops are
    `lax.while_loop`s, each wave BODY appears exactly once in the jaxpr —
    so the static census directly bounds the PER-WAVE collective count,
    independent of how many waves a solve actually runs: the shard-smoke
    gate asserts it stays O(shards) and that no full-axis gather ever
    appears."""
    from jax import core

    closed = jax.make_jaxpr(fn)(*args)
    counts: dict[str, int] = {}

    def walk(jaxpr):
        for eqn in jaxpr.eqns:
            name = eqn.primitive.name
            if name in COLLECTIVE_PRIMS:
                counts[name] = counts.get(name, 0) + 1
            for sub in core.jaxprs_in_params(eqn.params):
                walk(getattr(sub, "jaxpr", sub))

    walk(closed.jaxpr)
    return counts
