"""Static VMEM envelope model for the Pallas ring kernels (ISSUE 18).

ONE copy of the on-chip budget arithmetic, read by BOTH consumers:

- `parallel.kernels` derives its `PALLAS_MAX_ELECTION_ELEMS` solver gate
  (the static fall-back-to-lax threshold for oversize election payloads)
  from `derive_max_election_elems()` — the constant is no longer
  hand-picked;
- `tools/kernel_audit.py` (KA001) re-computes every traced kernel body's
  worst-case VMEM footprint against the same budget table and re-derives
  the threshold, failing closed if either side drifts.

The model is deliberately simple and conservative — it must UPPER-bound
what Mosaic resident-allocates, not estimate it:

- every kernel-body VMEM ref (block-mapped inputs/outputs + VMEM scratch)
  is resident for the whole body: bytes = prod(block_shape) * itemsize;
- with a nontrivial grid, Mosaic double-buffers each block-mapped operand
  to overlap the HBM copy of step k+1 with step k's compute — 2 copies
  per grid-streamed ref (scratch is never pipelined: 1 copy). The ring
  kernels are gridless today; the factor exists so ROADMAP item 3's
  grid-tiled mega election is checked against the budget it will actually
  occupy;
- semaphores live in semaphore memory, not VMEM: counted separately,
  never charged against the VMEM budget.

Derivation of the election threshold: every `parallel.kernels` ring
program holds `1 (input) + n_out (outputs) + COMM_SLOTS (comm scratch)`
same-shape int32 buffers in VMEM at once (`kernels._ring_call` — the one
shared pallas_call plumbing). The worst family is `ring_offsets` with
n_out = 2 → 6 buffer copies. The threshold is the largest power of two
E with E * worst_copies * 4 bytes <= the target budget; powers of two
keep the padded-buffer compile bucketing stable. At the 16 MiB/core
target this derives 2^19 — equal to the constant PR 13 hand-picked, so
the derivation changed the PROVENANCE of the number, not its value
(docs/kernel_audit.json records both).
"""

from __future__ import annotations

import os

__all__ = [
    "VMEM_BUDGET_BYTES",
    "VMEM_TARGET",
    "COMM_SLOTS",
    "RING_FAMILIES",
    "WORST_RING_COPIES",
    "PEAK_FLOPS_PER_S",
    "HBM_BYTES_PER_S",
    "ROOFLINE_TARGETS",
    "ring_buffer_copies",
    "derive_max_election_elems",
    "max_election_elems",
]

#: per-core VMEM budget, bytes, by lowering target. ~16 MiB/core on every
#: shipping TPU generation the repo targets (pallas guide §memory-spaces);
#: a per-generation row exists so a smaller-VMEM target can be audited
#: without touching the model.
VMEM_BUDGET_BYTES = {
    "tpu_v4": 16 * 1024 * 1024,
    "tpu_v5e": 16 * 1024 * 1024,
    "tpu_v5p": 16 * 1024 * 1024,
}

#: the audited lowering target (SPT_VMEM_TARGET to re-derive for another
#: generation — the committed manifest pins the target it was written for)
VMEM_TARGET = os.environ.get("SPT_VMEM_TARGET", "tpu_v4")

# ---------------------------------------------------------------------------
# Roofline peaks (ISSUE 20): ONE module owns all hardware numbers — the VMEM
# budget above and the chip peaks below — so the kernel auditor and the
# compiled-cost observatory (obs/costmodel.py) can never disagree about what
# "the hardware" is. Public per-chip spec-sheet numbers; deliberately the
# OPTIMISTIC peaks (dense-MXU bf16 FLOP/s, full HBM streams): the roofline
# they induce is a step-time FLOOR, never an estimate. The solver programs
# are int32/f64 vector work, so real chips land well above the floor — the
# committed `roofline_calibration` column on bench lines measures by how
# much, per backend.
# ---------------------------------------------------------------------------

#: peak dense FLOP/s per chip (bf16 MXU — the spec-sheet headline)
PEAK_FLOPS_PER_S = {
    "tpu_v4": 275e12,
    "tpu_v5e": 197e12,
    "tpu_v5p": 459e12,
}

#: HBM bandwidth, bytes/s per chip
HBM_BYTES_PER_S = {
    "tpu_v4": 1.2e12,
    "tpu_v5e": 0.82e12,
    "tpu_v5p": 2.765e12,
}

#: generations with a complete hardware row (VMEM budget + both peaks) —
#: the set a roofline can be projected for
ROOFLINE_TARGETS = tuple(
    sorted(set(VMEM_BUDGET_BYTES) & set(PEAK_FLOPS_PER_S) & set(HBM_BYTES_PER_S))
)

#: 3-slot ring communication buffer (kernels._ring_call scratch): slot k%3
#: receives while slot (k-1)%3 sends and the step k-1 buffer is folded
COMM_SLOTS = 3

#: ring kernel families -> output-buffer count (kernels._ring_call n_out).
#: Every family holds 1 input + n_out outputs + COMM_SLOTS comm slots of
#: ONE padded (H, L) int32 buffer in VMEM; DMA semaphores ride semaphore
#: memory. New ring kernels must add a row — tools/kernel_audit.py KA001
#: cross-checks the table against the traced bodies.
RING_FAMILIES = {
    "ring_offsets": 2,   # (exclusive_prefix, total)
    "elect_min": 1,
    "fused_election": 1,
}

#: worst-case same-shape VMEM buffer copies of any ring family
WORST_RING_COPIES = 1 + max(RING_FAMILIES.values()) + COMM_SLOTS

_INT32_BYTES = 4


def ring_buffer_copies(n_out: int) -> int:
    """Simultaneous whole-payload VMEM buffers of one ring program."""
    return 1 + n_out + COMM_SLOTS


def derive_max_election_elems(
    target: str | None = None, copies: int = WORST_RING_COPIES
) -> int:
    """Largest power-of-two padded int32 element count E whose worst-case
    ring footprint (`copies` same-shape buffers) fits the target VMEM
    budget. Power of two: the (8, 128)-tiled padded buffers bucket
    compile shapes, and a non-power threshold would re-bucket every call
    site on a budget-table tweak."""
    budget = VMEM_BUDGET_BYTES[target or VMEM_TARGET]
    cap = budget // (copies * _INT32_BYTES)
    if cap < 1:
        raise ValueError(
            f"VMEM budget {budget} cannot hold {copies} int32 buffers"
        )
    elems = 1
    while elems * 2 <= cap:
        elems *= 2
    return elems


def max_election_elems() -> int:
    """The solver-gate threshold: derived from the envelope model, with
    the SPT_PALLAS_MAX_ELECTION_ELEMS escape hatch for experiments (the
    kernel auditor refuses to write a manifest under an override — the
    committed number is always the derived one)."""
    override = os.environ.get("SPT_PALLAS_MAX_ELECTION_ELEMS")
    if override is not None:
        return int(override)
    return derive_max_election_elems()
