"""Optimistic-concurrency K-lane solve: speculate in parallel, commit
through one conflict fence.

The reference runs as a *second scheduler* beside kube-scheduler against
shared cluster state (SURVEY.md §L0, deploy/k8s.yaml): multiple actors
solve optimistically and the apiserver's bind serializes them. This
module reproduces that concurrency model INSIDE one process, against one
resident snapshot:

1. **Partition** (`partition_segments`): the sorted pending queue
   groups into SEGMENTS by a deterministic key — the PodGroup full name
   for gang members (a gang never splits across lanes), else the
   namespace (default) or the pod's admission serial
   (`Cluster.admission_serial`) — and segments pack onto K lanes by
   deterministic LPT (balance bounds the longest lane's scan, and the
   fence makes lane membership semantically irrelevant). Each lane's
   pods keep their global queue positions, so every lane is an
   order-preserving subsequence of the serial order.
2. **Speculate** (`lane_solve_fn`): every lane runs the bit-faithful
   sequential step (`framework.runtime._solve_step`) over ITS pods
   against the same cycle-initial state — one jit, vmapped over the lane
   axis (`dispatch="fused"`), K dispatches of the shared (1, L) program
   on named worker threads (`dispatch="threads"`), or the same K
   dispatches one-at-a-time with exact per-lane wall attribution
   (`dispatch="sequential"`).
3. **Fence** (`lane_screen_fn` + `_fence_refine`): pods commit in the
   DEFINED SERIAL ORDER (= global queue order, the exact order
   `run_cycle`'s scan commits). A compiled monotone screen (one jitted
   dispatch over the device-resident columns) first proves most pods
   order-independent wholesale; the (usually empty) remainder is
   re-checked exactly, in order, on host int64 twins of the device
   math. The first pod whose step would genuinely diverge triggers ONE
   whole-suffix re-solve against the committed state through the same
   program — so the result is bit-identical to the serial scan at
   every K, by construction.

Why the fence is exact (docs/SCALING.md has the long form, extending
docs/GANGS.md's monotone argument): under the fence-exact gate
(`fence_exact`) no profile Filter is live and no Score reads the carried
state, so pod p's step is a pure function of (admit verdicts, built-in
fit mask) — the step SIGNATURE. Equal signatures under the
lane-speculative and the committed state ⇒ identical feasible set ⇒
identical normalization, argmax choice, fail code and commits. Commits
move the carries MONOTONICALLY — `free` only shrinks, `eq_used` /
`gang_inflight` only grow (the GANGS.md direction) — and both states
pod p compares lie between the cycle-initial and the all-lanes-final
carries, differing only through OTHER lanes' commits. So a signature
component that agrees at those two precomputable extremes — restricted
to nodes/tables other lanes actually touched — is constant across the
whole interval (`lane_screen_fn`, ONE compiled dispatch, no per-pod
host work); only screen-flagged pods pay the exact per-pod twins
(`_fence_refine`). Disjoint-tenant lanes therefore validate wholesale
with an empty refine set; contended traffic degrades to the exact walk
plus one repair solve — never worse than serial by more than the
fence.
"""

from __future__ import annotations

import hashlib
import time
from concurrent.futures import ThreadPoolExecutor
from dataclasses import dataclass, field

import jax
import jax.numpy as jnp
import numpy as np

from scheduler_plugins_tpu.framework.plugin import Plugin, SolverState
from scheduler_plugins_tpu.framework.runtime import _solve_step
from scheduler_plugins_tpu.ops.fit import pod_fit_demand
from scheduler_plugins_tpu.tuning.gates import pod_fit_demand_np
from scheduler_plugins_tpu.utils import observability as obs

#: lane partition modes: gang members ALWAYS key on their PodGroup full
#: name (quorum accounting is per-gang state — splitting a gang across
#: lanes would let two lanes each count a partial quorum); non-members
#: key on the namespace (tenant traffic is naturally disjoint) or on the
#: admission serial (uniform spray, for single-tenant rosters)
PARTITION_MODES = ("namespace", "hash")

#: lane solver dispatch: "fused" = ONE jit, vmapped over the lane axis;
#: "threads" = K dispatches of the shared (1, L) program on named worker
#: threads ("spt-lane-w*", docs/race_audit.json) — same outputs, real
#: thread-level overlap when the backend releases the GIL AND the host
#: has cores to overlap onto; "sequential" = the same K dispatches on
#: the caller thread, one after another, each wall-timed into
#: `LaneStats.lane_ms` — the per-lane critical-path attribution mode
#: (on this repo's 1-core CI host threads cannot overlap, so sequential
#: is also the jitter-free way to measure what K independent scheduler
#: processes would each pay; see docs/SCALING.md)
DISPATCH_MODES = ("fused", "threads", "sequential")


def lane_key(pod, cluster, mode: str = "namespace") -> str:
    """The deterministic partition key for one pending pod."""
    pg = cluster.pod_group_of(pod) if cluster is not None else None
    if pg is not None:
        return "gang:" + pg.full_name
    if mode == "namespace":
        return "ns:" + pod.namespace
    serial = cluster.admission_serial(pod.uid) if cluster is not None else -1
    return "serial:%d" % serial


def lane_of(key: str, k: int) -> int:
    """Stable key -> lane hash (blake2b, not `hash()`: PYTHONHASHSEED
    must never affect it). `partition_segments` packs segments by
    balanced LPT rather than this modulo — the hash remains the
    run-independent spray an external sharder (e.g. a per-scheduler
    watch filter) would use, and tests key on its stability."""
    digest = hashlib.blake2b(key.encode(), digest_size=8).digest()
    return int.from_bytes(digest, "big") % k


def partition_segments(pending, cluster, k: int, mode: str = "namespace",
                       key_cache: dict | None = None):
    """(lanes, seg_of_pod, lane_of_seg, seg_keys) — the K lane index
    lists (each ascending: lanes are order-preserving subsequences of
    the serial order) plus the partition-KEY segmentation beneath them:
    pods with the same key (namespace / gang / serial) share a segment,
    every segment lives wholly inside one lane. The screen's fit
    certificate runs at segment grain — a lane is only as coarse as the
    tenants packed onto it, so certifying per segment keeps the
    certificate sharp when K is small (segment ids are first-seen
    ordered, deterministic for a given queue order).

    Segments pack onto lanes by deterministic LPT (longest first, ties
    by first-seen order; each to the least-loaded lane, ties to the
    lowest index) instead of key-hash modulo: the fence makes lane
    membership semantically irrelevant — bit-identity holds under ANY
    key-disjoint split — so the partition is free to chase balance. The
    critical path is the LONGEST lane's scan; a hash split leaves it
    ~30% over P/K at small K (measured: 1,070 of 3,600 pods on one of
    4 lanes), which a half-octave bucket then rounds UP again.

    `key_cache` (optional, caller-owned uid -> key dict) memoizes the
    per-pod key across cycles — pods persist until placed, so the
    steady-state cost is one dict hit per pod instead of a blake2b +
    group lookup (measured 6.1 ms -> sub-ms at P=3,600). A pod carrying
    a pod-group label whose PodGroup object is not registered YET is
    never cached: its key must flip to `gang:` the moment the group
    appears (a stale `ns:` key could split the gang across lanes).
    `fresh` lists the positions that MISSED the cache — for the caller
    these are exactly the pods not yet folded into any cross-cycle
    per-key aggregate keyed off this cache (all positions when no cache
    rides along)."""
    if mode not in PARTITION_MODES:
        raise ValueError(
            f"unknown lane partition mode {mode!r}; expected one of "
            f"{PARTITION_MODES}"
        )
    n = len(pending)
    seg_ids: dict = {}
    seg_list: list = []
    seg_keys: list = []
    fresh: list = []
    # the per-pod pass is THE serial prologue of the laned path — keep
    # it to one dict hit and one list append per pod (bulk-convert to
    # numpy after; per-element ndarray stores measured ~3x slower)
    cache_get = key_cache.get if key_cache is not None else None
    seg_get = seg_ids.get
    append = seg_list.append
    for i, pod in enumerate(pending):
        key = cache_get(pod.uid) if cache_get is not None else None
        if key is None:
            key = lane_key(pod, cluster, mode)
            if key_cache is not None and (
                key.startswith("gang:") or not pod.pod_group()
            ):
                key_cache[pod.uid] = key
            fresh.append(i)
        s = seg_get(key)
        if s is None:
            s = seg_ids[key] = len(seg_keys)
            seg_keys.append(key)
        append(s)
    S = len(seg_keys)
    seg_of_pod = (
        np.asarray(seg_list, np.int32) if n else np.zeros(0, np.int32)
    )
    lane_of_seg = np.zeros(max(1, S), np.int32)
    if k > 1 and S:
        sizes = np.bincount(seg_of_pod, minlength=S)
        load = [0] * k
        for s in np.argsort(-sizes, kind="stable"):
            j = min(range(k), key=load.__getitem__)
            lane_of_seg[s] = j
            load[j] += int(sizes[s])
    lane_of_pod = lane_of_seg[seg_of_pod]
    lanes = [np.flatnonzero(lane_of_pod == j).tolist() for j in range(k)]
    return lanes, seg_of_pod, lane_of_seg, seg_keys, fresh


def partition_lanes(pending, cluster, k: int, mode: str = "namespace"):
    """K lists of global queue positions (each ascending — lanes are
    order-preserving subsequences of the serial order)."""
    return partition_segments(pending, cluster, k, mode)[0]


def fence_exact(scheduler, snap):
    """(ok, reason) — whether the conflict fence's host validation is
    EXACT for this profile + snapshot. Outside the gate the laned path
    falls back to the sequential parity solve (counted by
    `scheduler_lane_serial_fallbacks_total`), never to a weaker fence:

    - side tables that arm profile Filters or state-dependent commits
      (scheduling / network / NUMA) break the "step is a pure function
      of (admit, fit)" argument;
    - preemption nominees make the built-in fit read nominee holds
      keyed on `placed_mask` — cross-lane state the fence's per-lane
      free mirror does not carry;
    - an admit plugin without a host twin here cannot be validated.
    """
    if snap.scheduling is not None:
        return False, "scheduling"
    if snap.network is not None:
        return False, "network"
    if snap.numa is not None:
        return False, "numa"
    if snap.nominees is not None:
        return False, "nominees"
    if snap.quota is not None:
        # the nominee axis is padded to M >= 1; only LIVE rows (nonzero
        # request or a set contribution mask) couple the quota admit to
        # the cross-lane placed_mask carry
        q = snap.quota
        if (
            np.asarray(q.nom_req).any()
            or np.asarray(q.nom_in_eq_mask).any()
            or np.asarray(q.nom_total_mask).any()
        ):
            return False, "quota_nominees"
    from scheduler_plugins_tpu.plugins import CapacityScheduling, Coscheduling

    for p in scheduler.profile.plugins:
        if type(p).admit is Plugin.admit:
            continue
        if not isinstance(p, (Coscheduling, CapacityScheduling)):
            return False, f"admit:{p.name}"
    return True, None


# ---------------------------------------------------------------------------
# The lane solver program
# ---------------------------------------------------------------------------


def lane_solve_fn(scheduler):
    """The speculative lane solve: vmap over the lane axis of a scan of
    THE parity step body (`_solve_step` — one copy, shared with
    `Scheduler.solve`, so a lane cannot drift from the serial scan).

    The throughput trick is pod-table RESIDENCY: each lane's pod rows
    are gathered ONCE, outside the scan (`pods_table[idx]`, one
    vectorized gather per column), and ride the scan `xs` — every step
    hands the body a one-pod snapshot view (`p = 0`, a static row
    select that compiles away). The step body therefore runs ZERO
    batched gathers: on CPU those lower to per-row scalar loops that
    made the per-step cost grow ~linearly with K (measured ~0.7 µs/K
    per step), capping fused lanes below 2x regardless of K; on TPU
    they are vmem-hostile dynamic slices (the CLAUDE.md gotcha).
    Padded slots fold `live` into the row's `mask`, so the step's own
    PreFilter gate makes them no-op carries emitting the "masked pod"
    outputs (-1 / False / 0) the serial scan produces for padded rows.

    Exactness note: the one-pod view relies on the fence-exact gate —
    every live table a plugin indexes by a POD axis lives in
    `snap.pods` (gathered here) or is pinned off (`snap.numa`'s
    presolve carries a pod axis; `fence_exact` rejects armed numa /
    scheduling / network / nominee tables). `SolverState.placed_mask`
    is written at the view-local index but never read under the gate
    (quota nominee rows are inert), and the serial-order fence ignores
    it.

    Signature: fn(snap, state0, auxes, idx, live) with idx/live shaped
    (K, L); returns ((K, L) int32 choice, (K, L) bool admitted,
    (K, L) int32 fail_code). The same program repairs conflicts at
    (1, L') — seeded with the committed state instead of state0."""
    plugins = tuple(scheduler.profile.plugins)
    unroll = scheduler._scan_unroll()

    def fn(snap, state0, auxes, idx, live):
        for plugin, aux in zip(plugins, auxes):
            plugin.bind_aux(aux)
        for plugin in plugins:
            plugin.bind_presolve(plugin.prepare_solve(snap))
        rows = jax.tree.map(lambda a: a[idx], snap.pods)
        rows = rows.replace(mask=rows.mask & live)

        def lane(lane_rows):
            def body(carry, r):
                step_snap = snap.replace(
                    pods=jax.tree.map(lambda a: a[None], r)
                )
                return _solve_step(plugins, carry, 0, step_snap)

            _, outs = jax.lax.scan(
                body, state0, lane_rows, unroll=unroll
            )
            return outs

        return jax.vmap(lane)(rows)

    return fn


def _cached_lane_fn(scheduler):
    """The jitted lane program, cached on the scheduler like every other
    solve-family program. The weight tuple rides the key (the lane scan
    BAKES `plugin.weight` trace constants, like explain/packing), so a
    live-weight swap retraces instead of serving stale scores — and
    `set_live_weights`' eviction sweep can find the entry."""
    key = ("lane_solve", scheduler._scan_unroll()) + scheduler.weights_key() \
        + tuple(p.static_key() for p in scheduler.profile.plugins)
    cache = scheduler._solve_cache
    if key not in cache:
        cache[key] = obs.compile_watch(
            jax.jit(lane_solve_fn(scheduler)), program="lane_solve"
        )
    return cache[key]


#: smallest lane scan bucket: sub-8 lane lengths all share one compiled
#: (K, 8) shape — masked padded steps cost microseconds, a fresh XLA
#: compile costs most of a second (and the tier-1 suite runs at the
#: budget cliff)
MIN_LANE_BUCKET = 8


def _pow2(n: int) -> int:
    return max(MIN_LANE_BUCKET, 1 << max(0, int(n - 1)).bit_length())


def _bucket(n: int) -> int:
    """Half-octave scan bucket: the next size in {8, 12, 16, 24, 32,
    48, ...} >= n. Pure power-of-two buckets waste up to 2x scan steps
    right above a boundary (a 1,070-pod lane would scan 2,048 padded
    steps); the intermediate 3·2^(m-2) sizes cap the waste at ~33% for
    at most 2x the compile-cache entries."""
    p = _pow2(n)
    h = (p * 3) // 4
    return h if n <= h and h >= MIN_LANE_BUCKET else p


# ---------------------------------------------------------------------------
# The conflict fence: host twins of the admit/commit math
# ---------------------------------------------------------------------------


def _lane_deficits(req, free0, assignment, lane_of_pod, k: int):
    """Shared screen prelude: per-lane speculative node deficits and the
    two state extremes. Sums ride float64 (exact below 2^53, the
    repo-wide dodge — int64 scatter-adds are the TPU gotcha); compares
    stay exact because every quantity is an integer-valued float64."""
    demand = pod_fit_demand(req)
    placed = assignment >= 0
    choice = jnp.maximum(assignment, 0)
    free0f = free0.astype(jnp.float64)
    N = free0f.shape[0]
    demf = demand.astype(jnp.float64)
    w = demf * placed[:, None]
    flat = lane_of_pod * N + choice
    lanedef = jax.ops.segment_sum(w, flat, num_segments=k * N)
    lanedef = lanedef.reshape(k, N, demand.shape[1])
    alldef = lanedef.sum(axis=0)
    othersdef = alldef[None] - lanedef
    free_fin = free0f - alldef
    return demf, placed, free0f, free_fin, othersdef, alldef


def lane_screen_fn(k: int, quota_on: bool, gang_on: bool):
    """The compiled fence stage 1 — the vectorized monotone screen as ONE
    jitted program over the device-resident snapshot columns, so the
    wholesale-commit fast path costs a single dispatch instead of a dozen
    device->host pulls plus O(P·N·R) numpy (measured 1.6 ms vs ~0.3 ms at
    P=1024, N=48 — the numpy screen alone out-weighed the K-lane solve it
    was validating).

    The math is the exact program `_fence_refine`'s docstring argument
    needs: per-lane speculative deficits -> the two state extremes
    (cycle-initial, all-lanes-final) -> a pod is flagged iff some
    signature component (fit row, quota admit, gang min-res admit)
    DISAGREES between the extremes restricted to nodes/tables OTHER
    lanes touched.

    The built-in fit component runs at SEGMENT granularity here (one
    segment per partition key — `partition_segments`), not pod
    granularity: `fit_unsafe` certifies per (segment, node) that no
    segment pod's fit bit at node n can flip, via three sufficient
    conditions (each one pins fits_hi == fits_lo for EVERY pod of the
    segment):

    - no OTHER lane committed onto n — then the committed and
      speculative columns for n are identical (the segment's own lane's
      commits appear in both), so there is no interval to cross;
    - the segment's axiswise MAX demand bound fits `free_fin[n]` — then
      every segment pod still fits at the low extreme (fits_lo true,
      and lo ⊆ hi);
    - the segment's axiswise MIN demand bound exceeds `free0[n]` on
      some axis — then no segment pod ever fit at the high extreme
      (tenant traffic on dedicated node groups certifies through this
      arm: a foreign group's extended-resource column is 0).

    The (S, R) demand extremes ride in as INPUTS (`seg_mx` / `seg_mn`),
    host-accumulated by `LaneSolver` over every pod ever seen with the
    key — a conservative SUPERSET of the live pods (max only grows, min
    only shrinks), so both arms stay sufficient while the O(P·R)
    segment reductions drop out of the per-cycle dispatch (measured:
    segment_max + segment_min alone were ~0.6 ms of a 1.65 ms dispatch
    at P=4,096). Padded segment rows carry the -inf/+inf identities and
    are trivially safe.

    That is O(S·N·R) compares instead of O(P·N·R) — the per-pod fit
    screen (`lane_screen_fit_fn`) dispatches ONLY when some (segment,
    node) pair stays unsafe, so disjoint-tenant traffic never pays it
    (measured: the P=3,600 per-pod screen alone cost ~2.5 ms, ~40% of
    the whole serial solve it was meant to beat).

    Args are three flat tuples (`core`, `quota`, `gang` — the latter
    two empty when the branch is off) of exactly the columns the
    branches read, NOT the snapshot/state pytrees: flattening the full
    snapshot per dispatch cost ~0.4 ms of host overhead at P=4,096.

    Returns (fit_unsafe: scalar bool, flagged: (P,) bool quota|gang
    component); the host ORs in the per-pod fit screen when unsafe and
    keeps `np.flatnonzero(flagged[:P_live])` as the refine candidate
    set — a conservative SUPERSET of true conflicts, empty on
    disjoint-lane traffic."""

    def fn(core, quota, gang_args):
        (req, pod_mask, gated, free0, node_mask, assignment,
         lane_of_pod, seg_mx, seg_mn, lane_of_seg) = core
        ok0 = pod_mask & ~gated
        demf, placed, free0f, free_fin, othersdef, alldef = _lane_deficits(
            req, free0, assignment, lane_of_pod, k
        )
        f64 = jnp.float64

        # segment-level fit certificates (see docstring)
        touched = (othersdef > 0).any(axis=2)  # (K, N)
        max_fits = (seg_mx[:, None, :] <= free_fin[None]).all(axis=2)
        min_fails = (seg_mn[:, None, :] > free0f[None]).any(axis=2)
        fit_unsafe = (
            touched[lane_of_seg] & ~max_fits & ~min_fails
            & node_mask[None]
        ).any()

        flagged = jnp.zeros(assignment.shape[0], bool)
        if quota_on:
            ns, qm, q_min, q_max, eq_used0 = quota
            reqf = req.astype(f64)
            hasq = qm[ns]
            contrib = placed & hasq
            eq0 = eq_used0.astype(f64)
            eq_fin = eq0 + jax.ops.segment_sum(
                reqf * contrib[:, None], ns, num_segments=eq0.shape[0]
            )
            eq_min = q_min.astype(f64)
            eq_max = q_max.astype(f64)
            agg_min = (eq_min * qm[:, None]).sum(axis=0)
            agg_hi = (eq0 * qm[:, None]).sum(axis=0)
            agg_lo = (eq_fin * qm[:, None]).sum(axis=0)
            pass_hi = (
                ~(eq0[ns] + reqf > eq_max[ns]).any(axis=1)
                & ~(agg_hi[None] + reqf > agg_min[None]).any(axis=1)
            )
            pass_lo = (
                ~(eq_fin[ns] + reqf > eq_max[ns]).any(axis=1)
                & ~(agg_lo[None] + reqf > agg_min[None]).any(axis=1)
            )
            lane_q = jax.ops.segment_sum(
                contrib.astype(f64), lane_of_pod, num_segments=k
            )
            others_q = lane_q.sum() - lane_q
            flagged |= (
                hasq & (others_q[lane_of_pod] > 0) & (pass_hi != pass_lo)
            )

        if gang_on:
            gang, g_slack, g_min_res, g_has_min_res, infl_used0 = gang_args
            g = jnp.maximum(gang, 0)
            total0 = free0f.sum(axis=0)
            total_fin = total0 - alldef.sum(axis=0)
            infl0 = infl_used0.astype(f64)
            ing = placed & (gang >= 0)
            infl_fin = infl0 + jax.ops.segment_sum(
                demf * ing[:, None], g, num_segments=infl0.shape[0]
            )
            lane_n = jax.ops.segment_sum(
                placed.astype(f64), lane_of_pod, num_segments=k
            )
            others_n = lane_n.sum() - lane_n
            slack = g_slack.astype(f64)
            min_res = g_min_res.astype(f64)
            cap_hi = total0[None] + slack[g] + infl0[g]
            cap_lo = total_fin[None] + slack[g] + infl_fin[g]
            pass_hi = (min_res[g] <= cap_hi).all(axis=1)
            pass_lo = (min_res[g] <= cap_lo).all(axis=1)
            flagged |= (
                (gang >= 0) & g_has_min_res[g]
                & (others_n[lane_of_pod] > 0) & (pass_hi != pass_lo)
            )

        # dead pods (masked / gated) decide (-1 / False / 0) under ANY
        # state — no flip can change their outputs or commits
        return fit_unsafe, flagged & ok0

    return fn


def lane_screen_fit_fn(k: int):
    """The per-pod fit screen — the O(P·N·R) refinement of the lane
    certificate, dispatched only when `lane_screen_fn` reports some
    (lane, node) pair fit-unsafe. A pod is flagged iff its fit bit flips
    between the extremes on a live node some OTHER lane committed onto —
    the exact per-pod form of the monotone-sandwich argument."""

    def fn(req, pod_mask, gated, free0, node_mask, assignment, lane_of_pod):
        ok0 = pod_mask & ~gated
        demf, _, free0f, free_fin, othersdef, _ = _lane_deficits(
            req, free0, assignment, lane_of_pod, k
        )
        fits_hi = (demf[:, None, :] <= free0f[None]).all(axis=2)
        fits_lo = (demf[:, None, :] <= free_fin[None]).all(axis=2)
        flipable = (othersdef > 0).any(axis=2)  # (K, N)
        flagged = (
            (fits_hi & ~fits_lo)
            & flipable[lane_of_pod] & node_mask[None]
        ).any(axis=1)
        return flagged & ok0

    return fn


def _cached_screen_fn(scheduler, k: int, quota_on: bool, gang_on: bool):
    """The jitted screen, cached beside the lane program. No weight
    dependence (the screen reads admit/fit inputs, never scores), so the
    key carries only the branch structure."""
    key = ("lane_screen", k, quota_on, gang_on)
    cache = scheduler._solve_cache
    if key not in cache:
        cache[key] = obs.compile_watch(
            jax.jit(lane_screen_fn(k, quota_on, gang_on)),
            program="lane_screen",
        )
    return cache[key]


def _cached_screen_fit_fn(scheduler, k: int):
    key = ("lane_screen_fit", k)
    cache = scheduler._solve_cache
    if key not in cache:
        cache[key] = obs.compile_watch(
            jax.jit(lane_screen_fit_fn(k)),
            program="lane_screen_fit",
        )
    return cache[key]


@dataclass
class _FenceState:
    """One actor's view of the in-cycle carried state, on host int64 —
    the committed truth, or one lane's speculative mirror. Mutations
    mirror `_solve_step`'s commits bit-exactly (trivially: int64 adds)."""

    free: np.ndarray  # (N, R)
    total_free: np.ndarray  # (R,) raw per-node sum, negatives included
    eq_used: np.ndarray | None  # (Q, R)
    gang_inflight: np.ndarray | None  # (G, R)

    def clone(self) -> "_FenceState":
        return _FenceState(
            self.free.copy(), self.total_free.copy(),
            None if self.eq_used is None else self.eq_used.copy(),
            None if self.gang_inflight is None else self.gang_inflight.copy(),
        )

    def commit(self, t: "_FenceTables", p: int, choice: int) -> None:
        if choice < 0:
            return  # failed pods mutate nothing (the scan's where-gates)
        d = t.demand[p]
        self.free[choice] -= d
        self.total_free -= d
        if self.eq_used is not None and t.has_quota[t.ns[p]]:
            self.eq_used[t.ns[p]] += t.req[p]
        g = t.gang[p]
        if self.gang_inflight is not None and g >= 0:
            self.gang_inflight[g] += d


@dataclass
class _FenceTables:
    """Host copies of the static snapshot columns the fence reads."""

    req: np.ndarray  # (P, R)
    demand: np.ndarray  # (P, R) — req with the pods slot forced to 1
    ns: np.ndarray  # (P,)
    gang: np.ndarray  # (P,)
    ok0: np.ndarray  # (P,) mask & ~gated
    node_mask: np.ndarray  # (N,)
    has_quota: np.ndarray | None  # (Q,)
    eq_min: np.ndarray | None  # (Q, R)
    eq_max: np.ndarray | None  # (Q, R)
    g_min_member: np.ndarray | None
    g_total: np.ndarray | None
    g_gated: np.ndarray | None
    g_backed_off: np.ndarray | None
    g_slack: np.ndarray | None  # (G, R)
    g_min_res: np.ndarray | None  # (G, R)
    g_has_min_res: np.ndarray | None
    g_assigned: np.ndarray | None
    #: admit twins in PROFILE ORDER ("gang" | "quota") — verdict
    #: equality must be compared per plugin, in order, or the
    #: attribution code could silently differ
    admit_plugins: list = field(default_factory=list)


def _fence_tables(scheduler, snap) -> _FenceTables:
    from scheduler_plugins_tpu.plugins import CapacityScheduling, Coscheduling

    req = np.asarray(snap.pods.req)
    t = _FenceTables(
        req=req,
        demand=np.asarray(pod_fit_demand(jnp.asarray(req))),
        ns=np.asarray(snap.pods.ns),
        gang=np.asarray(snap.pods.gang),
        ok0=np.asarray(snap.pods.mask) & ~np.asarray(snap.pods.gated),
        node_mask=np.asarray(snap.nodes.mask),
        has_quota=None, eq_min=None, eq_max=None,
        g_min_member=None, g_total=None, g_gated=None, g_backed_off=None,
        g_slack=None, g_min_res=None, g_has_min_res=None, g_assigned=None,
    )
    if snap.quota is not None:
        t.has_quota = np.asarray(snap.quota.has_quota)
        t.eq_min = np.asarray(snap.quota.min)
        t.eq_max = np.asarray(snap.quota.max)
    if snap.gangs is not None:
        t.g_min_member = np.asarray(snap.gangs.min_member)
        t.g_total = np.asarray(snap.gangs.total_members)
        t.g_gated = np.asarray(snap.gangs.gated)
        t.g_backed_off = np.asarray(snap.gangs.backed_off)
        t.g_slack = np.asarray(snap.gangs.cluster_slack)
        t.g_min_res = np.asarray(snap.gangs.min_resources)
        t.g_has_min_res = np.asarray(snap.gangs.has_min_resources)
        t.g_assigned = np.asarray(snap.gangs.assigned)
    for p in scheduler.profile.plugins:
        if isinstance(p, Coscheduling) and snap.gangs is not None:
            t.admit_plugins.append("gang")
        elif isinstance(p, CapacityScheduling) and snap.quota is not None:
            t.admit_plugins.append("quota")
    return t


def _gang_admit_np(t: _FenceTables, s: _FenceState, p: int) -> bool:
    """Numpy twin of `ops.gang.gang_admit` (gang_scheduled plays no role
    in admission — it only feeds the post-scan quorum reduction)."""
    g = int(t.gang[p])
    if g < 0:
        return True
    if t.g_total[g] < t.g_min_member[g]:
        return False
    if t.g_backed_off[g]:
        return False
    if t.g_total[g] - t.g_gated[g] < t.g_min_member[g]:
        return False
    if not t.g_has_min_res[g]:
        return True
    capacity = s.total_free + t.g_slack[g]
    if s.gang_inflight is not None:
        capacity = capacity + s.gang_inflight[g]
    return bool(np.all(t.g_min_res[g] <= capacity))


def _quota_admit_np(t: _FenceTables, s: _FenceState, p: int) -> bool:
    """Numpy twin of `ops.quota.quota_admit` with empty nominee
    aggregates (the fence-exact gate pins M == 0)."""
    ns = int(t.ns[p])
    if not t.has_quota[ns]:
        return True
    req = t.req[p]
    if np.any(s.eq_used[ns] + req > t.eq_max[ns]):
        return False
    agg_used = s.eq_used[t.has_quota].sum(axis=0)
    agg_min = t.eq_min[t.has_quota].sum(axis=0)
    return not np.any(agg_used + req > agg_min)


def _step_signature(t: _FenceTables, s: _FenceState, p: int):
    """Everything pod p's step depends on through the carried state,
    under the fence-exact gate: the per-plugin admit verdicts (profile
    order) and the built-in fit mask. Two states with equal signatures
    replay the step identically — equal feasible set ⇒ equal normalized
    scores ⇒ equal argmax/fail-code/commits."""
    verdicts = []
    for kind in t.admit_plugins:
        if kind == "gang":
            verdicts.append(_gang_admit_np(t, s, p))
        else:
            verdicts.append(_quota_admit_np(t, s, p))
    fit = np.all(t.demand[p] <= s.free, axis=1) & t.node_mask
    return verdicts, fit


def _fence_refine(t: _FenceTables, free0, eq0, infl0, assignment,
                  lane_of_pod, candidates, k: int):
    """Fence stage 2: exact serial-order validation of the screen's
    candidates. Every pod up to the last candidate replays its cheap
    int64 delta commits (the committed truth + each lane's speculative
    mirror); the expensive per-pod signature twins run ONLY at
    candidate indices. Returns (conflict_at, committed-state-at-
    conflict) — (-1, None) when every candidate validates, in which
    case screen + refine together prove the whole cycle conflict-free."""
    committed = _FenceState(
        free=free0.copy(), total_free=free0.sum(axis=0),
        eq_used=None if eq0 is None else eq0.copy(),
        gang_inflight=None if infl0 is None else infl0.copy(),
    )
    lane_states = [committed.clone() for _ in range(k)]
    cand = {int(c) for c in candidates}
    for p in range(max(cand) + 1):
        j = int(lane_of_pod[p])
        mine = lane_states[j]
        if p in cand:
            sig_lane = _step_signature(t, mine, p)
            sig_comm = _step_signature(t, committed, p)
            if sig_lane[0] != sig_comm[0] or not np.array_equal(
                sig_lane[1], sig_comm[1]
            ):
                return p, committed
        choice = int(assignment[p])
        committed.commit(t, p, choice)
        mine.commit(t, p, choice)
    return -1, None


# ---------------------------------------------------------------------------
# The orchestrator
# ---------------------------------------------------------------------------


@dataclass
class LaneStats:
    """One cycle's lane attribution (rides `CycleReport.lanes`)."""

    k: int
    path: str  # "laned" | "serial"
    sizes: list = field(default_factory=list)
    #: verbatim-committed pods per lane
    committed: list = field(default_factory=list)
    #: fence conflicts per lane (the lane whose pod first failed
    #: validation — at most one per cycle: the repair covers the rest)
    conflicts: list = field(default_factory=list)
    #: pods re-resolved against committed state by the repair solve
    re_resolved: int = 0
    serial_fallback_reason: str | None = None
    solve_ms: float = 0.0
    fence_ms: float = 0.0
    #: partition + segment-stat upkeep wall (ms): the serial coordinator
    #: prologue a K-process deployment pays before fanning out — counted
    #: INSIDE solve_ms, broken out so the critical path
    #: (partition_ms + max(lane_ms) + fence_ms) is honest
    partition_ms: float = 0.0
    #: per-lane dispatch wall (ms) — "sequential" mode times each lane's
    #: (1, L) program alone on the caller thread (exact per-lane
    #: attribution: max(lane_ms) + fence_ms is the critical path a
    #: K-core / K-process deployment pays); "threads" mode records the
    #: same spans but overlapping workers inflate each other's wall.
    #: Empty under "fused" (one program, no per-lane boundary).
    lane_ms: list = field(default_factory=list)

    def as_dict(self) -> dict:
        return {
            "k": self.k,
            "path": self.path,
            "sizes": list(self.sizes),
            "committed": list(self.committed),
            "conflicts": list(self.conflicts),
            "re_resolved": self.re_resolved,
            "serial_fallback_reason": self.serial_fallback_reason,
            "solve_ms": round(self.solve_ms, 3),
            "fence_ms": round(self.fence_ms, 3),
            "partition_ms": round(self.partition_ms, 3),
            "lane_ms": [round(m, 3) for m in self.lane_ms],
        }


class LaneSolver:
    """K speculative solver lanes over one scheduler, committed through
    the single conflict fence. `solve(snap, pending, cluster)` returns
    (assignment, admitted, wait, fail_codes) host arrays bit-identical
    to `Scheduler.solve`'s sequential scan, plus a `LaneStats`."""

    def __init__(self, scheduler, k: int = 4, partition: str = "namespace",
                 dispatch: str = "fused"):
        if k < 1:
            raise ValueError(f"lane count must be >= 1, got {k}")
        if dispatch not in DISPATCH_MODES:
            raise ValueError(
                f"unknown lane dispatch mode {dispatch!r}; expected one "
                f"of {DISPATCH_MODES}"
            )
        if partition not in PARTITION_MODES:
            raise ValueError(
                f"unknown lane partition mode {partition!r}; expected "
                f"one of {PARTITION_MODES}"
            )
        self.scheduler = scheduler
        self.k = k
        self.partition = partition
        self.dispatch = dispatch
        # cross-cycle partition + screen-input caches (pods persist
        # until placed, so steady-state upkeep is arrivals-only):
        # uid -> partition key, and key -> (axiswise max, axiswise min)
        # float64 (R,) demand extremes accumulated over every pod EVER
        # folded into the key — a conservative superset of any cycle's
        # live pods (max only grows, min only shrinks), which is
        # exactly the direction the screen's sufficient conditions
        # need. A pod folds exactly when it misses the key cache, so
        # the two caches prune together and the invariant "every cached
        # uid's demand is folded into its key's stats" holds by
        # construction. Invalidated wholesale whenever the snapshot's
        # resource axis changes (`_axis_sig`).
        self._key_cache: dict = {}
        self._seg_stats: dict = {}
        self._axis_sig = None
        self._pool = None
        if dispatch == "threads" and k > 1:
            # named per GL012: the race audit's entry table models these
            # workers (docs/race_audit.json "spt-lane-w*") — they only
            # EXECUTE the compiled lane program (tracing, which mutates
            # plugin bind state, happens on the caller thread first)
            self._pool = ThreadPoolExecutor(
                max_workers=k - 1, thread_name_prefix="spt-lane-w"
            )

    def close(self) -> None:
        if self._pool is not None:
            self._pool.shutdown(wait=True)
            self._pool = None

    # -- speculation -----------------------------------------------------
    def _dispatch(self, snap, state0, auxes, idx2d, live2d, stats):
        """Runs the lane program and returns PER-LANE output rows:
        a list of (choice, ok, fail) 1-D arrays, one per lane, each at
        least the lane's length."""
        fn = _cached_lane_fn(self.scheduler)
        if self.dispatch == "fused" or self.k == 1:
            with obs.tracer.span("Lane/solve", tid="Lane/solve",
                                 k=self.k, bucket=int(idx2d.shape[1])):
                out = fn(
                    snap, state0, auxes, jnp.asarray(idx2d),
                    jnp.asarray(live2d),
                )
                out = tuple(np.asarray(o) for o in out)
                return [tuple(o[j] for o in out) for j in range(self.k)]
        # threads/sequential: K dispatches of the (1, L) program. Lane 0
        # (or every lane, sequential) runs on the caller thread FIRST —
        # the one trace (bind_aux / bind_presolve mutate the shared
        # plugin objects at trace time) must not race; workers then
        # execute compiled code only, all at the SHARED max bucket (one
        # shape -> one trace). Sequential mode instead rides each lane's
        # OWN half-octave bucket — per-lane shapes are safe on one
        # thread, and the shorter scans are exactly what K independent
        # scheduler processes would compile. lane_ms writes are
        # per-index disjoint (each worker owns slot j).
        stats.lane_ms = [0.0] * self.k
        seq = self._pool is None

        def one(j):
            t0 = time.perf_counter()
            pods_j = int(live2d[j].sum())
            b = _bucket(pods_j) if seq else live2d.shape[1]
            with obs.tracer.span("Lane/solve", tid=f"Lane/{j}",
                                 pods=pods_j, bucket=b):
                out = fn(
                    snap, state0, auxes,
                    jnp.asarray(idx2d[j:j + 1, :b]),
                    jnp.asarray(live2d[j:j + 1, :b]),
                )
                out = tuple(np.asarray(o)[0] for o in out)
            stats.lane_ms[j] = (time.perf_counter() - t0) * 1000.0
            return out

        if seq:
            outs = [one(j) for j in range(self.k)]
        else:
            first = one(0)
            futures = [
                self._pool.submit(one, j) for j in range(1, self.k)
            ]
            outs = [first] + [f.result() for f in futures]
        return outs

    def _repair(self, snap, auxes, committed: _FenceState, suffix,
                quota_present: bool, gangs_present: bool):
        """Re-solve the whole remaining suffix in ONE dispatch, seeded
        with the committed state — from the first conflict on, this IS
        the serial scan (same step body, same state, same order)."""
        fn = _cached_lane_fn(self.scheduler)
        state = SolverState(
            free=jnp.asarray(committed.free),
            eq_used=(
                jnp.asarray(committed.eq_used) if quota_present else None
            ),
            gang_scheduled=(
                jnp.zeros(self._num_gangs(snap), jnp.int32)
                if gangs_present else None
            ),
            gang_inflight=(
                jnp.asarray(committed.gang_inflight)
                if gangs_present else None
            ),
            placed_mask=(
                jnp.zeros(snap.num_pods, bool) if quota_present else None
            ),
        )
        bucket = _bucket(len(suffix))
        idx = np.zeros((1, bucket), np.int32)
        idx[0, : len(suffix)] = suffix
        live = np.zeros((1, bucket), bool)
        live[0, : len(suffix)] = True
        with obs.tracer.span("Lane/repair", tid="Lane/fence",
                             pods=len(suffix)):
            out = fn(snap, state, auxes, jnp.asarray(idx), jnp.asarray(live))
            return tuple(np.asarray(o)[0, : len(suffix)] for o in out)

    @staticmethod
    def _num_gangs(snap) -> int:
        return int(snap.gangs.min_member.shape[0])

    # -- screen inputs ---------------------------------------------------
    def _segment_extremes(self, snap, pending, seg_of_pod, seg_keys,
                          fresh, meta):
        """(S_b, R) float64 axiswise per-segment demand extremes for the
        screen's fit certificate, padded to the segment bucket with the
        -inf/+inf identities (padded rows are trivially safe).

        Accumulated on host across cycles over every pod EVER folded
        into the key — a conservative superset of this cycle's live
        segment pods, so both certificate arms stay sufficient (the
        accumulated max dominates the live max; the accumulated min is
        dominated by the live min). A pod folds exactly when it misses
        the partition's key cache (`fresh`), so steady-state upkeep is
        arrivals-only and the (P, R) demand pull happens only on cycles
        that have any. `meta.index.names` fingerprints the resource
        axis — a changed axis (new extended resource) drops both caches
        wholesale; without meta the axis LENGTH stands in (axis
        identity is then assumed stable across this solver's
        lifetime)."""
        R = int(snap.pods.req.shape[1])
        sig = tuple(meta.index.names) if meta is not None else ("R", R)
        if sig != self._axis_sig:
            self._axis_sig = sig
            self._key_cache.clear()
            self._seg_stats.clear()
            fresh = range(len(pending))
        stats = self._seg_stats
        dem = None
        if len(fresh):
            dem = pod_fit_demand_np(
                np.asarray(snap.pods.req)
            ).astype(np.float64)
            for i in fresh:
                key = seg_keys[seg_of_pod[i]]
                row = dem[i]
                cur = stats.get(key)
                if cur is None:
                    stats[key] = (row.copy(), row.copy())
                else:
                    np.maximum(cur[0], row, out=cur[0])
                    np.minimum(cur[1], row, out=cur[1])
        missing = {
            s for s, key in enumerate(seg_keys) if key not in stats
        }
        if missing:
            # backstop (externally-mutated cache): a key whose pods all
            # HIT the uid cache yet has no stats — fold every pod of
            # the stats-less segments so the certificate stays sound
            if dem is None:
                dem = pod_fit_demand_np(
                    np.asarray(snap.pods.req)
                ).astype(np.float64)
            for i in range(len(pending)):
                s = int(seg_of_pod[i])
                if s not in missing:
                    continue
                key = seg_keys[s]
                row = dem[i]
                cur = stats.get(key)
                if cur is None:
                    stats[key] = (row.copy(), row.copy())
                else:
                    np.maximum(cur[0], row, out=cur[0])
                    np.minimum(cur[1], row, out=cur[1])
        if len(self._key_cache) > 4 * len(pending) + 1024:
            # bound the caches on long-lived solvers: keep live uids
            # and live keys only. Dropping a departed uid is harmless —
            # it re-folds (a no-op: max/min accumulation is idempotent)
            # if it ever pends again — and a pruned KEY has no live
            # pods left to cover (every kept uid's key is in
            # `seg_keys`, so the fold invariant holds).
            live = {p.uid for p in pending}
            self._key_cache = {
                u: key for u, key in self._key_cache.items() if u in live
            }
            keep = set(seg_keys)
            self._seg_stats = {
                key: v for key, v in self._seg_stats.items()
                if key in keep
            }
        S_b = _bucket(max(1, len(seg_keys)))
        seg_mx = np.full((S_b, R), -np.inf)
        seg_mn = np.full((S_b, R), np.inf)
        for s, key in enumerate(seg_keys):
            mx, mn = stats[key]
            seg_mx[s] = mx
            seg_mn[s] = mn
        return seg_mx, seg_mn

    # -- the solve + fence ----------------------------------------------
    def solve(self, snap, pending, cluster, meta=None):
        """Returns (assignment, admitted, wait, fail_codes, stats) —
        host arrays over the snapshot's (padded) pod axis, bit-identical
        to the sequential parity scan. Falls back to `Scheduler.solve`
        (still bit-identical — it IS the parity path) when K == 1 or the
        fence-exact gate rejects the profile/snapshot. `meta` (the
        snapshot's `SnapshotMeta`, optional) lets the cross-cycle
        screen-input cache fingerprint the resource axis exactly."""
        stats = LaneStats(k=self.k, path="laned")
        exact, reason = fence_exact(self.scheduler, snap)
        if self.k == 1 or not exact:
            stats.path = "serial"
            stats.serial_fallback_reason = reason if not exact else "k=1"
            if not exact:
                obs.metrics.inc(obs.LANE_SERIAL_FALLBACKS)
            t0 = time.perf_counter()
            result = self.scheduler.solve(snap, mode="sequential")
            assignment = np.asarray(result.assignment)
            admitted = np.asarray(result.admitted)
            wait = np.asarray(result.wait)
            codes = np.asarray(result.failed_plugin)
            stats.solve_ms = (time.perf_counter() - t0) * 1000.0
            return assignment, admitted, wait, codes, stats

        t0 = time.perf_counter()
        lanes, seg_of_pod, lane_of_seg, seg_keys, fresh = (
            partition_segments(
                pending, cluster, self.k, self.partition, self._key_cache
            )
        )
        seg_mx, seg_mn = self._segment_extremes(
            snap, pending, seg_of_pod, seg_keys, fresh, meta
        )
        stats.partition_ms = (time.perf_counter() - t0) * 1000.0
        stats.sizes = [len(lane) for lane in lanes]
        stats.committed = [0] * self.k
        stats.conflicts = [0] * self.k
        P_live = len(pending)
        P = snap.num_pods
        bucket = _bucket(max(1, max(stats.sizes) if stats.sizes else 1))
        idx2d = np.zeros((self.k, bucket), np.int32)
        live2d = np.zeros((self.k, bucket), bool)
        lane_of_pod = lane_of_seg[seg_of_pod]
        for j, lane in enumerate(lanes):
            idx2d[j, : len(lane)] = lane
            live2d[j, : len(lane)] = True

        state0 = self.scheduler.initial_state(snap)
        auxes = tuple(p.aux() for p in self.scheduler.profile.plugins)
        outs = self._dispatch(snap, state0, auxes, idx2d, live2d, stats)
        stats.solve_ms = (time.perf_counter() - t0) * 1000.0

        # scatter lane outputs back to pod order. Padded snapshot rows
        # (>= P_live) belong to no lane and keep the masked-pod outputs
        # (-1 / False / 0) — exactly what the serial scan emits for them.
        assignment = np.full(P, -1, np.int32)
        admitted = np.zeros(P, bool)
        codes = np.zeros(P, np.int32)
        for j in range(self.k):
            n = len(lanes[j])
            assignment[idx2d[j, :n]] = outs[j][0][:n]
            admitted[idx2d[j, :n]] = outs[j][1][:n]
            codes[idx2d[j, :n]] = outs[j][2][:n]

        # the conflict fence: stage-1 compiled monotone screen (one
        # dispatch), then the exact serial-order refine over its
        # (usually empty) candidate set — docs/SCALING.md carries the
        # proof. The host fence tables are built LAZILY: the wholesale-
        # commit fast path never pulls the snapshot columns to host.
        t0 = time.perf_counter()
        from scheduler_plugins_tpu.plugins import (
            CapacityScheduling, Coscheduling,
        )
        quota_on = snap.quota is not None and any(
            isinstance(p, CapacityScheduling)
            for p in self.scheduler.profile.plugins
        )
        gang_on = snap.gangs is not None and any(
            isinstance(p, Coscheduling)
            for p in self.scheduler.profile.plugins
        )
        lane_full = np.zeros(P, np.int32)
        lane_full[:P_live] = lane_of_pod
        # the segment axis rides its own bucket (set by
        # `_segment_extremes`) so tenant churn retraces at half-octave
        # boundaries, not every cycle
        S_b = seg_mx.shape[0]
        seg_lanes = np.zeros(S_b, np.int32)
        seg_lanes[: lane_of_seg.shape[0]] = lane_of_seg
        conflict_at, committed = -1, None
        gang_col = None
        with obs.tracer.span("Lane/fence", tid="Lane/fence",
                             pods=P_live):
            screen = _cached_screen_fn(
                self.scheduler, self.k, quota_on, gang_on
            )
            assign_dev = jnp.asarray(assignment)
            lane_dev = jnp.asarray(lane_full)
            core = (
                snap.pods.req, snap.pods.mask, snap.pods.gated,
                state0.free, snap.nodes.mask, assign_dev, lane_dev,
                jnp.asarray(seg_mx), jnp.asarray(seg_mn),
                jnp.asarray(seg_lanes),
            )
            quota_args = (
                (snap.pods.ns, snap.quota.has_quota, snap.quota.min,
                 snap.quota.max, state0.eq_used)
                if quota_on else ()
            )
            gang_args = (
                (snap.pods.gang, snap.gangs.cluster_slack,
                 snap.gangs.min_resources,
                 snap.gangs.has_min_resources, state0.gang_inflight)
                if gang_on else ()
            )
            fit_unsafe, flagged = screen(core, quota_args, gang_args)
            flagged = np.asarray(flagged)
            if bool(np.asarray(fit_unsafe)):
                fit_screen = _cached_screen_fit_fn(self.scheduler, self.k)
                flagged = flagged | np.asarray(
                    fit_screen(
                        snap.pods.req, snap.pods.mask, snap.pods.gated,
                        state0.free, snap.nodes.mask, assign_dev,
                        lane_dev,
                    )
                )
            candidates = np.flatnonzero(flagged[:P_live])
            if candidates.size:
                tables = _fence_tables(self.scheduler, snap)
                gang_col = tables.gang
                free0 = np.asarray(state0.free)
                eq0 = (
                    np.asarray(state0.eq_used)
                    if state0.eq_used is not None else None
                )
                infl0 = (
                    np.asarray(state0.gang_inflight)
                    if state0.gang_inflight is not None else None
                )
                conflict_at, committed = _fence_refine(
                    tables, free0, eq0, infl0, assignment, lane_of_pod,
                    candidates, self.k,
                )
        if conflict_at >= 0:
            j = int(lane_of_pod[conflict_at])
            stats.conflicts[j] += 1
            obs.metrics.inc(obs.LANE_CONFLICTS, lane=str(j))
            stats.committed = [
                int(c) for c in
                np.bincount(lane_of_pod[:conflict_at], minlength=self.k)
            ]
            suffix = list(range(conflict_at, P_live))
            stats.re_resolved = len(suffix)
            obs.metrics.inc(obs.LANE_RERESOLVES, len(suffix))
            r_choice, r_ok, r_fail = self._repair(
                snap, auxes, committed, suffix,
                quota_present=snap.quota is not None,
                gangs_present=snap.gangs is not None,
            )
            assignment[suffix] = r_choice
            admitted[suffix] = r_ok
            codes[suffix] = r_fail
        else:
            stats.committed = list(stats.sizes)

        # Permit quorum, post-scan (sequential_solve_body's reduction):
        # recomputed from the FINAL assignment — the per-gang placement
        # counts are exactly the gang_commit tallies the scan would carry
        wait = np.zeros(P, bool)
        if snap.gangs is not None:
            gang = (
                gang_col if gang_col is not None
                else np.asarray(snap.pods.gang)
            )
            placed_in_gang = (assignment >= 0) & (gang >= 0)
            sched = np.bincount(
                gang[placed_in_gang], minlength=self._num_gangs(snap)
            )
            g_assigned = np.asarray(snap.gangs.assigned)
            g_min_member = np.asarray(snap.gangs.min_member)
            quorum = (g_assigned + sched) >= g_min_member
            in_gang = gang >= 0
            pod_quorum = np.where(in_gang, quorum[np.maximum(gang, 0)], True)
            wait = (assignment >= 0) & ~pod_quorum
        stats.fence_ms = (time.perf_counter() - t0) * 1000.0
        obs.metrics.observe_ms(obs.LANE_COMMIT, stats.fence_ms)
        for j in range(self.k):
            with obs.tracer.span("Lane/commit", tid=f"Lane/{j}",
                                 committed=stats.committed[j],
                                 conflicts=stats.conflicts[j]):
                pass
        return assignment, admitted, wait, codes, stats
