"""Multi-chip scaling: shard the (pods x nodes) scheduling problem over a
`jax.sharding.Mesh`.

The reference scales by fanning Filter/Score across 16 goroutines on one
process (SURVEY.md §2.9); here the problem tensors shard across TPU chips:
the node axis plays the tensor-parallel role (scores/feasibility split by
node shard, argmax/reductions ride XLA collectives over ICI) and the pod
axis the data-parallel role (independent pods in a wave). XLA inserts the
collectives from sharding annotations — no hand-written NCCL analog.
"""

from scheduler_plugins_tpu.parallel.lanes import (  # noqa: F401
    LaneSolver,
    LaneStats,
    fence_exact,
    lane_key,
    lane_of,
    lane_solve_fn,
    partition_lanes,
)
from scheduler_plugins_tpu.parallel.mesh import (  # noqa: F401
    make_mesh,
    make_node_mesh,
    pad_to_shards,
    snapshot_shardings,
)
from scheduler_plugins_tpu.parallel.solver import (  # noqa: F401
    sharded_batch_solve,
    sharded_profile_batch_solve,
    sharded_wave_chunk_solver,
    sharded_wave_solve,
)
