"""Multi-host launch recipe: jax.distributed + DCN/ICI mesh placement.

The reference scales one Go process with goroutines (SURVEY.md §2.9); the
TPU-native analog is the standard JAX multi-controller runtime — N identical
processes (one per TPU host), each owning its local chips, jitting the SAME
sharded solve over one global mesh. This module packages the launch recipe
docs/SCALING.md describes:

Per host (identical binary, different process_id):

    from scheduler_plugins_tpu.parallel import launch
    launch.initialize()                # reads JAX_COORDINATOR/... env vars,
                                       # or pass explicitly; no-op when alone
    mesh = launch.make_multihost_mesh()

    # host 0 runs the cluster store + event feed; every cycle:
    snap = launch.broadcast_snapshot(snap_or_none)   # host 0 -> everyone
    assignment = launch.distributed_solve(snap, mesh, weights)
    # `assignment` is fully replicated: host 0 applies the bindings

Mesh placement follows docs/SCALING.md "Multi-host (DCN)": the "pods" axis
spans HOSTS (its per-wave work is embarrassingly parallel except log-depth
prefix scans, which tolerate DCN latency), the "nodes" axis stays INSIDE
each host's ICI domain (it carries the frequent small per-wave reductions).
`mesh_utils.create_hybrid_device_mesh` realizes exactly that: the outer
(DCN) factor maps to process granularity, the inner to local chips.

Environment (standard JAX multi-controller):

    JAX_COORDINATOR=host0:8476 JAX_NUM_PROCESSES=4 JAX_PROCESS_ID=k \
        python your_scheduler_host.py

On Cloud TPU pods, `jax.distributed.initialize()` discovers all three
automatically; the env vars are the manual/baremetal path.
"""

from __future__ import annotations

from typing import Optional

import jax
import numpy as np

from scheduler_plugins_tpu.parallel.mesh import (
    NODES_AXIS,
    PODS_AXIS,
    make_mesh,
)


def initialize(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> bool:
    """`jax.distributed.initialize` with env-var fallback
    (JAX_COORDINATOR / JAX_NUM_PROCESSES / JAX_PROCESS_ID). Returns True
    when a multi-process runtime was started, False for the single-process
    no-op (local runs, tests, the bench driver)."""
    import os

    coordinator_address = coordinator_address or os.environ.get(
        "JAX_COORDINATOR"
    )
    if num_processes is None and "JAX_NUM_PROCESSES" in os.environ:
        num_processes = int(os.environ["JAX_NUM_PROCESSES"])
    if process_id is None and "JAX_PROCESS_ID" in os.environ:
        process_id = int(os.environ["JAX_PROCESS_ID"])
    if coordinator_address is None and num_processes is None:
        # Cloud TPU pod slice: initialize() autodetects coordinator/count.
        # Must run BEFORE any JAX computation touches the backend (even
        # jax.process_count() would initialize it single-process); a raise
        # here means either "not a managed multi-host environment" or "the
        # backend is already up" (single-process tests) — both single.
        try:
            jax.distributed.initialize()
        except Exception:
            return False
        return jax.process_count() > 1
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )
    return True


def make_multihost_mesh() -> jax.sharding.Mesh:
    """Global ("pods", "nodes") mesh with the pods axis across hosts (DCN)
    and the nodes axis within each host (ICI) — docs/SCALING.md placement.
    Single-process: falls back to `make_mesh` over local devices."""
    n_proc = jax.process_count()
    if n_proc <= 1:
        return make_mesh()
    per_host = jax.local_device_count()
    from jax.experimental import mesh_utils

    try:
        # TPU pod slices: hybrid mesh for the best ICI ordering per host
        grid = mesh_utils.create_hybrid_device_mesh(
            mesh_shape=(1, per_host),  # within a host: all chips on "nodes"
            dcn_mesh_shape=(n_proc, 1),  # across hosts: "pods"
        )
    except ValueError:
        # backends without slice topology info (multi-process CPU — the
        # 2-process test tier): the process boundary IS the DCN boundary
        grid = mesh_utils.create_hybrid_device_mesh(
            mesh_shape=(1, per_host),
            dcn_mesh_shape=(n_proc, 1),
            process_is_granule=True,
        )
    return jax.sharding.Mesh(grid, (PODS_AXIS, NODES_AXIS))


def broadcast_snapshot(snap):
    """Replicate host 0's snapshot to every process (host 0 owns the
    cluster store + feed; the others only compute). Single-process: identity.
    """
    if jax.process_count() <= 1:
        return snap
    from jax.experimental import multihost_utils

    return multihost_utils.broadcast_one_to_all(snap)


def distributed_solve(snap, mesh, weights, max_waves: int = 8):
    """Run the sharded batched solve on the global mesh and return the
    (P,) assignment replicated to every host (host 0 binds)."""
    from scheduler_plugins_tpu.parallel.solver import sharded_batch_solve

    assignment, admitted, wait = sharded_batch_solve(
        snap, mesh, weights, max_waves=max_waves
    )
    # replicate across the whole mesh (XLA inserts the all-gather) so every
    # process holds the full (P,) result locally
    from jax.sharding import NamedSharding, PartitionSpec

    from scheduler_plugins_tpu.parallel.mesh import ambient_mesh

    with ambient_mesh(mesh):
        assignment = jax.jit(
            lambda a: a, out_shardings=NamedSharding(mesh, PartitionSpec())
        )(assignment)
    return np.asarray(assignment.addressable_data(0))
