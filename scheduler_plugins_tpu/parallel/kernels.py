"""On-chip Pallas ring kernels for the sharded wave election (ISSUE 13).

The sharded wave solver's per-wave cross-shard traffic is a handful of
O(window) champion reductions (docs/SCALING.md §sharded-wave): exclusive
prefix sums of per-shard block aggregates (`ops.assign
.block_exclusive_offsets`), min-rank champion elections (`lax.pmin`), and
a packed admission-verdict `lax.psum`. On TPU each framework collective is
its own XLA program region with its own rendezvous; this module implements
the same exchanges as hand-rolled Pallas ring kernels — double-buffered
`pltpu.make_async_remote_copy` neighbor DMAs with send/recv semaphores in
scratch, local accumulation overlapped with the in-flight transfer — so a
wave's election costs one fused kernel launch per exchange point instead
of a framework collective, and the verdict `psum` disappears entirely
(the winning shard's node id and free row ride the election payload, so
every shard resolves the admission verdict replicated; see
`fused_election`).

Kernels
-------

- `ring_offsets` — (exclusive_prefix, total) of a per-shard value over the
  mesh axis: the (S-1)-step `lax.ppermute` exclusive scan rewritten as a
  neighbor-DMA ring. Exact-int64/float64 inputs travel as base-2^18 int32
  limbs (`split_limbs`/`join_limbs`): Mosaic has no f64/i64 vector units,
  and limb sums stay exact below 2^53 at any shard count <= 2^13, so the
  recombined prefix is BIT-IDENTICAL to the lax formulation's left-to-
  right float64 block sums.
- `elect_min` — elementwise global minimum of per-shard int32 candidate
  rows (the bucket-position election).
- `fused_election` — min-key champion election WITH winner payload: row 0
  is the rank key (min-reduced); the payload rows (winner node id, winner
  free-capacity limbs) are selected from whichever shard carried the
  winning key. Keys are globally unique by construction (every proposed
  rank lives in exactly one shard's block; the shared sentinel N carries a
  zero payload), so the select is order-independent and the reduction is
  exact.

Ring scheme (all three kernels share it)
----------------------------------------

Each shard owns a 3-slot VMEM communication buffer. Step k sends slot
(k-1)%3 to the RIGHT neighbor's slot k%3 via `make_async_remote_copy`
(send/recv DMA semaphores in scratch) and, while that transfer is in
flight, folds the buffer RECEIVED at step k-1 into the local accumulators
— the double-buffering overlap. After S-1 steps every shard has seen
every other shard's original contribution; prefix rows accumulate only
sources with ring index below their own (the exclusive scan), total/min/
select rows accumulate all. On real TPU a per-step neighbor barrier
(`pltpu.get_barrier_semaphore`, signal left+right / wait 2) bounds
neighbor skew to one step so a 3-slot buffer can never be overwritten
while its previous content is still being folded; the barrier primitive
has no CPU lowering, so the `interpret=True` CPU twin — which executes
shards serially and race-free — elides exactly those barrier ops and
nothing else. The twin is the differential-gate path: placements under
`SPT_PALLAS=1` must be bit-identical to the lax formulation
(tests/test_differential.py, `make pallas-smoke`).

VMEM envelope: one election program holds `1 + n_out + 3` same-shape
copies of its (H, L) int32 buffer (input, outputs, 3 comm slots) in VMEM
— worst family ring_offsets at 6 copies. The static envelope model lives
in `parallel.vmem` (shared with `tools/kernel_audit.py` KA001, which
re-derives it from the traced bodies); `PALLAS_MAX_ELECTION_ELEMS` is
derived there, no longer hand-picked. Call sites whose padded payload
exceeds it (the mega config's whole-queue first wave) statically keep
the lax collectives — bit-parity holds either way, and the tiled
large-window variant is on-chip follow-up work (docs/SCALING.md).

TPU gotchas honored (CLAUDE.md + /opt/skills/guides/pallas_guide.md): no
f64/i64 inside kernel bodies (limbs), buffers padded to (8, 128) int32
tiles, scalars never 0-D, static python loops only (shard count is a
static), and kernel bodies never read the clock or call back to the host
(tools/graft_lint.py GL011 enforces this at the source level).
"""

from __future__ import annotations

import os
from functools import partial

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from scheduler_plugins_tpu.parallel import vmem

__all__ = [
    "pallas_enabled",
    "pallas_interpret",
    "ring_offsets_i32",
    "ring_offsets_f64",
    "elect_min",
    "fused_election",
    "split_limbs",
    "join_limbs",
    "election_elems",
    "fits_election_budget",
    "PALLAS_MAX_ELECTION_ELEMS",
]

#: base-2^18 limb split for exact quantities (int64 in reference units,
#: cumulative sums documented < 2^53): 3 limbs cover 2^54, and per-limb
#: partial sums stay below 2^31 for any shard count <= 2^13 — no carry
#: propagation needed inside the ring, one normalize at recombine time
LIMB_BITS = 18
N_LIMBS = 3
_LIMB_MASK = (1 << LIMB_BITS) - 1

#: int32 sublane/lane tile floor for the padded kernel buffers
_SUBLANES = 8
_LANES = 128

#: ceiling on one election program's padded (H, L) int32 payload, DERIVED
#: from the static VMEM envelope model (`parallel.vmem`): the worst ring
#: family (ring_offsets: input + 2 outputs + 3 comm slots = 6 same-shape
#: buffers) must fit the per-core budget, so the gate is the largest
#: power of two with 6 * 4 B * E <= 16 MiB — 2^19. tools/kernel_audit.py
#: (KA001) re-derives the same number from the traced kernel bodies and
#: fails closed on drift. Oversize call sites (the mega whole-queue wave)
#: statically fall back to the lax collectives — same math, same
#: placements. SPT_PALLAS_MAX_ELECTION_ELEMS still overrides, inside
#: vmem.max_election_elems().
PALLAS_MAX_ELECTION_ELEMS = vmem.max_election_elems()

#: distinct collective_id per kernel family (kernels with custom barriers
#: must not share matching ids with unrelated collectives in the program)
_CID_OFFSETS = 11
_CID_ELECT_MIN = 12
_CID_FUSED = 13


def pallas_enabled() -> bool:
    """Opt-in gate for the Pallas election path (`SPT_PALLAS=1`). Read at
    solver BUILD time — callers key their trace caches on it (toggling the
    env var must never reuse a differently-built program), exactly like
    the SPT_SANITIZE discipline in `parallel.solver.profile_batch_fn`."""
    return os.environ.get("SPT_PALLAS", "") == "1"


def pallas_interpret() -> bool:
    """True when the kernels should run as their interpret-mode CPU twins:
    forced by `SPT_PALLAS_INTERPRET=0/1`, else everything except a real
    TPU backend interprets. The twin is the CI/differential path; the
    compiled kernels are what `tools/tpu_lower.py` AOT-lowers and what
    `make tpu-first-cycle` runs the moment the tunnel is healthy."""
    forced = os.environ.get("SPT_PALLAS_INTERPRET")
    if forced is not None:
        return forced != "0"
    try:
        return jax.default_backend() != "tpu"
    except Exception:  # backend not initializable: interpret is the safe twin
        return True


# ---------------------------------------------------------------------------
# limb packing (exact int64/float64 <-> int32 rows)
# ---------------------------------------------------------------------------


def split_limbs(x):
    """(N_LIMBS, ...) int32 base-2^18 limbs of a nonnegative exact-integer
    tensor (int64, or float64 holding integers < 2^53 — the repo-wide
    quantity bound). Lossless by construction; `join_limbs` inverts."""
    v = x.astype(jnp.int64) if x.dtype != jnp.int64 else x
    return jnp.stack(
        [
            ((v >> (LIMB_BITS * i)) & _LIMB_MASK).astype(jnp.int32)
            for i in range(N_LIMBS)
        ]
    )


@partial(jax.jit, static_argnames=("dtype",))
def join_limbs(limbs, dtype=jnp.float64):
    """Recombine `split_limbs` rows (possibly SUMMED across shards — each
    limb then holds up to shards * 2^18, still exact in f64) back into one
    tensor. float64 arithmetic is exact here: every limb < 2^31 and the
    recombined value < 2^53. A named jit boundary ON PURPOSE (XLA inlines
    it — no runtime cost): the exactness argument is structural (the
    recombined value IS the original < 2^53 quantity sum), so
    `tools/kernel_audit.py` KA003 blesses the pjit call by name via
    `api.bounds.EXACT_FN_BOUNDS` — the naive interval on `limb2 * 2^36`
    overflows the 2^53 line that the reconstructed value respects."""
    acc = limbs[0].astype(jnp.float64)
    for i in range(1, N_LIMBS):
        acc = acc + limbs[i].astype(jnp.float64) * float(1 << (LIMB_BITS * i))
    return acc.astype(dtype)


def _pad2(x, fill):
    """Pad a 2-D int32 buffer up to the (8, 128) tile floor."""
    H, L = x.shape
    Hp = -(-H // _SUBLANES) * _SUBLANES
    Lp = -(-L // _LANES) * _LANES
    if Hp == H and Lp == L:
        return x
    return jnp.pad(x, ((0, Hp - H), (0, Lp - L)), constant_values=fill)


def election_elems(n_rows: int, length: int) -> int:
    """Padded int32 element count of one (n_rows, length) kernel buffer —
    the quantity `PALLAS_MAX_ELECTION_ELEMS` bounds."""
    Hp = -(-n_rows // _SUBLANES) * _SUBLANES
    Lp = -(-length // _LANES) * _LANES
    return Hp * Lp


def fits_election_budget(n_rows: int, length: int) -> bool:
    return election_elems(n_rows, length) <= PALLAS_MAX_ELECTION_ELEMS


# ---------------------------------------------------------------------------
# the shared ring engine
# ---------------------------------------------------------------------------


def _ring_kernel_body(x_ref, out_refs, comm, send_sem, recv_sem, *,
                      axis_name: str, n_shards: int, interpret: bool,
                      init_fn, combine_fn, finish_fn):
    """One (S-1)-step double-buffered neighbor-DMA ring. `init_fn(x)`
    builds the accumulator pytree from the local contribution;
    `combine_fn(acc, recv, src_offset)` folds the buffer received from the
    shard `src_offset` ring positions to the left; `finish_fn(acc,
    out_refs)` writes the results. The per-step barrier (TPU only; the
    interpret twin is serially executed and race-free) bounds neighbor
    skew so the 3-slot buffer is never overwritten before its previous
    content has been folded."""
    import numpy as np

    my = jax.lax.axis_index(axis_name)
    S = jnp.int32(n_shards)
    right = jax.lax.rem(my + jnp.int32(1), S)
    left = jax.lax.rem(my + S - jnp.int32(1), S)
    comm[np.int32(0)] = x_ref[...]
    acc = init_fn(x_ref[...])
    if not interpret:
        barrier = pltpu.get_barrier_semaphore()
    for k in range(1, n_shards):
        # np.int32 slot indices: python-int literals promote to i64 under
        # x64, which Mosaic's memref_slice rejects
        slot, nxt = np.int32((k - 1) % 3), np.int32(k % 3)
        if not interpret:
            pltpu.semaphore_signal(
                barrier, inc=1, device_id=left,
                device_id_type=pltpu.DeviceIdType.LOGICAL,
            )
            pltpu.semaphore_signal(
                barrier, inc=1, device_id=right,
                device_id_type=pltpu.DeviceIdType.LOGICAL,
            )
            pltpu.semaphore_wait(barrier, 2)
        rdma = pltpu.make_async_remote_copy(
            src_ref=comm.at[slot],
            dst_ref=comm.at[nxt],
            send_sem=send_sem.at[slot],
            recv_sem=recv_sem.at[nxt],
            device_id=right,
            device_id_type=pltpu.DeviceIdType.LOGICAL,
        )
        rdma.start()
        # overlap: fold the buffer received at step k-1 (the value of the
        # shard k-1 positions left) while step k's transfer is in flight
        if k >= 2:
            acc = combine_fn(acc, comm[slot], k - 1)
        rdma.wait()
    acc = combine_fn(
        acc, comm[np.int32((n_shards - 1) % 3)], n_shards - 1
    )
    finish_fn(acc, out_refs)


def _ring_call(x2d, axis_name: str, n_shards: int, interpret: bool,
               n_out: int, collective_id: int, init_fn, combine_fn,
               finish_fn, pad_fill: int = 0, padded=None,
               family: str = "ring"):
    """`pl.pallas_call` plumbing shared by ALL the kernels: pads the
    (H, L) int32 buffer to the tile floor (`pad_fill` — 0 for sum/prefix
    rows, INT32_MAX for min keys; `padded` lets a caller supply a buffer
    with MIXED fills, fused_election's key row vs payload rows),
    allocates the 3-slot comm scratch and DMA semaphores, and returns the
    UNPADDED outputs. One copy on purpose: the scratch/semaphore layout
    must never diverge between kernels."""
    H, L = x2d.shape

    def kernel(x_ref, *refs):
        out_refs = refs[:n_out]
        comm, send_sem, recv_sem = refs[n_out:]
        _ring_kernel_body(
            x_ref, out_refs, comm, send_sem, recv_sem,
            axis_name=axis_name, n_shards=n_shards, interpret=interpret,
            init_fn=init_fn, combine_fn=combine_fn, finish_fn=finish_fn,
        )

    if padded is None:
        padded = _pad2(x2d, pad_fill)
    Hp, Lp = padded.shape
    out = pl.pallas_call(
        kernel,
        out_shape=tuple(
            jax.ShapeDtypeStruct((Hp, Lp), jnp.int32) for _ in range(n_out)
        ),
        in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM)],
        out_specs=tuple(
            pl.BlockSpec(memory_space=pltpu.VMEM) for _ in range(n_out)
        ),
        scratch_shapes=[
            pltpu.VMEM((3, Hp, Lp), jnp.int32),
            pltpu.SemaphoreType.DMA((3,)),
            pltpu.SemaphoreType.DMA((3,)),
        ],
        compiler_params=pltpu.TPUCompilerParams(
            collective_id=collective_id
        ),
        interpret=interpret,
        # the family name rides the traced pallas_call so the kernel
        # auditor's per-family envelope cross-check (vmem.RING_FAMILIES)
        # can key traced bodies back to the budget table
        name=family,
    )(padded)
    return tuple(o[:H, :L] for o in out)


# ---------------------------------------------------------------------------
# public kernels
# ---------------------------------------------------------------------------


def _offsets_rows(rows, axis_name, n_shards, interpret):
    """(exclusive_prefix, total) of int32 `rows` (H, L) over the mesh axis
    — the ring engine with prefix/total accumulators. Padding rows are
    zero, so they sum to zero and never perturb the real rows."""

    def init(x):
        return {"excl": jnp.zeros_like(x), "tot": x}

    def combine(acc, recv, src_off):
        my = jax.lax.axis_index(axis_name)
        # the shard src_off ring positions to the LEFT contributed `recv`;
        # its ring index is my - src_off, i.e. strictly below mine (the
        # exclusive-prefix condition) exactly when src_off <= my
        take = (src_off <= my).astype(jnp.int32)
        return {
            "excl": acc["excl"] + recv * take,
            "tot": acc["tot"] + recv,
        }

    def finish(acc, out_refs):
        out_refs[0][...] = acc["excl"]
        out_refs[1][...] = acc["tot"]

    return _ring_call(
        rows, axis_name, n_shards, interpret, 2, _CID_OFFSETS,
        init, combine, finish, family="ring_offsets",
    )


def ring_offsets_i32(x, axis_name: str, n_shards: int, *, interpret: bool):
    """(exclusive_prefix, total) of a per-shard int32 value `x` (any
    shape) — the Pallas twin of `ops.assign.block_exclusive_offsets` for
    int32 payloads (rescue feasible counts). Caller contract: totals fit
    int32 (counts are bounded by the padded node count). Bit-identical to
    the lax formulation: integer addition is exact in any order."""
    if n_shards == 1:
        return jnp.zeros_like(x), x
    flat = x.reshape(1, -1).astype(jnp.int32)
    excl, tot = _offsets_rows(flat, axis_name, n_shards, interpret)
    return excl.reshape(x.shape), tot.reshape(x.shape)


def ring_offsets_f64(x, axis_name: str, n_shards: int, *, interpret: bool):
    """(exclusive_prefix, total) of a per-shard float64 exact-integer
    value `x` (the cumulative-free block aggregates): base-2^18 limbs ride
    the int32 ring and recombine exactly, so the result is bit-identical
    to the lax float64 block sums below the documented 2^53 bound."""
    if n_shards == 1:
        return jnp.zeros_like(x), x
    limbs = split_limbs(x)  # (N_LIMBS, ...)
    rows = limbs.reshape(N_LIMBS, -1)
    excl, tot = _offsets_rows(rows, axis_name, n_shards, interpret)
    shape = (N_LIMBS,) + x.shape
    return (
        join_limbs(excl.reshape(shape)),
        join_limbs(tot.reshape(shape)),
    )


def elect_min(rows, axis_name: str, n_shards: int, *, interpret: bool):
    """Elementwise global MINIMUM of per-shard int32 `rows` (H, L) — the
    bucket-position champion election (`lax.pmin` twin). Padding lanes
    are filled with INT32_MAX so they never win."""
    if n_shards == 1:
        return rows

    def init(x):
        return x

    def combine(acc, recv, _src_off):
        return jnp.minimum(acc, recv)

    def finish(acc, out_refs):
        out_refs[0][...] = acc

    (out,) = _ring_call(
        rows.astype(jnp.int32), axis_name, n_shards, interpret, 1,
        _CID_ELECT_MIN, init, combine, finish,
        pad_fill=jnp.iinfo(jnp.int32).max, family="elect_min",
    )
    return out


def fused_election(keys, payload_rows, axis_name: str, n_shards: int, *,
                   interpret: bool):
    """Min-key champion election WITH winner payload, in ONE ring program:
    `keys` (L,) int32 are per-shard candidate ranks (the shared sentinel
    for "no candidate" may repeat; real keys are globally unique — every
    proposed rank lives in exactly one shard's block); `payload_rows`
    (Hp, L) int32 are that shard's attachment (winner node id, free-row
    limbs). Returns (min_keys (L,), winner_payload (Hp, L)).

    This is the kernel that retires the packed admission-verdict `psum`:
    because the winner's free row arrives with the election result, the
    queue-order admission check runs REPLICATED on every shard instead of
    sharded-then-psum'd (`ops.assign.waterfill_targeted_sharded`'s pallas
    path), so the wave's champion reduction and verdict resolution cost
    one fused collective program. Sentinel keys tie with payload zero on
    every shard, so keeping the accumulator on ties is exact."""
    if n_shards == 1:
        return keys, payload_rows
    L = keys.shape[0]
    buf = jnp.concatenate(
        [keys.reshape(1, L).astype(jnp.int32),
         payload_rows.astype(jnp.int32)], axis=0
    )

    def init(x):
        return x

    def combine(acc, recv, _src_off):
        take = recv[0:1] < acc[0:1]  # (1, L) strict: keys unique or tied-0
        key = jnp.minimum(acc[0:1], recv[0:1])
        rest = jnp.where(take, recv[1:], acc[1:])
        return jnp.concatenate([key, rest], axis=0)

    def finish(acc, out_refs):
        out_refs[0][...] = acc

    # key padding lanes carry INT32_MAX (never win); payload pad rows are
    # zero — pad by hand so the two fills coexist in one buffer
    H = buf.shape[0]
    padded = _pad2(buf, 0).at[0, L:].set(jnp.iinfo(jnp.int32).max)
    (out,) = _ring_call(
        buf, axis_name, n_shards, interpret, 1, _CID_FUSED,
        init, combine, finish, padded=padded, family="fused_election",
    )
    return out[0], out[1:H]
