"""Donated, double-buffered chunk pipeline.

The north-star solve streams pods through the chunked targeted waterfill
with free capacity carried between chunks (queue order preserved across
chunk boundaries). The naive loop serializes three phases per chunk —
host->device transfer of the next chunk's inputs, the solve, and the
device->host transfer of the previous chunk's assignments — leaving the
device idle during both transfers and the host blocked during the solve.

`run_chunk_pipeline` overlaps all three with a one-chunk lag:

    dispatch solve(k)            # async — device starts immediately
    device_put(chunk k+1 inputs) # H2D overlaps solve(k)
    collect(result k-1)          # D2H blocks only until solve(k-1) done

so the device is never idle between chunks and the host is never more
than one chunk behind (the bounded in-flight window matters through the
tunneled TPU backend, where chaining everything device-side balloons the
working set — CLAUDE.md). The chunk solver DONATES its carry argument
(`donated_chunk_solver`), so the free-capacity tensor threads chunk to
chunk in place instead of being copied at every dispatch boundary.

Consumers: `bench.py north_star` (the 10,240x102,400 headline run) and the
daemon cycle loop (`framework.cycle.run_cycle(stream_chunk=...)`) via
`streamed_profile_solve` below.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field

import jax
import jax.numpy as jnp
import numpy as np

from scheduler_plugins_tpu.utils import observability as obs


@dataclass
class PipelineTimeline:
    """Host-sync stamps of one `run_chunk_pipeline` run.

    Every number here comes from HOST-observable boundaries — the async
    dispatch returning, `jax.device_put` ENQUEUE (the host-side staging
    cost; the transfer itself completes asynchronously and is only known
    to be done when the next dispatch consumes the buffers) and
    `jax.device_get` (D2H) actually completing — never from wall clocks
    inside jit (CLAUDE.md; GL008). The "h2d" stamps therefore measure
    host staging exposure, not wire time; only the D2H stamps are true
    completion fences. With the lag-1 window the host observes chunk k's
    completion only at its D2H, so per-chunk device busy time is NOT
    directly observable;
    `summary(solve_ms=...)` therefore takes a device-busy ESTIMATE the
    caller derives from a synchronously-timed calibration solve scaled by
    the per-chunk `collect_stats` wave counters (bench.north_star does
    exactly this), and charges the remainder of the wall time as the
    pipeline bubble.
    """

    n_chunks: int = 0
    #: [{stage: dispatch|h2d|d2h, chunk, start_s, end_s}] on the caller's
    #: clock (seconds); start_s/end_s are relative to nothing in
    #: particular — only differences matter
    events: list = field(default_factory=list)
    start_s: float = 0.0
    end_s: float = 0.0
    #: tracer-clock ns at pipeline start when the tracer was enabled
    #: (aligns replayed rows with live spans), else None
    _anchor_ns: int | None = None

    def open(self, start_s: float) -> None:
        self.start_s = start_s
        if obs.tracer.enabled:
            self._anchor_ns = obs.tracer.now_ns()

    def add(self, stage: str, chunk: int, start_s: float, end_s: float) -> None:
        self.events.append(
            {"stage": stage, "chunk": chunk,
             "start_s": start_s, "end_s": end_s}
        )

    def close(self, end_s: float) -> None:
        self.end_s = end_s

    def stage_ms(self, stage: str) -> float:
        return sum(
            (e["end_s"] - e["start_s"]) * 1000.0
            for e in self.events if e["stage"] == stage
        )

    @property
    def elapsed_ms(self) -> float:
        return (self.end_s - self.start_s) * 1000.0

    def summary(self, solve_ms: float | None = None) -> dict:
        """Pipeline-overlap report. `solve_ms` is the caller's estimate of
        TOTAL device busy time (calibration solve x wave-counter scaling);
        without it only the raw stage totals are reported.

        - `pipeline_bubble_ms` = wall time the device was NOT solving
          (elapsed - solve_ms, floored at 0): the un-overlapped remainder
          the double buffering exists to eliminate.
        - `overlap_efficiency` = solve_ms / elapsed (capped at 1): the
          fraction of the wall clock the device was busy.
        - per-stage `*_overlap_efficiency` = the fraction of that host
          stage's time hidden behind device work, attributing the bubble
          to host stages pro-rata by their time share (an estimate — the
          lag-1 window cannot observe which stage exposed which gap, and
          the h2d stage total is the ENQUEUE cost, not wire time: on an
          async backend an exposed in-flight transfer shows up in the
          bubble, not in `h2d_ms`).
        """
        h2d = self.stage_ms("h2d")
        d2h = self.stage_ms("d2h")
        dispatch = self.stage_ms("dispatch")
        out = {
            "elapsed_ms": round(self.elapsed_ms, 3),
            "chunks": self.n_chunks,
            "h2d_ms": round(h2d, 3),
            "d2h_ms": round(d2h, 3),
            "dispatch_ms": round(dispatch, 3),
            "pipeline_bubble_ms": None,
            "overlap_efficiency": None,
            "h2d_overlap_efficiency": None,
            "d2h_overlap_efficiency": None,
        }
        if solve_ms is None or self.elapsed_ms <= 0:
            return out
        bubble = max(0.0, self.elapsed_ms - solve_ms)
        out["pipeline_bubble_ms"] = round(bubble, 3)
        out["overlap_efficiency"] = round(
            min(1.0, solve_ms / self.elapsed_ms), 4
        )
        host_total = h2d + d2h + dispatch
        for key, stage_total in (("h2d_overlap_efficiency", h2d),
                                 ("d2h_overlap_efficiency", d2h)):
            if stage_total <= 0 or host_total <= 0:
                out[key] = 1.0
                continue
            exposed = min(stage_total, bubble * stage_total / host_total)
            out[key] = round(1.0 - exposed / stage_total, 4)
        return out

    def emit_trace(self, tracer=None) -> None:
        """Replay the stamps as Perfetto rows: H2D/solve/D2H per buffer
        (buffers alternate chunk parity under the double buffering). The
        solve row for chunk k spans dispatch-return to D2H-complete — a
        conservative envelope (the host cannot observe the device-side
        start/finish tighter than its own sync points)."""
        tracer = tracer or obs.tracer
        if not tracer.enabled or self._anchor_ns is None:
            return

        def ns(t_s: float) -> int:
            return self._anchor_ns + int((t_s - self.start_s) * 1e9)

        dispatch_end = {}
        d2h_end = {}
        for e in self.events:
            if e["stage"] == "dispatch":
                dispatch_end[e["chunk"]] = e["end_s"]
            elif e["stage"] == "d2h":
                d2h_end[e["chunk"]] = e["end_s"]
            tracer.complete(
                f'{e["stage"]} chunk {e["chunk"]}',
                ns(e["start_s"]),
                int((e["end_s"] - e["start_s"]) * 1e9),
                tid=f'pipeline/{e["stage"]}/buf{e["chunk"] % 2}',
                args={"chunk": e["chunk"]},
            )
        for k, disp_end in sorted(dispatch_end.items()):
            end = d2h_end.get(k)
            if end is None:
                continue
            tracer.complete(
                f"solve chunk {k}",
                ns(disp_end),
                int((end - disp_end) * 1e9),
                tid=f"pipeline/solve/buf{k % 2}",
                args={"chunk": k, "envelope": "dispatch->d2h (conservative)"},
            )


def donated_chunk_solver(fn, carry_argnum: int):
    """Jit `fn` with its carry argument donated — the pipeline's calling
    convention. Callers must treat the carry they pass in as CONSUMED
    (rebind it from the call's result; `tools/graft_lint.py` GL006 flags
    reuse of a donated buffer after the donating call).

    Under `SPT_SANITIZE=1` (utils.sanitize) the chunk program is built as a
    checkify-instrumented jit with the donation DROPPED (debug mode: the
    carry stays readable, checkify errors surface as structured JSON); the
    calling convention — rebind the carry from the result — is unchanged.
    """
    from scheduler_plugins_tpu.utils import sanitize

    name = getattr(fn, "__name__", "solve_chunk")
    if sanitize.enabled():
        jitted = sanitize.checkified(fn, program=f"chunk:{name}")
    else:
        jitted = jax.jit(fn, donate_argnums=(carry_argnum,))
    return obs.compile_watch(jitted, program=f"chunk:{name}")


def run_chunk_pipeline(solve_chunk, invariant_args, chunk_inputs, carry,
                       clock=None, fetch_deadline_s=None):
    """Stream `chunk_inputs` through `solve_chunk`, double-buffered.

    - ``solve_chunk(*invariant_args, *chunk_dev, carry) -> (result, carry)``
      — typically a `donated_chunk_solver`; `result` may be any pytree
      (e.g. ``(assignment, wave_stats)``).
    - ``chunk_inputs``: sequence of per-chunk argument tuples (host numpy
      or device arrays; they are `jax.device_put` one chunk ahead).
    - ``carry``: the threaded state (free capacity); returned updated.
    - ``clock``: optional ``time.perf_counter``-like callable for the
      completion stamps (injectable for tests).
    - ``fetch_deadline_s``: optional per-chunk deadline on the D2H
      completion fences (`jax.device_get` is the only point this loop
      blocks on the device, so it is where a hung backend strands the
      host): each fetch runs through
      `resilience.watchdog.call_with_deadline` and raises
      `BackendUnavailable` on timeout instead of hanging the cycle loop
      forever. None (the default) keeps the direct call.

    Returns ``(results, carry, done_s, timeline)`` where ``results[k]`` is
    chunk k's `result` pytree fetched to host and ``done_s[k]`` its
    completion time (seconds since the pipeline started) — the per-chunk
    decision-latency stamps the north-star p50/p99 derive from. Completion
    of chunk k is observed one dispatch later (lag-1), so the stamps are
    conservative by at most one dispatch overhead, never optimistic.
    ``timeline`` is a `PipelineTimeline` of the host-sync stamps (dispatch,
    H2D, D2H per chunk): `timeline.summary(solve_ms=...)` computes the
    `pipeline_bubble_ms` / overlap-efficiency metrics, and when the global
    tracer is enabled the stamps are replayed as Perfetto H2D/solve/D2H
    rows per buffer automatically.
    """
    clock = clock or time.perf_counter
    if fetch_deadline_s is None:
        fetch = jax.device_get
    else:
        from scheduler_plugins_tpu.resilience.watchdog import (
            call_with_deadline,
        )

        def fetch(x):
            return call_with_deadline(
                lambda: jax.device_get(x), fetch_deadline_s,
                label="pipeline-d2h",
            )

    n = len(chunk_inputs)
    results, done_s = [], []
    timeline = PipelineTimeline(n_chunks=n)
    start = clock()
    timeline.open(start)
    pending = None
    dev = ()
    if n:
        t0 = clock()
        dev = tuple(jax.device_put(a) for a in chunk_inputs[0])
        timeline.add("h2d", 0, t0, clock())
    for k in range(n):
        t0 = clock()
        result, carry = solve_chunk(*invariant_args, *dev, carry)
        timeline.add("dispatch", k, t0, clock())
        if k + 1 < n:
            # H2D for chunk k+1 overlaps solve(k)
            t0 = clock()
            dev = tuple(jax.device_put(a) for a in chunk_inputs[k + 1])
            timeline.add("h2d", k + 1, t0, clock())
        if pending is not None:
            # D2H for chunk k-1: blocks only until ITS solve finished
            t0 = clock()
            results.append(fetch(pending))
            t1 = clock()
            timeline.add("d2h", k - 1, t0, t1)
            done_s.append(t1 - start)
        pending = result
    if pending is not None:
        t0 = clock()
        results.append(fetch(pending))
        t1 = clock()
        timeline.add("d2h", n - 1, t0, t1)
        done_s.append(t1 - start)
    timeline.close(clock())
    timeline.emit_trace()
    return results, carry, done_s, timeline


# ---------------------------------------------------------------------------
# Streamed profile solve (the cycle loop's adoption point)
# ---------------------------------------------------------------------------


def _targeted_fast_gate(scheduler):
    """The profile shape the chunked targeted waterfill supports — THE gate
    is `parallel.solver.fast_path_scoring`, shared with
    `profile_batch_fn`'s fast branch so the two paths cannot drift."""
    from scheduler_plugins_tpu.parallel.solver import fast_path_scoring

    plugins = tuple(scheduler.profile.plugins)
    return fast_path_scoring(plugins), plugins


def streamed_profile_solve(scheduler, snap, chunk: int = 4096,
                           max_waves: int = 8, rescue_window: int = 256,
                           fetch_deadline_s=None):
    """Chunked, double-buffered variant of the targeted fast-path solve:
    admission and the static node ranking are computed once, then pod
    chunks stream through the donated targeted waterfill with free capacity
    carried chunk to chunk; gang quorum and the queue-order quota prefix
    run once over the full batch at the end (`finalize_assignment` needs
    whole-batch queue order, and chunk boundaries preserve it).

    Returns (assignment, admitted, wait) like `profile_batch_solve`, or
    None when the profile does not qualify (callers fall back). Placements
    match the unchunked targeted waterfill up to wave-budget effects; hard
    constraints (fit, queue-order admission, quota caps, gang quorum) hold
    identically.
    """
    from scheduler_plugins_tpu.ops.assign import waterfill_assign_targeted
    from scheduler_plugins_tpu.parallel.solver import finalize_assignment

    scoring, plugins = _targeted_fast_gate(scheduler)
    if scoring is None:
        return None
    P = snap.num_pods
    chunk = min(chunk, P)
    if P % chunk != 0:
        return None  # snapshot padding didn't land on a chunk multiple

    state0 = scheduler.initial_state(snap)
    auxes = tuple(p.aux() for p in plugins)

    cache = scheduler._solve_cache
    key = ("streamed_head",) + tuple(p.static_key() for p in plugins)
    if key not in cache:
        from scheduler_plugins_tpu.parallel.solver import fast_solve_head

        def head(snap, state0, auxes):
            # the shared traced head of the targeted fast path (admission
            # vmap + raw static ranking + masked initial free)
            return fast_solve_head(plugins, scoring, snap, state0, auxes)

        cache[key] = obs.compile_watch(jax.jit(head), program="streamed_head")
    admitted, raw, free0 = cache[key](snap, state0, auxes)

    from scheduler_plugins_tpu.utils import sanitize

    ckey = ("streamed_chunk", chunk, max_waves, rescue_window,
            sanitize.enabled())
    if ckey not in cache:

        def solve_one(raw, req_chunk, mask_chunk, free):
            return waterfill_assign_targeted(
                raw, req_chunk, mask_chunk, free,
                max_waves=max_waves, rescue_window=rescue_window,
            )

        cache[ckey] = donated_chunk_solver(solve_one, carry_argnum=3)

    chunk_inputs = [
        (snap.pods.req[lo:lo + chunk], admitted[lo:lo + chunk])
        for lo in range(0, P, chunk)
    ]
    parts, free, _, _ = run_chunk_pipeline(
        cache[ckey], (raw,), chunk_inputs, free0,
        fetch_deadline_s=fetch_deadline_s,
    )
    assignment = jnp.concatenate([jnp.asarray(a) for a in parts])
    assignment, wait = finalize_assignment(assignment, snap)
    return assignment, admitted, wait
