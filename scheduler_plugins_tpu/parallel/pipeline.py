"""Donated, double-buffered chunk pipeline.

The north-star solve streams pods through the chunked targeted waterfill
with free capacity carried between chunks (queue order preserved across
chunk boundaries). The naive loop serializes three phases per chunk —
host->device transfer of the next chunk's inputs, the solve, and the
device->host transfer of the previous chunk's assignments — leaving the
device idle during both transfers and the host blocked during the solve.

`run_chunk_pipeline` overlaps all three with a one-chunk lag:

    dispatch solve(k)            # async — device starts immediately
    device_put(chunk k+1 inputs) # H2D overlaps solve(k)
    collect(result k-1)          # D2H blocks only until solve(k-1) done

so the device is never idle between chunks and the host is never more
than one chunk behind (the bounded in-flight window matters through the
tunneled TPU backend, where chaining everything device-side balloons the
working set — CLAUDE.md). The chunk solver DONATES its carry argument
(`donated_chunk_solver`), so the free-capacity tensor threads chunk to
chunk in place instead of being copied at every dispatch boundary.

Consumers: `bench.py north_star` (the 10,240x102,400 headline run) and the
daemon cycle loop (`framework.cycle.run_cycle(stream_chunk=...)`) via
`streamed_profile_solve` below.
"""

from __future__ import annotations

import time

import jax
import jax.numpy as jnp
import numpy as np


def donated_chunk_solver(fn, carry_argnum: int):
    """Jit `fn` with its carry argument donated — the pipeline's calling
    convention. Callers must treat the carry they pass in as CONSUMED
    (rebind it from the call's result; `tools/graft_lint.py` GL006 flags
    reuse of a donated buffer after the donating call).

    Under `SPT_SANITIZE=1` (utils.sanitize) the chunk program is built as a
    checkify-instrumented jit with the donation DROPPED (debug mode: the
    carry stays readable, checkify errors surface as structured JSON); the
    calling convention — rebind the carry from the result — is unchanged.
    """
    from scheduler_plugins_tpu.utils import sanitize

    if sanitize.enabled():
        name = getattr(fn, "__name__", "solve_chunk")
        return sanitize.checkified(fn, program=f"chunk:{name}")
    return jax.jit(fn, donate_argnums=(carry_argnum,))


def run_chunk_pipeline(solve_chunk, invariant_args, chunk_inputs, carry,
                       clock=None):
    """Stream `chunk_inputs` through `solve_chunk`, double-buffered.

    - ``solve_chunk(*invariant_args, *chunk_dev, carry) -> (result, carry)``
      — typically a `donated_chunk_solver`; `result` may be any pytree
      (e.g. ``(assignment, wave_stats)``).
    - ``chunk_inputs``: sequence of per-chunk argument tuples (host numpy
      or device arrays; they are `jax.device_put` one chunk ahead).
    - ``carry``: the threaded state (free capacity); returned updated.
    - ``clock``: optional ``time.perf_counter``-like callable for the
      completion stamps (injectable for tests).

    Returns ``(results, carry, done_s)`` where ``results[k]`` is chunk k's
    `result` pytree fetched to host and ``done_s[k]`` its completion time
    (seconds since the pipeline started) — the per-chunk decision-latency
    stamps the north-star p50/p99 derive from. Completion of chunk k is
    observed one dispatch later (lag-1), so the stamps are conservative by
    at most one dispatch overhead, never optimistic.
    """
    clock = clock or time.perf_counter
    n = len(chunk_inputs)
    results, done_s = [], []
    start = clock()
    pending = None
    dev = tuple(jax.device_put(a) for a in chunk_inputs[0]) if n else ()
    for k in range(n):
        result, carry = solve_chunk(*invariant_args, *dev, carry)
        if k + 1 < n:
            # H2D for chunk k+1 overlaps solve(k)
            dev = tuple(jax.device_put(a) for a in chunk_inputs[k + 1])
        if pending is not None:
            # D2H for chunk k-1: blocks only until ITS solve finished
            results.append(jax.device_get(pending))
            done_s.append(clock() - start)
        pending = result
    if pending is not None:
        results.append(jax.device_get(pending))
        done_s.append(clock() - start)
    return results, carry, done_s


# ---------------------------------------------------------------------------
# Streamed profile solve (the cycle loop's adoption point)
# ---------------------------------------------------------------------------


def _targeted_fast_gate(scheduler):
    """The profile shape the chunked targeted waterfill supports — THE gate
    is `parallel.solver.fast_path_scoring`, shared with
    `profile_batch_fn`'s fast branch so the two paths cannot drift."""
    from scheduler_plugins_tpu.parallel.solver import fast_path_scoring

    plugins = tuple(scheduler.profile.plugins)
    return fast_path_scoring(plugins), plugins


def streamed_profile_solve(scheduler, snap, chunk: int = 4096,
                           max_waves: int = 8, rescue_window: int = 256):
    """Chunked, double-buffered variant of the targeted fast-path solve:
    admission and the static node ranking are computed once, then pod
    chunks stream through the donated targeted waterfill with free capacity
    carried chunk to chunk; gang quorum and the queue-order quota prefix
    run once over the full batch at the end (`finalize_assignment` needs
    whole-batch queue order, and chunk boundaries preserve it).

    Returns (assignment, admitted, wait) like `profile_batch_solve`, or
    None when the profile does not qualify (callers fall back). Placements
    match the unchunked targeted waterfill up to wave-budget effects; hard
    constraints (fit, queue-order admission, quota caps, gang quorum) hold
    identically.
    """
    from scheduler_plugins_tpu.ops.assign import waterfill_assign_targeted
    from scheduler_plugins_tpu.parallel.solver import finalize_assignment

    scoring, plugins = _targeted_fast_gate(scheduler)
    if scoring is None:
        return None
    P = snap.num_pods
    chunk = min(chunk, P)
    if P % chunk != 0:
        return None  # snapshot padding didn't land on a chunk multiple

    state0 = scheduler.initial_state(snap)
    auxes = tuple(p.aux() for p in plugins)

    cache = scheduler._solve_cache
    key = ("streamed_head",) + tuple(p.static_key() for p in plugins)
    if key not in cache:
        from scheduler_plugins_tpu.parallel.solver import fast_solve_head

        def head(snap, state0, auxes):
            # the shared traced head of the targeted fast path (admission
            # vmap + raw static ranking + masked initial free)
            return fast_solve_head(plugins, scoring, snap, state0, auxes)

        cache[key] = jax.jit(head)
    admitted, raw, free0 = cache[key](snap, state0, auxes)

    from scheduler_plugins_tpu.utils import sanitize

    ckey = ("streamed_chunk", chunk, max_waves, rescue_window,
            sanitize.enabled())
    if ckey not in cache:

        def solve_one(raw, req_chunk, mask_chunk, free):
            return waterfill_assign_targeted(
                raw, req_chunk, mask_chunk, free,
                max_waves=max_waves, rescue_window=rescue_window,
            )

        cache[ckey] = donated_chunk_solver(solve_one, carry_argnum=3)

    chunk_inputs = [
        (snap.pods.req[lo:lo + chunk], admitted[lo:lo + chunk])
        for lo in range(0, P, chunk)
    ]
    parts, free, _ = run_chunk_pipeline(
        cache[ckey], (raw,), chunk_inputs, free0
    )
    assignment = jnp.concatenate([jnp.asarray(a) for a in parts])
    assignment, wait = finalize_assignment(assignment, snap)
    return assignment, admitted, wait
