"""Device mesh construction and snapshot sharding specs."""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

PODS_AXIS = "pods"
NODES_AXIS = "nodes"


def ambient_mesh(mesh: Mesh):
    """Context manager installing `mesh` as the ambient mesh for jit's
    sharding propagation: `jax.set_mesh` where it exists (newer jax), else
    the classic `with mesh:` entry (jax <= 0.4.x, where `set_mesh` is not
    yet public). Both leave NamedSharding-committed inputs untouched."""
    set_mesh = getattr(jax, "set_mesh", None)
    if set_mesh is not None:
        return set_mesh(mesh)
    return mesh  # Mesh is itself a context manager


def make_mesh(
    n_devices: Optional[int] = None, devices: Optional[Sequence] = None
) -> Mesh:
    """Mesh with ("pods", "nodes") axes over the first `n_devices` devices.

    The factorization favors the node axis (clusters have more nodes than a
    wave has independent pods-per-shard): nodes gets the larger factor.
    """
    if devices is None:
        devices = jax.devices()
    if n_devices is not None:
        devices = devices[:n_devices]
    n = len(devices)
    pods_dim = 1
    for cand in range(int(np.sqrt(n)), 0, -1):
        if n % cand == 0:
            pods_dim = cand
            break
    nodes_dim = n // pods_dim
    grid = np.asarray(devices).reshape(pods_dim, nodes_dim)
    return Mesh(grid, (PODS_AXIS, NODES_AXIS))


def make_node_mesh(
    n_devices: Optional[int] = None, devices: Optional[Sequence] = None
) -> Mesh:
    """1-D ("nodes",) mesh for the sharded wave solver: EVERY device on the
    node axis. The wave hot loop's only sharded dimension is the node axis
    (pod-window state is replicated and cheap); a 2-D factorization would
    idle the pods-axis devices during the per-wave ring election."""
    if devices is None:
        devices = jax.devices()
    if n_devices is not None:
        devices = devices[:n_devices]
    return Mesh(np.asarray(devices), (NODES_AXIS,))


def pad_to_shards(n: int, n_shards: int) -> int:
    """Smallest multiple of `n_shards` >= n — the mesh-aligned node-axis
    padding rule shared by `dryrun_multichip` and the sharded wave solve
    (padded rows carry zero capacity and node id -1, so they can never win
    a wave election; tests/test_shard_wave.py gates the edge)."""
    return ((n + n_shards - 1) // n_shards) * n_shards


def snapshot_shardings(snap, mesh: Mesh):
    """Sharding pytree for a ClusterSnapshot: node-major arrays shard their
    leading axis over "nodes", pod-major arrays over "pods", side tables
    (gangs/quota/cost matrices) replicate — segment reductions over them ride
    collectives."""

    def spec_for(path, leaf):
        top = path[0].name if path else ""
        if top == "nodes" or top == "numa" or top == "metrics":
            return NamedSharding(mesh, P(NODES_AXIS, *([None] * (leaf.ndim - 1))))
        if top == "pods":
            return NamedSharding(mesh, P(PODS_AXIS, *([None] * (leaf.ndim - 1))))
        if top == "network" and path[-1].name == "placed_node" and leaf.ndim == 2:
            return NamedSharding(mesh, P(None, NODES_AXIS))
        if top == "syscalls":
            name = path[-1].name
            if name in ("host_sets", "counts", "host_pod_count"):
                return NamedSharding(mesh, P(NODES_AXIS, *([None] * (leaf.ndim - 1))))
            return NamedSharding(mesh, P(PODS_AXIS, *([None] * (leaf.ndim - 1))))
        return NamedSharding(mesh, P())  # replicate side tables

    return jax.tree_util.tree_map_with_path(spec_for, snap)


def shard_snapshot(snap, mesh: Mesh):
    """Place a snapshot on the mesh per `snapshot_shardings`."""
    shardings = snapshot_shardings(snap, mesh)
    return jax.tree.map(jax.device_put, snap, shardings)
