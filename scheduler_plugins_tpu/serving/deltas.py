"""Delta taxonomy + the jittable O(changed) scatter-apply program.

The reference's watch-driven design never rebuilds state: informer events
mutate NodeInfo incrementally and each cycle reads the live cache. This
module is the tensor equivalent for the serving engine
(`serving.engine.ServeEngine`): host mutations of the `Cluster` store are
captured as typed delta events by a `DeltaSink` (installed as
`Cluster.delta_sink`), coalesced and packed into two fixed-bucket array
groups, and applied to the device-resident `NodeState` columns by ONE
jitted scatter program whose resident carry is DONATED — the node tensors
thread cycle to cycle in place, and the per-cycle work is O(changed), not
O(cluster).

Delta taxonomy (the `api.events` kinds each group expresses):

- `NodeUpserts` — Node/Add, Node/Update: row overwrites of the static node
  columns (alloc, capacity, mask, region, zone). Expressed as
  scatter-ADD of `new - current` (gathered in-jit), so padded rows are
  exact no-ops and duplicate indices cannot race: the host coalesces to at
  most one upsert per slot per batch, making the add exact.
- `UsageDeltas` — Pod/Add (assigned), Pod/Update (bind / terminating
  flip), Pod/Delete: signed contributions to the usage columns
  (requested, nonzero_requested, limits, pod_count, terminating),
  mirroring exactly the per-assigned-pod accumulation
  `state.snapshot.build_snapshot` performs — scatter-add, where duplicate
  indices are well-defined (sum) and padded rows are zero.
- Node/Delete (and anything the scatter programs cannot express — row
  reordering, label re-interning, extended resources) re-bases instead:
  `api.events.SERVE_REBASE_EVENTS`, the same rule the C++ columnar
  mirror applies (`Cluster._native_rebuild`).

Both groups are padded to `utils.intmath.bucket_size` buckets so the jit
cache stays warm across cycles (distinct (U, K) bucket pairs retrace once
each, like every other padded shape in this repo). All inputs are
ARGUMENTS — no config closure captures (CLAUDE.md / GL001) and no wall
clocks inside jit (GL008).
"""

from __future__ import annotations

import numpy as np
from flax import struct

from scheduler_plugins_tpu.api.resources import PODS, ResourceIndex
from scheduler_plugins_tpu.resilience import faults as _faults
from scheduler_plugins_tpu.state.snapshot import NodeState, nonzero_request
from scheduler_plugins_tpu.utils.intmath import bucket_size

#: serve mode pins the resource axis to the canonical four (the same
#: constraint the C++ columnar store's 4-slot layout imposes); a pod or
#: node naming an extended resource disengages the engine until a rebase
CANON_INDEX = ResourceIndex(())
PODS_I = CANON_INDEX.position(PODS)

I64 = np.int64
I32 = np.int32

#: shared zero vector for events without a resource payload (terminating
#: flips); read-only by convention
ZERO_R = np.zeros(len(CANON_INDEX), I64)
ZERO_R.setflags(write=False)


class UnsupportedResource(ValueError):
    """An object names a resource outside the canonical axis — the packed
    delta vectors cannot carry it (serve falls back / re-bases)."""


def _encode(quantities: dict) -> np.ndarray:
    try:
        return CANON_INDEX.encode(quantities)
    except KeyError as exc:
        raise UnsupportedResource(str(exc)) from exc


def pod_quota_vector(pod) -> np.ndarray:
    """One assigned pod's contribution to its namespace's ElasticQuota
    `used` row — the RAW effective-request encode (no pods-slot override:
    `build_snapshot`'s quota accumulation sums `index.encode(
    pod.effective_request())` verbatim). Raises `UnsupportedResource` on
    extended resources, like the usage vectors."""
    return _encode(pod.effective_request())


def pod_usage_vectors(pod) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
    """(requested, nonzero_requested, limits) contribution of ONE assigned
    pod to its node's usage columns — the exact per-pod accumulation
    `build_snapshot` performs: nonzero defaults applied, limits clamped to
    >= requests per pod (SetMaxLimits), and the pods slot carrying the
    count contribution (1) on the requested/nonzero columns (the snapshot
    overwrites those slots with pod_count). Raises `UnsupportedResource`
    on extended resources."""
    req = _encode(pod.effective_request())
    nz = nonzero_request(req, CANON_INDEX)
    lim = np.maximum(_encode(pod.effective_limits()), req)
    req = req.copy()
    req[PODS_I] = 1
    nz[PODS_I] = 1
    return req, nz, lim


# ---------------------------------------------------------------------------
# delta sink: the Cluster's mutation hooks push typed events here
# ---------------------------------------------------------------------------

# event tuples: (kind, payload...) — kept as raw object references; the
# engine derives the RESOURCE vectors at drain time (upserts replace pod
# objects wholesale, so event-time references are stable for requests/
# limits), but the terminating FLAG is captured at event time:
# `mark_terminating` mutates the live pod in place AND queues its own
# POD_TERMINATING delta, so a drain-time read of the flag would double-
# count a flip that lands in the same drain window as the pod's assign
NODE_UPSERT = "node_upsert"
NODE_DELETE = "node_delete"
POD_ASSIGN = "pod_assign"
POD_UNASSIGN = "pod_unassign"
POD_TERMINATING = "pod_terminating"
#: gang GATED-count transition (resident gang side tables): an UNBOUND,
#: scheduling-gated gang member appeared (+1) or left that state (-1).
#: The full snapshot counts such pods into `GangState.gated`/`total`
#: (via `Cluster.gated_pods`), and no node-column event fires for them —
#: the mutators push this kind with the delta captured at EVENT time
#: (the gate/terminating flags mutate in place)
GANG_GATED = "gang_gated"


class DeltaSink:
    """Typed event queue installed as `Cluster.delta_sink`. The store's
    mutators (`add_node`, `bind`, `remove_pod`, ...) push exactly the
    state transitions that change node columns; `drain()` hands the
    accumulated batch to the engine once per cycle. Host-side and
    allocation-light: one list append per mutation."""

    #: backstop for a sink nobody drains (engine dropped, serve mode
    #: toggled off while still attached): past this many undrained events
    #: a full re-snapshot is cheaper than replaying them anyway, so the
    #: queue collapses to an `overflowed` marker (the next refresh
    #: re-bases) instead of pinning Pod references without bound
    MAX_EVENTS = 1 << 18

    def __init__(self):
        self.events: list[tuple] = []
        self.overflowed = False
        #: drain generation: bumped by every `drain()` — the pipelined
        #: engine's conflict-fence accounting compares it around a bind
        #: flush to tell whether the flush crossed an ingest boundary
        self.drains = 0
        #: unbound pods carrying a NominatedNodeName that the per-cycle
        #: pending gate cannot see (scheduling-gated pods arrive through
        #: `add_pod`, never through the pending batch) — any entry keeps
        #: `ServeEngine.compatible` False: the full snapshot counts such
        #: nominations into the `nominated` node column and nominee-hold
        #: tables, which the resident columns do not carry
        self.nominated_unbound: set[str] = set()

    def _push(self, ev: tuple) -> None:
        if _faults.ACTIVE is not None:
            # chaos harness only (zero overhead when no plan is
            # installed): drop/duplicate/corrupt THIS sink event — the
            # Cluster store never sees the mutation, so the poisoning is
            # invisible to everything except the serving engine's
            # anti-entropy digest (docs/ROBUSTNESS.md)
            for mutated in _faults.mutate_delta(ev):
                self._push_one(mutated)
            return
        self._push_one(ev)

    def _push_one(self, ev: tuple) -> None:
        if len(self.events) >= self.MAX_EVENTS:
            self.events.clear()
            self.overflowed = True
        self.events.append(ev)

    # -- node lifecycle --------------------------------------------------
    def node_upsert(self, node) -> None:
        self._push((NODE_UPSERT, node))

    def node_delete(self, name: str) -> None:
        self._push((NODE_DELETE, name))

    # -- pod usage transitions ------------------------------------------
    def pod_assigned(self, pod, node_name: str) -> None:
        """Pod now holds capacity on `node_name` (bound OR permit-
        reserved — reservations count exactly like bindings in the
        snapshot's assigned view). The terminating flag rides in the
        event (a later `mark_terminating` queues its OWN +1 delta)."""
        self._push(
            (POD_ASSIGN, pod, node_name, bool(pod.terminating))
        )

    def pod_unassigned(self, pod, node_name: str) -> None:
        self._push(
            (POD_UNASSIGN, pod, node_name, bool(pod.terminating))
        )

    def pod_terminating(self, pod, node_name: str) -> None:
        """Terminating flag flipped False -> True on a held (bound or
        reserved) pod."""
        self._push((POD_TERMINATING, pod, node_name))

    # -- gang side-table transitions ------------------------------------
    def gang_gated(self, gang_full_name: str, delta: int) -> None:
        """Unbound+gated membership transition of gang `gang_full_name`
        (+1 appeared / -1 left). Delta captured at event time — the
        scheduling-gate and terminating flags mutate pods in place, so a
        drain-time re-read could double- or under-count a flip landing in
        the same drain window (the POD_ASSIGN terminating-flag rule)."""
        self._push((GANG_GATED, gang_full_name, delta))

    # -- sticky compatibility flags -------------------------------------
    def note_nomination(self, pod) -> None:
        """Track/untrack an upserted pod's nomination (reads the SAME pod
        object the next full snapshot would, so the two views agree)."""
        if pod.node_name is None and pod.nominated_node_name is not None:
            self.nominated_unbound.add(pod.uid)
        else:
            self.nominated_unbound.discard(pod.uid)

    def forget_nomination(self, uid: str) -> None:
        self.nominated_unbound.discard(uid)

    def drain(self) -> list[tuple]:
        events, self.events = self.events, []
        self.drains += 1
        return events

    def consume_overflow(self) -> bool:
        """True once if the queue overflowed since the last drain — the
        surviving events are partial, so the caller must re-base."""
        overflowed, self.overflowed = self.overflowed, False
        return overflowed


# ---------------------------------------------------------------------------
# packed delta batches (fixed-bucket shapes; numpy on the host side)
# ---------------------------------------------------------------------------


class NodeUpserts:
    """Packed node-row overwrites: at most one row per slot (host-
    coalesced), padded to a bucket with valid=False rows."""

    __slots__ = ("idx", "valid", "alloc", "capacity", "mask", "region",
                 "zone")

    def __init__(self, idx, valid, alloc, capacity, mask, region, zone):
        self.idx = idx
        self.valid = valid
        self.alloc = alloc
        self.capacity = capacity
        self.mask = mask
        self.region = region
        self.zone = zone

    @classmethod
    def pack(cls, rows: list[tuple], R: int) -> "NodeUpserts":
        """`rows`: [(slot, alloc_vec, cap_vec, schedulable, region_code,
        zone_code)] with unique slots."""
        U = bucket_size(max(len(rows), 1))
        idx = np.zeros(U, I32)
        valid = np.zeros(U, bool)
        alloc = np.zeros((U, R), I64)
        capacity = np.zeros((U, R), I64)
        mask = np.zeros(U, I32)
        region = np.full(U, -1, I32)
        zone = np.full(U, -1, I32)
        for j, (slot, a, c, sched, r, z) in enumerate(rows):
            idx[j] = slot
            valid[j] = True
            alloc[j] = a
            capacity[j] = c
            mask[j] = 1 if sched else 0
            region[j] = r
            zone[j] = z
        return cls(idx, valid, alloc, capacity, mask, region, zone)

    def as_args(self) -> tuple:
        return (self.idx, self.valid, self.alloc, self.capacity, self.mask,
                self.region, self.zone)

    def as_dict(self) -> dict:
        """Plain-dict view for flight-recorder packing (generic unpack —
        no struct registry entry needed)."""
        return {
            "idx": self.idx, "valid": self.valid, "alloc": self.alloc,
            "capacity": self.capacity, "mask": self.mask,
            "region": self.region, "zone": self.zone,
        }


class UsageDeltas:
    """Packed signed usage contributions; duplicate slots sum (scatter-add
    semantics), padded rows are zero."""

    __slots__ = ("idx", "requested", "nonzero", "limits", "pod_count",
                 "terminating")

    def __init__(self, idx, requested, nonzero, limits, pod_count,
                 terminating):
        self.idx = idx
        self.requested = requested
        self.nonzero = nonzero
        self.limits = limits
        self.pod_count = pod_count
        self.terminating = terminating

    #: bucket floor: steady churn wobbles around its Poisson mean, and a
    #: 16/32/64 bucket flip-flop would retrace the apply program mid-run;
    #: one 64-row floor covers typical per-cycle event counts with a
    #: single compiled shape (padding 64 zero rows costs nothing)
    MIN_BUCKET = 64

    @classmethod
    def pack(cls, rows: list[tuple], R: int) -> "UsageDeltas":
        """`rows`: [(slot, req_vec, nz_vec, lim_vec, d_count, d_term)]
        where the vectors already carry the event's sign."""
        K = bucket_size(max(len(rows), 1), minimum=cls.MIN_BUCKET)
        idx = np.zeros(K, I32)
        requested = np.zeros((K, R), I64)
        nonzero = np.zeros((K, R), I64)
        limits = np.zeros((K, R), I64)
        pod_count = np.zeros(K, I32)
        terminating = np.zeros(K, I32)
        for j, (slot, req, nz, lim, d_count, d_term) in enumerate(rows):
            idx[j] = slot
            requested[j] = req
            nonzero[j] = nz
            limits[j] = lim
            pod_count[j] = d_count
            terminating[j] = d_term
        return cls(idx, requested, nonzero, limits, pod_count, terminating)

    def as_args(self) -> tuple:
        return (self.idx, self.requested, self.nonzero, self.limits,
                self.pod_count, self.terminating)

    def as_dict(self) -> dict:
        return {
            "idx": self.idx, "requested": self.requested,
            "nonzero": self.nonzero, "limits": self.limits,
            "pod_count": self.pod_count, "terminating": self.terminating,
        }


# ---------------------------------------------------------------------------
# the jittable apply program
# ---------------------------------------------------------------------------


def apply_node_deltas(nodes: NodeState,
                      up_idx, up_valid, up_alloc, up_capacity, up_mask,
                      up_region, up_zone,
                      d_idx, d_requested, d_nonzero, d_limits, d_pod_count,
                      d_terminating) -> NodeState:
    """Fold one packed delta batch into the resident `NodeState` columns.

    Upserts use the gather-diff form — `add(new - current)` under the
    valid mask — so padded rows are exact no-ops without needing current
    values host-side, and the only write primitive anywhere is a
    well-defined scatter-add (no unordered scatter-set). Bool/int32
    columns round-trip through int32 arithmetic (exact). Usage deltas are
    plain scatter-adds of signed contributions. The `nodes` argument is
    donated at the jit boundary (`delta_apply_program`): callers treat the
    resident carry as consumed and rebind it from the result."""
    import jax.numpy as jnp

    gi = up_idx

    def overwrite2(cur, new):
        # (N, R) row overwrite as add(new - current); pads contribute 0
        delta = jnp.where(up_valid[:, None], new - cur[gi], 0)
        return cur.at[gi].add(delta)

    def overwrite1(cur, new):
        # (N,) int32-or-bool overwrite through exact int32 arithmetic
        cur_i = cur.astype(jnp.int32)
        delta = jnp.where(up_valid, new - cur_i[gi], 0)
        return cur_i.at[gi].add(delta).astype(cur.dtype)

    nodes = nodes.replace(
        alloc=overwrite2(nodes.alloc, up_alloc),
        capacity=overwrite2(nodes.capacity, up_capacity),
        mask=overwrite1(nodes.mask, up_mask),
        region=overwrite1(nodes.region, up_region),
        zone=overwrite1(nodes.zone, up_zone),
        # serve mode owns the snapshot only while NO nomination exists
        # anywhere (ServeEngine.compatible) — the resident nominated
        # column is invariantly zero. Written fresh (not passed through)
        # so no donated buffer aliases an output (JA002).
        nominated=jnp.zeros_like(nodes.nominated),
    )
    di = d_idx
    return nodes.replace(
        requested=nodes.requested.at[di].add(d_requested),
        nonzero_requested=nodes.nonzero_requested.at[di].add(d_nonzero),
        limits=nodes.limits.at[di].add(d_limits),
        pod_count=nodes.pod_count.at[di].add(d_pod_count),
        terminating=nodes.terminating.at[di].add(d_terminating),
    )


def compact_node_rows(nodes: NodeState, gather_idx, valid) -> NodeState:
    """Delete node rows in place: gather the surviving rows into their
    shifted slots (`gather_idx`, host-computed) and re-pad the freed tail
    (`valid` False) with the exact values a fresh `build_snapshot` pad
    row carries (zeros; mask False; region/zone -1) — so the compacted
    resident columns stay byte-identical to a rebase's, and the
    anti-entropy digest cannot tell them apart. Row ORDER is preserved
    (a shift, never a swap-with-last): the store's dict pop preserves the
    order of the remaining nodes, and score tie-breaking is
    lowest-index. This turns the Node/Delete rebase — the one O(cluster)
    event in steady churn — into an O(changed)-host, O(N)-device
    gather (`StreamingServeEngine`). The `nodes` argument is donated at
    the jit boundary (`node_compact_program`)."""
    import jax.numpy as jnp

    def take2(cur):
        return jnp.where(valid[:, None], cur[gather_idx], 0)

    def take1(cur, pad=0):
        out = cur[gather_idx]
        return jnp.where(valid, out, jnp.asarray(pad).astype(out.dtype))

    return nodes.replace(
        alloc=take2(nodes.alloc),
        capacity=take2(nodes.capacity),
        requested=take2(nodes.requested),
        nonzero_requested=take2(nodes.nonzero_requested),
        limits=take2(nodes.limits),
        mask=take1(nodes.mask, False),
        region=take1(nodes.region, -1),
        zone=take1(nodes.zone, -1),
        pod_count=take1(nodes.pod_count),
        terminating=take1(nodes.terminating),
        # invariantly zero while serve mode owns the snapshot (the
        # compatibility gate excludes nominations); written fresh so no
        # donated buffer aliases an output (JA002)
        nominated=jnp.zeros_like(nodes.nominated),
    )


# ---------------------------------------------------------------------------
# resident gang/quota side tables (ISSUE 12; docs/SERVING.md)
# ---------------------------------------------------------------------------

@struct.dataclass
class SideTables:
    """Device-resident gang/quota aggregate side tables, in ENGINE-stable
    row order (first-seen gang / namespace; the per-cycle assembly
    permutes host copies into that cycle's snapshot interning order).
    These are the per-POD aggregates a fresh `build_snapshot` pays
    O(cluster) pod loops for — maintained O(changed) from the drained
    delta stream by `apply_side_deltas`, exactly like the node columns:

    - gang_assigned (G,) i32 / gang_slack (G, R) i64: bound+reserved
      members and their request sums (pods slot 1) per gang — the
      `GangState.assigned` / `cluster_slack` aggregates.
    - gang_gated (G,) i32: unbound scheduling-gated members (the
      `gated_pods()` contribution to `GangState.gated`/`total_members`).
    - quota_used (Q, R) i64: per-namespace assigned request sums (the
      `QuotaState.used` accumulation, raw encodes).
    - ns_assigned (Q,) i32: assigned-pod count per namespace — only used
      host-side to reproduce the fresh snapshot's namespace-interning
      tail (namespaces with assigned pods intern after batch + quotas;
      their rows are all-default, so only the SET matters).
    """

    gang_assigned: np.ndarray
    gang_gated: np.ndarray
    gang_slack: np.ndarray
    quota_used: np.ndarray
    ns_assigned: np.ndarray


def zero_side_tables(G: int, Q: int, R: int) -> SideTables:
    import jax.numpy as jnp

    return SideTables(
        gang_assigned=jnp.zeros(G, jnp.int32),
        gang_gated=jnp.zeros(G, jnp.int32),
        gang_slack=jnp.zeros((G, R), jnp.int64),
        quota_used=jnp.zeros((Q, R), jnp.int64),
        ns_assigned=jnp.zeros(Q, jnp.int32),
    )


class SideDeltas:
    """Packed side-table delta batch: gang rows (engine-stable gang row,
    d_assigned, d_gated, d_slack) + namespace rows (engine-stable ns row,
    d_used, d_count), bucket-padded with zero-delta rows (scatter-add
    no-ops) so the jit cache stays warm across cycles."""

    __slots__ = ("g_idx", "g_assigned", "g_gated", "g_slack",
                 "q_idx", "q_used", "q_count")

    MIN_BUCKET = 16

    def __init__(self, g_idx, g_assigned, g_gated, g_slack, q_idx, q_used,
                 q_count):
        self.g_idx = g_idx
        self.g_assigned = g_assigned
        self.g_gated = g_gated
        self.g_slack = g_slack
        self.q_idx = q_idx
        self.q_used = q_used
        self.q_count = q_count

    @classmethod
    def pack(cls, gang_rows: list[tuple], ns_rows: list[tuple],
             R: int) -> "SideDeltas":
        """`gang_rows`: [(row, d_assigned, d_gated, d_slack_vec)];
        `ns_rows`: [(row, d_used_vec, d_count)]. Duplicate rows sum."""
        Ug = bucket_size(max(len(gang_rows), 1), minimum=cls.MIN_BUCKET)
        Uq = bucket_size(max(len(ns_rows), 1), minimum=cls.MIN_BUCKET)
        g_idx = np.zeros(Ug, I32)
        g_assigned = np.zeros(Ug, I32)
        g_gated = np.zeros(Ug, I32)
        g_slack = np.zeros((Ug, R), I64)
        for j, (row, da, dg, ds) in enumerate(gang_rows):
            g_idx[j] = row
            g_assigned[j] = da
            g_gated[j] = dg
            g_slack[j] = ds
        q_idx = np.zeros(Uq, I32)
        q_used = np.zeros((Uq, R), I64)
        q_count = np.zeros(Uq, I32)
        for j, (row, du, dc) in enumerate(ns_rows):
            q_idx[j] = row
            q_used[j] = du
            q_count[j] = dc
        return cls(g_idx, g_assigned, g_gated, g_slack, q_idx, q_used,
                   q_count)

    def as_args(self) -> tuple:
        return (self.g_idx, self.g_assigned, self.g_gated, self.g_slack,
                self.q_idx, self.q_used, self.q_count)

    def as_dict(self) -> dict:
        return {
            "g_idx": self.g_idx, "g_assigned": self.g_assigned,
            "g_gated": self.g_gated, "g_slack": self.g_slack,
            "q_idx": self.q_idx, "q_used": self.q_used,
            "q_count": self.q_count,
        }


def apply_side_deltas(tables: SideTables, g_idx, g_assigned, g_gated,
                      g_slack, q_idx, q_used, q_count) -> SideTables:
    """Fold one packed side-table delta batch into the resident gang/
    quota aggregates. Pure scatter-adds (duplicate rows sum; padded rows
    are zero-delta no-ops at row 0), mirroring `apply_node_deltas`'s
    discipline; the `tables` argument is donated at the jit boundary
    (`side_apply_program`) — callers rebind the resident carry from the
    result."""
    return tables.replace(
        gang_assigned=tables.gang_assigned.at[g_idx].add(g_assigned),
        gang_gated=tables.gang_gated.at[g_idx].add(g_gated),
        gang_slack=tables.gang_slack.at[g_idx].add(g_slack),
        quota_used=tables.quota_used.at[q_idx].add(q_used),
        ns_assigned=tables.ns_assigned.at[q_idx].add(q_count),
    )


#: process-wide memo keyed by sanitize mode: every `ServeEngine` (and a
#: chaos-harness crash restart, which builds a fresh one mid-run) shares
#: ONE jitted apply program per mode, so engine reconstruction never pays
#: a recompile for an already-warm shape
_APPLY_PROGRAMS: dict = {}
_COMPACT_PROGRAMS: dict = {}
_SIDE_PROGRAMS: dict = {}


def side_apply_program():
    """The jitted side-table apply program with the resident carry
    DONATED — same constructor/memo discipline as `delta_apply_program`,
    registered with the AOT compile-readiness gate as
    `serving_side_apply`."""
    import jax

    from scheduler_plugins_tpu.utils import observability as obs
    from scheduler_plugins_tpu.utils import sanitize

    key = sanitize.enabled()
    if key in _SIDE_PROGRAMS:
        return _SIDE_PROGRAMS[key]
    if key:
        jitted = sanitize.checkified(
            apply_side_deltas, program="serve_side_apply"
        )
    else:
        jitted = jax.jit(apply_side_deltas, donate_argnums=(0,))
    _SIDE_PROGRAMS[key] = obs.compile_watch(
        jitted, program="serve_side_apply"
    )
    return _SIDE_PROGRAMS[key]


def node_compact_program():
    """The jitted row-compaction program with the resident carry DONATED
    (`StreamingServeEngine` node-delete path) — same constructor/memo
    discipline as `delta_apply_program`, registered with the AOT
    compile-readiness gate as `serving_node_compact`."""
    import jax

    from scheduler_plugins_tpu.utils import observability as obs
    from scheduler_plugins_tpu.utils import sanitize

    key = sanitize.enabled()
    if key in _COMPACT_PROGRAMS:
        return _COMPACT_PROGRAMS[key]
    if key:
        jitted = sanitize.checkified(
            compact_node_rows, program="serve_node_compact"
        )
    else:
        jitted = jax.jit(compact_node_rows, donate_argnums=(0,))
    _COMPACT_PROGRAMS[key] = obs.compile_watch(
        jitted, program="serve_node_compact"
    )
    return _COMPACT_PROGRAMS[key]


def delta_apply_program():
    """The jitted apply program with the resident carry DONATED — the
    serving engine's calling convention (rebind the carry from the
    result; GL006). One constructor shared by `ServeEngine` and the AOT
    compile-readiness gate (`tools/tpu_lower.py` serving_delta_apply) so
    the certified program is the shipped program, memoized process-wide
    per sanitize mode. Under `SPT_SANITIZE=1` the program is built
    checkify-instrumented with donation dropped, like every other
    donated jit in the repo."""
    import jax

    from scheduler_plugins_tpu.utils import observability as obs
    from scheduler_plugins_tpu.utils import sanitize

    key = sanitize.enabled()
    if key in _APPLY_PROGRAMS:
        return _APPLY_PROGRAMS[key]
    if key:
        jitted = sanitize.checkified(
            apply_node_deltas, program="serve_delta_apply"
        )
    else:
        jitted = jax.jit(apply_node_deltas, donate_argnums=(0,))
    _APPLY_PROGRAMS[key] = obs.compile_watch(
        jitted, program="serve_delta_apply"
    )
    return _APPLY_PROGRAMS[key]
