"""Resident-state serving engine: device-resident SolverState inputs across
cycles + O(changed) delta ingestion (ROADMAP item 3; docs/SERVING.md)."""

from scheduler_plugins_tpu.serving.deltas import (  # noqa: F401
    DeltaSink,
    NodeUpserts,
    UsageDeltas,
    apply_node_deltas,
    compact_node_rows,
    delta_apply_program,
    node_compact_program,
    pod_usage_vectors,
)
from scheduler_plugins_tpu.serving.engine import (  # noqa: F401
    ServeEngine,
    StreamingServeEngine,
)
