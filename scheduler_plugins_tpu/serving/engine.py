"""ServeEngine: device-resident node state across cycles, O(changed) ingest.

`framework.cycle.run_cycle(serve=engine)` swaps the per-cycle full
re-snapshot (`Cluster.snapshot`: an O(nodes + assigned pods) Python
rebuild plus a full host->device ship) for this engine's `refresh`: the
`NodeState` columns live on device across cycles and each refresh applies
only the deltas the store's mutation hooks captured since the last one
(`serving.deltas.DeltaSink`), via one donated scatter program. The solve
itself is untouched — the assembled snapshot feeds the SAME bit-faithful
sequential parity path, so serve-mode placements are bit-identical to a
fresh-snapshot solve (gated by tests/test_serving.py's delta-equivalence
differential).

Capacity policy (docs/SERVING.md):

- **grow**: node adds past the padded capacity pad the resident columns
  to the next `bucket_size` bucket device-side (cheap `jnp.pad`, usage
  history preserved; one retrace for the new shape).
- **re-base** (the compact path): Node/Delete, an existing node's
  region/zone label change, an extended-resource sighting, or a pod event
  against a node the engine has never seen (cross-watch ordering) all
  invalidate either the row order or the packed axis — the engine
  rebuilds from a fresh `Cluster.snapshot` at the canonical bucket for
  the new node count, exactly like the C++ columnar mirror's
  `_native_rebuild`. Rare control-plane events pay O(cluster); steady
  churn pays O(changed).

Compatibility gate: the engine owns the snapshot while every side table
is either None or one the resident state fully describes. Gang
(PodGroup) and quota (ElasticQuota) rosters are OWNED since ISSUE 12 —
their aggregate tensors assemble O(G + Q) from resident side tables
(`serving.deltas.SideTables`) maintained O(changed) from the same
drained delta stream, docs/SERVING.md "Resident gang/quota side
tables". NRTs/AppGroups/seccomp profiles/node metrics/selector-spec
pods/node taints and any nomination or extended resource still gate
(the same shape of condition as the native-store fast path in
`Cluster.snapshot`). While incompatible, `refresh` returns None (the
cycle falls back to the full snapshot) but KEEPS absorbing deltas, so
the resident columns stay in sync and serving resumes without a rebase
once the side objects go away.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from scheduler_plugins_tpu.serving import deltas as D
from scheduler_plugins_tpu.state.snapshot import (
    ClusterSnapshot,
    GangState,
    QuotaState,
    SnapshotMeta,
    _Interner,
    build_pod_state,
    empty_quota_nominees,
    gang_object_tables,
    quota_object_tables,
)
from scheduler_plugins_tpu.utils import observability as obs
from scheduler_plugins_tpu.utils.intmath import bucket_size


class ServeEngine:
    """Long-lived serving engine for one `Cluster` store."""

    def __init__(self):
        self._sink = D.DeltaSink()
        self._cluster = None
        self._nodes = None  # resident NodeState (device arrays) or None
        self._npad = 0
        self._names: list[str] = []  # slot order == cluster.nodes order
        self._slots: dict[str, int] = {}
        # first-seen label interning over the shared tables (the snapshot
        # path's own _Interner — one convention, O(1) lookups)
        self._regions: list[str] = []
        self._zones: list[str] = []
        self._regions_in = _Interner(self._regions)
        self._zones_in = _Interner(self._zones)
        self._node_labels: dict[str, tuple] = {}  # name -> (region, zone)
        self._tainted: set[str] = set()
        self._apply = D.delta_apply_program()
        self._generation = 0
        self._rebases = 0
        self._staleness = 0  # delta events applied since last rebase
        self._base_digest: Optional[str] = None
        #: last refresh's packed batch + mode, for the flight recorder
        self._last: Optional[dict] = None
        # -- anti-entropy (docs/ROBUSTNESS.md) --------------------------
        #: digest the resident columns against a freshly built snapshot
        #: every N serving refreshes (0 = periodic checks off); any
        #: divergence forces a rebase, so a corrupted/dropped delta can
        #: poison at most one verification window. SPT_SERVE_VERIFY_EVERY
        #: overrides.
        self.verify_every = self._verify_every_default()
        self._refreshes = 0
        #: force a verify at the next refresh (set by `note_fault` — any
        #: watchdog/backend fault is treated as potential corruption)
        self._verify_pending = False
        self.antientropy_divergences = 0
        self.last_fault: Optional[str] = None
        # -- rank-gang awareness (docs/GANGS.md) ------------------------
        #: gang full_name -> {pod uid: node name}: the per-gang resident
        #: rank-assignment mirror, maintained O(changed) from the SAME
        #: drained delta stream that feeds the node columns — elastic
        #: grow/shrink consumers read the current rank roster without a
        #: cluster re-scan. Gang-carrying rosters still DEGRADE the
        #: snapshot path to fallback (`compatible` returns False while
        #: PodGroups exist): the resident node columns cannot express
        #: gang/quota side tables, and serving them anyway would
        #: silently mis-serve — the mirror keeps absorbing so serving
        #: resumes the moment the gangs drain away.
        self.resident_ranks: dict[str, dict] = {}
        #: refreshes that fell back while the cluster carried PodGroups.
        #: Since ISSUE 12 a gang/quota roster is served RESIDENT (the
        #: side tables below) — this counts only fallbacks forced by some
        #: OTHER incompatibility while gangs were present, so a compatible
        #: gang roster keeps it at 0 (`make endurance-smoke` gates that).
        #: Exported as `scheduler_serve_gang_fallbacks_total`.
        self.gang_fallbacks = 0
        # -- resident gang/quota side tables (ISSUE 12; docs/SERVING.md)
        #: device-resident `serving.deltas.SideTables` aggregates in
        #: engine-stable row order, maintained O(changed) by the donated
        #: `side_apply_program` from the SAME drained delta stream as the
        #: node columns; None until first built
        self._side = None
        self._gang_rows: dict[str, int] = {}  # gang full_name -> row
        self._ns_rows: dict[str, int] = {}  # namespace -> row
        self._side_apply = D.side_apply_program()
        self._side_gpad = 0
        self._side_qpad = 0
        #: gang slack depends on node EXISTENCE (a fresh snapshot drops
        #: contributions of pods bound to since-deleted nodes) — the rare
        #: invalidating events (node delete under streaming compaction, a
        #: previously-unknown node arriving, checkpoint restore) mark the
        #: side tables dirty; the next assembly rebuilds them with ONE
        #: O(pods) store scan instead of corrupting incrementally
        self._side_dirty = True
        #: per-namespace quota aggregates are maintained only once an
        #: ElasticQuota has been sighted — without this gate every bind in
        #: a quota-less cluster would pay a side-delta row (and a second
        #: apply dispatch) for tables nobody reads
        self._quota_tracking = False

    @staticmethod
    def _verify_every_default() -> int:
        import os

        try:
            return int(os.environ.get("SPT_SERVE_VERIFY_EVERY", "32"))
        except ValueError:
            return 32

    # -- wiring ---------------------------------------------------------
    def attach(self, cluster) -> "ServeEngine":
        """Install the delta sink on `cluster`. The resident base is built
        lazily at the first `refresh` (which sees the full store)."""
        cluster.delta_sink = self._sink
        self._cluster = cluster
        self._nodes = None
        return self

    def detach(self) -> None:
        """Uninstall the sink and drop the resident base. Call when serve
        mode is retired for a still-live cluster — otherwise every mutator
        keeps appending events nobody drains (bounded by
        `DeltaSink.MAX_EVENTS`, but pinning Pod references until then)."""
        if (
            self._cluster is not None
            and self._cluster.delta_sink is self._sink
        ):
            self._cluster.delta_sink = None
        self._cluster = None
        self._nodes = None
        self._sink.events.clear()
        self._sink.overflowed = False
        self._sink.nominated_unbound.clear()
        self._side = None
        self._side_dirty = True
        self._gang_rows.clear()
        self._ns_rows.clear()

    @property
    def generation(self) -> int:
        return self._generation

    @property
    def rebases(self) -> int:
        """Full re-snapshots THIS engine performed (the process-global
        `scheduler_serve_rebases_total` sums across engines/runs)."""
        return self._rebases

    @property
    def resident_nodes(self):
        """The live resident `NodeState` (None before the first refresh).
        Treat as consumed after the next `refresh` — the apply program
        donates it."""
        return self._nodes

    @property
    def npad(self) -> int:
        return self._npad

    # -- compatibility gate ---------------------------------------------
    def compatible(self, cluster, pending) -> bool:
        """True when the engine can own this cycle's snapshot: every
        side table is either None or one the resident state fully
        describes. Gang (PodGroup) and quota (ElasticQuota) rosters are
        OWNED since ISSUE 12 — their aggregate tensors assemble from the
        resident side tables — as long as their resources stay on the
        canonical axis; NRTs/AppGroups/seccomp/metrics/selector-spec
        pods/taints/nominations still fall back."""
        if (
            cluster.nrts
            or cluster.app_groups
            or cluster.seccomp_profiles
            or cluster.node_metrics is not None
            or cluster._selector_spec_pods
            or self._tainted
        ):
            return False
        # gang/quota objects naming an extended resource widen the fresh
        # snapshot's packed axis past the canonical four (build_snapshot
        # unions PodGroup.min_resources and quota min/max) — the resident
        # columns cannot express that; O(G + Q), objects only
        for pg in cluster.pod_groups.values():
            if pg.min_resources and any(
                r not in D.CANON_INDEX for r in pg.min_resources
            ):
                return False
        for eq in cluster.quotas.values():
            if any(r not in D.CANON_INDEX for r in eq.min) or any(
                r not in D.CANON_INDEX for r in eq.max
            ):
                return False
        # nominations OUTSIDE the pending batch still count into the full
        # snapshot's nominated column / nominee holds: scheduling-gated
        # nominees (sink-tracked at upsert) and reserved nominees
        # (O(reserved), in practice unreachable without gangs)
        if self._sink.nominated_unbound:
            return False
        for uid in cluster.reserved:
            p = cluster.pods.get(uid)
            if p is not None and p.nominated_node_name is not None:
                return False
        # batch-local specs (O(batch), not O(cluster)): node affinity
        # feeds SchedulingState; nominations feed the nominee holds;
        # extended resources fall outside the canonical packed axis
        for pod in pending:
            if (
                pod.node_selector
                or pod.node_affinity_required
                or pod.node_affinity_preferred
                or pod.nominated_node_name is not None
                or any(
                    r not in D.CANON_INDEX for r in pod.effective_request()
                )
                or any(
                    r not in D.CANON_INDEX for r in pod.effective_limits()
                )
            ):
                return False
        return True

    # -- the per-cycle entry --------------------------------------------
    def refresh(self, cluster, pending, now_ms: int = 0):
        """(snapshot, meta) for this cycle, or None when the engine cannot
        own the state (caller falls back to `Cluster.snapshot`). Drains
        the sink either way — deltas are absorbed even while falling
        back, so the resident columns never go stale."""
        with obs.tracer.span("ServeRefresh/drain", tid="serve"):
            events = self._sink.drain()
        obs.metrics.set_gauge(obs.SERVE_PENDING_DELTAS, len(events))
        if cluster.quotas and not self._quota_tracking:
            # first ElasticQuota sighting: start maintaining the quota
            # aggregates; the activation rebuild picks up every already-
            # assigned pod (classification below only carries deltas)
            self._quota_tracking = True
            self._side_dirty = True
        with obs.tracer.span(
            "ServeRefresh/classify", tid="serve", events=len(events)
        ):
            upserts, usage, side, rebase = self._ingest(events)
        if self._sink.consume_overflow():
            # the queue collapsed while nobody drained: the surviving
            # events are a partial window — the resident base is
            # unrecoverable from deltas alone
            rebase = "sink-overflow"
            self._side_dirty = True
        n_nodes = len(cluster.nodes)
        grow = self._nodes is not None and n_nodes > self._npad

        if not self.compatible(cluster, pending):
            if cluster.pod_groups:
                self.gang_fallbacks += 1
                obs.metrics.inc(obs.SERVE_GANG_FALLBACKS)
            # keep the columns in sync while incompatible; a rebase-class
            # event just drops the base (rebuilt at the next compatible
            # refresh)
            if rebase:
                self._nodes = None
                self._side_dirty = True
            elif self._nodes is not None:
                if grow:
                    self._grow(bucket_size(n_nodes))
                self._apply_batch(upserts, usage, side)
            self._last = None
            return None

        if rebase or self._nodes is None:
            return self._rebase(cluster, pending, now_ms)
        if grow:
            self._grow(bucket_size(n_nodes))
        self._apply_batch(upserts, usage, side)
        self._refreshes += 1
        if self._verify_pending or (
            self.verify_every and self._refreshes % self.verify_every == 0
        ):
            divergence = self.verify(cluster)
            if divergence is not None:
                return self._rebase(cluster, pending, now_ms)
        if (cluster.pod_groups or cluster.quotas) and not self._ensure_side(
            cluster
        ):
            # defensive: the side tables could not be rebuilt (an
            # extended-resource assigned pod appeared between the axis
            # checks) — serve this cycle from the full snapshot
            self._last = None
            return None
        return self._assemble(cluster, pending, now_ms)

    # -- event classification -------------------------------------------
    def _ingest(self, events):
        """Classification seam: the streaming subclass splits the event
        stream at node-delete boundaries (compacting rows in place); the
        base engine classifies the whole batch, with a node delete
        forcing a rebase."""
        return self._classify(events)

    def _pod_vectors(self, pod, final=False):
        """One pod's (requested, nonzero, limits, quota) contribution
        vectors — the node usage columns' per-pod arithmetic plus the
        ElasticQuota `used` row's raw request encode. The streaming
        subclass memoizes this per pod object (`final` marks the pod's
        last event, releasing its entry)."""
        return D.pod_usage_vectors(pod) + (D.pod_quota_vector(pod),)

    def _row_cache(self):
        """Per-pod assembly memo passed to `build_pod_state` (None in the
        base engine: every cycle lowers its batch from scratch)."""
        return None

    def _stage_args(self, args):
        """Host->device staging of one packed delta batch. The base
        engine ships explicit device copies; the streaming engine hands
        pjit the numpy arrays directly (one C++ shard_args pass instead
        of a Python conversion per array — same bytes either way)."""
        import jax.numpy as jnp

        return tuple(jnp.asarray(a) for a in args)

    def _stage_pods(self, pod_state):
        """Host->device staging of the assembled pod tensors (same
        split as `_stage_args`)."""
        import jax
        import jax.numpy as jnp

        return jax.tree.map(jnp.asarray, pod_state)

    def _gang_row(self, name: str) -> int:
        row = self._gang_rows.get(name)
        if row is None:
            row = self._gang_rows[name] = len(self._gang_rows)
        return row

    def _ns_row(self, name: str) -> int:
        row = self._ns_rows.get(name)
        if row is None:
            row = self._ns_rows[name] = len(self._ns_rows)
        return row

    def _classify(self, events):
        """Coalesce drained events into packed-row lists. Returns
        (upsert_rows, usage_rows, side_rows, rebase_reason|None) where
        `side_rows` is the (gang_rows, ns_rows) pair feeding the resident
        gang/quota side tables (`serving.deltas.SideDeltas.pack`)."""
        upserts: dict[int, tuple] = {}  # slot -> row (last write wins)
        usage: list[tuple] = []
        # side aggregates coalesce per engine-stable row (sums)
        gang_acc: dict[int, list] = {}
        ns_acc: dict[int, list] = {}
        R = len(D.CANON_INDEX)
        rebase = None

        def fail(reason):
            nonlocal rebase
            if rebase is None:
                rebase = reason

        def gang_add(name, d_assigned, d_gated, d_slack):
            row = self._gang_row(name)
            acc = gang_acc.get(row)
            if acc is None:
                acc = gang_acc[row] = [0, 0, np.zeros(R, np.int64)]
            acc[0] += d_assigned
            acc[1] += d_gated
            if d_slack is not None:
                acc[2] = acc[2] + d_slack

        def ns_add(name, d_used, d_count):
            row = self._ns_row(name)
            acc = ns_acc.get(row)
            if acc is None:
                acc = ns_acc[row] = [np.zeros(R, np.int64), 0]
            acc[0] = acc[0] + d_used
            acc[1] += d_count

        for ev in events:
            kind = ev[0]
            if kind == D.GANG_GATED:
                # unbound gated gang-membership transition (event-time
                # delta; see Cluster._gang_gated_key)
                gang_add(ev[1], 0, ev[2], None)
                continue
            if kind == D.NODE_DELETE:
                # the row order dies with the node — but so do its label/
                # taint entries: a deleted node must not pin `compatible`
                # False forever (the rebase that follows rebuilds these
                # tables only on the COMPATIBLE path)
                name = ev[1]
                self._tainted.discard(name)
                self._node_labels.pop(name, None)
                fail("node-delete")
            elif kind == D.NODE_UPSERT:
                node = ev[1]
                if node.taints:
                    self._tainted.add(node.name)
                else:
                    self._tainted.discard(node.name)
                labels = (node.region or "", node.zone or "")
                prev = self._node_labels.get(node.name)
                if prev is not None and prev != labels:
                    # region/zone re-interning cannot be expressed as a
                    # row overwrite (codes are first-seen in slot order)
                    fail("label-change")
                self._node_labels[node.name] = labels
                slot = self._slots.get(node.name)
                if slot is None:
                    slot = len(self._names)
                    self._slots[node.name] = slot
                    self._names.append(node.name)
                    if self._gang_rows:
                        # a NEW node name can resurrect gang slack for
                        # pods already bound to it (cross-watch arrival:
                        # fresh snapshots include slack only for nodes
                        # that exist) — rebuild rather than drift
                        self._side_dirty = True
                try:
                    alloc = D._encode(node.allocatable)
                    cap = D._encode(node.capacity)
                except D.UnsupportedResource:
                    fail("extended-resource")
                    continue
                upserts[slot] = (
                    slot, alloc, cap, not node.unschedulable,
                    self._regions_in.code(node.region) if node.region
                    else -1,
                    self._zones_in.code(node.zone) if node.zone else -1,
                )
            else:  # pod usage transitions
                pod, node_name = ev[1], ev[2]
                gang = pod.pod_group()
                if gang:
                    # O(changed) per-gang resident rank mirror: assigns
                    # record the rank's node, unassigns drop it (the
                    # terminating transition keeps the slot — the rank
                    # still occupies its node until the delete lands)
                    roster = self.resident_ranks.setdefault(
                        f"{pod.namespace}/{gang}", {}
                    )
                    if kind == D.POD_ASSIGN:
                        roster[pod.uid] = node_name
                    elif kind != D.POD_TERMINATING:
                        roster.pop(pod.uid, None)
                        if not roster:
                            self.resident_ranks.pop(
                                f"{pod.namespace}/{gang}", None
                            )
                slot = self._slots.get(node_name)
                if kind == D.POD_TERMINATING:
                    if slot is None:
                        fail("unknown-node")
                        continue
                    usage.append((slot, D.ZERO_R, D.ZERO_R, D.ZERO_R, 0, 1))
                    continue
                sign = 1 if kind == D.POD_ASSIGN else -1
                try:
                    req, nz, lim, qreq = self._pod_vectors(
                        pod, final=kind == D.POD_UNASSIGN
                    )
                except D.UnsupportedResource:
                    fail("extended-resource")
                    continue
                # side-table contributions FIRST: the quota used row and
                # the gang assigned count follow the pod regardless of
                # node existence (build_snapshot's rule); gang slack only
                # when the node is known (fresh drops unknown-node slack)
                if self._quota_tracking:
                    ns_add(pod.namespace, sign * qreq, sign)
                if gang:
                    gang_add(
                        f"{pod.namespace}/{gang}", sign, 0,
                        sign * req if slot is not None else None,
                    )
                if slot is None:
                    # pod referenced a node the engine never saw (cross-
                    # watch ordering): the fresh snapshot skips such pods
                    # until the node arrives, at which point row contents
                    # change wholesale — re-base to stay exact
                    fail("unknown-node")
                    continue
                # event-time flag, NOT pod.terminating: a mark_terminating
                # between event and drain mutates the pod in place and
                # queues its own +1 — a drain-time read would double-count
                term = 1 if ev[3] else 0
                usage.append((
                    slot, sign * req, sign * nz, sign * lim, sign,
                    sign * term,
                ))
        side = (
            [(row, a, g, s) for row, (a, g, s) in gang_acc.items()],
            [(row, u, c) for row, (u, c) in ns_acc.items()],
        )
        return list(upserts.values()), usage, side, rebase

    # -- state transitions ----------------------------------------------
    def _apply_batch(self, upsert_rows, usage_rows, side=None) -> None:
        with obs.tracer.span(
            "ServeRefresh/apply", tid="serve",
            upserts=len(upsert_rows), usage=len(usage_rows),
        ):
            self._apply_batch_inner(upsert_rows, usage_rows, side)

    def _apply_batch_inner(self, upsert_rows, usage_rows, side=None) -> None:
        import warnings

        import jax
        import jax.numpy as jnp

        R = len(D.CANON_INDEX)
        ups = D.NodeUpserts.pack(upsert_rows, R)
        use = D.UsageDeltas.pack(usage_rows, R)
        # slot indices are host-validated (< npad); the jit scatter relies
        # on that, and SPT_SANITIZE=1 re-checks it with checkify
        with warnings.catch_warnings(record=True) as caught:
            warnings.simplefilter("always")
            self._nodes = self._apply(
                self._nodes,
                *self._stage_args(ups.as_args()),
                *self._stage_args(use.as_args()),
            )
        for w in caught:
            msg = str(w.message)
            if "donated buffers were not usable" not in msg:
                warnings.warn_explicit(
                    w.message, w.category, w.filename, w.lineno
                )
            elif msg.count("[") > 1 and jax.default_backend() != "cpu":
                # ONE undonated buffer is expected — the intentionally
                # unused `nominated` column (rewritten as zeros). More
                # than one on a donating backend means the resident
                # columns silently stopped aliasing, i.e. every apply
                # pays the O(cluster) copy this subsystem exists to
                # remove — keep that visible. (CPU never donates and
                # lists everything, like the profile solves of PR 2.)
                warnings.warn_explicit(
                    w.message, w.category, w.filename, w.lineno
                )
        side_dict = self._apply_side(side)
        self._generation += 1
        n_events = len(upsert_rows) + len(usage_rows)
        self._staleness += n_events
        self._last = {
            "mode": "delta", "events": n_events,
            "upserts": ups.as_dict(), "usage": use.as_dict(),
        }
        if side_dict is not None:
            self._last["side"] = side_dict
        self._observe()

    def _apply_side(self, side):
        """Fold this window's packed side-table deltas into the resident
        gang/quota aggregates (donated jit scatter). Skipped entirely for
        windows without gang/quota rows (the common quota-less churn
        case pays nothing) and while the tables are dirty — the pending
        O(pods) rebuild supersedes any incremental application."""
        if side is None:
            return None
        gang_rows, ns_rows = side
        if (not gang_rows and not ns_rows) or self._side_dirty:
            return None
        if self._side is None:
            self._side_dirty = True
            return None
        import warnings

        R = len(D.CANON_INDEX)
        need_g = max((row for row, *_ in gang_rows), default=-1) + 1
        need_q = max((row for row, *_ in ns_rows), default=-1) + 1
        self._grow_side(need_g, need_q)
        packed = D.SideDeltas.pack(gang_rows, ns_rows, R)
        with warnings.catch_warnings():
            # CPU backends never donate and list every buffer
            warnings.filterwarnings(
                "ignore", message=".*donated buffers were not usable.*"
            )
            self._side = self._side_apply(
                self._side, *self._stage_args(packed.as_args())
            )
        return packed.as_dict()

    def _grow_side(self, need_g: int, need_q: int) -> None:
        """Pad the resident side tables to cover rows `need_g`/`need_q`
        (bucketed, zero-padded — new gangs/namespaces appear mid-run)."""
        import jax.numpy as jnp

        new_g = bucket_size(max(need_g, self._side_gpad, 1))
        new_q = bucket_size(max(need_q, self._side_qpad, 1))
        if new_g == self._side_gpad and new_q == self._side_qpad:
            return

        def pad1(arr, n):
            widths = [(0, n - arr.shape[0])] + [(0, 0)] * (arr.ndim - 1)
            return jnp.pad(arr, widths)

        self._side = self._side.replace(
            gang_assigned=pad1(self._side.gang_assigned, new_g),
            gang_gated=pad1(self._side.gang_gated, new_g),
            gang_slack=pad1(self._side.gang_slack, new_g),
            quota_used=pad1(self._side.quota_used, new_q),
            ns_assigned=pad1(self._side.ns_assigned, new_q),
        )
        self._side_gpad = new_g
        self._side_qpad = new_q

    def _grow(self, new_npad: int) -> None:
        """Pad the resident columns to a larger bucket device-side —
        usage history is preserved, only the shape changes (one retrace
        of the apply/solve programs for the new bucket)."""
        import jax.numpy as jnp

        pad = new_npad - self._npad
        if pad <= 0:
            return
        nodes = self._nodes

        def pad1(arr, value=0):
            widths = [(0, pad)] + [(0, 0)] * (arr.ndim - 1)
            return jnp.pad(arr, widths, constant_values=value)

        self._nodes = nodes.replace(
            alloc=pad1(nodes.alloc),
            capacity=pad1(nodes.capacity),
            requested=pad1(nodes.requested),
            nonzero_requested=pad1(nodes.nonzero_requested),
            limits=pad1(nodes.limits),
            mask=pad1(nodes.mask, False),
            region=pad1(nodes.region, -1),
            zone=pad1(nodes.zone, -1),
            pod_count=pad1(nodes.pod_count),
            terminating=pad1(nodes.terminating),
            nominated=pad1(nodes.nominated),
        )
        self._npad = new_npad

    def _rebase(self, cluster, pending, now_ms: int):
        """Full re-snapshot: rebuild the resident base from the store (the
        compact path — the new bucket fits the CURRENT node count) and
        reset slot/interning tables to the store's own order."""
        with obs.tracer.span(
            "ServeRefresh/rebase", tid="serve", nodes=len(cluster.nodes)
        ):
            return self._rebase_inner(cluster, pending, now_ms)

    def _rebase_inner(self, cluster, pending, now_ms: int):
        npad = bucket_size(max(len(cluster.nodes), 1))
        snap, meta = cluster.snapshot(
            pending, now_ms=now_ms, pad_nodes=npad,
        )
        if len(meta.index) != len(D.CANON_INDEX):
            # an extended resource somewhere in the store (node column or
            # an ASSIGNED pod's requests) widens the packed axis past the
            # canonical four the delta vectors carry — the resident
            # columns cannot own this state. Serve this cycle from the
            # fresh snapshot and keep re-basing (full-snapshot cost,
            # exact) until the extended objects go away.
            self._nodes = None
            self._side_dirty = True
            self._generation += 1
            self._staleness = 0
            self._rebases += 1
            obs.metrics.inc(obs.SERVE_REBASES)
            self._observe()
            self._last = None
            return snap, meta
        self._nodes = snap.nodes
        self._npad = npad
        self._names = list(meta.node_names)
        self._slots = {n: i for i, n in enumerate(self._names)}
        self._regions = meta.regions  # share: _assemble copies per cycle
        self._zones = meta.zones
        self._regions_in = _Interner(self._regions)
        self._zones_in = _Interner(self._zones)
        self._node_labels = {
            n.name: (n.region or "", n.zone or "")
            for n in cluster.nodes.values()
        }
        self._tainted = {n.name for n in cluster.nodes.values() if n.taints}
        # a rebase is already O(cluster): rebuild the gang/quota side
        # tables in the same breath (their aggregates must match the
        # fresh snapshot this rebase just served from)
        self._rebuild_side_tables(cluster)
        self._generation += 1
        self._staleness = 0
        self._rebases += 1
        obs.metrics.inc(obs.SERVE_REBASES)
        self._base_digest = None
        from scheduler_plugins_tpu.utils import flightrec

        if flightrec.recorder.enabled:
            self._base_digest = flightrec._pack_digest(
                {k: np.asarray(v) for k, v in self._node_columns().items()}
            )
        self._last = {"mode": "rebase", "events": 0}
        self._observe()
        return snap, meta

    # -- resident gang/quota side tables --------------------------------
    def _ensure_side(self, cluster) -> bool:
        """Side tables ready for assembly: rebuild them from one O(pods)
        store scan when dirty or absent (activation, node-set change,
        restore, divergence)."""
        if self._side is not None and not self._side_dirty:
            return True
        return self._rebuild_side_tables(cluster)

    def _scan_side_aggregates(self, cluster):
        """ONE store scan producing the gang/quota aggregate dicts a
        fresh `build_snapshot` would accumulate: {gang full_name:
        [assigned, gated, slack_vec]} + {namespace: [used_vec, count]}.
        Shared by the rebuild (packs them resident) and the anti-entropy
        verify (compares them against the resident copies). Raises
        `UnsupportedResource` on extended-resource assigned pods — the
        same condition that already keeps the engine on the
        full-snapshot rebase path."""
        R = len(D.CANON_INDEX)
        gangs: dict[str, list] = {}
        namespaces: dict[str, list] = {}

        def gang_acc(name):
            acc = gangs.get(name)
            if acc is None:
                acc = gangs[name] = [0, 0, np.zeros(R, np.int64)]
            return acc

        for pod in cluster.pods.values():
            held = pod.node_name or cluster.reserved.get(pod.uid)
            gang = pod.pod_group()
            if held is not None:
                req, _nz, _lim, qreq = self._pod_vectors(pod)
                if self._quota_tracking:
                    acc = namespaces.get(pod.namespace)
                    if acc is None:
                        acc = namespaces[pod.namespace] = [
                            np.zeros(R, np.int64), 0,
                        ]
                    acc[0] = acc[0] + qreq
                    acc[1] += 1
                if gang:
                    acc = gang_acc(f"{pod.namespace}/{gang}")
                    acc[0] += 1
                    if held in cluster.nodes:
                        # fresh snapshots count slack only for nodes that
                        # exist (node_pos membership)
                        acc[2] = acc[2] + req
            # gated runs on `gated_pods()`'s own predicate (node_name is
            # None), INDEPENDENT of a permit reservation: a reserved
            # gated pod counts BOTH ways in a fresh snapshot (assigned
            # via its materialized reserved copy, gated via the real
            # unbound object) and the delta stream mirrors that
            # (POD_ASSIGN at reserve + GANG_GATED at upsert)
            if (
                gang
                and pod.node_name is None
                and pod.scheduling_gated
                and not pod.terminating
            ):
                gang_acc(f"{pod.namespace}/{gang}")[1] += 1
        return gangs, namespaces

    def _rebuild_side_tables(self, cluster) -> bool:
        """Rebuild the resident side tables from the store (O(pods), the
        rare path — steady state is the O(changed) `_apply_side`).
        Returns False (tables stay dirty) when an extended-resource
        assigned pod makes the canonical-axis aggregates unrepresentable
        — the axis-width rebase rule already keeps the engine off the
        resident path in exactly that state."""
        import jax.numpy as jnp

        with obs.tracer.span(
            "ServeRefresh/side_rebuild", tid="serve",
            pods=len(cluster.pods),
        ):
            try:
                gangs, namespaces = self._scan_side_aggregates(cluster)
            except D.UnsupportedResource:
                self._side_dirty = True
                return False
            R = len(D.CANON_INDEX)
            self._gang_rows = {name: i for i, name in enumerate(gangs)}
            self._ns_rows = {name: i for i, name in enumerate(namespaces)}
            self._side_gpad = bucket_size(max(len(gangs), 1))
            self._side_qpad = bucket_size(max(len(namespaces), 1))
            ga = np.zeros(self._side_gpad, np.int32)
            gg = np.zeros(self._side_gpad, np.int32)
            gs = np.zeros((self._side_gpad, R), np.int64)
            qu = np.zeros((self._side_qpad, R), np.int64)
            qc = np.zeros(self._side_qpad, np.int32)
            for name, (assigned, gated, slack) in gangs.items():
                row = self._gang_rows[name]
                ga[row] = assigned
                gg[row] = gated
                gs[row] = slack
            for name, (used, count) in namespaces.items():
                row = self._ns_rows[name]
                qu[row] = used
                qc[row] = count
            self._side = D.SideTables(
                gang_assigned=jnp.asarray(ga),
                gang_gated=jnp.asarray(gg),
                gang_slack=jnp.asarray(gs),
                quota_used=jnp.asarray(qu),
                ns_assigned=jnp.asarray(qc),
            )
            self._side_dirty = False
            return True

    def _side_host(self) -> dict:
        """Host copies of the resident side tables (small: (G,)/(Q, R))."""
        return {
            "gang_assigned": np.asarray(self._side.gang_assigned),
            "gang_gated": np.asarray(self._side.gang_gated),
            "gang_slack": np.asarray(self._side.gang_slack),
            "quota_used": np.asarray(self._side.quota_used),
            "ns_assigned": np.asarray(self._side.ns_assigned),
        }

    def _side_verify_live(self, cluster) -> bool:
        """True when the side tables have state worth verifying (skipped
        — costing nothing — in plain churn)."""
        return (
            self._side is not None
            and not self._side_dirty
            and bool(
                cluster.pod_groups or cluster.quotas
                or self._quota_tracking
            )
        )

    def _side_divergence(self, gangs: dict, namespaces: dict
                         ) -> Optional[str]:
        """Compare expected aggregate dicts (a `_scan_side_aggregates`
        result) against the resident side tables. Consumes the dicts."""
        host = self._side_host()
        for name, row in self._gang_rows.items():
            exp = gangs.pop(name, None)
            if exp is None:
                exp = [0, 0, np.zeros(len(D.CANON_INDEX), np.int64)]
            if (
                int(host["gang_assigned"][row]) != exp[0]
                or int(host["gang_gated"][row]) != exp[1]
                or not (host["gang_slack"][row] == exp[2]).all()
            ):
                return "side-gang"
        if gangs:
            return "side-gang"  # expected rows the resident table lacks
        for name, row in self._ns_rows.items():
            exp = namespaces.pop(name, None)
            if exp is None:
                exp = [np.zeros(len(D.CANON_INDEX), np.int64), 0]
            if (
                int(host["ns_assigned"][row]) != exp[1]
                or not (host["quota_used"][row] == exp[0]).all()
            ):
                return "side-quota"
        if namespaces:
            return "side-quota"
        return None

    def _verify_side(self, cluster) -> Optional[str]:
        """Anti-entropy over the gang/quota side tables: recompute the
        expected aggregates from the store (independent of the delta
        path) and compare to the resident copies. Skipped — costing
        nothing — while no gang/quota state is live. (The streaming
        engine folds the expectation into its single `_expected_columns`
        pass instead of paying a second store scan.)"""
        if not self._side_verify_live(cluster):
            return None
        try:
            gangs, namespaces = self._scan_side_aggregates(cluster)
        except D.UnsupportedResource:
            return None  # axis-width rule owns this state
        return self._side_divergence(gangs, namespaces)

    # -- anti-entropy ----------------------------------------------------
    def note_fault(self, reason: Optional[str] = None) -> None:
        """Treat any runtime fault (watchdog timeout/device error/garbage
        output, crash restore) as potential resident-state corruption:
        the NEXT refresh digests the resident columns against a freshly
        built snapshot before serving from them."""
        self._verify_pending = True
        self.last_fault = reason

    def verify(self, cluster) -> Optional[str]:
        """Anti-entropy digest: blake2b over the canonical tensor bytes
        of the resident node columns (the flight-recorder content-address
        scheme) vs the same columns of a freshly built snapshot. Returns
        a divergence reason (caller re-bases) or None (resident state is
        byte-exact). O(cluster) host work — cadenced by `verify_every`,
        forced by `note_fault`; a corrupted or dropped delta can
        therefore poison at most one verification window
        (tests/test_resilience.py::TestAntiEntropy)."""
        from scheduler_plugins_tpu.utils import flightrec

        with obs.tracer.span(
            "ServeRefresh/verify", tid="serve", staleness=self._staleness
        ):
            self._verify_pending = False
            obs.metrics.inc(obs.ANTIENTROPY_CHECKS)
            if self._nodes is None:
                return None
            fresh, meta = cluster.snapshot(
                [], now_ms=0, pad_nodes=self._npad
            )
            reason = None
            if len(meta.index) != len(D.CANON_INDEX):
                reason = "axis-width"
            elif list(meta.node_names) != self._names:
                reason = "row-order"
            else:
                mine = flightrec._pack_digest(
                    {k: np.asarray(v)
                     for k, v in self._node_columns().items()}
                )
                theirs = flightrec._pack_digest(
                    {k: np.asarray(getattr(fresh.nodes, k))
                     for k in self._node_columns()}
                )
                if mine != theirs:
                    reason = "column-digest"
            if reason is None:
                reason = self._verify_side(cluster)
            if reason is not None:
                self.antientropy_divergences += 1
                obs.metrics.inc(obs.ANTIENTROPY_DIVERGENCE)
                obs.logger.warning(
                    "serve anti-entropy divergence (%s) after %d delta "
                    "events%s: re-basing", reason, self._staleness,
                    f" (last fault: {self.last_fault})"
                    if self.last_fault else "",
                )
            return reason

    # -- checkpoint / restore -------------------------------------------
    #: checkpoint format version (bump on layout change; restore refuses
    #: versions it does not understand)
    CHECKPOINT_VERSION = 1

    def checkpoint_bytes(self) -> Optional[bytes]:
        """Self-contained npz of the resident columns + slot/interning
        tables, or None before the first refresh. Written crash-safe by
        `save_checkpoint`; a process killed after writing one resumes
        serving via `restore_checkpoint` without rebuilding the resident
        base from the store."""
        import io
        import json as _json

        if self._nodes is None:
            return None
        cols = {k: np.asarray(v) for k, v in self._node_columns().items()}
        cols["nominated"] = np.asarray(self._nodes.nominated)
        header = {
            "version": self.CHECKPOINT_VERSION,
            "npad": self._npad,
            "generation": self._generation,
            "staleness": self._staleness,
            "names": self._names,
            "regions": self._regions,
            "zones": self._zones,
            "node_labels": {k: list(v) for k, v in
                            self._node_labels.items()},
            "tainted": sorted(self._tainted),
        }
        buf = io.BytesIO()
        np.savez(
            buf,
            header=np.frombuffer(
                _json.dumps(header, sort_keys=True).encode(), np.uint8
            ),
            **cols,
        )
        return buf.getvalue()

    def save_checkpoint(self, path: str) -> bool:
        """Crash-safe checkpoint write (`obs.atomic_write` temp+rename).
        Returns False when there is no resident base to checkpoint."""
        data = self.checkpoint_bytes()
        if data is None:
            return False
        obs.atomic_write(path, data)
        return True

    def restore_checkpoint(self, source) -> bool:
        """Rebuild the resident base from a checkpoint (`bytes` or a file
        path) — call AFTER `attach`. The restored state is NOT trusted
        blindly: `note_fault` marks it for an anti-entropy verify at the
        next refresh, so a checkpoint stale against the live store (the
        usual case after a crash — the dying sink's undrained deltas are
        gone) re-bases within one window, while an exact one resumes
        serving with generation continuity and no rebase
        (tests/test_resilience.py::TestCheckpointRestore)."""
        import io
        import json as _json

        import jax.numpy as jnp

        if isinstance(source, (str, bytes, bytearray)):
            data = source
            if isinstance(source, str):
                with open(source, "rb") as f:
                    data = f.read()
        else:
            raise TypeError(f"checkpoint source {type(source).__name__}")
        with np.load(io.BytesIO(data), allow_pickle=False) as z:
            header = _json.loads(bytes(z["header"].tobytes()).decode())
            if header.get("version") != self.CHECKPOINT_VERSION:
                raise ValueError(
                    f"checkpoint version {header.get('version')} != "
                    f"{self.CHECKPOINT_VERSION}"
                )
            from scheduler_plugins_tpu.state.snapshot import NodeState

            self._nodes = NodeState(
                **{k: jnp.asarray(z[k]) for k in (
                    "alloc", "capacity", "requested", "nonzero_requested",
                    "limits", "mask", "region", "zone", "pod_count",
                    "terminating", "nominated",
                )}
            )
        self._npad = int(header["npad"])
        self._generation = int(header["generation"])
        self._staleness = int(header["staleness"])
        self._names = list(header["names"])
        self._slots = {n: i for i, n in enumerate(self._names)}
        self._regions = list(header["regions"])
        self._zones = list(header["zones"])
        self._regions_in = _Interner(self._regions)
        self._zones_in = _Interner(self._zones)
        self._node_labels = {
            k: tuple(v) for k, v in header["node_labels"].items()
        }
        self._tainted = set(header["tainted"])
        # side tables are cheap to re-derive (one store scan) relative to
        # checkpointing them: rebuilt lazily at the next gang/quota use
        self._side = None
        self._side_dirty = True
        self._gang_rows = {}
        self._ns_rows = {}
        self._quota_tracking = False
        self._base_digest = None
        self._last = None
        self.note_fault("checkpoint-restore")
        self._observe()
        return True

    def _node_columns(self) -> dict:
        n = self._nodes
        return {
            "alloc": n.alloc, "capacity": n.capacity,
            "requested": n.requested,
            "nonzero_requested": n.nonzero_requested, "limits": n.limits,
            "mask": n.mask, "region": n.region, "zone": n.zone,
            "pod_count": n.pod_count, "terminating": n.terminating,
        }

    def _assemble(self, cluster, pending, now_ms: int = 0):
        """Snapshot view over the resident node columns + this cycle's
        pending batch (built through the same `build_pod_state` the full
        snapshot path uses, so the pod tensors are bit-identical). Gang
        and quota rosters assemble their `GangState`/`QuotaState` from
        the resident side tables: the per-PodGroup/per-quota OBJECT
        columns re-lower O(G + Q) through the SAME
        `gang_object_tables`/`quota_object_tables` the fresh path uses,
        the per-pod AGGREGATES come from the O(changed)-maintained side
        tables — never an O(cluster) pod loop."""
        with obs.tracer.span(
            "ServeRefresh/assemble", tid="serve", pending=len(pending)
        ):
            return self._assemble_inner(cluster, pending, now_ms)

    def _assemble_inner(self, cluster, pending, now_ms: int = 0):
        P = bucket_size(max(len(pending), 1))
        R = len(D.CANON_INDEX)
        meta = SnapshotMeta(index=D.CANON_INDEX)
        meta.node_names = list(self._names)
        meta.pod_names = [p.uid for p in pending]
        meta.regions = list(self._regions)
        meta.zones = list(self._zones)
        ns_in = _Interner(meta.namespaces)

        # gang interning in pod_groups-dict order — build_snapshot's own
        # first-seen rule, so codes match the fresh path's exactly
        pod_groups = list(cluster.pod_groups.values())
        gangs_in = _Interner(meta.gang_names)
        gang_pos = {
            pg.full_name: gangs_in.code(pg.full_name) for pg in pod_groups
        }

        def gang_of(pod):
            name = pod.pod_group()
            if not name:
                return -1
            return gang_pos.get(f"{pod.namespace}/{name}", -1)

        batch_counts: dict[int, int] = {}
        if pod_groups:
            def gang_of_counted(pod, _inner=gang_of):
                g = _inner(pod)
                if g >= 0:
                    batch_counts[g] = batch_counts.get(g, 0) + 1
                return g
            gang_code = gang_of_counted
        else:
            gang_code = gang_of
        pod_state = build_pod_state(
            pending, P, D.CANON_INDEX, ns_in, gang_code,
            cluster.tlp_prediction, row_cache=self._row_cache(),
        )

        gang_state = quota_state = None
        side = (
            self._side_host() if (pod_groups or cluster.quotas) else None
        )
        if pod_groups:
            G = max(len(gang_pos), 1)
            backed_off = [
                name
                for name, until in cluster.gang_backoff_until_ms.items()
                if until > now_ms
            ]
            obj = gang_object_tables(
                pod_groups, gang_pos, D.CANON_INDEX, G, backed_off
            )
            assigned = np.zeros(G, np.int32)
            gated = np.zeros(G, np.int32)
            slack = np.zeros((G, R), np.int64)
            for pg in pod_groups:
                row = self._gang_rows.get(pg.full_name)
                if row is None:
                    continue
                g = gang_pos[pg.full_name]
                assigned[g] = side["gang_assigned"][row]
                gated[g] = side["gang_gated"][row]
                slack[g] = side["gang_slack"][row]
            # total = this cycle's batch members + assigned + gated: the
            # same three populations build_snapshot's pod loop walks
            total = (assigned + gated).astype(np.int32)
            for g, count in batch_counts.items():
                total[g] += count
            gang_state = GangState(
                total_members=total,
                assigned=assigned,
                gated=gated,
                cluster_slack=slack,
                **obj,
            )
        if cluster.quotas:
            quotas = list(cluster.quotas.values())
            # fresh interning order: batch namespaces (above), then quota
            # namespaces, then assigned-pod namespaces. The assigned tail
            # rows are all-default (used accumulates only under a quota),
            # so only the SET matters — the resident count tracks it.
            for q in quotas:
                ns_in.code(q.namespace)
            for name, row in self._ns_rows.items():
                if side["ns_assigned"][row] > 0:
                    ns_in.code(name)
            Q = max(len(meta.namespaces), 1)
            qmin, qmax, qhas = quota_object_tables(
                quotas, D.CANON_INDEX, ns_in, Q
            )
            qused = np.zeros((Q, R), np.int64)
            for q in quotas:
                row = self._ns_rows.get(q.namespace)
                if row is not None:
                    qused[ns_in.get(q.namespace)] = side["quota_used"][row]
            nom_req, nom_in_eq, nom_total, nom_batch = empty_quota_nominees(
                R, P
            )
            quota_state = QuotaState(
                min=qmin, max=qmax, used=qused, has_quota=qhas,
                nom_req=nom_req, nom_in_eq_mask=nom_in_eq,
                nom_total_mask=nom_total, nom_batch_idx=nom_batch,
            )
        snap = ClusterSnapshot(
            nodes=self._nodes,
            pods=self._stage_pods(pod_state),
            gangs=self._stage_pods(gang_state)
            if gang_state is not None else None,
            quota=self._stage_pods(quota_state)
            if quota_state is not None else None,
        )
        return snap, meta

    def _observe(self) -> None:
        obs.metrics.set_gauge(obs.SERVE_GENERATION, self._generation)
        obs.metrics.set_gauge(obs.SERVE_STALENESS, self._staleness)

    # -- observability hookups ------------------------------------------
    def annotate_record(self, rec) -> None:
        """Attach the serve-cycle provenance to a flight-recorder record:
        resident generation, events-since-base staleness, the base
        snapshot digest, and the packed delta stream itself (as plain
        dict-of-array specs, so generic `unpack_pytree` reads them back).
        The record stays replayable through the standard path — the
        assembled snapshot is captured in full — and this block is the
        evidence tying it to the delta stream that produced it."""
        from scheduler_plugins_tpu.utils.flightrec import pack_pytree

        if self._last is None:
            return
        serve = {
            "generation": self._generation,
            "staleness_events": self._staleness,
            "base_digest": self._base_digest,
            "mode": self._last["mode"],
            "events": self._last["events"],
        }
        if self._last["mode"] == "delta":
            packed = {
                "upserts": self._last["upserts"],
                "usage": self._last["usage"],
            }
            if "side" in self._last:
                packed["side"] = self._last["side"]
            serve["deltas"] = pack_pytree(packed, rec.blobs)
        rec.manifest["serve"] = serve


def _shift_gather_args(npad: int, slot: int, survivors: int):
    """(gather_idx, valid) for `compact_node_rows`: rows above `slot`
    shift down one, the freed tail re-pads; `survivors` real rows remain.
    ONE constructor shared by the live compaction path and the AOT
    compile-readiness gate, so the certified argument layout IS the
    shipped one."""
    idx = np.empty(npad, np.int32)
    idx[:slot] = np.arange(slot, dtype=np.int32)
    idx[slot:npad - 1] = np.arange(slot + 1, npad, dtype=np.int32)
    idx[npad - 1] = npad - 1
    valid = np.zeros(npad, bool)
    valid[:survivors] = True
    return idx, valid


class StreamingServeEngine(ServeEngine):
    """O(changed)-everything serving engine for the pipelined cycle
    engine (`framework.pipeline_cycle.PipelinedCycle`; docs/SCALING.md
    measured breakdown). Same exactness contract as the base engine —
    the differential gates hold it bit-identical to fresh snapshots —
    with three streaming-ingest upgrades:

    - **Node-delete compaction**: a Node/Delete no longer forces the
      O(cluster) rebase. The resident rows are shift-compacted in place
      by one donated gather program (`serving.deltas.compact_node_rows`),
      preserving row order (= the store's dict order after the pop) and
      re-padding the freed tail byte-identically to a fresh snapshot's
      pad rows. The event stream is segmented at each delete so slot
      numbering stays exact within every applied batch. Remaining
      rebase-class events (label re-interning, extended resources,
      unknown-node pods, sink overflow) rebase exactly as before. One
      self-healing caveat: the region/zone interning tables survive a
      compaction, so deleting the first-seen carrier of a label code can
      make the next anti-entropy digest diverge from a fresh re-intern —
      the divergence rebases (exact, just slower), never mis-serves.
    - **Usage-vector memo**: `pod_usage_vectors` is cached per pod
      OBJECT (a feed upsert replaces the object wholesale, naturally
      invalidating); a pod's final unassign releases its entry.
    - **Pod-row memo**: `build_pod_state` runs with a per-pod row cache,
      so retried pods re-lower nothing (hits are bit-identical by
      construction — the cache stores the same encodes the cold path
      computes).
    """

    #: safety valve on the memo tables (not a tuning knob): beyond this
    #: many entries the caches clear wholesale and rebuild from misses
    MAX_CACHE = 1 << 16

    def __init__(self):
        super().__init__()
        self._compact_fn = D.node_compact_program()
        self._compact_warm: set = set()
        self._vec_cache: dict = {}
        self._rows: dict = {}
        #: node-delete row compactions performed (each replaces what the
        #: base engine counts as a rebase)
        self.compactions = 0

    # -- memo seams ------------------------------------------------------
    def _row_cache(self):
        if len(self._rows) > self.MAX_CACHE:
            self._rows.clear()
        return self._rows

    def _pod_vectors(self, pod, final=False):
        ent = self._vec_cache.get(pod.uid)
        if ent is not None and ent[0] is pod:
            if final:
                del self._vec_cache[pod.uid]
            return ent[1]
        vecs = D.pod_usage_vectors(pod) + (D.pod_quota_vector(pod),)
        if final:
            self._vec_cache.pop(pod.uid, None)
        else:
            if len(self._vec_cache) > self.MAX_CACHE:
                self._vec_cache.clear()
            self._vec_cache[pod.uid] = (pod, vecs)
        return vecs

    def _stage_args(self, args):
        # pjit stages numpy args itself in one C++ pass; the explicit
        # per-array device conversion is pure Python overhead here
        return args

    def _stage_pods(self, pod_state):
        # the solve jit stages the pod tensors with the call; keeping
        # them numpy also spares the recorder a device round-trip
        return pod_state

    def _rebase_inner(self, cluster, pending, now_ms: int):
        out = super()._rebase_inner(cluster, pending, now_ms)
        # prime the usage-vector memo for the whole assigned population:
        # a rebase is already O(cluster), and paying the per-pod encodes
        # here keeps the FIRST O(assigned) verify from owning them on a
        # timed cycle (every later verify then runs at memo speed). Prime
        # on the REAL pod objects (never `_assigned_pods`'s per-reserved
        # copies — a copy-keyed entry can never hit the identity check)
        try:
            for pod in cluster.pods.values():
                if pod.node_name is not None or pod.uid in cluster.reserved:
                    self._pod_vectors(pod)
        except D.UnsupportedResource:
            pass  # extended resources: verify falls back to base anyway
        if self._nodes is not None and self._npad not in self._compact_warm:
            # compile the compaction program for this resident shape NOW,
            # on a throwaway zero-state (NEVER the live carry — the
            # program donates its input, and the rebase just handed the
            # live tensors to the current cycle's snapshot), so the first
            # real node delete never pays a mid-run retrace
            self._compact_warm.add(self._npad)
            import warnings

            import jax
            import jax.numpy as jnp

            dummy = jax.tree.map(
                lambda a: jnp.zeros_like(a), self._nodes
            )
            idx = np.arange(self._npad, dtype=np.int32)
            valid = np.zeros(self._npad, bool)
            valid[:len(self._names)] = True
            with warnings.catch_warnings():
                warnings.filterwarnings(
                    "ignore", message=".*donated buffers were not usable.*"
                )
                self._compact_fn(dummy, idx, valid)
        return out

    # -- segmented ingest -----------------------------------------------
    def _ingest(self, events):
        """Split the drained stream at compactable node-delete
        boundaries: classify+apply each preceding segment (slot numbering
        is exact within a segment — deletes renumber slots), compact the
        deleted row, continue. Returns the final delete-free tail for the
        base refresh flow. Falls back to the base whole-batch classify
        (rebase on delete) whenever there is no resident base to
        compact."""
        if self._nodes is None or not any(
            ev[0] == D.NODE_DELETE for ev in events
        ):
            return self._classify(events)
        segment: list = []
        rebase = None
        side_gang: list = []
        side_ns: list = []
        for ev in events:
            if ev[0] == D.NODE_DELETE and rebase is None:
                name = ev[1]
                # classify+apply the preceding segment FIRST: a node
                # added (or otherwise touched) in THIS drain window gets
                # its slot from the segment's upserts — looking the slot
                # up before applying would discard the delete and leave
                # a ghost resident row for a node the store no longer
                # has (an add+remove flap within one window)
                ups, use, side, rebase = self._classify(segment)
                segment = []
                if rebase is not None:
                    continue  # the resident base is dying anyway
                side_gang.extend(side[0])
                side_ns.extend(side[1])
                if ups or use:
                    self._grow(bucket_size(max(len(self._names), 1)))
                    self._apply_batch(ups, use)
                slot = self._slots.get(name)
                if slot is None:
                    # node the engine truly never saw: nothing resident
                    # to remove — keep the base bookkeeping only
                    self._tainted.discard(name)
                    self._node_labels.pop(name, None)
                    continue
                self._compact_row(name, slot)
                continue
            segment.append(ev)
        ups, use, side, seg_rebase = self._classify(segment)
        side = (side_gang + side[0], side_ns + side[1])
        return ups, use, side, rebase if rebase is not None else seg_rebase

    # -- O(assigned) anti-entropy ---------------------------------------
    def verify(self, cluster) -> Optional[str]:
        """Anti-entropy digest without the O(cluster) snapshot rebuild:
        the expected node columns are accumulated directly from the store
        objects through the SAME shared per-pod encode
        (`pod_usage_vectors`, memoized per pod object) and per-node
        encode the fresh snapshot would use, then digest-compared to the
        resident columns — byte-identical expectations by construction
        (tests/test_pipeline_cycle.py::TestStreamingVerify holds this
        against the base engine's fresh-snapshot verify on clean AND
        corrupted state). Independence is preserved: the resident
        columns were built through the sink+device path, the expectation
        comes straight from the store objects. Anything outside the
        canonical axis (an extended resource) falls back to the base
        engine's full verify, which classifies it exactly."""
        from scheduler_plugins_tpu.utils import flightrec

        if self._nodes is None:
            self._verify_pending = False
            obs.metrics.inc(obs.ANTIENTROPY_CHECKS)
            return None
        names = list(cluster.nodes)
        expected = side_exp = None
        if names == self._names:
            try:
                expected, side_exp = self._expected_columns(
                    cluster, names, want_side=self._side_verify_live(cluster)
                )
            except D.UnsupportedResource:
                # extended resource somewhere: the packed axis is wider
                # than the canonical four — delegate to the base
                # engine's fresh-snapshot verify BEFORE opening this
                # path's span/counter (one check = one count, one span)
                return super().verify(cluster)
        with obs.tracer.span(
            "ServeRefresh/verify", tid="serve", staleness=self._staleness,
            fast=True,
        ):
            self._verify_pending = False
            obs.metrics.inc(obs.ANTIENTROPY_CHECKS)
            reason = None
            if expected is None:
                reason = "row-order"
            else:
                mine = flightrec._pack_digest(
                    {k: np.asarray(v)
                     for k, v in self._node_columns().items()}
                )
                theirs = flightrec._pack_digest(expected)
                if mine != theirs:
                    reason = "column-digest"
            if reason is None and side_exp is not None:
                reason = self._side_divergence(*side_exp)
            if reason is not None:
                self.antientropy_divergences += 1
                obs.metrics.inc(obs.ANTIENTROPY_DIVERGENCE)
                obs.logger.warning(
                    "serve anti-entropy divergence (%s) after %d delta "
                    "events%s: re-basing", reason, self._staleness,
                    f" (last fault: {self.last_fault})"
                    if self.last_fault else "",
                )
            return reason

    def _expected_columns(self, cluster, names, want_side=False):
        """The node columns a fresh `build_snapshot` at this padding
        would produce, accumulated O(nodes + assigned) — the exact
        per-pod arithmetic rides the shared `pod_usage_vectors`
        (requested/nonzero carry the pods-count slot per pod, so their
        sums equal the snapshot's pod_count overwrite). With
        `want_side`, the SAME pass also accumulates the expected
        gang/quota side aggregates (`_scan_side_aggregates` semantics —
        one store walk covers both verifications); returns
        (columns, (gangs, namespaces) | None)."""
        R = len(D.CANON_INDEX)
        side_gangs: dict = {}
        side_ns: dict = {}

        def side_gang_acc(name):
            acc = side_gangs.get(name)
            if acc is None:
                acc = side_gangs[name] = [0, 0, np.zeros(R, np.int64)]
            return acc

        def side_assigned(pod, held, req, qreq):
            if self._quota_tracking:
                acc = side_ns.get(pod.namespace)
                if acc is None:
                    acc = side_ns[pod.namespace] = [
                        np.zeros(R, np.int64), 0,
                    ]
                acc[0] = acc[0] + qreq
                acc[1] += 1
            gang = pod.pod_group()
            if gang:
                acc = side_gang_acc(f"{pod.namespace}/{gang}")
                acc[0] += 1
                if held in cluster.nodes:
                    acc[2] = acc[2] + req
        npad = self._npad
        alloc = np.zeros((npad, R), np.int64)
        capacity = np.zeros((npad, R), np.int64)
        requested = np.zeros((npad, R), np.int64)
        nonzero = np.zeros((npad, R), np.int64)
        limits = np.zeros((npad, R), np.int64)
        mask = np.zeros(npad, bool)
        region = np.full(npad, -1, np.int32)
        zone = np.full(npad, -1, np.int32)
        pod_count = np.zeros(npad, np.int32)
        terminating = np.zeros(npad, np.int32)
        # fresh first-seen label interning in store order (NOT the
        # engine's surviving tables): this keeps the label-drift check
        # the fresh-snapshot verify performs — deleting the first-seen
        # carrier of a code diverges here and rebases
        regions: dict = {}
        zones: dict = {}
        node_pos = {}
        for i, node in enumerate(cluster.nodes.values()):
            node_pos[node.name] = i
            alloc[i] = D._encode(node.allocatable)
            capacity[i] = D._encode(node.capacity)
            mask[i] = not node.unschedulable
            if node.region:
                region[i] = regions.setdefault(node.region, len(regions))
            if node.zone:
                zone[i] = zones.setdefault(node.zone, len(zones))
        # the assigned view, on the REAL pod objects: bound pods at their
        # node plus reserved (permit-waiting) pods at their held node —
        # the same definition `Cluster._assigned_pods` materializes, but
        # without its per-reserved-pod copies (a copy would miss the
        # usage-vector memo's identity check and evict the real pod's
        # entry on every verify)
        for pod in cluster.pods.values():
            if pod.node_name is None:
                if want_side:
                    # the `gated_pods()` predicate, INDEPENDENT of a
                    # permit reservation: a reserved gated pod counts
                    # both gated (here) and assigned (the reserved
                    # loop), exactly like the fresh snapshot and the
                    # delta stream (`_scan_side_aggregates`)
                    gang = pod.pod_group()
                    if (
                        gang and pod.scheduling_gated
                        and not pod.terminating
                    ):
                        side_gang_acc(f"{pod.namespace}/{gang}")[1] += 1
                continue
            i = node_pos.get(pod.node_name)
            if i is None:
                if want_side:
                    # bound to a node the store no longer has: still
                    # counts into quota used + gang assigned (never
                    # slack) — build_snapshot's rule
                    req, _nz, _lim, qreq = self._pod_vectors(pod)
                    side_assigned(pod, pod.node_name, req, qreq)
                continue
            req, nz, lim, qreq = self._pod_vectors(pod)
            requested[i] += req
            nonzero[i] += nz
            limits[i] += lim
            pod_count[i] += 1
            if pod.terminating:
                terminating[i] += 1
            if want_side:
                side_assigned(pod, pod.node_name, req, qreq)
        for uid, node in cluster.reserved.items():
            pod = cluster.pods.get(uid)
            if pod is None or pod.node_name is not None:
                continue
            req, nz, lim, qreq = self._pod_vectors(pod)
            if want_side:
                side_assigned(pod, node, req, qreq)
            i = node_pos.get(node)
            if i is None:
                continue
            requested[i] += req
            nonzero[i] += nz
            limits[i] += lim
            pod_count[i] += 1
            if pod.terminating:
                terminating[i] += 1
        # same key order as _node_columns so the digests align
        return {
            "alloc": alloc, "capacity": capacity, "requested": requested,
            "nonzero_requested": nonzero, "limits": limits,
            "mask": mask, "region": region, "zone": zone,
            "pod_count": pod_count, "terminating": terminating,
        }, ((side_gangs, side_ns) if want_side else None)

    def _compact_row(self, name: str, slot: int) -> None:
        import warnings

        import jax.numpy as jnp

        with obs.tracer.span(
            "ServeRefresh/compact", tid="serve", slot=slot
        ):
            self._tainted.discard(name)
            self._node_labels.pop(name, None)
            idx, valid = _shift_gather_args(
                self._npad, slot, len(self._names) - 1
            )
            with warnings.catch_warnings():
                # CPU backends never donate and list every buffer (the
                # delta-apply program's known shape, PR 2/6)
                warnings.filterwarnings(
                    "ignore", message=".*donated buffers were not usable.*"
                )
                self._nodes = self._compact_fn(
                    self._nodes, jnp.asarray(idx), jnp.asarray(valid)
                )
            self._names.pop(slot)
            self._slots = {n: i for i, n in enumerate(self._names)}
            if self._gang_rows:
                # fresh snapshots drop gang slack of pods bound to a
                # deleted node — rebuild rather than drift (the base
                # engine's rebase path rebuilds side tables implicitly)
                self._side_dirty = True
            self.compactions += 1
            self._generation += 1
            self._staleness += 1
            self._last = {"mode": "compact", "events": 1}
            self._observe()


def compact_lower_args(n_nodes: int = 256, delete_slot: int = 3):
    """(jitted fn, sample args) for the AOT compile-readiness gate — the
    exact donated row-compaction program `StreamingServeEngine` runs on a
    node delete (`tools/tpu_lower.py` serving_node_compact), at the same
    reduced resident shape as `lower_program_args`. One constructor so
    the certified program and the shipped program cannot drift."""
    from scheduler_plugins_tpu.models import allocatable_scenario

    cluster = allocatable_scenario(n_nodes=n_nodes, n_pods=1)
    npad = bucket_size(n_nodes)
    snap, _meta = cluster.snapshot([], now_ms=0, pad_nodes=npad)
    idx, valid = _shift_gather_args(npad, delete_slot, n_nodes - 1)
    return D.node_compact_program(), (snap.nodes, idx, valid)


def side_lower_args(n_gangs: int = 8, n_ns: int = 4, n_rows: int = 16):
    """(jitted fn, sample args) for the AOT compile-readiness gate — the
    exact donated side-table apply program `ServeEngine` folds gang/quota
    aggregate deltas with (`tools/tpu_lower.py` serving_side_apply), at a
    reduced resident shape. One constructor so the certified program and
    the shipped program cannot drift."""
    import jax.numpy as jnp

    R = len(D.CANON_INDEX)
    G = bucket_size(n_gangs)
    Q = bucket_size(n_ns)
    tables = D.zero_side_tables(G, Q, R)
    gang_rows = [
        (j % n_gangs, 1, 0, np.ones(R, np.int64)) for j in range(n_rows)
    ]
    ns_rows = [
        (j % n_ns, np.ones(R, np.int64), 1) for j in range(n_rows)
    ]
    packed = D.SideDeltas.pack(gang_rows, ns_rows, R)
    args = (tables, *(jnp.asarray(a) for a in packed.as_args()))
    return D.side_apply_program(), args


def lower_program_args(n_nodes: int = 256, n_upserts: int = 8,
                       n_deltas: int = 64):
    """(jitted fn, sample args) for the AOT compile-readiness gate — the
    exact donated apply program `ServeEngine` runs, at a reduced resident
    shape (`tools/tpu_lower.py` serving_delta_apply). One constructor so
    the certified program and the shipped program cannot drift."""
    import jax
    import jax.numpy as jnp

    from scheduler_plugins_tpu.models import allocatable_scenario

    cluster = allocatable_scenario(n_nodes=n_nodes, n_pods=1)
    npad = bucket_size(n_nodes)
    snap, _meta = cluster.snapshot([], now_ms=0, pad_nodes=npad)
    R = len(D.CANON_INDEX)
    ups = D.NodeUpserts.pack(
        [(j, np.zeros(R, np.int64), np.zeros(R, np.int64), True, -1, -1)
         for j in range(n_upserts)],
        R,
    )
    use = D.UsageDeltas.pack(
        [(j % n_nodes, np.zeros(R, np.int64), np.zeros(R, np.int64),
          np.zeros(R, np.int64), 0, 0) for j in range(n_deltas)],
        R,
    )
    args = (
        snap.nodes,
        *(jnp.asarray(a) for a in ups.as_args()),
        *(jnp.asarray(a) for a in use.as_args()),
    )
    return D.delta_apply_program(), args
