"""SPT_SANITIZE=1 checkify sanitizer mode.

`jax.experimental.checkify` instruments the traced solve programs with
runtime checks — index out-of-bounds on the commit scatters, NaN
production, division by zero — that XLA otherwise silently clamps, drops
or propagates. The wrap points are the three program families the
compile-readiness gates certify: `parallel.solver.profile_batch_fn`,
`parallel.pipeline.donated_chunk_solver` and `__graft_entry__.entry()`.

Semantics under sanitize mode:

- **donation is dropped** — this is a debug mode; keeping every carry
  readable after the call beats the peak-memory win, and checkify threads
  an error value through the program that must not alias a donated buffer.
- errors surface as STRUCTURED JSON (one line per checked invocation on
  stderr when an error fired) and accumulate in an in-process report list;
  `drain()` hands them to drivers — `bench.py --sanitize-smoke` fails CI
  on any, `framework.cycle.run_cycle` attaches them to its CycleReport.
- the mode is decided when a solver is BUILT (solver caches key on it), so
  flipping the env var mid-process yields fresh, correctly-instrumented
  jits instead of stale cache hits.
"""

from __future__ import annotations

import json
import os
import sys

_REPORTS: list[dict] = []


def enabled() -> bool:
    return os.environ.get("SPT_SANITIZE", "") == "1"


def checks():
    """The check set: index OOB (commit scatters), NaN, div-by-zero."""
    from jax.experimental import checkify

    return checkify.index_checks | checkify.float_checks | checkify.div_checks


def checkified_fn(fn):
    """The jittable `(error, out)` form of `fn` — for callers that manage
    the error value themselves (e.g. `__graft_entry__.entry()`, whose
    contract is to stay jittable)."""
    from jax.experimental import checkify

    return checkify.checkify(fn, errors=checks())


def checkified(fn, program: str):
    """Host-callable sanitized build of `fn`: jits the checkified form,
    extracts the error after every call, records a structured report, and
    returns `fn`'s own outputs — a drop-in for the production jit (minus
    donation, see module docstring)."""
    import functools

    import jax

    checked = jax.jit(checkified_fn(fn))

    @functools.wraps(fn)
    def wrapped(*args, **kwargs):
        err, out = checked(*args, **kwargs)
        report(program, err)
        return out

    wrapped.__name__ = f"sanitized_{program}"
    return wrapped


def report(program: str, err) -> None:
    """Record one checked invocation. `err` is a checkify Error pytree;
    `err.get()` is None when every check passed."""
    msg = err.get()
    entry = {"sanitize": program, "ok": msg is None}
    if msg is not None:
        entry["error"] = " ".join(msg.split())[:400]
        print(json.dumps(entry), file=sys.stderr, flush=True)
    _REPORTS.append(entry)


def drain() -> list[dict]:
    """All reports since the last drain (clears the buffer)."""
    out = list(_REPORTS)
    _REPORTS.clear()
    return out
