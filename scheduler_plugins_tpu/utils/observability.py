"""Observability: flow logging, metrics with histograms, and a cycle tracer.

Mirrors the reference's observability surface (SURVEY.md §5):
- contextual leveled logging with FlowBegin/FlowEnd markers, subsystem names
  and a cache GENERATION attached to every line so a scheduling decision can
  be cross-correlated with the resync that produced its data
  (/root/reference/pkg/noderesourcetopology/logging/logging.go:30-56);
- prometheus-style counters AND fixed-bucket histograms the reference
  registers (plugin execution latency per extension point, unschedulable
  attribution; cmd/scheduler/main.go:23-24, capacity_scheduling.go:333 and
  the upstream framework's `plugin_execution_duration_seconds` /
  `UnschedulablePlugins` shape), rendered in prometheus text format by
  `Metrics.prometheus_text` (the daemon's `/metrics`);
- a `Tracer` recording host-side spans as Chrome-trace-event / Perfetto
  JSON ("traceEvents" with X complete events + M thread-name metadata), so
  one scheduling cycle or one chunk-pipeline run loads as a timeline in
  ui.perfetto.dev. Device-side numbers always come from host-transfer
  timestamps — never wall clocks inside jit-traced code (CLAUDE.md; lint
  rule GL008 enforces this).

Everything here is host-side and must stay cheap: the tracer is OFF by
default and its disabled spans short-circuit before taking any timestamp.
"""

from __future__ import annotations

import bisect
import json
import logging
import os
import threading
import time
from contextlib import contextmanager

logger = logging.getLogger("scheduler_plugins_tpu")

FLOW_BEGIN = "FlowBegin"
FLOW_END = "FlowEnd"

#: fixed histogram buckets in milliseconds (upper bounds; +Inf implicit) —
#: the upstream scheduler-latency bucket ladder, in ms instead of seconds
HIST_BUCKETS_MS = (
    1.0, 2.0, 5.0, 10.0, 25.0, 50.0, 100.0, 250.0, 500.0,
    1000.0, 2500.0, 5000.0, 10000.0,
)


def _label_items(labels: dict) -> tuple:
    return tuple(sorted(labels.items()))


def _escape_label(value) -> str:
    return (
        str(value)
        .replace("\\", "\\\\")
        .replace('"', '\\"')
        .replace("\n", "\\n")
    )


def _render_labels(items) -> str:
    if not items:
        return ""
    inner = ",".join(f'{k}="{_escape_label(v)}"' for k, v in items)
    return "{" + inner + "}"


def atomic_write(path: str, data) -> None:
    """Write `data` (str or bytes) to `path` via a same-directory temp file
    + `os.replace`, fsync'd first — the crash-safe write discipline shared
    by `Tracer.write` and the flight-recorder bundle writers
    (utils.flightrec): a process killed mid-write leaves at worst a stray
    `.tmp.*` file, never a truncated artifact under the real name."""
    mode = "wb" if isinstance(data, (bytes, bytearray)) else "w"
    tmp = f"{path}.tmp.{os.getpid()}"
    try:
        with open(tmp, mode) as f:
            f.write(data)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise


class _Histogram:
    __slots__ = ("counts", "sum", "count", "max")

    def __init__(self):
        self.counts = [0] * (len(HIST_BUCKETS_MS) + 1)  # last slot = +Inf
        self.sum = 0.0
        self.count = 0
        self.max = 0.0

    def observe(self, ms: float) -> None:
        self.counts[bisect.bisect_left(HIST_BUCKETS_MS, ms)] += 1
        self.sum += ms
        self.count += 1
        if ms > self.max:
            self.max = ms


class Metrics:
    """Process-wide scheduling counters + histograms (the scheduler_perf
    surface). Counters and histograms accept prometheus-style labels as
    keyword args: `metrics.inc(UNSCHEDULABLE_BY_PLUGIN, plugin="Coscheduling")`.

    `observe_ms` keeps the legacy `<name>_ms_total` / `<name>_count` /
    `<name>_ms_max` counter keys for UNLABELED names (existing tests and
    panels read them) while also feeding a fixed-bucket histogram
    (`HIST_BUCKETS_MS`) that `prometheus_text` renders as
    `_bucket{le=...}` / `_sum` / `_count` series."""

    def __init__(self):
        # (name, sorted label items) -> value; single source of truth
        self._counters: dict[tuple[str, tuple], int] = {}
        self._hists: dict[tuple[str, tuple], _Histogram] = {}
        self._lock = threading.Lock()

    def inc(self, name: str, value: int = 1, **labels) -> None:
        key = (name, _label_items(labels))
        with self._lock:
            self._counters[key] = self._counters.get(key, 0) + value

    def set_gauge(self, name: str, value, **labels) -> None:
        """Gauge semantics: last write wins (e.g. resident-state
        generation/staleness). Rendered as `# TYPE ... gauge` by
        `prometheus_text` — gauge names must not end in `_total`/`_count`
        (those suffixes type as counters)."""
        key = (name, _label_items(labels))
        with self._lock:
            self._counters[key] = value

    def _set_max(self, name: str, value: int, items: tuple = ()) -> None:
        key = (name, items)
        if value > self._counters.get(key, 0):
            self._counters[key] = value

    def observe_ms(self, name: str, ms: float, **labels) -> None:
        """Duration observation: fixed-bucket histogram plus (for unlabeled
        names) the legacy `_ms_total`/`_count`/`_ms_max` summary counters."""
        items = _label_items(labels)
        key = (name, items)
        with self._lock:
            hist = self._hists.get(key)
            if hist is None:
                hist = self._hists[key] = _Histogram()
            hist.observe(ms)
            if not items:
                ms_int = int(ms)
                self._counters[(f"{name}_ms_total", ())] = (
                    self._counters.get((f"{name}_ms_total", ()), 0) + ms_int
                )
                self._counters[(f"{name}_count", ())] = (
                    self._counters.get((f"{name}_count", ()), 0) + 1
                )
                self._set_max(f"{name}_ms_max", ms_int)

    def observe_batch(self, observations) -> None:
        """Histogram-only batch feed under ONE lock acquisition:
        `observations` is an iterable of (name, value, items) with
        `items` pre-sorted label tuples (as `_label_items` returns).
        No legacy `_ms_total` mirrors — this path exists for hot
        per-pod feeds (the lifecycle ledger's bind-time SLI fan-out)
        where per-call lock round-trips and kwargs packing dominate,
        and for values that are not durations at all (attempt counts)."""
        with self._lock:
            for name, value, items in observations:
                key = (name, items)
                hist = self._hists.get(key)
                if hist is None:
                    hist = self._hists[key] = _Histogram()
                hist.observe(value)

    def get(self, name: str, **labels) -> int:
        return self._counters.get((name, _label_items(labels)), 0)

    def snapshot(self) -> dict[str, int]:
        """Flat debug map: rendered `name{k="v"}` keys -> counter values."""
        with self._lock:
            return {
                f"{name}{_render_labels(items)}": value
                for (name, items), value in self._counters.items()
            }

    def histograms(self) -> dict[str, dict]:
        """Rendered-key -> {buckets, counts, sum, count, max} debug view."""
        with self._lock:
            return {
                f"{name}{_render_labels(items)}": {
                    "buckets": list(HIST_BUCKETS_MS),
                    "counts": list(h.counts),
                    "sum": h.sum,
                    "count": h.count,
                    "max": h.max,
                }
                for (name, items), h in self._hists.items()
            }

    def reset(self) -> None:
        with self._lock:
            self._counters.clear()
            self._hists.clear()

    def scoped(self) -> "ScopedMetrics":
        """A snapshot/diff view: reads return counts accumulated SINCE
        this call. Arm-vs-arm benches read per-arm deltas through one of
        these instead of the process-global totals (the PR 12 `rebases`
        fix, generalized — see bench.py's sweep baselines)."""
        return ScopedMetrics(self)

    def prometheus_text(self) -> str:
        """Prometheus text exposition format 0.0.4: `# HELP` + `# TYPE`
        per family, counters as counters, histograms as cumulative
        `_bucket{le=...}` + `_sum` + `_count`.
        The legacy `<name>_count` summary counter `observe_ms` keeps for
        unlabeled names is the SAME sample the histogram's `_count` child
        renders — it is skipped here (the JSON snapshot still carries it)
        so a scrape never contains duplicate samples."""
        with self._lock:
            counters = sorted(self._counters.items())
            hists = sorted(self._hists.items(), key=lambda kv: kv[0])
        hist_count_names = {f"{name}_count" for (name, _), _h in hists}
        lines: list[str] = []
        typed: set[str] = set()

        def _head(name: str, kind: str) -> None:
            text = HELP.get(name, f"{name} (scheduler-plugins-tpu)")
            text = text.replace("\\", "\\\\").replace("\n", "\\n")
            lines.append(f"# HELP {name} {text}")
            lines.append(f"# TYPE {name} {kind}")

        for (name, items), value in counters:
            if name in hist_count_names:
                continue  # rendered as the histogram's _count child below
            if name not in typed:
                typed.add(name)
                kind = "counter" if name.endswith(("_total", "_count")) else "gauge"
                _head(name, kind)
            lines.append(f"{name}{_render_labels(items)} {value}")
        for (name, items), hist in hists:
            if name not in typed:
                typed.add(name)
                _head(name, "histogram")
            cumulative = 0
            for bound, count in zip(HIST_BUCKETS_MS, hist.counts):
                cumulative += count
                le = _render_labels(items + (("le", f"{bound:g}"),))
                lines.append(f"{name}_bucket{le} {cumulative}")
            le = _render_labels(items + (("le", "+Inf"),))
            lines.append(f"{name}_bucket{le} {hist.count}")
            lines.append(f"{name}_sum{_render_labels(items)} {hist.sum:g}")
            lines.append(f"{name}_count{_render_labels(items)} {hist.count}")
        return "\n".join(lines) + "\n"


class ScopedMetrics:
    """Delta view over a `Metrics` registry: every read subtracts the
    counter/histogram state captured at construction, so two interleaved
    bench arms sharing the process-global registry each see only their
    own increments. Reads are as cheap as the underlying `get` — the
    base is a plain dict snapshot, never re-captured."""

    def __init__(self, metrics: Metrics):
        self._m = metrics
        with metrics._lock:
            self._base = dict(metrics._counters)
            self._hbase = {
                key: (h.count, h.sum)
                for key, h in metrics._hists.items()
            }

    def get(self, name: str, **labels) -> int:
        key = (name, _label_items(labels))
        return self._m._counters.get(key, 0) - self._base.get(key, 0)

    def hist_count(self, name: str, **labels) -> int:
        key = (name, _label_items(labels))
        h = self._m._hists.get(key)
        base = self._hbase.get(key, (0, 0.0))[0]
        return (h.count if h is not None else 0) - base

    def hist_sum(self, name: str, **labels) -> float:
        key = (name, _label_items(labels))
        h = self._m._hists.get(key)
        base = self._hbase.get(key, (0, 0.0))[1]
        return (h.sum if h is not None else 0.0) - base

    def delta(self) -> dict[str, int]:
        """Rendered-key -> delta for every counter that moved since the
        scope opened (the flat `snapshot()` shape, diffed)."""
        with self._m._lock:
            cur = dict(self._m._counters)
        out = {}
        for (name, items), value in cur.items():
            d = value - self._base.get((name, items), 0)
            if d:
                out[f"{name}{_render_labels(items)}"] = d
        return out


#: global registry, like the upstream prometheus default registry
metrics = Metrics()

# counter names (prometheus-style)
SCHEDULING_CYCLES = "scheduler_scheduling_cycles_total"
PODS_BOUND = "scheduler_pods_bound_total"
PODS_FAILED = "scheduler_pods_unschedulable_total"
PREEMPTION_ATTEMPTS = "scheduler_preemption_attempts_total"
PREEMPTION_VICTIMS = "scheduler_preemption_victims_total"
GANG_REJECTIONS = "scheduler_gang_rejections_total"
CACHE_RESYNC_FLUSHES = "scheduler_nrt_cache_flushes_total"
#: per-plugin attribution (labels: plugin) — the upstream
#: `UnschedulablePlugins` signal: which plugin made each pod unschedulable
UNSCHEDULABLE_BY_PLUGIN = "scheduler_unschedulable_by_plugin_total"
#: per-plugin, per-extension-point latency histogram (labels: plugin,
#: extension_point) — the upstream plugin_execution_duration_seconds shape
PLUGIN_EXECUTION = "scheduler_plugin_execution_ms"
#: compile wall-time histogram (labels: program) — total XLA
#: trace+lower+compile seconds observed during one watched call that
#: actually compiled (jax.monitoring compile-duration events, attributed
#: to the program whose call triggered them)
JIT_COMPILE = "scheduler_jit_compile_ms"
#: jit-cache misses per program (labels: program): watched calls during
#: which a compile event fired — each one paid a fresh trace+compile
JIT_CACHE_MISS = "scheduler_jit_cache_misses_total"
#: cycles captured by the flight recorder (utils.flightrec)
FLIGHTREC_CYCLES = "scheduler_flightrec_cycles_total"
#: serve-mode decision latency histogram: wall ms from delta ingest to
#: host-visible bind decisions for one resident-state cycle
#: (framework.cycle.run_cycle(serve=...))
SERVE_DECISION_LATENCY = "scheduler_serve_decision_latency_ms"
#: gauge: resident-state generation (monotonic per applied delta batch /
#: rebase; serving.engine.ServeEngine)
SERVE_GENERATION = "scheduler_serve_state_generation"
#: gauge: delta events applied since the resident base was last rebuilt —
#: how long the replay chain from the base snapshot has grown
SERVE_STALENESS = "scheduler_serve_state_staleness_events"
#: gauge: delta events drained at the START of the current refresh (queue
#: depth the engine saw — sustained growth means ingest is falling behind)
SERVE_PENDING_DELTAS = "scheduler_serve_pending_deltas"
#: full re-snapshots the serving engine performed (node deletes, label
#: re-interning, extended resources — docs/SERVING.md taxonomy)
SERVE_REBASES = "scheduler_serve_rebases_total"
#: serve refreshes that fell back to the full snapshot while the cluster
#: carried PodGroups. Gang/quota rosters serve RESIDENT since ISSUE 12
#: (gang/quota side tables), so on a compatible gang roster this stays 0
#: — the production signal that the resident-gang win is actually
#: engaged (`make endurance-smoke` gates it)
SERVE_GANG_FALLBACKS = "scheduler_serve_gang_fallbacks_total"
#: gauge (labels: objective): the latest cycle's placement-quality
#: objective values (tuning.quality — fragmentation, util_imbalance,
#: gang_wait_frac, unplaced_frac, preemptions, nominations), stamped by
#: `framework.cycle.run_cycle` on every solved cycle
PLACEMENT_QUALITY = "scheduler_placement_quality"
#: gauge: 1 while the process serves from the host-side parity solve
#: because the device backend failed past the watchdog's retry budget
#: (resilience.watchdog.Resilience); 0 on the fast path. Also surfaced
#: as `degraded` on the daemon's /healthz and every chaos bench line
DEGRADED = "scheduler_degraded"
#: watchdog retry attempts that failed (labels: label=solve|probe) —
#: each is one timeout/device-error/garbage-output before backoff
SOLVE_RETRIES = "scheduler_solve_retries_total"
#: fast-path -> degraded transitions (retry budget exhausted)
SOLVE_FAILOVERS = "scheduler_solve_failovers_total"
#: probation probes dispatched while degraded (successful ones restore
#: the fast path; `scheduler_degraded` returning to 0 is the signal)
PROBATION_PROBES = "scheduler_probation_probes_total"
#: watchdog workers orphaned inside a hung backend call (they cannot be
#: interrupted, only abandoned — a flapping backend shows up here)
SOLVE_WORKERS_ABANDONED = "scheduler_solve_workers_abandoned_total"
#: live threads whose names match no entry of the committed concurrency
#: manifest (docs/race_audit.json, tools/race_audit.py): a thread the
#: static lockset analysis never modeled — audited code but unaudited
#: topology. Counted per /healthz probe sighting.
THREAD_TOPOLOGY_DRIFT = "scheduler_thread_topology_drift_total"
#: anti-entropy digest checks of the resident serve state vs a freshly
#: built snapshot (serving.engine.ServeEngine.verify)
ANTIENTROPY_CHECKS = "scheduler_serve_antientropy_checks_total"
#: anti-entropy divergences detected (each forces a rebase — a corrupted
#: or dropped delta can poison at most one verification window)
ANTIENTROPY_DIVERGENCE = "scheduler_serve_antientropy_divergence_total"
#: unschedulable pods currently parked in a requeue backoff window
#: (upstream backoffQ semantics; framework.cycle._requeue_eligible)
REQUEUE_BACKOFF_SKIPS = "scheduler_requeue_backoff_skips_total"
#: fraction of the in-flight device-solve envelope the pipelined cycle
#: engine covered with useful host work (framework.pipeline_cycle;
#: 1.0 = the fence never waited on the device)
CYCLE_OVERLAP_EFFICIENCY = "scheduler_cycle_overlap_efficiency"
#: wall-clock ms the pipelined engine's fence idled waiting on the
#: in-flight device solve after the overlap work ran dry — the
#: per-cycle pipeline bubble the overlap exists to eliminate
CYCLE_PIPELINE_BUBBLE = "scheduler_cycle_pipeline_bubble_ms"
#: binds flushed by the pipelined engine's async flusher that landed
#: AFTER a later cycle's ingest boundary — each one reached the resident
#: serving state as an ordinary DeltaSink delta (the conflict-fence
#: taxonomy, docs/SERVING.md)
CYCLE_LATE_BINDS = "scheduler_cycle_late_binds_total"
#: live weight promotions applied by the online shadow tuner
#: (tuning.shadow.ShadowTuner — gated through the tuning.promotion
#: oracles, rolled out via the aux channel with zero recompiles)
TUNER_PROMOTIONS = "scheduler_tuner_promotions_total"
#: probation auto-rollbacks (quality-gauge regression or watchdog fault
#: within the probation window — the guarded-rollout guarantee)
TUNER_ROLLBACKS = "scheduler_tuner_rollbacks_total"
#: shadow-lane sweep evaluations completed (each one replays the ring
#: corpus under K candidate weight vectors off the cycle thread)
TUNER_SWEEPS = "scheduler_tuner_sweeps_total"
#: shadow-lane faults: sweep failures (deadline expiry, worker error)
#: AND promotion-apply crashes — every one degraded to "no tuning" with
#: the incumbent weights kept; repeated consecutive faults disable the
#: tuner (one counter on purpose: it feeds the one self-disable budget)
TUNER_SWEEP_FAILURES = "scheduler_tuner_sweep_failures_total"
#: gauge: the active per-plugin weight vector's content digest as an
#: integer (the first 48 bits of `tuning.promotion.weights_digest`,
#: exact in float64) — two processes serving the same promoted profile
#: show the same value; the hex string rides /healthz
TUNER_ACTIVE_WEIGHTS = "scheduler_tuner_active_weights_digest"
#: gauge: tuner controller state (0 idle, 1 probation, 2 cooldown,
#: 3 disabled)
TUNER_STATE = "scheduler_tuner_state"
#: conflict-fence rejections per lane (parallel.lanes.LaneSolver): pod p
#: of lane j failed the speculative-vs-committed step-signature check —
#: the whole remaining suffix re-resolves against committed state
LANE_CONFLICTS = "scheduler_lane_conflicts_total"
#: wall-clock ms of the host conflict fence per laned cycle (serial-order
#: validation walk + wait recomputation + any suffix repair dispatch)
LANE_COMMIT = "scheduler_lane_commit_ms"
#: pods re-resolved against committed state by the suffix repair solve
LANE_RERESOLVES = "scheduler_lane_reresolves_total"
#: laned cycles that fell back to the sequential parity solve because the
#: fence-exact gate rejected the profile/snapshot (side tables armed,
#: preemption nominees present, or an admit plugin without a host twin)
LANE_SERIAL_FALLBACKS = "scheduler_lane_serial_fallbacks_total"
#: per-pod e2e scheduling latency histogram (labels: priority) — the
#: upstream `scheduler_e2e_scheduling_duration_seconds` family in ms
#: (vendored registration: cmd/scheduler/main.go:23-24), fed by the
#: pod-lifecycle ledger (obs.ledger) when a pod retires bound
E2E_SCHEDULING_MS = "scheduler_e2e_scheduling_duration_ms"
#: scheduling attempts per successfully-scheduled pod (histogram) — the
#: upstream `scheduler_pod_scheduling_attempts` family
POD_SCHEDULING_ATTEMPTS = "scheduler_pod_scheduling_attempts"
#: per-stage share of the e2e latency (labels: stage ∈ obs.ledger.STAGES)
#: — the upstream `scheduler_pod_scheduling_sli_duration_seconds` shape,
#: decomposed into queue-wait / backoff-held / gang-wait / solve / fence /
#: bind-flush buckets that provably sum to e2e per pod
POD_SCHEDULING_SLI_MS = "scheduler_pod_scheduling_sli_duration_ms"
#: gauge: device-memory bytes currently allocated across local devices
#: (backend allocator stats, summed; absent on backends without stats —
#: the CPU fallback — so the gauge simply never appears there). Stamped
#: once per cycle by the daemon via obs.costmodel.stamp_device_memory.
DEVICE_BYTES_IN_USE = "scheduler_device_bytes_in_use"
#: gauge: device-memory high-water mark across local devices (allocator
#: peak_bytes_in_use, summed) — the runtime companion of the STATIC peak
#: in docs/cost_model.json: the committed manifest predicts, this gauge
#: measures
DEVICE_PEAK_BYTES = "scheduler_device_peak_bytes_in_use"

#: `# HELP` registry for `prometheus_text` (exposition format 0.0.4
#: requires families to be self-describing; families not listed here get
#: an auto-generated line). One copy, next to the name constants.
HELP: dict[str, str] = {
    SCHEDULING_CYCLES: "Scheduling cycles run.",
    PODS_BOUND: "Pods bound to a node.",
    PODS_FAILED: "Pods reported unschedulable.",
    PREEMPTION_ATTEMPTS: "Preemption attempts (upstream PreemptionAttempts).",
    PREEMPTION_VICTIMS: "Pods nominated for eviction by preemption.",
    GANG_REJECTIONS: "Whole-gang admission rejections.",
    CACHE_RESYNC_FLUSHES: "NRT cache resync flushes.",
    UNSCHEDULABLE_BY_PLUGIN:
        "Unschedulable verdicts attributed per plugin "
        "(upstream UnschedulablePlugins).",
    PLUGIN_EXECUTION:
        "Per-plugin, per-extension-point execution latency in ms.",
    JIT_COMPILE: "XLA compile wall time per program in ms.",
    JIT_CACHE_MISS: "Jit-cache misses per program.",
    FLIGHTREC_CYCLES: "Cycles captured by the flight recorder.",
    SERVE_DECISION_LATENCY:
        "Delta ingest to host-visible bind decisions, per cycle, in ms.",
    SERVE_GENERATION: "Resident-state generation (gauge).",
    SERVE_STALENESS:
        "Delta events applied since the resident base was rebuilt (gauge).",
    SERVE_PENDING_DELTAS:
        "Delta events drained at the start of the current refresh (gauge).",
    SERVE_REBASES: "Full re-snapshots performed by the serving engine.",
    SERVE_GANG_FALLBACKS:
        "Serve refreshes that fell back to a full snapshot on a gang "
        "roster.",
    PLACEMENT_QUALITY:
        "Latest cycle's placement-quality objective values (gauge).",
    DEGRADED: "1 while serving from the host-side parity solve (gauge).",
    SOLVE_RETRIES: "Failed watchdog retry attempts.",
    SOLVE_FAILOVERS: "Fast-path to degraded transitions.",
    PROBATION_PROBES: "Probation probes dispatched while degraded.",
    SOLVE_WORKERS_ABANDONED:
        "Watchdog workers orphaned inside a hung backend call.",
    THREAD_TOPOLOGY_DRIFT:
        "Live threads unknown to the committed concurrency manifest.",
    ANTIENTROPY_CHECKS: "Anti-entropy digest checks of resident state.",
    ANTIENTROPY_DIVERGENCE: "Anti-entropy divergences detected.",
    REQUEUE_BACKOFF_SKIPS:
        "Requeue attempts skipped inside a backoff window.",
    CYCLE_OVERLAP_EFFICIENCY:
        "Fraction of the in-flight solve envelope covered by host work "
        "(gauge).",
    CYCLE_PIPELINE_BUBBLE:
        "Wall ms the pipelined fence idled waiting on the device (gauge).",
    CYCLE_LATE_BINDS:
        "Async bind flushes that landed after a later ingest boundary.",
    TUNER_PROMOTIONS: "Live weight promotions applied by the shadow tuner.",
    TUNER_ROLLBACKS: "Probation auto-rollbacks.",
    TUNER_SWEEPS: "Shadow-lane sweep evaluations completed.",
    TUNER_SWEEP_FAILURES: "Shadow-lane sweep/promotion faults.",
    TUNER_ACTIVE_WEIGHTS:
        "Active weight vector content digest, first 48 bits (gauge).",
    TUNER_STATE:
        "Tuner controller state: 0 idle, 1 probation, 2 cooldown, "
        "3 disabled (gauge).",
    LANE_CONFLICTS: "Conflict-fence rejections per lane.",
    LANE_COMMIT: "Host conflict-fence wall ms per laned cycle.",
    LANE_RERESOLVES: "Pods re-resolved by the suffix repair solve.",
    LANE_SERIAL_FALLBACKS:
        "Laned cycles that fell back to the sequential parity solve.",
    E2E_SCHEDULING_MS:
        "Per-pod e2e scheduling latency in ms, labeled by priority "
        "(upstream scheduler_e2e_scheduling_duration_seconds, in ms).",
    POD_SCHEDULING_ATTEMPTS:
        "Scheduling attempts per scheduled pod "
        "(upstream scheduler_pod_scheduling_attempts).",
    POD_SCHEDULING_SLI_MS:
        "Per-stage share of pod scheduling latency in ms, labeled by "
        "stage (upstream scheduler_pod_scheduling_sli_duration_seconds, "
        "in ms, decomposed).",
    DEVICE_BYTES_IN_USE:
        "Device-memory bytes in use across local devices (gauge).",
    DEVICE_PEAK_BYTES:
        "Device-memory high-water mark across local devices (gauge).",
}


# ---------------------------------------------------------------------------
# Compile observability: per-program jit-cache misses + compile wall time
# ---------------------------------------------------------------------------


class CompileWatch:
    """Attributes XLA compile wall time to named programs.

    `watch(fn, program=...)` wraps a jitted callable: while a wrapped call
    runs, a `jax.monitoring` duration listener credits any
    `/jax/core/compile/...` event (jaxpr trace, MLIR lowering, backend
    compile) to that program. A call during which at least one compile
    event fired counts as ONE jit-cache miss
    (`scheduler_jit_cache_misses_total{program}`) and observes the summed
    compile seconds into `scheduler_jit_compile_ms{program}`; cache hits
    cost two thread-local writes and nothing else. Shape signatures
    (pytree structure + leaf shape/dtype) are collected per program ONLY
    on misses, and crossing `SPT_SHAPE_CHURN_N` (default 8) distinct
    signatures logs a shape-churn warning — the signature a mesh-padding
    bug in `dryrun_multichip` leaves behind is the same program
    recompiling once per ragged shape instead of hitting one padded
    bucket.

    The wrapper is transparent to AOT tooling: `functools.wraps` carries
    the inner jit's `trace`/`lower` attributes through, so
    `jax.export.export` on a watched callable still exports the exact
    cached program (the tools/tpu_lower.py seam).
    """

    def __init__(self):
        self._signatures: dict[str, set] = {}
        self._tls = threading.local()
        self._lock = threading.Lock()
        self._installed = False

    def _install_listener(self) -> None:
        with self._lock:
            if self._installed:
                return
            self._installed = True
        try:
            from jax import monitoring as _monitoring

            _monitoring.register_event_duration_secs_listener(self._on_event)
        except Exception:  # graft-lint: ignore[GL010] — optional-dep probe: jax absent/too old, misses still count without ms
            pass

    def _on_event(self, event, duration, **_kw) -> None:
        if not isinstance(event, str) or not event.startswith(
            "/jax/core/compile/"
        ):
            return
        if getattr(self._tls, "program", None) is not None:
            self._tls.compile_s += float(duration)

    @staticmethod
    def _signature(args, kwargs):
        import jax

        leaves, treedef = jax.tree_util.tree_flatten((args, kwargs))
        return (
            str(treedef),
            tuple(
                (getattr(leaf, "shape", None), str(getattr(leaf, "dtype", "")))
                for leaf in leaves
            ),
        )

    def churn_threshold(self) -> int:
        try:
            return int(os.environ.get("SPT_SHAPE_CHURN_N", 8))
        except ValueError:
            return 8

    def watch(self, fn, program: str):
        """Wrap jitted callable `fn` for compile attribution under `program`."""
        import functools

        self._install_listener()
        tls = self._tls

        @functools.wraps(fn)
        def watched(*args, **kwargs):
            prev = (getattr(tls, "program", None),
                    getattr(tls, "compile_s", 0.0))
            tls.program, tls.compile_s = program, 0.0
            try:
                return fn(*args, **kwargs)
            finally:
                compiled_s = tls.compile_s
                tls.program, tls.compile_s = prev
                if compiled_s > 0.0:
                    metrics.inc(JIT_CACHE_MISS, program=program)
                    metrics.observe_ms(
                        JIT_COMPILE, compiled_s * 1000.0, program=program
                    )
                    # shape churn: signatures only collected on misses
                    # (the hit path never pays the pytree flatten)
                    try:
                        sig = self._signature(args, kwargs)
                    except Exception:
                        sig = None
                    if sig is not None:
                        with self._lock:
                            seen = self._signatures.setdefault(program, set())
                            fresh = sig not in seen
                            seen.add(sig)
                            n = len(seen)
                        # warn only when a NEW distinct signature lands past
                        # the threshold — a re-miss of a seen shape (cache
                        # eviction, new scheduler instance) must not spam
                        if fresh and n > self.churn_threshold():
                            logger.warning(
                                "shape churn: program %r has compiled %d "
                                "distinct shape signatures this run — "
                                "inputs are probably not landing on padded "
                                "buckets (mesh-aligned padding bug?)",
                                program, n,
                            )

        return watched


#: global compile watcher; `compile_watch(fn, program=...)` is the
#: cache-insertion-site hook (runtime/solver/pipeline jit caches)
_compile_watch = CompileWatch()


def compile_watch(fn, program: str):
    return _compile_watch.watch(fn, program=program)


# ---------------------------------------------------------------------------
# Tracer: Chrome-trace-event / Perfetto JSON spans
# ---------------------------------------------------------------------------


class Tracer:
    """Host-side span recorder exporting Chrome trace-event JSON (the
    "traceEvents" array Perfetto and chrome://tracing load).

    - Spans are complete "X" events: name, pid, tid, ts/dur in MICROSECONDS
      (trace-event convention) derived from `time.perf_counter_ns` relative
      to `start()`.
    - tids are logical row names ("cycle", "pipeline/h2d/buf0", ...) mapped
      to small ints, with "M" thread_name metadata events naming each row.
    - OFF by default; `span()` short-circuits to a no-op context (no clock
      read, no allocation beyond the generator frame) when disabled, so
      always-instrumented code paths stay within the ≤2% overhead budget.
    - Device work is NEVER timed from inside jit: spans bracket host-sync
      points — dispatch returns, `device_put` enqueues (host staging cost;
      the transfer itself is async), and `device_get`/`np.asarray`
      completion fences — the only honest clocks through the tunneled TPU
      backend (CLAUDE.md; GL004/GL008).
    """

    def __init__(self):
        self._enabled = False
        self._events: list[dict] = []
        self._tids: dict[str, int] = {}
        self._lock = threading.Lock()
        self._origin_ns = 0

    @property
    def enabled(self) -> bool:
        return self._enabled

    def start(self, clear: bool = True) -> None:
        with self._lock:
            if clear:
                self._events.clear()
                self._tids.clear()
            self._origin_ns = time.perf_counter_ns()
            self._enabled = True

    def stop(self) -> None:
        self._enabled = False

    def now_ns(self) -> int:
        """Current timestamp on the tracer clock (ns since `start()`)."""
        return time.perf_counter_ns() - self._origin_ns

    def _tid(self, name: str) -> int:
        tid = self._tids.get(name)
        if tid is None:
            tid = self._tids[name] = len(self._tids) + 1
        return tid

    def complete(self, name: str, start_ns: int, dur_ns: int,
                 tid: str = "host", args: dict | None = None) -> None:
        """Record one complete ("X") event from explicit tracer-clock
        stamps (ns since `start()`), e.g. replayed pipeline timelines."""
        if not self._enabled:
            return
        event = {
            "name": name,
            "ph": "X",
            "ts": start_ns / 1000.0,
            "dur": max(dur_ns, 0) / 1000.0,
            "pid": os.getpid(),
        }
        if args:
            event["args"] = args
        with self._lock:
            event["tid"] = self._tid(tid)
            self._events.append(event)

    @contextmanager
    def span(self, name: str, tid: str = "host", **args):
        if not self._enabled:
            yield
            return
        start_ns = self.now_ns()
        try:
            yield
        finally:
            self.complete(
                name, start_ns, self.now_ns() - start_ns, tid=tid,
                args=args or None,
            )

    def export(self) -> dict:
        """{"traceEvents": [...]} — X spans plus M thread_name metadata."""
        with self._lock:
            events = list(self._events)
            tids = dict(self._tids)
        pid = os.getpid()
        meta = [
            {
                "name": "thread_name",
                "ph": "M",
                "pid": pid,
                "tid": tid,
                "args": {"name": row},
            }
            for row, tid in sorted(tids.items(), key=lambda kv: kv[1])
        ]
        return {"traceEvents": meta + events, "displayTimeUnit": "ms"}

    def write(self, path: str) -> None:
        """Export to `path` atomically (temp file + `os.replace`): a crash —
        or SIGKILL — mid-write can never leave a truncated, unparsable
        trace at the target path (the reader sees either the previous
        complete file or the new complete file)."""
        atomic_write(path, json.dumps(self.export()))


#: global tracer, off by default (`bench.py --trace out.json` and
#: `tools/trace_smoke.py` turn it on around their runs)
tracer = Tracer()


@contextmanager
def extension_span(extension_point: str, plugin: str, tid: str = "framework",
                   **args):
    """One extension-point execution: a tracer span on the "framework" row
    plus a `scheduler_plugin_execution_ms{plugin,extension_point}` histogram
    observation — the upstream per-plugin, per-extension-point latency
    metric (frameworkruntime plugin_execution_duration_seconds). `tid`
    overrides the row for stages the pipelined cycle engine runs off the
    main thread (per-tid spans must stay disjoint-or-nested for the
    Perfetto validity gate)."""
    with tracer.span(
        f"{extension_point}/{plugin}", tid=tid, **args
    ):
        start = time.perf_counter_ns()
        try:
            yield
        finally:
            metrics.observe_ms(
                PLUGIN_EXECUTION,
                (time.perf_counter_ns() - start) / 1e6,
                plugin=plugin,
                extension_point=extension_point,
            )


@contextmanager
def flow(subsystem: str, generation: int | None = None, **ctx):
    """Flow-correlated log span: emits FlowBegin/FlowEnd with the subsystem,
    optional cache generation and contextual key/values, plus duration.
    An exception inside the span marks the FlowEnd line `status=error
    error=<ExceptionType>` (and re-raises) so a failed flow is
    distinguishable from a completed one in the log stream."""
    fields = " ".join(f"{k}={v}" for k, v in ctx.items())
    gen = f" generation={generation}" if generation is not None else ""
    logger.debug("%s subsystem=%s%s %s", FLOW_BEGIN, subsystem, gen, fields)
    start = time.perf_counter()
    try:
        yield
    except BaseException as exc:
        logger.debug(
            "%s subsystem=%s%s %s status=error error=%s durationMs=%.2f",
            FLOW_END, subsystem, gen, fields, type(exc).__name__,
            (time.perf_counter() - start) * 1000,
        )
        raise
    logger.debug(
        "%s subsystem=%s%s %s status=ok durationMs=%.2f",
        FLOW_END, subsystem, gen, fields,
        (time.perf_counter() - start) * 1000,
    )
