"""Observability: leveled flow-correlated logging and scheduling metrics.

Mirrors the reference's observability surface (SURVEY.md §5):
- contextual leveled logging with FlowBegin/FlowEnd markers, subsystem names
  and a cache GENERATION attached to every line so a scheduling decision can
  be cross-correlated with the resync that produced its data
  (/root/reference/pkg/noderesourcetopology/logging/logging.go:30-56);
- prometheus-style counters the reference increments (preemption attempts,
  scheduling cycle stats; cmd/scheduler/main.go:23-24,
  capacity_scheduling.go:333).
"""

from __future__ import annotations

import logging
import time
from collections import Counter
from contextlib import contextmanager

logger = logging.getLogger("scheduler_plugins_tpu")

FLOW_BEGIN = "FlowBegin"
FLOW_END = "FlowEnd"


class Metrics:
    """Process-wide scheduling counters (the scheduler_perf surface)."""

    def __init__(self):
        self._counts: Counter[str] = Counter()

    def inc(self, name: str, value: int = 1) -> None:
        self._counts[name] += value

    def observe_ms(self, name: str, ms: float) -> None:
        """Duration observation -> `<name>_ms_total` / `<name>_count` /
        `<name>_ms_max` counters (the prometheus summary shape without
        quantile sketches — enough for rate() and mean/max panels)."""
        ms_int = int(ms)
        self._counts[f"{name}_ms_total"] += ms_int
        self._counts[f"{name}_count"] += 1
        key = f"{name}_ms_max"
        if ms_int > self._counts[key]:
            self._counts[key] = ms_int

    def get(self, name: str) -> int:
        return self._counts[name]

    def snapshot(self) -> dict[str, int]:
        return dict(self._counts)

    def reset(self) -> None:
        self._counts.clear()


#: global registry, like the upstream prometheus default registry
metrics = Metrics()

# counter names (prometheus-style)
SCHEDULING_CYCLES = "scheduler_scheduling_cycles_total"
PODS_BOUND = "scheduler_pods_bound_total"
PODS_FAILED = "scheduler_pods_unschedulable_total"
PREEMPTION_ATTEMPTS = "scheduler_preemption_attempts_total"
PREEMPTION_VICTIMS = "scheduler_preemption_victims_total"
GANG_REJECTIONS = "scheduler_gang_rejections_total"
CACHE_RESYNC_FLUSHES = "scheduler_nrt_cache_flushes_total"


@contextmanager
def flow(subsystem: str, generation: int | None = None, **ctx):
    """Flow-correlated log span: emits FlowBegin/FlowEnd with the subsystem,
    optional cache generation and contextual key/values, plus duration."""
    fields = " ".join(f"{k}={v}" for k, v in ctx.items())
    gen = f" generation={generation}" if generation is not None else ""
    logger.debug("%s subsystem=%s%s %s", FLOW_BEGIN, subsystem, gen, fields)
    start = time.perf_counter()
    try:
        yield
    finally:
        logger.debug(
            "%s subsystem=%s%s %s durationMs=%.2f",
            FLOW_END, subsystem, gen, fields,
            (time.perf_counter() - start) * 1000,
        )
