"""Shared HTTPS client plumbing for every apiserver-facing component
(reflector agent, binding POSTs, lease elector): one place for the
CA-trust / skip-verify policy so a TLS fix cannot silently diverge
between the three callers."""

from __future__ import annotations

import ssl
from typing import Optional


def ssl_context(url: str, ca_file: Optional[str] = None,
                insecure_skip_verify: bool = False):
    """Default-verifying SSL context for an https URL (None for http).
    `ca_file` trusts a private CA (in-cluster: the serviceaccount ca.crt)
    without disabling verification; `insecure_skip_verify` is the
    public-API equivalent of the old private _create_unverified_context."""
    if not url.startswith("https"):
        return None
    ctx = ssl.create_default_context(cafile=ca_file)
    if insecure_skip_verify:
        ctx.check_hostname = False
        ctx.verify_mode = ssl.CERT_NONE
    return ctx
