"""Go-faithful integer/float math primitives.

Bit-identical placement requires matching Go's arithmetic conventions exactly
(SURVEY.md §7 "hard parts"):
- Go integer division truncates toward zero; Python/JAX `//` floors. Matters
  whenever a score can be negative (e.g. Least-mode allocatable scores,
  /root/reference/pkg/noderesources/allocatable.go:126).
- Go `math.Round` rounds half away from zero; `jnp.round` rounds half-to-even.
- Masked min/max must mirror the "iterate the score list" loops
  (e.g. /root/reference/pkg/noderesources/allocatable.go:143-157).
"""

from __future__ import annotations

import jax.numpy as jnp


def go_div(a, b):
    """Integer division truncating toward zero (Go semantics), b > 0."""
    a = jnp.asarray(a)
    q = jnp.abs(a) // b
    return jnp.where(a < 0, -q, q).astype(a.dtype)


def round_half_away(x):
    """Go `math.Round`: round half away from zero, as int64."""
    x = jnp.asarray(x)
    return jnp.where(x >= 0, jnp.floor(x + 0.5), jnp.ceil(x - 0.5)).astype(jnp.int64)


def _dtype_bounds(dtype):
    if jnp.issubdtype(dtype, jnp.inexact):
        info = jnp.finfo(dtype)
    else:
        info = jnp.iinfo(dtype)
    return info.min, info.max


def masked_min(scores, mask, axis=-1, keepdims=False):
    """Min over `mask`-selected entries; dtype max where mask is empty
    (mirrors `lowest := math.MaxInt64` loop initialisation)."""
    _, sentinel = _dtype_bounds(scores.dtype)
    return jnp.min(jnp.where(mask, scores, sentinel), axis=axis, keepdims=keepdims)


def masked_max(scores, mask, axis=-1, keepdims=False):
    """Max over `mask`-selected entries; dtype min where mask is empty."""
    sentinel, _ = _dtype_bounds(scores.dtype)
    return jnp.max(jnp.where(mask, scores, sentinel), axis=axis, keepdims=keepdims)


def pad_axis(arr, target: int, axis: int = 0, fill=0):
    """Pad `arr` along `axis` to length `target` with `fill` (numpy or jnp)."""
    length = arr.shape[axis]
    if length == target:
        return arr
    if length > target:
        raise ValueError(f"cannot pad axis of length {length} down to {target}")
    widths = [(0, 0)] * arr.ndim
    widths[axis] = (0, target - length)
    return jnp.pad(arr, widths, constant_values=fill)


def bucket_size(n: int, minimum: int = 8) -> int:
    """Next power-of-two bucket for static-shape padding (SURVEY.md §7:
    dynamic pod/node counts vs XLA static shapes)."""
    size = minimum
    while size < n:
        size *= 2
    return size
