"""Go-faithful integer/float math primitives.

Bit-identical placement requires matching Go's arithmetic conventions exactly
(SURVEY.md §7 "hard parts"):
- Go integer division truncates toward zero; Python/JAX `//` floors. Matters
  whenever a score can be negative (e.g. Least-mode allocatable scores,
  /root/reference/pkg/noderesources/allocatable.go:126).
- Go `math.Round` rounds half away from zero; `jnp.round` rounds half-to-even.
- Masked min/max must mirror the "iterate the score list" loops
  (e.g. /root/reference/pkg/noderesources/allocatable.go:143-157).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def go_div(a, b):
    """Integer division truncating toward zero (Go semantics), b > 0.

    Floor division plus a remainder correction, NOT the abs-based form
    (`-(|a| // b)`): `abs(INT64_MIN)` wraps to itself, so that form
    returned +2^62-range garbage at the int64 lower boundary (found by the
    property suite against the Go oracle). `q * b` may wrap when `a` is
    within `b` of INT64_MIN, but two's-complement wraparound makes the
    subtraction self-correcting: `a - (q*b mod 2^64) mod 2^64` is the true
    remainder (0 <= r < b)."""
    a = jnp.asarray(a)
    q = a // b
    r = a - q * b
    return jnp.where((a < 0) & (r != 0), q + 1, q).astype(a.dtype)


def floordiv_exact(a, b):
    """Exact floor(a/b) in floating point for integer-valued inputs, b > 0.

    Runs in `a`'s dtype when it is floating (callers guarantee the values and
    intermediate products are exactly representable there — < 2^24 for f32,
    < 2^53 for f64), else float64. Computed as a correctly-rounded division
    plus a one-step correction (the float quotient can land one off across
    an integer boundary; the remainder check is exact at these magnitudes).
    Integer division is the slow path on both backends — CPU SIMD has no
    integer divide and TPU emulates s64 arithmetic — while float division
    vectorizes. For non-negative a this equals Go's truncating division.
    """
    a = jnp.asarray(a)
    dt = a.dtype if jnp.issubdtype(a.dtype, jnp.floating) else jnp.float64
    af = a.astype(dt)
    bf = jnp.asarray(b).astype(dt)
    q = jnp.floor(af / bf)
    r = af - q * bf  # exact: |r| < 2b
    q = jnp.where(r < 0, q - 1.0, q)
    q = jnp.where(r >= bf, q + 1.0, q)
    return q


def floordiv_recip(a, b, brecip):
    """`floordiv_exact` with a precomputed reciprocal `brecip` ~= 1/b: one
    multiply plus exact remainder corrections instead of a division. For a
    batched numerator over a batch-invariant divisor (the NUMA score's
    per-pod requests against one snapshot's zone capacities), the
    reciprocal hoists out of the vmap and the (P, N, Z, R) pass runs at
    multiply speed. The initial estimate can be off by a couple of units
    (brecip carries rounding error scaled by a); two exact remainder
    correction rounds pin floor(a/b) — products must be exactly
    representable in the working dtype (same caller contract as
    `floordiv_exact`), so each correction step is provably toward the true
    quotient and |q0 - floor(a/b)| <= 2 at these magnitudes."""
    a = jnp.asarray(a)
    dt = a.dtype if jnp.issubdtype(a.dtype, jnp.floating) else jnp.float64
    af = a.astype(dt)
    bf = jnp.asarray(b).astype(dt)
    q = jnp.floor(af * brecip.astype(dt))
    for _ in range(2):
        r = af - q * bf  # exact at caller-guaranteed magnitudes
        q = jnp.where(r < 0, q - 1.0, q)
        q = jnp.where(r >= bf, q + 1.0, q)
    return q


def round_half_away(x):
    """Go `math.Round`: round half away from zero, as int64 (exact for
    |x| < 2^53).

    Compares the EXACT fractional part against 0.5 instead of the
    `floor(x + 0.5)` idiom: `x + 0.5` itself rounds (the largest double
    below 0.5 plus 0.5 is exactly 1.0), so the idiom rounds UP values Go's
    bit-exact math.Round rounds down — caught by the property suite.
    `x - floor(x)` is exact (Sterbenz for x >= 1, floor == 0 below), so the
    half-boundary compare here is exact at every magnitude."""
    x = jnp.asarray(x)
    f = jnp.floor(x)
    pos = jnp.where(x - f >= 0.5, f + 1, f)
    c = jnp.ceil(x)
    neg = jnp.where(c - x >= 0.5, c - 1, c)
    return jnp.where(x >= 0, pos, neg).astype(jnp.int64)


def _dtype_bounds(dtype):
    if jnp.issubdtype(dtype, jnp.inexact):
        info = jnp.finfo(dtype)
    else:
        info = jnp.iinfo(dtype)
    return info.min, info.max


def masked_min(scores, mask, axis=-1, keepdims=False):
    """Min over `mask`-selected entries; dtype max where mask is empty
    (mirrors `lowest := math.MaxInt64` loop initialisation)."""
    _, sentinel = _dtype_bounds(scores.dtype)
    return jnp.min(jnp.where(mask, scores, sentinel), axis=axis, keepdims=keepdims)


def masked_max(scores, mask, axis=-1, keepdims=False):
    """Max over `mask`-selected entries; dtype min where mask is empty."""
    sentinel, _ = _dtype_bounds(scores.dtype)
    return jnp.max(jnp.where(mask, scores, sentinel), axis=axis, keepdims=keepdims)


def pad_axis(arr, target: int, axis: int = 0, fill=0):
    """Pad `arr` along `axis` to length `target` with `fill` (numpy or jnp)."""
    length = arr.shape[axis]
    if length == target:
        return arr
    if length > target:
        raise ValueError(f"cannot pad axis of length {length} down to {target}")
    widths = [(0, 0)] * arr.ndim
    widths[axis] = (0, target - length)
    return jnp.pad(arr, widths, constant_values=fill)


def bucket_size(n: int, minimum: int = 8) -> int:
    """Static-shape padding bucket (SURVEY.md §7: dynamic pod/node counts
    vs XLA static shapes): powers of two up to 1024, then multiples of
    1024. Pure doubling wastes up to 2x on every (P, N) pass at cluster
    scale (5000 nodes -> 8192); 1024-steps keep lane-friendly shapes
    (multiples of 128) while capping pad waste at ~20% past 4k, at the
    cost of more distinct compile buckets (one per 1024 above that —
    cheap, since real cluster/queue sizes move slowly)."""
    size = minimum
    while size < n and size < 1024:
        size *= 2
    if n <= size:
        return size
    return ((n + 1023) // 1024) * 1024


@jax.jit
def exact_f64(x):
    """The blessed int64 -> float64 exact cast (ISSUE 18).

    Callers assert the values are quantity-scale integers (< 2^53 — the
    repo-wide aggregation bound, `api.bounds.QUANTITY_SUM_MAX`), so the
    cast is value-preserving. A named jit boundary ON PURPOSE (XLA
    inlines it — no runtime cost): `tools/kernel_audit.py` KA003 blesses
    the pjit call by name via `api.bounds.EXACT_FN_BOUNDS`, and
    `tools/graft_lint.py` GL013 requires NEW float64 casts of int64
    quantity tensors outside the audited modules to route through here
    rather than a raw `.astype(jnp.float64)`."""
    return jnp.asarray(x).astype(jnp.float64)
