"""Cycle flight recorder: deterministic record/replay bundles + explain.

The upstream scheduler leaves two postmortem trails this rebuild lacked:
`Scheduled`/`FailedScheduling` events with per-pod reasons, and the
`--v=10` per-plugin score dump (SURVEY.md §5). This module is the data
substrate for both — and for any score-tuning loop (PAPERS.md "Learning
to Score"): you cannot tune or audit placement quality without
per-decision score breakdowns tied to **reproducible inputs**.

Three layers:

- **FlightRecorder** (`recorder`, process-global, OFF by default): a
  bounded ring buffer of `CycleRecord`s. When enabled, `framework.cycle
  .run_cycle` captures each cycle's FULL solver inputs at the Snapshot
  boundary — every snapshot tensor (content-addressed by digest), the
  queue order (`SnapshotMeta.pod_names`), each plugin's traced `aux()`
  config arrays, `static_key`, weight and cluster-derived `host_state`
  (specializations like the NRT uniform scope that a replay rebuild
  without a Cluster could not recompute), the profile + solve mode and an
  optional scenario seed — and its outputs at the Solve/Bind boundaries
  (assignment / admitted / wait / failed_plugin, then the report's
  bound/failed_by maps). Records enter the ring at capture time, so a
  crash mid-solve still leaves the inputs that provoked it.
- **Bundles**: `recorder.save(dir)` persists the ring as a self-contained
  `cycles.jsonl` manifest + `blobs/<digest>.npy` array store. Every file
  lands via temp-file + `os.replace` (`observability.atomic_write`), blobs
  before the manifest, so a kill mid-save never leaves a manifest naming
  missing or truncated blobs. `load_bundle(dir)` rebuilds the exact
  `ClusterSnapshot` / `SnapshotMeta` / aux pytrees; `tools/replay.py`
  re-runs them through the bit-identical sequential parity path
  (`Scheduler.solve`) and diffs placements.
- **Explain**: `explain_solver(...)` formats the per-(pod, cycle) score
  table — top-k candidate nodes with per-plugin weighted normalized score
  columns, the built-in fit margin and the winner gap (the upstream
  `--v=10` score dump) — from `Scheduler.explain_rows` (sequential) or
  `parallel.solver.batch_explain_rows` (batched); both share the
  framework's attribution/score helpers so they cannot drift. Exposed as
  `tools/replay.py explain`, the daemon's `/explain?uid=`, and
  `CycleReport.explain(uid)`.

Digest scheme: `blake2b-128(dtype ":" shape ":" C-order bytes)` per
array; a cycle's digest is `blake2b-128` over its canonical (sorted-key,
compact) manifest JSON with the digest field blanked — stable across
save/load round-trips, so "same digest" means "bit-identical record".

Privacy note: bundles carry FULL solver inputs — pod names/uids, node
names, namespaces, requests, the entire snapshot. Treat a recorded bundle
like an apiserver dump, not like a metrics scrape (docs/OBSERVABILITY.md).
"""

from __future__ import annotations

import hashlib
import io
import json
import os
import threading
from collections import deque
from dataclasses import dataclass, field
from typing import Optional

import numpy as np

from scheduler_plugins_tpu.utils import observability as obs

#: manifest format version (bump on incompatible schema changes)
FORMAT = 1

#: fit-margin sentinel for masked-out (unschedulable/padded) nodes
MARGIN_MASKED = -(2 ** 62)


# ---------------------------------------------------------------------------
# array digests + pytree (de)serialization
# ---------------------------------------------------------------------------


def array_digest(arr: np.ndarray) -> str:
    """Content address of one array: blake2b-128 over dtype, shape and
    C-order bytes (dtype/shape prefixed so a reshape or cast can never
    collide with the original)."""
    arr = np.ascontiguousarray(arr)
    h = hashlib.blake2b(digest_size=16)
    h.update(str(arr.dtype).encode())
    h.update(b":")
    h.update(",".join(map(str, arr.shape)).encode())
    h.update(b":")
    h.update(arr.tobytes())
    return h.hexdigest()


def _struct_registry() -> dict:
    """Class-name -> struct dataclass for every snapshot pytree node type
    (state.snapshot + state.scheduling)."""
    import dataclasses

    from scheduler_plugins_tpu.state import scheduling as _scheduling
    from scheduler_plugins_tpu.state import snapshot as _snapshot

    registry = {}
    for mod in (_snapshot, _scheduling):
        for name in dir(mod):
            obj = getattr(mod, name)
            if isinstance(obj, type) and dataclasses.is_dataclass(obj):
                registry[obj.__name__] = obj
    return registry


def pack_pytree(value, blobs: dict) -> object:
    """Lower a snapshot/aux pytree into a JSON-able spec, depositing every
    array into `blobs` keyed by content digest. Handles struct dataclasses
    (incl. non-pytree static fields like `NumaState.pack_scales`), plain
    containers, arrays and scalars."""
    import dataclasses

    if value is None:
        return None
    if isinstance(value, (bool, int, float, str)):
        return {"v": value}
    if isinstance(value, np.generic):
        return {"v": value.item()}
    if dataclasses.is_dataclass(value) and not isinstance(value, type):
        return {
            "s": type(value).__name__,
            "f": {
                f.name: pack_pytree(getattr(value, f.name), blobs)
                for f in dataclasses.fields(value)
            },
        }
    if isinstance(value, (tuple, list)):
        return {
            "t": [pack_pytree(v, blobs) for v in value],
            "k": "tuple" if isinstance(value, tuple) else "list",
        }
    if isinstance(value, dict):
        return {"d": {str(k): pack_pytree(v, blobs) for k, v in value.items()}}
    arr = np.asarray(value)  # np.ndarray or jax.Array
    if arr.dtype == object:
        raise TypeError(f"unrecordable value of type {type(value).__name__}")
    digest = array_digest(arr)
    blobs[digest] = arr
    return {"a": digest, "dtype": str(arr.dtype), "shape": list(arr.shape)}


def unpack_pytree(spec, blobs: dict, registry: Optional[dict] = None):
    """Inverse of `pack_pytree` (arrays come back as host numpy)."""
    if spec is None:
        return None
    if registry is None:
        registry = _struct_registry()
    if "v" in spec:
        return spec["v"]
    if "a" in spec:
        arr = blobs[spec["a"]]
        expect = (spec["dtype"], tuple(spec["shape"]))
        if (str(arr.dtype), arr.shape) != expect:
            raise ValueError(
                f"blob {spec['a']}: dtype/shape {arr.dtype}/{arr.shape} "
                f"does not match manifest {expect}"
            )
        return arr
    if "t" in spec:
        items = [unpack_pytree(v, blobs, registry) for v in spec["t"]]
        return tuple(items) if spec.get("k") == "tuple" else items
    if "d" in spec:
        return {k: unpack_pytree(v, blobs, registry) for k, v in spec["d"].items()}
    cls = registry.get(spec["s"])
    if cls is None:
        raise ValueError(f"unknown struct {spec['s']!r} in bundle")
    return cls(**{
        name: unpack_pytree(v, blobs, registry)
        for name, v in spec["f"].items()
    })


def pack_meta(meta) -> dict:
    """`SnapshotMeta` -> JSON (host-only name<->code tables; the resource
    axis is recorded as the full ordered name list)."""
    from scheduler_plugins_tpu.api.resources import CANONICAL

    names = list(meta.index.names)
    if tuple(names[: len(CANONICAL)]) != CANONICAL:
        raise ValueError("resource index does not start with CANONICAL")
    return {
        "resources": names,
        "node_names": list(meta.node_names),
        "pod_names": list(meta.pod_names),
        "namespaces": list(meta.namespaces),
        "gang_names": list(meta.gang_names),
        "regions": list(meta.regions),
        "zones": list(meta.zones),
        "workloads": list(meta.workloads),
    }


def unpack_meta(spec: dict):
    from scheduler_plugins_tpu.api.resources import CANONICAL, ResourceIndex
    from scheduler_plugins_tpu.state.snapshot import SnapshotMeta

    index = ResourceIndex(spec["resources"][len(CANONICAL):])
    if tuple(index.names) != tuple(spec["resources"]):
        raise ValueError("resource axis did not round-trip")
    return SnapshotMeta(
        index=index,
        node_names=list(spec["node_names"]),
        pod_names=list(spec["pod_names"]),
        namespaces=list(spec["namespaces"]),
        gang_names=list(spec["gang_names"]),
        regions=list(spec["regions"]),
        zones=list(spec["zones"]),
        workloads=list(spec["workloads"]),
    )


def _canonical_json(obj) -> str:
    return json.dumps(obj, sort_keys=True, separators=(",", ":"))


# ---------------------------------------------------------------------------
# cycle records + the ring-buffer recorder
# ---------------------------------------------------------------------------


@dataclass
class CycleRecord:
    """One recorded scheduling cycle: inputs captured at the Snapshot
    boundary, outputs at Solve/Bind. `manifest` is the JSON-able view
    (arrays as digest references); `blobs` holds the arrays."""

    seq: int
    now_ms: int
    profile: str
    seed: object = None
    manifest: dict = field(default_factory=dict)
    blobs: dict = field(default_factory=dict)
    complete: bool = False

    def capture_inputs(self, snap, meta, scheduler, stream_chunk=None,
                       profile_config=None) -> None:
        """Record the full solver input surface for this cycle. Must run
        AFTER `scheduler.prepare(meta, ...)` so the captured `aux()`
        pytrees are exactly what the solve would bind."""
        self.manifest["snapshot"] = pack_pytree(snap, self.blobs)
        self.manifest["meta"] = pack_meta(meta)
        self.manifest["stream_chunk"] = stream_chunk
        if profile_config is not None:
            self.manifest["profile_config"] = profile_config
        else:
            from scheduler_plugins_tpu.api.config import profile_spec

            self.manifest["profile_config"] = profile_spec(scheduler.profile)
        self.manifest["plugins"] = [
            {
                "name": p.name,
                "class": type(p).__name__,
                "weight": int(p.weight),
                "static_key": repr(p.static_key()),
                "aux": pack_pytree(p.aux(), self.blobs),
                # cluster-derived trace specialization (e.g. NRT uniform
                # scope, NetworkOverhead cost matrices) that a rebuild
                # without a Cluster cannot recompute — restored on replay
                "host_state": pack_pytree(p.host_state(), self.blobs),
            }
            for p in scheduler.profile.plugins
        ]

    def capture_outputs(self, mode: str, assignment, admitted, wait,
                        failed_plugin=None) -> None:
        out = {
            "mode": mode,
            "assignment": pack_pytree(np.asarray(assignment), self.blobs),
            "admitted": pack_pytree(np.asarray(admitted), self.blobs),
            "wait": pack_pytree(np.asarray(wait), self.blobs),
            "failed_plugin": (
                None if failed_plugin is None
                else pack_pytree(np.asarray(failed_plugin), self.blobs)
            ),
        }
        self.manifest["outputs"] = out

    def commit(self, report=None, drift=None) -> None:
        if report is not None:
            self.manifest["report"] = {
                "bound": dict(report.bound),
                "reserved": dict(report.reserved),
                "failed": list(report.failed),
                "failed_by": dict(report.failed_by),
            }
            # per-cycle placement-quality objectives (tuning.quality) —
            # `tools/replay.py quality` diffs its recomputation against
            # this recorded stamp
            if getattr(report, "quality", None) is not None:
                self.manifest["report"]["quality"] = dict(report.quality)
        self.manifest["drift"] = drift
        self.complete = True
        obs.metrics.inc(obs.FLIGHTREC_CYCLES)

    def to_manifest(self) -> dict:
        line = {
            "format": FORMAT,
            "cycle": self.seq,
            "now_ms": self.now_ms,
            "profile": self.profile,
            "seed": self.seed,
            "complete": self.complete,
            **self.manifest,
        }
        line["digest"] = record_digest(line)
        return line

    @property
    def pod_names(self) -> list:
        return self.manifest.get("meta", {}).get("pod_names", [])


def record_digest(manifest: dict) -> str:
    """Cycle digest: blake2b-128 over the canonical manifest JSON with the
    digest field blanked. Arrays contribute through their content
    digests, so equal digest == bit-identical inputs AND outputs."""
    scrubbed = {k: v for k, v in manifest.items() if k != "digest"}
    return hashlib.blake2b(
        _canonical_json(scrubbed).encode(), digest_size=16
    ).hexdigest()


class FlightRecorder:
    """Bounded ring buffer of `CycleRecord`s. OFF by default; when off,
    `begin()` returns None and the cycle hooks cost one attribute read.
    `start(capacity)` arms it; records enter the ring as soon as `begin`
    returns (partial records are visible — the point of a flight recorder
    is surviving the crash that would have prevented a tidy commit)."""

    def __init__(self):
        self._enabled = False
        self._ring: deque = deque(maxlen=8)
        self._seq = 0
        self._lock = threading.Lock()
        #: optional exact profile config (the daemon sets its decoded
        #: profile file here); falls back to `api.config.profile_spec`
        self.profile_config: Optional[dict] = None
        #: optional scenario seed stamped into every record (bench sets it)
        self.seed = None

    @property
    def enabled(self) -> bool:
        return self._enabled

    def start(self, capacity: int = 8) -> None:
        with self._lock:
            self._ring = deque(maxlen=max(int(capacity), 1))
            self._seq = 0
            self._enabled = True

    def stop(self) -> None:
        self._enabled = False

    def resume(self) -> None:
        """Re-arm WITHOUT resetting the ring (`start` resets; `stop` is
        the pause) — the interleaved-pairs overhead benches toggle the
        recorder per cycle and must not lose the accumulated corpus."""
        with self._lock:
            self._enabled = True

    def begin(self, now_ms: int, profile: str) -> Optional[CycleRecord]:
        if not self._enabled:
            return None
        with self._lock:
            self._seq += 1
            rec = CycleRecord(
                seq=self._seq, now_ms=now_ms, profile=profile, seed=self.seed
            )
            self._ring.append(rec)
        return rec

    def records(self) -> list:
        with self._lock:
            return list(self._ring)

    def find(self, uid: str, cycle: Optional[int] = None):
        """Newest COMPLETE record whose pending batch contains `uid` (or
        the exact `cycle` number when given); when no complete record has
        it, the newest in-flight record with captured inputs (outputs
        missing — crash postmortems live here). Records still inside
        `capture_inputs` (the current cycle, seen from another thread)
        are never returned — a half-built manifest would crash the
        caller."""
        recs = self.records()
        for want_complete in (True, False):
            for rec in reversed(recs):
                if cycle is not None and rec.seq != cycle:
                    continue
                if rec.complete is not want_complete:
                    continue
                if "plugins" not in rec.manifest:  # capture in flight
                    continue
                if uid in rec.pod_names:
                    return rec
        return None

    def save(self, directory: str) -> dict:
        """Persist the ring as a bundle: `blobs/<digest>.npy` (each written
        atomically) then the `cycles.jsonl` manifest LAST — a reader only
        trusts arrays the manifest names, so a crash mid-save leaves at
        worst orphan blobs, never a manifest with missing data. An
        existing manifest in `directory` is appended to, not replaced
        (blobs are content-addressed, so successive runs — e.g. several
        `bench.py --record` invocations — accumulate into one bundle);
        records already present verbatim are not duplicated. Returns a
        small summary dict."""
        records = [r for r in self.records() if r.manifest.get("snapshot")]
        os.makedirs(os.path.join(directory, "blobs"), exist_ok=True)
        written = 0
        seen: set = set()
        for rec in records:
            for digest, arr in rec.blobs.items():
                if digest in seen:
                    continue
                seen.add(digest)
                path = os.path.join(directory, "blobs", f"{digest}.npy")
                if os.path.exists(path):
                    continue
                buf = io.BytesIO()
                np.save(buf, np.ascontiguousarray(arr), allow_pickle=False)
                obs.atomic_write(path, buf.getvalue())
                written += 1
        # sidecars land with the blobs, BEFORE the manifest: cycles.jsonl
        # stays the last write so a crash mid-save never leaves a
        # manifest naming missing data (gated by test_flightrec
        # TestAtomicWrites)
        self._save_cost_stamp(directory)
        manifest_path = os.path.join(directory, "cycles.jsonl")
        lines: list = []
        if os.path.exists(manifest_path):
            with open(manifest_path) as f:
                lines = [ln.strip() for ln in f if ln.strip()]
        have = set(lines)
        lines += [
            line for rec in records
            if (line := _canonical_json(rec.to_manifest())) not in have
        ]
        obs.atomic_write(
            manifest_path,
            "\n".join(lines) + ("\n" if lines else ""),
        )
        ledger_pods = self._save_ledger_segment(directory)
        return {
            "cycles": len(lines),
            "blobs_written": written,
            "ledger_pods": ledger_pods,
            "path": directory,
        }

    @staticmethod
    def _save_cost_stamp(directory: str) -> None:
        """Stamp the committed static-cost provenance (docs/cost_model.json,
        ISSUE 20) beside the cycle manifest: `cost.json` records the
        manifest digest + per-program cost digests in force when the
        bundle was written, so `tools/replay.py info` can flag "recorded
        under a program with a different cost shape" instead of silently
        replaying across an algorithmic change. A sidecar like
        ledger.json — NOT a manifest field — because record digests
        (`record_digest`) cover the cycle manifest, and provenance about
        the surrounding tree must not churn the integrity digest of the
        recorded data itself. Best-effort: no cost manifest, no stamp."""
        from scheduler_plugins_tpu.obs import costmodel

        manifest = costmodel.load_manifest()
        if not manifest:
            return
        stamp = {
            "manifest_digest": costmodel.manifest_digest(manifest),
            "jax": manifest.get("jax"),
            "programs": {
                name: row.get("cost_digest")
                for name, row in sorted(manifest.get("programs", {}).items())
            },
        }
        obs.atomic_write(
            os.path.join(directory, "cost.json"),
            json.dumps(stamp, sort_keys=True),
        )

    @staticmethod
    def _save_ledger_segment(directory: str) -> int:
        """Persist the pod-lifecycle ledger (obs.ledger) alongside the
        cycle manifest when it is live: `ledger.json` lets
        `tools/replay.py timeline <bundle> <uid>` reconstruct a pod's
        cross-cycle story next to the cycle-level replay evidence. Lazy
        import — flightrec must not pull the ledger in for the many
        callers that never record. Returns the number of pod records
        persisted (0 when the ledger is off or empty)."""
        from scheduler_plugins_tpu.obs import ledger as podledger

        led = podledger.LEDGER
        if not led.enabled:
            return 0
        export = led.export()
        n = len(export["retired"]) + len(export["live"])
        if n == 0:
            return 0
        obs.atomic_write(
            os.path.join(directory, "ledger.json"),
            json.dumps(export, sort_keys=True),
        )
        return n


#: global recorder, off by default (`run_cycle` hooks, daemon `--record`,
#: `bench.py --record dir/`, `tools/replay.py smoke` turn it on)
recorder = FlightRecorder()


# ---------------------------------------------------------------------------
# bundle loading + replay reconstruction
# ---------------------------------------------------------------------------


def rebuild_scheduler(manifest: dict, blob_resolver, profile_name=None):
    """(Scheduler, meta, faithful): THE one profile-rebuild recipe, shared
    by the bundle replay path (`LoadedCycle.scheduler`) and the live
    daemon `/explain` path (`explain_record` on a ring `CycleRecord`):
    `load_profile` on the recorded config, recorded per-plugin weights,
    `prepare(meta, None)` (no Cluster exists at replay), then each
    plugin's recorded `host_state` re-baked — so the rebuilt plugins trace
    the same specialized program the recorded solve ran. `faithful` is
    False when, after all that, a rebuilt plugin's class/static_key still
    disagrees with the record (lossy config export). `blob_resolver`
    lowers a packed pytree spec back to arrays (bundle blob dir or the
    in-memory record's blobs)."""
    from scheduler_plugins_tpu.api.config import load_profile
    from scheduler_plugins_tpu.framework.runtime import Scheduler

    profile = load_profile(manifest["profile_config"])
    profile.name = (
        profile_name if profile_name is not None
        else manifest.get("profile", profile.name)
    )
    recorded = manifest["plugins"]
    faithful = len(profile.plugins) == len(recorded)
    if faithful:
        for plugin, rec in zip(profile.plugins, recorded):
            plugin.weight = int(rec.get("weight", plugin.weight))
    scheduler = Scheduler(profile)
    meta = unpack_meta(manifest["meta"])
    scheduler.prepare(meta, None)
    if faithful:
        for plugin, rec in zip(profile.plugins, recorded):
            hs = rec.get("host_state")
            if hs is not None:
                plugin.restore_host_state(blob_resolver(hs))
            if type(plugin).__name__ != rec["class"] or repr(
                plugin.static_key()
            ) != rec["static_key"]:
                faithful = False
    return scheduler, meta, faithful


class LoadedCycle:
    """One manifest line + lazy blob access from a bundle directory."""

    def __init__(self, manifest: dict, blob_dir: str):
        self.manifest = manifest
        self._blob_dir = blob_dir
        self._cache: dict = {}
        self._registry = None

    def blob(self, digest: str) -> np.ndarray:
        arr = self._cache.get(digest)
        if arr is None:
            arr = np.load(
                os.path.join(self._blob_dir, f"{digest}.npy"),
                allow_pickle=False,
            )
            if array_digest(arr) != digest:
                raise ValueError(f"blob {digest} content does not match name")
            self._cache[digest] = arr
        return arr

    def _blobs_for(self, spec) -> dict:
        digests: set = set()

        def walk(node):
            if node is None:
                return
            if "a" in node:
                digests.add(node["a"])
            for child in node.get("f", {}).values():
                walk(child)
            for child in node.get("t", []):
                walk(child)
            for child in node.get("d", {}).values():
                walk(child)

        walk(spec)
        return {d: self.blob(d) for d in digests}

    def snapshot(self):
        spec = self.manifest["snapshot"]
        return unpack_pytree(spec, self._blobs_for(spec))

    def meta(self):
        return unpack_meta(self.manifest["meta"])

    def auxes(self) -> tuple:
        return tuple(
            unpack_pytree(p["aux"], self._blobs_for(p["aux"]))
            for p in self.manifest["plugins"]
        )

    def output(self, name: str):
        out = self.manifest.get("outputs") or {}
        spec = out.get(name)
        if spec is None:
            return None
        return unpack_pytree(spec, self._blobs_for(spec))

    def scheduler(self):
        """Rebuild (Scheduler, faithful: bool) from the recorded profile
        config — prepared and host-state-restored (`rebuild_scheduler`).
        Even when `faithful` is False (lossy config export) replay still
        runs, with the recorded aux arrays force-bound so the traced
        config inputs are exact either way."""
        scheduler, _meta, faithful = rebuild_scheduler(
            self.manifest,
            lambda spec: unpack_pytree(spec, self._blobs_for(spec)),
        )
        return scheduler, faithful

    def digest_ok(self) -> bool:
        return record_digest(self.manifest) == self.manifest.get("digest")


def load_bundle(directory: str) -> list:
    """Parse a bundle directory into `LoadedCycle`s (manifest order)."""
    path = os.path.join(directory, "cycles.jsonl")
    blob_dir = os.path.join(directory, "blobs")
    cycles = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            manifest = json.loads(line)
            if manifest.get("format") != FORMAT:
                raise ValueError(
                    f"bundle format {manifest.get('format')!r} != {FORMAT}"
                )
            cycles.append(LoadedCycle(manifest, blob_dir))
    return cycles


def replay_cycle(loaded: LoadedCycle) -> dict:
    """Re-run one recorded cycle through the bit-identical sequential
    parity path (`Scheduler.solve`) with the RECORDED aux arrays bound,
    and diff placements against the recorded outputs. The diff must be
    empty for cycles recorded in sequential mode; wave-mode recordings
    (batch/streamed) may legitimately differ on soft tie-breaking, so the
    mismatch list is evidence, not an error, there."""
    scheduler, faithful = loaded.scheduler()  # prepared + host-state restored
    snap = loaded.snapshot()
    meta = loaded.meta()
    auxes = loaded.auxes()
    aux_match = all(
        _pack_digest(plugin.aux()) == _pack_digest(aux)
        for plugin, aux in zip(scheduler.profile.plugins, auxes)
    )
    # mode pinned: replay certifies the sequential parity semantics even
    # when the recorded profile selects another solve mode (packing)
    result = scheduler.solve(snap, auxes=auxes, mode="sequential")
    assignment = np.asarray(result.assignment)
    recorded = loaded.output("assignment")
    mode = (loaded.manifest.get("outputs") or {}).get("mode")
    mismatches = []
    if recorded is not None:
        diff = np.nonzero(assignment != np.asarray(recorded))[0]
        pod_names = loaded.manifest["meta"]["pod_names"]
        node_names = loaded.manifest["meta"]["node_names"]

        def node(ix):
            return node_names[ix] if 0 <= ix < len(node_names) else None

        for i in diff[:64]:
            i = int(i)
            mismatches.append({
                "pod": pod_names[i] if i < len(pod_names) else f"<pad {i}>",
                "recorded": node(int(np.asarray(recorded)[i])),
                "replayed": node(int(assignment[i])),
            })
    return {
        "cycle": loaded.manifest["cycle"],
        "mode": mode,
        "digest_ok": loaded.digest_ok(),
        "profile_faithful": faithful,
        "aux_match": bool(aux_match),
        "placed_recorded": (
            None if recorded is None else int((np.asarray(recorded) >= 0).sum())
        ),
        "placed_replayed": int((assignment >= 0).sum()),
        "placements_match": recorded is not None and not mismatches,
        "mismatches": mismatches,
        "_assignment": assignment,
        "_scheduler": scheduler,
        "_snap": snap,
        "_meta": meta,
        "_auxes": auxes,
    }


def _pack_digest(pytree) -> str:
    blobs: dict = {}
    spec = pack_pytree(pytree, blobs)
    return hashlib.blake2b(
        _canonical_json(spec).encode(), digest_size=16
    ).hexdigest()


# ---------------------------------------------------------------------------
# explain: the per-(pod, cycle) score table
# ---------------------------------------------------------------------------


def explain_solver(scheduler, snap, meta, uid: str, top_k: int = 5,
                   assignment=None, auxes=None, batched: bool = False,
                   cycle=None) -> dict:
    """The "why this node" table for one pod of one solved cycle: top-k
    candidate nodes with per-plugin weighted normalized score columns, the
    built-in fit margin (min over resources of free - demand; most
    negative binding), and each candidate's gap to the winner — the
    upstream `--v=10` score dump as JSON. Scores are evaluated against the
    CYCLE-INITIAL state (the objective both solve modes rank by,
    `parallel.solver.profile_initial_scores`); `batched=True` derives the
    same columns through the batched solver's class-collapsed row hooks
    instead of the per-pod tensor methods (gated for agreement by
    tests/test_explain.py)."""
    try:
        pod_index = meta.pod_names.index(uid)
    except ValueError:
        raise KeyError(f"pod {uid!r} is not in this cycle's pending batch")
    if batched:
        from scheduler_plugins_tpu.parallel.solver import batch_explain_rows

        rows = batch_explain_rows(scheduler, snap, [pod_index], auxes=auxes)
    else:
        rows = scheduler.explain_rows(snap, [pod_index], auxes=auxes)
    plugins = scheduler.profile.plugins
    fail_names = scheduler.fail_plugin_names()
    n_real = len(meta.node_names)

    total = rows["total"][0][:n_real]
    feasible = rows["feasible"][0][:n_real]
    margin = rows["fit_margin"][0][:n_real]
    columns = rows["columns"][0][:, :n_real]
    admitted = bool(rows["admitted"][0])
    fail_code = int(rows["fail_code"][0])

    # infeasible nodes keep their relative score order but rank after
    # every feasible node (scores are bounded far below 2^61, so the
    # shift cannot overflow or let an infeasible node catch a feasible one)
    masked = np.where(feasible, total, total + MARGIN_MASKED)
    # score desc, lowest node index tie-break — the solver's own argmax rule
    order = np.lexsort((np.arange(n_real), -masked))
    any_feasible = bool(feasible.any())
    winner = int(order[0]) if any_feasible else None
    winner_total = int(total[winner]) if winner is not None else None
    runner_up_gap = None
    if any_feasible and int(feasible.sum()) >= 2:
        runner_up_gap = int(winner_total - masked[order[1]])

    assigned_node = None
    placed = None
    if assignment is not None:
        a = int(np.asarray(assignment)[pod_index])
        placed = a >= 0
        if placed and a < n_real:
            assigned_node = meta.node_names[a]
    failed_plugin = None
    if placed is not True and (not admitted or not any_feasible or
                               placed is False):
        failed_plugin = fail_names[fail_code] if fail_code > 0 else fail_names[0]

    candidates = []
    # feasible nodes first, then the best-scoring near-misses — an
    # unschedulable pod's table shows its closest candidates with the fit
    # margins telling why each missed
    for n in order[: max(int(top_k), 1)]:
        n = int(n)
        candidates.append({
            "node": meta.node_names[n],
            "total": int(total[n]),
            "gap_to_winner": (
                None if winner_total is None else int(winner_total - total[n])
            ),
            "feasible": bool(feasible[n]),
            "fit_margin": (
                None if int(margin[n]) == MARGIN_MASKED else int(margin[n])
            ),
            "scores": {
                p.name: int(columns[l][n]) for l, p in enumerate(plugins)
            },
        })
    return {
        "uid": uid,
        "cycle": cycle,
        "pod_index": pod_index,
        "profile": scheduler.profile.name,
        "path": "batched" if batched else "sequential",
        "admitted": admitted,
        "placed": placed,
        "assigned": assigned_node,
        "failed_plugin": failed_plugin,
        "winner": meta.node_names[winner] if winner is not None else None,
        "winner_total": winner_total,
        "runner_up_gap": runner_up_gap,
        "weights": {p.name: int(p.weight) for p in plugins},
        "candidates": candidates,
    }


#: rebuilt-scheduler cache for `explain_record`, keyed by record IDENTITY
#: (a polling `/explain` client hits the same ring `CycleRecord` object
#: repeatedly — without this every request would re-trace+compile the
#: explain program on the HTTP thread, contending with the cycle loop).
#: Identity keying is exact: the ring holds records by reference, and a
#: rotated-out record simply ages out of this deque with it.
_REBUILD_CACHE: deque = deque(maxlen=4)

#: serializes `explain_record`: the daemon serves `/explain` from
#: ThreadingHTTPServer worker threads, and two concurrent requests would
#: otherwise race on the rebuild cache AND trace jit programs against the
#: same rebuilt plugin objects mid-bind (UnexpectedTracerError at best)
_EXPLAIN_LOCK = threading.Lock()


def _cached_rebuild(rec, build):
    for key, value in _REBUILD_CACHE:
        if key is rec:
            return value
    value = build()
    _REBUILD_CACHE.append((rec, value))
    return value


def explain_record(rec, uid: str, top_k: int = 5,
                   batched: bool = False) -> dict:
    """Explain one pod of a ring-buffer `CycleRecord` (the daemon's live
    `/explain` path) or a bundle `LoadedCycle` (the offline replay path).
    Rebuilds both the snapshot and a FRESH scheduler from the record's own
    arrays and profile config — the daemon's live scheduler is never
    touched (re-preparing it for an older record's layout from an HTTP
    thread would corrupt the cycle loop's prepared plugin state), and the
    recorded aux arrays are force-bound so the traced config inputs are
    exactly what the recorded solve saw. The rebuilt scheduler (and its
    compiled explain program) is cached per record, so repeat requests
    for the same recorded cycle pay host unpacking only. Thread-safe:
    concurrent callers (the daemon's HTTP worker threads) serialize on a
    module lock."""
    with _EXPLAIN_LOCK:
        return _explain_record(rec, uid, top_k=top_k, batched=batched)


def _explain_record(rec, uid: str, top_k: int, batched: bool) -> dict:
    if isinstance(rec, CycleRecord):
        spec = rec.manifest["snapshot"]
        snap = unpack_pytree(spec, rec.blobs)
        out = rec.manifest.get("outputs") or {}
        a_spec = out.get("assignment")
        assignment = (
            unpack_pytree(a_spec, rec.blobs) if a_spec is not None else None
        )
        auxes = tuple(
            unpack_pytree(p["aux"], rec.blobs)
            for p in rec.manifest["plugins"]
        )
        cycle = rec.seq
        scheduler, meta = _cached_rebuild(
            rec,
            lambda: rebuild_scheduler(
                rec.manifest, lambda s: unpack_pytree(s, rec.blobs),
                profile_name=rec.profile,
            )[:2],
        )
    else:
        snap = rec.snapshot()
        meta = rec.meta()
        assignment = rec.output("assignment")
        auxes = rec.auxes()
        cycle = rec.manifest["cycle"]
        # prepared + host-state restored (faithfulness flag dropped here —
        # `replay_cycle` is the surface that reports it)
        scheduler = _cached_rebuild(rec, lambda: rec.scheduler()[0])
    return explain_solver(
        scheduler, snap, meta, uid, top_k=top_k, assignment=assignment,
        auxes=auxes, batched=batched, cycle=cycle,
    )
