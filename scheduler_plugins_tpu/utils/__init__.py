"""Shared helpers: Go-faithful integer math, masked reductions, padding."""

from scheduler_plugins_tpu.utils.intmath import (  # noqa: F401
    go_div,
    masked_max,
    masked_min,
    round_half_away,
)
