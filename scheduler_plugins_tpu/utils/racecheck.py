"""Runtime race checker (`SPT_RACE=1`) — the dynamic counterpart of
`tools/race_audit.py`.

`install(seed)` monkeypatches `threading.Lock` / `threading.RLock` /
`threading.Event` with factories that return CHECKED proxies, but ONLY
for locks created from inside `scheduler_plugins_tpu` (the creating
frame's module is inspected): stdlib internals (Condition, queue,
concurrent.futures) keep raw primitives, so their undocumented lock
internals are never disturbed.

What the proxies check, per operation:

- **lock-order inversion** — a global acquisition-order graph (edge
  A→B when B is acquired while A is held, with creation/acquire
  provenance); acquiring B while holding A after (B→A) was observed on
  any thread is a recorded violation — the runtime twin of CA002.
- **non-owner release** — releasing a lock a different thread holds.
- **double acquire** — blocking re-acquire of a non-reentrant Lock by
  its holder (a guaranteed self-deadlock): recorded AND raised, because
  letting it proceed would hang the harness.
- **seeded cooperative yields** — a `random.Random(seed)` injector
  sleeps a few hundred microseconds around acquire/release points,
  steering the interleaving differently per seed. Replaying the same
  composite under N seeds (`make race-smoke`) explores N schedules
  deterministically enough to compare end states bit-for-bit.

Usage:
    racecheck.install(seed=3)
    try:
        ... drive the composite ...
        assert not racecheck.violations()
    finally:
        racecheck.uninstall()

`install` is a no-op (returns False) unless SPT_RACE=1 — production
code never pays for any of this.
"""

from __future__ import annotations

import os
import random
import sys
import threading
import time

_WRAP_PREFIX = "scheduler_plugins_tpu"

_state = {
    "installed": False,
    "orig": {},
    "rng": None,
    "lock": threading.Lock(),   # guards the shared tables below
    "edges": {},                # (a_name, b_name) -> provenance str
    "violations": [],
    "locks_created": 0,
    "events_created": 0,
    "yields": 0,
}
_held = threading.local()       # per-thread stack of held CheckedLocks


def _caller_module(depth: int = 2) -> str:
    try:
        return sys._getframe(depth).f_globals.get("__name__", "")
    except ValueError:
        return ""


def _should_wrap(extra_prefixes) -> bool:
    mod = _caller_module(3)
    prefixes = (_WRAP_PREFIX,) + tuple(extra_prefixes)
    return any(mod == p or mod.startswith(p + ".") for p in prefixes)


def _maybe_yield():
    rng = _state["rng"]
    if rng is None:
        return
    # Random() is GIL-atomic enough for a perturbation source; the point
    # is a seed-deterministic *sequence* of sleep decisions, not a
    # per-thread reproducible schedule
    if rng.random() < 0.5:
        _state["yields"] += 1
        time.sleep(rng.random() * 0.0005)


def _record(kind: str, detail: str):
    with _state["lock"]:
        _state["violations"].append({"kind": kind, "detail": detail})


class CheckedLock:
    """Non-reentrant Lock proxy: ownership, order-graph, seeded yields."""

    _REENTRANT = False

    def __init__(self, real, name: str):
        self._real = real
        self.name = name
        self._owner = None
        self._count = 0

    # -- checks -----------------------------------------------------------

    def _check_order(self):
        held = getattr(_held, "stack", None) or []
        me = threading.current_thread().name
        with _state["lock"]:
            for h in held:
                if h is self:
                    continue
                fwd = (h.name, self.name)
                rev = (self.name, h.name)
                if rev in _state["edges"]:
                    _state["violations"].append({
                        "kind": "lock-order-inversion",
                        "detail": (
                            f"{me} acquires {self.name!r} while holding "
                            f"{h.name!r}, but the opposite order was "
                            f"observed at {_state['edges'][rev]}"
                        ),
                    })
                _state["edges"].setdefault(fwd, me)

    def acquire(self, blocking=True, timeout=-1):
        me = threading.current_thread()
        if (not self._REENTRANT and self._owner is me and blocking
                and timeout == -1):
            _record(
                "double-acquire",
                f"{me.name} blocking re-acquire of non-reentrant lock "
                f"{self.name!r} it already holds (guaranteed deadlock)",
            )
            raise RuntimeError(
                f"racecheck: double acquire of {self.name!r}"
            )
        self._check_order()
        _maybe_yield()
        if timeout == -1:
            got = self._real.acquire(blocking)
        else:
            got = self._real.acquire(blocking, timeout)
        if got:
            self._owner = me
            self._count += 1
            stack = getattr(_held, "stack", None)
            if stack is None:
                stack = _held.stack = []
            stack.append(self)
        return got

    def release(self):
        me = threading.current_thread()
        if self._owner is not me:
            owner = self._owner.name if self._owner else "<nobody>"
            _record(
                "non-owner-release",
                f"{me.name} releases {self.name!r} held by {owner}",
            )
        self._count -= 1
        if self._count <= 0:
            self._owner = None
        stack = getattr(_held, "stack", None)
        if stack and self in stack:
            stack.remove(self)
        self._real.release()
        _maybe_yield()

    def locked(self):
        return self._real.locked()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False


class CheckedRLock(CheckedLock):
    _REENTRANT = True


class CheckedEvent:
    """Event proxy: seeded yields around set() (the cross-thread handoff
    edge the injector most wants to perturb)."""

    def __init__(self, real):
        self._real = real

    def set(self):
        _maybe_yield()
        self._real.set()

    def clear(self):
        self._real.clear()

    def is_set(self):
        return self._real.is_set()

    def wait(self, timeout=None):
        return self._real.wait(timeout)


def install(seed: int = 0, extra_prefixes=()) -> bool:
    """Patch threading's factories; False (no-op) unless SPT_RACE=1."""
    if os.environ.get("SPT_RACE") != "1" or _state["installed"]:
        return False
    orig_lock, orig_rlock = threading.Lock, threading.RLock
    orig_event = threading.Event

    def make_lock():
        if not _should_wrap(extra_prefixes):
            return orig_lock()
        with _state["lock"]:
            _state["locks_created"] += 1
            n = _state["locks_created"]
        name = f"{_caller_module(2)}#L{n}"
        return CheckedLock(orig_lock(), name)

    def make_rlock():
        if not _should_wrap(extra_prefixes):
            return orig_rlock()
        with _state["lock"]:
            _state["locks_created"] += 1
            n = _state["locks_created"]
        name = f"{_caller_module(2)}#R{n}"
        return CheckedRLock(orig_rlock(), name)

    def make_event():
        if not _should_wrap(extra_prefixes):
            return orig_event()
        _state["events_created"] += 1
        return CheckedEvent(orig_event())

    _state["orig"] = {
        "Lock": orig_lock, "RLock": orig_rlock, "Event": orig_event,
    }
    threading.Lock = make_lock
    threading.RLock = make_rlock
    threading.Event = make_event
    _state["rng"] = random.Random(seed)
    _state["edges"].clear()
    _state["violations"].clear()
    _state["locks_created"] = 0
    _state["events_created"] = 0
    _state["yields"] = 0
    _state["installed"] = True
    return True


def uninstall():
    if not _state["installed"]:
        return
    threading.Lock = _state["orig"]["Lock"]
    threading.RLock = _state["orig"]["RLock"]
    threading.Event = _state["orig"]["Event"]
    _state["rng"] = None
    _state["installed"] = False


def violations():
    with _state["lock"]:
        return list(_state["violations"])


def report() -> dict:
    with _state["lock"]:
        return {
            "violations": list(_state["violations"]),
            "locks_created": _state["locks_created"],
            "events_created": _state["events_created"],
            "order_edges": len(_state["edges"]),
            "yields": _state["yields"],
        }
