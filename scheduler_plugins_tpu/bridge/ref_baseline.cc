// Compiled reference-shaped sequential baselines for the five BASELINE.md
// configs — the honest denominator for the bench's `vs_compiled_baseline`
// column (VERDICT r2 item 3: a pure-Python loop flatters the ≥50× north
// star; the reference is compiled Go, so the denominator must be compiled).
//
// Each function mirrors the ALGORITHMIC SHAPE of the reference's hot loop —
// a per-pod × per-node sequential scan with plugin-specific filter/score
// math and post-placement commits — not this repo's tensor formulation:
//   cfg1  NodeResourcesAllocatable score + fit
//         (/root/reference/pkg/noderesources/resource_allocation.go:49-76,
//          allocatable.go:117-168)
//   cfg2  Trimaran TargetLoadPacking piecewise curve + LoadVariationRisk
//         (/root/reference/pkg/trimaran/targetloadpacking/targetloadpacking.go
//          :170-205, loadvariationriskbalancing/analysis.go:34-60)
//   cfg3  NUMA single-numa zone bitmask fit + LeastAllocated min-over-zones
//         (/root/reference/pkg/noderesourcetopology/filter.go:90-160,
//          least_allocated.go:25-55, score.go:110-124) with the OverReserve
//          pessimistic all-zone deduction (cache/store.go:129-160)
//   cfg4  ElasticQuota own-Max / aggregate-Min admission + allocatable score
//         (/root/reference/pkg/capacityscheduling/capacity_scheduling.go
//          :208-282, elasticquota.go:189-221)
//   cfg5  NetworkOverhead dependency satisfied/violated tallies + cost
//         accumulation (/root/reference/pkg/networkaware/networkoverhead/
//          networkoverhead.go:500-638)
//
// Build: make native  (or auto-built on first use by bridge/ref_baseline.py)

#include <cstdint>
#include <cstring>
#include <vector>

namespace {

// Go integer division truncates toward zero (the reference's score math is
// int64 end to end — allocatable.go:126).
inline int64_t godiv(int64_t a, int64_t b) { return b == 0 ? 0 : a / b; }

// Shared min-max normalize + argmax + commit tail: pick the best feasible
// node (max normalized score, lowest index tie-break) and subtract the
// request from its free row. raw scores follow Least mode (negated weighted
// sum), normalized to [0,100] over the feasible set (allocatable.go:143-168).
inline int32_t pick_and_commit(
    int64_t n_nodes, int64_t n_res, const int64_t* req_row,
    std::vector<int64_t>& free_flat, const std::vector<char>& feasible,
    const std::vector<int64_t>& raw) {
  int64_t lo = 0, hi = 0;
  bool any = false;
  for (int64_t n = 0; n < n_nodes; ++n) {
    if (!feasible[n]) continue;
    if (!any) { lo = hi = raw[n]; any = true; }
    else { if (raw[n] < lo) lo = raw[n]; if (raw[n] > hi) hi = raw[n]; }
  }
  if (!any) return -1;
  int32_t best = -1;
  int64_t best_score = -1;
  for (int64_t n = 0; n < n_nodes; ++n) {
    if (!feasible[n]) continue;
    int64_t score = hi == lo ? 0 : godiv((raw[n] - lo) * 100, hi - lo);
    if (score > best_score) { best_score = score; best = (int32_t)n; }
  }
  int64_t* f = &free_flat[(int64_t)best * n_res];
  for (int64_t r = 0; r < n_res; ++r) f[r] -= req_row[r];
  return best;
}

}  // namespace

extern "C" {

// -- config 1: allocatable-scored placement ---------------------------------
// free0 (N,R) initial free capacity; req (P,R) effective requests with the
// "pods" column already set to 1; weights (R,). Returns placed count.
int64_t ref_seq_alloc(int64_t N, int64_t P, int64_t R,
                      const int64_t* alloc, const int64_t* free0,
                      const int64_t* req, const int64_t* weights,
                      int32_t* out_assign) {
  std::vector<int64_t> free_flat(free0, free0 + N * R);
  int64_t wsum = 0;
  for (int64_t r = 0; r < R; ++r) wsum += weights[r];
  std::vector<char> feasible(N);
  std::vector<int64_t> raw(N);
  int64_t placed = 0;
  for (int64_t p = 0; p < P; ++p) {
    const int64_t* rq = &req[p * R];
    for (int64_t n = 0; n < N; ++n) {
      const int64_t* f = &free_flat[n * R];
      char ok = 1;
      for (int64_t r = 0; r < R; ++r) ok &= (char)(f[r] >= rq[r]);
      feasible[n] = ok;
      // the reference recomputes the weighted allocatable sum per (pod,
      // node) Score invocation (resource_allocation.go:49-76)
      int64_t s = 0;
      for (int64_t r = 0; r < R; ++r) s += weights[r] * alloc[n * R + r];
      raw[n] = -godiv(s, wsum);  // Least mode
    }
    int32_t choice = pick_and_commit(N, R, rq, free_flat, feasible, raw);
    out_assign[p] = choice;
    placed += choice >= 0;
  }
  return placed;
}

// -- config 2: trimaran TLP + LVRB ------------------------------------------
// cpu metrics in percent of capacity; pred_millis (P,) the TLP per-pod CPU
// prediction; missing (N,) ScheduledPodsCache compensation millis.
int64_t ref_seq_trimaran(int64_t N, int64_t P, int64_t R,
                         const int64_t* free0, const int64_t* req,
                         const int64_t* cpu_cap, const double* cpu_tlp,
                         const unsigned char* cpu_valid,
                         const double* cpu_avg, const double* cpu_std,
                         const double* mem_avg, const double* mem_std,
                         const int64_t* missing, const int64_t* pred_millis,
                         double target, double margin, double sensitivity,
                         int32_t* out_assign) {
  std::vector<int64_t> free_flat(free0, free0 + N * R);
  std::vector<char> feasible(N);
  std::vector<int64_t> raw(N);
  std::vector<int64_t> missing_live(missing, missing + N);
  int64_t placed = 0;
  for (int64_t p = 0; p < P; ++p) {
    const int64_t* rq = &req[p * R];
    for (int64_t n = 0; n < N; ++n) {
      const int64_t* f = &free_flat[n * R];
      char ok = 1;
      for (int64_t r = 0; r < R; ++r) ok &= (char)(f[r] >= rq[r]);
      feasible[n] = ok;
      // TargetLoadPacking piecewise curve (targetloadpacking.go:147-196)
      double tlp = 0;
      if (cpu_valid[n] && cpu_cap[n] > 0) {
        double measured = cpu_tlp[n] * (double)cpu_cap[n] / 100.0;
        double predicted =
            measured + (double)missing_live[n] + (double)pred_millis[p];
        double U = 100.0 * predicted / (double)cpu_cap[n];
        if (U <= target)
          tlp = (100.0 - target) * U / target + target;
        else if (U <= 100.0)
          tlp = target * (100.0 - U) / (100.0 - target);
      }
      // LoadVariationRiskBalancing (analysis.go:34-60): per-resource risk =
      // (mu + sigma^(1/sensitivity) * margin) / 2 clamped, score = min
      double mu_c = cpu_avg[n] / 100.0, sg_c = cpu_std[n] / 100.0;
      double mu_m = mem_avg[n] / 100.0, sg_m = mem_std[n] / 100.0;
      auto risk = [&](double mu, double sg) {
        // Go analysis.go:48-50: the root applies for sensitivity >= 0
        // (1/0 = +Inf, pow(x, inf) = 0 for x < 1); negative skips it
        double s = sensitivity >= 0 ? __builtin_pow(sg, 1.0 / sensitivity) : sg;
        double v = (mu + s * margin) / 2.0;
        return v < 0 ? 0.0 : (v > 1 ? 1.0 : v);
      };
      double lvrb_c = (1.0 - risk(mu_c, sg_c)) * 100.0;
      double lvrb_m = (1.0 - risk(mu_m, sg_m)) * 100.0;
      double lvrb = lvrb_c < lvrb_m ? lvrb_c : lvrb_m;
      raw[n] = (int64_t)(tlp + lvrb);
    }
    int32_t choice = pick_and_commit(N, R, rq, free_flat, feasible, raw);
    out_assign[p] = choice;
    placed += choice >= 0;
    if (choice >= 0) missing_live[choice] += pred_millis[p];
  }
  return placed;
}

// -- config 3: NUMA single-numa fit + LeastAllocated ------------------------
// zavail (N,Z,R) zone available; zalloc (N,Z,R) zone allocatable;
// zone_mask (N,Z); reported (N,Z,R). Pessimistic all-zone deduction on
// commit (cache/store.go:129-160).
int64_t ref_seq_numa(int64_t N, int64_t P, int64_t R, int64_t Z,
                     const int64_t* free0, const int64_t* req,
                     const int64_t* zavail0, const int64_t* zalloc,
                     const unsigned char* zone_mask,
                     const unsigned char* reported,
                     int32_t* out_assign) {
  std::vector<int64_t> free_flat(free0, free0 + N * R);
  std::vector<int64_t> zavail(zavail0, zavail0 + N * Z * R);
  std::vector<char> feasible(N);
  std::vector<int64_t> raw(N);
  int64_t placed = 0;
  for (int64_t p = 0; p < P; ++p) {
    const int64_t* rq = &req[p * R];
    for (int64_t n = 0; n < N; ++n) {
      const int64_t* f = &free_flat[n * R];
      char fit = 1;
      for (int64_t r = 0; r < R; ++r) fit &= (char)(f[r] >= rq[r]);
      // zone bitmask AND over per-resource feasibility (filter.go:90-160)
      uint64_t bitmask = 0;
      int64_t worst_zone_score = -1;  // min over zones (score.go:110-124)
      bool any_zone = false;
      for (int64_t z = 0; z < Z; ++z) {
        if (!zone_mask[n * Z + z]) continue;
        const int64_t* za = &zavail[(n * Z + z) * R];
        const int64_t* zl = &zalloc[(n * Z + z) * R];
        const unsigned char* rep = &reported[(n * Z + z) * R];
        char zok = 1;
        int64_t zscore_sum = 0, zscore_cnt = 0;
        for (int64_t r = 0; r < R; ++r) {
          if (rq[r] <= 0 || !rep[r]) continue;
          zok &= (char)(za[r] >= rq[r]);
          // LeastAllocated per resource: (alloc - used') * 100 / alloc
          int64_t used_after = zl[r] - za[r] + rq[r];
          zscore_sum += godiv((zl[r] - used_after) * 100, zl[r]);
          zscore_cnt += 1;
        }
        if (zok) {
          bitmask |= (uint64_t)1 << z;
          any_zone = true;
        }
        int64_t zscore = zscore_cnt ? godiv(zscore_sum, zscore_cnt) : 100;
        if (worst_zone_score < 0 || zscore < worst_zone_score)
          worst_zone_score = zscore;
      }
      feasible[n] = fit && any_zone;
      raw[n] = worst_zone_score < 0 ? 0 : worst_zone_score;
    }
    // argmax over feasible (scores already 0..100; no re-normalize in the
    // NUMA score path — score.go returns strategy output directly)
    int32_t best = -1;
    int64_t best_score = -1;
    for (int64_t n = 0; n < N; ++n) {
      if (!feasible[n]) continue;
      if (raw[n] > best_score) { best_score = raw[n]; best = (int32_t)n; }
    }
    out_assign[p] = best;
    if (best >= 0) {
      placed += 1;
      int64_t* f = &free_flat[(int64_t)best * R];
      for (int64_t r = 0; r < R; ++r) f[r] -= rq[r];
      for (int64_t z = 0; z < Z; ++z) {
        if (!zone_mask[(int64_t)best * Z + z]) continue;
        int64_t* za = &zavail[((int64_t)best * Z + z) * R];
        const unsigned char* rep = &reported[((int64_t)best * Z + z) * R];
        for (int64_t r = 0; r < R; ++r)
          if (rep[r]) za[r] -= rq[r];  // pessimistic all-zone deduction
      }
    }
  }
  return placed;
}

// -- config 4: gang + elastic quota + allocatable ---------------------------
// ns_of_pod (P,) quota-namespace row (-1 none); q_min/q_max/q_used (M,R);
// gang_of_pod (P,), gang_min (G,), gang_assigned (G,) pre-assigned counts.
// Quota admission: used+req <= Max(own) AND agg_used+req <= agg_min
// (capacity_scheduling.go:273-279); gang quorum evaluated per placement
// tally like Permit (core.go:308-345) — pods failing quorum at the end
// still count as placed-this-cycle (they Wait, they are not rejected).
int64_t ref_seq_gang_quota(int64_t N, int64_t P, int64_t R,
                           const int64_t* alloc, const int64_t* free0,
                           const int64_t* req, const int64_t* quota_req,
                           const int64_t* weights,
                           const int64_t* ns_of_pod, int64_t M,
                           const int64_t* q_min, const int64_t* q_max,
                           const unsigned char* has_quota,
                           const int64_t* q_used0,
                           const int64_t* gang_of_pod, int64_t G,
                           const int64_t* gang_min,
                           const int64_t* gang_assigned,
                           int32_t* out_assign, int32_t* out_wait) {
  std::vector<int64_t> free_flat(free0, free0 + N * R);
  std::vector<int64_t> used(q_used0, q_used0 + M * R);
  std::vector<int64_t> agg_min(R, 0), agg_used(R, 0);
  for (int64_t m = 0; m < M; ++m) {
    if (!has_quota[m]) continue;
    for (int64_t r = 0; r < R; ++r) {
      agg_min[r] += q_min[m * R + r];
      agg_used[r] += q_used0[m * R + r];
    }
  }
  int64_t wsum = 0;
  for (int64_t r = 0; r < R; ++r) wsum += weights[r];
  std::vector<int64_t> gang_sched(G, 0);
  std::vector<char> feasible(N);
  std::vector<int64_t> raw(N);
  int64_t placed = 0;
  for (int64_t p = 0; p < P; ++p) {
    const int64_t* rq = &req[p * R];
    const int64_t* qrq = &quota_req[p * R];  // raw request: pods slot 0
    int64_t ns = ns_of_pod[p];
    // PreFilter: elastic quota admission (absent Max entries arrive as
    // int64 max, absent Min as 0 — the snapshot builder's encoding)
    if (ns >= 0 && has_quota[ns]) {
      char ok = 1;
      for (int64_t r = 0; r < R; ++r) {
        ok &= (char)(used[ns * R + r] + qrq[r] <= q_max[ns * R + r]);
        ok &= (char)(agg_used[r] + qrq[r] <= agg_min[r]);
      }
      if (!ok) { out_assign[p] = -1; out_wait[p] = 0; continue; }
    }
    for (int64_t n = 0; n < N; ++n) {
      const int64_t* f = &free_flat[n * R];
      char ok = 1;
      for (int64_t r = 0; r < R; ++r) ok &= (char)(f[r] >= rq[r]);
      feasible[n] = ok;
      int64_t s = 0;
      for (int64_t r = 0; r < R; ++r) s += weights[r] * alloc[n * R + r];
      raw[n] = -godiv(s, wsum);
    }
    int32_t choice = pick_and_commit(N, R, rq, free_flat, feasible, raw);
    out_assign[p] = choice;
    out_wait[p] = 0;
    if (choice >= 0) {
      placed += 1;
      if (ns >= 0 && has_quota[ns])
        for (int64_t r = 0; r < R; ++r) {
          used[ns * R + r] += qrq[r];
          agg_used[r] += qrq[r];
        }
      int64_t g = gang_of_pod[p];
      if (g >= 0) gang_sched[g] += 1;
    }
  }
  // Permit: gang quorum
  for (int64_t p = 0; p < P; ++p) {
    int64_t g = gang_of_pod[p];
    if (out_assign[p] >= 0 && g >= 0)
      out_wait[p] = gang_assigned[g] + gang_sched[g] < gang_min[g];
  }
  return placed;
}

// -- config 5: network overhead ---------------------------------------------
// Costs (networkoverhead.go:576-638): same node 0; same zone 1; same region
// different zone -> zone_cost lookup (missing: cost MaxCost, no count);
// different region -> region_cost lookup; unlocated placed pod -> violated +
// MaxCost. Filter drops a node when violated > satisfied (:326-359); score
// is accumulated cost, lowest wins (inverted normalize).
int64_t ref_seq_network(int64_t N, int64_t P, int64_t R,
                        const int64_t* free0, const int64_t* req,
                        const int32_t* node_zone, const int32_t* node_region,
                        int64_t ZC, int64_t RC, const int32_t* zone_region,
                        const int64_t* zone_cost, const int64_t* region_cost,
                        int64_t W, const int64_t* placed0,
                        const int32_t* pod_wl, int64_t D,
                        const int32_t* dep_wl, const int64_t* dep_cost,
                        const unsigned char* dep_mask,
                        int32_t* out_assign) {
  const int64_t MAX_COST = 100;
  std::vector<int64_t> free_flat(free0, free0 + N * R);
  std::vector<int64_t> placed_wn(placed0, placed0 + W * N);
  std::vector<char> feasible(N);
  std::vector<int64_t> cost_acc(N), sat(N), vio(N);
  std::vector<int64_t> dep_zone_cnt(ZC), dep_region_noz(RC);
  int64_t placed = 0;
  for (int64_t p = 0; p < P; ++p) {
    const int64_t* rq = &req[p * R];
    for (int64_t n = 0; n < N; ++n) {
      const int64_t* f = &free_flat[n * R];
      char ok = 1;
      for (int64_t r = 0; r < R; ++r) ok &= (char)(f[r] >= rq[r]);
      feasible[n] = ok;
      cost_acc[n] = 0; sat[n] = 0; vio[n] = 0;
    }
    for (int64_t d = 0; d < D; ++d) {
      if (!dep_mask[p * D + d]) continue;
      int64_t w = dep_wl[p * D + d];
      int64_t maxc = dep_cost[p * D + d];
      const int64_t* pw = &placed_wn[w * N];
      // aggregate this dependency's placed pods by location
      std::fill(dep_zone_cnt.begin(), dep_zone_cnt.end(), 0);
      std::fill(dep_region_noz.begin(), dep_region_noz.end(), 0);
      int64_t unloc = 0;
      for (int64_t m = 0; m < N; ++m) {
        if (pw[m] == 0) continue;
        if (node_zone[m] >= 0) dep_zone_cnt[node_zone[m]] += pw[m];
        else if (node_region[m] >= 0) dep_region_noz[node_region[m]] += pw[m];
        else unloc += pw[m];
      }
      for (int64_t n = 0; n < N; ++n) {
        int64_t same_node = pw[n];
        int32_t nz = node_zone[n], nr = node_region[n];
        sat[n] += same_node;  // cost 0
        for (int64_t z = 0; z < ZC; ++z) {
          int64_t cnt = dep_zone_cnt[z] - (nz == (int32_t)z ? same_node : 0);
          if (cnt == 0) continue;
          int64_t c;
          if (nz == (int32_t)z) {
            c = 1;  // same zone
            sat[n] += cnt;
          } else if (nz >= 0 && nr >= 0 && zone_region[z] == nr) {
            c = zone_cost[(int64_t)nz * ZC + z];
            if (c < 0) c = MAX_COST;  // missing pair: cost only
            else { if (c <= maxc) sat[n] += cnt; else vio[n] += cnt; }
          } else if (nr >= 0 && zone_region[z] >= 0) {
            c = region_cost[(int64_t)nr * RC + zone_region[z]];
            if (c < 0) c = MAX_COST;
            else { if (c <= maxc) sat[n] += cnt; else vio[n] += cnt; }
          } else {
            c = MAX_COST;
            vio[n] += cnt;
          }
          cost_acc[n] += c * cnt;
        }
        for (int64_t rg = 0; rg < RC; ++rg) {
          int64_t cnt = dep_region_noz[rg];
          if (cnt == 0) continue;
          int64_t c;
          if (nr >= 0) {
            if (nr == (int32_t)rg) c = 1;
            else c = region_cost[(int64_t)nr * RC + rg];
            if (c < 0) { c = MAX_COST; vio[n] += cnt; }
            else { if (c <= maxc) sat[n] += cnt; else vio[n] += cnt; }
          } else { c = MAX_COST; vio[n] += cnt; }
          cost_acc[n] += c * cnt;
        }
        if (unloc) { vio[n] += unloc; cost_acc[n] += MAX_COST * unloc; }
      }
    }
    int32_t best = -1;
    int64_t best_cost = 0;
    for (int64_t n = 0; n < N; ++n) {
      if (!feasible[n] || vio[n] > sat[n]) continue;
      if (best < 0 || cost_acc[n] < best_cost) {
        best = (int32_t)n;
        best_cost = cost_acc[n];
      }
    }
    out_assign[p] = best;
    if (best >= 0) {
      placed += 1;
      int64_t* f = &free_flat[(int64_t)best * R];
      for (int64_t r = 0; r < R; ++r) f[r] -= rq[r];
      if (pod_wl[p] >= 0) placed_wn[(int64_t)pod_wl[p] * N + best] += 1;
    }
  }
  return placed;
}

}  // extern "C"
