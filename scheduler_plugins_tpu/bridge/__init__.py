"""Native bridge: C++ columnar cluster-state store behind a ctypes ABI.

The event-ingestion/snapshot-lowering hot path of the host shell — the part
the reference implements as Go informer caches and the north star recasts as
a bridge feeding the TPU solver (SURVEY.md §2.9) — implemented in C++
(`snapshot_store.cc`) and consumed here without per-object Python overhead.
The shared library builds on first use with g++ (cached next to the source).
"""

from __future__ import annotations

import ctypes
import subprocess
from pathlib import Path

import numpy as np

_SRC = Path(__file__).with_name("snapshot_store.cc")
_LIB = Path(__file__).with_name("libsnapshot_store.so")

_I64 = ctypes.POINTER(ctypes.c_int64)
_I32 = ctypes.POINTER(ctypes.c_int32)


def _build() -> Path:
    if _LIB.exists() and _LIB.stat().st_mtime >= _SRC.stat().st_mtime:
        return _LIB
    subprocess.run(
        ["g++", "-O2", "-std=c++17", "-shared", "-fPIC", str(_SRC), "-o", str(_LIB)],
        check=True,
        capture_output=True,
    )
    return _LIB


def _load():
    lib = ctypes.CDLL(str(_build()))
    lib.store_new.restype = ctypes.c_void_p
    lib.store_new.argtypes = [ctypes.c_int]
    lib.store_free.argtypes = [ctypes.c_void_p]
    lib.store_upsert_node.argtypes = [ctypes.c_void_p, ctypes.c_int64, _I64, _I64]
    lib.store_upsert_pod.argtypes = [
        ctypes.c_void_p, ctypes.c_int64, _I64, _I64,
        ctypes.c_int64, ctypes.c_int64, ctypes.c_int64, ctypes.c_int64,
    ]
    lib.store_upsert_nodes_batch.argtypes = [
        ctypes.c_void_p, ctypes.c_int64, _I64, _I64, _I64,
    ]
    lib.store_upsert_pods_batch.argtypes = [
        ctypes.c_void_p, ctypes.c_int64] + [_I64] * 7
    lib.store_bind.argtypes = [ctypes.c_void_p, ctypes.c_int64, ctypes.c_int64]
    lib.store_delete_pod.argtypes = [ctypes.c_void_p, ctypes.c_int64]
    lib.store_num_nodes.restype = ctypes.c_int64
    lib.store_num_nodes.argtypes = [ctypes.c_void_p]
    lib.store_num_pending.restype = ctypes.c_int64
    lib.store_num_pending.argtypes = [ctypes.c_void_p]
    lib.store_export_nodes.argtypes = [ctypes.c_void_p] + [_I64] * 6 + [_I32] * 2
    lib.store_export_pending.argtypes = [ctypes.c_void_p] + [_I64] * 5
    lib.store_dirty_count.restype = ctypes.c_int64
    lib.store_dirty_count.argtypes = [ctypes.c_void_p]
    lib.store_generation.restype = ctypes.c_int64
    lib.store_generation.argtypes = [ctypes.c_void_p]
    lib.store_export_dirty.restype = ctypes.c_int64
    lib.store_export_dirty.argtypes = (
        [ctypes.c_void_p] + [_I64] * 6 + [_I32] * 2
    )
    return lib


_lib = None


def _get_lib():
    global _lib
    if _lib is None:
        _lib = _load()
    return _lib


def _ptr64(arr: np.ndarray):
    return arr.ctypes.data_as(_I64)


def _ptr32(arr: np.ndarray):
    return arr.ctypes.data_as(_I32)


class NativeStore:
    """Columnar cluster store (C++). Quantities are int64 vectors on the
    fixed resource axis (cpu-milli, memory-bytes, ephemeral, pods, ...)."""

    def __init__(self, num_resources: int):
        self._lib = _get_lib()
        self.R = num_resources
        self._handle = ctypes.c_void_p(self._lib.store_new(num_resources))

    def close(self):
        if self._handle:
            self._lib.store_free(self._handle)
            self._handle = None

    def __del__(self):  # pragma: no cover - GC path
        try:
            self.close()
        except Exception:  # graft-lint: ignore[GL010] — GC finalizer: nothing to route a close failure to
            pass

    def upsert_node(self, node_id: int, alloc: np.ndarray, capacity=None):
        alloc = np.ascontiguousarray(alloc, np.int64)
        cap = alloc if capacity is None else np.ascontiguousarray(capacity, np.int64)
        self._lib.store_upsert_node(self._handle, node_id, _ptr64(alloc), _ptr64(cap))

    def upsert_pod(self, pod_id: int, req, limits=None, priority=0,
                   creation_ms=0, node_id=-1, terminating=False):
        req = np.ascontiguousarray(req, np.int64)
        lim = (
            np.zeros_like(req)
            if limits is None
            else np.ascontiguousarray(limits, np.int64)
        )
        self._lib.store_upsert_pod(
            self._handle, pod_id, _ptr64(req), _ptr64(lim),
            priority, creation_ms, node_id, 1 if terminating else 0,
        )

    def upsert_nodes_batch(self, ids, alloc, capacity=None):
        ids = np.ascontiguousarray(ids, np.int64)
        alloc = np.ascontiguousarray(alloc, np.int64)
        cap = alloc if capacity is None else np.ascontiguousarray(capacity, np.int64)
        self._lib.store_upsert_nodes_batch(
            self._handle, len(ids), _ptr64(ids), _ptr64(alloc), _ptr64(cap)
        )

    def upsert_pods_batch(self, ids, req, limits=None, priority=None,
                          creation_ms=None, node_ids=None, flags=None):
        k = len(ids)
        ids = np.ascontiguousarray(ids, np.int64)
        req = np.ascontiguousarray(req, np.int64)
        z = lambda v, fill=0: np.ascontiguousarray(
            np.full(k, fill, np.int64) if v is None else v, np.int64
        )
        lim = np.zeros_like(req) if limits is None else np.ascontiguousarray(limits, np.int64)
        self._lib.store_upsert_pods_batch(
            self._handle, k, _ptr64(ids), _ptr64(req), _ptr64(lim),
            _ptr64(z(priority)), _ptr64(z(creation_ms)), _ptr64(z(node_ids, -1)),
            _ptr64(z(flags)),
        )

    def bind(self, pod_id: int, node_id: int):
        self._lib.store_bind(self._handle, pod_id, node_id)

    def delete_pod(self, pod_id: int):
        self._lib.store_delete_pod(self._handle, pod_id)

    @property
    def num_nodes(self) -> int:
        return self._lib.store_num_nodes(self._handle)

    @property
    def num_pending(self) -> int:
        return self._lib.store_num_pending(self._handle)

    def export_nodes(self):
        """Dense node tensors: dict of numpy arrays (ids, alloc, capacity,
        requested, nonzero_requested, limits, pod_count, terminating)."""
        n, R = self.num_nodes, self.R
        out = {
            "ids": np.zeros(n, np.int64),
            "alloc": np.zeros((n, R), np.int64),
            "capacity": np.zeros((n, R), np.int64),
            "requested": np.zeros((n, R), np.int64),
            "nonzero_requested": np.zeros((n, R), np.int64),
            "limits": np.zeros((n, R), np.int64),
            "pod_count": np.zeros(n, np.int32),
            "terminating": np.zeros(n, np.int32),
        }
        self._lib.store_export_nodes(
            self._handle, _ptr64(out["ids"]), _ptr64(out["alloc"]),
            _ptr64(out["capacity"]), _ptr64(out["requested"]),
            _ptr64(out["nonzero_requested"]), _ptr64(out["limits"]),
            _ptr32(out["pod_count"]), _ptr32(out["terminating"]),
        )
        return out

    @property
    def dirty_count(self) -> int:
        """Rows touched since the last `export_dirty` drain."""
        return self._lib.store_dirty_count(self._handle)

    @property
    def generation(self) -> int:
        """Drain generation (bumped by every `export_dirty`)."""
        return self._lib.store_generation(self._handle)

    def export_dirty(self):
        """Streaming-delta export: ONLY the node rows whose columns
        changed since the last drain (first-touch order) — the
        O(changed) bridge seam a downstream mirror ingests instead of
        the O(cluster) `export_nodes`. Clears the dirty window and
        bumps `generation` (single-consumer semantics). A fresh store's
        first drain is a full resync by construction. Returns a dict of
        numpy arrays plus the post-drain generation."""
        n, R = self.dirty_count, self.R
        out = {
            "ids": np.zeros(n, np.int64),
            "alloc": np.zeros((n, R), np.int64),
            "capacity": np.zeros((n, R), np.int64),
            "requested": np.zeros((n, R), np.int64),
            "nonzero_requested": np.zeros((n, R), np.int64),
            "limits": np.zeros((n, R), np.int64),
            "pod_count": np.zeros(n, np.int32),
            "terminating": np.zeros(n, np.int32),
        }
        written = self._lib.store_export_dirty(
            self._handle, _ptr64(out["ids"]), _ptr64(out["alloc"]),
            _ptr64(out["capacity"]), _ptr64(out["requested"]),
            _ptr64(out["nonzero_requested"]), _ptr64(out["limits"]),
            _ptr32(out["pod_count"]), _ptr32(out["terminating"]),
        )
        assert written == n, (written, n)
        out["generation"] = self.generation
        return out

    def export_pending(self):
        """Pending-pod tensors in (creation_ms, id) queue order."""
        p, R = self.num_pending, self.R
        out = {
            "ids": np.zeros(p, np.int64),
            "req": np.zeros((p, R), np.int64),
            "limits": np.zeros((p, R), np.int64),
            "priority": np.zeros(p, np.int64),
            "creation_ms": np.zeros(p, np.int64),
        }
        self._lib.store_export_pending(
            self._handle, _ptr64(out["ids"]), _ptr64(out["req"]),
            _ptr64(out["limits"]), _ptr64(out["priority"]),
            _ptr64(out["creation_ms"]),
        )
        return out
