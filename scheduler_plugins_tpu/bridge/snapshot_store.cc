// Native cluster-state store: the bridge tier of the framework.
//
// The reference's cross-process feed is client-go informers hydrating Go
// object caches (SURVEY.md §2.9); the TPU-native equivalent is an event
// stream ("pod added/bound/deleted", "node upserted") applied to a compact
// columnar store that exports the scheduler's dense snapshot tensors
// without Python object traversal. This C ABI is consumed through ctypes
// (scheduler_plugins_tpu/bridge/__init__.py); a gRPC front end can feed the
// same ABI from a remote cluster agent.
//
// Layout contract (must match api.resources.CANONICAL):
//   slot 0 = cpu (millicores), slot 1 = memory (bytes),
//   slot 2 = ephemeral-storage, slot 3 = pods (count; requested tracks the
//   number of bound pods, pod demand is 1).
// Non-zero scoring defaults mirror the upstream NonZeroRequested accounting:
// 100 millicores / 200 MiB when a pod requests nothing.

#include <algorithm>
#include <cstdint>
#include <cstring>
#include <unordered_map>
#include <utility>
#include <vector>

namespace {

constexpr int kCpu = 0;
constexpr int kMemory = 1;
constexpr int kPods = 3;
constexpr int64_t kDefaultMilliCpu = 100;
constexpr int64_t kDefaultMemory = 200LL * 1024 * 1024;

struct Pod {
  std::vector<int64_t> req;
  std::vector<int64_t> limits;  // clamped to >= req on ingest
  int64_t priority = 0;
  int64_t creation_ms = 0;
  int64_t node = -1;  // bound node id, -1 pending
  bool terminating = false;
};

struct Store {
  int R;
  // node id -> dense row index; rows are append-only per id
  std::unordered_map<int64_t, int32_t> node_pos;
  std::vector<int64_t> node_ids;
  std::vector<int64_t> alloc;       // (N * R)
  std::vector<int64_t> capacity;    // (N * R)
  std::vector<int64_t> requested;   // (N * R)
  std::vector<int64_t> nonzero;     // (N * R)
  std::vector<int64_t> limits;      // (N * R)
  std::vector<int32_t> pod_count;   // (N)
  std::vector<int32_t> terminating; // (N)
  std::unordered_map<int64_t, Pod> pods;
  // Streaming-delta export (the O(changed) bridge seam): rows whose
  // columns changed since the last drain, first-touch ordered. A drain
  // exports ONLY these rows and bumps `generation`, so a downstream
  // mirror (serve engine, remote shard) ingests O(changed) per cycle
  // instead of the O(cluster) full export. A fresh store marks every
  // row dirty as it hydrates — a new consumer's first drain is a full
  // resync by construction.
  std::vector<int32_t> dirty_rows;  // first-touch order, unique
  std::vector<uint8_t> dirty_flag;  // (N)
  int64_t generation = 0;

  explicit Store(int r) : R(r) {}

  void MarkDirty(int32_t row) {
    if (row >= static_cast<int32_t>(dirty_flag.size()))
      dirty_flag.resize(row + 1, 0);
    if (!dirty_flag[row]) {
      dirty_flag[row] = 1;
      dirty_rows.push_back(row);
    }
  }

  int32_t NodeRow(int64_t id) {
    auto it = node_pos.find(id);
    if (it != node_pos.end()) return it->second;
    int32_t row = static_cast<int32_t>(node_ids.size());
    node_pos.emplace(id, row);
    node_ids.push_back(id);
    alloc.resize(alloc.size() + R, 0);
    capacity.resize(capacity.size() + R, 0);
    requested.resize(requested.size() + R, 0);
    nonzero.resize(nonzero.size() + R, 0);
    limits.resize(limits.size() + R, 0);
    pod_count.push_back(0);
    terminating.push_back(0);
    MarkDirty(row);
    return row;
  }

  void NonZero(const int64_t* req, int64_t* out) const {
    std::memcpy(out, req, sizeof(int64_t) * R);
    if (out[kCpu] == 0) out[kCpu] = kDefaultMilliCpu;
    if (out[kMemory] == 0) out[kMemory] = kDefaultMemory;
  }

  void Apply(int32_t row, const Pod& pod, int sign) {
    MarkDirty(row);
    int64_t* rq = requested.data() + static_cast<size_t>(row) * R;
    int64_t* nz = nonzero.data() + static_cast<size_t>(row) * R;
    int64_t* lm = limits.data() + static_cast<size_t>(row) * R;
    std::vector<int64_t> nonzero_req(R);
    NonZero(pod.req.data(), nonzero_req.data());
    for (int r = 0; r < R; ++r) {
      rq[r] += sign * pod.req[r];
      nz[r] += sign * nonzero_req[r];
      lm[r] += sign * pod.limits[r];
    }
    pod_count[row] += sign;
    rq[kPods] = pod_count[row];
    nz[kPods] = pod_count[row];
    if (pod.terminating) terminating[row] += sign;
  }
};

}  // namespace

extern "C" {

void* store_new(int r) { return new Store(r); }

void store_free(void* handle) { delete static_cast<Store*>(handle); }

void store_upsert_node(void* handle, int64_t id, const int64_t* alloc,
                       const int64_t* capacity) {
  Store* s = static_cast<Store*>(handle);
  int32_t row = s->NodeRow(id);
  s->MarkDirty(row);
  std::memcpy(s->alloc.data() + static_cast<size_t>(row) * s->R, alloc,
              sizeof(int64_t) * s->R);
  std::memcpy(s->capacity.data() + static_cast<size_t>(row) * s->R, capacity,
              sizeof(int64_t) * s->R);
}

// flags bit 0: terminating
void store_upsert_pod(void* handle, int64_t id, const int64_t* req,
                      const int64_t* lim, int64_t priority,
                      int64_t creation_ms, int64_t node_id, int64_t flags) {
  Store* s = static_cast<Store*>(handle);
  auto it = s->pods.find(id);
  if (it != s->pods.end()) {
    // remove the previous incarnation's contribution first
    if (it->second.node >= 0) {
      auto row = s->node_pos.find(it->second.node);
      if (row != s->node_pos.end()) s->Apply(row->second, it->second, -1);
    }
    s->pods.erase(it);
  }
  Pod pod;
  pod.req.assign(req, req + s->R);
  pod.limits.resize(s->R);
  for (int r = 0; r < s->R; ++r)
    pod.limits[r] = lim[r] > req[r] ? lim[r] : req[r];
  pod.priority = priority;
  pod.creation_ms = creation_ms;
  pod.node = node_id;
  pod.terminating = (flags & 1) != 0;
  if (node_id >= 0) {
    int32_t row = s->NodeRow(node_id);
    s->Apply(row, pod, +1);
  }
  s->pods.emplace(id, std::move(pod));
}

void store_bind(void* handle, int64_t pod_id, int64_t node_id) {
  Store* s = static_cast<Store*>(handle);
  auto it = s->pods.find(pod_id);
  if (it == s->pods.end() || it->second.node >= 0) return;
  it->second.node = node_id;
  s->Apply(s->NodeRow(node_id), it->second, +1);
}

void store_delete_pod(void* handle, int64_t pod_id) {
  Store* s = static_cast<Store*>(handle);
  auto it = s->pods.find(pod_id);
  if (it == s->pods.end()) return;
  if (it->second.node >= 0) {
    auto row = s->node_pos.find(it->second.node);
    if (row != s->node_pos.end()) s->Apply(row->second, it->second, -1);
  }
  s->pods.erase(it);
}

// Batched ingestion — the wire-protocol shape: one call applies a whole
// event batch (K nodes or K pods) without per-event FFI crossings.
void store_upsert_nodes_batch(void* handle, int64_t k, const int64_t* ids,
                              const int64_t* alloc, const int64_t* capacity) {
  Store* s = static_cast<Store*>(handle);
  for (int64_t i = 0; i < k; ++i) {
    int32_t row = s->NodeRow(ids[i]);
    s->MarkDirty(row);
    std::memcpy(s->alloc.data() + static_cast<size_t>(row) * s->R,
                alloc + i * s->R, sizeof(int64_t) * s->R);
    std::memcpy(s->capacity.data() + static_cast<size_t>(row) * s->R,
                capacity + i * s->R, sizeof(int64_t) * s->R);
  }
}

void store_upsert_pods_batch(void* handle, int64_t k, const int64_t* ids,
                             const int64_t* req, const int64_t* lim,
                             const int64_t* priority,
                             const int64_t* creation_ms,
                             const int64_t* node_ids, const int64_t* flags) {
  for (int64_t i = 0; i < k; ++i) {
    store_upsert_pod(handle, ids[i], req + i * static_cast<Store*>(handle)->R,
                     lim + i * static_cast<Store*>(handle)->R, priority[i],
                     creation_ms[i], node_ids[i], flags[i]);
  }
}

int64_t store_num_nodes(void* handle) {
  return static_cast<int64_t>(static_cast<Store*>(handle)->node_ids.size());
}

int64_t store_num_pending(void* handle) {
  Store* s = static_cast<Store*>(handle);
  int64_t n = 0;
  for (const auto& [id, pod] : s->pods)
    if (pod.node < 0 && !pod.terminating) ++n;
  return n;
}

// Fills caller-allocated buffers sized (num_nodes x R) / (num_nodes).
void store_export_nodes(void* handle, int64_t* ids, int64_t* alloc,
                        int64_t* capacity, int64_t* requested,
                        int64_t* nonzero, int64_t* limits, int32_t* pod_count,
                        int32_t* terminating) {
  Store* s = static_cast<Store*>(handle);
  size_t n = s->node_ids.size();
  std::memcpy(ids, s->node_ids.data(), sizeof(int64_t) * n);
  std::memcpy(alloc, s->alloc.data(), sizeof(int64_t) * n * s->R);
  std::memcpy(capacity, s->capacity.data(), sizeof(int64_t) * n * s->R);
  std::memcpy(requested, s->requested.data(), sizeof(int64_t) * n * s->R);
  std::memcpy(nonzero, s->nonzero.data(), sizeof(int64_t) * n * s->R);
  std::memcpy(limits, s->limits.data(), sizeof(int64_t) * n * s->R);
  std::memcpy(pod_count, s->pod_count.data(), sizeof(int32_t) * n);
  std::memcpy(terminating, s->terminating.data(), sizeof(int32_t) * n);
}

// -- streaming-delta export (O(changed) bridge seam) ------------------------

int64_t store_dirty_count(void* handle) {
  return static_cast<int64_t>(static_cast<Store*>(handle)->dirty_rows.size());
}

int64_t store_generation(void* handle) {
  return static_cast<Store*>(handle)->generation;
}

// Fills caller-allocated buffers sized (store_dirty_count() x R) /
// (store_dirty_count()) with ONLY the rows touched since the last drain
// (first-touch order), then clears the dirty set and bumps the
// generation. Returns the number of rows written. Single-consumer
// semantics: a drain consumes the delta window.
int64_t store_export_dirty(void* handle, int64_t* ids, int64_t* alloc,
                           int64_t* capacity, int64_t* requested,
                           int64_t* nonzero, int64_t* limits,
                           int32_t* pod_count, int32_t* terminating) {
  Store* s = static_cast<Store*>(handle);
  const size_t R = s->R;
  for (size_t i = 0; i < s->dirty_rows.size(); ++i) {
    const int32_t row = s->dirty_rows[i];
    ids[i] = s->node_ids[row];
    std::memcpy(alloc + i * R, s->alloc.data() + static_cast<size_t>(row) * R,
                sizeof(int64_t) * R);
    std::memcpy(capacity + i * R,
                s->capacity.data() + static_cast<size_t>(row) * R,
                sizeof(int64_t) * R);
    std::memcpy(requested + i * R,
                s->requested.data() + static_cast<size_t>(row) * R,
                sizeof(int64_t) * R);
    std::memcpy(nonzero + i * R,
                s->nonzero.data() + static_cast<size_t>(row) * R,
                sizeof(int64_t) * R);
    std::memcpy(limits + i * R,
                s->limits.data() + static_cast<size_t>(row) * R,
                sizeof(int64_t) * R);
    pod_count[i] = s->pod_count[row];
    terminating[i] = s->terminating[row];
    s->dirty_flag[row] = 0;
  }
  const int64_t n = static_cast<int64_t>(s->dirty_rows.size());
  s->dirty_rows.clear();
  ++s->generation;
  return n;
}

// Fills caller-allocated buffers sized (num_pending x R) / (num_pending),
// ordered by (creation_ms, id) — the default queue order.
void store_export_pending(void* handle, int64_t* ids, int64_t* req,
                          int64_t* limits, int64_t* priority,
                          int64_t* creation_ms) {
  Store* s = static_cast<Store*>(handle);
  std::vector<std::pair<int64_t, int64_t>> order;  // (creation, id)
  for (const auto& [id, pod] : s->pods)
    if (pod.node < 0 && !pod.terminating) order.emplace_back(pod.creation_ms, id);
  std::sort(order.begin(), order.end());
  for (size_t i = 0; i < order.size(); ++i) {
    const Pod& pod = s->pods.at(order[i].second);
    ids[i] = order[i].second;
    std::memcpy(req + i * s->R, pod.req.data(), sizeof(int64_t) * s->R);
    std::memcpy(limits + i * s->R, pod.limits.data(), sizeof(int64_t) * s->R);
    priority[i] = pod.priority;
    creation_ms[i] = pod.creation_ms;
  }
}

}  // extern "C"
