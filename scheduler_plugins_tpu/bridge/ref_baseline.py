"""ctypes driver for the compiled reference-shaped baselines
(`ref_baseline.cc`) — the honest denominator for `vs_compiled_baseline`.

The reference is compiled Go; a pure-Python loop as the only denominator
flatters every speedup multiplier (VERDICT r2 item 3). Each entry here runs
the full per-pod × per-node sequential scan in C++ on the SAME snapshot
tensors the TPU path consumes and returns (pods_per_sec, placed).
"""

from __future__ import annotations

import ctypes
import subprocess
import time
from pathlib import Path

import numpy as np

from scheduler_plugins_tpu.api.resources import CANONICAL

_SRC = Path(__file__).with_name("ref_baseline.cc")
_LIB = Path(__file__).with_name("libref_baseline.so")

_I64 = ctypes.POINTER(ctypes.c_int64)
_I32 = ctypes.POINTER(ctypes.c_int32)
_U8 = ctypes.POINTER(ctypes.c_uint8)
_F64 = ctypes.POINTER(ctypes.c_double)

_PODS_I = CANONICAL.index("pods")


def _build() -> Path:
    if _LIB.exists() and _LIB.stat().st_mtime >= _SRC.stat().st_mtime:
        return _LIB
    subprocess.run(
        ["g++", "-O2", "-std=c++17", "-shared", "-fPIC", str(_SRC), "-o", str(_LIB)],
        check=True,
        capture_output=True,
    )
    return _LIB


_lib = None


def _load():
    global _lib
    if _lib is not None:
        return _lib
    lib = ctypes.CDLL(str(_build()))
    c64, c32 = ctypes.c_int64, ctypes.c_int32
    lib.ref_seq_alloc.restype = c64
    lib.ref_seq_alloc.argtypes = [c64] * 3 + [_I64] * 4 + [_I32]
    lib.ref_seq_trimaran.restype = c64
    lib.ref_seq_trimaran.argtypes = (
        [c64] * 3 + [_I64] * 3 + [_F64, _U8] + [_F64] * 4 + [_I64] * 2
        + [ctypes.c_double] * 3 + [_I32]
    )
    lib.ref_seq_numa.restype = c64
    lib.ref_seq_numa.argtypes = [c64] * 4 + [_I64] * 4 + [_U8] * 2 + [_I32]
    lib.ref_seq_gang_quota.restype = c64
    lib.ref_seq_gang_quota.argtypes = (
        [c64] * 3 + [_I64] * 5 + [_I64, c64] + [_I64] * 2 + [_U8, _I64]
        + [_I64, c64] + [_I64] * 2 + [_I32] * 2
    )
    lib.ref_seq_network.restype = c64
    lib.ref_seq_network.argtypes = (
        [c64] * 3 + [_I64] * 2 + [_I32] * 2 + [c64, c64, _I32]
        + [_I64] * 2 + [c64, _I64] + [_I32, c64] + [_I32, _I64, _U8] + [_I32]
    )
    _lib = lib
    return lib


def _arr(a, dtype):
    return np.ascontiguousarray(np.asarray(a), dtype)


def _ptr(a):
    dt = {np.dtype(np.int64): _I64, np.dtype(np.int32): _I32,
          np.dtype(np.uint8): _U8, np.dtype(np.float64): _F64}[a.dtype]
    return a.ctypes.data_as(dt)


def _real_counts(snap, n_nodes, n_pods):
    """Trim padding: the baseline must scan the REAL cluster shape, not the
    snapshot's power-of-two padded buckets — otherwise the denominator does
    extra work per pod and the reported multiplier inflates. Padding rows are
    appended after the real rows, so mask prefixes give the real counts when
    the caller doesn't pass them."""
    if n_nodes is None:
        n_nodes = int(np.asarray(snap.nodes.mask).sum())
    if n_pods is None:
        n_pods = int(np.asarray(snap.pods.mask).sum())
    return n_nodes, n_pods


def _fit_inputs(snap, n_nodes=None, n_pods=None):
    """(alloc, free0, req) trimmed to the real (node, pod) rows, with
    unschedulable nodes fenced and the pods slot set to 1
    (ops.fit.pod_fit_demand semantics)."""
    n_nodes, n_pods = _real_counts(snap, n_nodes, n_pods)
    alloc = _arr(snap.nodes.alloc, np.int64)[:n_nodes]
    requested = _arr(snap.nodes.requested, np.int64)[:n_nodes]
    free0 = alloc - requested
    node_mask = _arr(snap.nodes.mask, np.uint8).astype(bool)[:n_nodes]
    free0[~node_mask] = -1  # cordoned/invalid: never feasible
    req = _arr(snap.pods.req, np.int64)[:n_pods].copy()
    req[:, _PODS_I] = 1
    pod_mask = _arr(snap.pods.mask, np.uint8).astype(bool)[:n_pods]
    req[~pod_mask] = np.iinfo(np.int64).max // 4  # gated rows never place
    return alloc, free0, req


def compiled_alloc_baseline(snap, weights, n_nodes=None, n_pods=None):
    """Config 1/flagship: allocatable Least score + fit (pods/s, placed)."""
    lib = _load()
    alloc, free0, req = _fit_inputs(snap, n_nodes, n_pods)
    N, R = alloc.shape
    P = req.shape[0]
    w = _arr(weights, np.int64)
    out = np.empty(P, np.int32)
    start = time.perf_counter()
    placed = lib.ref_seq_alloc(N, P, R, _ptr(alloc), _ptr(free0), _ptr(req),
                               _ptr(w), _ptr(out))
    elapsed = time.perf_counter() - start
    return P / elapsed, int(placed), out


def compiled_trimaran_baseline(snap, target=40.0, margin=1.0, sensitivity=1.0,
                               n_nodes=None, n_pods=None):
    """Config 2: TLP piecewise + LVRB risk scores over live metrics."""
    lib = _load()
    _, free0, req = _fit_inputs(snap, n_nodes, n_pods)
    N, R = free0.shape
    P = req.shape[0]
    m = snap.metrics
    cap = _arr(snap.nodes.capacity, np.int64)[:N, CANONICAL.index("cpu")]
    cpu_tlp = _arr(m.cpu_tlp, np.float64)[:N]
    cpu_valid = _arr(m.cpu_tlp_valid, np.uint8)[:N]
    cpu_avg = _arr(m.cpu_avg, np.float64)[:N]
    cpu_std = _arr(m.cpu_std, np.float64)[:N]
    mem_avg = _arr(m.mem_avg, np.float64)[:N]
    mem_std = _arr(m.mem_std, np.float64)[:N]
    missing = _arr(m.missing_cpu_millis, np.int64)[:N]
    pred = _arr(snap.pods.predicted_cpu_millis, np.int64)[:P]
    out = np.empty(P, np.int32)
    start = time.perf_counter()
    placed = lib.ref_seq_trimaran(
        N, P, R, _ptr(free0), _ptr(req), _ptr(cap), _ptr(cpu_tlp),
        _ptr(cpu_valid), _ptr(cpu_avg), _ptr(cpu_std), _ptr(mem_avg),
        _ptr(mem_std), _ptr(missing), _ptr(pred),
        float(target), float(margin), float(sensitivity), _ptr(out))
    elapsed = time.perf_counter() - start
    return P / elapsed, int(placed), out


def compiled_numa_baseline(snap, n_nodes=None, n_pods=None):
    """Config 3: single-numa zone bitmask fit + LeastAllocated min-over-zones
    with pessimistic all-zone commit."""
    lib = _load()
    _, free0, req = _fit_inputs(snap, n_nodes, n_pods)
    N, R = free0.shape
    P = req.shape[0]
    numa = snap.numa
    zavail = _arr(numa.available, np.int64)[:N]
    zalloc = _arr(numa.allocatable, np.int64)[:N]
    zmask = _arr(numa.zone_mask, np.uint8)[:N]
    reported = _arr(numa.reported, np.uint8)[:N]
    Z = zavail.shape[1]
    out = np.empty(P, np.int32)
    start = time.perf_counter()
    placed = lib.ref_seq_numa(N, P, R, Z, _ptr(free0), _ptr(req),
                              _ptr(zavail), _ptr(zalloc), _ptr(zmask),
                              _ptr(reported), _ptr(out))
    elapsed = time.perf_counter() - start
    return P / elapsed, int(placed), out


def compiled_gang_quota_baseline(snap, weights, n_nodes=None, n_pods=None):
    """Config 4: elastic-quota admission + allocatable score + gang quorum."""
    lib = _load()
    alloc, free0, req = _fit_inputs(snap, n_nodes, n_pods)
    # quota admission uses the RAW effective request (pods slot 0), matching
    # ops.quota.quota_admit; the fit demand (pods slot 1) is only for fitting
    N, R = alloc.shape
    P = req.shape[0]
    quota_req = _arr(snap.pods.req, np.int64)[:P]
    w = _arr(weights, np.int64)
    quota = snap.quota
    if quota is not None:
        q_min = _arr(quota.min, np.int64)
        q_max = _arr(quota.max, np.int64)
        q_used = _arr(quota.used, np.int64)
        has_q = _arr(quota.has_quota, np.uint8)
        ns = _arr(snap.pods.ns, np.int64)[:P]
    else:
        q_min = q_max = q_used = np.zeros((1, R), np.int64)
        has_q = np.zeros(1, np.uint8)
        ns = np.full(P, -1, np.int64)
    M = q_min.shape[0]
    gangs = snap.gangs
    if gangs is not None:
        gang = _arr(snap.pods.gang, np.int64)[:P]
        g_min = _arr(gangs.min_member, np.int64)
        g_assigned = _arr(gangs.assigned, np.int64)
    else:
        gang = np.full(P, -1, np.int64)
        g_min = g_assigned = np.zeros(1, np.int64)
    G = g_min.shape[0]
    out = np.empty(P, np.int32)
    out_wait = np.empty(P, np.int32)
    start = time.perf_counter()
    placed = lib.ref_seq_gang_quota(
        N, P, R, _ptr(alloc), _ptr(free0), _ptr(req), _ptr(quota_req), _ptr(w),
        _ptr(ns), M, _ptr(q_min), _ptr(q_max), _ptr(has_q), _ptr(q_used),
        _ptr(gang), G, _ptr(g_min), _ptr(g_assigned), _ptr(out),
        _ptr(out_wait))
    elapsed = time.perf_counter() - start
    return P / elapsed, int(placed), out


def compiled_network_baseline(snap, zone_cost, region_cost,
                              n_nodes=None, n_pods=None):
    """Config 5: dependency satisfied/violated tallies + cost accumulation."""
    lib = _load()
    _, free0, req = _fit_inputs(snap, n_nodes, n_pods)
    N, R = free0.shape
    P = req.shape[0]
    net = snap.network
    node_zone = _arr(snap.nodes.zone, np.int32)[:N]
    node_region = _arr(snap.nodes.region, np.int32)[:N]
    zone_region = _arr(net.zone_region, np.int32)
    zc = _arr(zone_cost, np.int64)
    rc = _arr(region_cost, np.int64)
    ZC = zc.shape[0]
    RC = rc.shape[0]
    placed0 = _arr(net.placed_node, np.int64)[:, :N].copy()
    W = placed0.shape[0]
    pod_wl = _arr(net.pod_workload, np.int32)[:P]
    dep_wl = _arr(net.dep_workload, np.int32)[:P]
    dep_cost = _arr(net.dep_max_cost, np.int64)[:P]
    dep_mask = _arr(net.dep_mask, np.uint8)[:P]
    D = dep_wl.shape[1]
    out = np.empty(P, np.int32)
    start = time.perf_counter()
    placed = lib.ref_seq_network(
        N, P, R, _ptr(free0), _ptr(req), _ptr(node_zone), _ptr(node_region),
        ZC, RC, _ptr(zone_region), _ptr(zc), _ptr(rc), W, _ptr(placed0),
        _ptr(pod_wl), D, _ptr(dep_wl), _ptr(dep_cost), _ptr(dep_mask),
        _ptr(out))
    elapsed = time.perf_counter() - start
    return P / elapsed, int(placed), out
