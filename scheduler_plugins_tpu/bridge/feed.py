"""Cluster event feed — the cross-process bridge front end.

The reference's cross-process feed is apiserver List/Watch into informer
caches (SURVEY.md §2.9); the north-star design ships cluster snapshots from
a cluster-side agent to the TPU scheduler host. This module implements that
boundary as a newline-delimited JSON event protocol over TCP — deliberately
language-agnostic so a Go/C++ agent can speak it without Python bindings —
applied to the host `Cluster` store (and through it the native columnar
store when attached):

    {"op": "upsert_node", "name": ..., "allocatable": {res: int}, ...}
    {"op": "upsert_pod",  "name": ..., "namespace": ..., "requests": {...},
     "limits": {...}, "priority": 0, "node": null|name, "labels": {...}}
    {"op": "delete_pod", "uid": ...}          (or namespace+name)
    {"op": "delete_node", "name": ...}
    {"op": "upsert_quota"|"delete_quota", ...}
    {"op": "upsert_pod_group"|"delete_pod_group", ...}
    {"op": "metrics", "nodes": {node: {"cpu_avg": ..., ...}}}

Protocol v2 covers the FULL CR surface the reference's informers watch
(plugin.go:86-115 NRT; networkoverhead.go:136-171 AppGroup/NetworkTopology;
sysched.go:305-396 pod/profile handlers; PriorityClass/PDB consumed by the
preemption tier):

    {"op": "upsert_nrt", "node": ..., "policy": int, "scope": int,
     "max_numa_nodes": 8, "pod_fingerprint": "...",
     "zones": [{"numa_id": 0, "available": {...}, "allocatable": {...},
                "costs": {"1": 20}}]}                      | "delete_nrt"
    {"op": "upsert_app_group", "name": ..., "namespace": ...,
     "workloads": [{"selector": ..., "dependencies":
                    [{"workload_selector": ..., "max_network_cost": 10}]}],
     "topology_order": {selector: index}}                  | "delete_app_group"
    {"op": "upsert_network_topology", "name": ..., "weights":
     {weightsName: {"zone"|"region": [[orig, dest, cost], ...]}}}
                                                  | "delete_network_topology"
    {"op": "upsert_seccomp_profile", "name": ..., "syscalls": [...]}
                                                  | "delete_seccomp_profile"
    {"op": "upsert_priority_class", "name": ..., "value": 0,
     "annotations": {...}}                        | "delete_priority_class"
    {"op": "upsert_pdb", "name": ..., "selector": {...},
     "disruptions_allowed": 1, "disrupted_pods": [...]}    | "delete_pdb"

Pod events may carry scheduler_name/phase/deletion_ms so foreign-pod
detection and lifecycle accounting work through this boundary, plus the full
spec surface: "containers"/"init_containers" (each {"requests", "limits",
"restart_policy_always", "seccomp_profile"}), "overhead", "annotations",
"nominated_node", "priority_class_name" and "scheduling_gated" — the
single-container "requests"/"limits" shorthand remains valid. A bound pod
is not demoted by a stale echo without a node (informer-cache semantics).

Node events may carry "taints"; pod events the in-tree spec fragments the
companion plugins consume (plugins/intree.py): "node_selector",
"node_affinity" {"required": [term], "preferred": [{"weight", "preference":
term}]} (term = {"match_expressions"/"match_fields":
[{"key","operator","values"}]}), "tolerations", "topology_spread"
[{"max_skew","topology_key","when_unsatisfiable","label_selector"}], and
"pod_affinity"/"pod_anti_affinity" {"required": [pterm], "preferred":
[{"weight","term": pterm}]} (pterm = {"topology_key","label_selector",
"namespaces","namespace_selector"}; label_selector =
{"match_labels","match_expressions"}). Spread constraints also accept
"min_domains", "match_label_keys", "node_affinity_policy" and
"node_taints_policy"; {"op": "upsert_namespace", "name": ..., "labels":
{...}} | "delete_namespace" carry the namespaceSelector targets.

Every object event may carry "rv" — a per-object monotonic resource
version; the server drops events at or below the last applied version
({"ok": true, "stale": true}), giving informer-grade fencing across
replays, reordering, and redundant agents.

Each line is acknowledged with {"ok": true} or {"ok": false, "error": ...};
the {"op": "sync"} barrier acks with cluster counts, so an agent can fence a
batch before requesting a scheduling cycle.

Transports: newline-JSON (above), the same events in gRPC message framing
(5-byte prefix; auto-detected per connection, `FramedFeedClient`), or real
gRPC via `bridge.grpc_feed` (HTTP/2, JSON codec, no protobuf stubs).
"""

from __future__ import annotations

import json
import socket
import socketserver
import threading
from typing import Optional

from scheduler_plugins_tpu.api.objects import (
    AppGroup,
    AppGroupDependency,
    AppGroupWorkload,
    Container,
    ElasticQuota,
    LabelSelector,
    LabelSelectorRequirement,
    Namespace,
    NetworkTopology,
    Node,
    NodeResourceTopology,
    NodeSelectorTerm,
    NUMAZone,
    Pod,
    PodAffinityTerm,
    PodDisruptionBudget,
    PodGroup,
    PreferredSchedulingTerm,
    PriorityClass,
    SeccompProfile,
    Taint,
    Toleration,
    TopologyManagerPolicy,
    TopologyManagerScope,
    TopologySpreadConstraint,
    WeightedPodAffinityTerm,
)
from scheduler_plugins_tpu.api import events as ev
from scheduler_plugins_tpu.state.cluster import Cluster

#: framed-transport sanity bound — far above any real event, far below a
#: memory-exhausting allocation from a garbage header
MAX_FRAME_BYTES = 16 << 20


def _container(spec: dict) -> Container:
    return Container(
        name=spec.get("name", "c"),
        requests={k: int(v) for k, v in spec.get("requests", {}).items()},
        limits={k: int(v) for k, v in spec.get("limits", {}).items()},
        restart_policy_always=bool(spec.get("restart_policy_always", False)),
        seccomp_profile=spec.get("seccomp_profile"),
    )


_node_term = NodeSelectorTerm.from_wire


def _label_selector(spec: Optional[dict]) -> Optional[LabelSelector]:
    if spec is None:
        return None
    return LabelSelector(
        match_labels=spec.get("match_labels") or {},
        match_expressions=[
            LabelSelectorRequirement(
                key=r["key"], operator=r["operator"],
                values=tuple(r.get("values") or ()),
            )
            for r in spec.get("match_expressions") or []
        ],
    )


def _pod_term(spec: dict) -> PodAffinityTerm:
    return PodAffinityTerm(
        topology_key=spec["topology_key"],
        label_selector=_label_selector(spec.get("label_selector")),
        namespaces=tuple(spec.get("namespaces") or ()),
        namespace_selector=_label_selector(spec.get("namespace_selector")),
    )


def _pod_spec_fragments(event: dict) -> dict:
    """In-tree scheduling spec fragments (nodeSelector / affinity /
    tolerations / topology spread) from a pod event — the pieces real
    profiles need for the companion plugins (plugins/intree.py)."""
    out: dict = {}
    # `or {}` / `or []` throughout: agents marshaling structs without
    # omitempty emit JSON null for absent fields
    if event.get("node_selector"):
        out["node_selector"] = dict(event["node_selector"])
    na = event.get("node_affinity") or {}
    if na.get("required"):
        out["node_affinity_required"] = [
            _node_term(t) for t in na["required"]
        ]
    if na.get("preferred"):
        out["node_affinity_preferred"] = [
            PreferredSchedulingTerm(
                weight=int(t["weight"]),
                preference=_node_term(t.get("preference", {})),
            )
            for t in na["preferred"]
        ]
    if event.get("tolerations"):
        out["tolerations"] = [
            Toleration(
                key=t.get("key", ""),
                operator=t.get("operator", "Equal"),
                value=t.get("value", ""),
                effect=t.get("effect", ""),
            )
            for t in event["tolerations"]
        ]
    if event.get("topology_spread"):
        out["topology_spread"] = [
            TopologySpreadConstraint(
                max_skew=int(c["max_skew"]),
                topology_key=c["topology_key"],
                when_unsatisfiable=c.get(
                    "when_unsatisfiable", "DoNotSchedule"
                ),
                label_selector=_label_selector(c.get("label_selector")),
                min_domains=(
                    int(c["min_domains"]) if c.get("min_domains") else None
                ),
                match_label_keys=tuple(c.get("match_label_keys") or ()),
                node_affinity_policy=c.get("node_affinity_policy", "Honor"),
                node_taints_policy=c.get("node_taints_policy", "Ignore"),
            )
            for c in event["topology_spread"]
        ]
    for side, attr in (
        ("pod_affinity", "pod_affinity"),
        ("pod_anti_affinity", "pod_anti_affinity"),
    ):
        spec = event.get(side) or {}
        if spec.get("required"):
            out[f"{attr}_required"] = [_pod_term(t) for t in spec["required"]]
        if spec.get("preferred"):
            out[f"{attr}_preferred"] = [
                WeightedPodAffinityTerm(
                    weight=int(t["weight"]), term=_pod_term(t["term"])
                )
                for t in spec["preferred"]
            ]
    return out


#: op -> (kind, key fields) for resource-version fencing; namespaced kinds
#: key on "namespace/name"
_RV_KINDS = {
    "upsert_node": ("node", ("name",)),
    "delete_node": ("node", ("name",)),
    "upsert_pod": ("pod", ("namespace", "name")),
    "delete_pod": ("pod", ("namespace", "name")),
    "upsert_quota": ("quota", ("namespace",)),
    "delete_quota": ("quota", ("namespace",)),
    "upsert_pod_group": ("pod_group", ("namespace", "name")),
    "delete_pod_group": ("pod_group", ("namespace", "name")),
    "upsert_nrt": ("nrt", ("node",)),
    "delete_nrt": ("nrt", ("node",)),
    "upsert_app_group": ("app_group", ("namespace", "name")),
    "delete_app_group": ("app_group", ("namespace", "name")),
    "upsert_network_topology": ("network_topology", ("namespace", "name")),
    "delete_network_topology": ("network_topology", ("namespace", "name")),
    "upsert_seccomp_profile": ("seccomp_profile", ("namespace", "name")),
    "delete_seccomp_profile": ("seccomp_profile", ("namespace", "name")),
    "upsert_priority_class": ("priority_class", ("name",)),
    "upsert_namespace": ("namespace", ("name",)),
    "delete_namespace": ("namespace", ("name",)),
    "delete_priority_class": ("priority_class", ("name",)),
    "upsert_pdb": ("pdb", ("namespace", "name")),
    "delete_pdb": ("pdb", ("namespace", "name")),
}


def _rv_key(event: dict):
    spec = _RV_KINDS.get(event.get("op"))
    if spec is None:
        return None
    kind, fields = spec
    if kind == "pod":
        # one fence lane per pod regardless of which identifier a given
        # agent sends: namespace/name when available (the default uid
        # format), bare uid only as the delete-by-uid fallback
        if event.get("name"):
            return (kind, f"{event.get('namespace', 'default')}/{event['name']}")
        return (kind, event.get("uid", ""))
    ident = "/".join(
        str(event.get(f, "default" if f == "namespace" else ""))
        for f in fields
    )
    return (kind, ident)


def apply_event(
    cluster: Cluster, event: dict, rv_table: Optional[dict] = None
) -> dict:
    """Apply one event to the store; returns the ack payload.

    When the event carries `rv` (a per-object monotonic resource version,
    the informer-cache fencing the reference gets from the apiserver) and
    `rv_table` is provided, an event at or below the last applied version
    for that object is dropped with ``{"ok": true, "stale": true}`` — so
    replays, races between redundant agents, and out-of-order delivery
    cannot regress the store. Events without `rv` apply unconditionally
    (last-writer-wins, protocol v1/v2 behavior).
    """
    op = event.get("op")
    fence = None
    if rv_table is not None and "rv" in event:
        key = _rv_key(event)
        if key is not None:
            rv = int(event["rv"])
            last = rv_table.get(key)
            if last is not None and rv <= last:
                return {"ok": True, "stale": True, "last_rv": last}
            # recorded only AFTER the op applies cleanly — a malformed
            # event must not burn its version (the agent retries the
            # corrected event under the same rv)
            fence = (key, rv)
    ack = _apply_op(cluster, event, op)
    if fence is not None and ack.get("ok", True):
        rv_table[fence[0]] = fence[1]
    return ack


def _apply_op(cluster: Cluster, event: dict, op) -> dict:
    if op == "upsert_node":
        cluster.add_node(
            Node(
                name=event["name"],
                allocatable={k: int(v) for k, v in event["allocatable"].items()},
                labels=event.get("labels", {}),
                unschedulable=event.get("unschedulable", False),
                taints=[
                    Taint(
                        key=t["key"],
                        value=t.get("value", ""),
                        effect=t.get("effect", "NoSchedule"),
                    )
                    for t in event.get("taints", [])
                ],
            )
        )
    elif op == "upsert_pod":
        if "containers" in event:
            containers = [_container(c) for c in event["containers"]]
        else:  # single-container shorthand (protocol v1)
            containers = [
                Container(
                    requests={k: int(v) for k, v in event.get("requests", {}).items()},
                    limits={k: int(v) for k, v in event.get("limits", {}).items()},
                )
            ]
        pod = Pod(
            name=event["name"],
            namespace=event.get("namespace", "default"),
            uid=event.get("uid", ""),
            priority=int(event.get("priority", 0)),
            creation_ms=int(event.get("creation_ms", 0)),
            labels=event.get("labels", {}),
            annotations=event.get("annotations", {}),
            scheduler_name=event.get(
                "scheduler_name", "tpu-scheduler"
            ),
            phase=event.get("phase", "Pending"),
            deletion_ms=event.get("deletion_ms"),
            scheduling_gated=bool(event.get("scheduling_gated", False)),
            priority_class_name=event.get("priority_class_name", ""),
            preemption_policy=event.get("preemption_policy"),
            overhead={k: int(v) for k, v in event.get("overhead", {}).items()},
            containers=containers,
            init_containers=[
                _container(c) for c in event.get("init_containers", [])
            ],
            **_pod_spec_fragments(event),
        )
        pod.node_name = event.get("node")
        pod.nominated_node_name = event.get("nominated_node")
        existing = cluster.pods.get(pod.uid)
        if (
            existing is not None
            and existing.node_name is not None
            and pod.node_name is None
            and "rv" not in event
        ):
            # un-fenced stale watch echo predating our bind: the local
            # binding is the newer truth. An rv-carrying event already
            # passed the fence, so its missing node is REAL (e.g. the
            # apiserver rejected the bind) and must apply as-is.
            pod.node_name = existing.node_name
        cluster.add_pod(pod)
    elif op == "delete_pod":
        uid = event.get("uid") or f"{event.get('namespace', 'default')}/{event.get('name')}"
        if uid not in cluster.pods:
            return {"ok": False, "error": f"unknown pod {uid!r}"}
        cluster.remove_pod(uid)
    elif op == "delete_node":
        cluster.remove_node(event["name"])
    elif op == "delete_quota":
        if cluster.quotas.pop(event.get("namespace", "default"), None):
            cluster.note_event(ev.ELASTIC_QUOTA_DELETE)
    elif op == "delete_pod_group":
        if cluster.pod_groups.pop(
            f"{event.get('namespace', 'default')}/{event['name']}", None
        ):
            cluster.note_event(ev.POD_GROUP_DELETE)
    elif op == "upsert_quota":
        cluster.add_quota(
            ElasticQuota(
                name=event["name"],
                namespace=event.get("namespace", "default"),
                min={k: int(v) for k, v in event.get("min", {}).items()},
                max={k: int(v) for k, v in event.get("max", {}).items()},
            )
        )
    elif op == "upsert_pod_group":
        cluster.add_pod_group(
            PodGroup(
                name=event["name"],
                namespace=event.get("namespace", "default"),
                min_member=int(event.get("min_member", 1)),
                min_resources={
                    k: int(v) for k, v in event.get("min_resources", {}).items()
                },
                creation_ms=int(event.get("creation_ms", 0)),
            )
        )
    elif op == "upsert_nrt":
        cluster.add_nrt(
            NodeResourceTopology(
                node_name=event["node"],
                policy=TopologyManagerPolicy(int(event.get("policy", 0))),
                scope=TopologyManagerScope(int(event.get("scope", 0))),
                max_numa_nodes=int(event.get("max_numa_nodes", 8)),
                pod_fingerprint=event.get("pod_fingerprint", ""),
                pod_fingerprint_method=event.get(
                    "pod_fingerprint_method", ""
                ),
                zones=[
                    NUMAZone(
                        numa_id=int(z["numa_id"]),
                        available={
                            k: int(v)
                            for k, v in z.get("available", {}).items()
                        },
                        allocatable={
                            k: int(v)
                            for k, v in z.get("allocatable", {}).items()
                        },
                        costs={
                            int(k): int(v)
                            for k, v in z.get("costs", {}).items()
                        },
                    )
                    for z in event.get("zones", [])
                ],
            )
        )
    elif op == "delete_nrt":
        cluster.remove_nrt(event["node"])
    elif op == "upsert_app_group":
        cluster.add_app_group(
            AppGroup(
                name=event["name"],
                namespace=event.get("namespace", "default"),
                workloads=[
                    AppGroupWorkload(
                        selector=w["selector"],
                        dependencies=[
                            AppGroupDependency(
                                workload_selector=d["workload_selector"],
                                max_network_cost=int(
                                    d.get("max_network_cost", 0)
                                ),
                            )
                            for d in w.get("dependencies", [])
                        ],
                    )
                    for w in event.get("workloads", [])
                ],
                topology_order={
                    k: int(v)
                    for k, v in event.get("topology_order", {}).items()
                },
            )
        )
    elif op == "delete_app_group":
        if cluster.app_groups.pop(
            f"{event.get('namespace', 'default')}/{event['name']}", None
        ):
            cluster.note_event(ev.APP_GROUP_DELETE)
    elif op == "upsert_network_topology":
        # (origin, dest) pairs ride as [orig, dest, cost] triples on the wire
        cluster.add_network_topology(
            NetworkTopology(
                name=event.get("name", "nt-default"),
                namespace=event.get("namespace", "default"),
                weights={
                    wname: {
                        key: {
                            (str(o), str(d)): int(c) for o, d, c in triples
                        }
                        for key, triples in keys.items()
                    }
                    for wname, keys in event.get("weights", {}).items()
                },
            )
        )
    elif op == "delete_network_topology":
        if cluster.network_topologies.pop(
            f"{event.get('namespace', 'default')}/{event['name']}", None
        ):
            cluster.note_event(ev.NETWORK_TOPOLOGY_DELETE)
    elif op == "upsert_seccomp_profile":
        cluster.add_seccomp_profile(
            SeccompProfile(
                name=event["name"],
                namespace=event.get("namespace", "default"),
                syscalls=frozenset(event.get("syscalls", [])),
            )
        )
    elif op == "delete_seccomp_profile":
        if cluster.seccomp_profiles.pop(
            f"{event.get('namespace', 'default')}/{event['name']}", None
        ):
            cluster.note_event(ev.SECCOMP_PROFILE_DELETE)
    elif op == "upsert_priority_class":
        cluster.add_priority_class(
            PriorityClass(
                name=event["name"],
                value=int(event.get("value", 0)),
                annotations=event.get("annotations", {}),
            )
        )
    elif op == "delete_priority_class":
        if cluster.priority_classes.pop(event["name"], None):
            cluster.note_event(ev.PRIORITY_CLASS_DELETE)
    elif op == "upsert_namespace":
        cluster.add_namespace(
            Namespace(name=event["name"], labels=event.get("labels") or {})
        )
    elif op == "delete_namespace":
        if cluster.namespaces.pop(event["name"], None):
            cluster.note_event(ev.NAMESPACE_DELETE)
    elif op == "upsert_pdb":
        cluster.add_pdb(
            PodDisruptionBudget(
                name=event["name"],
                namespace=event.get("namespace", "default"),
                selector=event.get("selector", {}),
                disruptions_allowed=int(event.get("disruptions_allowed", 0)),
                disrupted_pods=frozenset(event.get("disrupted_pods", [])),
            )
        )
    elif op == "delete_pdb":
        if cluster.pdbs.pop(
            f"{event.get('namespace', 'default')}/{event['name']}", None
        ):
            cluster.note_event(ev.PDB_DELETE)
    elif op == "metrics":
        cluster.node_metrics = event["nodes"]
    elif op == "drain_deltas":
        # streaming-delta bridge seam (SURVEY §L5): export ONLY the node
        # rows the native columnar mirror touched since the last drain —
        # a remote consumer (mirror shard, dashboard) polls this instead
        # of a full O(cluster) snapshot. Single-consumer semantics: the
        # drain consumes the delta window and bumps the generation.
        native = cluster.native
        if native is None:
            return {
                "ok": False,
                "error": "no native store attached "
                         "(Cluster.attach_native_store)",
            }
        deltas = native.export_dirty()
        return {
            "ok": True,
            "generation": int(deltas["generation"]),
            "count": int(len(deltas["ids"])),
            "nodes": [
                {
                    "id": int(deltas["ids"][i]),
                    "alloc": [int(v) for v in deltas["alloc"][i]],
                    "capacity": [int(v) for v in deltas["capacity"][i]],
                    "requested": [int(v) for v in deltas["requested"][i]],
                    "nonzero_requested": [
                        int(v) for v in deltas["nonzero_requested"][i]
                    ],
                    "limits": [int(v) for v in deltas["limits"][i]],
                    "pod_count": int(deltas["pod_count"][i]),
                    "terminating": int(deltas["terminating"][i]),
                }
                for i in range(len(deltas["ids"]))
            ],
        }
    elif op == "sync":
        return {
            "ok": True,
            "nodes": len(cluster.nodes),
            "pods": len(cluster.pods),
            "pending": len(cluster.pending_pods()),
        }
    else:
        return {"ok": False, "error": f"unknown op {op!r}"}
    return {"ok": True}


class FeedServer:
    """TCP server applying the event protocol to a Cluster store.

    `lock` serializes event application; anything else touching the store
    concurrently (scheduling cycles, controllers) must hold it too — use
    `run_cycle` / `locked()` rather than calling framework.run_cycle
    directly on a live-fed cluster.
    """

    def __init__(self, cluster: Cluster, host: str = "127.0.0.1", port: int = 0):
        self.cluster = cluster
        self.lock = threading.Lock()
        #: (kind, id) -> last applied resource version (shared across
        #: connections: redundant agents fence against each other)
        self.rv_table: dict = {}
        outer = self

        class Handler(socketserver.StreamRequestHandler):
            def _apply(self, raw: bytes) -> bytes:
                try:
                    event = json.loads(raw)
                    with outer.lock:
                        ack = apply_event(
                            outer.cluster, event, rv_table=outer.rv_table
                        )
                except Exception as exc:  # malformed: report, keep going
                    ack = {"ok": False, "error": str(exc)}
                return json.dumps(ack).encode()

            def handle(self):
                # transport sniff: a gRPC-style frame starts with the
                # 0x00/0x01 compressed-flag byte; newline-JSON starts with
                # "{" — one port speaks both
                first = self.rfile.peek(1)[:1]
                if first in (b"\x00", b"\x01"):
                    self._handle_framed()
                else:
                    self._handle_lines()

            def _handle_lines(self):
                for raw in self.rfile:
                    raw = raw.strip()
                    if not raw:
                        continue
                    self.wfile.write(self._apply(raw) + b"\n")
                    self.wfile.flush()

            def _handle_framed(self):
                """gRPC message framing (1-byte compressed flag + 4-byte
                big-endian length) carrying the same JSON events — the wire
                shape a Go agent's grpc stack produces, minus HTTP/2."""
                import struct as _struct

                while True:
                    header = self.rfile.read(5)
                    if len(header) < 5:
                        return
                    _flag, length = _struct.unpack(">BI", header)
                    if length > MAX_FRAME_BYTES:
                        # a bogus length would commit us to buffering GiBs
                        # (one garbage byte routes a connection here) —
                        # refuse and drop the connection
                        body = json.dumps({
                            "ok": False,
                            "error": f"frame of {length} bytes exceeds "
                                     f"max {MAX_FRAME_BYTES}",
                        }).encode()
                        self.wfile.write(
                            _struct.pack(">BI", 0, len(body)) + body
                        )
                        self.wfile.flush()
                        return
                    payload = self.rfile.read(length)
                    if len(payload) < length:
                        return
                    body = self._apply(payload)
                    self.wfile.write(_struct.pack(">BI", 0, len(body)) + body)
                    self.wfile.flush()

        self._server = socketserver.ThreadingTCPServer((host, port), Handler)
        self._server.daemon_threads = True
        self._thread: Optional[threading.Thread] = None

    @property
    def address(self) -> tuple[str, int]:
        return self._server.server_address

    def start(self):
        self._thread = threading.Thread(
            target=self._server.serve_forever, daemon=True,
            name="feed-server",
        )
        self._thread.start()
        return self

    def stop(self):
        self._server.shutdown()
        self._server.server_close()

    def locked(self):
        """Context manager guarding store access against the feed threads."""
        return self.lock

    def run_cycle(self, scheduler, now=None, serve=None, resilience=None,
                  tuner=None):
        """One scheduling cycle holding the feed lock."""
        from scheduler_plugins_tpu.framework.cycle import run_cycle

        with self.lock:
            return run_cycle(scheduler, self.cluster, now, serve=serve,
                             resilience=resilience, tuner=tuner)


class FeedClient:
    """Minimal agent-side client (what a Go/C++ sidecar would implement)."""

    def __init__(self, host: str, port: int):
        self._sock = socket.create_connection((host, port))
        self._file = self._sock.makefile("rwb")

    def send(self, event: dict) -> dict:
        self._file.write((json.dumps(event) + "\n").encode())
        self._file.flush()
        return json.loads(self._file.readline())

    def close(self):
        self._file.close()
        self._sock.close()


class FramedFeedClient:
    """Agent-side client speaking the gRPC-framed transport (same events,
    5-byte message prefix instead of newlines)."""

    def __init__(self, host: str, port: int):
        import struct as _struct

        self._struct = _struct
        self._sock = socket.create_connection((host, port))
        self._file = self._sock.makefile("rwb")

    def send(self, event: dict) -> dict:
        body = json.dumps(event).encode()
        self._file.write(self._struct.pack(">BI", 0, len(body)) + body)
        self._file.flush()
        header = self._file.read(5)
        _flag, length = self._struct.unpack(">BI", header)
        return json.loads(self._file.read(length))

    def close(self):
        self._file.close()
        self._sock.close()
