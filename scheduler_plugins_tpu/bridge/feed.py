"""Cluster event feed — the cross-process bridge front end.

The reference's cross-process feed is apiserver List/Watch into informer
caches (SURVEY.md §2.9); the north-star design ships cluster snapshots from
a cluster-side agent to the TPU scheduler host. This module implements that
boundary as a newline-delimited JSON event protocol over TCP — deliberately
language-agnostic so a Go/C++ agent can speak it without Python bindings —
applied to the host `Cluster` store (and through it the native columnar
store when attached):

    {"op": "upsert_node", "name": ..., "allocatable": {res: int}, ...}
    {"op": "upsert_pod",  "name": ..., "namespace": ..., "requests": {...},
     "limits": {...}, "priority": 0, "node": null|name, "labels": {...}}
    {"op": "delete_pod", "uid": ...}          (or namespace+name)
    {"op": "delete_node", "name": ...}
    {"op": "upsert_quota"|"delete_quota", ...}
    {"op": "upsert_pod_group"|"delete_pod_group", ...}
    {"op": "metrics", "nodes": {node: {"cpu_avg": ..., ...}}}

Pod events may carry scheduler_name/phase/deletion_ms so foreign-pod
detection and lifecycle accounting work through this boundary. A bound pod
is not demoted by a stale echo without a node (informer-cache semantics).

Each line is acknowledged with {"ok": true} or {"ok": false, "error": ...};
the {"op": "sync"} barrier acks with cluster counts, so an agent can fence a
batch before requesting a scheduling cycle.
"""

from __future__ import annotations

import json
import socket
import socketserver
import threading
from typing import Optional

from scheduler_plugins_tpu.api.objects import (
    Container,
    ElasticQuota,
    Node,
    Pod,
    PodGroup,
)
from scheduler_plugins_tpu.state.cluster import Cluster


def apply_event(cluster: Cluster, event: dict) -> dict:
    """Apply one event to the store; returns the ack payload."""
    op = event.get("op")
    if op == "upsert_node":
        cluster.add_node(
            Node(
                name=event["name"],
                allocatable={k: int(v) for k, v in event["allocatable"].items()},
                labels=event.get("labels", {}),
                unschedulable=event.get("unschedulable", False),
            )
        )
    elif op == "upsert_pod":
        pod = Pod(
            name=event["name"],
            namespace=event.get("namespace", "default"),
            uid=event.get("uid", ""),
            priority=int(event.get("priority", 0)),
            creation_ms=int(event.get("creation_ms", 0)),
            labels=event.get("labels", {}),
            scheduler_name=event.get(
                "scheduler_name", "tpu-scheduler"
            ),
            phase=event.get("phase", "Pending"),
            deletion_ms=event.get("deletion_ms"),
            containers=[
                Container(
                    requests={k: int(v) for k, v in event.get("requests", {}).items()},
                    limits={k: int(v) for k, v in event.get("limits", {}).items()},
                )
            ],
        )
        pod.node_name = event.get("node")
        existing = cluster.pods.get(pod.uid)
        if existing is not None and existing.node_name is not None and pod.node_name is None:
            # stale watch echo predating our bind: the local binding is the
            # newer truth (informer caches resolve the same way via resource
            # versions; this protocol carries none)
            pod.node_name = existing.node_name
        cluster.add_pod(pod)
    elif op == "delete_pod":
        uid = event.get("uid") or f"{event.get('namespace', 'default')}/{event.get('name')}"
        if uid not in cluster.pods:
            return {"ok": False, "error": f"unknown pod {uid!r}"}
        cluster.remove_pod(uid)
    elif op == "delete_node":
        cluster.remove_node(event["name"])
    elif op == "delete_quota":
        cluster.quotas.pop(event.get("namespace", "default"), None)
    elif op == "delete_pod_group":
        cluster.pod_groups.pop(
            f"{event.get('namespace', 'default')}/{event['name']}", None
        )
    elif op == "upsert_quota":
        cluster.add_quota(
            ElasticQuota(
                name=event["name"],
                namespace=event.get("namespace", "default"),
                min={k: int(v) for k, v in event.get("min", {}).items()},
                max={k: int(v) for k, v in event.get("max", {}).items()},
            )
        )
    elif op == "upsert_pod_group":
        cluster.add_pod_group(
            PodGroup(
                name=event["name"],
                namespace=event.get("namespace", "default"),
                min_member=int(event.get("min_member", 1)),
                min_resources={
                    k: int(v) for k, v in event.get("min_resources", {}).items()
                },
                creation_ms=int(event.get("creation_ms", 0)),
            )
        )
    elif op == "metrics":
        cluster.node_metrics = event["nodes"]
    elif op == "sync":
        return {
            "ok": True,
            "nodes": len(cluster.nodes),
            "pods": len(cluster.pods),
            "pending": len(cluster.pending_pods()),
        }
    else:
        return {"ok": False, "error": f"unknown op {op!r}"}
    return {"ok": True}


class FeedServer:
    """TCP server applying the event protocol to a Cluster store.

    `lock` serializes event application; anything else touching the store
    concurrently (scheduling cycles, controllers) must hold it too — use
    `run_cycle` / `locked()` rather than calling framework.run_cycle
    directly on a live-fed cluster.
    """

    def __init__(self, cluster: Cluster, host: str = "127.0.0.1", port: int = 0):
        self.cluster = cluster
        self.lock = threading.Lock()
        outer = self

        class Handler(socketserver.StreamRequestHandler):
            def handle(self):
                for raw in self.rfile:
                    raw = raw.strip()
                    if not raw:
                        continue
                    try:
                        event = json.loads(raw)
                        with outer.lock:
                            ack = apply_event(outer.cluster, event)
                    except Exception as exc:  # malformed line: report, keep going
                        ack = {"ok": False, "error": str(exc)}
                    self.wfile.write((json.dumps(ack) + "\n").encode())
                    self.wfile.flush()

        self._server = socketserver.ThreadingTCPServer((host, port), Handler)
        self._server.daemon_threads = True
        self._thread: Optional[threading.Thread] = None

    @property
    def address(self) -> tuple[str, int]:
        return self._server.server_address

    def start(self):
        self._thread = threading.Thread(
            target=self._server.serve_forever, daemon=True
        )
        self._thread.start()
        return self

    def stop(self):
        self._server.shutdown()
        self._server.server_close()

    def locked(self):
        """Context manager guarding store access against the feed threads."""
        return self.lock

    def run_cycle(self, scheduler, now=None):
        """One scheduling cycle holding the feed lock."""
        from scheduler_plugins_tpu.framework.cycle import run_cycle

        with self.lock:
            return run_cycle(scheduler, self.cluster, now)


class FeedClient:
    """Minimal agent-side client (what a Go/C++ sidecar would implement)."""

    def __init__(self, host: str, port: int):
        self._sock = socket.create_connection((host, port))
        self._file = self._sock.makefile("rwb")

    def send(self, event: dict) -> dict:
        self._file.write((json.dumps(event) + "\n").encode())
        self._file.flush()
        return json.loads(self._file.readline())

    def close(self):
        self._file.close()
        self._sock.close()
