"""gRPC transport adapter for the cluster event feed.

Same event schema as `bridge.feed` (that module's docstring is the wire
contract), carried over real gRPC (HTTP/2, multiplexing, deadlines) instead
of a raw TCP socket. No protobuf: messages are the JSON event/ack bytes with
identity (de)serializers — the widely-used "JSON codec" pattern — so agents
in any language with a gRPC stack can call it without generated stubs:

    service scheduler_plugins_tpu.Feed {
      rpc Apply  (bytes JSON event)         returns (bytes JSON ack);
      rpc Stream (stream bytes JSON event)  returns (stream bytes JSON ack);
    }

`Stream` acks every event in order, so an agent can pipeline a replay and
fence with one {"op": "sync"} at the end. Resource-version fencing and the
store lock are shared with any `FeedServer` attached to the same cluster
when you pass its `lock`/`rv_table`.

Streaming deltas: when the cluster carries the native columnar mirror
(`Cluster.attach_native_store`), the {"op": "drain_deltas"} query returns
ONLY the node rows touched since the last drain (`snapshot_store.cc`
dirty-row export) — a remote mirror polls `drain_deltas` over `Apply` (or
interleaves it on a `Stream`) and ingests O(changed) per cycle instead of
re-shipping the whole snapshot; `GrpcFeedClient.drain_deltas()` is the
client-side convenience.

grpcio is an optional dependency: importing this module is always safe; the
deferred `import grpc` raises ImportError only when constructing
`GrpcFeedServer` / `GrpcFeedClient` (the plain TCP feed keeps working).
"""

from __future__ import annotations

import json
import threading
from typing import Optional

from scheduler_plugins_tpu.bridge.feed import apply_event
from scheduler_plugins_tpu.state.cluster import Cluster

SERVICE = "scheduler_plugins_tpu.Feed"


class GrpcFeedServer:
    """gRPC front end applying the event protocol to a Cluster store."""

    def __init__(
        self,
        cluster: Cluster,
        host: str = "127.0.0.1",
        port: int = 0,
        lock: Optional[threading.Lock] = None,
        rv_table: Optional[dict] = None,
    ):
        import grpc  # deferred: optional dependency

        self.cluster = cluster
        self.lock = lock if lock is not None else threading.Lock()
        self.rv_table = rv_table if rv_table is not None else {}

        def _apply(raw: bytes) -> bytes:
            try:
                event = json.loads(raw)
                with self.lock:
                    ack = apply_event(
                        self.cluster, event, rv_table=self.rv_table
                    )
            except Exception as exc:
                ack = {"ok": False, "error": str(exc)}
            return json.dumps(ack).encode()

        def apply_unary(request, context):
            return _apply(request)

        def apply_stream(request_iterator, context):
            for request in request_iterator:
                yield _apply(request)

        ident = lambda b: b  # noqa: E731 — JSON codec: bytes through
        handler = grpc.method_handlers_generic_handler(
            SERVICE,
            {
                "Apply": grpc.unary_unary_rpc_method_handler(
                    apply_unary,
                    request_deserializer=ident,
                    response_serializer=ident,
                ),
                "Stream": grpc.stream_stream_rpc_method_handler(
                    apply_stream,
                    request_deserializer=ident,
                    response_serializer=ident,
                ),
            },
        )
        from concurrent import futures

        self._server = grpc.server(futures.ThreadPoolExecutor(max_workers=8))
        self._server.add_generic_rpc_handlers((handler,))
        self.port = self._server.add_insecure_port(f"{host}:{port}")
        self.host = host

    def start(self):
        self._server.start()
        return self

    def stop(self, grace: float = 0.5):
        self._server.stop(grace)

    def run_cycle(self, scheduler, now=None):
        from scheduler_plugins_tpu.framework.cycle import run_cycle

        with self.lock:
            return run_cycle(scheduler, self.cluster, now)


class GrpcFeedClient:
    """Agent-side client for `GrpcFeedServer` (JSON codec, no stubs)."""

    def __init__(self, host: str, port: int):
        import grpc

        self._channel = grpc.insecure_channel(f"{host}:{port}")
        ident = lambda b: b  # noqa: E731
        self._apply = self._channel.unary_unary(
            f"/{SERVICE}/Apply",
            request_serializer=ident,
            response_deserializer=ident,
        )
        self._stream = self._channel.stream_stream(
            f"/{SERVICE}/Stream",
            request_serializer=ident,
            response_deserializer=ident,
        )

    def send(self, event: dict) -> dict:
        return json.loads(self._apply(json.dumps(event).encode()))

    def send_batch(self, events: list[dict]) -> list[dict]:
        payloads = (json.dumps(e).encode() for e in events)
        return [json.loads(ack) for ack in self._stream(payloads)]

    def drain_deltas(self) -> dict:
        """Pull the server store's streaming node-delta window (the rows
        touched since the last drain; O(changed), consumes the window)."""
        return self.send({"op": "drain_deltas"})

    def close(self):
        self._channel.close()
