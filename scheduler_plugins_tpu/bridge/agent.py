"""Cluster-side agent: Kubernetes apiserver watch streams -> feed-v2 events.

The reference's entire comm tier is client-go informers List/Watching the
apiserver (/root/reference/pkg/util/client_util.go:14-32, SURVEY.md §2.9);
this module is the drop-in adapter on the cluster side of our bridge: it
consumes the apiserver's own wire format — `{"type": "ADDED"|"MODIFIED"|
"DELETED", "object": {...}}` newline-JSON watch events for core/v1 Nodes,
Pods, Namespaces, PriorityClasses, PodDisruptionBudgets and the CRDs the
reference registers informers for (PodGroup, ElasticQuota,
NodeResourceTopology, AppGroup, NetworkTopology, SeccompProfile) — and
emits the equivalent feed-v2 events (`bridge/feed.py`) to a FeedServer.

No SDK: live mode watches with plain streaming HTTP (`?watch=1`, bearer
token), exactly the protocol client-go speaks; tests replay RECORDED watch
streams through the same translation path and drive `FeedServer.run_cycle`
end to end (tests/test_agent.py).

Quantities convert to this repo's reference units (CLAUDE.md): cpu ->
millicores, memory/storage -> bytes, pods/extended -> counts.
"""

from __future__ import annotations

import json
from decimal import Decimal
from typing import Callable, Iterable, Optional

# -- resource quantities -----------------------------------------------------

_DECIMAL_SUFFIX = {
    "n": Decimal("1e-9"), "u": Decimal("1e-6"), "m": Decimal("1e-3"),
    "k": Decimal("1e3"), "M": Decimal("1e6"), "G": Decimal("1e9"),
    "T": Decimal("1e12"), "P": Decimal("1e15"), "E": Decimal("1e18"),
    "Ki": Decimal(1 << 10), "Mi": Decimal(1 << 20), "Gi": Decimal(1 << 30),
    "Ti": Decimal(1 << 40), "Pi": Decimal(1 << 50), "Ei": Decimal(1 << 60),
}


def parse_quantity(text) -> Decimal:
    """resource.Quantity string -> Decimal in base units."""
    text = str(text).strip()
    for suffix in sorted(_DECIMAL_SUFFIX, key=len, reverse=True):
        if text.endswith(suffix):
            return Decimal(text[: -len(suffix)]) * _DECIMAL_SUFFIX[suffix]
    return Decimal(text)


def quantity_to_units(resource: str, text) -> int:
    """Quantity -> int64 reference units: cpu in MILLIcores, everything
    else in base units (bytes / counts), ceiling like Go's ScaledValue."""
    value = parse_quantity(text)
    if resource == "cpu":
        value *= 1000
    return int(value.to_integral_value(rounding="ROUND_CEILING"))


def _resource_map(spec: Optional[dict]) -> dict:
    return {
        res: quantity_to_units(res, qty) for res, qty in (spec or {}).items()
    }


def _rfc3339_ms(text) -> int:
    """metadata timestamps -> epoch milliseconds (0 when absent)."""
    if not text:
        return 0
    from datetime import datetime

    try:
        stamp = datetime.fromisoformat(str(text).replace("Z", "+00:00"))
    except ValueError:
        return 0
    return int(stamp.timestamp() * 1000)


def _rv(obj: dict) -> Optional[int]:
    raw = (obj.get("metadata") or {}).get("resourceVersion")
    try:
        return int(raw)
    except (TypeError, ValueError):
        return None


def _meta(obj: dict) -> dict:
    return obj.get("metadata") or {}


def _with_rv(event: dict, obj: dict) -> dict:
    rv = _rv(obj)
    if rv is not None:
        event["rv"] = rv
    return event


# -- core/v1 translators -----------------------------------------------------

def node_event(obj: dict) -> dict:
    meta, spec = _meta(obj), obj.get("spec") or {}
    status = obj.get("status") or {}
    return _with_rv({
        "op": "upsert_node",
        "name": meta.get("name", ""),
        "allocatable": _resource_map(
            status.get("allocatable") or status.get("capacity")
        ),
        "labels": meta.get("labels") or {},
        "unschedulable": bool(spec.get("unschedulable", False)),
        "taints": [
            {"key": t.get("key", ""), "value": t.get("value", ""),
             "effect": t.get("effect", "NoSchedule")}
            for t in spec.get("taints") or []
        ],
    }, obj)


def _selector_fragment(sel: Optional[dict]) -> Optional[dict]:
    if sel is None:
        return None
    return {
        "match_labels": sel.get("matchLabels") or {},
        "match_expressions": [
            {"key": e.get("key", ""), "operator": e.get("operator", "In"),
             "values": e.get("values") or []}
            for e in sel.get("matchExpressions") or []
        ],
    }


def _node_term_fragment(term: dict) -> dict:
    out = {}
    for src, dst in (("matchExpressions", "match_expressions"),
                     ("matchFields", "match_fields")):
        if term.get(src):
            out[dst] = [
                {"key": e.get("key", ""), "operator": e.get("operator", "In"),
                 "values": e.get("values") or []}
                for e in term[src]
            ]
    return out


def _pod_term_fragment(term: dict) -> dict:
    return {
        "topology_key": term.get("topologyKey", ""),
        "label_selector": _selector_fragment(term.get("labelSelector")),
        "namespaces": term.get("namespaces") or [],
        "namespace_selector": _selector_fragment(
            term.get("namespaceSelector")
        ),
    }


def _container_fragment(spec: dict, init: bool = False) -> dict:
    resources = spec.get("resources") or {}
    out = {
        "requests": _resource_map(resources.get("requests")),
        "limits": _resource_map(resources.get("limits")),
    }
    if init and spec.get("restartPolicy") == "Always":
        out["restart_policy_always"] = True
    # SPO localhost profile "operator/<ns>/<name>.json" -> "<ns>/<name>"
    # (sysched.go:124-210 profile resolution)
    profile = (
        (spec.get("securityContext") or {}).get("seccompProfile") or {}
    ).get("localhostProfile")
    if profile:
        parts = str(profile).removesuffix(".json").split("/")
        if len(parts) >= 2:
            out["seccomp_profile"] = "/".join(parts[-2:])
    return out


def pod_event(obj: dict) -> dict:
    meta, spec = _meta(obj), obj.get("spec") or {}
    status = obj.get("status") or {}
    event = {
        "op": "upsert_pod",
        "name": meta.get("name", ""),
        "namespace": meta.get("namespace", "default"),
        "uid": meta.get("uid", ""),
        "labels": meta.get("labels") or {},
        "annotations": meta.get("annotations") or {},
        "creation_ms": _rfc3339_ms(meta.get("creationTimestamp")),
        "priority": int(spec.get("priority") or 0),
        "priority_class_name": spec.get("priorityClassName", ""),
        "preemption_policy": spec.get("preemptionPolicy"),
        "scheduler_name": spec.get("schedulerName", "tpu-scheduler"),
        "phase": status.get("phase", "Pending"),
        "node": spec.get("nodeName"),
        "nominated_node": status.get("nominatedNodeName"),
        "scheduling_gated": bool(spec.get("schedulingGates")),
        "overhead": _resource_map(spec.get("overhead")),
        "containers": [
            _container_fragment(c) for c in spec.get("containers") or []
        ],
        "init_containers": [
            _container_fragment(c, init=True)
            for c in spec.get("initContainers") or []
        ],
    }
    if meta.get("deletionTimestamp"):
        event["deletion_ms"] = _rfc3339_ms(meta["deletionTimestamp"])
    if spec.get("nodeSelector"):
        event["node_selector"] = dict(spec["nodeSelector"])
    affinity = spec.get("affinity") or {}
    node_aff = affinity.get("nodeAffinity") or {}
    required = (
        node_aff.get("requiredDuringSchedulingIgnoredDuringExecution") or {}
    ).get("nodeSelectorTerms")
    preferred = node_aff.get(
        "preferredDuringSchedulingIgnoredDuringExecution"
    )
    if required or preferred:
        event["node_affinity"] = {}
        if required:
            event["node_affinity"]["required"] = [
                _node_term_fragment(t) for t in required
            ]
        if preferred:
            event["node_affinity"]["preferred"] = [
                {"weight": int(t.get("weight", 1)),
                 "preference": _node_term_fragment(t.get("preference") or {})}
                for t in preferred
            ]
    for src, dst in (("podAffinity", "pod_affinity"),
                     ("podAntiAffinity", "pod_anti_affinity")):
        aff = affinity.get(src) or {}
        required = aff.get("requiredDuringSchedulingIgnoredDuringExecution")
        preferred = aff.get("preferredDuringSchedulingIgnoredDuringExecution")
        if required or preferred:
            event[dst] = {}
            if required:
                event[dst]["required"] = [
                    _pod_term_fragment(t) for t in required
                ]
            if preferred:
                event[dst]["preferred"] = [
                    {"weight": int(t.get("weight", 1)),
                     "term": _pod_term_fragment(t.get("podAffinityTerm")
                                                or {})}
                    for t in preferred
                ]
    if spec.get("tolerations"):
        event["tolerations"] = [
            {"key": t.get("key", ""), "operator": t.get("operator", "Equal"),
             "value": t.get("value", ""), "effect": t.get("effect", "")}
            for t in spec["tolerations"]
        ]
    if spec.get("topologySpreadConstraints"):
        event["topology_spread"] = [
            {
                "max_skew": int(c.get("maxSkew", 1)),
                "topology_key": c.get("topologyKey", ""),
                "when_unsatisfiable": c.get(
                    "whenUnsatisfiable", "DoNotSchedule"
                ),
                "label_selector": _selector_fragment(c.get("labelSelector")),
                "min_domains": c.get("minDomains"),
                "match_label_keys": c.get("matchLabelKeys") or [],
                "node_affinity_policy": c.get("nodeAffinityPolicy", "Honor"),
                "node_taints_policy": c.get("nodeTaintsPolicy", "Ignore"),
            }
            for c in spec["topologySpreadConstraints"]
        ]
    return _with_rv(event, obj)


def namespace_event(obj: dict) -> dict:
    meta = _meta(obj)
    return _with_rv({
        "op": "upsert_namespace",
        "name": meta.get("name", ""),
        "labels": meta.get("labels") or {},
    }, obj)


def priority_class_event(obj: dict) -> dict:
    meta = _meta(obj)
    return _with_rv({
        "op": "upsert_priority_class",
        "name": meta.get("name", ""),
        "value": int(obj.get("value", 0)),
        "annotations": meta.get("annotations") or {},
    }, obj)


def pdb_event(obj: dict) -> dict:
    meta, spec = _meta(obj), obj.get("spec") or {}
    status = obj.get("status") or {}
    return _with_rv({
        "op": "upsert_pdb",
        "name": meta.get("name", ""),
        "namespace": meta.get("namespace", "default"),
        "selector": _selector_fragment(spec.get("selector")),
        "disruptions_allowed": int(status.get("disruptionsAllowed", 0)),
        "disrupted_pods": sorted(status.get("disruptedPods") or {}),
    }, obj)


# -- CRD translators ---------------------------------------------------------

def pod_group_event(obj: dict) -> dict:
    meta, spec = _meta(obj), obj.get("spec") or {}
    return _with_rv({
        "op": "upsert_pod_group",
        "name": meta.get("name", ""),
        "namespace": meta.get("namespace", "default"),
        "min_member": int(spec.get("minMember", 1)),
        "min_resources": _resource_map(spec.get("minResources")),
        "creation_ms": _rfc3339_ms(meta.get("creationTimestamp")),
    }, obj)


def elastic_quota_event(obj: dict) -> dict:
    meta, spec = _meta(obj), obj.get("spec") or {}
    return _with_rv({
        "op": "upsert_quota",
        "name": meta.get("name", ""),
        "namespace": meta.get("namespace", "default"),
        "min": _resource_map(spec.get("min")),
        "max": _resource_map(spec.get("max")),
    }, obj)


#: NRT attribute/deprecated-policy decoding
#: (/root/reference/pkg/noderesourcetopology/nodeconfig/topologymanager.go
#: :64-162): attributes "topologyManagerPolicy"/"topologyManagerScope"
#: preferred; TopologyPolicies fallback.
_POLICY_CODES = {
    "none": 0, "best-effort": 1, "restricted": 2, "single-numa-node": 3,
}
_SCOPE_CODES = {"container": 0, "pod": 1}
_DEPRECATED_POLICIES = {
    "None": (0, 0),
    "BestEffort": (1, 0),
    "Restricted": (2, 0),
    "SingleNUMANodeContainerLevel": (3, 0),
    "SingleNUMANodePodLevel": (3, 1),
}
#: podfingerprint attribute stamped by the node agent
#: (cache/overreserve.go fingerprint check; podfingerprint.Attribute)
_FINGERPRINT_ATTR = "nodeTopologyPodsFingerprint"


def nrt_event(obj: dict) -> dict:
    meta = _meta(obj)
    attrs = {
        a.get("name"): a.get("value") for a in obj.get("attributes") or []
    }
    policy = _POLICY_CODES.get(str(attrs.get("topologyManagerPolicy")), None)
    scope = _SCOPE_CODES.get(str(attrs.get("topologyManagerScope")), None)
    if policy is None or scope is None:
        for deprecated in obj.get("topologyPolicies") or []:
            if deprecated in _DEPRECATED_POLICIES:
                dep_policy, dep_scope = _DEPRECATED_POLICIES[deprecated]
                policy = dep_policy if policy is None else policy
                scope = dep_scope if scope is None else scope
                break
    zones = []
    for zone in obj.get("zones") or []:
        if zone.get("type") not in (None, "Node"):
            continue  # only NUMA-node zones build the model (:105-134)
        name = str(zone.get("name", ""))
        digits = "".join(ch for ch in name if ch.isdigit())
        numa_id = int(digits) if digits else len(zones)
        available, allocatable = {}, {}
        for res in zone.get("resources") or []:
            rname = res.get("name", "")
            if "available" in res:
                available[rname] = quantity_to_units(rname, res["available"])
            if "allocatable" in res:
                allocatable[rname] = quantity_to_units(
                    rname, res["allocatable"]
                )
        costs = {}
        for cost in zone.get("costs") or []:
            dest = "".join(ch for ch in str(cost.get("name", "")) if ch.isdigit())
            if dest:
                costs[dest] = int(cost.get("value", 10))
        zones.append({
            "numa_id": numa_id,
            "available": available,
            "allocatable": allocatable,
            "costs": costs,
        })
    event = {
        "op": "upsert_nrt",
        "node": meta.get("name", ""),
        "zones": zones,
    }
    if policy is not None:
        event["policy"] = policy
    if scope is not None:
        event["scope"] = scope
    max_numa = attrs.get("topologyManagerPolicyMaxNUMANodes") or attrs.get(
        "maxNUMANodes"
    )
    if max_numa is not None:
        event["max_numa_nodes"] = int(max_numa)
    fingerprint = attrs.get(_FINGERPRINT_ATTR) or (
        meta.get("annotations") or {}
    ).get("topology.node.k8s.io/fingerprint")
    if fingerprint:
        event["pod_fingerprint"] = str(fingerprint)
    return _with_rv(event, obj)


def app_group_event(obj: dict) -> dict:
    meta, spec = _meta(obj), obj.get("spec") or {}
    status = obj.get("status") or {}

    def selector_of(workload_ref: Optional[dict]) -> str:
        return str((workload_ref or {}).get("selector", ""))

    workloads = []
    for entry in spec.get("workloads") or []:
        workloads.append({
            "selector": selector_of(entry.get("workload")),
            "dependencies": [
                {
                    "workload_selector": selector_of(dep.get("workload")),
                    "max_network_cost": int(dep.get("maxNetworkCost", 0)),
                }
                for dep in entry.get("dependencies") or []
            ],
        })
    topology_order = {
        selector_of(item.get("workload")): int(item.get("index", 0))
        for item in status.get("topologyOrder") or []
    }
    return _with_rv({
        "op": "upsert_app_group",
        "name": meta.get("name", ""),
        "namespace": meta.get("namespace", "default"),
        "workloads": workloads,
        "topology_order": topology_order,
    }, obj)


def network_topology_event(obj: dict) -> dict:
    meta, spec = _meta(obj), obj.get("spec") or {}
    weights: dict = {}
    for weight in spec.get("weights") or []:
        per_key = weights.setdefault(str(weight.get("name", "")), {})
        for topology in weight.get("topologyList") or []:
            key = str(topology.get("topologyKey", ""))
            triples = per_key.setdefault(key, [])
            for origin in topology.get("originList") or []:
                orig = str(origin.get("origin", ""))
                for cost in origin.get("costList") or []:
                    triples.append([
                        orig,
                        str(cost.get("destination", "")),
                        int(cost.get("networkCost", 0)),
                    ])
    return _with_rv({
        "op": "upsert_network_topology",
        "name": meta.get("name", ""),
        "namespace": meta.get("namespace", "default"),
        "weights": weights,
    }, obj)


def seccomp_profile_event(obj: dict) -> dict:
    meta, spec = _meta(obj), obj.get("spec") or {}
    syscalls = []
    for group in spec.get("syscalls") or []:
        if group.get("action") in ("SCMP_ACT_ALLOW", None):
            syscalls.extend(group.get("names") or [])
    return _with_rv({
        "op": "upsert_seccomp_profile",
        "name": meta.get("name", ""),
        "namespace": meta.get("namespace", "default"),
        "syscalls": sorted(set(syscalls)),
    }, obj)


# -- watch-event dispatch ----------------------------------------------------

#: kind -> (upsert translator, delete-op name, delete key builder)
_KINDS = {
    "Node": (node_event, "delete_node",
             lambda m: {"name": m.get("name", "")}),
    "Pod": (pod_event, "delete_pod",
            lambda m: {"namespace": m.get("namespace", "default"),
                       "name": m.get("name", ""),
                       "uid": m.get("uid", "")}),
    "Namespace": (namespace_event, "delete_namespace",
                  lambda m: {"name": m.get("name", "")}),
    "PriorityClass": (priority_class_event, "delete_priority_class",
                      lambda m: {"name": m.get("name", "")}),
    "PodDisruptionBudget": (pdb_event, "delete_pdb",
                            lambda m: {"namespace": m.get("namespace",
                                                          "default"),
                                       "name": m.get("name", "")}),
    "PodGroup": (pod_group_event, "delete_pod_group",
                 lambda m: {"namespace": m.get("namespace", "default"),
                            "name": m.get("name", "")}),
    "ElasticQuota": (elastic_quota_event, "delete_quota",
                     lambda m: {"namespace": m.get("namespace", "default"),
                                "name": m.get("name", "")}),
    "NodeResourceTopology": (nrt_event, "delete_nrt",
                             lambda m: {"node": m.get("name", "")}),
    "AppGroup": (app_group_event, "delete_app_group",
                 lambda m: {"namespace": m.get("namespace", "default"),
                            "name": m.get("name", "")}),
    "NetworkTopology": (network_topology_event, "delete_network_topology",
                        lambda m: {"namespace": m.get("namespace",
                                                      "default"),
                                   "name": m.get("name", "")}),
    "SeccompProfile": (seccomp_profile_event, "delete_seccomp_profile",
                       lambda m: {"namespace": m.get("namespace", "default"),
                                  "name": m.get("name", "")}),
}

#: the List/Watch surface the agent covers — core/v1 + every CRD the
#: reference registers informers for (SURVEY.md §2.2/§2.6/§2.8)
DEFAULT_WATCH_PATHS = (
    "/api/v1/nodes",
    "/api/v1/pods",
    "/api/v1/namespaces",
    "/apis/scheduling.k8s.io/v1/priorityclasses",
    "/apis/policy/v1/poddisruptionbudgets",
    "/apis/scheduling.x-k8s.io/v1alpha1/podgroups",
    "/apis/scheduling.x-k8s.io/v1alpha1/elasticquotas",
    "/apis/topology.node.k8s.io/v1alpha2/noderesourcetopologies",
    "/apis/appgroup.diktyo.x-k8s.io/v1alpha1/appgroups",
    "/apis/networktopology.diktyo.x-k8s.io/v1alpha1/networktopologies",
    "/apis/security-profiles-operator.x-k8s.io/v1beta1/seccompprofiles",
)


def translate(watch_event: dict) -> Optional[dict]:
    """One apiserver watch event -> one feed-v2 event (None for BOOKMARK/
    ERROR/unknown kinds)."""
    etype = watch_event.get("type")
    obj = watch_event.get("object") or {}
    kind = obj.get("kind", "")
    if kind not in _KINDS or etype not in ("ADDED", "MODIFIED", "DELETED"):
        return None
    upsert, delete_op, delete_keys = _KINDS[kind]
    if etype == "DELETED":
        event = {"op": delete_op, **delete_keys(_meta(obj))}
        return _with_rv(event, obj)
    return upsert(obj)


class ClusterAgent:
    """Feeds translated watch events to a send callable (e.g.
    `FeedClient.send`). `replay` drives recorded streams; `watch` follows a
    live apiserver with plain streaming HTTP."""

    def __init__(self, send: Callable[[dict], dict]):
        self.send = send
        self.translated = 0
        self.skipped = 0

    def replay(self, watch_events: Iterable[dict]) -> int:
        """Translate + send recorded watch events; returns events sent."""
        sent = 0
        for watch_event in watch_events:
            event = translate(watch_event)
            if event is None:
                self.skipped += 1
                continue
            self.send(event)
            sent += 1
            self.translated += 1
        return sent

    def replay_lines(self, lines: Iterable[str]) -> int:
        """Replay newline-JSON watch records (the wire format)."""
        return self.replay(
            json.loads(line) for line in lines if line.strip()
        )

    def sync(self) -> dict:
        """Feed barrier: returns the server's cluster counts."""
        return self.send({"op": "sync"})

    # -- live mode -----------------------------------------------------
    def list_then_watch(self, apiserver: str, path: str, token: str = "",
                        insecure_skip_verify: bool = False,
                        ca_file: Optional[str] = None,
                        max_events: Optional[int] = None,
                        max_failures: Optional[int] = 8,
                        backoff_base_s: float = 0.25,
                        backoff_cap_s: float = 30.0,
                        timeout_s: float = 300.0,
                        _sleep=None) -> int:
        """client-go Reflector semantics over plain streaming HTTP
        (ListAndWatch, the machinery behind
        /root/reference/pkg/util/client_util.go:14-32):

        - LIST (items emitted as ADDED events; the server's rv-fence
          dedupes re-lists), then WATCH with ``allowWatchBookmarks=true``
          from the list's resourceVersion.
        - The resume point advances on EVERY event's object
          resourceVersion, including BOOKMARKs (whose whole purpose is
          advancing rv without payload traffic) — but only AFTER the event
          was delivered downstream, so a send-side failure redelivers the
          event on reconnect instead of silently dropping it.
        - ``410 Gone`` — as an HTTP status or an ERROR watch event with
          ``code: 410`` — means the rv is too old: relist. Relists count
          toward the failure budget/backoff so a persistent 410 storm
          (watch-cache compaction loops) cannot hammer the apiserver with
          back-to-back full LISTs.
        - An idle-stream read timeout (``timeout_s`` with no traffic) on
          an ESTABLISHED watch stream is NOT a failure: a
          healthy-but-quiet watch re-connects from the same rv without
          consuming the budget. A timeout during LIST or while opening
          the watch connection IS a failure (with backoff): an apiserver
          that consistently times out must not hold ``max_failures``
          callers in an unbounded relist loop.
        - Any other stream failure or clean close reconnects the WATCH
          from the last delivered rv with exponential backoff
          (``backoff_base_s * 2^k`` capped at ``backoff_cap_s``); the
          failure counter resets whenever an event arrives.

        Stops after ``max_events`` sends or ``max_failures`` consecutive
        failures (None = retry forever). Returns events sent."""
        import time as _time
        import urllib.error
        import urllib.request

        from scheduler_plugins_tpu.utils.httptls import ssl_context

        sleep = _sleep if _sleep is not None else _time.sleep

        def request(url):
            req = urllib.request.Request(url)
            if token:
                req.add_header("Authorization", f"Bearer {token}")
            ctx = ssl_context(url, ca_file, insecure_skip_verify)
            return urllib.request.urlopen(req, timeout=timeout_s, context=ctx)

        base = apiserver.rstrip("/") + path
        sent = 0
        rv: Optional[str] = None  # None -> (re)list before watching
        failures = 0

        stream_open = False  # True once the current watch stream is up

        while True:
            try:
                stream_open = False
                if rv is None:
                    with request(base) as resp:
                        listing = json.loads(resp.read())
                    sent += self.replay(
                        {"type": "ADDED",
                         "object": {**item,
                                    "kind": _list_item_kind(listing)}}
                        for item in listing.get("items", [])
                    )
                    rv = str(
                        (listing.get("metadata") or {})
                        .get("resourceVersion", "")
                    )
                    failures = 0
                    if max_events is not None and sent >= max_events:
                        return sent
                watch_url = f"{base}?watch=1&allowWatchBookmarks=true"
                if rv:
                    watch_url += f"&resourceVersion={rv}"
                with request(watch_url) as stream:
                    stream_open = True
                    for raw in stream:
                        line = raw.decode("utf-8", "replace").strip()
                        if not line:
                            continue
                        watch_event = json.loads(line)
                        etype = watch_event.get("type")
                        obj = watch_event.get("object") or {}
                        if etype == "ERROR":
                            if (obj.get("code") == 410
                                    or obj.get("reason") == "Expired"):
                                rv = None  # too old: relist
                            break
                        # deliver FIRST (replay skips BOOKMARK/unknown
                        # kinds itself), advance the resume point after:
                        # a send that raises must redeliver this event
                        sent += self.replay([watch_event])
                        new_rv = (obj.get("metadata") or {}).get(
                            "resourceVersion"
                        )
                        if new_rv:
                            rv = str(new_rv)
                        failures = 0
                        if max_events is not None and sent >= max_events:
                            return sent
            except TimeoutError:
                if stream_open:
                    # idle healthy stream: re-watch from rv, no budget burn
                    continue
                # LIST/connect timeout: ordinary failure (ADVICE r4)
                failures += 1
            except urllib.error.HTTPError as exc:
                if exc.code == 410:
                    rv = None  # relist (counted below like any failure)
                failures += 1
            except (urllib.error.URLError, OSError, ValueError):
                # connection refused/reset, mid-line JSON truncation, ...
                failures += 1
            else:
                # clean close or ERROR break: reconnect (relist when the
                # ERROR was a 410); both count toward the backoff budget
                failures += 1
            if max_failures is not None and failures >= max_failures:
                return sent
            sleep(min(backoff_base_s * (2 ** (failures - 1)),
                      backoff_cap_s))


def _list_item_kind(listing: dict) -> str:
    """PodList -> Pod etc. (list items omit kind on the wire)."""
    kind = str(listing.get("kind", ""))
    return kind[:-4] if kind.endswith("List") else kind
