"""Lease-based leader election against the kube-apiserver.

The analog of the reference controller's leader election
(/root/reference/cmd/controller/app/server.go:56-58: controller-runtime's
LeaderElection with LeaderElectionID "sched-plugins-controllers" in
kube-system) — client-go leaderelection semantics over the
coordination.k8s.io/v1 Lease API, in plain HTTP:

- try to GET the Lease; 404 -> POST-create on the COLLECTION URL holding
  our identity (409 AlreadyExists = someone else won the create race);
- held by someone else and renewed within lease_duration_s -> standby;
- stale (renewTime older than leaseDurationSeconds) or already ours ->
  PUT carrying the observed metadata.resourceVersion — the optimistic-
  concurrency guard kube enforces: two racers GETting the same stale
  lease cannot both win, the second PUT gets 409 Conflict and stays on
  standby (client-go's resourceVersion-conditional update);
- on clean shutdown, release by clearing holderIdentity (client-go's
  ReleaseOnCancel), same conditional-update rules.

Clock skew caveat as upstream: expiry is judged by THIS client's clock
against the renewTime stamped by the holder.
"""

from __future__ import annotations

import json
import threading
import time
import urllib.error
import urllib.request
from typing import Optional

from scheduler_plugins_tpu.utils.httptls import ssl_context


def _micro_time(unix_s: float) -> str:
    base = time.strftime("%Y-%m-%dT%H:%M:%S", time.gmtime(unix_s))
    return f"{base}.{int((unix_s % 1) * 1e6):06d}Z"


def _parse_micro_time(text: str) -> float:
    text = text.rstrip("Z")
    frac = 0.0
    if "." in text:
        text, frac_s = text.split(".", 1)
        frac = float(f"0.{frac_s}") if frac_s else 0.0
    import calendar

    return calendar.timegm(time.strptime(text, "%Y-%m-%dT%H:%M:%S")) + frac


class LeaseElector:
    """Single-Lease leader elector. Drive with `step(now)` (returns True
    while we hold the lease) or `run(stop_event)` in a thread."""

    def __init__(self, apiserver: str, identity: str,
                 name: str = "scheduler-plugins-tpu",
                 namespace: str = "kube-system",
                 lease_duration_s: float = 15.0,
                 renew_period_s: float = 5.0,
                 token: str = "",
                 ca_file: Optional[str] = None,
                 insecure_skip_verify: bool = False):
        self.apiserver = apiserver.rstrip("/")
        self.identity = identity
        self.name = name
        self.namespace = namespace
        self.lease_duration_s = lease_duration_s
        self.renew_period_s = renew_period_s
        self.token = token
        self.ca_file = ca_file
        self.insecure_skip_verify = insecure_skip_verify
        self._leader_event = threading.Event()
        self.observed_holder: Optional[str] = None

    @property
    def is_leader(self) -> bool:
        """True while we hold the lease. Event-backed: the renew loop
        writes it from the elector thread while the scheduling loop and
        /healthz read it from theirs — a plain bool attribute is a
        cross-thread handoff with no synchronization (race_audit CA001);
        an Event is the one-word flag a leader gate is allowed to be."""
        return self._leader_event.is_set()

    @is_leader.setter
    def is_leader(self, value: bool) -> None:
        if value:
            self._leader_event.set()
        else:
            self._leader_event.clear()

    @property
    def _collection_url(self) -> str:
        return (f"{self.apiserver}/apis/coordination.k8s.io/v1/namespaces/"
                f"{self.namespace}/leases")

    @property
    def _url(self) -> str:
        return f"{self._collection_url}/{self.name}"

    def _request(self, method: str, url: str, body: Optional[dict] = None):
        data = json.dumps(body).encode() if body is not None else None
        req = urllib.request.Request(
            url, data=data, method=method,
            headers={"Content-Type": "application/json"} if data else {},
        )
        if self.token:
            req.add_header("Authorization", f"Bearer {self.token}")
        ctx = ssl_context(url, self.ca_file, self.insecure_skip_verify)
        with urllib.request.urlopen(req, timeout=5, context=ctx) as r:
            payload = r.read()
        return json.loads(payload) if payload else {}

    def _lease_body(self, spec: dict,
                    resource_version: Optional[str] = None) -> dict:
        meta = {"name": self.name, "namespace": self.namespace}
        if resource_version is not None:
            # conditional update: kube rejects the PUT with 409 Conflict
            # when someone replaced the lease since our GET
            meta["resourceVersion"] = str(resource_version)
        return {
            "apiVersion": "coordination.k8s.io/v1", "kind": "Lease",
            "metadata": meta,
            "spec": spec,
        }

    def step(self, now: Optional[float] = None) -> bool:
        """One acquire-or-renew attempt; updates and returns is_leader.
        Network errors and conditional-update conflicts demote to standby
        (fail-safe: a partitioned or out-raced ex-leader must stop acting
        before a peer takes over)."""
        now = time.time() if now is None else now
        try:
            try:
                lease = self._request("GET", self._url)
            except urllib.error.HTTPError as exc:
                if exc.code != 404:
                    raise
                try:
                    self._request("POST", self._collection_url,
                                  self._lease_body({
                                      "holderIdentity": self.identity,
                                      "leaseDurationSeconds": int(
                                          self.lease_duration_s),
                                      "acquireTime": _micro_time(now),
                                      "renewTime": _micro_time(now),
                                      "leaseTransitions": 0,
                                  }))
                except urllib.error.HTTPError as create_exc:
                    if create_exc.code == 409:  # lost the create race
                        self.is_leader = False
                        return False
                    raise
                self.is_leader = True
                self.observed_holder = self.identity
                return True

            spec = lease.get("spec") or {}
            holder = spec.get("holderIdentity") or None
            renew = spec.get("renewTime")
            self.observed_holder = holder
            fresh = (
                holder is not None
                and renew is not None
                and now - _parse_micro_time(renew)
                < float(spec.get("leaseDurationSeconds",
                                 self.lease_duration_s))
            )
            if holder not in (None, self.identity) and fresh:
                self.is_leader = False
                return False
            transitions = int(spec.get("leaseTransitions") or 0)
            if holder != self.identity:
                transitions += 1  # takeover/acquisition of a stale lease
            new_spec = {
                "holderIdentity": self.identity,
                "leaseDurationSeconds": int(self.lease_duration_s),
                "acquireTime": (
                    spec.get("acquireTime", _micro_time(now))
                    if holder == self.identity else _micro_time(now)
                ),
                "renewTime": _micro_time(now),
                "leaseTransitions": transitions,
            }
            rv = (lease.get("metadata") or {}).get("resourceVersion")
            try:
                self._request("PUT", self._url,
                              self._lease_body(new_spec,
                                               resource_version=rv))
            except urllib.error.HTTPError as put_exc:
                if put_exc.code == 409:  # out-raced: stay on standby
                    self.is_leader = False
                    return False
                raise
            self.is_leader = True
            self.observed_holder = self.identity
            return True
        except Exception:
            self.is_leader = False
            return False

    def release(self) -> None:
        """Clear holderIdentity if we hold the lease (ReleaseOnCancel)."""
        if not self.is_leader:
            return
        try:
            lease = self._request("GET", self._url)
            spec = lease.get("spec") or {}
            if spec.get("holderIdentity") == self.identity:
                spec["holderIdentity"] = None
                rv = (lease.get("metadata") or {}).get("resourceVersion")
                self._request("PUT", self._url,
                              self._lease_body(spec, resource_version=rv))
        except Exception:  # graft-lint: ignore[GL010] — best-effort lease release on shutdown; the lease expires on its own
            pass
        self.is_leader = False

    def run(self, stop_event: threading.Event) -> None:
        """Renew loop until `stop_event`; releases on the way out."""
        while not stop_event.is_set():
            self.step()
            stop_event.wait(self.renew_period_s)
        self.release()
