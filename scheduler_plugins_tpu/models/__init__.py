"""Synthetic cluster/workload scenario generators for the five BASELINE.md
benchmark configurations and for tests."""

from scheduler_plugins_tpu.models.scenarios import (  # noqa: F401
    allocatable_scenario,
    gang_quota_scenario,
    metric_affinity_scenario,
    mixed_scenario,
    network_scenario,
    numa_scenario,
    rank_gang_scenario,
    trimaran_scenario,
)
