"""Synthetic scenario builders mirroring BASELINE.json's five configs:

1. NodeResourcesAllocatable Score — nodes x pods (CPU-style integration scale)
2. Trimaran TLP + LVRB — nodes with synthetic load metrics
3. NodeResourceTopology NUMA Filter+Score — nodes x NUMA zones
4. Coscheduling PodGroups + CapacityScheduling ElasticQuota — gangs x members
5. NetworkAware NetworkOverhead — multi-region AppGroup topology

All generators are deterministic (seeded numpy) so benchmark runs and
differential tests are reproducible.
"""

from __future__ import annotations

import numpy as np

from scheduler_plugins_tpu.api.objects import (
    AppGroup,
    AppGroupDependency,
    AppGroupWorkload,
    Container,
    ElasticQuota,
    LabelSelector,
    NetworkTopology,
    Node,
    NodeResourceTopology,
    NUMAZone,
    Pod,
    PodGroup,
    APP_GROUP_LABEL,
    POD_GROUP_LABEL,
    REGION_LABEL,
    TopologyManagerPolicy,
    TopologySpreadConstraint,
    WORKLOAD_SELECTOR_LABEL,
    ZONE_LABEL,
)
from scheduler_plugins_tpu.api.resources import CPU, MEMORY, PODS
from scheduler_plugins_tpu.state.cluster import Cluster

GIB = 1 << 30


def _nodes(n, cpu=64_000, mem=256 * GIB, pods=256):
    return [
        Node(name=f"node-{i:05d}", allocatable={CPU: cpu, MEMORY: mem, PODS: pods})
        for i in range(n)
    ]


def _pods(p, rng, cpu_range=(100, 4000), mem_range=(256 << 20, 8 * GIB)):
    cpus = rng.integers(*cpu_range, size=p)
    mems = rng.integers(*mem_range, size=p)
    return [
        Pod(
            name=f"pod-{i:06d}",
            creation_ms=i,
            containers=[Container(requests={CPU: int(cpus[i]), MEMORY: int(mems[i])})],
        )
        for i in range(p)
    ]


def allocatable_scenario(n_nodes=100, n_pods=1000, seed=0) -> Cluster:
    """Config 1: plain allocatable-scored placement."""
    rng = np.random.default_rng(seed)
    cluster = Cluster()
    for node in _nodes(n_nodes):
        cluster.add_node(node)
    for pod in _pods(n_pods, rng):
        cluster.add_pod(pod)
    return cluster


def trimaran_scenario(n_nodes=5000, n_pods=2000, seed=0) -> Cluster:
    """Config 2: load-aware scoring with synthetic metrics."""
    rng = np.random.default_rng(seed)
    cluster = allocatable_scenario(n_nodes, n_pods, seed)
    cluster.node_metrics = {
        f"node-{i:05d}": {
            "cpu_avg": float(rng.uniform(5, 90)),
            "cpu_std": float(rng.uniform(0, 15)),
            "mem_avg": float(rng.uniform(5, 80)),
            "mem_std": float(rng.uniform(0, 10)),
        }
        for i in range(n_nodes)
    }
    return cluster


def numa_scenario(n_nodes=1000, n_pods=1000, zones=8, seed=0) -> Cluster:
    """Config 3: NUMA-aware filter+score (guaranteed pods)."""
    rng = np.random.default_rng(seed)
    cluster = Cluster()
    for node in _nodes(n_nodes):
        cluster.add_node(node)
        per_zone_cpu = 64_000 // zones
        per_zone_mem = 256 * GIB // zones
        cluster.add_nrt(
            NodeResourceTopology(
                node_name=node.name,
                policy=TopologyManagerPolicy.SINGLE_NUMA_NODE,
                zones=[
                    NUMAZone(
                        numa_id=z,
                        available={CPU: per_zone_cpu, MEMORY: per_zone_mem},
                        costs={
                            o: 10 if o == z else 20 for o in range(zones)
                        },
                    )
                    for z in range(zones)
                ],
            )
        )
    cpus = rng.integers(500, per_zone_cpu // 2, size=n_pods)
    for i in range(n_pods):
        cpu = int(cpus[i])
        cluster.add_pod(
            Pod(
                name=f"pod-{i:06d}",
                creation_ms=i,
                containers=[
                    Container(
                        requests={CPU: cpu, MEMORY: 1 * GIB},
                        limits={CPU: cpu, MEMORY: 1 * GIB},
                    )
                ],
            )
        )
    return cluster


def gang_quota_scenario(n_gangs=100, gang_size=64, n_nodes=1000, seed=0) -> Cluster:
    """Config 4: gangs with quota-governed namespaces."""
    cluster = Cluster()
    for node in _nodes(n_nodes):
        cluster.add_node(node)
    for g in range(n_gangs):
        ns = f"team-{g % 16}"
        if ns not in cluster.quotas:
            cluster.add_quota(
                ElasticQuota(
                    name=f"eq-{ns}",
                    namespace=ns,
                    min={CPU: n_nodes * 4000, MEMORY: n_nodes * 16 * GIB},
                    max={CPU: n_nodes * 8000, MEMORY: n_nodes * 32 * GIB},
                )
            )
        cluster.add_pod_group(
            PodGroup(name=f"gang-{g:04d}", namespace=ns, min_member=gang_size)
        )
        for m in range(gang_size):
            cluster.add_pod(
                Pod(
                    name=f"gang-{g:04d}-m{m:03d}",
                    namespace=ns,
                    creation_ms=g * 1000 + m,
                    containers=[
                        Container(requests={CPU: 1000, MEMORY: 2 * GIB})
                    ],
                    labels={POD_GROUP_LABEL: f"gang-{g:04d}"},
                )
            )
    return cluster


def _add_app_group_mesh(cluster, rng, n_workloads, n_regions,
                        zones_per_region, max_network_cost):
    """Shared AppGroup("mesh") dependency chain + UserDefined zone/region
    NetworkTopology weights (used by network_scenario and mixed_scenario)."""
    workloads = [AppGroupWorkload(selector=f"wl-{w}") for w in range(n_workloads)]
    for w in range(1, n_workloads):
        workloads[w].dependencies.append(
            AppGroupDependency(
                workload_selector=f"wl-{rng.integers(0, w)}",
                max_network_cost=max_network_cost,
            )
        )
    cluster.add_app_group(
        AppGroup(
            name="mesh",
            workloads=workloads,
            topology_order={f"wl-{w}": w for w in range(n_workloads)},
        )
    )
    zone_names = [f"zone-{z}" for z in range(n_regions * zones_per_region)]
    region_names = [f"region-{r}" for r in range(n_regions)]
    cluster.add_network_topology(
        NetworkTopology(
            weights={
                "UserDefined": {
                    "zone": {
                        (a, b): 5
                        for a in zone_names
                        for b in zone_names
                        if a != b
                    },
                    "region": {
                        (a, b): 50
                        for a in region_names
                        for b in region_names
                        if a != b
                    },
                }
            }
        )
    )
    return zone_names


def mixed_scenario(n_nodes=16, n_pods=32, zones=2, n_regions=2,
                   zones_per_region=2, n_workloads=4, seed=0) -> Cluster:
    """Full-roster mixed scenario: every node carries an NRT (single-numa
    policy) AND region/zone topology labels; pods are guaranteed-QoS members
    of an AppGroup dependency graph with a zone topology-spread constraint —
    so one profile exercises allocatable scoring, NUMA zone fitting, network
    dependency thresholds and spread skew guards together (the multi-chip
    dryrun roster, VERDICT r2 item 2)."""
    rng = np.random.default_rng(seed)
    cluster = Cluster()
    per_zone_cpu = 64_000 // zones
    per_zone_mem = 256 * GIB // zones
    zone_names = [f"zone-{z}" for z in range(n_regions * zones_per_region)]
    for i, node in enumerate(_nodes(n_nodes)):
        node.labels = {
            REGION_LABEL: f"region-{i % n_regions}",
            ZONE_LABEL: zone_names[i % len(zone_names)],
        }
        cluster.add_node(node)
        cluster.add_nrt(
            NodeResourceTopology(
                node_name=node.name,
                policy=TopologyManagerPolicy.SINGLE_NUMA_NODE,
                zones=[
                    NUMAZone(
                        numa_id=z,
                        available={CPU: per_zone_cpu, MEMORY: per_zone_mem},
                        costs={o: 10 if o == z else 20 for o in range(zones)},
                    )
                    for z in range(zones)
                ],
            )
        )
    _add_app_group_mesh(cluster, rng, n_workloads, n_regions,
                        zones_per_region, max_network_cost=60)
    cpus = rng.integers(500, per_zone_cpu // 4, size=n_pods)
    for i in range(n_pods):
        cpu = int(cpus[i])
        w = int(rng.integers(0, n_workloads))
        cluster.add_pod(
            Pod(
                name=f"pod-{i:06d}",
                creation_ms=i,
                containers=[
                    Container(
                        requests={CPU: cpu, MEMORY: 1 * GIB},
                        limits={CPU: cpu, MEMORY: 1 * GIB},
                    )
                ],
                labels={
                    APP_GROUP_LABEL: "mesh",
                    WORKLOAD_SELECTOR_LABEL: f"wl-{w}",
                },
                topology_spread=[
                    TopologySpreadConstraint(
                        max_skew=max(2, n_pods // len(zone_names)),
                        topology_key=ZONE_LABEL,
                        when_unsatisfiable="DoNotSchedule",
                        label_selector=LabelSelector(
                            match_labels={APP_GROUP_LABEL: "mesh"}
                        ),
                    ),
                    TopologySpreadConstraint(
                        max_skew=1,
                        topology_key=REGION_LABEL,
                        when_unsatisfiable="ScheduleAnyway",
                        label_selector=LabelSelector(
                            match_labels={APP_GROUP_LABEL: "mesh"}
                        ),
                    ),
                ],
            )
        )
    return cluster


def metric_affinity_scenario(n_nodes=16, n_pods=32, seed=3) -> Cluster:
    """The plugin families outside `mixed_scenario`'s roster: synthetic
    load metrics (trimaran TLP/LVRB), inter-pod (anti-)affinity terms over
    zone domains with an assigned seed pod (InterPodAffinity's symmetric
    carry), and seccomp profiles (SySched syscall-set scores) — so one
    profile exercises all three under the sharded mesh."""
    from scheduler_plugins_tpu.api.objects import (
        PodAffinityTerm,
        SeccompProfile,
        WeightedPodAffinityTerm,
    )

    rng = np.random.default_rng(seed)
    cluster = Cluster()
    for i, node in enumerate(_nodes(n_nodes, cpu=16_000, mem=64 * GIB,
                                    pods=40)):
        node.labels = {ZONE_LABEL: f"z-{i % 4}"}
        cluster.add_node(node)
    cluster.node_metrics = {
        f"node-{i:05d}": {
            "cpu_avg": float(rng.uniform(5, 80)),
            "cpu_std": float(rng.uniform(0, 12)),
            "mem_avg": float(rng.uniform(5, 70)),
            "mem_std": float(rng.uniform(0, 8)),
        }
        for i in range(n_nodes)
    }
    cluster.add_seccomp_profile(SeccompProfile(
        name="web", syscalls=frozenset({"read", "write", "accept"})))
    cluster.add_seccomp_profile(SeccompProfile(
        name="db", syscalls=frozenset({"read", "write", "fsync"})))
    seed_pod = Pod(name="seed-db", labels={"app": "db"},
                   containers=[Container(
                       requests={CPU: 500},
                       seccomp_profile="operator/default/db.json")])
    seed_pod.node_name = "node-00000"
    cluster.add_pod(seed_pod)
    affinity = PodAffinityTerm(
        topology_key=ZONE_LABEL,
        label_selector=LabelSelector(match_labels={"app": "db"}),
    )
    for j in range(n_pods):
        kind = j % 3
        cluster.add_pod(Pod(
            name=f"p{j}", creation_ms=j,
            labels={"app": "web" if kind else "db"},
            containers=[Container(
                requests={
                    CPU: int(rng.integers(200, 1500)),
                    MEMORY: int(rng.integers(1, 4)) * GIB},
                seccomp_profile=(
                    "operator/default/web.json" if kind
                    else "operator/default/db.json"
                ))],
            pod_affinity_preferred=[WeightedPodAffinityTerm(
                weight=50, term=affinity)],
            pod_affinity_required=[affinity] if kind == 1 else [],
        ))
    return cluster


def rank_gang_scenario(n_nodes=96, n_regions=2, zones_per_region=3,
                       n_mpi=6, mpi_ranks=8, n_dl=4, dl_min=2, dl_desired=4,
                       dl_max=8, node_cpu=8_000, node_pods=16,
                       seed=0) -> Cluster:
    """Config 10: rank-aware MPI gangs + elastic DL jobs on a 3-level
    topology (node / zone block / region — docs/GANGS.md).

    Zones are assigned ROUND-ROBIN over the node index (node i -> zone
    i % Z), so index-order packing — what the quorum-only Coscheduling
    baseline does on a homogeneous fleet — stripes a gang ACROSS blocks
    (adjacent indices sit in different zones, often different regions),
    while the topology-block waterfill packs block-first. That makes the
    max inter-rank cost gap a property of the placement policy, not of a
    lucky node layout. Zone-pair weights exist only WITHIN a region
    (cost 5); cross-region pairs fall through to the region weight (50),
    the 3rd level.

    - MPI gangs are rigid (`min_member == ranks`) and HETEROGENEOUS:
      rank 0 (the launcher) requests 2x its workers' cpu.
    - DL jobs are elastic: `min_member=dl_min`, `desired_replicas=
      dl_desired`, `max_replicas=dl_max`, members created at desired
      width (the bench moves `desired_replicas` to exercise grow/shrink).
    - Each namespace carries an ElasticQuota sized to the fleet (the
      quota cap stays a live hard constraint, not a bench prop).
    """
    rng = np.random.default_rng(seed)
    cluster = Cluster()
    Z = n_regions * zones_per_region
    zone_names = [f"zone-{z}" for z in range(Z)]
    for i, node in enumerate(
        _nodes(n_nodes, cpu=node_cpu, mem=32 * GIB, pods=node_pods)
    ):
        z = i % Z
        node.labels = {
            REGION_LABEL: f"region-{z // zones_per_region}",
            ZONE_LABEL: zone_names[z],
        }
        cluster.add_node(node)
    zone_weights = {
        (a, b): 5
        for za, a in enumerate(zone_names)
        for zb, b in enumerate(zone_names)
        if a != b and za // zones_per_region == zb // zones_per_region
    }
    region_names = [f"region-{r}" for r in range(n_regions)]
    cluster.add_network_topology(NetworkTopology(weights={
        "UserDefined": {
            "zone": zone_weights,
            "region": {
                (a, b): 50
                for a in region_names for b in region_names if a != b
            },
        }
    }))
    ns = "mpi-team"
    # min covers the fleet on every requested resource (the aggregated-min
    # borrowing rule charges EVERY resource a pod requests, memory
    # included); max stays the live cap the gang solve and
    # CapacityScheduling both enforce
    cluster.add_quota(ElasticQuota(
        name=f"eq-{ns}", namespace=ns,
        min={CPU: n_nodes * node_cpu, MEMORY: n_nodes * 32 * GIB},
        max={CPU: n_nodes * node_cpu, MEMORY: n_nodes * 32 * GIB},
    ))

    def add_members(pg_name, count, cpus, base_ms):
        for m in range(count):
            cluster.add_pod(Pod(
                name=f"{pg_name}-r{m:03d}", namespace=ns,
                creation_ms=base_ms + m,
                containers=[Container(
                    requests={CPU: int(cpus[m]), MEMORY: 1 * GIB}
                )],
                labels={POD_GROUP_LABEL: pg_name},
            ))

    for g in range(n_mpi):
        name = f"mpi-{g:03d}"
        cluster.add_pod_group(PodGroup(
            name=name, namespace=ns, min_member=mpi_ranks,
            creation_ms=g * 1000, rank_aware=True,
        ))
        worker = int(rng.integers(800, 1600))
        cpus = [2 * worker] + [worker] * (mpi_ranks - 1)
        add_members(name, mpi_ranks, cpus, g * 1000)
    for j in range(n_dl):
        name = f"dl-{j:03d}"
        cluster.add_pod_group(PodGroup(
            name=name, namespace=ns, min_member=dl_min,
            creation_ms=(n_mpi + j) * 1000, rank_aware=True,
            desired_replicas=dl_desired, max_replicas=dl_max,
        ))
        cpu = int(rng.integers(600, 1200))
        add_members(name, dl_desired, [cpu] * dl_desired,
                    (n_mpi + j) * 1000)
    return cluster


def network_scenario(n_nodes=1000, n_pods=1000, n_regions=4, zones_per_region=4,
                     n_workloads=32, seed=0) -> Cluster:
    """Config 5: multi-region AppGroup dependency graph."""
    rng = np.random.default_rng(seed)
    cluster = Cluster()
    for i, node in enumerate(_nodes(n_nodes)):
        region = f"region-{i % n_regions}"
        zone = f"zone-{i % (n_regions * zones_per_region)}"
        node.labels = {REGION_LABEL: region, ZONE_LABEL: zone}
        cluster.add_node(node)
    _add_app_group_mesh(cluster, rng, n_workloads, n_regions,
                        zones_per_region, max_network_cost=10)
    for i in range(n_pods):
        w = int(rng.integers(0, n_workloads))
        cluster.add_pod(
            Pod(
                name=f"pod-{i:06d}",
                creation_ms=i,
                containers=[Container(requests={CPU: 500, MEMORY: 1 * GIB})],
                labels={
                    APP_GROUP_LABEL: "mesh",
                    WORKLOAD_SELECTOR_LABEL: f"wl-{w}",
                },
            )
        )
    return cluster
