"""Observability tests: metrics counters and flow-correlated logging."""

import logging

from scheduler_plugins_tpu.api.objects import Container, Node, Pod
from scheduler_plugins_tpu.api.resources import CPU, MEMORY, PODS
from scheduler_plugins_tpu.framework import Profile, Scheduler, run_cycle
from scheduler_plugins_tpu.plugins import NodeResourcesAllocatable
from scheduler_plugins_tpu.state.cluster import Cluster
from scheduler_plugins_tpu.utils import observability as obs

gib = 1 << 30


class TestMetrics:
    def test_cycle_counters(self):
        obs.metrics.reset()
        c = Cluster()
        c.add_node(Node(name="n0", allocatable={CPU: 1000, MEMORY: 4 * gib, PODS: 10}))
        c.add_pod(Pod(name="ok", creation_ms=1, containers=[Container(requests={CPU: 100})]))
        c.add_pod(Pod(name="huge", creation_ms=2, containers=[Container(requests={CPU: 99_000})]))
        run_cycle(Scheduler(Profile(plugins=[NodeResourcesAllocatable()])), c, now=1000)
        snap = obs.metrics.snapshot()
        assert snap[obs.SCHEDULING_CYCLES] == 1
        assert snap[obs.PODS_BOUND] == 1
        assert snap[obs.PODS_FAILED] == 1

    def test_flow_markers_emitted(self, caplog):
        obs.metrics.reset()
        with caplog.at_level(logging.DEBUG, logger="scheduler_plugins_tpu"):
            with obs.flow("cycle", generation=7, pending=3):
                pass
        text = caplog.text
        assert "FlowBegin" in text and "FlowEnd" in text
        assert "generation=7" in text and "durationMs" in text
