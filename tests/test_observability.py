"""Observability tests: metrics counters + histograms, prometheus text
exposition, flow-correlated logging, and the cycle tracer (span pairing,
Perfetto-loadable export, per-tid monotonicity)."""

import json
import logging

import pytest

from scheduler_plugins_tpu.api.objects import Container, Node, Pod
from scheduler_plugins_tpu.api.resources import CPU, MEMORY, PODS
from scheduler_plugins_tpu.framework import Profile, Scheduler, run_cycle
from scheduler_plugins_tpu.plugins import NodeResourcesAllocatable
from scheduler_plugins_tpu.state.cluster import Cluster
from scheduler_plugins_tpu.utils import observability as obs
from tools.trace_smoke import validate_trace

gib = 1 << 30


@pytest.fixture(autouse=True)
def _tracer_off():
    yield
    obs.tracer.stop()


class TestMetrics:
    def test_cycle_counters(self):
        obs.metrics.reset()
        c = Cluster()
        c.add_node(Node(name="n0", allocatable={CPU: 1000, MEMORY: 4 * gib, PODS: 10}))
        c.add_pod(Pod(name="ok", creation_ms=1, containers=[Container(requests={CPU: 100})]))
        c.add_pod(Pod(name="huge", creation_ms=2, containers=[Container(requests={CPU: 99_000})]))
        run_cycle(Scheduler(Profile(plugins=[NodeResourcesAllocatable()])), c, now=1000)
        snap = obs.metrics.snapshot()
        assert snap[obs.SCHEDULING_CYCLES] == 1
        assert snap[obs.PODS_BOUND] == 1
        assert snap[obs.PODS_FAILED] == 1

    def test_flow_markers_emitted(self, caplog):
        obs.metrics.reset()
        with caplog.at_level(logging.DEBUG, logger="scheduler_plugins_tpu"):
            with obs.flow("cycle", generation=7, pending=3):
                pass
        text = caplog.text
        assert "FlowBegin" in text and "FlowEnd" in text
        assert "generation=7" in text and "durationMs" in text
        assert "status=ok" in text

    def test_flow_failure_marked_on_flow_end(self, caplog):
        # an exception inside the span must NOT look like a clean FlowEnd
        with caplog.at_level(logging.DEBUG, logger="scheduler_plugins_tpu"):
            with pytest.raises(ValueError):
                with obs.flow("resync", generation=3):
                    raise ValueError("boom")
        end_line = next(
            r.getMessage() for r in caplog.records
            if obs.FLOW_END in r.getMessage()
        )
        assert "status=error" in end_line
        assert "error=ValueError" in end_line
        assert "durationMs" in end_line


class TestHistograms:
    def test_observe_keeps_legacy_summary_keys(self):
        m = obs.Metrics()
        m.observe_ms("scheduler_cycle", 12.4)
        m.observe_ms("scheduler_cycle", 3.2)
        snap = m.snapshot()
        assert snap["scheduler_cycle_ms_total"] == 15
        assert snap["scheduler_cycle_count"] == 2
        assert snap["scheduler_cycle_ms_max"] == 12

    def test_bucket_counts_cumulative_in_text(self):
        m = obs.Metrics()
        for ms in (0.5, 3.0, 30.0, 20_000.0):
            m.observe_ms("lat", ms)
        text = m.prometheus_text()
        samples = dict(
            line.rsplit(" ", 1)
            for line in text.splitlines()
            if line and not line.startswith("#")
        )
        assert samples['lat_bucket{le="1"}'] == "1"
        assert samples['lat_bucket{le="5"}'] == "2"
        assert samples['lat_bucket{le="50"}'] == "3"
        assert samples['lat_bucket{le="10000"}'] == "3"
        assert samples['lat_bucket{le="+Inf"}'] == "4"
        assert samples["lat_count"] == "4"
        assert float(samples["lat_sum"]) == pytest.approx(20_033.5)
        assert "# TYPE lat histogram" in text

    def test_labeled_histograms_and_counters(self):
        m = obs.Metrics()
        m.observe_ms(obs.PLUGIN_EXECUTION, 7.0, plugin="Coscheduling",
                     extension_point="QueueSort")
        m.inc(obs.UNSCHEDULABLE_BY_PLUGIN, plugin="NodeAffinity")
        m.inc(obs.UNSCHEDULABLE_BY_PLUGIN, plugin="NodeAffinity")
        assert m.get(obs.UNSCHEDULABLE_BY_PLUGIN, plugin="NodeAffinity") == 2
        text = m.prometheus_text()
        assert (
            'scheduler_unschedulable_by_plugin_total{plugin="NodeAffinity"} 2'
            in text
        )
        assert (
            'scheduler_plugin_execution_ms_bucket{extension_point='
            '"QueueSort",plugin="Coscheduling",le="10"} 1' in text
        )

    def test_label_values_escaped(self):
        m = obs.Metrics()
        m.inc("weird_total", plugin='a"b\\c')
        assert '{plugin="a\\"b\\\\c"}' in m.prometheus_text()

    def test_counter_type_lines(self):
        m = obs.Metrics()
        m.inc("x_total", 3)
        text = m.prometheus_text()
        assert "# TYPE x_total counter" in text
        assert "x_total 3" in text

    def test_no_duplicate_samples_for_observed_names(self):
        # the legacy <name>_count summary counter and the histogram's
        # _count child are the SAME sample: a scrape must contain each
        # sample key exactly once or prometheus rejects it wholesale
        m = obs.Metrics()
        m.observe_ms("scheduler_cycle", 4.2)
        m.inc("scheduler_pods_bound_total", 2)
        lines = [
            line for line in m.prometheus_text().splitlines()
            if line and not line.startswith("#")
        ]
        keys = [line.rsplit(" ", 1)[0] for line in lines]
        assert len(keys) == len(set(keys)), keys
        assert keys.count("scheduler_cycle_count") == 1
        # ...while the JSON snapshot keeps the legacy key for panels
        assert m.snapshot()["scheduler_cycle_count"] == 1


class TestTracer:
    def test_disabled_tracer_records_nothing(self):
        t = obs.Tracer()
        with t.span("work", tid="row"):
            pass
        t.complete("late", 0, 10)
        assert t.export()["traceEvents"] == []

    def test_span_records_complete_event_with_thread_name(self):
        t = obs.Tracer()
        t.start()
        with t.span("solve", tid="cycle", pods=3):
            pass
        t.stop()
        trace = t.export()
        xs = [e for e in trace["traceEvents"] if e["ph"] == "X"]
        ms = [e for e in trace["traceEvents"] if e["ph"] == "M"]
        assert len(xs) == 1 and xs[0]["name"] == "solve"
        assert xs[0]["args"] == {"pods": 3}
        assert xs[0]["ts"] >= 0 and xs[0]["dur"] >= 0
        assert ms[0]["name"] == "thread_name"
        assert ms[0]["args"]["name"] == "cycle"
        assert ms[0]["tid"] == xs[0]["tid"]

    def test_start_clears_previous_run(self):
        t = obs.Tracer()
        t.start()
        with t.span("old"):
            pass
        t.start()
        t.stop()
        assert t.export()["traceEvents"] == []

    def test_export_is_perfetto_valid(self):
        t = obs.Tracer()
        t.start()
        with t.span("outer", tid="cycle"):
            with t.span("inner", tid="cycle"):
                pass
        with t.span("other-row", tid="pipeline/h2d/buf0"):
            pass
        t.stop()
        assert validate_trace(t.export()) == []


class TestCycleTrace:
    def _cluster(self):
        c = Cluster()
        c.add_node(Node(name="n0",
                        allocatable={CPU: 8000, MEMORY: 32 * gib, PODS: 110}))
        c.add_pod(Pod(name="ok", creation_ms=1,
                      containers=[Container(requests={CPU: 100})]))
        c.add_pod(Pod(name="huge", creation_ms=2,
                      containers=[Container(requests={CPU: 99_000})]))
        return c

    def test_traced_cycle_exports_loadable_timeline(self, tmp_path):
        obs.tracer.start()
        run_cycle(Scheduler(Profile(plugins=[NodeResourcesAllocatable()])),
                  self._cluster(), now=1000)
        obs.tracer.stop()
        out = tmp_path / "cycle.json"
        obs.tracer.write(str(out))
        trace = json.loads(out.read_text())
        assert validate_trace(trace) == []
        events = trace["traceEvents"]
        # only Perfetto-loadable chrome-trace phases
        assert {e["ph"] for e in events} <= {"X", "B", "E", "M"}
        names = {e["name"] for e in events if e["ph"] == "X"}
        # extension points QueueSort -> Bind appear as spans
        for expected in ("QueueSort/PrioritySort",
                         "Prepare/NodeResourcesAllocatable",
                         "Solve/tpu-scheduler", "Bind", "Attribution"):
            assert expected in names, (expected, sorted(names))
        # per-tid timestamps are monotonic in record order
        by_tid = {}
        for e in events:
            if e["ph"] == "X":
                by_tid.setdefault(e["tid"], []).append(e["ts"] + e["dur"])
        for ends in by_tid.values():
            assert all(b >= a for a, b in zip(ends, ends[1:]))

    def test_untraced_cycle_is_clean_and_silent(self):
        # tracing off (the default): the same cycle runs without touching
        # the tracer event buffer (stale events from earlier traced runs
        # stay untouched until the next start(clear=True))
        before = len(obs.tracer.export()["traceEvents"])
        run_cycle(Scheduler(Profile(plugins=[NodeResourcesAllocatable()])),
                  self._cluster(), now=1000)
        assert len(obs.tracer.export()["traceEvents"]) == before


class TestServeTraceRows:
    """PR 6 gap closure: ServeEngine.refresh stages appear as spans on
    the "serve" row of a traced serve-mode cycle, and the trace stays
    Perfetto-valid with the new rows."""

    def _cluster(self):
        c = Cluster()
        for i in range(4):
            c.add_node(Node(
                name=f"n{i}",
                allocatable={CPU: 8000, MEMORY: 32 * gib, PODS: 110},
            ))
        for p in range(6):
            c.add_pod(Pod(name=f"p{p}", creation_ms=p,
                          containers=[Container(requests={CPU: 100})]))
        return c

    def test_serve_refresh_stage_spans(self):
        from scheduler_plugins_tpu.serving import ServeEngine

        cluster = self._cluster()
        engine = ServeEngine().attach(cluster)
        sched = Scheduler(Profile(plugins=[NodeResourcesAllocatable()]))
        obs.tracer.start()
        try:
            # first serve cycle re-bases; a churned second cycle applies
            # deltas and assembles from the resident columns
            run_cycle(sched, cluster, now=1000, serve=engine)
            cluster.add_pod(Pod(
                name="late", creation_ms=99,
                containers=[Container(requests={CPU: 100})],
            ))
            run_cycle(sched, cluster, now=2000, serve=engine)
        finally:
            obs.tracer.stop()
        trace = obs.tracer.export()
        assert validate_trace(trace) == []
        rows = {
            e["args"]["name"] for e in trace["traceEvents"]
            if e.get("ph") == "M" and e.get("name") == "thread_name"
        }
        assert "serve" in rows
        names = {e["name"] for e in trace["traceEvents"]
                 if e.get("ph") == "X"}
        for expected in ("ServeRefresh/drain", "ServeRefresh/classify",
                         "ServeRefresh/rebase", "ServeRefresh/apply",
                         "ServeRefresh/assemble"):
            assert expected in names, (expected, sorted(names))

    def test_untraced_serve_cycle_records_nothing(self):
        from scheduler_plugins_tpu.serving import ServeEngine

        cluster = self._cluster()
        engine = ServeEngine().attach(cluster)
        sched = Scheduler(Profile(plugins=[NodeResourcesAllocatable()]))
        before = len(obs.tracer.export()["traceEvents"])
        run_cycle(sched, cluster, now=1000, serve=engine)
        assert len(obs.tracer.export()["traceEvents"]) == before


class TestShardWaveTraceRows:
    """PR 7 gap closure: a traced sharded-wave solve emits per-chunk rows
    (waves + wave_occupancy) and the static collective census on the
    "shard_wave" row, and the merged trace stays Perfetto-valid."""

    def test_shard_wave_rows_and_census(self):
        import jax.numpy as jnp

        from scheduler_plugins_tpu.models import allocatable_scenario
        from scheduler_plugins_tpu.parallel.mesh import make_node_mesh
        from scheduler_plugins_tpu.parallel.solver import sharded_wave_solve

        cluster = allocatable_scenario(n_nodes=64, n_pods=256)
        pending = sorted(cluster.pending_pods(), key=lambda p: p.creation_ms)
        snap, meta = cluster.snapshot(pending, now_ms=0)
        weights = jnp.asarray(
            meta.index.encode({CPU: 1 << 20, MEMORY: 1}), jnp.int64
        )
        mesh = make_node_mesh(8)
        obs.tracer.start()
        try:
            sharded_wave_solve(
                snap, mesh, weights, chunk=128, collect_stats=True
            )
        finally:
            obs.tracer.stop()
        trace = obs.tracer.export()
        assert validate_trace(trace) == []
        rows = {
            e["args"]["name"] for e in trace["traceEvents"]
            if e.get("ph") == "M" and e.get("name") == "thread_name"
        }
        assert "shard_wave" in rows
        spans = [e for e in trace["traceEvents"] if e.get("ph") == "X"]
        chunks = [e for e in spans if e["name"].startswith("chunk[")]
        assert len(chunks) == 2  # 256 pods / 128 chunk
        for e in chunks:
            assert e["args"]["waves"] >= 1
            assert sum(e["args"]["wave_occupancy"]) > 0
        census = [e for e in spans if e["name"] == "census"]
        assert len(census) == 1
        args = census[0]["args"]
        assert args["shards"] == 8
        # the ring election never gathers the node axis (GL009's
        # trace-level twin)
        for prim in ("all_gather", "all_gather_invariant", "all_to_all"):
            assert args.get(prim, 0) == 0
        assert sum(
            v for k, v in args.items()
            if k in ("psum", "pmin", "pmax", "ppermute")
        ) > 0
