"""Per-plugin unschedulability attribution (`CycleReport.failed_by`, the
upstream UnschedulablePlugins signal) — a decision table covering EVERY
plugin with a Filter plus the built-in fit and a PreFilter rejection, each
asserting (a) the sequential parity path names the responsible plugin and
(b) the batched reduction (`Scheduler.attribution_codes`, what streamed /
batched solves use) decodes to the same name."""

import numpy as np
import pytest

from scheduler_plugins_tpu.api.objects import (
    AppGroup,
    AppGroupDependency,
    AppGroupWorkload,
    Container,
    LabelSelector,
    NetworkTopology,
    Node,
    NodeResourceTopology,
    NodeSelectorRequirement,
    NodeSelectorTerm,
    NUMAZone,
    Pod,
    PodGroup,
    PodAffinityTerm,
    Taint,
    TopologyManagerPolicy,
    TopologyManagerScope,
    TopologySpreadConstraint,
    APP_GROUP_LABEL,
    POD_GROUP_LABEL,
    REGION_LABEL,
    WORKLOAD_SELECTOR_LABEL,
    ZONE_LABEL,
)
from scheduler_plugins_tpu.api.resources import CPU, MEMORY, PODS
from scheduler_plugins_tpu.framework import Profile, Scheduler, run_cycle
from scheduler_plugins_tpu.framework.runtime import BUILTIN_FIT
from scheduler_plugins_tpu.plugins import (
    Coscheduling,
    InterPodAffinity,
    NetworkOverhead,
    NodeAffinity,
    NodeResourcesAllocatable,
    NodeResourceTopologyMatch,
    PodTopologySpread,
    TaintToleration,
)
from scheduler_plugins_tpu.state.cluster import Cluster

gib = 1 << 30
ZONE = "topology.kubernetes.io/zone"


def mknode(name, labels=None, taints=None, cpu=8000):
    return Node(
        name=name,
        allocatable={CPU: cpu, MEMORY: 32 * gib, PODS: 110},
        labels=labels or {},
        taints=taints or [],
    )


def mkpod(name, cpu=100, **kw):
    return Pod(
        name=name,
        containers=[Container(requests={CPU: cpu, MEMORY: gib})],
        **kw,
    )


def _node_affinity_case():
    c = Cluster()
    c.add_node(mknode("a", {"disk": "hdd"}))
    c.add_pod(mkpod("p", node_selector={"disk": "ssd"}))
    plugins = [NodeResourcesAllocatable(), NodeAffinity(), TaintToleration()]
    return c, plugins, "default/p", "NodeAffinity"


def _taint_case():
    c = Cluster()
    c.add_node(mknode("a", taints=[Taint(key="dedicated", value="gpu")]))
    c.add_pod(mkpod("p"))
    plugins = [NodeResourcesAllocatable(), NodeAffinity(), TaintToleration()]
    return c, plugins, "default/p", "TaintToleration"


def _spread_case():
    # both schedulable nodes sit in z-a holding 2 matching pods; the empty
    # z-b domain (its node cordoned) pins the global min at 0, so maxSkew 1
    # blocks z-a — PodTopologySpread empties the feasible set
    c = Cluster()
    c.add_node(mknode("n0", {ZONE: "z-a"}))
    c.add_node(mknode("n1", {ZONE: "z-a"}))
    blocked = mknode("n2", {ZONE: "z-b"})
    blocked.unschedulable = True
    c.add_node(blocked)
    for i in range(2):
        existing = Pod(name=f"e{i}", labels={"app": "web"},
                       containers=[Container(requests={CPU: 100})])
        existing.node_name = "n0"
        c.add_pod(existing)
    c.add_pod(Pod(
        name="p", labels={"app": "web"},
        containers=[Container(requests={CPU: 100, MEMORY: gib})],
        topology_spread=[TopologySpreadConstraint(
            max_skew=1, topology_key=ZONE,
            when_unsatisfiable="DoNotSchedule",
            label_selector=LabelSelector(match_labels={"app": "web"}),
        )],
    ))
    plugins = [NodeResourcesAllocatable(), PodTopologySpread()]
    return c, plugins, "default/p", "PodTopologySpread"


def _inter_pod_affinity_case():
    # required affinity toward app=db with no db pod anywhere (and no
    # self-match): InterPodAffinity filters every node
    c = Cluster()
    c.add_node(mknode("n0", {ZONE: "z-a"}))
    c.add_node(mknode("n1", {ZONE: "z-b"}))
    c.add_pod(Pod(
        name="web", labels={"app": "web"},
        containers=[Container(requests={CPU: 100})],
        pod_affinity_required=[PodAffinityTerm(
            topology_key=ZONE,
            label_selector=LabelSelector(match_labels={"app": "db"}),
        )],
    ))
    plugins = [NodeResourcesAllocatable(), InterPodAffinity()]
    return c, plugins, "default/web", "InterPodAffinity"


def _network_case():
    # the only uncordoned node violates the dependency's maxNetworkCost
    def net_node(name, region, zone):
        return Node(
            name=name,
            allocatable={CPU: 10_000, MEMORY: 32 * gib, PODS: 110},
            labels={REGION_LABEL: region, ZONE_LABEL: zone},
        )

    c = Cluster()
    c.add_node(net_node("na1", "r-a", "z-a1"))
    c.add_node(net_node("nb1", "r-b", "z-b1"))
    c.nodes["na1"].unschedulable = True
    c.add_app_group(AppGroup(
        name="ag",
        workloads=[
            AppGroupWorkload(selector="db"),
            AppGroupWorkload(selector="web", dependencies=[
                AppGroupDependency(workload_selector="db",
                                   max_network_cost=5),
            ]),
        ],
        topology_order={"db": 1, "web": 2},
    ))
    c.add_network_topology(NetworkTopology(weights={
        "UserDefined": {
            "region": {("r-a", "r-b"): 50, ("r-b", "r-a"): 50},
        }
    }))
    db = Pod(name="db-0", containers=[Container(requests={CPU: 100})],
             labels={APP_GROUP_LABEL: "ag", WORKLOAD_SELECTOR_LABEL: "db"})
    db.node_name = "na1"
    c.add_pod(db)
    c.add_pod(Pod(
        name="web-0", containers=[Container(requests={CPU: 100})],
        labels={APP_GROUP_LABEL: "ag", WORKLOAD_SELECTOR_LABEL: "web"},
    ))
    plugins = [NetworkOverhead()]
    return c, plugins, "default/web-0", "NetworkOverhead"


def _numa_case():
    # 5 cores fit the node total but no single NUMA zone: the topology
    # match filter rejects while the built-in fit passes
    c = Cluster()
    c.add_node(Node(name="n0", allocatable={CPU: 8000, MEMORY: 32 * gib,
                                            PODS: 110}))
    c.add_nrt(NodeResourceTopology(
        node_name="n0",
        zones=[
            NUMAZone(numa_id=i,
                     available={CPU: 4000, MEMORY: 16 * gib},
                     costs={0: 10 if i == 0 else 20,
                            1: 10 if i == 1 else 20})
            for i in range(2)
        ],
        policy=TopologyManagerPolicy.SINGLE_NUMA_NODE,
        scope=TopologyManagerScope.CONTAINER,
    ))
    c.add_pod(Pod(name="p", containers=[Container(
        requests={CPU: 5000, MEMORY: 8 * gib},
        limits={CPU: 5000, MEMORY: 8 * gib},
    )]))
    plugins = [NodeResourceTopologyMatch()]
    return c, plugins, "default/p", "NodeResourceTopologyMatch"


def _builtin_fit_case():
    c = Cluster()
    c.add_node(mknode("a"))
    c.add_pod(mkpod("huge", cpu=99_000))
    plugins = [NodeResourcesAllocatable()]
    return c, plugins, "default/huge", BUILTIN_FIT


def _coscheduling_prefilter_case():
    # gang of minMember 3 with a single member present: Coscheduling's
    # PreFilter (membership sweep) rejects before any node is considered
    c = Cluster()
    c.add_node(mknode("a"))
    c.add_pod_group(PodGroup(name="g", namespace="default", min_member=3,
                             creation_ms=0))
    c.add_pod(mkpod("p", labels={POD_GROUP_LABEL: "g"}))
    plugins = [NodeResourcesAllocatable(), Coscheduling()]
    return c, plugins, "default/p", "Coscheduling"


CASES = [
    _node_affinity_case,
    _taint_case,
    _spread_case,
    _inter_pod_affinity_case,
    _network_case,
    _numa_case,
    _builtin_fit_case,
    _coscheduling_prefilter_case,
]


class TestFailedByDecisionTable:
    @pytest.mark.parametrize("case", CASES, ids=lambda c: c.__name__)
    def test_sequential_cycle_names_responsible_plugin(self, case):
        cluster, plugins, uid, expected = case()
        report = run_cycle(Scheduler(Profile(plugins=plugins)), cluster,
                           now=1000)
        assert uid in report.failed
        assert report.failed_by[uid] == expected

    @pytest.mark.parametrize("case", CASES, ids=lambda c: c.__name__)
    def test_batched_reduction_matches_sequential(self, case):
        # the batched/streamed attribution (cycle-initial per-plugin mask
        # reduction) must decode to the same plugin the sequential parity
        # path's in-solve codes name
        cluster, plugins, uid, expected = case()
        sched = Scheduler(Profile(plugins=plugins))
        pending = sched.sort_pending(cluster.pending_pods(), cluster)
        snap, meta = cluster.snapshot(pending, now_ms=1000)
        sched.prepare(meta, cluster)
        seq_codes = np.asarray(sched.solve(snap).failed_plugin)
        names = sched.fail_plugin_names()
        uid_idx = next(
            i for i, p in enumerate(pending)
            if f"{p.namespace}/{p.name}" == uid
        )
        red_codes = sched.attribution_codes(snap, [uid_idx])
        assert red_codes.shape == (1,)  # failed rows only, unpadded
        assert seq_codes[uid_idx] >= 0  # the pod failed in the scan
        decode = lambda code: names[code] if code > 0 else names[0]
        assert decode(int(seq_codes[uid_idx])) == expected
        assert decode(int(red_codes[0])) == expected

    def test_placed_pods_carry_no_attribution(self):
        cluster, plugins, uid, _ = _builtin_fit_case()
        cluster.add_pod(mkpod("fits", cpu=100))
        report = run_cycle(Scheduler(Profile(plugins=plugins)), cluster,
                           now=1000)
        assert "default/fits" in report.bound
        assert "default/fits" not in report.failed_by
        assert set(report.failed_by) == {uid}

    def test_metrics_counter_populated(self):
        from scheduler_plugins_tpu.utils import observability as obs

        obs.metrics.reset()
        cluster, plugins, uid, expected = _taint_case()
        run_cycle(Scheduler(Profile(plugins=plugins)), cluster, now=1000)
        assert obs.metrics.get(obs.UNSCHEDULABLE_BY_PLUGIN,
                               plugin=expected) == 1

    def test_streamed_cycle_attributes_failures(self):
        # the streamed chunk-pipeline solve returns no per-pod codes; the
        # cycle must fall back to the batched reduction
        c = Cluster()
        for i in range(4):
            c.add_node(mknode(f"n{i}"))
        for p in range(7):
            c.add_pod(mkpod(f"p{p}", cpu=100))
        c.add_pod(mkpod("huge", cpu=99_000))
        report = run_cycle(
            Scheduler(Profile(plugins=[NodeResourcesAllocatable()])), c,
            now=1000, stream_chunk=4,
        )
        assert report.failed_by["default/huge"] == BUILTIN_FIT
