"""In-tree companion plugins (NodeAffinity, TaintToleration): decision tables
mirroring upstream kube-scheduler plugin unit-test patterns (these plugins are
not in /root/reference; see docs/PARITY.md "companion plugins")."""

import numpy as np

from scheduler_plugins_tpu.api.objects import (
    Container,
    Node,
    NodeSelectorRequirement,
    NodeSelectorTerm,
    Pod,
    PreferredSchedulingTerm,
    Taint,
    Toleration,
)
from scheduler_plugins_tpu.api.resources import CPU, MEMORY, PODS
from scheduler_plugins_tpu.framework import Profile, Scheduler, run_cycle
from scheduler_plugins_tpu.plugins import (
    NodeAffinity,
    NodeResourcesAllocatable,
    TaintToleration,
)
from scheduler_plugins_tpu.state.cluster import Cluster

gib = 1 << 30


def mknode(name, labels=None, taints=None):
    return Node(
        name=name,
        allocatable={CPU: 8000, MEMORY: 32 * gib, PODS: 110},
        labels=labels or {},
        taints=taints or [],
    )


def mkpod(name, **kw):
    return Pod(name=name, containers=[Container(requests={CPU: 100, MEMORY: gib})], **kw)


def run(nodes, pods, plugins=None):
    c = Cluster()
    for n in nodes:
        c.add_node(n)
    for p in pods:
        c.add_pod(p)
    sched = Scheduler(Profile(plugins=plugins or [NodeResourcesAllocatable(),
                                                  NodeAffinity(), TaintToleration()]))
    return run_cycle(sched, c, now=1000), c


class TestNodeSelector:
    def test_selector_restricts_to_labeled_node(self):
        r, c = run(
            [mknode("a", {"disk": "hdd"}), mknode("b", {"disk": "ssd"})],
            [mkpod("p", node_selector={"disk": "ssd"})],
        )
        assert r.bound["default/p"] == "b"

    def test_selector_no_match_unschedulable(self):
        r, c = run([mknode("a", {"disk": "hdd"})],
                   [mkpod("p", node_selector={"disk": "ssd"})])
        assert "default/p" in r.failed

    def test_no_selector_unconstrained(self):
        r, c = run([mknode("a"), mknode("b")], [mkpod("p")])
        assert "default/p" in r.bound


class TestNodeAffinityRequired:
    def _term(self, key, op, *values):
        return NodeSelectorTerm(match_expressions=[
            NodeSelectorRequirement(key=key, operator=op, values=tuple(values))])

    def test_terms_are_ored(self):
        # pod accepts ssd OR gpu nodes
        r, c = run(
            [mknode("a", {"disk": "hdd"}), mknode("b", {"gpu": "yes"})],
            [mkpod("p", node_affinity_required=[
                self._term("disk", "In", "ssd"), self._term("gpu", "Exists")])],
        )
        assert r.bound["default/p"] == "b"

    def test_expressions_are_anded(self):
        term = NodeSelectorTerm(match_expressions=[
            NodeSelectorRequirement(key="disk", operator="In", values=("ssd",)),
            NodeSelectorRequirement(key="zone", operator="In", values=("z1",)),
        ])
        r, c = run(
            [mknode("a", {"disk": "ssd"}), mknode("b", {"disk": "ssd", "zone": "z1"})],
            [mkpod("p", node_affinity_required=[term])],
        )
        assert r.bound["default/p"] == "b"

    def test_notin_matches_absent_label(self):
        r, c = run(
            [mknode("a", {"tier": "db"}), mknode("b")],
            [mkpod("p", node_affinity_required=[self._term("tier", "NotIn", "db")])],
        )
        assert r.bound["default/p"] == "b"

    def test_gt_numeric(self):
        r, c = run(
            [mknode("a", {"cores": "8"}), mknode("b", {"cores": "64"})],
            [mkpod("p", node_affinity_required=[self._term("cores", "Gt", "16")])],
        )
        assert r.bound["default/p"] == "b"

    def test_match_fields_metadata_name(self):
        term = NodeSelectorTerm(match_fields=[
            NodeSelectorRequirement(key="metadata.name", operator="In", values=("b",))])
        r, c = run([mknode("a"), mknode("b")],
                   [mkpod("p", node_affinity_required=[term])])
        assert r.bound["default/p"] == "b"


class TestNodeAffinityPreferred:
    def test_weighted_preference_steers(self):
        pref = [PreferredSchedulingTerm(weight=100, preference=NodeSelectorTerm(
            match_expressions=[NodeSelectorRequirement(key="disk", operator="In",
                                                       values=("ssd",))]))]
        r, c = run(
            [mknode("a", {"disk": "hdd"}), mknode("b", {"disk": "ssd"})],
            [mkpod("p", node_affinity_preferred=pref)],
            plugins=[NodeAffinity()],
        )
        assert r.bound["default/p"] == "b"

    def test_weights_sum_across_terms(self):
        mk = lambda key, val, w: PreferredSchedulingTerm(weight=w,
            preference=NodeSelectorTerm(match_expressions=[
                NodeSelectorRequirement(key=key, operator="In", values=(val,))]))
        # a matches one 60-weight term; b matches two 40-weight terms
        r, c = run(
            [mknode("a", {"x": "1"}), mknode("b", {"y": "1", "z": "1"})],
            [mkpod("p", node_affinity_preferred=[
                mk("x", "1", 60), mk("y", "1", 40), mk("z", "1", 40)])],
            plugins=[NodeAffinity()],
        )
        assert r.bound["default/p"] == "b"


class TestTaintToleration:
    def test_untolerated_noschedule_filters(self):
        r, c = run(
            [mknode("a", taints=[Taint(key="dedicated", value="gpu")]), mknode("b")],
            [mkpod("p")],
        )
        assert r.bound["default/p"] == "b"

    def test_tolerated_taint_passes(self):
        r, c = run(
            [mknode("a", taints=[Taint(key="dedicated", value="gpu")])],
            [mkpod("p", tolerations=[Toleration(key="dedicated", value="gpu")])],
        )
        assert r.bound["default/p"] == "a"

    def test_exists_toleration_ignores_value(self):
        r, c = run(
            [mknode("a", taints=[Taint(key="dedicated", value="anything")])],
            [mkpod("p", tolerations=[Toleration(key="dedicated", operator="Exists")])],
        )
        assert r.bound["default/p"] == "a"

    def test_empty_key_exists_tolerates_everything(self):
        r, c = run(
            [mknode("a", taints=[Taint(key="k1"), Taint(key="k2", effect="NoExecute")])],
            [mkpod("p", tolerations=[Toleration(operator="Exists")])],
        )
        assert r.bound["default/p"] == "a"

    def test_effect_scoped_toleration(self):
        # toleration scoped to NoSchedule does not cover a NoExecute taint
        r, c = run(
            [mknode("a", taints=[Taint(key="k", effect="NoExecute")]), mknode("b")],
            [mkpod("p", tolerations=[Toleration(key="k", operator="Exists",
                                                effect="NoSchedule")])],
        )
        assert r.bound["default/p"] == "b"

    def test_all_nodes_tainted_unschedulable(self):
        r, c = run([mknode("a", taints=[Taint(key="k")])], [mkpod("p")])
        assert "default/p" in r.failed

    def test_prefer_noschedule_scores_away(self):
        r, c = run(
            [mknode("a", taints=[Taint(key="k", effect="PreferNoSchedule")]),
             mknode("b")],
            [mkpod("p")],
            plugins=[TaintToleration()],
        )
        assert r.bound["default/p"] == "b"

    def test_prefer_noschedule_is_soft(self):
        r, c = run(
            [mknode("a", taints=[Taint(key="k", effect="PreferNoSchedule")])],
            [mkpod("p")],
        )
        assert r.bound["default/p"] == "a"


class TestSpecInterning:
    def test_replicas_share_rows(self):
        from scheduler_plugins_tpu.state.scheduling import build_scheduling
        nodes = [mknode("a", {"disk": "ssd"}), mknode("b")]
        pods = [mkpod(f"p{i}", node_selector={"disk": "ssd"},
                      tolerations=[Toleration(key="k", operator="Exists")])
                for i in range(50)]
        s = build_scheduling(nodes, pods, N=4, P=64)
        assert s.node_term_ok.shape[0] == 2  # one unique spec + pad row
        assert s.tol_ok.shape[0] == 1
        assert (np.asarray(s.pod_node_term[:50]) == 0).all()


from scheduler_plugins_tpu.api.objects import LabelSelector, TopologySpreadConstraint
from scheduler_plugins_tpu.plugins import PodTopologySpread

ZONE = "topology.kubernetes.io/zone"


def spread_pod(name, order=0, hard=True, max_skew=1, key=ZONE, labels=None):
    sel = LabelSelector(match_labels={"app": "web"})
    return Pod(
        name=name,
        creation_ms=order,
        labels=labels if labels is not None else {"app": "web"},
        containers=[Container(requests={CPU: 100, MEMORY: gib})],
        topology_spread=[TopologySpreadConstraint(
            max_skew=max_skew, topology_key=key,
            when_unsatisfiable="DoNotSchedule" if hard else "ScheduleAnyway",
            label_selector=sel)],
    )


class TestPodTopologySpread:
    def _zones(self, *zone_of_node):
        return [mknode(f"n{i}", {ZONE: z}) for i, z in enumerate(zone_of_node)]

    def test_hard_skew_blocks_overloaded_domain(self):
        # z-a already has 2 matching pods, z-b has 0; maxSkew 1 forces z-b
        c = Cluster()
        for n in self._zones("z-a", "z-a", "z-b"):
            c.add_node(n)
        for i in range(2):
            existing = Pod(name=f"e{i}", labels={"app": "web"},
                           containers=[Container(requests={CPU: 100})])
            existing.node_name = "n0"
            c.add_pod(existing)
        c.add_pod(spread_pod("p"))
        sched = Scheduler(Profile(plugins=[NodeResourcesAllocatable(),
                                           PodTopologySpread()]))
        r = run_cycle(sched, c, now=1000)
        assert r.bound["default/p"] == "n2"  # the z-b node

    def test_in_cycle_placements_update_skew(self):
        # 4 replicas over 2 zones: the carry must alternate domains, never
        # exceeding skew 1 at any point in the sequential placement
        c = Cluster()
        for n in self._zones("z-a", "z-a", "z-b", "z-b"):
            c.add_node(n)
        for j in range(4):
            c.add_pod(spread_pod(f"p{j}", order=j))
        sched = Scheduler(Profile(plugins=[NodeResourcesAllocatable(),
                                           PodTopologySpread()]))
        r = run_cycle(sched, c, now=1000)
        zones = {"z-a": 0, "z-b": 0}
        for uid, node in r.bound.items():
            zones[{"n0": "z-a", "n1": "z-a", "n2": "z-b", "n3": "z-b"}[node]] += 1
        assert len(r.bound) == 4
        assert abs(zones["z-a"] - zones["z-b"]) <= 1

    def test_node_missing_key_fails_hard_constraint(self):
        c = Cluster()
        c.add_node(mknode("labeled", {ZONE: "z-a"}))
        c.add_node(mknode("bare"))
        c.add_pod(spread_pod("p"))
        sched = Scheduler(Profile(plugins=[NodeResourcesAllocatable(),
                                           PodTopologySpread()]))
        r = run_cycle(sched, c, now=1000)
        assert r.bound["default/p"] == "labeled"

    def test_unschedulable_when_skew_cannot_hold(self):
        # one zone only has capacity... rather: both nodes in z-a with 3
        # existing matches, maxSkew 1 vs empty existing z-b domain that has
        # no node? -> z-b nodes all cordoned: pod cannot schedule into z-a
        c = Cluster()
        nodes = self._zones("z-a", "z-b")
        nodes[1].unschedulable = True
        for n in nodes:
            c.add_node(n)
        for i in range(2):
            e = Pod(name=f"e{i}", labels={"app": "web"},
                    containers=[Container(requests={CPU: 100})])
            e.node_name = "n0"
            c.add_pod(e)
        c.add_pod(spread_pod("p"))
        sched = Scheduler(Profile(plugins=[NodeResourcesAllocatable(),
                                           PodTopologySpread()]))
        r = run_cycle(sched, c, now=1000)
        # skew on z-a would become 3 vs 0 on (existing, nodeless) z-b
        assert "default/p" in r.failed

    def test_soft_constraint_scores_toward_sparse_domain(self):
        c = Cluster()
        for n in self._zones("z-a", "z-b"):
            c.add_node(n)
        e = Pod(name="e", labels={"app": "web"},
                containers=[Container(requests={CPU: 100})])
        e.node_name = "n0"
        c.add_pod(e)
        c.add_pod(spread_pod("p", hard=False))
        sched = Scheduler(Profile(plugins=[PodTopologySpread()]))
        r = run_cycle(sched, c, now=1000)
        assert r.bound["default/p"] == "n1"

    def test_non_matching_pod_unaffected(self):
        # a pod whose labels do not match its own selector still spreads by
        # counts but does not increment them for later pods
        c = Cluster()
        for n in self._zones("z-a", "z-b"):
            c.add_node(n)
        c.add_pod(spread_pod("p0", order=0, labels={"app": "other"}))
        c.add_pod(spread_pod("p1", order=1, labels={"app": "other"}))
        sched = Scheduler(Profile(plugins=[NodeResourcesAllocatable(),
                                           PodTopologySpread()]))
        r = run_cycle(sched, c, now=1000)
        assert len(r.bound) == 2  # skew stays 0-0, both place

    def test_batched_mode_respects_hard_spread(self):
        # cross-node same-wave conflict: 6 replicas, 2 zones x 2 nodes,
        # maxSkew 1 -> at most ... replay oracle in queue order
        from scheduler_plugins_tpu.parallel.solver import profile_batch_solve

        c = Cluster()
        for n in self._zones("z-a", "z-a", "z-b", "z-b"):
            c.add_node(n)
        for j in range(6):
            c.add_pod(spread_pod(f"p{j}", order=j))
        sched = Scheduler(Profile(plugins=[NodeResourcesAllocatable(),
                                           PodTopologySpread()]))
        pending = sched.sort_pending(c.pending_pods(), c)
        snap, meta = c.snapshot(pending, now_ms=0)
        sched.prepare(meta, c)
        an = np.asarray(profile_batch_solve(sched, snap)[0])[: len(pending)]
        zone_of = {0: "z-a", 1: "z-a", 2: "z-b", 3: "z-b"}
        counts = {"z-a": 0, "z-b": 0}
        for q, n in enumerate(an):
            if n < 0:
                continue
            # replay: at placement time (queue order) the skew must hold
            counts[zone_of[int(n)]] += 1
            assert abs(counts["z-a"] - counts["z-b"]) <= 1, (q, counts)
        assert (an >= 0).sum() >= 4


from scheduler_plugins_tpu.api.objects import (
    PodAffinityTerm,
    WeightedPodAffinityTerm,
)
from scheduler_plugins_tpu.plugins import InterPodAffinity


def term(key=ZONE, app="db", namespaces=()):
    return PodAffinityTerm(
        topology_key=key,
        label_selector=LabelSelector(match_labels={"app": app}),
        namespaces=tuple(namespaces),
    )


def zone_nodes(*zones):
    return [mknode(f"n{i}", {ZONE: z}) for i, z in enumerate(zones)]


def assigned(name, node, labels, **kw):
    p = Pod(name=name, labels=labels,
            containers=[Container(requests={CPU: 100})], **kw)
    p.node_name = node
    return p


def ipa_sched():
    return Scheduler(Profile(plugins=[NodeResourcesAllocatable(),
                                      InterPodAffinity()]))


class TestInterPodAffinity:
    def test_required_affinity_colocates_by_domain(self):
        c = Cluster()
        for n in zone_nodes("z-a", "z-a", "z-b"):
            c.add_node(n)
        c.add_pod(assigned("db-0", "n0", {"app": "db"}))
        c.add_pod(Pod(name="web", labels={"app": "web"},
                      containers=[Container(requests={CPU: 100})],
                      pod_affinity_required=[term()]))
        r = run_cycle(ipa_sched(), c, now=1000)
        assert r.bound["default/web"] in ("n0", "n1")  # the z-a domain

    def test_required_affinity_unschedulable_without_match(self):
        c = Cluster()
        for n in zone_nodes("z-a", "z-b"):
            c.add_node(n)
        c.add_pod(Pod(name="web", labels={"app": "web"},
                      containers=[Container(requests={CPU: 100})],
                      pod_affinity_required=[term()]))
        r = run_cycle(ipa_sched(), c, now=1000)
        assert "default/web" in r.failed

    def test_first_pod_self_match_escape(self):
        # nobody matches app=db, but the pod matches its own term -> allowed
        c = Cluster()
        for n in zone_nodes("z-a"):
            c.add_node(n)
        c.add_pod(Pod(name="db-0", labels={"app": "db"},
                      containers=[Container(requests={CPU: 100})],
                      pod_affinity_required=[term()]))
        r = run_cycle(ipa_sched(), c, now=1000)
        assert r.bound["default/db-0"] == "n0"

    def test_in_cycle_affinity_sees_earlier_placement(self):
        # db places first (self-escape), web must follow into db's domain
        c = Cluster()
        for n in zone_nodes("z-a", "z-b"):
            c.add_node(n)
        c.add_pod(Pod(name="db-0", creation_ms=1, labels={"app": "db"},
                      containers=[Container(requests={CPU: 100})],
                      pod_affinity_required=[term(app="db")]))
        c.add_pod(Pod(name="web", creation_ms=2, labels={"app": "web"},
                      containers=[Container(requests={CPU: 100})],
                      pod_affinity_required=[term(app="db")]))
        r = run_cycle(ipa_sched(), c, now=1000)
        assert r.bound["default/web"] == r.bound["default/db-0"]

    def test_own_anti_affinity_avoids_domain(self):
        c = Cluster()
        for n in zone_nodes("z-a", "z-b"):
            c.add_node(n)
        c.add_pod(assigned("db-0", "n0", {"app": "db"}))
        c.add_pod(Pod(name="db-1", labels={"app": "db"},
                      containers=[Container(requests={CPU: 100})],
                      pod_anti_affinity_required=[term(app="db")]))
        r = run_cycle(ipa_sched(), c, now=1000)
        assert r.bound["default/db-1"] == "n1"

    def test_existing_pod_anti_affinity_symmetry(self):
        # the ASSIGNED pod carries the anti term; the incoming pod has no
        # constraints but matches the term's selector -> blocked from z-a
        c = Cluster()
        for n in zone_nodes("z-a", "z-b"):
            c.add_node(n)
        c.add_pod(assigned("lonely", "n0", {"app": "db"},
                           pod_anti_affinity_required=[term(app="db")]))
        c.add_pod(Pod(name="db-1", labels={"app": "db"},
                      containers=[Container(requests={CPU: 100})]))
        r = run_cycle(ipa_sched(), c, now=1000)
        assert r.bound["default/db-1"] == "n1"

    def test_in_cycle_anti_carrier_blocks_later_pod(self):
        # replicas with self-anti-affinity spread one per zone; the third
        # has nowhere to go
        c = Cluster()
        for n in zone_nodes("z-a", "z-a", "z-b"):
            c.add_node(n)
        for j in range(3):
            c.add_pod(Pod(name=f"db-{j}", creation_ms=j, labels={"app": "db"},
                          containers=[Container(requests={CPU: 100})],
                          pod_anti_affinity_required=[term(app="db")]))
        r = run_cycle(ipa_sched(), c, now=1000)
        assert len(r.bound) == 2
        zones = {r.bound[u][:2] for u in r.bound}  # n0/n1 vs n2
        bound_nodes = set(r.bound.values())
        assert not {"n0", "n1"} <= bound_nodes  # never two in z-a

    def test_preferred_affinity_steers(self):
        c = Cluster()
        for n in zone_nodes("z-a", "z-b"):
            c.add_node(n)
        c.add_pod(assigned("db-0", "n0", {"app": "db"}))
        c.add_pod(Pod(name="web", labels={"app": "web"},
                      containers=[Container(requests={CPU: 100})],
                      pod_affinity_preferred=[
                          WeightedPodAffinityTerm(weight=100, term=term())]))
        r = run_cycle(Scheduler(Profile(plugins=[InterPodAffinity()])), c,
                      now=1000)
        assert r.bound["default/web"] == "n0"

    def test_preferred_anti_affinity_steers_away(self):
        c = Cluster()
        for n in zone_nodes("z-a", "z-b"):
            c.add_node(n)
        c.add_pod(assigned("db-0", "n0", {"app": "db"}))
        c.add_pod(Pod(name="db-1", labels={"app": "db"},
                      containers=[Container(requests={CPU: 100})],
                      pod_anti_affinity_preferred=[
                          WeightedPodAffinityTerm(weight=100, term=term())]))
        r = run_cycle(Scheduler(Profile(plugins=[InterPodAffinity()])), c,
                      now=1000)
        assert r.bound["default/db-1"] == "n1"

    def test_namespace_scope(self):
        # term scoped to namespace "prod": a "dev" db does not satisfy it
        c = Cluster()
        for n in zone_nodes("z-a", "z-b"):
            c.add_node(n)
        c.add_pod(assigned("db-dev", "n0", {"app": "db"}, namespace="dev"))
        c.add_pod(Pod(name="web", namespace="prod", labels={"app": "web"},
                      containers=[Container(requests={CPU: 100})],
                      pod_affinity_required=[term(namespaces=("prod",))]))
        r = run_cycle(ipa_sched(), c, now=1000)
        assert "prod/web" in r.failed

    def test_batched_anti_affinity_respected(self):
        from scheduler_plugins_tpu.parallel.solver import profile_batch_solve

        c = Cluster()
        for n in zone_nodes("z-a", "z-a", "z-b", "z-b"):
            c.add_node(n)
        for j in range(4):
            c.add_pod(Pod(name=f"db-{j}", creation_ms=j, labels={"app": "db"},
                          containers=[Container(requests={CPU: 100})],
                          pod_anti_affinity_required=[term(app="db")]))
        sched = ipa_sched()
        pending = sched.sort_pending(c.pending_pods(), c)
        snap, meta = c.snapshot(pending, now_ms=0)
        sched.prepare(meta, c)
        an = np.asarray(profile_batch_solve(sched, snap)[0])[: len(pending)]
        zone_of = {0: "z-a", 1: "z-a", 2: "z-b", 3: "z-b"}
        used_zones = [zone_of[int(n)] for n in an if n >= 0]
        assert len(used_zones) == 2  # one per zone, two deferred
        assert len(set(used_zones)) == 2


class TestNativeStoreGate:
    def test_fast_path_disengages_for_selector_specs(self):
        # the native snapshot fast path passes assigned=[] to
        # build_snapshot; spread/affinity tables need assigned pod objects,
        # so pods carrying such specs must disengage it
        c = Cluster()
        for i, z in enumerate(["z-a", "z-b"]):
            c.add_node(mknode(f"n{i}", {ZONE: z}))
        c.attach_native_store()
        e = Pod(name="e", labels={"app": "web"},
                containers=[Container(requests={CPU: 100})],
                pod_anti_affinity_required=[PodAffinityTerm(
                    topology_key=ZONE,
                    label_selector=LabelSelector(match_labels={"app": "web"}))])
        e.node_name = "n0"
        c.add_pod(e)
        c.add_pod(Pod(name="p", labels={"app": "web"},
                      containers=[Container(requests={CPU: 100})]))
        sched = Scheduler(Profile(plugins=[NodeResourcesAllocatable(),
                                           InterPodAffinity()]))
        r = run_cycle(sched, c, now=1000)
        # symmetry from the ASSIGNED carrier must still block z-a
        assert r.bound["default/p"] == "n1"
        # and removing the spec-carrying pods re-engages the fast path
        c.remove_pod("default/e")
        c.remove_pod("default/p")
        assert not c._selector_spec_pods


class TestWaveCapacityHostLevelBypass:
    def test_host_level_request_does_not_zero_capacity(self):
        # ephemeral-storage is host-level: zones never report it; the
        # batched NUMA capacity estimate must not starve such nodes
        import jax.numpy as jnp
        from scheduler_plugins_tpu.api.resources import EPHEMERAL_STORAGE
        from scheduler_plugins_tpu.framework import Profile, Scheduler
        from scheduler_plugins_tpu.parallel.solver import profile_batch_solve
        from scheduler_plugins_tpu.plugins import (
            NodeResourcesAllocatable,
            NodeResourceTopologyMatch,
        )
        from scheduler_plugins_tpu.api.objects import (
            NodeResourceTopology, NUMAZone, TopologyManagerPolicy,
            TopologyManagerScope,
        )

        c = Cluster()
        c.add_node(Node(name="n0", allocatable={
            CPU: 8000, MEMORY: 64 * gib, EPHEMERAL_STORAGE: 100 * gib,
            PODS: 110}))
        c.add_nrt(NodeResourceTopology(
            node_name="n0",
            zones=[NUMAZone(numa_id=z, available={CPU: 4000, MEMORY: 24 * gib})
                   for z in range(2)],
            policy=TopologyManagerPolicy.SINGLE_NUMA_NODE,
            scope=TopologyManagerScope.CONTAINER))
        for j in range(2):
            c.add_pod(Pod(name=f"p{j}", creation_ms=j, containers=[Container(
                requests={CPU: 1000, MEMORY: 2 * gib, EPHEMERAL_STORAGE: gib},
                limits={CPU: 1000, MEMORY: 2 * gib, EPHEMERAL_STORAGE: gib})]))
        sched = Scheduler(Profile(plugins=[NodeResourcesAllocatable(),
                                           NodeResourceTopologyMatch()]))
        pending = sched.sort_pending(c.pending_pods(), c)
        snap, meta = c.snapshot(pending, now_ms=0)
        sched.prepare(meta, c)
        an = np.asarray(profile_batch_solve(sched, snap)[0])[: len(pending)]
        assert (an >= 0).all(), an.tolist()


class TestAddedAffinity:
    def test_profile_fenced_to_node_subset(self):
        # NodeAffinityArgs.addedAffinity: every pod of the profile is
        # confined to matching nodes, even with no pod-level affinity
        from scheduler_plugins_tpu.api.config import load_profile
        from scheduler_plugins_tpu.framework import Scheduler

        sched = Scheduler(load_profile({
            "plugins": ["NodeResourcesAllocatable", "NodeAffinity"],
            "pluginConfig": [{"name": "NodeAffinity", "args": {
                "addedAffinity": [{"match_expressions": [
                    {"key": "pool", "operator": "In", "values": ["gpu"]}]}],
            }}],
        }))
        c = Cluster()
        c.add_node(mknode("plain"))
        c.add_node(mknode("fenced", {"pool": "gpu"}))
        c.add_pod(mkpod("p"))
        r = run_cycle(sched, c, now=1000)
        assert r.bound["default/p"] == "fenced"

    def test_added_affinity_ands_with_pod_affinity(self):
        from scheduler_plugins_tpu.api.objects import (
            NodeSelectorRequirement, NodeSelectorTerm,
        )

        plug = NodeAffinity(added_affinity=[NodeSelectorTerm(
            match_expressions=[NodeSelectorRequirement(
                key="pool", operator="In", values=("gpu",))])])
        r, c = run(
            [mknode("gpu-hdd", {"pool": "gpu", "disk": "hdd"}),
             mknode("gpu-ssd", {"pool": "gpu", "disk": "ssd"}),
             mknode("cpu-ssd", {"disk": "ssd"})],
            [mkpod("p", node_selector={"disk": "ssd"})],
            plugins=[plug],
        )
        assert r.bound["default/p"] == "gpu-ssd"
