"""LowRiskOverCommitment beta-distribution edge tables.

Mirrors the reference's beta_test.go + lowriskovercommitment_test.go:
- moment recursion goldens for beta(1,1)/(1,2)/(3,1) (beta_test.go:26-110):
  the moment-matched fit must recover (alpha, beta) from (m1, m2).
- DistributionFunction vectors for beta(2,2) (beta_test.go:236-330).
- GetMaxVariance table (beta_test.go:329-375) via fit validity.
- ComputeProbability degenerate branches (beta.go:173-191).
- computeRisk goldens for node_A / nrla_A1 / nrla_A2
  (lowriskovercommitment_test.go:245-392): 0.5 / 0.25 / 1.0 / 0.75.
- the Score best-effort gate (lowriskovercommitment.go:122-129).
"""

import math

import jax.numpy as jnp
import numpy as np
import pytest

from scheduler_plugins_tpu.ops.trimaran import (
    _risk_one_resource,
    compute_probability,
)


def prob(mu, sigma, threshold):
    p, valid, alpha, beta = compute_probability(
        jnp.float64(mu), jnp.float64(sigma), jnp.float64(threshold))
    return float(p), bool(valid), float(alpha), float(beta)


class TestMomentMatchedFit:
    """NewBetaDistribution moment goldens: matching (m1, m2) recovers the
    (alpha, beta) pair the reference tabulates (beta_test.go:26-110)."""

    @pytest.mark.parametrize("alpha,beta,m1,m2", [
        (1.0, 1.0, 0.5, 1.0 / 3.0),
        (1.0, 2.0, 1.0 / 3.0, 1.0 / 6.0),
        (3.0, 1.0, 0.75, 0.6),
    ])
    def test_fit_recovers_parameters(self, alpha, beta, m1, m2):
        sigma = math.sqrt(m2 - m1 * m1)
        # threshold in the open interval so no degenerate branch fires
        _, valid, got_a, got_b = prob(m1, sigma, 0.42)
        assert valid
        assert got_a == pytest.approx(alpha, abs=1e-9)
        assert got_b == pytest.approx(beta, abs=1e-9)


class TestDistributionFunction:
    """beta(2,2) CDF vectors (beta_test.go:236-330). beta(2,2): m1=0.5,
    var = 4/(16*5) = 0.05."""

    SIGMA = math.sqrt(0.05)

    def test_cdf_at_half_is_half(self):
        p, valid, a, b = prob(0.5, self.SIGMA, 0.5)
        assert valid
        assert (a, b) == (pytest.approx(2.0), pytest.approx(2.0))
        assert p == pytest.approx(0.5, abs=1e-5)

    def test_cdf_at_zero_is_zero(self):
        p, _, _, _ = prob(0.5, self.SIGMA, 0.0)
        assert p == 0.0

    def test_cdf_at_one_is_one(self):
        p, _, _, _ = prob(0.5, self.SIGMA, 1.0)
        assert p == 1.0


class TestComputeProbabilityEdges:
    """ComputeProbability (beta.go:173-191)."""

    def test_mu_zero_is_certain(self):
        p, valid, _, _ = prob(0.0, 0.3, 0.1)
        assert (p, valid) == (1.0, False)

    def test_sigma_zero_below_threshold_is_certain(self):
        p, valid, _, _ = prob(0.4, 0.0, 0.5)
        assert (p, valid) == (1.0, False)

    def test_sigma_zero_above_threshold_is_impossible(self):
        p, valid, _, _ = prob(0.8, 0.0, 0.5)
        assert (p, valid) == (0.0, False)

    def test_moment_mismatch_returns_zero_invalid(self):
        # variance beyond the beta maximum m1*(1-m1) cannot be matched
        # (MatchMoments false -> ComputeProbability returns 0, nil)
        sigma = math.sqrt(0.5 * 0.5) + 0.01
        p, valid, _, _ = prob(0.5, sigma, 0.4)
        assert (p, valid) == (0.0, False)

    @pytest.mark.parametrize("m1", [0.0, 1.0, -1.0])
    def test_max_variance_zero_ends_invalid(self, m1):
        # GetMaxVariance(m1) == 0 at the boundaries (beta_test.go:329-375):
        # any positive sigma then fails the fit
        _, valid, _, _ = prob(m1, 0.1, 0.4)
        assert not valid


def risk(avg, std, cap, req, limit, req_minus, limit_minus,
         weight=0.5, window=5):
    out = _risk_one_resource(
        jnp.asarray([avg], jnp.float64),
        jnp.asarray([std], jnp.float64),
        jnp.asarray([True]),
        jnp.asarray([cap], jnp.int64),
        jnp.asarray([req], jnp.int64),
        jnp.asarray([limit], jnp.int64),
        jnp.asarray([req_minus], jnp.int64),
        jnp.asarray([limit_minus], jnp.int64),
        window,
        weight,
    )
    return float(np.asarray(out)[0])


class TestComputeRiskGoldens:
    """node_A (4000m, 4096 bytes; cpu avg 80/std 0, mem avg 25/std 0) with
    nrla_A1/nrla_A2 (lowriskovercommitment_test.go:245-392)."""

    def test_a1_cpu(self):
        # riskLimit 0 (limit 3000 < cap), riskLoad 1 (mu .8 > thr .25)
        assert risk(80, 0, 4000, 2000, 3000, 1000, 2000) == pytest.approx(0.5)

    def test_a1_memory(self):
        # riskLimit (6144-4096)/(6144-2048) = .5; zero-over-zero conditioning
        # forces allocProb 1 -> riskLoad 0
        assert risk(25, 0, 4096, 2048, 6144, 0, 0) == pytest.approx(0.25)

    def test_a2_cpu(self):
        # riskLimit (5000-4000)/(5000-4000) = 1; riskLoad 1 (mu .8 > thr .75)
        assert risk(80, 0, 4000, 4000, 5000, 3000, 4000) == pytest.approx(1.0)

    def test_a2_memory(self):
        # riskLimit (7168-4096)/(7168-1024) = .5; riskLoad 1 (mu .25 > .125)
        assert risk(25, 0, 4096, 1024, 7168, 512, 6144) == pytest.approx(0.75)

    def test_risk_clamped_to_unit_interval(self):
        assert 0.0 <= risk(100, 50, 4000, 8000, 16000, 8000, 16000) <= 1.0


class TestScoreGates:
    """Score early-outs (lowriskovercommitment.go:122-137)."""

    def _snap(self, pod):
        from conftest import raw_plugin_scores
        from scheduler_plugins_tpu.api.objects import Node
        from scheduler_plugins_tpu.api.resources import CPU, MEMORY, PODS
        from scheduler_plugins_tpu.framework import Profile, Scheduler
        from scheduler_plugins_tpu.plugins import LowRiskOverCommitment
        from scheduler_plugins_tpu.state.cluster import Cluster

        gib = 1 << 30
        c = Cluster()
        c.add_node(Node(name="node-1",
                        allocatable={CPU: 1000, MEMORY: gib, PODS: 110}))
        c.node_metrics = {"node-1": {"cpu_avg": 20.0}}
        c.add_pod(pod)
        sched = Scheduler(Profile(plugins=[LowRiskOverCommitment()]))
        raw, _ = raw_plugin_scores(c, sched, pod)
        return raw

    def test_best_effort_pod_scores_minimum(self):
        # the reference's "new node" Score vector: empty pod -> score 0
        from scheduler_plugins_tpu.api.objects import Container, Pod

        raw = self._snap(Pod(name="p", containers=[Container()]))
        assert int(raw[0]) == 0

    def test_requesting_pod_scores_positive(self):
        from scheduler_plugins_tpu.api.objects import Container, Pod
        from scheduler_plugins_tpu.api.resources import CPU

        raw = self._snap(Pod(name="p",
                             containers=[Container(requests={CPU: 100})]))
        assert int(raw[0]) > 0
