"""CrossNodePreemption decision tables — the opt-in mirror of the
reference's commented-out brute-force algorithm
(cross_node_preemption.go:144-208: collect lower-priority pods, DFS all
victim subsets, nominate any victim-hosting node the preemptor then fits,
select by the upstream pickOneNode criteria)."""

from scheduler_plugins_tpu.api.objects import (
    Container,
    Node,
    Pod,
    PodDisruptionBudget,
)
from scheduler_plugins_tpu.api.resources import CPU, MEMORY, PODS
from scheduler_plugins_tpu.framework import Profile, Scheduler, run_cycle
from scheduler_plugins_tpu.plugins import (
    CrossNodePreemption,
    NodeResourcesAllocatable,
)
from scheduler_plugins_tpu.state.cluster import Cluster

gib = 1 << 30


def mknode(name, cpu=4000):
    return Node(name=name, allocatable={CPU: cpu, MEMORY: 32 * gib, PODS: 110})


def mkpod(name, cpu, priority=0, node=None, labels=None):
    p = Pod(name=name, priority=priority, labels=labels or {},
            containers=[Container(requests={CPU: cpu, MEMORY: gib})])
    p.node_name = node
    return p


def sched(**kw):
    return Scheduler(Profile(plugins=[NodeResourcesAllocatable(),
                                      CrossNodePreemption(**kw)]))


class TestCrossNodePreemption:
    def test_single_node_victim(self):
        c = Cluster()
        c.add_node(mknode("n0"))
        c.add_pod(mkpod("low", 3000, priority=1, node="n0"))
        c.add_pod(mkpod("high", 3000, priority=10))
        r = run_cycle(sched(), c, now=1000)
        node, victims = r.preempted["default/high"]
        assert node == "n0" and victims == ["default/low"]

    def test_minimal_subset_wins(self):
        # both v1+v2 or just v2 would fit the preemptor on n0; the
        # fewest-victims criterion keeps v1 (and the lower-priority victim
        # is preferred by the max-priority criterion)
        c = Cluster()
        c.add_node(mknode("n0", cpu=4000))
        c.add_pod(mkpod("v1", 1500, priority=5, node="n0"))
        c.add_pod(mkpod("v2", 1500, priority=1, node="n0"))
        c.add_pod(mkpod("p", 1400, priority=10))
        r = run_cycle(sched(), c, now=1000)
        _, victims = r.preempted["default/p"]
        assert victims == ["default/v2"]

    def test_picks_node_minimizing_victim_priority(self):
        c = Cluster()
        c.add_node(mknode("a"))
        c.add_node(mknode("b"))
        c.add_pod(mkpod("va", 3000, priority=8, node="a"))
        c.add_pod(mkpod("vb", 3000, priority=2, node="b"))
        c.add_pod(mkpod("p", 3000, priority=10))
        r = run_cycle(sched(), c, now=1000)
        node, victims = r.preempted["default/p"]
        assert node == "b" and victims == ["default/vb"]

    def test_no_eligible_victims(self):
        c = Cluster()
        c.add_node(mknode("n0"))
        c.add_pod(mkpod("peer", 3000, priority=10, node="n0"))
        c.add_pod(mkpod("p", 3000, priority=10))
        r = run_cycle(sched(), c, now=1000)
        assert not r.preempted

    def test_pdb_violations_rank_last(self):
        # victims of equal priority on two nodes; a's victim is PDB-guarded
        # with no budget -> b wins on fewest violations
        c = Cluster()
        c.add_node(mknode("a"))
        c.add_node(mknode("b"))
        c.add_pdb(PodDisruptionBudget(name="guard",
                                      selector={"app": "guarded"},
                                      disruptions_allowed=0))
        c.add_pod(mkpod("va", 3000, priority=2, node="a",
                        labels={"app": "guarded"}))
        c.add_pod(mkpod("vb", 3000, priority=2, node="b"))
        c.add_pod(mkpod("p", 3000, priority=10))
        r = run_cycle(sched(), c, now=1000)
        node, victims = r.preempted["default/p"]
        assert node == "b" and victims == ["default/vb"]

    def test_pool_bound_keeps_lowest_priority(self):
        # pool capped at 1: only the lowest-priority pod is searched
        c = Cluster()
        c.add_node(mknode("n0", cpu=4000))
        c.add_pod(mkpod("v-hi", 2000, priority=9, node="n0"))
        c.add_pod(mkpod("v-lo", 2000, priority=1, node="n0"))
        c.add_pod(mkpod("p", 3500, priority=10))
        r = run_cycle(sched(max_pool=1), c, now=1000)
        # removing only v-lo frees 2000 < 3500 needed beyond free 0 -> no
        # candidate within the bounded pool
        assert not r.preempted
        r = run_cycle(sched(max_pool=2), c, now=100_000_000)
        _, victims = r.preempted["default/p"]
        assert sorted(victims) == ["default/v-hi", "default/v-lo"]

    def test_nomination_and_binding_after_victims_leave(self):
        c = Cluster()
        c.add_node(mknode("n0"))
        c.add_pod(mkpod("low", 3000, priority=1, node="n0"))
        c.add_pod(mkpod("p", 3000, priority=10))
        s = sched()
        r1 = run_cycle(s, c, now=1000)
        assert c.pods["default/p"].nominated_node_name == "n0"
        c.remove_pod("default/low")  # victim actually deleted
        r2 = run_cycle(s, c, now=2000)
        assert r2.bound["default/p"] == "n0"
