"""Concurrency auditor tests (tools/race_audit.py) and the runtime
checker (utils/racecheck.py): each golden-bad fixture fires exactly its
CA rule, the committed manifest stays fail-closed, the racecheck proxies
catch order inversions / ownership violations, and the concurrency bugs
the auditor's first run surfaced stay fixed."""

import json
import threading
import time
from pathlib import Path
from types import SimpleNamespace

import pytest

import tools.race_audit as R
from tools.race_audit import audit_paths

FIXTURES = Path(__file__).parent / "fixtures" / "race_audit"


def fired(path):
    res = audit_paths([str(path)])
    return {rule for rule, count in res["rules"].items() if count}


class TestGoldenBad:
    @pytest.mark.parametrize(
        "fixture, rule",
        [
            ("bad_unlocked_shared.py", "CA001"),
            ("bad_lock_inversion.py", "CA002"),
            ("bad_unserialized_trace.py", "CA003"),
            ("bad_signal_lock.py", "CA004"),
            ("bad_watchdog_writer.py", "CA005"),
        ],
    )
    def test_flagged_exactly(self, fixture, rule):
        # each fixture isolates ONE failure mode: its own rule fires and
        # no other rule piggybacks (the docstrings explain why the
        # neighboring rules stay silent)
        assert fired(FIXTURES / fixture) == {rule}

    def test_every_rule_has_a_fixture(self):
        covered = set()
        for fx in sorted(FIXTURES.glob("bad_*.py")):
            covered |= fired(fx)
        assert covered == set(R.RULES)

    def test_fixtures_invisible_to_graft_lint(self):
        # the race corpus must not double as a lint corpus: graft_lint
        # walks these only when pointed at them directly, and even then
        # has nothing to say (threads are named, no swallowed excepts)
        from tools.graft_lint import lint_paths

        assert lint_paths(sorted(FIXTURES.glob("bad_*.py"))) == []

    def test_sanction_suppresses(self, tmp_path):
        bad = (FIXTURES / "bad_signal_lock.py").read_text()
        sanctioned = bad.replace(
            "with STATE_LOCK:\n        PENDING.clear()",
            "with STATE_LOCK:  "
            "# race-audit: safe[CA004] — fixture sanction\n"
            "        PENDING.clear()",
        )
        assert sanctioned != bad
        p = tmp_path / "sanctioned_signal_lock.py"
        p.write_text(sanctioned)
        res = audit_paths([str(p)])
        assert not any(res["rules"].values())
        assert res["census"]["sanctioned_sites"] == 1


class TestTreeAndManifest:
    def test_tree_audits_clean_against_manifest(self):
        # THE gate: the whole package, checked read-only against the
        # committed manifest (entry-table and census drift included)
        assert R.run(check=True) == 0

    def test_manifest_shape(self):
        man = json.loads(R.MANIFEST.read_text())
        assert man["tool"] == R.TOOL_VERSION
        assert set(man["rules"]) == set(R.RULES)
        assert not any(man["rules"].values())
        # the daemon's known thread topology must be covered
        for entry in (
            "main", "spt-bind-flusher*", "shadow-tuner", "wd-*",
            "solve-watchdog", "health-server", "feed-server",
            "leader-elector", "load-watcher",
        ):
            assert entry in man["entries"], entry
            assert man["entries"][entry]["targets"], entry

    def test_check_fails_closed_without_manifest(self, monkeypatch,
                                                 tmp_path):
        monkeypatch.setattr(R, "MANIFEST", tmp_path / "absent.json")
        fx = str(FIXTURES / "bad_unlocked_shared.py")
        assert R.run(paths=[fx], check=True) == 1
        assert not (tmp_path / "absent.json").exists()

    def test_check_flags_entry_table_drift(self, capsys):
        # auditing a different file set against the committed manifest
        # must trip the drift tripwire, not silently pass
        assert R.run(paths=[str(FIXTURES)], check=True) == 1
        assert "drift" in capsys.readouterr().err


class TestRacecheck:
    def test_install_noop_without_env(self, monkeypatch):
        from scheduler_plugins_tpu.utils import racecheck

        monkeypatch.delenv("SPT_RACE", raising=False)
        assert racecheck.install(seed=0) is False
        assert threading.Lock is racecheck._state.get(
            "orig", {}
        ).get("Lock", threading.Lock)

    def test_proxies_catch_violations(self, monkeypatch):
        from scheduler_plugins_tpu.utils import racecheck

        monkeypatch.setenv("SPT_RACE", "1")
        assert racecheck.install(seed=0, extra_prefixes=(__name__,))
        try:
            a, b = threading.Lock(), threading.Lock()
            with a:
                with b:
                    pass
            with b:  # reversed: the runtime twin of CA002
                with a:
                    pass
            lock = threading.Lock()
            t = threading.Thread(
                target=lock.acquire, name="rc-owner", daemon=True
            )
            t.start()
            t.join()
            lock.release()  # released from a thread that never acquired
            held = threading.Lock()
            held.acquire()
            with pytest.raises(RuntimeError, match="double acquire"):
                held.acquire()  # guaranteed self-deadlock: raised too
            kinds = {v["kind"] for v in racecheck.violations()}
            assert kinds == {
                "lock-order-inversion",
                "non-owner-release",
                "double-acquire",
            }
            rep = racecheck.report()
            assert rep["locks_created"] == 4
            assert rep["order_edges"] >= 2
        finally:
            racecheck.uninstall()
        assert threading.Lock is racecheck._state["orig"]["Lock"]

    def test_stdlib_locks_stay_raw(self, monkeypatch):
        # Condition/queue/futures internals must keep real primitives:
        # only scheduler_plugins_tpu-created locks get proxied
        from scheduler_plugins_tpu.utils import racecheck

        monkeypatch.setenv("SPT_RACE", "1")
        assert racecheck.install(seed=0)
        try:
            cond = threading.Condition()  # allocates its own lock
            with cond:
                cond.notify_all()
            assert racecheck.report()["locks_created"] == 0
        finally:
            racecheck.uninstall()


class TestRegressions:
    """The auditor's first tree run surfaced these for real — each fix
    keeps a runtime witness so a revert fails loudly, not statically."""

    def test_shadow_rebuild_serialized(self, monkeypatch):
        # ShadowTuner._shadow_scheduler: the sweep worker and a deadlined
        # wd-* probe both land here; pre-fix, both could trace through
        # rebuild_scheduler at once (CA001 on _shadow_key/_shadow_sched,
        # CA003 on the shared jit cache). _shadow_lock must serialize the
        # rebuild itself, not just the memo publish.
        from scheduler_plugins_tpu.tuning.shadow import ShadowTuner
        from scheduler_plugins_tpu.utils import flightrec

        tuner = ShadowTuner.__new__(ShadowTuner)
        tuner._shadow_lock = threading.Lock()
        tuner._shadow_key = None
        tuner._shadow_sched = None

        gate = threading.Barrier(4)
        active, peak, calls = [0], [0], [0]
        meter = threading.Lock()

        def slow_rebuild(manifest, loader):
            with meter:
                calls[0] += 1
                active[0] += 1
                peak[0] = max(peak[0], active[0])
            time.sleep(0.02)
            with meter:
                active[0] -= 1
            return object(), {}, True

        monkeypatch.setattr(flightrec, "rebuild_scheduler", slow_rebuild)
        rec = SimpleNamespace(
            manifest={"profile_config": {}, "plugins": []}, blobs={}
        )
        out = []

        def probe():
            gate.wait()
            out.append(tuner._shadow_scheduler(rec))

        threads = [
            threading.Thread(target=probe, name=f"wd-test-{i}",
                             daemon=True)
            for i in range(4)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=10)
        assert peak[0] == 1, "concurrent rebuild_scheduler trace"
        assert calls[0] == 1, "memo check must happen under the lock"
        assert len(out) == 4 and all(s is out[0] for s in out)

    def test_is_leader_event_backed(self):
        # LeaseElector.is_leader: written by the elector thread, read by
        # the scheduling loop and /healthz — pre-fix a plain bool
        # attribute (CA001). Now an Event behind a property; assignment
        # sites keep working unchanged through the setter.
        from scheduler_plugins_tpu.bridge.leader import LeaseElector

        el = LeaseElector("http://127.0.0.1:9", "tester")
        assert isinstance(el._leader_event, threading.Event)
        assert el.is_leader is False
        el.is_leader = True
        seen = []
        t = threading.Thread(
            target=lambda: seen.append(el.is_leader),
            name="leader-reader", daemon=True,
        )
        t.start()
        t.join()
        assert seen == [True]
        el.is_leader = False
        assert el.is_leader is False

    def test_counterfactual_weights_snapshot_under_lock(self):
        # ShadowTuner._counterfactual_pair must snapshot active /
        # last_known_good inside _lock (torn-pair read pre-fix): the
        # source now witnesses both the lock and the copies
        import inspect

        from scheduler_plugins_tpu.tuning.shadow import ShadowTuner

        src = inspect.getsource(ShadowTuner._counterfactual_pair)
        head = src.split("shadow = self._shadow_scheduler", 1)[0]
        assert "with self._lock:" in head
        assert head.count(".copy()") >= 2
