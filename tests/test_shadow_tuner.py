"""Online self-tuning shadow lane (tuning.shadow + tuning.promotion,
ISSUE 15): the shared promotion-gate body's rank/disqualify decision
tables (the one copy tools/tune.py and the shadow lane both consume),
the guarded-rollout rollback decision tables (each objective regressing
in isolation rolls back within the probation window; sub-threshold noise
does not; a watchdog fault during probation rolls back immediately; the
controller cannot flap), the tune.sweep / tune.promote chaos sites, the
live-weights rollout seam (traced-argument weights, zero recompiles),
and the tuner state persistence round trip.

The end-to-end tuned-serving claim (shadow sweeps over real ring
records, gated promotion, measured quality win, injected-regression
rollback) is `make tune-live-smoke` (bench config 14); the tuner-fault
bit-identity claim is the chaos gate's tuner phase (`make chaos-smoke`).
These tests stay host-side where possible — only the live-weights seam
class compiles a (tiny) solve."""

from types import SimpleNamespace

import numpy as np
import pytest

from scheduler_plugins_tpu.framework import Profile, Scheduler
from scheduler_plugins_tpu.plugins import NodeResourcesAllocatable
from scheduler_plugins_tpu.resilience import faults
from scheduler_plugins_tpu.tuning import promotion
from scheduler_plugins_tpu.tuning.shadow import (
    PROBATION_OBJECTIVES,
    ShadowTuner,
)


def make_scheduler(weights=(1, 1)):
    plugins = [NodeResourcesAllocatable() for _ in weights]
    for plugin, w in zip(plugins, weights):
        plugin.weight = int(w)
    return Scheduler(Profile(plugins=plugins))


def make_tuner(scheduler=None, **kw):
    scheduler = scheduler or make_scheduler()
    kw.setdefault("probation_cycles", 6)
    kw.setdefault("baseline_min", 1)
    kw.setdefault("hysteresis", 0.01)
    kw.setdefault("regress_cycles", 2)
    kw.setdefault("cooldown_cycles", 4)
    kw.setdefault("sync", True)
    tuner = ShadowTuner(scheduler, **kw)
    return tuner


def report(quality=None, degraded=False, solve_path="device"):
    return SimpleNamespace(
        quality=quality, degraded=degraded, solve_path=solve_path,
    )


def flat_quality(**over):
    q = {name: 0.5 for name in PROBATION_OBJECTIVES}
    q.update(over)
    return q


class _ScriptedProbe:
    """Scripted paired-counterfactual probe: each entry is the
    {objective: (q_active, q_good)} pair the next probation cycle sees —
    the decision tables drive the regression detector without any jit."""

    def __init__(self, tuner, pairs):
        self.pairs = list(pairs)
        tuner._counterfactual_pair = self._next

    def _next(self):
        spec = self.pairs.pop(0) if self.pairs else {}
        q_active = flat_quality(**{k: v[0] for k, v in spec.items()})
        q_good = flat_quality(**{k: v[1] for k, v in spec.items()})
        return q_active, q_good


def start_probation(tuner, weights=(3, 3)):
    """Baseline one observed cycle, then promote `weights` via the
    harness injection hook (the decision tables adjudicate the window,
    not the gate)."""
    tuner.begin_cycle()
    tuner.observe_report(report(quality=flat_quality()))
    tuner.inject_promotion(weights)
    tuner.begin_cycle()
    assert tuner.state == "probation"
    assert [int(w) for w in tuner.active] == list(weights)


class TestPromotionGateBody:
    """Decision tables for the shared rank/disqualify rules — and the
    regression lock that tools/tune.py actually consumes them."""

    def _objectives(self, **cols):
        # lane 0 is the incumbent; columns are per-candidate values
        base = {name: np.zeros(3) for name in promotion.RANKED_OBJECTIVES}
        for name, vals in cols.items():
            base[name] = np.asarray(vals, float)
        return base

    def test_improvement_ranks_and_wins(self):
        objs = self._objectives(util_imbalance=[0.20, 0.15, 0.25])
        order, score, imps = promotion.rank_candidates(
            objs, np.zeros(3, np.int64), tolerance=0.01
        )
        assert int(order[0]) == 1 and score[1] == pytest.approx(0.05)
        assert promotion.strict_improvements(imps, 1) == ["util_imbalance"]

    def test_violations_disqualify(self):
        objs = self._objectives(util_imbalance=[0.20, 0.10, 0.25])
        order, score, _ = promotion.rank_candidates(
            objs, np.asarray([0, 3, 0]), tolerance=0.01
        )
        assert not np.isfinite(score[1])
        assert int(order[0]) == 0  # nothing beats the incumbent

    def test_tolerance_disqualifies_sold_objective(self):
        # candidate 1 buys util_imbalance by selling fragmentation
        objs = self._objectives(
            util_imbalance=[0.20, 0.10, 0.20],
            fragmentation=[0.50, 0.55, 0.50],
        )
        _, score, _ = promotion.rank_candidates(
            objs, np.zeros(3, np.int64), tolerance=0.01
        )
        assert not np.isfinite(score[1])
        # a looser tolerance readmits it
        _, score2, _ = promotion.rank_candidates(
            objs, np.zeros(3, np.int64), tolerance=0.10
        )
        assert score2[1] == pytest.approx(0.05)

    def test_rail_objective_guards_but_does_not_vote(self):
        # drift regresses 0.05: inside its own rail tolerance, excluded
        # from the rank sum — the shadow lane's configuration
        objs = self._objectives(
            util_imbalance=[0.20, 0.10, 0.20], drift=[0.0, -0.05, 0.0],
        )
        _, score, _ = promotion.rank_candidates(
            objs, np.zeros(3, np.int64), tolerance=0.01,
            rank_objectives=PROBATION_OBJECTIVES,
            tolerances={"drift": 0.10},
        )
        assert score[1] == pytest.approx(0.10)  # drift did not vote
        # beyond the rail it still disqualifies
        objs["drift"] = np.asarray([0.0, -0.15, 0.0])
        _, score3, _ = promotion.rank_candidates(
            objs, np.zeros(3, np.int64), tolerance=0.01,
            rank_objectives=PROBATION_OBJECTIVES,
            tolerances={"drift": 0.10},
        )
        assert not np.isfinite(score3[1])

    def test_offline_driver_consumes_shared_body(self):
        import inspect

        import tools.tune as tune

        # the refactor left exactly one copy of the gate: tools/tune.py
        # no longer defines its own rank/sweep/disqualify
        for legacy in ("_rank", "_sweep_corpus", "_strict_improvements"):
            assert not hasattr(tune, legacy)
        src = inspect.getsource(tune.cmd_tune)
        assert "promotion.evaluate_candidates" in src

    def test_weights_digest_stable_and_distinct(self):
        a = promotion.weights_digest([1, 20])
        assert a == promotion.weights_digest(np.asarray([1, 20]))
        assert a != promotion.weights_digest([20, 1])


class TestRollbackDecisionTables:
    """The probation window, driven by a scripted counterfactual probe."""

    @pytest.mark.parametrize("objective", PROBATION_OBJECTIVES)
    def test_each_objective_regressing_in_isolation_rolls_back(
        self, objective
    ):
        tuner = make_tuner()
        # sustained regression just past the band: detected by the
        # consecutive-cycles trigger within regress_cycles (= 2)
        _ScriptedProbe(tuner, [
            {objective: (0.515, 0.50)} for _ in range(4)
        ])
        start_probation(tuner)
        for k in range(4):
            tuner.begin_cycle()
            tuner.observe_report(report(quality=flat_quality()))
            if tuner.rollbacks:
                break
        assert tuner.rollbacks == 1
        assert tuner.last_rollback_reason == (
            f"quality-regression:{objective}"
        )
        assert tuner.last_rollback_detect_cycles <= 2
        assert [int(w) for w in tuner.active] == [1, 1]  # last-known-good
        assert (3, 3) in tuner.blocked

    def test_large_single_cycle_regression_rolls_back_immediately(self):
        tuner = make_tuner()
        # one cycle at >= hysteresis * regress_cycles: immediate
        _ScriptedProbe(tuner, [{"util_imbalance": (0.525, 0.50)}])
        start_probation(tuner)
        tuner.begin_cycle()
        tuner.observe_report(report(quality=flat_quality()))
        assert tuner.rollbacks == 1
        assert tuner.last_rollback_detect_cycles == 0

    def test_sub_threshold_noise_does_not_flap(self):
        tuner = make_tuner(probation_cycles=4)
        # alternating +/- inside the hysteresis band: never counted
        _ScriptedProbe(tuner, [
            {"util_imbalance": (0.505, 0.50)},
            {"util_imbalance": (0.495, 0.50)},
            {"util_imbalance": (0.508, 0.50)},
            {"util_imbalance": (0.494, 0.50)},
        ])
        start_probation(tuner)
        for _ in range(4):
            tuner.begin_cycle()
            tuner.observe_report(report(quality=flat_quality()))
        assert tuner.rollbacks == 0
        assert tuner.state == "idle"  # probation confirmed
        assert [int(w) for w in tuner.last_known_good] == [3, 3]

    def test_intermittent_regression_does_not_confirm_silently(self):
        # an above-band regression on non-consecutive cycles: each hit
        # resets nothing it should not, and a later big hit still fires
        tuner = make_tuner(probation_cycles=8)
        _ScriptedProbe(tuner, [
            {"util_imbalance": (0.515, 0.50)},
            {},
            {"util_imbalance": (0.525, 0.50)},  # large: immediate
        ])
        start_probation(tuner)
        for _ in range(3):
            tuner.begin_cycle()
            tuner.observe_report(report(quality=flat_quality()))
        assert tuner.rollbacks == 1

    def test_watchdog_fault_during_probation_rolls_back_immediately(self):
        tuner = make_tuner()
        _ScriptedProbe(tuner, [{}] * 4)
        start_probation(tuner)
        tuner.begin_cycle()
        tuner.observe_report(
            report(quality=flat_quality(), degraded=True)
        )
        assert tuner.rollbacks == 1
        assert tuner.last_rollback_reason.startswith("watchdog-fault")
        assert [int(w) for w in tuner.active] == [1, 1]

    def test_host_path_solve_counts_as_watchdog_fault(self):
        tuner = make_tuner()
        _ScriptedProbe(tuner, [{}] * 4)
        start_probation(tuner)
        tuner.begin_cycle()
        tuner.observe_report(
            report(quality=flat_quality(), solve_path="host")
        )
        assert tuner.rollbacks == 1

    def test_unadjudicable_probe_rolls_back(self):
        tuner = make_tuner()

        def boom():
            raise RuntimeError("probe died")

        tuner._counterfactual_pair = boom
        start_probation(tuner)
        tuner.begin_cycle()
        tuner.observe_report(report(quality=flat_quality()))
        assert tuner.rollbacks == 1
        assert "probe-unavailable" in tuner.last_rollback_reason

    def test_rolled_back_vector_is_blocked_and_cooldown_holds(self):
        tuner = make_tuner(cooldown_cycles=6)
        _ScriptedProbe(tuner, [{"util_imbalance": (0.53, 0.50)}])
        start_probation(tuner, weights=(5, 7))
        tuner.begin_cycle()
        tuner.observe_report(report(quality=flat_quality()))
        assert tuner.state == "cooldown"
        # a sweep winner equal to the rolled-back vector is never staged
        W = np.asarray([[1, 1], [5, 7]], np.int64)
        verdict = promotion.PromotionVerdict(
            objectives={}, violations=np.zeros(2, np.int64),
            anchor_mismatches=0, order=np.asarray([1, 0]),
            score=np.asarray([0.0, 0.5]),
            improvements={"util_imbalance": np.asarray([0.0, 0.1])},
            best=1, improved=["util_imbalance"], accepted=True,
        )
        for _ in range(tuner.confirm_sweeps + 1):
            tuner._consume_sweep_locked((verdict, W))
        assert tuner._pending is None
        assert tuner.promotions == 1  # only the injected one, ever

    def test_quality_none_cycles_do_not_advance_probation(self):
        tuner = make_tuner(probation_cycles=2)
        _ScriptedProbe(tuner, [{}] * 2)
        start_probation(tuner)
        for _ in range(3):
            tuner.begin_cycle()
            tuner.observe_report(report(quality=None))
        assert tuner.state == "probation"  # no evidence, no progress


class TestTunerFaultSites:
    def test_promote_crash_keeps_incumbent_and_counts(self):
        tuner = make_tuner()
        plan = faults.FaultPlan(seed=0)
        plan.specs = [faults.FaultSpec(
            site=faults.TUNE_PROMOTE, cycle=0, kind="crash", sticky=True,
        )]
        faults.install(plan)
        try:
            tuner.begin_cycle()
            tuner.observe_report(report(quality=flat_quality()))
            tuner.inject_promotion((9, 9))
            plan.begin_cycle(0)
            tuner.begin_cycle()
        finally:
            faults.clear()
        assert tuner.promotions == 0
        assert [int(w) for w in tuner.active] == [1, 1]
        assert tuner.sweep_failures == 1
        assert plan.log == [(0, faults.TUNE_PROMOTE, "crash")]

    def test_repeated_faults_disable_the_lane(self):
        tuner = make_tuner(max_failures=2)
        plan = faults.FaultPlan(seed=0)
        plan.specs = [
            faults.FaultSpec(site=faults.TUNE_PROMOTE, cycle=c,
                             kind="crash")
            for c in range(2)
        ]
        faults.install(plan)
        try:
            tuner.begin_cycle()
            tuner.observe_report(report(quality=flat_quality()))
            for c in range(2):
                tuner.inject_promotion((9, 9))
                plan.begin_cycle(c)
                tuner.begin_cycle()
        finally:
            faults.clear()
        assert tuner.state == "disabled"
        assert tuner.disabled_reason is not None
        # disabled lane is inert: further cycles change nothing
        tuner.inject_promotion((9, 9))
        tuner.begin_cycle()
        assert tuner.promotions == 0

    def test_sites_registered(self):
        assert faults.TUNE_SWEEP in faults.ALL_SITES
        assert faults.TUNE_PROMOTE in faults.ALL_SITES

    def test_sweep_failure_drops_shadow_scheduler_cache(self):
        # an abandoned (timed-out) job keeps running on its zombie
        # worker and still holds the cached shadow scheduler — the next
        # sweep/probe must rebuild fresh, never share it
        tuner = make_tuner()
        tuner._shadow_sched = object()
        tuner._shadow_key = ("k",)
        with tuner._lock:
            tuner._sweep_failed_locked("timeout (0.1s) in tune.sweep")
        assert tuner._shadow_sched is None and tuner._shadow_key is None


class TestTunerRequiresSequentialMode:
    def test_packing_profile_refused_at_construction(self):
        # a packing-mode profile would accept a gated promotion and then
        # raise on every solve (the live seam is the sequential path) —
        # the tuner must refuse at construction, not at first promotion
        sched = make_scheduler((1, 1))
        sched.profile.solve_mode = "packing"
        with pytest.raises(ValueError, match="sequential parity path"):
            ShadowTuner(sched, sync=True)


class TestStatePersistence:
    def test_state_dict_round_trip_resumes_weights_and_probation(self):
        tuner = make_tuner()
        _ScriptedProbe(tuner, [{}] * 8)
        start_probation(tuner, weights=(4, 6))
        tuner.begin_cycle()
        tuner.observe_report(report(quality=flat_quality()))
        state = tuner.state_dict()
        assert state["state"] == "probation"

        fresh_sched = make_scheduler()
        fresh = make_tuner(scheduler=fresh_sched)
        assert fresh.restore_state(state)
        assert [int(w) for w in fresh.active] == [4, 6]
        assert fresh.state == "probation"
        assert list(np.asarray(fresh_sched.live_weights)) == [4, 6]
        # the restored probation window still adjudicates: a watchdog
        # fault rolls back to the restored last-known-good
        fresh.begin_cycle()
        fresh.observe_report(
            report(quality=flat_quality(), degraded=True)
        )
        assert fresh.rollbacks == 1
        assert [int(w) for w in fresh.active] == [1, 1]

    def test_bad_state_file_starts_fresh(self):
        tuner = make_tuner()
        assert not tuner.restore_state({"format": 99})
        assert not tuner.restore_state({"format": 1, "active_weights": [1]})
        assert not tuner.restore_state("garbage")
        assert tuner.state == "idle"


class TestLiveWeightsSeam:
    """The rollout seam itself: a live-weight swap is bit-identical to a
    statically-weighted scheduler and never recompiles the solve."""

    def _solve(self, scheduler, seed=3):
        from scheduler_plugins_tpu.models import trimaran_scenario

        cluster = trimaran_scenario(n_nodes=16, n_pods=24, seed=seed)
        pending = scheduler.sort_pending(cluster.pending_pods(), cluster)
        snap, meta = cluster.snapshot(pending, now_ms=0)
        scheduler.prepare(meta, cluster)
        return np.asarray(scheduler.solve(snap).assignment)

    def test_live_swap_parity_and_zero_recompiles(self):
        from scheduler_plugins_tpu import plugins as P
        from scheduler_plugins_tpu.utils import observability as obs

        def trimaran_sched(w):
            sched = Scheduler(Profile(plugins=[
                P.TargetLoadPacking(), P.LoadVariationRiskBalancing(),
            ]))
            for plugin, wi in zip(sched.profile.plugins, w):
                plugin.weight = wi
            return sched

        static = trimaran_sched([3, 7])
        want = self._solve(static)

        live = trimaran_sched([1, 1])
        base = self._solve(live)
        live.set_live_weights([3, 7])
        m0 = obs.metrics.get(obs.JIT_CACHE_MISS, program="solve_live")
        got = self._solve(live)
        m1 = obs.metrics.get(obs.JIT_CACHE_MISS, program="solve_live")
        np.testing.assert_array_equal(got, want)
        assert (got != base).any()  # the swap really changed placements
        # rollback = argument change on the SAME compiled program
        live.set_live_weights([1, 1])
        back = self._solve(live)
        m2 = obs.metrics.get(obs.JIT_CACHE_MISS, program="solve_live")
        np.testing.assert_array_equal(back, base)
        assert m1 - m0 == 1 and m2 - m1 == 0
        # host-side consumers follow the swap (hostsolve/recorder read
        # plugin.weight)
        assert [p.weight for p in live.profile.plugins] == [1, 1]

    def test_live_weights_validated(self):
        sched = make_scheduler((1, 1))
        with pytest.raises(ValueError, match="shape"):
            sched.set_live_weights([1, 2, 3])
        with pytest.raises(ValueError, match="positive"):
            sched.set_live_weights([0, 1])
        sched.set_live_weights(None)
        assert sched.live_weights is None

    def test_packing_mode_refuses_live_weights(self):
        from scheduler_plugins_tpu.models import trimaran_scenario

        sched = make_scheduler((1,))
        sched.profile.solve_mode = "packing"
        sched.set_live_weights([2])
        cluster = trimaran_scenario(n_nodes=8, n_pods=4, seed=0)
        pending = sched.sort_pending(cluster.pending_pods(), cluster)
        snap, meta = cluster.snapshot(pending, now_ms=0)
        sched.prepare(meta, cluster)
        with pytest.raises(ValueError, match="sequential parity path"):
            sched.solve(snap)
