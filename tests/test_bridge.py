"""Native bridge tests: the C++ columnar store must agree with the Python
snapshot builder on node usage accounting, and beat it on throughput."""

import numpy as np
import pytest

from scheduler_plugins_tpu.api.objects import Container, Node, Pod
from scheduler_plugins_tpu.api.resources import CPU, MEMORY, PODS, ResourceIndex
from scheduler_plugins_tpu.state.snapshot import build_snapshot

bridge = pytest.importorskip("scheduler_plugins_tpu.bridge")

gib = 1 << 30


def make_store(R=4):
    return bridge.NativeStore(R)


class TestNativeStore:
    def test_node_accounting_matches_python_builder(self):
        idx = ResourceIndex()
        nodes = [
            Node(name=f"n{i}", allocatable={CPU: 8000, MEMORY: 32 * gib, PODS: 110})
            for i in range(3)
        ]
        assigned = [
            Pod(name="a0", containers=[Container(requests={CPU: 500, MEMORY: gib},
                                                 limits={CPU: 1000, MEMORY: gib})]),
            Pod(name="a1", containers=[Container(requests={CPU: 250})]),
            Pod(name="zero", containers=[Container()]),  # non-zero defaults
        ]
        assigned[0].node_name = "n0"
        assigned[1].node_name = "n0"
        assigned[2].node_name = "n2"
        pending = [Pod(name="p0", containers=[Container(requests={CPU: 100})])]
        snap, meta = build_snapshot(nodes, pending, assigned_pods=assigned)

        store = make_store()
        for i, node in enumerate(nodes):
            store.upsert_node(i, idx.encode(node.allocatable))
        for j, pod in enumerate(assigned):
            store.upsert_pod(
                j,
                idx.encode(pod.effective_request()),
                idx.encode(pod.effective_limits()),
                node_id={"n0": 0, "n1": 1, "n2": 2}[pod.node_name],
            )
        out = store.export_nodes()
        np_req = np.asarray(snap.nodes.requested)[:3]
        np_nonzero = np.asarray(snap.nodes.nonzero_requested)[:3]
        np_limits = np.asarray(snap.nodes.limits)[:3]
        assert np.array_equal(out["requested"], np_req)
        assert np.array_equal(out["nonzero_requested"], np_nonzero)
        assert np.array_equal(out["limits"], np_limits)
        assert out["pod_count"].tolist() == [2, 0, 1]

    def test_bind_and_delete_lifecycle(self):
        idx = ResourceIndex()
        store = make_store()
        store.upsert_node(0, idx.encode({CPU: 4000, MEMORY: 8 * gib, PODS: 10}))
        store.upsert_pod(7, idx.encode({CPU: 1000, MEMORY: gib}), creation_ms=5)
        assert store.num_pending == 1
        store.bind(7, 0)
        assert store.num_pending == 0
        out = store.export_nodes()
        assert out["requested"][0, 0] == 1000
        assert out["requested"][0, 3] == 1  # pods slot = count
        store.delete_pod(7)
        out = store.export_nodes()
        assert out["requested"][0].tolist() == [0, 0, 0, 0]

    def test_pending_export_queue_order(self):
        idx = ResourceIndex()
        store = make_store()
        store.upsert_pod(2, idx.encode({CPU: 1}), creation_ms=30)
        store.upsert_pod(1, idx.encode({CPU: 2}), creation_ms=10)
        store.upsert_pod(3, idx.encode({CPU: 3}), creation_ms=20)
        out = store.export_pending()
        assert out["ids"].tolist() == [1, 3, 2]
        assert out["req"][:, 0].tolist() == [2, 3, 1]

    def test_upsert_replaces_previous_contribution(self):
        idx = ResourceIndex()
        store = make_store()
        store.upsert_node(0, idx.encode({CPU: 4000, PODS: 10}))
        store.upsert_pod(1, idx.encode({CPU: 1000}), node_id=0)
        store.upsert_pod(1, idx.encode({CPU: 500}), node_id=0)  # update
        out = store.export_nodes()
        assert out["requested"][0, 0] == 500
        assert out["pod_count"][0] == 1

    def test_throughput_beats_python_builder(self):
        import time

        idx = ResourceIndex()
        n_nodes, n_pods = 200, 5000
        nodes = [
            Node(name=f"n{i}", allocatable={CPU: 64_000, MEMORY: 256 * gib, PODS: 500})
            for i in range(n_nodes)
        ]
        pods = []
        for j in range(n_pods):
            p = Pod(name=f"p{j}", creation_ms=j,
                    containers=[Container(requests={CPU: 100, MEMORY: gib})])
            p.node_name = f"n{j % n_nodes}"
            pods.append(p)

        t0 = time.perf_counter()
        build_snapshot(nodes, [Pod(name="x", containers=[Container()])],
                       assigned_pods=pods)
        t_python = time.perf_counter() - t0

        reqs = np.stack([idx.encode(p.effective_request()) for p in pods])
        lims = np.stack([idx.encode(p.effective_limits()) for p in pods])
        node_alloc = np.stack([idx.encode(n.allocatable) for n in nodes])
        node_ids = np.arange(n_pods) % n_nodes
        make_store()  # warm the .so build outside the timed section
        t0 = time.perf_counter()
        store = make_store()
        store.upsert_nodes_batch(np.arange(n_nodes), node_alloc)
        store.upsert_pods_batch(np.arange(n_pods), reqs, lims, node_ids=node_ids)
        store.export_nodes()
        t_native = time.perf_counter() - t0
        # batched native ingestion must clearly beat the Python builder loop
        assert t_native < t_python / 2, (t_native, t_python)

    def test_batch_matches_single_event_path(self):
        idx = ResourceIndex()
        a = make_store()
        b = make_store()
        reqs = np.array([[1000, gib, 0, 0], [500, 2 * gib, 0, 0]], np.int64)
        a.upsert_node(0, idx.encode({CPU: 8000, MEMORY: 32 * gib, PODS: 10}))
        b.upsert_node(0, idx.encode({CPU: 8000, MEMORY: 32 * gib, PODS: 10}))
        for j in range(2):
            a.upsert_pod(j, reqs[j], node_id=0)
        b.upsert_pods_batch(np.arange(2), reqs, node_ids=np.zeros(2, np.int64))
        assert np.array_equal(
            a.export_nodes()["requested"], b.export_nodes()["requested"]
        )
